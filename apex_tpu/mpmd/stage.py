"""``StageProgram`` — one pipeline stage as its own compiled SPMD world.

Where the ring engine compiles ALL stages into one program over one
mesh, the MPMD engine gives every stage its own mesh (an intra-pod
``dp x tp`` :class:`~apex_tpu.resilience.elastic.ElasticPlan` build),
its own packed parameters
(:func:`~apex_tpu.models.gpt.pack_for_shard_map` with ``n_stages=1``)
and its own small set of jitted ``shard_map`` programs:

* first stage: ``embed`` (token embedding for all microbatches at
  once, exactly the ring's flattened-batch embed), ``fwd``/``bwd``
  that slice microbatch ``m`` out of the stacked activations, and
  ``embed_bwd`` (the embedding pullback + tied-head gradient merge +
  data-axis pmean);
* interior stages: ``fwd`` and a recompute-``bwd`` (local ``jax.vjp``
  of the stage forward — the ring's activation-recompute discipline,
  which also sidesteps the jax 0.4.x psum-transpose bug the ring
  documents);
* last stage: a joint ``bwd`` that recomputes the stage forward AND
  the loss head under one vjp seeded ``(0, 1/M)`` — byte-for-byte the
  ring's last-stage tick.

Per-microbatch gradient accumulators keep a leading data axis
(``P("data", ...)``) so each data shard accumulates exactly what its
ring counterpart accumulates; the ``finish`` programs apply the same
``pmean`` over ``data`` the ring applies.  That is what makes a
2-stage MPMD run bitwise-equal (f32) to the ring engine — asserted by
``__graft_entry__._dryrun_mpmd`` and ``tests/test_mpmd.py``.

Every backward program donates its accumulator arguments and the
optimizer step donates params + state, so steady-state HBM holds one
copy of each.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

__all__ = ["StageProgram"]


def _dyn0(tree, i):
    import jax
    return jax.tree_util.tree_map(
        lambda a: jax.lax.dynamic_index_in_dim(a, i, 0, keepdims=False),
        tree)


class StageProgram:
    """One stage's parameters, mesh and compiled programs.

    ``cfg`` is this stage's :class:`~apex_tpu.models.gpt.GPTConfig`
    (``num_layers`` = layers per stage, TP/SP knobs from the intra-pod
    plan); ``stage_params`` the serial-layout dict holding this
    stage's layer chunk plus the (replicated) embedding / final-LN
    copies; ``plan`` the intra-pod :class:`ParallelPlan`
    (``pp == 1``); ``devices`` this pod's device slice.
    """

    def __init__(self, cfg, stage_params, *, stage_index: int,
                 n_stages: int, n_microbatches: int, plan, devices,
                 optimizer=None, lr: float = 1e-3):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from apex_tpu.models.gpt import GPTModel, pack_for_shard_map
        from apex_tpu.optimizers import FusedAdam
        from apex_tpu.resilience.elastic import ElasticPlan

        if cfg.n_experts > 0:
            raise ValueError(
                "MPMD v1 does not support MoE stages (expert-parallel "
                "collectives inside a stage program are untested "
                "against the cross-pod schedule); use the single-mesh "
                "ring engine for MoE models")
        if cfg.tensor_parallel_size > 1 and not cfg.sequence_parallel:
            raise ValueError(
                "MPMD stages under tensor parallelism require "
                "sequence_parallel=True — same rule as pipeline_step: "
                "the recompute backward never crosses shard_map's "
                "auto-psum, only the SP custom-VJP mappings reduce "
                "replicated-leaf grads")
        self.cfg = cfg
        self.index = int(stage_index)
        self.n_stages = int(n_stages)
        self.M = int(n_microbatches)
        self.is_first = self.index == 0
        self.is_last = self.index == self.n_stages - 1
        self.plan = plan
        self.model = GPTModel(cfg)
        self.sp = self.model._sp_enabled()
        self.dp = int(plan.dp)
        self.tp = int(plan.tp)
        self.inv_m = jnp.float32(1.0 / self.M)

        self.elastic = ElasticPlan.build(plan, devices=devices)
        self.mesh = self.elastic.mesh
        tensor_axis = "model" if self.tp > 1 else None
        (self.packed, self.in_specs, self._local_fn,
         self._repack_fn) = pack_for_shard_map(
            self.model, stage_params, n_stages=1,
            tensor_axis=tensor_axis)

        # -- the train state is only what this stage's role updates --
        self.embed_keys = (["embedding"]
                           + ([] if cfg.rotary else ["position_embedding"]))
        keys = ["layers"]
        if self.is_first:
            keys = self.embed_keys + keys
        if self.is_last:
            keys += ["final_layernorm"]
            if not self.is_first:
                keys += ["embedding"]     # tied-head replica
        self.state_keys = keys
        self.state = {k: self.packed[k] for k in keys}
        self.opt = optimizer if optimizer is not None else FusedAdam(lr=lr)
        self.opt_state = self.opt.init(self.state)

        # -- activation / accumulator placements ----------------------
        mspec = "model" if self.sp else None
        self.act_spec = P("data", None, mspec)          # (dp, mb, s, h)
        self.acts_spec = P("data", None, None, mspec)   # (dp, M, mb, s, h)
        self._P, self._NS = P, NamedSharding
        self.act_sharding = NamedSharding(self.mesh, self.act_spec)
        self.acts_sharding = NamedSharding(self.mesh, self.acts_spec)
        self.last_keys = ["final_layernorm", "embedding"]
        self._build_programs()

    # -- packing helpers (data-axis-leading accumulators) -----------------

    def sharding(self, spec):
        return self._NS(self.mesh, spec)

    def _subspecs(self, keys):
        return {k: self.in_specs[k] for k in keys}

    def _acc_specs(self, keys):
        """in_specs with a leading ``"data"`` axis on every leaf — the
        per-data-shard accumulator placement."""
        import jax
        from apex_tpu.models.gpt import _is_spec_leaf
        P = self._P
        return jax.tree_util.tree_map(
            lambda s: P(*(("data",) + tuple(s))), self._subspecs(keys),
            is_leaf=_is_spec_leaf)

    def shardings_of(self, spec_tree):
        """NamedShardings on this stage's mesh for a PartitionSpec
        pytree (e.g. a subtree of ``in_specs`` / ``_acc_specs``)."""
        import jax
        from apex_tpu.models.gpt import _is_spec_leaf
        return jax.tree_util.tree_map(
            lambda s: self._NS(self.mesh, s), spec_tree,
            is_leaf=_is_spec_leaf)

    def _local(self, tree: Dict[str, Any]):
        return self._local_fn(tree)

    def _acc_local(self, tree: Dict[str, Any]):
        import jax
        return self._local_fn(jax.tree_util.tree_map(
            lambda a: a[0], tree))

    def _acc_repack(self, tree: Dict[str, Any]):
        import jax
        return jax.tree_util.tree_map(
            lambda a: a[None], self._repack_fn(tree))

    def fresh_acc(self, keys) -> Dict[str, Any]:
        """Zeroed per-data-shard accumulator for ``keys`` — donated by
        the backward programs, so a fresh one is placed every step."""
        import jax
        import jax.numpy as jnp
        return jax.tree_util.tree_map(
            lambda leaf, spec: jax.device_put(
                jnp.zeros((self.dp,) + leaf.shape, leaf.dtype),
                self.sharding(spec)),
            {k: self.packed[k] for k in keys}, self._acc_specs(keys))

    def fresh_loss_acc(self):
        import jax
        import jax.numpy as jnp
        return jax.device_put(jnp.zeros((self.dp,), jnp.float32),
                              self.sharding(self._P("data")))

    def fresh_dx0(self, act_shape, dtype):
        """Zeroed ``(dp, M, mb, s, h)`` buffer the first stage's
        backward scatters per-microbatch input cotangents into — the
        engine-side image of the ring's ``dx0_acc``."""
        import jax
        import jax.numpy as jnp
        return jax.device_put(jnp.zeros(act_shape, dtype),
                              self.acts_sharding)

    # -- program construction ---------------------------------------------

    def _stage_fn(self):
        from apex_tpu.models.gpt import make_stage_fn
        return make_stage_fn(self.model, None)

    def _last_fn(self):
        import jax.numpy as jnp
        model = self.model

        def last_fn(lp, y, tgt, info):
            if self.sp:
                y = model._sp_gather(y)
            return jnp.mean(model.head_loss(lp, y, tgt))

        return last_fn

    def _shmap(self, body, in_specs, out_specs, donate=()):
        import jax
        from apex_tpu.utils.collectives import shard_map_compat
        fn = shard_map_compat(body, mesh=self.mesh, in_specs=in_specs,
                              out_specs=out_specs, check=False)
        return jax.jit(fn, donate_argnums=tuple(donate))

    def _build_programs(self):
        import jax
        import jax.numpy as jnp
        from apex_tpu.transformer.pipeline_parallel import JobInfo

        P = self._P
        model, M = self.model, self.M
        stage_fn = self._stage_fn()
        tmap = jax.tree_util.tree_map

        def info(m):
            return JobInfo(m, jnp.int32(self.index), jnp.int32(0))

        layer_specs = self._subspecs(["layers"])["layers"]
        layer_acc_specs = self._acc_specs(["layers"])["layers"]

        if self.is_first:
            embed_specs = self._subspecs(self.embed_keys)

            def embed_fn_of(tokens):
                # the ring's flattened-batch embed: per-token lookup,
                # so one (M*mb, s) embed is bitwise the M per-mb embeds
                def embed_fn(ep):
                    x = model.embed(ep, tokens)
                    if self.sp:
                        x = model._sp_scatter(x)
                    return x.reshape((M, -1) + x.shape[1:])
                return embed_fn

            def embed_body(ep, tokens):
                x = embed_fn_of(tokens)(self._local(ep))
                return x[None]

            self._embed = self._shmap(
                embed_body, (embed_specs, P("data")), self.acts_spec)

            def fwd0_body(lp, x_all, m):
                chunk = self._local({"layers": lp})["layers"]
                xm = _dyn0(x_all[0], m)
                return stage_fn(chunk, xm, info(m))[None]

            self._fwd = self._shmap(
                fwd0_body, (layer_specs, self.acts_spec, P()),
                self.act_spec)

            def bwd0_body(lp, x_all, dy, sacc, dx0, m):
                chunk = self._local({"layers": lp})["layers"]
                xm = _dyn0(x_all[0], m)

                def f(cp, xx):
                    return stage_fn(cp, xx, info(m))

                _, pull = jax.vjp(f, chunk, xm)
                dcp, dx = pull(dy[0])
                acc = self._acc_local({"layers": sacc})["layers"]
                acc = tmap(lambda a, g: a + g, acc, dcp)
                new_dx0 = dx0[0].at[m].add(dx)
                return (self._acc_repack({"layers": acc})["layers"],
                        new_dx0[None])

            self._bwd = self._shmap(
                bwd0_body,
                (layer_specs, self.acts_spec, self.act_spec,
                 layer_acc_specs, self.acts_spec, P()),
                (layer_acc_specs, self.acts_spec), donate=(3, 4))

            emb_acc_specs = self._acc_specs(["embedding"])["embedding"]

            def embed_bwd_body(ep, tokens, dx_all, head_eg):
                p = self._local(ep)
                _, pull = jax.vjp(embed_fn_of(tokens), p)
                (eg,) = pull(dx_all[0])
                heg = self._acc_local(
                    {"embedding": head_eg})["embedding"]
                eg = dict(eg)
                # tied weight: add the head's contribution BEFORE the
                # data pmean (the ring sums then pmeans; pmean(a)+
                # pmean(b) is not bitwise pmean(a+b))
                eg["embedding"] = tmap(jnp.add, eg["embedding"], heg)
                eg = tmap(lambda g: jax.lax.pmean(g, "data"), eg)
                return self._repack_fn(eg)

            self._embed_bwd = self._shmap(
                embed_bwd_body,
                (embed_specs, P("data"), self.acts_spec, emb_acc_specs),
                embed_specs)

        elif not self.is_last:
            def fwd_body(lp, x, m):
                chunk = self._local({"layers": lp})["layers"]
                return stage_fn(chunk, x[0], info(m))[None]

            self._fwd = self._shmap(
                fwd_body, (layer_specs, self.act_spec, P()),
                self.act_spec)

            def bwd_body(lp, x, dy, sacc, m):
                chunk = self._local({"layers": lp})["layers"]

                def f(cp, xx):
                    return stage_fn(cp, xx, info(m))

                _, pull = jax.vjp(f, chunk, x[0])
                dcp, dx = pull(dy[0])
                acc = self._acc_local({"layers": sacc})["layers"]
                acc = tmap(lambda a, g: a + g, acc, dcp)
                return (self._acc_repack({"layers": acc})["layers"],
                        dx[None])

            self._bwd = self._shmap(
                bwd_body,
                (layer_specs, self.act_spec, self.act_spec,
                 layer_acc_specs, P()),
                (layer_acc_specs, self.act_spec), donate=(3,))

        if self.is_last:
            last_fn = self._last_fn()
            state_specs = self._subspecs(["layers"] + self.last_keys)
            last_acc_specs = self._acc_specs(self.last_keys)

            def bwd_last_body(sp, targets, x, sacc, lacc, loss_acc, m):
                loc = self._local(sp)
                chunk = loc["layers"]
                lp = {k: loc[k] for k in self.last_keys}
                tgt = _dyn0(targets[0], m)

                def job(cp, lpp, xx):
                    y = stage_fn(cp, xx, info(m))
                    return y, last_fn(lpp, y, tgt, info(m))

                (y_b, lm), pull = jax.vjp(job, chunk, lp, x[0])
                # the ring's last-stage seed: zero the activation
                # cotangent, seed the loss at 1/M
                dy = tmap(jnp.zeros_like, y_b)
                dcp, dlp, dx = pull((dy, self.inv_m))
                acc = self._acc_local({"layers": sacc})["layers"]
                acc = tmap(lambda a, g: a + g, acc, dcp)
                lac = self._acc_local(lacc)
                lac = tmap(lambda a, g: a + g, lac, dlp)
                return (self._acc_repack({"layers": acc})["layers"],
                        self._acc_repack(lac), loss_acc + lm, dx[None])

            self._bwd_last = self._shmap(
                bwd_last_body,
                (state_specs, P("data"), self.act_spec,
                 layer_acc_specs, last_acc_specs, P("data"), P()),
                (layer_acc_specs, last_acc_specs, P("data"),
                 self.act_spec),
                donate=(3, 4, 5))

            fln_specs = self._subspecs(["final_layernorm"])

            def finish_last_body(lacc):
                g = self._acc_local(
                    {"final_layernorm": lacc["final_layernorm"]})
                g = tmap(lambda a: jax.lax.pmean(a, "data"), g)
                return self._repack_fn(g)

            self._finish_last = self._shmap(
                finish_last_body, (last_acc_specs,), fln_specs)

            def loss_final_body(loss_acc):
                return jax.lax.pmean(loss_acc[0] * self.inv_m, "data")

            self._loss_final = self._shmap(
                loss_final_body, (P("data"),), P())

        def finish_body(sacc):
            g = self._acc_local({"layers": sacc})
            g = tmap(lambda a: jax.lax.pmean(a, "data"), g)
            return self._repack_fn(g)["layers"]

        self._finish = self._shmap(
            finish_body, (layer_acc_specs,), layer_specs)

        self._opt_step = jax.jit(
            lambda g, p, o: self.opt.step(g, p, o),
            donate_argnums=(1, 2))

    # -- execution (called by the engine in schedule order) ---------------

    def run_embed(self, tokens):
        return self._embed({k: self.state[k] for k in self.embed_keys},
                           tokens)

    def run_fwd(self, x, m):
        import jax.numpy as jnp
        if self.is_last:
            raise RuntimeError("the last stage's forward is folded "
                               "into its joint backward")
        return self._fwd(self.state["layers"], x, jnp.int32(m))

    def run_bwd(self, x, dy, sacc, m, *, dx0=None):
        import jax.numpy as jnp
        if self.is_first:
            return self._bwd(self.state["layers"], x, dy, sacc, dx0,
                             jnp.int32(m))
        return self._bwd(self.state["layers"], x, dy, sacc,
                         jnp.int32(m))

    def run_bwd_last(self, targets, x, sacc, lacc, loss_acc, m):
        import jax.numpy as jnp
        sp = {k: self.state[k] for k in ["layers"] + self.last_keys}
        return self._bwd_last(sp, targets, x, sacc, lacc, loss_acc,
                              jnp.int32(m))

    def run_embed_bwd(self, tokens, dx0, head_eg):
        return self._embed_bwd(
            {k: self.state[k] for k in self.embed_keys}, tokens, dx0,
            head_eg)

    def run_finish_layers(self, sacc):
        return self._finish(sacc)

    def run_finish_last(self, lacc):
        return self._finish_last(lacc)

    def run_loss_final(self, loss_acc):
        return self._loss_final(loss_acc)

    def apply_grads(self, grads: Dict[str, Any]) -> None:
        """One optimizer step on this stage's state (donated in
        place).  ``grads`` must cover exactly ``state_keys``."""
        g = {k: grads[k] for k in self.state_keys}
        self.state, self.opt_state = self._opt_step(
            g, self.state, self.opt_state)
