"""Explicit slow-link (DCN) channel between MPMD stage programs.

Inside a pod, activations hop between ring-pipeline stages as
``ppermute`` collectives compiled into the one SPMD program.  Across
pods there is no shared program and no ICI: the MPMD engine moves
stage boundaries through a *channel* object — an explicit, host-driven
transfer with its own failure mode (:class:`DcnTimeout`, retryable)
and its own cost (per-hop latency alpha + inverse bandwidth beta, or a
fitted ``dcn`` curve from
:class:`~apex_tpu.observability.costmodel.CostModel`).

:class:`LocalDcnChannel` is the single-process realisation used by
tests and the CPU dryrun: the payload round-trips through host memory
(``device_get`` → ``device_put`` onto the destination stage's mesh),
which preserves bytes exactly — the bitwise parity contract of the
engine does not bend for the transport.  Latency is *accounted*, not
slept (``simulated_seconds``), so CI stays fast while the numbers feed
the same schedule simulator the autotuner prices plans with.  Faults
come from the shared :class:`~apex_tpu.resilience.faults.FaultInjector`
(kind ``"dcn_fault"``): one scheduled fault drops one transfer attempt,
and because :meth:`~apex_tpu.resilience.faults.FaultInjector.check_dcn`
consumes the fault, the engine's retry of the SAME send succeeds.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

__all__ = ["DcnTimeout", "Edge", "LocalDcnChannel"]


class DcnTimeout(RuntimeError):
    """A cross-pod transfer dropped or timed out.  Retryable: the
    payload is still owned by the sending stage, so the engine
    re-issues the identical send (bounded by the channel's
    ``max_retries``)."""

    def __init__(self, step: int, edge: "Edge", attempt: int):
        super().__init__(
            f"DCN transfer {edge.src}->{edge.dst} dropped at step "
            f"{step} (attempt {attempt})")
        self.step = step
        self.edge = edge
        self.attempt = attempt


@dataclasses.dataclass(frozen=True)
class Edge:
    """One directed stage boundary; ``link_class`` decides whether the
    channel's DCN pricing/faulting applies (``"ici"`` edges transfer
    for free — they model same-pod hops routed through the engine for
    uniformity)."""
    src: int
    dst: int
    link_class: str = "dcn"


class LocalDcnChannel:
    """Single-process DCN channel: byte-exact host round-trip plus
    accounted latency and injectable faults.

    ``alpha_s``/``beta_s_per_byte`` price a transfer as
    ``alpha + beta * nbytes``; alternatively
    :meth:`from_cost_model` pulls the coefficients from a fitted
    ``dcn`` ``ppermute`` curve so the channel and the autotuner price
    the same fabric identically.
    """

    def __init__(self, *, alpha_s: float = 0.0,
                 beta_s_per_byte: float = 0.0,
                 fault_injector=None, max_retries: int = 2):
        if max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, "
                             f"got {max_retries}")
        self.alpha_s = float(alpha_s)
        self.beta_s_per_byte = float(beta_s_per_byte)
        self.fault_injector = fault_injector
        self.max_retries = int(max_retries)
        # -- transfer ledger (tests + bench read these) ---------------
        self.sends = 0
        self.retries = 0
        self.bytes_sent = 0
        self.simulated_seconds = 0.0

    @classmethod
    def from_cost_model(cls, cost_model, *, link_class: str = "dcn",
                        **kw) -> "LocalDcnChannel":
        """Build from a fitted :class:`CostModel`: a point-to-point
        hop is priced off the ``ppermute`` curve of ``link_class``
        (every ring op reduces to per-hop alpha + per-byte beta)."""
        fit = cost_model._fit_for("ppermute", "f32", link_class)
        return cls(alpha_s=fit.alpha_s,
                   beta_s_per_byte=fit.beta_s_per_byte, **kw)

    # -- transfer ---------------------------------------------------------

    def transfer_seconds(self, nbytes: int) -> float:
        return self.alpha_s + self.beta_s_per_byte * float(nbytes)

    @staticmethod
    def _nbytes(tree: Any) -> int:
        import jax
        return sum(x.size * x.dtype.itemsize
                   for x in jax.tree_util.tree_leaves(tree))

    def send(self, value: Any, dst_shardings: Any = None, *,
             step: int = 0, edge: Optional[Edge] = None,
             _attempt: int = 0) -> Any:
        """One transfer attempt of pytree ``value`` onto the
        destination placement (``dst_shardings``: one sharding for
        every leaf, or a matching pytree of shardings).  Raises
        :class:`DcnTimeout` when a ``dcn_fault`` is scheduled for this
        ``step`` on a DCN-class edge."""
        import jax

        edge = edge if edge is not None else Edge(-1, -1)
        dcn = edge.link_class == "dcn"
        if dcn and self.fault_injector is not None \
                and self.fault_injector.check_dcn(step) is not None:
            raise DcnTimeout(step, edge, _attempt)
        host = jax.device_get(value)
        nbytes = self._nbytes(host)
        self.sends += 1
        self.bytes_sent += nbytes
        if dcn:
            self.simulated_seconds += self.transfer_seconds(nbytes)
        if dst_shardings is None:
            return jax.tree_util.tree_map(jax.numpy.asarray, host)
        if jax.tree_util.treedef_is_leaf(
                jax.tree_util.tree_structure(dst_shardings)):
            return jax.tree_util.tree_map(
                lambda x: jax.device_put(x, dst_shardings), host)
        return jax.tree_util.tree_map(
            lambda x, sh: jax.device_put(x, sh), host, dst_shardings)

    def send_with_retry(self, value: Any, dst_shardings: Any = None, *,
                        step: int = 0,
                        edge: Optional[Edge] = None) -> Any:
        """The engine's send: retry :class:`DcnTimeout` up to
        ``max_retries`` times (each consumed fault frees the retry to
        succeed); re-raises when the budget is exhausted."""
        last: Optional[DcnTimeout] = None
        for attempt in range(self.max_retries + 1):
            try:
                return self.send(value, dst_shardings, step=step,
                                 edge=edge, _attempt=attempt)
            except DcnTimeout as e:
                last = e
                self.retries += 1
        assert last is not None
        raise last
