"""Cross-pod MPMD pipeline parallelism over the two-tier fabric.

One pod = one SPMD program on its ICI mesh (the ring pipeline of
:mod:`apex_tpu.transformer.pipeline_parallel` stays the intra-pod fast
path and the bitwise reference).  Across pods there is no shared
program: each pipeline stage compiles separately
(:class:`StageProgram`), a host-driven schedule
(:mod:`~apex_tpu.mpmd.schedule`) orders the work, and stage boundaries
move through an explicit DCN channel
(:class:`LocalDcnChannel` / retryable :class:`DcnTimeout`).  The
:class:`MpmdPipeline` engine binds them and stays bitwise-equal (f32)
to the ring engine at matching layouts.

Plans: set ``n_pods > 1`` (and optionally per-pod ``stage_plans``) on
a :class:`~apex_tpu.parallel.plan.ParallelPlan`;
``tools/autotune.py --mpmd`` enumerates two-tier plans against
per-link-class :class:`~apex_tpu.observability.costmodel.CostModel`
fits.  See ``docs/parallel.md`` ("Two-tier MPMD") for the decision
table versus single-mesh SPMD.
"""

from apex_tpu.mpmd.channel import DcnTimeout, Edge, LocalDcnChannel
from apex_tpu.mpmd.engine import MPMD_PLAN_FILE, MpmdPipeline
from apex_tpu.mpmd.schedule import (SCHEDULES, Op, edge_link_classes,
                                    merge_stage_ops, schedule_1f1b,
                                    schedule_dcn_hiding, simulate,
                                    stage_ops_1f1b, validate_order)
from apex_tpu.mpmd.stage import StageProgram

__all__ = [
    "DcnTimeout", "Edge", "LocalDcnChannel", "MpmdPipeline",
    "MPMD_PLAN_FILE", "StageProgram", "Op", "SCHEDULES",
    "schedule_1f1b", "schedule_dcn_hiding", "stage_ops_1f1b",
    "merge_stage_ops", "validate_order", "edge_link_classes",
    "simulate",
]
