"""``MpmdPipeline`` — host-driven cross-pod pipeline training.

The engine owns one :class:`~apex_tpu.mpmd.stage.StageProgram` per
pipeline stage (each with its own mesh and intra-pod
:class:`~apex_tpu.parallel.plan.ParallelPlan`), executes jobs in the
order a :mod:`~apex_tpu.mpmd.schedule` produced, and moves stage
boundaries through a :class:`~apex_tpu.mpmd.channel.LocalDcnChannel`
— retrying :class:`~apex_tpu.mpmd.channel.DcnTimeout` drops in place.

Numerics contract: at ``n_pods = pp`` with homogeneous intra-pod
plans, one :meth:`loss_and_grads` is **bitwise-equal (f32)** to the
single-mesh ring engine (:func:`~apex_tpu.models.gpt.pipeline_step`
over a ``dp x pp`` mesh) — the stage programs replay the ring's exact
per-microbatch accumulation (ascending ``m``, loss cotangent seeded
``1/M``, per-data-shard partial sums pmean'd at the end), and the
channel moves bytes verbatim.  Asserted by
``__graft_entry__._dryrun_mpmd`` and ``tests/test_mpmd.py``.

Tied embedding across pods: the last stage ships its per-data-shard
head gradient to the first stage, which merges it into the embedding
pullback BEFORE the data pmean (the ring's summation order); the
resulting total then ships back so the last stage's embedding replica
applies the identical (elementwise) optimizer update — the two copies
stay bitwise in lockstep without an all-reduce spanning pods.

Integration: :meth:`save_checkpoint` writes per-stage
:class:`~apex_tpu.resilience.checkpoint.CheckpointManager` trees under
one stamped ``MPMD_PLAN.json`` (restore validates the cross-pod plan
and :meth:`restore_stage` re-seats a single killed stage);
``trace=True`` gives every stage a
:class:`~apex_tpu.observability.spans.Tracer` lane, threads
per-microbatch flow events (``dcn_send``/``dcn_recv``) through every
cross-pod hop, and records the structured per-op anatomy events
(``mpmd_op`` compute spans, ``mpmd_xfer`` link spans with their link
class, one ``mpmd_schedule`` marker per step) that
:mod:`apex_tpu.observability.anatomy` reconstructs into a measured
timeline — :meth:`anatomy_events` hands them over, and
``measure_ops=True`` additionally blocks on each op so the spans
measure device time, not dispatch.  :meth:`collector` returns the
:class:`~apex_tpu.observability.fleetobs.FleetCollector` whose
``continuity()`` must come back unbroken.
"""

from __future__ import annotations

import contextlib
import json
import os
from typing import Any, Dict, List, Optional

from apex_tpu.mpmd.channel import DcnTimeout, Edge, LocalDcnChannel
from apex_tpu.mpmd.schedule import SCHEDULES, edge_link_classes
from apex_tpu.mpmd.stage import StageProgram

__all__ = ["MpmdPipeline", "MPMD_PLAN_FILE"]

MPMD_PLAN_FILE = "MPMD_PLAN.json"
_PLAN_VERSION = 1


class MpmdPipeline:
    """Cross-pod MPMD pipeline over per-stage compiled programs.

    ``model_kw`` are the serial :class:`~apex_tpu.models.gpt.GPTConfig`
    kwargs of the FULL model (``num_layers`` total); ``params`` its
    serial-layout init; ``plan`` the cross-pod
    :class:`~apex_tpu.parallel.plan.ParallelPlan` (``pp`` = stage
    count, ``n_pods`` pod blocks, optional per-pod ``stage_plans``).
    """

    def __init__(self, model_kw: Dict[str, Any], params, plan, *,
                 devices=None, lr: float = 1e-3, channel=None,
                 fault_injector=None, schedule: str = "1f1b",
                 trace: bool = False, measure_ops: bool = False):
        import jax

        from apex_tpu.parallel.plan import ParallelPlan

        if plan.pp < 2:
            raise ValueError(
                f"MPMD needs pp >= 2 (got pp={plan.pp}): a one-stage "
                "pipeline has no cross-pod edges — use the single-mesh "
                "engines")
        if plan.n_virtual != 1:
            raise ValueError(
                "MPMD stages are whole programs; the interleaved "
                "schedule (n_virtual > 1) only exists inside the ring "
                "engine's scan")
        if schedule not in SCHEDULES:
            raise ValueError(f"unknown schedule {schedule!r}; "
                             f"one of {sorted(SCHEDULES)}")
        self.plan = plan
        self.n_stages = int(plan.pp)
        self.M = int(plan.n_microbatches)
        self.dp = int(plan.dp)
        self.schedule_name = schedule
        self.order = SCHEDULES[schedule](self.n_stages, self.M)
        self._edge_class = edge_link_classes(self.n_stages, plan.n_pods)
        self.channel = (channel if channel is not None
                        else LocalDcnChannel(
                            fault_injector=fault_injector))

        kw = dict(model_kw)
        n_layers = int(kw.pop("num_layers"))
        if n_layers % self.n_stages:
            raise ValueError(
                f"num_layers ({n_layers}) must divide into pp "
                f"({self.n_stages}) equal stage chunks")
        lpc = n_layers // self.n_stages
        for drop in ("tensor_parallel_size", "axis_name",
                     "sequence_parallel"):
            kw.pop(drop, None)

        per_pod = self.n_stages // plan.n_pods
        devices = list(devices) if devices is not None else jax.devices()
        self.stages: List[StageProgram] = []
        cursor = 0
        for i in range(self.n_stages):
            pod = i // per_pod
            if plan.stage_plans is not None:
                sub = plan.stage_plans[pod]
            else:
                sub = ParallelPlan(
                    dp=plan.dp, tp=plan.tp,
                    sequence_parallel=plan.sequence_parallel)
            from apex_tpu.models.gpt import GPTConfig
            cfg = GPTConfig(
                num_layers=lpc, tensor_parallel_size=sub.tp,
                axis_name="model" if sub.tp > 1 else None,
                sequence_parallel=sub.sequence_parallel, **kw)
            stage_params = {
                "embedding": params["embedding"],
                "final_layernorm": params["final_layernorm"],
                "layers": params["layers"][i * lpc:(i + 1) * lpc],
            }
            if "position_embedding" in params:
                stage_params["position_embedding"] = \
                    params["position_embedding"]
            need = sub.dp * sub.tp
            if cursor + need > len(devices):
                raise ValueError(
                    f"stage {i} needs devices [{cursor}, "
                    f"{cursor + need}) but only {len(devices)} are "
                    f"available; the cross-pod plan wants "
                    f"{plan.n_devices} in total")
            self.stages.append(StageProgram(
                cfg, stage_params, stage_index=i,
                n_stages=self.n_stages, n_microbatches=self.M,
                plan=sub, devices=devices[cursor:cursor + need],
                lr=lr))
            cursor += need

        # measure_ops implies trace: each op's result is blocked on
        # inside its span, so span durations are honest device times —
        # at the cost of serializing dispatch (an anatomy/profiling
        # mode, not the production fast path)
        self.measure_ops = bool(measure_ops)
        self.tracers = None
        if trace or self.measure_ops:
            from apex_tpu.observability.spans import Tracer
            self.tracers = [Tracer(id_tag=f"stage{i}")
                            for i in range(self.n_stages)]
        self.step_count = 0

    # -- transfers --------------------------------------------------------

    def _link_class(self, src: int, dst: int) -> str:
        if abs(src - dst) == 1:
            return self._edge_class.get(min(src, dst), "ici")
        # the tied-embedding sync between the first and last pod
        return "dcn" if self.plan.n_pods > 1 else "ici"

    def _block(self, tree):
        """Wait for every leaf (anatomy mode): span durations then
        measure the work, not just its dispatch."""
        if self.measure_ops:
            import jax
            for leaf in jax.tree_util.tree_leaves(tree):
                blocker = getattr(leaf, "block_until_ready", None)
                if blocker is not None:
                    blocker()
        return tree

    def _op_span(self, s: int, kind: str, m: int, step: int, **extra):
        """The per-op structured trace span anatomy reconstructs from
        (no-op without tracing)."""
        if self.tracers is None:
            return contextlib.nullcontext()
        return self.tracers[s].span(
            "mpmd_op", device=False, op=kind, stage=s, mb=m,
            step=step, **extra)

    def _transfer(self, src: int, dst: int, value, dst_shardings, *,
                  step: int, ctx=None, phase: str = "act"):
        from apex_tpu.observability.fleetobs import emit_flow
        edge = Edge(src, dst, self._link_class(src, dst))
        # phase is "fwd.m3" / "bwd.m5" for schedule edges and
        # "head_grad" / "embed_total" for the tied-embedding sync
        kind, _, mbs = phase.partition(".m")
        cm = contextlib.nullcontext()
        if self.tracers is not None:
            emit_flow(self.tracers[src], ctx, "dcn_send",
                      edge=f"{src}->{dst}", payload=phase)
            cm = self.tracers[src].span(
                "mpmd_xfer", device=False, src=src, dst=dst,
                kind=kind, mb=int(mbs) if mbs else -1,
                link_class=edge.link_class, step=step)
        with cm:
            out = self._block(self.channel.send_with_retry(
                value, dst_shardings, step=step, edge=edge))
        if self.tracers is not None:
            emit_flow(self.tracers[dst], ctx, "dcn_recv",
                      edge=f"{src}->{dst}", payload=phase)
        return out

    # -- tied-embedding repacking across heterogeneous tp -----------------

    def _convert_embed(self, tree, src: StageProgram,
                       dst: StageProgram, *, leading_dp: bool):
        """Re-stack a packed embedding-gradient tree from ``src``'s tp
        layout to ``dst``'s.  Pure split/concat on host, so f32 values
        round-trip bitwise; a no-op when the layouts match."""
        if src.tp == dst.tp:
            return tree
        import jax
        import numpy as np
        from apex_tpu.models.gpt import _is_sharded, _is_spec_leaf
        specs = src.model.partition_specs()["embedding"]
        off = 1 if leading_dp else 0

        def shard_dim(s):
            for d, a in enumerate(s):
                if a is not None:
                    return d
            return None

        def conv(s, a):
            if not _is_sharded(s):
                return a
            d = shard_dim(s) + off + 1   # behind the tp-stack axis
            a = np.asarray(a)
            serial = np.concatenate(
                [a[(slice(None),) * off + (r,)]
                 for r in range(a.shape[off])], axis=d - 1)
            parts = np.split(serial, dst.tp, axis=d - 1)
            return np.stack(parts, axis=off)

        return jax.tree_util.tree_map(conv, specs, tree,
                                      is_leaf=_is_spec_leaf)

    # -- one training step ------------------------------------------------

    def _place_inputs(self, tokens, targets):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        st0, stl = self.stages[0], self.stages[-1]
        tokens = jnp.asarray(tokens)
        targets = jnp.asarray(targets)
        rows, seq = tokens.shape
        if rows != self.dp * self.M * (rows // (self.dp * self.M)):
            raise ValueError(
                f"tokens rows ({rows}) must be dp*M*microbatch "
                f"(dp={self.dp}, M={self.M})")
        mb = rows // (self.dp * self.M)
        tokens_d = jax.device_put(tokens, st0.sharding(P("data")))
        targets_d = jax.device_put(
            targets.reshape(self.dp, self.M, mb, seq),
            stl.sharding(P("data")))
        return tokens_d, targets_d

    def loss_and_grads(self, tokens, targets, *,
                       step: Optional[int] = None):
        """Run one full schedule; returns ``(loss, per-stage grads)``
        with each stage's grads in ITS packed layout, keyed by its
        ``state_keys``."""
        from apex_tpu.observability.fleetobs import (TraceContext,
                                                     emit_flow)
        step = self.step_count if step is None else int(step)
        S, M = self.n_stages, self.M
        st0, stl = self.stages[0], self.stages[-1]
        tokens_d, targets_d = self._place_inputs(tokens, targets)

        accs = [st.fresh_acc(["layers"])["layers"]
                for st in self.stages]
        lacc = stl.fresh_acc(stl.last_keys)
        loss_acc = stl.fresh_loss_acc()
        x_all = st0.run_embed(tokens_d)
        dx0 = st0.fresh_dx0(x_all.shape, x_all.dtype)

        ctxs = {}
        if self.tracers is not None:
            ctxs = {m: TraceContext.mint(f"s{step}.m{m}")
                    for m in range(M)}
            self.tracers[0].instant(
                "mpmd_schedule", n_stages=S, n_microbatches=M,
                schedule=self.schedule_name, step=step, dp=self.dp,
                link_classes={str(e): c for e, c
                              in self._edge_class.items()},
                measured=self.measure_ops)
        stash_x: Dict[Any, Any] = {}
        stash_dy: Dict[Any, Any] = {}

        for s, kind, m in self.order:
            st = self.stages[s]
            ctx = ctxs.get(m)
            if kind == "fwd":
                if st.is_last:
                    continue       # folded into the joint backward
                # interior stages keep their input in the stash: the
                # backward recomputes the stage forward from it
                x = x_all if st.is_first else stash_x[(s, m)]
                with self._op_span(s, "fwd", m, step):
                    y = self._block(st.run_fwd(x, m))
                nxt = self.stages[s + 1]
                stash_x[(s + 1, m)] = self._transfer(
                    s, s + 1, y, nxt.act_sharding, step=step, ctx=ctx,
                    phase=f"fwd.m{m}")
            else:
                if st.is_last:
                    with self._op_span(s, "bwd", m, step,
                                       folded_fwd=True):
                        accs[s], lacc, loss_acc, dx = st.run_bwd_last(
                            targets_d, stash_x.pop((s, m)), accs[s],
                            lacc, loss_acc, m)
                        self._block(dx)
                elif st.is_first:
                    with self._op_span(s, "bwd", m, step):
                        accs[s], dx0 = st.run_bwd(
                            x_all, stash_dy.pop((s, m)), accs[s], m,
                            dx0=dx0)
                        self._block(dx0)
                    if self.tracers is not None:
                        emit_flow(self.tracers[0], ctx, "mb_done",
                                  final=True)
                    continue
                else:
                    with self._op_span(s, "bwd", m, step):
                        accs[s], dx = st.run_bwd(
                            stash_x.pop((s, m)), stash_dy.pop((s, m)),
                            accs[s], m)
                        self._block(dx)
                prv = self.stages[s - 1]
                stash_dy[(s - 1, m)] = self._transfer(
                    s, s - 1, dx, prv.act_sharding, step=step, ctx=ctx,
                    phase=f"bwd.m{m}")

        # -- tied-embedding gradient sync: last -> first -> last ------
        sync_ctx = None
        if self.tracers is not None:
            sync_ctx = TraceContext.mint(f"s{step}.sync")
        head_eg = self._transfer(
            S - 1, 0,
            self._convert_embed(lacc["embedding"], stl, st0,
                                leading_dp=True),
            st0.shardings_of(st0._acc_specs(["embedding"])["embedding"]),
            step=step, ctx=sync_ctx, phase="head_grad")
        g0 = st0.run_embed_bwd(tokens_d, dx0, head_eg)

        grads: List[Dict[str, Any]] = []
        for i, st in enumerate(self.stages):
            gi: Dict[str, Any] = {
                "layers": st.run_finish_layers(accs[i])}
            if st.is_first:
                gi.update(g0)
            if st.is_last:
                gi.update(st.run_finish_last(lacc))
                if not st.is_first:
                    gi["embedding"] = self._transfer(
                        0, S - 1,
                        self._convert_embed(g0["embedding"], st0, stl,
                                            leading_dp=False),
                        stl.shardings_of(stl.in_specs["embedding"]),
                        step=step, ctx=sync_ctx, phase="embed_total")
            grads.append(gi)
        if self.tracers is not None:
            emit_flow(self.tracers[S - 1], sync_ctx, "sync_done",
                      final=True)
        loss = stl.run_loss_final(loss_acc)
        return loss, grads

    def train_step(self, tokens, targets, *,
                   step: Optional[int] = None):
        """Full schedule + per-stage (donated) optimizer step."""
        loss, grads = self.loss_and_grads(tokens, targets, step=step)
        for st, g in zip(self.stages, grads):
            st.apply_grads(g)
        self.step_count += 1
        return loss

    # -- checkpointing ----------------------------------------------------

    def _manager(self, directory: str, i: int, keep: int = 2):
        from apex_tpu.resilience.checkpoint import CheckpointManager
        st = self.stages[i]
        return CheckpointManager(
            os.path.join(directory, f"stage_{i:02d}"), keep=keep,
            topology=st.plan.topology(), parallel_plan=st.plan)

    def save_checkpoint(self, directory: str, step: int, *,
                        keep: int = 2) -> None:
        """Per-stage checkpoint trees under one stamped cross-pod
        plan: ``directory/MPMD_PLAN.json`` + ``directory/stage_XX/``
        per stage — each stage's manifest carries ITS intra-pod plan,
        the top-level stamp the plan that binds them."""
        os.makedirs(directory, exist_ok=True)
        with open(os.path.join(directory, MPMD_PLAN_FILE), "w",
                  encoding="utf-8") as f:
            json.dump({"version": _PLAN_VERSION,
                       "n_stages": self.n_stages,
                       "plan": self.plan.to_dict()}, f, indent=1)
        for i, st in enumerate(self.stages):
            self._manager(directory, i, keep).save(
                step, {"state": st.state, "opt": st.opt_state})

    def _check_plan_stamp(self, directory: str) -> None:
        path = os.path.join(directory, MPMD_PLAN_FILE)
        with open(path, encoding="utf-8") as f:
            stamp = json.load(f)
        if stamp.get("plan") != self.plan.to_dict():
            raise ValueError(
                f"checkpoint at {directory} was saved under cross-pod "
                f"plan {stamp.get('plan')} but this engine runs "
                f"{self.plan.to_dict()}; restore onto a matching "
                "MpmdPipeline (per-stage states are packed for their "
                "stamped intra-pod layouts)")

    def restore_stage(self, i: int, directory: str, *,
                      step: Optional[int] = None,
                      _checked: bool = False) -> int:
        """Re-seat ONE stage from its checkpoint tree — the
        kill-one-stage recovery path: the surviving stages keep their
        live state, the replaced pod reloads."""
        if not _checked:
            self._check_plan_stamp(directory)
        st = self.stages[i]
        loaded, got = self._manager(directory, i).restore(
            {"state": st.state, "opt": st.opt_state}, step=step)
        st.state = loaded["state"]
        st.opt_state = loaded["opt"]
        return got

    def restore_checkpoint(self, directory: str, *,
                           step: Optional[int] = None) -> int:
        """Restore every stage from the newest (or pinned) step after
        validating the cross-pod plan stamp."""
        self._check_plan_stamp(directory)
        got = None
        for i in range(self.n_stages):
            s = self.restore_stage(i, directory, step=step,
                                   _checked=True)
            if got is not None and s != got:
                raise ValueError(
                    f"stage {i} restored step {s} but earlier stages "
                    f"restored {got}; the per-stage trees are torn — "
                    "pin step= to a step present in every stage tree")
            got = s
        self.step_count = int(got)
        return int(got)

    # -- observability ----------------------------------------------------

    def collector(self):
        """A :class:`FleetCollector` with one lane per stage (requires
        ``trace=True``)."""
        if self.tracers is None:
            raise ValueError("engine built with trace=False; pass "
                             "trace=True to collect per-stage lanes")
        from apex_tpu.observability.fleetobs import FleetCollector
        c = FleetCollector()
        for i, tr in enumerate(self.tracers):
            c.add_replica(f"stage{i}", tracer=tr)
        return c

    def anatomy_events(self) -> List[dict]:
        """Every stage tracer's events, merged (the tracers share one
        clock, so timestamps are directly comparable) — the input
        :func:`apex_tpu.observability.anatomy.reconstruct` expects.
        Requires ``trace=True``; pass ``measure_ops=True`` for span
        durations that include device time."""
        if self.tracers is None:
            raise ValueError("engine built with trace=False; pass "
                             "trace=True (or measure_ops=True) to "
                             "record per-op anatomy events")
        events: List[dict] = []
        for tr in self.tracers:
            events.extend(tr.events)
        return events
