"""FusedLAMB — TPU rebuild of ``apex/optimizers/fused_lamb.py``.

Apex's two-phase design is preserved: phase 1 is ``multi_tensor_l2norm``
over the gradients (global norm for clipping), phase 2 is the two-stage
``multi_tensor_lamb`` (moments+raw update, then per-tensor trust-ratio
apply).  Here phase-2 stage 1 also emits per-row ‖u‖²/‖p‖² partial sums, the
per-tensor norms come from one segment-sum over the row→tensor map, and
stage 2 applies the trust ratio with a per-row gather — all inside the same
jitted step.

``max_grad_norm`` (default 1.0, apex parity) clips by the global gradient
norm; ``use_nvlamb`` applies the trust ratio even where the param norm is
zero (NVLAMB variant).
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.optimizers.base import (FusedOptimizer, per_tensor_ratio_rows,
                                      per_tensor_sums)
from apex_tpu.ops import multi_tensor as K

_f32 = jnp.float32


class FusedLAMB(FusedOptimizer):
    def __init__(self, params=None, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-6, weight_decay=0.01,
                 amsgrad=False, adam_w_mode=True, grad_averaging=True,
                 set_grad_none=True, max_grad_norm=1.0, use_nvlamb=False,
                 **kw):
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad "
                               "variant.")  # apex parity
        del params, set_grad_none
        super().__init__(lr, weight_decay=weight_decay, betas=tuple(betas),
                         eps=eps, bias_correction=bool(bias_correction),
                         adam_w_mode=bool(adam_w_mode),
                         grad_averaging=bool(grad_averaging),
                         max_grad_norm=max_grad_norm,
                         use_nvlamb=bool(use_nvlamb), **kw)

    def _init_bucket(self, info):
        shape = (info.meta.nrows, 128)
        return {"m": jnp.zeros(shape, _f32), "v": jnp.zeros(shape, _f32)}

    def _pre_step(self, layout, packed_grads, state, *, lr, grad_scale):
        # Phase 1 (apex: multi_tensor_l2norm over grads): global grad norm
        # → clip factor folded into the stage-1 kernel as a multiplier.
        total_sq = jnp.zeros((), _f32)
        for info in layout.buckets:
            rowsq, _ = K.l2norm_rowsq_packed(packed_grads[info.key],
                                             block_rows=self.block_rows)
            total_sq = total_sq + jnp.sum(rowsq)
        gnorm = jnp.sqrt(total_sq) * jnp.asarray(grad_scale, _f32)
        max_norm = jnp.asarray(self.defaults["max_grad_norm"], _f32)
        clip = jnp.where(gnorm > max_norm, max_norm / gnorm, 1.0)
        return {"global_grad_clip": clip}

    def _update_bucket(self, info, g, p, st, hyper, step_count, grad_scale,
                       noop, extras):
        beta1, beta2 = hyper["betas"]
        bc1, bc2 = self._bias_corrections(hyper, step_count)
        u, m_new, v_new, usq, psq = K.lamb_stage1_packed(
            g, p, st["m"], st["v"], beta1=beta1, beta2=beta2,
            eps=hyper["eps"], weight_decay=hyper["weight_decay"],
            bias_correction1=bc1, bias_correction2=bc2,
            grad_scale=grad_scale,
            global_grad_clip=extras["global_grad_clip"],
            grad_averaging=hyper["grad_averaging"],
            adam_w_mode=hyper["adam_w_mode"], noop_flag=noop,
            block_rows=self.block_rows)
        p_norm = jnp.sqrt(per_tensor_sums(info.meta, psq))
        u_norm = jnp.sqrt(per_tensor_sums(info.meta, usq))
        if hyper["use_nvlamb"]:
            ratio = jnp.where(u_norm > 0, p_norm / u_norm, 1.0)
        else:
            ratio = jnp.where((p_norm > 0) & (u_norm > 0),
                              p_norm / u_norm, 1.0)
        row_ratio = per_tensor_ratio_rows(info.meta, ratio)
        p_new = K.lamb_stage2_packed(u, p, row_ratio, lr=hyper["lr"],
                                     noop_flag=noop,
                                     block_rows=self.block_rows)
        return p_new, {"m": m_new, "v": v_new}

    # -- per-leaf (bucketed=False) layout -----------------------------------

    def _init_leaves(self, info, ps):
        return {"m": [jnp.zeros(p.shape, _f32) for p in ps],
                "v": [jnp.zeros(p.shape, _f32) for p in ps]}

    def _pre_step_leaves(self, layout, g_leaves, state, *, lr, grad_scale):
        total_sq = sum(jnp.sum(jnp.square(g.astype(_f32)))
                       for g in g_leaves)
        gnorm = jnp.sqrt(total_sq) * jnp.asarray(grad_scale, _f32)
        max_norm = jnp.asarray(self.defaults["max_grad_norm"], _f32)
        clip = jnp.where(gnorm > max_norm, max_norm / gnorm, 1.0)
        return {"global_grad_clip": clip}

    def _update_leaves(self, info, gs, ps, st, hyper, step_count, grad_scale,
                       noop, extras):
        from apex_tpu.ops.multi_tensor import _lamb_stage1_math
        beta1, beta2 = hyper["betas"]
        bc1, bc2 = self._bias_corrections(hyper, step_count)
        beta3 = 1.0 - beta1 if hyper["grad_averaging"] else 1.0
        scal = jnp.stack([jnp.asarray(s, _f32) for s in
                          (beta1, beta2, hyper["eps"],
                           hyper["weight_decay"], bc1, bc2, grad_scale,
                           extras["global_grad_clip"], beta3)])
        skip = False if noop is None else (noop != 0)
        lr_ = jnp.asarray(hyper["lr"], _f32)
        new_ps, ms, vs = [], [], []
        for g, p, m, v in zip(gs, ps, st["m"], st["v"]):
            # the (1, n) view makes the stage-1 kernel math's axis-1 row
            # sums the per-TENSOR sums — same single-source update
            p1 = p.astype(_f32).reshape(1, -1)
            u, m2, v2, usq, psq = _lamb_stage1_math(
                hyper["adam_w_mode"], scal, skip,
                g.astype(_f32).reshape(1, -1), p1,
                m.reshape(1, -1), v.reshape(1, -1))
            p_norm = jnp.sqrt(psq[0, 0])
            u_norm = jnp.sqrt(usq[0, 0])
            if hyper["use_nvlamb"]:
                ratio = jnp.where(u_norm > 0, p_norm / u_norm, 1.0)
            else:
                ratio = jnp.where((p_norm > 0) & (u_norm > 0),
                                  p_norm / u_norm, 1.0)
            p2 = jnp.where(skip, p1, p1 - lr_ * ratio * u)
            new_ps.append(p2.reshape(p.shape))
            ms.append(m2.reshape(p.shape))
            vs.append(v2.reshape(p.shape))
        return new_ps, {"m": ms, "v": vs}


class FusedMixedPrecisionLamb(FusedLAMB):
    """Apex ``fused_mixed_precision_lamb.py``: LAMB with fp32 master weights
    and low-precision model params — here simply FusedLAMB with
    ``master_weights=True`` (the base class owns the master-copy plumbing).
    """

    def __init__(self, params=None, reduced_precision_dtype=None, **kw):
        kw.setdefault("master_weights", True)
        self.reduced_precision_dtype = reduced_precision_dtype
        super().__init__(params, **kw)
