from apex_tpu.optimizers.base import FusedOptimizer
from apex_tpu.optimizers.fused_adam import FusedAdam
from apex_tpu.optimizers.fused_sgd import FusedSGD
from apex_tpu.optimizers.fused_lamb import FusedLAMB, FusedMixedPrecisionLamb
from apex_tpu.optimizers.fused_novograd import FusedNovoGrad
from apex_tpu.optimizers.fused_adagrad import FusedAdagrad

__all__ = [
    "FusedOptimizer",
    "FusedAdam",
    "FusedSGD",
    "FusedLAMB",
    "FusedMixedPrecisionLamb",
    "FusedNovoGrad",
    "FusedAdagrad",
]
