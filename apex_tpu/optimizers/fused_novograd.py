"""FusedNovoGrad — TPU rebuild of ``apex/optimizers/fused_novograd.py``.

NovoGrad keeps the second moment per *tensor* (one scalar per layer), not
per element: ``v_t = beta2*v + (1-beta2)*||g||²`` (init ``v_0 = ||g||²``).
Per-tensor grad norms come from the packed l2norm kernel + a segment-sum;
the elementwise stage is one fused kernel with the per-tensor ``sqrt(v)``
broadcast per row.  ``reg_inside_moment`` puts weight decay inside the
moment (apex option); ``norm_type`` 2 only (apex also only implements 2).
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.optimizers.base import (FusedOptimizer, per_tensor_ratio_rows,
                                      per_tensor_sums)
from apex_tpu.ops import multi_tensor as K

_f32 = jnp.float32


class FusedNovoGrad(FusedOptimizer):
    def __init__(self, params=None, lr=1e-3, bias_correction=True,
                 betas=(0.95, 0.98), eps=1e-8, weight_decay=0.0,
                 amsgrad=False, reg_inside_moment=False, grad_averaging=True,
                 norm_type=2, init_zero=False, set_grad_none=True, **kw):
        if amsgrad:
            raise RuntimeError("FusedNovoGrad does not support the AMSGrad "
                               "variant.")
        if norm_type != 2:
            raise RuntimeError("FusedNovoGrad only supports l2 norm.")
        del params, set_grad_none
        super().__init__(lr, weight_decay=weight_decay, betas=tuple(betas),
                         eps=eps, bias_correction=bool(bias_correction),
                         reg_inside_moment=bool(reg_inside_moment),
                         grad_averaging=bool(grad_averaging),
                         init_zero=bool(init_zero), **kw)

    def _init_bucket(self, info):
        n = len(info.meta.shapes)
        return {"m": jnp.zeros((info.meta.nrows, 128), _f32),
                "v": jnp.zeros((n,), _f32)}

    def _update_bucket(self, info, g, p, st, hyper, step_count, grad_scale,
                       noop, extras):
        beta1, beta2 = hyper["betas"]
        rowsq, _ = K.l2norm_rowsq_packed(g, block_rows=self.block_rows)
        gnorm_sq = per_tensor_sums(info.meta, rowsq) * \
            jnp.asarray(grad_scale, _f32) ** 2
        if hyper["init_zero"]:
            v_new = beta2 * st["v"] + (1.0 - beta2) * gnorm_sq
        else:
            # apex: v initialized to the first ||g||², not zero
            v_new = jnp.where(step_count == 1, gnorm_sq,
                              beta2 * st["v"] + (1.0 - beta2) * gnorm_sq)
        if noop is not None:
            v_new = jnp.where(noop != 0, st["v"], v_new)
        # bias correction on the moment denominators (apex applies via lr)
        if hyper["bias_correction"]:
            t = step_count.astype(_f32)
            lr_eff = hyper["lr"] * jnp.sqrt(1.0 - beta2 ** t) / \
                (1.0 - beta1 ** t)
        else:
            lr_eff = hyper["lr"]
        v_row = per_tensor_ratio_rows(info.meta, v_new)
        p_new, m_new = K.novograd_packed(
            g, p, st["m"], v_row, lr=lr_eff, beta1=beta1,
            weight_decay=hyper["weight_decay"], eps=hyper["eps"],
            grad_scale=grad_scale, grad_averaging=hyper["grad_averaging"],
            reg_inside_moment=hyper["reg_inside_moment"],
            noop_flag=noop, block_rows=self.block_rows)
        return p_new, {"m": m_new, "v": v_new}

    # -- per-leaf (bucketed=False) layout -----------------------------------

    def _init_leaves(self, info, ps):
        return {"m": [jnp.zeros(p.shape, _f32) for p in ps],
                "v": [jnp.zeros((), _f32) for _ in ps]}

    def _update_leaves(self, info, gs, ps, st, hyper, step_count, grad_scale,
                       noop, extras):
        from apex_tpu.ops.multi_tensor import _novograd_math
        beta1, beta2 = hyper["betas"]
        if hyper["bias_correction"]:
            t = step_count.astype(_f32)
            lr_eff = hyper["lr"] * jnp.sqrt(1.0 - beta2 ** t) / \
                (1.0 - beta1 ** t)
        else:
            lr_eff = hyper["lr"]
        beta3 = 1.0 - beta1 if hyper["grad_averaging"] else 1.0
        scal = jnp.stack([jnp.asarray(s, _f32) for s in
                          (lr_eff, beta1, hyper["weight_decay"],
                           hyper["eps"], grad_scale, beta3)])
        skip = False if noop is None else (noop != 0)
        new_ps, ms, vs = [], [], []
        for g, p, m, v in zip(gs, ps, st["m"], st["v"]):
            gf = g.astype(_f32)
            gnorm_sq = jnp.sum(gf * gf) * jnp.asarray(grad_scale, _f32) ** 2
            if hyper["init_zero"]:
                v2 = beta2 * v + (1.0 - beta2) * gnorm_sq
            else:
                v2 = jnp.where(step_count == 1, gnorm_sq,
                               beta2 * v + (1.0 - beta2) * gnorm_sq)
            if noop is not None:
                v2 = jnp.where(noop != 0, v, v2)
            p2, m2 = _novograd_math(
                bool(hyper["reg_inside_moment"]), scal, skip, gf,
                p.astype(_f32), m, v2)
            new_ps.append(p2)
            ms.append(m2)
            vs.append(v2)
        return new_ps, {"m": ms, "v": vs}
