"""FusedAdam — TPU rebuild of ``apex/optimizers/fused_adam.py``.

Apex semantics preserved: ``adam_w_mode`` selects AdamW (decoupled decay,
default) vs classic Adam (L2 in gradient); ``bias_correction`` toggles the
``1-beta^t`` terms; one fused kernel launch per dtype bucket per step;
``amsgrad`` unsupported (apex raises too).  ``capturable`` (CUDA-graph
safety) is accepted for signature parity and ignored — every step here is
XLA-compiled, which is the TPU analogue of graph capture.  The
``master_weights`` variant keeps packed fp32 master params in optimizer
state and casts down to the model dtype after each step (apex
``master_weights=True``).
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.optimizers.base import FusedOptimizer
from apex_tpu.ops import multi_tensor as K


class FusedAdam(FusedOptimizer):
    def __init__(self, params=None, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-8, adam_w_mode=True,
                 weight_decay=0.0, amsgrad=False, set_grad_none=True,
                 capturable=False, master_weights=False, **kw):
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad "
                               "variant.")  # apex parity
        del params, set_grad_none, capturable  # signature parity only
        super().__init__(lr, weight_decay=weight_decay,
                         master_weights=master_weights,
                         betas=tuple(betas), eps=eps,
                         bias_correction=bool(bias_correction),
                         adam_w_mode=bool(adam_w_mode), **kw)

    def _init_bucket(self, info):
        shape = (info.meta.nrows, 128)
        return {"m": jnp.zeros(shape, jnp.float32),
                "v": jnp.zeros(shape, jnp.float32)}

    def _update_bucket(self, info, g, p, st, hyper, step_count, grad_scale,
                       noop, extras):
        beta1, beta2 = hyper["betas"]
        bc1, bc2 = self._bias_corrections(hyper, step_count)
        p_new, m_new, v_new = K.adam_packed(
            g, p, st["m"], st["v"], lr=hyper["lr"], beta1=beta1, beta2=beta2,
            eps=hyper["eps"], weight_decay=hyper["weight_decay"],
            bias_correction1=bc1, bias_correction2=bc2,
            grad_scale=grad_scale, adam_w_mode=hyper["adam_w_mode"],
            noop_flag=noop, block_rows=self.block_rows)
        return p_new, {"m": m_new, "v": v_new}

    # -- per-leaf (bucketed=False) layout -----------------------------------

    def _init_leaves(self, info, ps):
        return {"m": [jnp.zeros(p.shape, jnp.float32) for p in ps],
                "v": [jnp.zeros(p.shape, jnp.float32) for p in ps]}

    def _update_leaves(self, info, gs, ps, st, hyper, step_count, grad_scale,
                       noop, extras):
        beta1, beta2 = hyper["betas"]
        bc1, bc2 = self._bias_corrections(hyper, step_count)
        scal = jnp.stack([jnp.asarray(s, jnp.float32) for s in
                          (hyper["lr"], beta1, beta2, hyper["eps"],
                           hyper["weight_decay"], bc1, bc2, grad_scale)])
        skip = False if noop is None else (noop != 0)
        new_ps, ms, vs = [], [], []
        for g, p, m, v in zip(gs, ps, st["m"], st["v"]):
            p2, m2, v2 = K._adam_math(
                hyper["adam_w_mode"], scal, skip, g.astype(jnp.float32),
                p.astype(jnp.float32), m, v)
            new_ps.append(p2)
            ms.append(m2)
            vs.append(v2)
        return new_ps, {"m": ms, "v": vs}
