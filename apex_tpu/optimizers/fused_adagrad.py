"""FusedAdagrad — TPU rebuild of ``apex/optimizers/fused_adagrad.py``.

Plain Adagrad (``h += g²; p -= lr·g/(sqrt(h)+eps)``) with apex's
``adagrad_w_mode`` decoupled weight decay option, one fused kernel per
dtype bucket.
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.optimizers.base import FusedOptimizer
from apex_tpu.ops import multi_tensor as K


class FusedAdagrad(FusedOptimizer):
    def __init__(self, params=None, lr=1e-2, eps=1e-10, weight_decay=0.0,
                 set_grad_none=True, adagrad_w_mode=False, **kw):
        del params, set_grad_none
        super().__init__(lr, weight_decay=weight_decay, eps=eps,
                         adagrad_w_mode=bool(adagrad_w_mode), **kw)

    def _init_bucket(self, info):
        return {"sum": jnp.zeros((info.meta.nrows, 128), jnp.float32)}

    def _update_bucket(self, info, g, p, st, hyper, step_count, grad_scale,
                       noop, extras):
        if hyper["adagrad_w_mode"]:
            # decoupled (apex adagrad_w_mode): p -= lr*(update + wd*p_old)
            p_new, h_new = K.adagrad_packed(
                g, p, st["sum"], lr=hyper["lr"], eps=hyper["eps"],
                weight_decay=0.0, grad_scale=grad_scale, noop_flag=noop,
                block_rows=self.block_rows)
            decay = hyper["lr"] * hyper["weight_decay"]
            p_new = (p_new.astype(jnp.float32)
                     - decay * p.astype(jnp.float32)).astype(p_new.dtype)
            if noop is not None:
                p_new = jnp.where(noop != 0, p, p_new)
        else:
            p_new, h_new = K.adagrad_packed(
                g, p, st["sum"], lr=hyper["lr"], eps=hyper["eps"],
                weight_decay=hyper["weight_decay"], grad_scale=grad_scale,
                noop_flag=noop, block_rows=self.block_rows)
        return p_new, {"sum": h_new}

    # -- per-leaf (bucketed=False) layout -----------------------------------

    def _init_leaves(self, info, ps):
        return {"sum": [jnp.zeros(p.shape, jnp.float32) for p in ps]}

    def _update_leaves(self, info, gs, ps, st, hyper, step_count, grad_scale,
                       noop, extras):
        from apex_tpu.ops.multi_tensor import _adagrad_math
        w_mode = hyper["adagrad_w_mode"]
        scal = jnp.stack([jnp.asarray(s, jnp.float32) for s in
                          (hyper["lr"], hyper["eps"],
                           0.0 if w_mode else hyper["weight_decay"],
                           grad_scale)])
        skip = False if noop is None else (noop != 0)
        decay = hyper["lr"] * hyper["weight_decay"]
        new_ps, hs = [], []
        for g, p, h in zip(gs, ps, st["sum"]):
            pf = p.astype(jnp.float32)
            p2, h2 = _adagrad_math(scal, skip, g.astype(jnp.float32), pf, h)
            if w_mode:
                # decoupled decay outside the accumulator (same as the
                # bucketed branch)
                p2 = jnp.where(skip, pf, p2 - decay * pf)
            new_ps.append(p2)
            hs.append(h2)
        return new_ps, {"sum": hs}
