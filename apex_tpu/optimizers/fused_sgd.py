"""FusedSGD — TPU rebuild of ``apex/optimizers/fused_sgd.py``.

Matches torch.optim.SGD semantics (momentum, dampening, nesterov, weight
decay) with apex's extras: ``wd_after_momentum`` and ``materialize_master_grads``-era
``first_run`` handling (the momentum buffer is initialized to the first
gradient, not zero).  One fused kernel per dtype bucket per step.
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.optimizers.base import FusedOptimizer
from apex_tpu.ops import multi_tensor as K


class FusedSGD(FusedOptimizer):
    def __init__(self, params=None, lr=1e-3, momentum=0.0, dampening=0.0,
                 weight_decay=0.0, nesterov=False, wd_after_momentum=False,
                 materialize_master_grads=True, set_grad_none=False,
                 master_weights=False, **kw):
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError(
                "Nesterov momentum requires a momentum and zero dampening")
        del params, materialize_master_grads, set_grad_none
        super().__init__(lr, weight_decay=weight_decay,
                         master_weights=master_weights,
                         momentum=momentum, dampening=dampening,
                         nesterov=bool(nesterov),
                         wd_after_momentum=bool(wd_after_momentum), **kw)

    def _init_bucket(self, info):
        return {"momentum_buffer": jnp.zeros((info.meta.nrows, 128),
                                             jnp.float32)}

    def _update_bucket(self, info, g, p, st, hyper, step_count, grad_scale,
                       noop, extras):
        # `first_run` (momentum buffer seeded with g) triggers on step 1.
        # Steps are traced, so implement it branchlessly: both paths are
        # cheap elementwise math, select per-element via the kernel's
        # first_run flag is static in apex; here first==1 only differs in
        # buf init, reproduced by running the generic rule on a zero buffer
        # seeded as g/momentum when step==1 is not expressible statically —
        # instead follow torch semantics: buf0 = 0, first update gives
        # buf = g (dampening skipped on first step in torch/apex). We get
        # that by scaling the dampening term: damp_eff = 0 on step 1.
        damp = jnp.where(step_count == 1, 0.0,
                         jnp.asarray(hyper["dampening"], jnp.float32))
        p_new, buf_new = K.sgd_packed(
            g, p, st["momentum_buffer"], lr=hyper["lr"],
            weight_decay=hyper["weight_decay"], momentum=hyper["momentum"],
            dampening=damp, nesterov=hyper["nesterov"], first_run=False,
            wd_after_momentum=hyper["wd_after_momentum"],
            grad_scale=grad_scale, noop_flag=noop,
            block_rows=self.block_rows)
        return p_new, {"momentum_buffer": buf_new}

    # -- per-leaf (bucketed=False) layout -----------------------------------

    def _init_leaves(self, info, ps):
        return {"momentum_buffer": [jnp.zeros(p.shape, jnp.float32)
                                    for p in ps]}

    def _update_leaves(self, info, gs, ps, st, hyper, step_count, grad_scale,
                       noop, extras):
        from apex_tpu.ops.multi_tensor import _sgd_math
        damp = jnp.where(step_count == 1, 0.0,
                         jnp.asarray(hyper["dampening"], jnp.float32))
        scal = jnp.stack([jnp.asarray(s, jnp.float32) for s in
                          (hyper["lr"], hyper["weight_decay"],
                           hyper["momentum"], damp, grad_scale)])
        momentum = hyper["momentum"]
        momentum_zero = isinstance(momentum, (int, float)) and momentum == 0.0
        flags = (bool(hyper["nesterov"]), False,
                 bool(hyper["wd_after_momentum"]), momentum_zero)
        skip = False if noop is None else (noop != 0)
        new_ps, bufs = [], []
        for g, p, buf in zip(gs, ps, st["momentum_buffer"]):
            p2, b2 = _sgd_math(*flags, scal, skip, g.astype(jnp.float32),
                               p.astype(jnp.float32), buf)
            new_ps.append(p2)
            bufs.append(b2)
        return new_ps, {"momentum_buffer": bufs}
