"""Shared machinery for the fused optimizers (apex ``apex/optimizers/*``).

Apex optimizers hold mutable per-param ``state`` and update params in place
with one ``multi_tensor_apply`` launch per dtype group per step.  The JAX
equivalent is functional: ``opt.init(params) -> state`` and
``opt.step(grads, params, state) -> (new_params, new_state)``, where state
holds the moments as *packed* ``(rows, 128)`` buckets (one per param-group ×
dtype) so each step is one Pallas kernel sweep per bucket — the same
O(#dtypes) launch count apex achieves, not O(#params).

Param groups: apex takes a list of ``{"params": [...], "lr": ..., ...}``
dicts.  Pytrees have no identity-based grouping, so groups are expressed as
``param_group_fn(path_str) -> group_name`` plus per-group hyperparameter
overrides in ``param_groups={name: {...}}``; ungrouped leaves fall into
``"default"``.

Two execution layouts (``bucketed`` ctor flag, default ``None`` =
per-class default):

* ``bucketed=False`` (the single-chip DEFAULT): state lives per leaf and
  the step is the same single-source ``_*_math`` update applied per leaf
  as plain jnp, which XLA fuses into the surrounding train step.  On a
  single chip this is the FASTER path: a pallas_call's operands must be
  materialized buffers, so the packed path pays a pack (concat) + unpack
  (slice) HBM round trip per step that per-leaf fusion never performs —
  measured ~150 ms vs ~40 ms for the BERT-large LAMB census on v5e, i.e.
  ``packed_vs_optax_speedup = 0.531`` in BENCH_r05 (bench.py
  ``fused_adam_vs_optax``).  apex has no equivalent switch because CUDA
  launch overhead forces fusion the other way (see SURVEY §3.2); on TPU
  the launch-count argument inverts.
* ``bucketed=True`` (apex parity layout): state lives in packed
  ``(rows, 128)`` buckets and each step is one Pallas kernel sweep per
  bucket.  This is the layout the ZeRO/distributed optimizers REQUIRE —
  the packed rows are what reduce-scatter/all-gather shard evenly — so
  it stays THEIR default.  It is no longer a public opt-in on plain
  optimizers: two rounds of measurement (BENCH_r05
  ``packed_vs_optax_speedup = 0.49–0.53``) found no single-chip regime
  where it wins, so requesting it explicitly on a plain optimizer now
  raises.  The engine itself survives as the distributed optimizers'
  sharding unit (and the parity tests flip ``opt.bucketed`` by
  attribute to keep pinning the kernel path).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import bucketing as B

_f32 = jnp.float32


class BucketInfo(NamedTuple):
    key: str               # "group/dtype" — state dict key
    group: str
    indices: tuple         # leaf positions in the flattened param list
    meta: B.BucketMeta     # layout in the *param* dtype


class Layout(NamedTuple):
    buckets: tuple         # tuple[BucketInfo]
    n_leaves: int


def _leaf_key(path, leaf):
    return (jax.tree_util.keystr(path), tuple(leaf.shape), str(leaf.dtype))


class FusedOptimizer:
    """Base class: bucket layout, hyperparameter resolution, master weights."""

    # per-leaf is the single-chip default (see module docstring); the
    # distributed/ZeRO mixin overrides this to True — its sharding IS the
    # packed layout
    _default_bucketed = False

    def __init__(self, lr, *, weight_decay=0.0,
                 param_group_fn: Optional[Callable[[str], str]] = None,
                 param_groups: Optional[dict] = None,
                 master_weights: bool = False,
                 block_rows: int = B.DEFAULT_BLOCK_ROWS,
                 bucketed: Optional[bool] = None,
                 message_size: Optional[int] = None,
                 **defaults):
        self.defaults = dict(lr=lr, weight_decay=weight_decay, **defaults)
        self.param_group_fn = param_group_fn
        self.param_groups = dict(param_groups or {})
        self.master_weights = bool(master_weights)
        self.block_rows = int(block_rows)
        if bucketed is None:
            bucketed = self._default_bucketed
        elif bucketed and not self._default_bucketed:
            raise ValueError(
                "bucketed=True (packed multi_tensor layout) is not "
                "supported on plain optimizers: it measured ~2x slower "
                "than the per-leaf default for single-chip steps across "
                "two bench rounds (packed_vs_optax_speedup=0.49-0.53) — "
                "the pack/unpack HBM round trip outweighs the launch "
                "savings on TPU.  Use the per-leaf default; the packed "
                "layout remains the distributed (ZeRO) optimizers' "
                "internal sharding unit.")
        self.bucketed = bool(bucketed)
        # apex semantics: cap each packed bucket at ``message_size`` BYTES
        # (dtype-aware — the cap bounds the flattened collective payload,
        # so a bf16 bucket holds twice the elements of an f32 one).
        # None = one bucket per (group, dtype), the prior behavior.
        self.message_size = None if message_size is None else int(message_size)
        self._layout_cache: dict = {}

    # -- layout ------------------------------------------------------------

    def _meta_block_rows(self) -> int:
        """Row multiple for bucket padding.  Distributed (ZeRO) subclasses
        align to ``block_rows * world_size`` so every per-device shard is a
        whole number of kernel blocks."""
        return self.block_rows

    def _layout(self, params) -> Layout:
        leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(
            params)
        cache_key = tuple(_leaf_key(p, l) for p, l in leaves_with_path)
        hit = self._layout_cache.get(cache_key)
        if hit is not None:
            return hit
        groups: dict = {}
        for i, (path, leaf) in enumerate(leaves_with_path):
            name = "default"
            if self.param_group_fn is not None:
                name = self.param_group_fn(jax.tree_util.keystr(path))
            groups.setdefault((name, jnp.dtype(leaf.dtype)), []).append(i)
        leaves = [l for _, l in leaves_with_path]
        buckets = []
        for (name, dtype), idxs in groups.items():
            shapes = tuple(tuple(leaves[i].shape) for i in idxs)
            if self.message_size is None:
                parts = [list(range(len(idxs)))]
            else:
                parts = B.split_by_message_size(shapes, dtype,
                                                self.message_size)
            for j, part in enumerate(parts):
                sub_idxs = tuple(idxs[k] for k in part)
                sub_shapes = tuple(shapes[k] for k in part)
                meta = B.bucket_meta(sub_shapes, dtype,
                                     self._meta_block_rows())
                key = (f"{name}/{dtype}" if len(parts) == 1
                       else f"{name}/{dtype}/{j}")
                buckets.append(BucketInfo(key, name, sub_idxs, meta))
        layout = Layout(tuple(buckets), len(leaves))
        self._layout_cache[cache_key] = layout
        return layout

    def _hyper(self, group: str, lr=None) -> dict:
        h = dict(self.defaults)
        h.update(self.param_groups.get(group, {}))
        if lr is not None:
            h["lr"] = lr
        return h

    # -- state -------------------------------------------------------------

    def init(self, params):
        """Build optimizer state for a param pytree — packed moment
        buckets (``bucketed=True``) or per-leaf moment lists."""
        layout = self._layout(params)
        leaves = jax.tree_util.tree_leaves(params)
        buckets = {}
        for info in layout.buckets:
            ps = [leaves[i] for i in info.indices]
            if self.bucketed:
                st = self._init_bucket(info)
                if self.master_weights and info.meta.dtype != _f32:
                    f32_meta = info.meta._replace(dtype=_f32)
                    st["master"] = B.flatten_bucket(ps, f32_meta)
            else:
                st = self._init_leaves(info, ps)
                if self.master_weights and info.meta.dtype != _f32:
                    st["master"] = [p.astype(_f32) for p in ps]
            buckets[info.key] = st
        return {"step": jnp.zeros((), jnp.int32), "buckets": buckets}

    def _full_master_bucket(self, packed_master):
        """The bucket's FULL packed master rows (hook: the ZeRO mixin
        stores row shards and all-gathers here)."""
        return packed_master

    def master_params(self, params, state):
        """fp32 master copies as a pytree shaped like ``params`` (apex
        ``amp.master_params(optimizer)``).  Buckets without a master copy
        (already-fp32 params) return the params upcast as-is."""
        layout = self._layout(params)
        leaves, treedef = jax.tree_util.tree_flatten(params)
        out = [l.astype(_f32) if jnp.issubdtype(l.dtype, jnp.floating)
               else l for l in leaves]
        for info in layout.buckets:
            bucket_state = state["buckets"][info.key]
            if "master" not in bucket_state:
                continue
            if self.bucketed:
                masters = B.unflatten_bucket(
                    self._full_master_bucket(bucket_state["master"]),
                    info.meta._replace(dtype=_f32))
            else:
                masters = bucket_state["master"]
            for i, t in zip(info.indices, masters):
                out[i] = t
        return jax.tree_util.tree_unflatten(treedef, out)

    # -- step --------------------------------------------------------------

    def step(self, grads, params, state, *, lr=None, grad_scale=1.0,
             noop_flag=None):
        """One fused optimizer step.

        ``grad_scale`` multiplies gradients (pass ``1/loss_scale`` to fuse
        amp unscaling); a non-zero ``noop_flag`` skips the update entirely
        on-device (dynamic loss scaling overflow skip, apex's ``noop``
        buffer) including the step counter.
        """
        layout = self._layout(params)
        p_leaves, treedef = jax.tree_util.tree_flatten(params)
        g_leaves = jax.tree_util.tree_leaves(grads)
        if len(g_leaves) != len(p_leaves) or any(
                tuple(g.shape) != tuple(p.shape)
                for g, p in zip(g_leaves, p_leaves)):
            raise ValueError(
                "grads pytree does not match params: "
                f"{[tuple(g.shape) for g in g_leaves]} vs "
                f"{[tuple(p.shape) for p in p_leaves]}")
        noop = (None if noop_flag is None
                else jnp.asarray(noop_flag).reshape(()))
        if not self.bucketed:
            return self._step_per_leaf(layout, g_leaves, p_leaves, treedef,
                                       state, lr, grad_scale, noop)
        packed = {}
        for info in layout.buckets:
            gs = [g_leaves[i] for i in info.indices]
            g_meta = info.meta._replace(dtype=jnp.dtype(gs[0].dtype))
            packed[info.key] = B.flatten_bucket(gs, g_meta)
        extras = self._pre_step(layout, packed, state, lr=lr,
                                grad_scale=grad_scale)
        new_p_leaves = list(p_leaves)
        new_buckets = {}
        step_count = state["step"] + 1
        if noop is not None:
            step_count = state["step"] + (noop == 0).astype(jnp.int32)
        for info in layout.buckets:
            bucket_state = dict(state["buckets"][info.key])
            use_master = "master" in bucket_state
            if use_master:
                p_meta = info.meta._replace(dtype=_f32)
                p_packed = bucket_state["master"]
            else:
                p_meta = info.meta
                p_packed = B.flatten_bucket(
                    [p_leaves[i] for i in info.indices], p_meta)
            hyper = self._hyper(info.group, lr)
            new_p_packed, new_bucket = self._update_bucket(
                info, packed[info.key], p_packed, bucket_state, hyper,
                step_count, grad_scale, noop, extras)
            if use_master:
                new_bucket["master"] = new_p_packed
            new_buckets[info.key] = new_bucket
            outs = B.unflatten_bucket(new_p_packed, p_meta)
            for i, t in zip(info.indices, outs):
                new_p_leaves[i] = t.astype(p_leaves[i].dtype)
        new_params = jax.tree_util.tree_unflatten(treedef, new_p_leaves)
        return new_params, {"step": step_count, "buckets": new_buckets}

    def _step_per_leaf(self, layout, g_leaves, p_leaves, treedef, state,
                       lr, grad_scale, noop):
        """The ``bucketed=False`` step: per-leaf jnp updates XLA fuses
        into the surrounding graph — no pack/unpack HBM round trips.
        Same ``_*_math`` single-source update as the packed kernels."""
        step_count = state["step"] + 1
        if noop is not None:
            step_count = state["step"] + (noop == 0).astype(jnp.int32)
        extras = self._pre_step_leaves(layout, g_leaves, state, lr=lr,
                                       grad_scale=grad_scale)
        new_p_leaves = list(p_leaves)
        new_buckets = {}
        for info in layout.buckets:
            bucket_state = dict(state["buckets"][info.key])
            gs = [g_leaves[i] for i in info.indices]
            use_master = "master" in bucket_state
            if use_master:
                ps = bucket_state["master"]
            else:
                ps = [p_leaves[i] for i in info.indices]
            hyper = self._hyper(info.group, lr)
            new_ps, new_bucket = self._update_leaves(
                info, gs, ps, bucket_state, hyper, step_count, grad_scale,
                noop, extras)
            if use_master:
                new_bucket["master"] = new_ps
            new_buckets[info.key] = new_bucket
            for i, t in zip(info.indices, new_ps):
                new_p_leaves[i] = t.astype(p_leaves[i].dtype)
        new_params = jax.tree_util.tree_unflatten(treedef, new_p_leaves)
        return new_params, {"step": step_count, "buckets": new_buckets}

    # -- shared helpers ----------------------------------------------------

    @staticmethod
    def _bias_corrections(hyper, step_count):
        """Adam-family ``1 - beta^t`` terms (1.0 when disabled)."""
        beta1, beta2 = hyper["betas"]
        if hyper["bias_correction"]:
            t = step_count.astype(jnp.float32)
            return 1.0 - beta1 ** t, 1.0 - beta2 ** t
        return 1.0, 1.0

    # -- subclass hooks ----------------------------------------------------

    def _init_bucket(self, info: BucketInfo) -> dict:
        raise NotImplementedError

    def _pre_step(self, layout, packed_grads, state, *, lr, grad_scale):
        """Cross-bucket pre-pass (e.g. LAMB's global grad norm)."""
        return None

    def _update_bucket(self, info, g_packed, p_packed, bucket_state, hyper,
                       step_count, grad_scale, noop, extras):
        raise NotImplementedError

    def _init_leaves(self, info: BucketInfo, ps) -> dict:
        """Per-leaf state for ``bucketed=False`` — dict of LISTS aligned
        with ``info.indices``."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement the per-leaf "
            "(bucketed=False) layout")

    def _pre_step_leaves(self, layout, g_leaves, state, *, lr, grad_scale):
        """Cross-leaf pre-pass for ``bucketed=False``."""
        return None

    def _update_leaves(self, info, gs, ps, bucket_state, hyper, step_count,
                       grad_scale, noop, extras):
        """Per-leaf update: returns ``(new_ps, new_bucket_state)`` with
        lists aligned like ``_init_leaves``."""
        raise NotImplementedError

    # -- interop -----------------------------------------------------------

    def as_optax(self):
        """Adapter to an ``optax.GradientTransformation``.

        ``update`` returns deltas (``new_params - params``) so it composes
        with ``optax.apply_updates``; params must be passed (like any
        params-dependent optax transform).
        """
        import optax

        def init_fn(params):
            return self.init(params)

        def update_fn(grads, state, params=None):
            if params is None:
                raise ValueError(
                    "apex_tpu fused optimizers require params in update()")
            new_params, new_state = self.step(grads, params, state)
            updates = jax.tree_util.tree_map(
                lambda n, p: (n.astype(_f32) - p.astype(_f32)).astype(p.dtype),
                new_params, params)
            return updates, new_state

        return optax.GradientTransformation(init_fn, update_fn)

    # -- checkpoint parity helpers ------------------------------------------

    @staticmethod
    def state_dict(state):
        """Device → host copy of optimizer state (checkpoint surface)."""
        return jax.device_get(state)

    @staticmethod
    def load_state_dict(state_dict):
        return jax.tree_util.tree_map(jnp.asarray, state_dict)


def per_tensor_ratio_rows(meta: B.BucketMeta, per_tensor_vals: jax.Array):
    """Broadcast per-tensor scalars to per-row ``(rows, 1)`` via the
    row→tensor map (used by LAMB trust ratios and NovoGrad's v)."""
    from apex_tpu.multi_tensor_apply.functional import _row_ids_cached
    ids = _row_ids_cached(meta)
    return per_tensor_vals[ids][:, None]


def per_tensor_sums(meta: B.BucketMeta, rowsq: jax.Array):
    from apex_tpu.multi_tensor_apply.functional import _per_tensor_from_rowsq
    return _per_tensor_from_rowsq(rowsq, meta)
