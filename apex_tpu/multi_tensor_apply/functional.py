"""Tensor-list level multi-tensor ops (functional).

These are the TPU equivalents of the ``amp_C.multi_tensor_*`` entry points as
*used through* ``apex.multi_tensor_apply.multi_tensor_applier``: they take
lists of arbitrarily-shaped tensors, group them by dtype, pack each group
into one ``(rows, 128)`` buffer, run ONE Pallas kernel per group, and return
new tensor lists (JAX is functional — apex mutates in place).

The ``found_inf`` flag returned by scale/axpby/l2norm is the functional
analogue of apex's ``overflow_buf``/``noop`` buffer.
"""

from __future__ import annotations

import functools
import inspect
from typing import Sequence

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import bucketing as B
from apex_tpu.ops import multi_tensor as K

_f32 = jnp.float32


@functools.lru_cache(maxsize=512)
def _meta(shapes: tuple, dtype_str: str, block_rows: int) -> B.BucketMeta:
    return B.bucket_meta(shapes, jnp.dtype(dtype_str), block_rows)


def _meta_for(tensors: Sequence[jax.Array], dtype=None,
              block_rows: int = B.DEFAULT_BLOCK_ROWS) -> B.BucketMeta:
    shapes = tuple(tuple(t.shape) for t in tensors)
    dtype = jnp.dtype(dtype or tensors[0].dtype)
    return _meta(shapes, str(dtype), block_rows)


@functools.lru_cache(maxsize=512)
def _row_ids_cached(meta: B.BucketMeta):
    # host constant, NOT a jnp array: a device array created inside a
    # trace (e.g. first call under shard_map) would cache a tracer
    return B.row_tensor_ids(meta)


def _per_tensor_from_rowsq(rowsq: jax.Array, meta: B.BucketMeta) -> jax.Array:
    """Segment-reduce per-row sums of squares into per-tensor sums."""
    ids = _row_ids_cached(meta)
    return jax.ops.segment_sum(rowsq[:, 0], ids,
                               num_segments=len(meta.shapes))


def multi_tensor_scale(tensors: Sequence[jax.Array], scale, out_dtype=None,
                       block_rows: int = B.DEFAULT_BLOCK_ROWS):
    """out_i = tensor_i * scale for all i; returns (outs, found_inf).

    Reference: ``csrc/multi_tensor_scale_kernel.cu`` via
    ``amp_C.multi_tensor_scale`` (used by amp unscale + master-grad copies).
    """
    groups = B.group_by_dtype(tensors)
    outs: list = [None] * len(tensors)
    finf = jnp.zeros((), _f32)
    for dt, idxs in groups.items():
        ts = [tensors[i] for i in idxs]
        meta = _meta_for(ts, dt, block_rows)
        packed = B.flatten_bucket(ts, meta)
        od = out_dtype or dt
        out_packed, f = K.scale_packed(packed, scale, od,
                                       block_rows=block_rows)
        out_meta = meta._replace(dtype=jnp.dtype(od))
        for i, t in zip(idxs, B.unflatten_bucket(out_packed, out_meta)):
            outs[i] = t
        finf = jnp.maximum(finf, f)
    return outs, finf


def multi_tensor_axpby(a, xs: Sequence[jax.Array], b, ys: Sequence[jax.Array],
                       out_dtype=None,
                       block_rows: int = B.DEFAULT_BLOCK_ROWS):
    """out_i = a*x_i + b*y_i; returns (outs, found_inf).

    Reference: ``csrc/multi_tensor_axpby_kernel.cu``.
    """
    assert len(xs) == len(ys)
    groups = B.group_by_dtype(xs)
    outs: list = [None] * len(xs)
    finf = jnp.zeros((), _f32)
    for dt, idxs in groups.items():
        xg = [xs[i] for i in idxs]
        yg = [ys[i] for i in idxs]
        meta_x = _meta_for(xg, dt, block_rows)
        meta_y = _meta_for(yg, yg[0].dtype, block_rows)
        od = out_dtype or dt
        out_packed, f = K.axpby_packed(
            a, B.flatten_bucket(xg, meta_x), b,
            B.flatten_bucket(yg, meta_y), od, block_rows=block_rows)
        out_meta = meta_x._replace(dtype=jnp.dtype(od))
        for i, t in zip(idxs, B.unflatten_bucket(out_packed, out_meta)):
            outs[i] = t
        finf = jnp.maximum(finf, f)
    return outs, finf


def multi_tensor_l2norm(tensors: Sequence[jax.Array], per_tensor: bool = False,
                        block_rows: int = B.DEFAULT_BLOCK_ROWS):
    """Global L2 norm over all tensors (and per-tensor norms if asked).

    Returns ``(norm, per_tensor_norms, found_inf)``; ``per_tensor_norms`` is
    an f32 vector aligned with the input order, or None.
    Reference: ``csrc/multi_tensor_l2norm_kernel.cu`` (per-tensor variant =
    apex's ``per_tensor_python=True``).
    """
    groups = B.group_by_dtype(tensors)
    total = jnp.zeros((), _f32)
    finf = jnp.zeros((), _f32)
    per = jnp.zeros((len(tensors),), _f32) if per_tensor else None
    for dt, idxs in groups.items():
        ts = [tensors[i] for i in idxs]
        meta = _meta_for(ts, dt, block_rows)
        packed = B.flatten_bucket(ts, meta)
        rowsq, f = K.l2norm_rowsq_packed(packed, block_rows=block_rows)
        total = total + jnp.sum(rowsq)
        finf = jnp.maximum(finf, f)
        if per_tensor:
            seg = _per_tensor_from_rowsq(rowsq, meta)
            per = per.at[jnp.asarray(idxs)].set(jnp.sqrt(seg))
    return jnp.sqrt(total), per, finf


class MultiTensorApply:
    """API-parity shim for ``apex.multi_tensor_apply.MultiTensorApply``.

    In apex this dispatches a CUDA kernel over chunked pointer lists:
    ``multi_tensor_applier(op, overflow_buf, tensor_lists, *args)`` where
    tensor_lists follows each op's convention (scale: ``[in, out]``, axpby:
    ``[x, y, out]``, l2norm: ``[in]``).  JAX is functional, so "out" lists
    are ignored and the new tensors are *returned*; ``noop_flag`` maps to the
    returned ``found_inf``.  The chunk size maps to the Pallas block row
    count (elements per block ≈ ``chunk_size``, rounded to a lane multiple).
    """

    available = True
    warned = False

    def __init__(self, chunk_size: int = B.DEFAULT_BLOCK_ROWS * B.LANE):
        self.chunk_size = int(chunk_size)
        # apex chunk sizes reach 2048*32768; the Pallas block must stay a
        # multiple of 8 sublanes and small enough that a ~7-operand kernel
        # (adam) fits VMEM, so round up and clamp to [8, 2*default].
        rows = -(-self.chunk_size // B.LANE)
        rows = (rows + 7) // 8 * 8
        self.block_rows = max(8, min(2 * B.DEFAULT_BLOCK_ROWS, rows))

    def __call__(self, op, noop_flag, tensor_lists, *args, **kwargs):
        params = inspect.signature(op).parameters
        kw = dict(kwargs)
        if "noop_flag" in params and "noop_flag" not in kw:
            kw["noop_flag"] = noop_flag
        if "block_rows" in params and "block_rows" not in kw:
            kw["block_rows"] = self.block_rows
        if op is multi_tensor_scale:
            # apex convention: tensor_lists = [in, out]; args = (scale,)
            return op(tensor_lists[0], *args, **kw)
        if op is multi_tensor_axpby:
            # apex convention: tensor_lists = [x, y, out]; args = (a, b, ...)
            a, b = args[0], args[1]
            return op(a, tensor_lists[0], b, tensor_lists[1], **kw)
        if op is multi_tensor_l2norm:
            return op(tensor_lists[0], *args, **kw)
        return op(tensor_lists, *args, **kw)


multi_tensor_applier = MultiTensorApply()
