"""Pytree → dtype-bucket flattening for the multi-tensor engine.

Apex's ``multi_tensor_apply`` (reference: ``csrc/multi_tensor_apply.cuh``,
``apex/multi_tensor_apply/multi_tensor_apply.py``) packs pointers to N
tensors into chunked kernel arguments so one CUDA launch updates all of them.
On TPU, Pallas kernels take a fixed number of refs, so the equivalent design
packs the *data* instead of pointers: each tensor list is flattened into one
lane-aligned 2-D buffer of shape ``(rows, 128)`` per dtype, a single Pallas
kernel sweeps the buffer with a 1-D grid, and the buffer is split back into
the original shapes afterwards.  Under ``jit`` XLA fuses the producers of the
inputs into the concatenation, so the packing is bandwidth-cheap.

Alignment rules:

* every tensor is padded (with zeros) to a multiple of LANE=128 so that a row
  of the packed buffer never spans two tensors — per-tensor reductions
  (LAMB trust ratios, per-tensor L2 norms) then become exact row-segment
  reductions;
* the total row count is padded to a multiple of the kernel block so the
  Pallas grid divides evenly and no masking is needed.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

LANE = 128
# Default rows per Pallas block: 512 rows × 128 lanes × 4 B = 256 KiB per
# f32 operand, small enough that adam's 4-in/3-out working set fits VMEM.
DEFAULT_BLOCK_ROWS = 512


class BucketMeta(NamedTuple):
    """Static (hashable) description of a packed bucket."""

    shapes: tuple          # original tensor shapes
    dtype: jnp.dtype       # bucket dtype
    sizes: tuple           # original element counts
    padded_sizes: tuple    # per-tensor counts padded to LANE
    row_offsets: tuple     # starting row of each tensor in the packed buffer
    nrows: int             # total rows including block padding
    block_rows: int


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def bucket_meta(shapes: Sequence[tuple], dtype,
                block_rows: int = DEFAULT_BLOCK_ROWS) -> BucketMeta:
    sizes = tuple(int(np.prod(s, dtype=np.int64)) if len(s) else 1
                  for s in shapes)
    padded = tuple(_round_up(max(s, 1), LANE) for s in sizes)
    row_offsets, off = [], 0
    for p in padded:
        row_offsets.append(off)
        off += p // LANE
    nrows = _round_up(max(off, 1), block_rows)
    return BucketMeta(tuple(tuple(s) for s in shapes), jnp.dtype(dtype),
                      sizes, padded, tuple(row_offsets), nrows, block_rows)


def flatten_bucket(tensors: Sequence[jax.Array], meta: BucketMeta) -> jax.Array:
    """Pack a list of same-dtype tensors into one ``(nrows, 128)`` buffer.

    Each tensor is reshaped to ``(rows_i, 128)`` BEFORE the concat (legal
    because every tensor is LANE-padded/row-aligned by construction).
    Concatenating 1-D and reshaping the whole bucket afterwards is
    value-identical but lets the TPU compiler factorize the giant 1-D→2-D
    reshape through a ``(n/2, 2)`` bf16 intermediate whose (8,128)-tiled
    layout pads 2→128 lanes — observed 42 GB of HBM for a 335M-element
    BERT-large bf16 bucket.  Per-leaf reshapes never hit that path.
    """
    parts = []
    for t, size, padded in zip(tensors, meta.sizes, meta.padded_sizes):
        flat = jnp.ravel(t).astype(meta.dtype)
        if padded != size:
            flat = jnp.pad(flat, (0, padded - size))
        parts.append(flat.reshape(padded // LANE, LANE))
    data = jnp.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]
    if data.shape[0] != meta.nrows:
        data = jnp.pad(data, ((0, meta.nrows - data.shape[0]), (0, 0)))
    return data


def unflatten_bucket(data: jax.Array, meta: BucketMeta) -> list[jax.Array]:
    """Split a packed buffer back into the original tensor shapes.

    Row-slices the 2-D buffer per tensor and reshapes only the per-leaf
    slab — never the whole bucket (see :func:`flatten_bucket` on why the
    full-buffer reshape is pathological on TPU).
    """
    out = []
    for shape, size, padded, row in zip(meta.shapes, meta.sizes,
                                        meta.padded_sizes, meta.row_offsets):
        rows = padded // LANE
        slab = jax.lax.dynamic_slice_in_dim(data, row, rows, axis=0)
        flat = slab.reshape(rows * LANE)
        if size != rows * LANE:
            flat = jax.lax.slice_in_dim(flat, 0, size)
        out.append(flat.reshape(shape))
    return out


def row_tensor_ids(meta: BucketMeta) -> np.ndarray:
    """int32 ``(nrows,)`` map from packed row → tensor index (host constant).

    Padding rows past the last tensor map to the last tensor id; their data
    is zero so they contribute nothing to segment reductions.
    """
    ids = np.zeros(meta.nrows, dtype=np.int32)
    for i, (row, padded) in enumerate(zip(meta.row_offsets,
                                          meta.padded_sizes)):
        ids[row:row + padded // LANE] = i
    used = meta.row_offsets[-1] + meta.padded_sizes[-1] // LANE
    ids[used:] = len(meta.shapes) - 1
    return ids


def split_by_message_size(shapes: Sequence[tuple], dtype,
                          message_size: int) -> list[list[int]]:
    """Partition tensor indices into contiguous groups of ≤ ``message_size``
    BYTES each (apex bucket semantics: ``DistributedDataParallel``'s
    ``message_size`` caps the flattened allreduce payload in bytes, so the
    element budget is dtype-aware — a 10 MB cap holds 2.5M f32 elements
    but 5M bf16).  Sizing uses each tensor's LANE-padded footprint
    (``padded_elements * itemsize``), the bytes the packed buffer actually
    ships.  A single tensor larger than the cap gets its own group rather
    than being split — a bucket is the *unit* of collective dispatch and
    tensors are never torn across buckets (matching apex, where one
    oversized param simply becomes its own flush).
    """
    if message_size <= 0:
        raise ValueError(f"message_size must be positive, got {message_size}")
    itemsize = jnp.dtype(dtype).itemsize
    sizes = tuple(int(np.prod(s, dtype=np.int64)) if len(s) else 1
                  for s in shapes)
    groups: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    for i, size in enumerate(sizes):
        nbytes = _round_up(max(size, 1), LANE) * itemsize
        if cur and cur_bytes + nbytes > message_size:
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
    if cur:
        groups.append(cur)
    return groups


def group_by_dtype(tensors: Sequence[jax.Array]):
    """Group tensor indices by dtype (order-preserving).

    Mirrors apex optimizers' per-dtype grouping of param groups before
    launching one multi-tensor kernel per dtype (reference:
    ``apex/optimizers/fused_adam.py``).
    """
    groups: dict = {}
    for i, t in enumerate(tensors):
        groups.setdefault(jnp.dtype(t.dtype), []).append(i)
    return groups
