from apex_tpu.multi_tensor_apply.bucketing import (
    LANE,
    DEFAULT_BLOCK_ROWS,
    BucketMeta,
    bucket_meta,
    flatten_bucket,
    unflatten_bucket,
    row_tensor_ids,
    group_by_dtype,
)
from apex_tpu.multi_tensor_apply.functional import (
    MultiTensorApply,
    multi_tensor_applier,
    multi_tensor_scale,
    multi_tensor_axpby,
    multi_tensor_l2norm,
)

__all__ = [
    "LANE",
    "DEFAULT_BLOCK_ROWS",
    "BucketMeta",
    "bucket_meta",
    "flatten_bucket",
    "unflatten_bucket",
    "row_tensor_ids",
    "group_by_dtype",
    "MultiTensorApply",
    "multi_tensor_applier",
    "multi_tensor_scale",
    "multi_tensor_axpby",
    "multi_tensor_l2norm",
]
