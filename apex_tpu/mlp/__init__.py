"""Fused MLP — TPU rebuild of ``apex/mlp/mlp.py`` (+ ``csrc/mlp_cuda.cu``).

Apex chains cuBLAS GEMMs with bias/activation epilogues under a single
autograd node and one workspace.  On TPU the entire chain is one XLA fusion
region inside the surrounding jit — GEMMs land on the MXU, bias+activation
fuse into their epilogues — so the module is a plain functional chain; the
"fused" property is achieved by construction rather than by a kernel.

The claim is pinned by the on-chip lane
(``tests/test_on_chip.py::TestXlaFusionClaim``): the compiled ENTRY
computation contains only fusions/GEMMs/plumbing — a standalone
elementwise kernel (un-fused epilogue) fails the test.  That covers the
epilogues only; the GEMM→GEMM activation still crosses HBM, and
``fused_ffn=True`` routes the 2-layer GELU shape onto the Pallas
fused-FFN kernel (:mod:`apex_tpu.ops.fused_ffn`) that keeps it in VMEM.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

__all__ = ["MLP", "mlp_forward"]


def _activate(h, activation):
    if activation == "none":
        return h
    if activation == "relu":
        return jax.nn.relu(h)
    if activation == "sigmoid":
        return jax.nn.sigmoid(h)
    if activation == "gelu":
        return jax.nn.gelu(h, approximate=True)
    raise ValueError(f"unsupported activation {activation!r}")


def mlp_forward(params, x, activation="relu", fused_ffn=False):
    """Chained ``x @ W.T + b`` with activation between layers (last layer
    linear) — apex ``mlp_function`` semantics, weights stored (out, in).

    ``fused_ffn=True`` routes the canonical 2-layer GELU shape onto the
    Pallas fused-FFN kernel (:mod:`apex_tpu.ops.fused_ffn`) — the same
    implementation the model FFNs use; other shapes raise so a silently
    unfused path cannot masquerade as the kernel."""
    n = len(params["weights"])
    if fused_ffn:
        if n != 2 or activation != "gelu" \
                or params.get("biases") is None:
            raise ValueError(
                "fused_ffn covers the 2-layer biased GELU MLP "
                f"(got {n} layers, activation={activation!r}, "
                f"biases={'yes' if params.get('biases') else 'no'})")
        from apex_tpu.ops.fused_ffn import fused_ffn as _fused_ffn
        return _fused_ffn(x, params["weights"][0], params["biases"][0],
                          params["weights"][1], params["biases"][1])
    h = x
    for i, w in enumerate(params["weights"]):
        h = h @ w.T
        if params.get("biases") is not None:
            h = h + params["biases"][i]
        if i + 1 < n:
            h = _activate(h, activation)
    return h


class MLP:
    """apex ``apex.mlp.MLP(mlp_sizes, bias=True, relu=True, activation=...)``.

    ``mlp_sizes`` includes the input size:  MLP([in, h1, h2]) builds two
    layers.  Functional usage: ``params = m.init_params(key); y = m(params, x)``.
    """

    def __init__(self, mlp_sizes: Sequence[int], bias=True, relu=True,
                 activation=None, param_dtype=jnp.float32,
                 fused_ffn=False):
        if len(mlp_sizes) < 2:
            raise ValueError("MLP needs at least an input and output size")
        self.mlp_sizes = tuple(int(s) for s in mlp_sizes)
        self.bias = bool(bias)
        if activation is None:
            activation = "relu" if relu else "none"
        self.activation = activation
        self.param_dtype = param_dtype
        self.fused_ffn = bool(fused_ffn)

    def init_params(self, key):
        weights, biases = [], []
        for i in range(len(self.mlp_sizes) - 1):
            key, sub = jax.random.split(key)
            fan_in = self.mlp_sizes[i]
            bound = 1.0 / jnp.sqrt(fan_in)
            w = jax.random.uniform(
                sub, (self.mlp_sizes[i + 1], fan_in),
                minval=-bound, maxval=bound, dtype=jnp.float32)
            weights.append(w.astype(self.param_dtype))
            if self.bias:
                key, sub = jax.random.split(key)
                b = jax.random.uniform(sub, (self.mlp_sizes[i + 1],),
                                       minval=-bound, maxval=bound,
                                       dtype=jnp.float32)
                biases.append(b.astype(self.param_dtype))
        params = {"weights": weights}
        if self.bias:
            params["biases"] = biases
        return params

    def __call__(self, params, x):
        return mlp_forward(params, x, self.activation,
                           fused_ffn=self.fused_ffn)

    apply = __call__
