// apex_tpu host runtime — native host-side machinery.
//
// The reference keeps its host-side runtime in C++ (apex_C
// flatten/unflatten over torch::utils::flatten_dense_tensors;
// apex/contrib/csrc/gpu_direct_storage/*.cpp for cuFile tensor IO).  The
// TPU rebuild keeps the same split: device code is XLA/Pallas, but the
// host-side hot paths — gathering thousands of parameter buffers into one
// contiguous pack before device_put, and streaming checkpoints between
// host RAM and disk — are plain-C-ABI C++ with the GIL released, loaded
// from Python via ctypes (no pybind11 in this environment).
//
// Exported C ABI (all return 0 on success, negative errno-style on error):
//   apex_pack(srcs, sizes, n, dst)            gather n buffers -> dst
//   apex_unpack(src, dsts, sizes, n)          scatter src -> n buffers
//   apex_file_write(path, buf, size, threads) parallel chunked pwrite
//   apex_file_read(path, buf, size, threads)  parallel chunked pread
//   apex_version()                            ABI version int

#include <cerrno>
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

// Gather (GATHER) or scatter (!GATHER) between n separate buffers and one
// contiguous pack.  Inputs split across threads at buffer granularity.
template <bool GATHER>
int copy_many(void *pack, void *const *bufs, const size_t *sizes, int n) {
  if (n < 0) return -EINVAL;
  size_t total = 0;
  std::vector<size_t> offs((size_t)n);
  for (int i = 0; i < n; ++i) {
    offs[(size_t)i] = total;
    total += sizes[i];
  }
  unsigned hw = std::thread::hardware_concurrency();
  int nt = (int)(hw ? hw : 1);
  if (nt > n) nt = n > 0 ? n : 1;
  if (total < (1u << 20)) nt = 1;  // small packs: thread spawn dominates
  auto run = [&](int t) {
    for (int i = t; i < n; i += nt) {
      char *at = (char *)pack + offs[(size_t)i];
      if (GATHER)
        std::memcpy(at, bufs[i], sizes[i]);
      else
        std::memcpy(bufs[i], at, sizes[i]);
    }
  };
  if (nt == 1) {
    run(0);
  } else {
    std::vector<std::thread> ts;
    ts.reserve((size_t)nt);
    for (int t = 0; t < nt; ++t) ts.emplace_back(run, t);
    for (auto &th : ts) th.join();
  }
  return 0;
}

// Parallel chunked file IO: each thread opens its own fd and
// preads/pwrites a contiguous slice, so the kernel can keep multiple
// requests in flight (the TPU-host analogue of cuFile's multi-channel
// DMA; the destination here is host RAM that jax.device_put streams on).
template <bool WRITE>
int file_io(const char *path, void *buf, size_t size, int threads) {
  if (threads < 1) threads = 1;
  if (size < (8u << 20)) threads = 1;  // <8 MiB: syscall path is enough
  int flags = WRITE ? (O_WRONLY | O_CREAT) : O_RDONLY;
  if (WRITE) {
    // create + size the file once so per-thread fds can pwrite anywhere
    int fd = open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (fd < 0) return -errno;
    if (size > 0 && ftruncate(fd, (off_t)size) != 0) {
      int e = errno;
      close(fd);
      return -e;
    }
    close(fd);
  }
  std::vector<int> errs((size_t)threads, 0);
  size_t chunk = (size + (size_t)threads - 1) / (size_t)threads;
  auto run = [&](int t) {
    size_t off = (size_t)t * chunk;
    if (off >= size) return;
    size_t end = off + chunk < size ? off + chunk : size;
    int fd = open(path, flags, 0644);
    if (fd < 0) {
      errs[(size_t)t] = -errno;
      return;
    }
    char *p = (char *)buf + off;
    size_t left = end - off;
    while (left > 0) {
      ssize_t k = WRITE ? pwrite(fd, p, left, (off_t)off)
                        : pread(fd, p, left, (off_t)off);
      if (k <= 0) {
        errs[(size_t)t] = k == 0 ? -EIO : -errno;
        break;
      }
      p += k;
      off += (size_t)k;
      left -= (size_t)k;
    }
    close(fd);
  };
  if (threads == 1) {
    run(0);
  } else {
    std::vector<std::thread> ts;
    ts.reserve((size_t)threads);
    for (int t = 0; t < threads; ++t) ts.emplace_back(run, t);
    for (auto &th : ts) th.join();
  }
  for (int e : errs)
    if (e != 0) return e;
  return 0;
}

}  // namespace

extern "C" {

int apex_version() { return 1; }

int apex_pack(const void **srcs, const size_t *sizes, int n, void *dst) {
  return copy_many<true>(dst, const_cast<void *const *>(srcs), sizes, n);
}

int apex_unpack(const void *src, void **dsts, const size_t *sizes, int n) {
  return copy_many<false>(const_cast<void *>(src), dsts, sizes, n);
}

int apex_file_write(const char *path, const void *buf, size_t size,
                    int threads) {
  return file_io<true>(path, const_cast<void *>(buf), size, threads);
}

int apex_file_read(const char *path, void *buf, size_t size, int threads) {
  return file_io<false>(path, buf, size, threads);
}

}  // extern "C"
