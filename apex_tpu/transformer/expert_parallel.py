"""Expert parallelism: Switch-style MoE FFN over an ``expert`` mesh axis
— BEYOND-REFERENCE (SURVEY §2.3: MoE/expert parallelism is NOT in apex;
it lives in Megatron-LM proper.  Built here because EP is a first-class
sharding axis for a complete TPU framework).

Design (the standard TPU MoE dataflow, cf. Switch Transformer / GShard):
every device holds ``n_experts / ep`` expert FFNs and a shard of the
token batch.  Per device: top-k gate (``top_k=1`` Switch with raw top-1
prob, ``top_k=2`` GShard with gates renormalized over the selected
pair) → capacity-bounded dispatch into an ``(n_experts, capacity,
hidden)`` buffer (second choices claim slots after all first choices) →
``all_to_all`` over the expert axis (tokens travel to the device owning
their expert) → batched expert FFN (one einsum over the local expert
stack — MXU-friendly, no ragged loops) → inverse ``all_to_all`` →
gate-weighted combine over the k choices.  Tokens over capacity are
dropped (contribute zero), exactly like the references.

``axis_name=None`` runs the identical math single-device (the serial
golden for tests).  The auxiliary output is the Switch load-balancing
loss (mean fraction·probability product, scaled by ``n_experts``).

MoE composes with tensor parallelism (``tensor_axis``/
``tensor_parallel_size``): each expert's FFN inner dim is sharded over
the tensor axis with the Megatron Column→Row collective pairing
(identity/psum at entry, psum/identity at exit — the same
``mappings`` the dense ``ParallelMLP`` uses), so an expert runs as a
Column-parallel ``w1`` einsum → ReLU → Row-parallel ``w2`` einsum.
The expert axis (all_to_all over tokens) and the tensor axis (psum
over the FFN reduction) are independent mesh axes and compose
orthogonally: the all_to_all moves ``(…, hidden)`` buffers whose
hidden dim is never sharded.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from apex_tpu.utils.collectives import axis_size as _axis_size

__all__ = ["MoEConfig", "MoEMLP", "is_gpt_expert_leaf",
           "localize_expert_params", "reduce_moe_grads",
           "vary_params_over_axis"]

_f32 = jnp.float32


@dataclasses.dataclass
class MoEConfig:
    hidden_size: int
    ffn_hidden_size: int
    n_experts: int
    capacity_factor: float = 1.25
    top_k: int = 1                           # 1 = Switch, 2 = GShard
    expert_parallel_size: int = 1
    axis_name: Optional[str] = None          # "expert" inside shard_map
    tensor_parallel_size: int = 1            # shard each expert's FFN dim
    tensor_axis: Optional[str] = None        # "model" inside shard_map
    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.float32   # expert einsums/dispatch
    # (gate softmax + aux loss always run f32)

    def __post_init__(self):
        if self.n_experts % self.expert_parallel_size:
            raise ValueError("n_experts must be divisible by "
                             "expert_parallel_size")
        if not 1 <= self.top_k <= self.n_experts:
            raise ValueError("top_k must be in [1, n_experts]")
        if self.expert_parallel_size > 1 and self.axis_name is None:
            raise ValueError(
                "expert_parallel_size > 1 requires axis_name (the expert "
                "mesh axis the call runs under)")
        if self.ffn_hidden_size % self.tensor_parallel_size:
            raise ValueError("ffn_hidden_size must be divisible by "
                             "tensor_parallel_size")
        if self.tensor_parallel_size > 1 and self.tensor_axis is None:
            raise ValueError(
                "tensor_parallel_size > 1 requires tensor_axis (the "
                "tensor mesh axis the call runs under)")

    @property
    def local_experts(self):
        return self.n_experts // self.expert_parallel_size

    @property
    def local_ffn(self):
        return self.ffn_hidden_size // self.tensor_parallel_size


class MoEMLP:
    """Top-k MoE FFN (``top_k=1`` Switch, ``top_k=2`` GShard).

    ``params = m.init_params(key)`` holds THIS DEVICE's expert stack
    (``(local_experts, ...)`` leaves) plus the replicated gate;
    ``out, aux_loss = m(params, x)`` with ``x (tokens, hidden)`` local.
    """

    def __init__(self, cfg: MoEConfig):
        self.cfg = cfg

    def init_params(self, key):
        cfg = self.cfg
        k1, k2, k3 = jax.random.split(key, 3)
        e, h, f = cfg.local_experts, cfg.hidden_size, cfg.local_ffn
        return {
            "gate": 0.02 * jax.random.normal(
                k1, (h, cfg.n_experts), cfg.param_dtype),
            "w1": (h ** -0.5) * jax.random.normal(
                k2, (e, h, f), cfg.param_dtype),
            "w2": (f ** -0.5) * jax.random.normal(
                k3, (e, f, h), cfg.param_dtype),
        }

    def _capacity(self, n_tokens: int) -> int:
        cfg = self.cfg
        cap = int(cfg.capacity_factor * cfg.top_k * n_tokens
                  / cfg.n_experts)
        return max(cap, 1)

    def __call__(self, params, x):
        cfg = self.cfg
        ep = cfg.expert_parallel_size
        t, h = x.shape
        ne, nl = cfg.n_experts, cfg.local_experts
        k = cfg.top_k
        cap = self._capacity(t)

        xf = x.astype(_f32)
        logits = xf @ params["gate"].astype(_f32)          # (T, E)
        probs = jax.nn.softmax(logits, axis=-1)
        topk_prob, topk_idx = jax.lax.top_k(probs, k)      # (T, k)
        if k > 1:
            # GShard: gates renormalized over the selected experts
            gate_probs = topk_prob / jnp.sum(topk_prob, axis=-1,
                                             keepdims=True)
        else:
            gate_probs = topk_prob      # Switch keeps the raw top-1 prob

        # aux loss over FIRST choices (Switch form; GShard's is the same
        # statistic): n_e * sum_e(fraction_e * mean_prob_e)
        onehot1 = jax.nn.one_hot(topk_idx[:, 0], ne, dtype=_f32)
        fraction = jnp.mean(onehot1, axis=0)
        mean_prob = jnp.mean(probs, axis=0)
        aux_loss = ne * jnp.sum(fraction * mean_prob)

        # deterministic capacity per choice: first choices claim slots
        # first (GShard's assignment order), then second choices append.
        # integer cumsums — f32 counts lose exactness past 2^24
        expert_idx, slot, keep = [], [], []
        claimed = jnp.zeros((ne,), jnp.int32)
        for c in range(k):
            idx_c = topk_idx[:, c]
            onehot_i = jax.nn.one_hot(idx_c, ne, dtype=jnp.int32)
            pos = jnp.cumsum(onehot_i, axis=0) * onehot_i
            # pos_c >= 0 always (own one-hot contributes 1, claimed >= 0)
            pos_c = jnp.max(pos, axis=-1) - 1 + claimed[idx_c]
            keep_c = pos_c < cap
            expert_idx.append(idx_c)
            slot.append(jnp.clip(pos_c, 0, cap - 1))
            keep.append(keep_c)
            claimed = claimed + jnp.sum(onehot_i, axis=0)

        # dispatch: (E, cap, H) buffer in the compute dtype (each slot
        # receives at most one token, so low-precision add is exact);
        # dropped tokens scatter nothing
        cdt = cfg.compute_dtype
        xc = x.astype(cdt)
        buf = jnp.zeros((ne, cap, h), cdt)
        for c in range(k):
            buf = buf.at[expert_idx[c], slot[c]].add(
                xc * keep[c][:, None].astype(cdt), mode="drop")

        if cfg.axis_name is not None and ep > 1:
            # (ep, nl, cap, H): chunk e goes to the device owning expert
            # group e; received chunks stack on axis 0 as SOURCE device
            buf = buf.reshape(ep, nl, cap, h)
            buf = jax.lax.all_to_all(buf, cfg.axis_name, split_axis=0,
                                     concat_axis=0, tiled=False)
            # (ep_src, nl, cap, H) -> per local expert, all sources' slots
            expert_in = buf.transpose(1, 0, 2, 3).reshape(nl, ep * cap, h)
        else:
            expert_in = buf                                # (E, cap, H)

        # batched expert FFN: one einsum over the local expert stack,
        # operands in compute dtype (bf16 rides the MXU), f32 accumulate.
        # Under tensor parallelism w1/w2 hold the f-dim shard and the
        # Column→Row mapping pair brackets the two einsums: copy_to's
        # backward psums the dispatch-buffer cotangent over the tensor
        # ranks, reduce_from's forward psums the partial expert outputs
        # (identical collective structure to the dense ParallelMLP).
        tp_on = cfg.tensor_axis is not None and cfg.tensor_parallel_size > 1
        if tp_on:
            from apex_tpu.transformer.tensor_parallel import mappings as M
            expert_in = M.copy_to_tensor_model_parallel_region(
                expert_in, cfg.tensor_axis)
        h1 = jnp.maximum(jnp.einsum(
            "ech,ehf->ecf", expert_in, params["w1"].astype(cdt),
            preferred_element_type=_f32), 0.0).astype(cdt)
        out_e = jnp.einsum("ecf,efh->ech", h1,
                           params["w2"].astype(cdt),
                           preferred_element_type=_f32)
        if tp_on:
            out_e = M.reduce_from_tensor_model_parallel_region(
                out_e, cfg.tensor_axis)

        if cfg.axis_name is not None and ep > 1:
            # return trip in compute dtype (halves the ICI traffic)
            out_e = out_e.astype(cdt)
            out_e = out_e.reshape(nl, ep, cap, h).transpose(1, 0, 2, 3)
            out_e = jax.lax.all_to_all(out_e, cfg.axis_name, split_axis=0,
                                       concat_axis=0, tiled=False)
            out_e = out_e.reshape(ne, cap, h)

        # combine: gather each choice's slot, weight by its gate prob
        out = jnp.zeros((t, h), _f32)
        for c in range(k):
            out = out + out_e[expert_idx[c], slot[c]].astype(_f32) * (
                gate_probs[:, c] * keep[c].astype(_f32))[:, None]
        return out.astype(x.dtype), aux_loss


# -- EP training-recipe helpers ---------------------------------------------

def is_gpt_expert_leaf(path) -> bool:
    """True for a GPT MoE expert-stack leaf (``mlp.w1`` / ``mlp.w2``)."""
    ks = jax.tree_util.keystr(path)
    return "mlp" in ks and ("'w1'" in ks or "'w2'" in ks)


def localize_expert_params(params, is_expert=is_gpt_expert_leaf):
    """Drop the unit mesh axis from expert-stack leaves inside
    ``shard_map`` (``(1, nl, ...) -> (nl, ...)``)."""
    return jax.tree_util.tree_map_with_path(
        lambda p, x: x[0] if is_expert(p) else x, params)


def vary_params_over_axis(params, axis_name: str):
    """Mark every param leaf device-varying over ``axis_name`` (leaves
    already varying pass through).

    Load-bearing for EP training under ``check_vma=True``: the expert
    axis doubles as a batch axis for the dense compute, so dense-param
    grads must be psummed across it.  JAX's automatic
    psum-of-invariant-grads handles plain-jnp paths, but ``custom_vjp``
    kernels (the Pallas LayerNorm, the TP mappings) compute their own
    cotangents and leave them axis-varying with no way for JAX to insert
    the reduction.  ``pcast``-ing the params varying BEFORE the compute
    moves the reduction into pcast's transpose — a psum over the added
    axis — uniformly for every leaf.  Do NOT use this on
    the TENSOR axis: the Megatron mappings' custom_vjp rules already own
    model-axis grad reduction and would double-reduce.
    """
    def v(p):
        if not hasattr(jax, "typeof"):  # pre-vma JAX: implicitly varying
            return p
        if axis_name in jax.typeof(p).vma:
            return p
        return jax.lax.pcast(p, (axis_name,), to="varying")
    return jax.tree_util.tree_map(v, params)


def reduce_moe_grads(grads, axis_name: str,
                     is_expert=is_gpt_expert_leaf):
    """The EP gradient reduction recipe (single source of truth for the
    example, the test and the driver dryrun).

    Differentiating the LOCAL per-device loss of a mean-over-devices
    objective: dense grads are pmean'd across the axis; expert-stack
    grads — whose cross-device contributions the ``all_to_all``
    transpose already routed to the owning device — divide by the axis
    size and regain the unit mesh axis for ``out_specs``.
    """
    ep = _axis_size(axis_name)
    return jax.tree_util.tree_map_with_path(
        lambda p, g: (g / ep)[None] if is_expert(p)
        else jax.lax.pmean(g, axis_name), grads)
