"""Model-parallel topology — TPU rebuild of ``apex/transformer/parallel_state.py``.

Apex builds NCCL process groups for TP/PP/DP (plus embedding and
position-embedding groups) from a world of ranks.  On TPU the topology is a
named :class:`jax.sharding.Mesh` over the device grid — collectives are
compiled against mesh axes, so "groups" are just axis names:

* ``data``  — data parallel (apex ``_DATA_PARALLEL_GROUP``)
* ``pipe``  — pipeline model parallel (apex ``_PIPELINE_MODEL_PARALLEL_GROUP``)
* ``model`` — tensor model parallel (apex ``_TENSOR_MODEL_PARALLEL_GROUP``)

``initialize_model_parallel`` mirrors the apex signature (sizes +
virtual-pipeline + split rank) and stores a module-global mesh; rank/world
accessors return traced values inside ``shard_map``/``pjit`` contexts (via
``axis_index``) and host-side integers otherwise, so code written against
the apex accessors works in both worlds.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
PIPELINE_AXIS = "pipe"
TENSOR_AXIS = "model"

_MESH: Optional[Mesh] = None
_VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK: Optional[int] = None
_VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE: Optional[int] = None
_PIPELINE_MODEL_PARALLEL_SPLIT_RANK: Optional[int] = None


def initialize_model_parallel(
        tensor_model_parallel_size_: int = 1,
        pipeline_model_parallel_size_: int = 1,
        virtual_pipeline_model_parallel_size_: Optional[int] = None,
        pipeline_model_parallel_split_rank_: Optional[int] = None,
        *, devices=None, default_backend=None) -> Mesh:
    """Build and install the global ``(data, pipe, model)`` mesh.

    World size is ``len(devices)`` (default: all JAX devices); the data
    parallel size is inferred as ``world // (tp * pp)`` exactly like apex.
    Returns the mesh (also retrievable via :func:`get_mesh`).
    """
    global _MESH, _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK
    global _PIPELINE_MODEL_PARALLEL_SPLIT_RANK
    del default_backend  # apex arg (nccl/ucc); meaningless here
    devices = list(devices if devices is not None else jax.devices())
    world = len(devices)
    tp = int(tensor_model_parallel_size_)
    pp = int(pipeline_model_parallel_size_)
    if world % (tp * pp) != 0:
        raise RuntimeError(
            f"world size ({world}) is not divisible by tensor parallel "
            f"size ({tp}) x pipeline parallel size ({pp})")
    dp = world // (tp * pp)
    dev_array = np.asarray(devices).reshape(dp, pp, tp)
    _MESH = Mesh(dev_array, (DATA_AXIS, PIPELINE_AXIS, TENSOR_AXIS))
    if virtual_pipeline_model_parallel_size_ is not None:
        _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK = 0
        _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE = \
            int(virtual_pipeline_model_parallel_size_)
    else:
        _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK = None
        _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE = None
    _PIPELINE_MODEL_PARALLEL_SPLIT_RANK = pipeline_model_parallel_split_rank_
    return _MESH


def model_parallel_is_initialized() -> bool:
    return _MESH is not None


def get_mesh() -> Mesh:
    if _MESH is None:
        raise RuntimeError("model parallel mesh is not initialized "
                           "(call initialize_model_parallel first)")
    return _MESH


def destroy_model_parallel():
    global _MESH, _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
    global _PIPELINE_MODEL_PARALLEL_SPLIT_RANK
    _MESH = None
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK = None
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE = None
    _PIPELINE_MODEL_PARALLEL_SPLIT_RANK = None


# -- world sizes (host-side static) -----------------------------------------

def get_tensor_model_parallel_world_size() -> int:
    return get_mesh().shape[TENSOR_AXIS]


def get_pipeline_model_parallel_world_size() -> int:
    return get_mesh().shape[PIPELINE_AXIS]


def get_data_parallel_world_size() -> int:
    return get_mesh().shape[DATA_AXIS]


def get_model_parallel_world_size() -> int:
    """tp*pp (apex asserts pp==1 here; we return the product)."""
    return (get_tensor_model_parallel_world_size()
            * get_pipeline_model_parallel_world_size())


# -- ranks (traced inside shard_map, 0 on host) ------------------------------

def _axis_rank(axis: str):
    try:
        return jax.lax.axis_index(axis)
    except NameError:
        return 0


def get_tensor_model_parallel_rank():
    return _axis_rank(TENSOR_AXIS)


def get_pipeline_model_parallel_rank():
    return _axis_rank(PIPELINE_AXIS)


def get_data_parallel_rank():
    return _axis_rank(DATA_AXIS)


def is_pipeline_first_stage(ignore_virtual: bool = False):
    if not ignore_virtual:
        vp = _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
        if vp is not None and \
                get_virtual_pipeline_model_parallel_rank() != 0:
            return False
    return get_pipeline_model_parallel_rank() == 0


def is_pipeline_last_stage(ignore_virtual: bool = False):
    if not ignore_virtual:
        vp = _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE
        if vp is not None and (get_virtual_pipeline_model_parallel_rank()
                               != vp - 1):
            return False
    return (get_pipeline_model_parallel_rank()
            == get_pipeline_model_parallel_world_size() - 1)


# -- virtual pipeline ranks (host-side ints, like apex) ----------------------

def get_virtual_pipeline_model_parallel_rank():
    return _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK


def set_virtual_pipeline_model_parallel_rank(rank: int):
    global _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK
    _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK = rank


def get_virtual_pipeline_model_parallel_world_size():
    return _VIRTUAL_PIPELINE_MODEL_PARALLEL_WORLD_SIZE


def get_pipeline_model_parallel_split_rank():
    return _PIPELINE_MODEL_PARALLEL_SPLIT_RANK


def set_pipeline_model_parallel_split_rank(rank: int):
    global _PIPELINE_MODEL_PARALLEL_SPLIT_RANK
    _PIPELINE_MODEL_PARALLEL_SPLIT_RANK = rank


# -- sharding helpers --------------------------------------------------------

def get_tensor_model_parallel_src_rank() -> int:
    """apex: global rank of the first rank in one's TP group — under a
    single-controller mesh this is only meaningful for logging; return 0."""
    return 0
