"""Transformer-stack logging — apex surface parity
(reference: ``apex/transformer/log_util.py``: ``get_transformer_logger``
returning a per-module child of the "apex" logger and
``set_logging_level`` on the root apex logger)."""

from __future__ import annotations

import logging

_ROOT_NAME = "apex_tpu"


def get_transformer_logger(name: str) -> logging.Logger:
    """Child logger under the package root (apex: ``apex.transformer.X``)."""
    name_wo_ext = name.split(".")[0]
    return logging.getLogger(f"{_ROOT_NAME}.transformer.{name_wo_ext}")


def set_logging_level(verbosity) -> None:
    """Set the package root logger's level (apex ``set_logging_level``)."""
    logging.getLogger(_ROOT_NAME).setLevel(verbosity)
