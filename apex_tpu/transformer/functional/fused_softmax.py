"""FusedScaleMaskSoftmax — TPU rebuild of
``apex/transformer/functional/fused_softmax.py``.

Apex dispatches between three CUDA kernels (causal / masked / generic) by
shape and a ``is_kernel_available`` check with seq≤4K templates; the TPU
ops have no such limits so dispatch is purely on mask type.  The
``scaled_masked_softmax_fusion`` flag and fp16/bf16 flags are kept for
constructor parity (mask_func/softmax_in_fp32 behave as in apex).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.softmax import (scaled_masked_softmax, scaled_softmax,
                                  scaled_upper_triang_masked_softmax)
from apex_tpu.transformer.enums import AttnMaskType


class FusedScaleMaskSoftmax:
    def __init__(self, input_in_fp16: bool = False,
                 input_in_bf16: bool = True,
                 attn_mask_type: AttnMaskType = AttnMaskType.padding,
                 scaled_masked_softmax_fusion: bool = True,
                 mask_func: Optional[Callable] = None,
                 softmax_in_fp32: bool = True,
                 scale: Optional[float] = None):
        if input_in_fp16 and input_in_bf16:
            raise RuntimeError(
                "both fp16 and bf16 flags cannot be active at the same "
                "time.")  # apex parity
        if scale is not None and not softmax_in_fp32:
            raise RuntimeError(
                "softmax should be in fp32 when scaled")  # apex parity
        self.attn_mask_type = attn_mask_type
        self.mask_func = mask_func
        self.softmax_in_fp32 = softmax_in_fp32
        self.scale = 1.0 if scale is None else float(scale)

    def __call__(self, x, mask=None):
        if not self.softmax_in_fp32:
            # apex non-fp32 path: softmax in the input dtype
            xs = x * jnp.asarray(self.scale, x.dtype)
            if self.attn_mask_type == AttnMaskType.causal:
                sq, sk = x.shape[-2], x.shape[-1]
                from apex_tpu.ops.softmax import _causal_mask, MASK_FILL
                xs = jnp.where(_causal_mask(sq, sk), MASK_FILL, xs)
            elif mask is not None:
                if self.mask_func is not None:
                    xs = self.mask_func(xs, mask)
                else:
                    xs = jnp.where(mask, jnp.asarray(-10000.0, x.dtype), xs)
            return jax.nn.softmax(xs, axis=-1)
        if self.attn_mask_type == AttnMaskType.causal:
            # apex kernel takes (b*np, sq, sk)
            b, np_, sq, sk = x.shape
            y = scaled_upper_triang_masked_softmax(
                x.reshape(b * np_, sq, sk), self.scale)
            return y.reshape(b, np_, sq, sk)
        if mask is not None:
            if self.mask_func is not None:
                xm = self.mask_func(x.astype(jnp.float32) * self.scale,
                                    mask)
                return scaled_masked_softmax(xm, None, 1.0).astype(x.dtype)
            return scaled_masked_softmax(x, mask, self.scale)
        return scaled_softmax(x, self.scale)

    forward = __call__
