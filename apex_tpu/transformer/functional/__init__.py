from apex_tpu.transformer.functional.fused_softmax import (
    FusedScaleMaskSoftmax,
)
from apex_tpu.ops.rope import (
    fused_apply_rotary_pos_emb,
    fused_apply_rotary_pos_emb_cached,
    fused_apply_rotary_pos_emb_thd,
)

__all__ = [
    "FusedScaleMaskSoftmax",
    "fused_apply_rotary_pos_emb",
    "fused_apply_rotary_pos_emb_cached",
    "fused_apply_rotary_pos_emb_thd",
]
