"""TPU rebuild of ``apex/transformer/layers/layer_norm.py``.

Apex picks FastLayerNorm (fixed hidden sizes) or MixedFusedLayerNorm and
tags weights with ``sequence_parallel_enabled`` so the grad-sync pass knows
those params are replicated along the sequence-parallel region.  Here both
names resolve to the Pallas-backed mixed norm; the sequence-parallel tag is
carried on the module (GSPMD handles the replication, the tag is for
recipe-level introspection)."""

from __future__ import annotations

from apex_tpu.normalization.fused_layer_norm import MixedFusedLayerNorm


class FusedLayerNorm(MixedFusedLayerNorm):
    def __init__(self, hidden_size, eps=1e-5,
                 sequence_parallel_enabled: bool = False, **kw):
        super().__init__(hidden_size, eps=eps, **kw)
        self.sequence_parallel_enabled = bool(sequence_parallel_enabled)


class FastLayerNorm(FusedLayerNorm):
    """apex routes hidden sizes with a persistent kernel here; the Pallas
    kernel handles every size, so this is an alias."""
