"""TPU rebuild of ``apex/transformer/tensor_parallel/data.py``.

Apex broadcasts each batch from TP rank 0 to the group over NCCL
(``broadcast_data``).  A single-controller JAX program hands every device
its data through shardings, so broadcast is a replication placement; the
dtype-checking surface is kept.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.transformer.parallel_state import get_mesh

_MAX_DATA_DIM = 5


def _check_data_types(keys, data, target_dtype):
    for k in keys:
        if data[k].dtype != target_dtype:
            raise AssertionError(
                f"{k} has data type {data[k].dtype} which "
                f"is different than {target_dtype}")


def broadcast_data(keys, data, datatype):
    """Replicate ``data[k]`` for each key across the mesh (apex
    ``broadcast_data``)."""
    _check_data_types(keys, data, datatype)
    mesh = get_mesh()
    repl = NamedSharding(mesh, P())
    return {k: jax.device_put(jnp.asarray(data[k]), repl) for k in keys}
