from apex_tpu.transformer.tensor_parallel.layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    linear_with_grad_accumulation_and_async_allreduce,
)
from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    scatter_to_tensor_model_parallel_region,
    gather_from_tensor_model_parallel_region,
    scatter_to_sequence_parallel_region,
    gather_from_sequence_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    column_parallel_linear_overlap,
    row_parallel_linear_overlap,
)
from apex_tpu.transformer.tensor_parallel.random import (
    checkpoint,
    get_cuda_rng_tracker,
    model_parallel_cuda_manual_seed,
    model_parallel_rng_key,
    CudaRNGStatesTracker,
)
from apex_tpu.transformer.tensor_parallel.utils import (
    divide,
    split_tensor_along_last_dim,
    split_tensor_into_1d_equal_chunks,
    gather_split_1d_tensor,
    VocabUtility,
)
from apex_tpu.transformer.tensor_parallel.data import broadcast_data

__all__ = [
    "ColumnParallelLinear",
    "RowParallelLinear",
    "VocabParallelEmbedding",
    "linear_with_grad_accumulation_and_async_allreduce",
    "vocab_parallel_cross_entropy",
    "copy_to_tensor_model_parallel_region",
    "reduce_from_tensor_model_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "scatter_to_sequence_parallel_region",
    "gather_from_sequence_parallel_region",
    "reduce_scatter_to_sequence_parallel_region",
    "column_parallel_linear_overlap",
    "row_parallel_linear_overlap",
    "checkpoint",
    "get_cuda_rng_tracker",
    "model_parallel_cuda_manual_seed",
    "model_parallel_rng_key",
    "CudaRNGStatesTracker",
    "divide",
    "split_tensor_along_last_dim",
    "split_tensor_into_1d_equal_chunks",
    "gather_split_1d_tensor",
    "VocabUtility",
    "broadcast_data",
]
