"""TPU rebuild of ``apex/transformer/tensor_parallel/utils.py``."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def divide(numerator: int, denominator: int) -> int:
    """Integer division asserting divisibility (apex ``divide``)."""
    if numerator % denominator != 0:
        raise ValueError(f"{numerator} is not divisible by {denominator}")
    return numerator // denominator


def split_tensor_along_last_dim(tensor, num_partitions: int,
                                contiguous_split_chunks: bool = False):
    """Split along the last dim into ``num_partitions`` chunks."""
    del contiguous_split_chunks  # always contiguous on TPU
    size = divide(tensor.shape[-1], num_partitions)
    return tuple(
        jnp.take(tensor,
                 jnp.arange(i * size, (i + 1) * size), axis=-1)
        for i in range(num_partitions))


def split_tensor_into_1d_equal_chunks(tensor, rank: int, world: int):
    """1-D equal chunk for distributed activation storage (apex
    ``split_tensor_into_1d_equal_chunks``; functional: rank explicit)."""
    flat = tensor.reshape(-1)
    size = divide(flat.shape[0], world)
    return jax.lax.dynamic_slice_in_dim(flat, rank * size, size)


def gather_split_1d_tensor(chunks):
    """Inverse of the split: concatenate chunks back to one flat tensor."""
    return jnp.concatenate(list(chunks))


class VocabUtility:
    """Vocab range helpers (apex ``VocabUtility``)."""

    @staticmethod
    def vocab_range_from_per_partition_vocab_size(per_partition_vocab_size,
                                                  rank, world_size):
        f = rank * per_partition_vocab_size
        return f, f + per_partition_vocab_size

    @staticmethod
    def vocab_range_from_global_vocab_size(global_vocab_size, rank,
                                           world_size):
        per = divide(global_vocab_size, world_size)
        return VocabUtility.vocab_range_from_per_partition_vocab_size(
            per, rank, world_size)

