"""Vocab-parallel cross entropy — TPU rebuild of
``apex/transformer/tensor_parallel/cross_entropy.py``.

Computes softmax cross-entropy over logits whose vocab (last) dim is sharded
across the tensor axis WITHOUT gathering them: max and sum-exp reduce with
``pmax``/``psum``, the target logit is picked locally (masked where the
label falls outside this shard's vocab range) and summed.  The backward is
the analytic ``softmax - onehot`` on the local shard — no collective needed,
exactly apex's ``_VocabParallelCrossEntropy``.  Label smoothing matches the
apex formula.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import TENSOR_AXIS
from apex_tpu.utils.collectives import axis_size as _axis_size

_f32 = jnp.float32


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def vocab_parallel_cross_entropy(vocab_parallel_logits, target,
                                 label_smoothing: float = 0.0,
                                 axis_name: str = TENSOR_AXIS):
    """Per-token loss for logits ``(..., vocab/t)`` and int targets
    ``(...)`` (global vocab ids).  Use inside ``shard_map`` with the vocab
    dim sharded over ``axis_name``; pass ``axis_name=None`` for the serial
    reference."""
    loss, _ = _vp_xent_fwd(vocab_parallel_logits, target, label_smoothing,
                           axis_name)
    return loss


def _vary(x, axis_name):
    if axis_name is None:
        return x
    from apex_tpu.utils.collectives import ensure_varying
    return ensure_varying(x, axis_name)


def _vp_xent_fwd(logits, target, label_smoothing, axis_name):
    x = _vary(logits.astype(_f32), axis_name)
    partition_vocab = x.shape[-1]
    if axis_name is not None:
        rank = jax.lax.axis_index(axis_name)
        world = _axis_size(axis_name)
        local_max = jnp.max(x, axis=-1)
        gmax = jax.lax.pmax(local_max, axis_name)
    else:
        rank, world = 0, 1
        gmax = jnp.max(x, axis=-1)
    x = x - gmax[..., None]
    exp_x = jnp.exp(x)
    local_sum = jnp.sum(exp_x, axis=-1)
    sum_exp = (jax.lax.psum(local_sum, axis_name)
               if axis_name is not None else local_sum)

    start = rank * partition_vocab
    local_t = target - start
    in_range = (local_t >= 0) & (local_t < partition_vocab)
    safe_t = jnp.where(in_range, local_t, 0)
    picked = jnp.take_along_axis(x, safe_t[..., None], axis=-1)[..., 0]
    picked = jnp.where(in_range, picked, 0.0)
    if axis_name is not None:
        picked = jax.lax.psum(picked, axis_name)

    log_z = jnp.log(sum_exp)
    loss = log_z - picked
    if label_smoothing > 0.0:
        # apex scales the mix: s_adj = s * V/(V-1), then
        # loss = (1-s_adj)*nll + s_adj * mean_i(log_z - logit_i)
        # INTENTIONAL DEVIATION from apex/Megatron for TP>1: the reference
        # forward averages logits over the LOCAL vocab shard only
        # (inconsistent with its own backward, which smooths over the full
        # vocab); here the mean is over the GLOBAL vocab (psum of shard
        # sums / full V), making fwd and bwd self-consistent.  Loss values
        # therefore differ from the reference when tp>1 and smoothing>0.
        assert 1.0 > label_smoothing > 0.0, label_smoothing
        vocab = partition_vocab * world if axis_name is not None else \
            partition_vocab
        s_adj = label_smoothing * vocab / (vocab - 1)
        local_logit_sum = jnp.sum(x, axis=-1)
        logit_sum = (jax.lax.psum(local_logit_sum, axis_name)
                     if axis_name is not None else local_logit_sum)
        smooth = log_z - logit_sum / vocab
        loss = (1.0 - s_adj) * loss + s_adj * smooth
    residuals = (exp_x, sum_exp, in_range, safe_t,
                 jnp.zeros((0,), logits.dtype))
    return loss, residuals


def _vp_xent_bwd(label_smoothing, axis_name, res, dloss):
    exp_x, sum_exp, in_range, safe_t, carrier = res
    softmax = exp_x / sum_exp[..., None]
    vocab_local = softmax.shape[-1]
    onehot = jax.nn.one_hot(safe_t, vocab_local, dtype=_f32)
    onehot = onehot * in_range[..., None]
    if label_smoothing > 0.0:
        world = (_axis_size(axis_name)
                 if axis_name is not None else 1)
        vocab = vocab_local * world
        s_adj = label_smoothing * vocab / (vocab - 1)
        grad = softmax - (1.0 - s_adj) * onehot - s_adj / vocab
    else:
        grad = softmax - onehot
    grad = grad * dloss.astype(_f32)[..., None]
    return grad.astype(carrier.dtype), None


vocab_parallel_cross_entropy.defvjp(_vp_xent_fwd, _vp_xent_bwd)
