"""TP region mappings — TPU rebuild of
``apex/transformer/tensor_parallel/mappings.py``.

Each mapping is a forward/backward-paired collective over the ``model`` mesh
axis, for use inside ``shard_map`` (the explicit-collective expression of
Megatron TP).  Under pure GSPMD (sharding annotations) these calls are not
needed — the compiler inserts them — but the explicit forms are the
load-bearing semantics for the 1:1 apex surface and for tests.

| apex function                                   | fwd            | bwd            |
|-------------------------------------------------|----------------|----------------|
| ``copy_to_tensor_model_parallel_region``         | identity       | all-reduce     |
| ``reduce_from_tensor_model_parallel_region``     | all-reduce     | identity       |
| ``scatter_to_tensor_model_parallel_region``      | split (last)   | all-gather     |
| ``gather_from_tensor_model_parallel_region``     | all-gather     | split (last)   |
| ``scatter_to_sequence_parallel_region``          | split (seq)    | all-gather     |
| ``gather_from_sequence_parallel_region``         | all-gather     | reduce-scatter |
| ``reduce_scatter_to_sequence_parallel_region``   | reduce-scatter | all-gather     |

The sequence mappings take a ``seq_dim`` (default 0, the apex ``(s, b, h)``
layout; GPT/BERT activations are ``(b, s, h)`` and pass ``seq_dim=1``).

Latency-hiding forms: :func:`column_parallel_linear_overlap` and
:func:`row_parallel_linear_overlap` fuse the sequence-parallel collective
with its adjacent GEMM as a ``ppermute`` ring — the gather→GEMM (column)
and GEMM→reduce-scatter (row) pairs decompose into per-shard steps where
each ICI transfer runs concurrently with the previous shard's GEMM, and a
custom VJP applies the same decomposition to the backward
all-gather/reduce-scatter (with the weight-grad partials accumulated
chunkwise during the same ring, Megatron's
``linear_with_grad_accumulation_and_async_allreduce`` overlap).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import TENSOR_AXIS


from apex_tpu.utils.collectives import ensure_varying as _vary
from apex_tpu.utils.collectives import axis_size as _axis_size


def _reduce(x, axis):
    return jax.lax.psum(_vary(x, axis), axis)


def _split_along_dim(x, dim, axis):
    n = _axis_size(axis)
    r = jax.lax.axis_index(axis)
    size = x.shape[dim] // n
    return jax.lax.dynamic_slice_in_dim(x, r * size, size, axis=dim)


def _gather_along_dim(x, dim, axis):
    return jax.lax.all_gather(_vary(x, axis), axis, axis=dim, tiled=True)


def _reduce_scatter_along_dim(x, dim, axis):
    return jax.lax.psum_scatter(_vary(x, axis), axis, scatter_dimension=dim,
                                tiled=True)


def _mk(name, fwd_fn, bwd_fn):
    @functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
    def f(x, axis=TENSOR_AXIS):
        return fwd_fn(x, axis)

    def f_fwd(x, axis):
        return fwd_fn(x, axis), None

    def f_bwd(axis, _, g):
        return (bwd_fn(g, axis),)

    f.defvjp(f_fwd, f_bwd)
    f.__name__ = name
    f.__qualname__ = name
    return f


copy_to_tensor_model_parallel_region = _mk(
    "copy_to_tensor_model_parallel_region",
    lambda x, ax: _vary(x, ax),
    lambda g, ax: _reduce(g, ax))

reduce_from_tensor_model_parallel_region = _mk(
    "reduce_from_tensor_model_parallel_region",
    lambda x, ax: _reduce(x, ax),
    lambda g, ax: _vary(g, ax))

scatter_to_tensor_model_parallel_region = _mk(
    "scatter_to_tensor_model_parallel_region",
    lambda x, ax: _split_along_dim(_vary(x, ax), -1, ax),
    lambda g, ax: _gather_along_dim(g, -1, ax))

gather_from_tensor_model_parallel_region = _mk(
    "gather_from_tensor_model_parallel_region",
    lambda x, ax: _gather_along_dim(x, -1, ax),
    lambda g, ax: _split_along_dim(_vary(g, ax), -1, ax))

def _mk_seq(name, fwd_fn, bwd_fn):
    """Like :func:`_mk` but with a ``seq_dim`` knob (nondiff, like the
    axis name) selecting which dimension is sequence-sharded."""
    @functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
    def f(x, axis=TENSOR_AXIS, seq_dim=0):
        return fwd_fn(x, axis, seq_dim)

    def f_fwd(x, axis, seq_dim):
        return fwd_fn(x, axis, seq_dim), None

    def f_bwd(axis, seq_dim, _, g):
        return (bwd_fn(g, axis, seq_dim),)

    f.defvjp(f_fwd, f_bwd)
    f.__name__ = name
    f.__qualname__ = name
    return f


scatter_to_sequence_parallel_region = _mk_seq(
    "scatter_to_sequence_parallel_region",
    lambda x, ax, d: _split_along_dim(_vary(x, ax), d, ax),
    lambda g, ax, d: _gather_along_dim(g, d, ax))

gather_from_sequence_parallel_region = _mk_seq(
    "gather_from_sequence_parallel_region",
    lambda x, ax, d: _gather_along_dim(x, d, ax),
    lambda g, ax, d: _reduce_scatter_along_dim(g, d, ax))

reduce_scatter_to_sequence_parallel_region = _mk_seq(
    "reduce_scatter_to_sequence_parallel_region",
    lambda x, ax, d: _reduce_scatter_along_dim(x, d, ax),
    lambda g, ax, d: _gather_along_dim(g, d, ax))


# -- latency-hiding ring forms (sequence parallelism + overlap) --------------

def _ring_perm(t):
    """Send-left ring: device ``i`` sends to ``i-1`` (receives from
    ``i+1``), so after ``k`` hops device ``r`` holds shard ``(r+k) % t``."""
    return [(i, (i - 1) % t) for i in range(t)]


def _chunked_matmul(block, w_t, chunks, seq_dim):
    """``block @ w_t`` split into ``chunks`` independent sub-GEMMs along
    ``seq_dim``.  Numerically identical to the monolithic product (row
    partitioning does not reorder any output element's contraction); the
    split lets the latency-hiding scheduler start the next ring transfer
    after the first sub-GEMM instead of after the whole block."""
    if chunks <= 1:
        return block @ w_t
    pieces = jnp.split(block, chunks, axis=seq_dim)
    return jnp.concatenate([p @ w_t for p in pieces], axis=seq_dim)


def _ring_gather_matmul(x, w_t, axis, seq_dim, chunks):
    """``all_gather(x, seq_dim, tiled) @ w_t`` without materializing the
    gather: a send-left ``ppermute`` ring where each step's GEMM overlaps
    the next shard's ICI transfer.  ``x``: the local sequence shard
    ``(..., s/t, ..., in)``; returns ``(..., s, ..., out)``."""
    t = int(_axis_size(axis))
    r = jax.lax.axis_index(axis)
    s_local = x.shape[seq_dim]
    out_shape = list(x.shape)
    out_shape[seq_dim] = s_local * t
    out_shape[-1] = w_t.shape[-1]
    y = jnp.zeros(out_shape, x.dtype)
    buf = _vary(x, axis)
    for k in range(t):
        blk = _chunked_matmul(buf, w_t, chunks, seq_dim)
        y = jax.lax.dynamic_update_slice_in_dim(
            y, blk.astype(y.dtype), ((r + k) % t) * s_local, axis=seq_dim)
        if k + 1 < t:
            buf = jax.lax.ppermute(buf, axis, _ring_perm(t))
    return y


def _ring_matmul_reduce_scatter(x, w_t, axis, seq_dim, chunks):
    """``psum_scatter(x @ w_t, seq_dim, tiled)`` without materializing the
    full product: at step ``k`` device ``d`` computes the partial product
    for target shard ``(d+k+1) % t``, adds the accumulator arriving from
    its ring neighbour, and forwards the sum — the partial GEMMs overlap
    the accumulator transfers, and after ``t`` steps each device holds its
    own fully-reduced shard.  ``x``: ``(..., s, ..., in)`` (full sequence,
    partial values); returns ``(..., s/t, ..., out)`` (reduced)."""
    t = int(_axis_size(axis))
    r = jax.lax.axis_index(axis)
    s_local = x.shape[seq_dim] // t
    x = _vary(x, axis)
    acc = None
    for k in range(t):
        blk = jax.lax.dynamic_slice_in_dim(
            x, ((r + k + 1) % t) * s_local, s_local, axis=seq_dim)
        part = _chunked_matmul(blk, w_t, chunks, seq_dim)
        acc = part if acc is None else acc + part
        if k + 1 < t:
            acc = jax.lax.ppermute(acc, axis, _ring_perm(t))
    return acc


def _mk_overlap(name, fwd_fn, bwd_fn):
    @functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
    def f(x, weight, axis=TENSOR_AXIS, seq_dim=0, chunks=1):
        return fwd_fn(x, weight, axis, seq_dim, chunks)

    def f_fwd(x, weight, axis, seq_dim, chunks):
        return fwd_fn(x, weight, axis, seq_dim, chunks), (x, weight)

    def f_bwd(axis, seq_dim, chunks, res, g):
        return bwd_fn(res, g, axis, seq_dim, chunks)

    f.defvjp(f_fwd, f_bwd)
    f.__name__ = name
    f.__qualname__ = name
    return f


def _column_overlap_fwd(x, weight, axis, seq_dim, chunks):
    # gather(x, seq) @ Wᵀ as one ring; W (out/t, in), x the local seq shard
    return _ring_gather_matmul(x, weight.astype(x.dtype).T, axis, seq_dim,
                               chunks)


def _column_overlap_bwd(res, g, axis, seq_dim, chunks):
    # dx = reduce_scatter(g @ W, seq) and dW = Σₖ g[shard k]ᵀ x[shard k]
    # share one fused ring: the dx accumulator and the regathered x shard
    # travel together, and each step's two partial GEMMs overlap both
    # transfers (the backward half of apex's
    # linear_with_grad_accumulation_and_async_allreduce).
    x, weight = res
    w_c = weight.astype(g.dtype)
    t = int(_axis_size(axis))
    r = jax.lax.axis_index(axis)
    s_local = x.shape[seq_dim]
    acc = None
    xbuf = _vary(x, axis)
    dw = jnp.zeros(weight.shape, jnp.float32)
    g = _vary(g, axis)
    for k in range(t):
        blk = jax.lax.dynamic_slice_in_dim(
            g, ((r + k + 1) % t) * s_local, s_local, axis=seq_dim)
        part = _chunked_matmul(blk, w_c, chunks, seq_dim)
        acc = part if acc is None else acc + part
        gk = jax.lax.dynamic_slice_in_dim(
            g, ((r + k) % t) * s_local, s_local, axis=seq_dim)
        dw = dw + jnp.einsum("...o,...h->oh", gk, xbuf,
                             preferred_element_type=jnp.float32)
        if k + 1 < t:
            acc = jax.lax.ppermute(acc, axis, _ring_perm(t))
            xbuf = jax.lax.ppermute(xbuf, axis, _ring_perm(t))
    return acc.astype(x.dtype), dw.astype(weight.dtype)


def _row_overlap_fwd(x, weight, axis, seq_dim, chunks):
    # (x @ Wᵀ) reduce-scattered over seq as one ring; W (out, in/t)
    return _ring_matmul_reduce_scatter(x, weight.astype(x.dtype).T, axis,
                                       seq_dim, chunks)


def _row_overlap_bwd(res, g, axis, seq_dim, chunks):
    # dx = gather(g, seq) @ W and dW = Σₖ g[shard k]ᵀ x[shard k] share the
    # g-regather ring: each arriving g shard feeds both partial GEMMs.
    x, weight = res
    w_c = weight.astype(g.dtype)
    t = int(_axis_size(axis))
    r = jax.lax.axis_index(axis)
    s_local = g.shape[seq_dim]
    dx = jnp.zeros(x.shape, x.dtype)
    dw = jnp.zeros(weight.shape, jnp.float32)
    buf = _vary(g, axis)
    for k in range(t):
        j = (r + k) % t
        blk = _chunked_matmul(buf, w_c, chunks, seq_dim)
        dx = jax.lax.dynamic_update_slice_in_dim(
            dx, blk.astype(dx.dtype), j * s_local, axis=seq_dim)
        xk = jax.lax.dynamic_slice_in_dim(
            x, j * s_local, s_local, axis=seq_dim)
        dw = dw + jnp.einsum("...o,...h->oh", buf, xk,
                             preferred_element_type=jnp.float32)
        if k + 1 < t:
            buf = jax.lax.ppermute(buf, axis, _ring_perm(t))
    return dx, dw.astype(weight.dtype)


column_parallel_linear_overlap = _mk_overlap(
    "column_parallel_linear_overlap",
    _column_overlap_fwd, _column_overlap_bwd)

row_parallel_linear_overlap = _mk_overlap(
    "row_parallel_linear_overlap",
    _row_overlap_fwd, _row_overlap_bwd)
