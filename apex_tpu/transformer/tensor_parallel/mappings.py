"""TP region mappings — TPU rebuild of
``apex/transformer/tensor_parallel/mappings.py``.

Each mapping is a forward/backward-paired collective over the ``model`` mesh
axis, for use inside ``shard_map`` (the explicit-collective expression of
Megatron TP).  Under pure GSPMD (sharding annotations) these calls are not
needed — the compiler inserts them — but the explicit forms are the
load-bearing semantics for the 1:1 apex surface and for tests.

| apex function                                   | fwd            | bwd            |
|-------------------------------------------------|----------------|----------------|
| ``copy_to_tensor_model_parallel_region``         | identity       | all-reduce     |
| ``reduce_from_tensor_model_parallel_region``     | all-reduce     | identity       |
| ``scatter_to_tensor_model_parallel_region``      | split (last)   | all-gather     |
| ``gather_from_tensor_model_parallel_region``     | all-gather     | split (last)   |
| ``scatter_to_sequence_parallel_region``          | split (first)  | all-gather     |
| ``gather_from_sequence_parallel_region``         | all-gather     | reduce-scatter |
| ``reduce_scatter_to_sequence_parallel_region``   | reduce-scatter | all-gather     |
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import TENSOR_AXIS


from apex_tpu.utils.collectives import ensure_varying as _vary
from apex_tpu.utils.collectives import axis_size as _axis_size


def _reduce(x, axis):
    return jax.lax.psum(_vary(x, axis), axis)


def _split_along_dim(x, dim, axis):
    n = _axis_size(axis)
    r = jax.lax.axis_index(axis)
    size = x.shape[dim] // n
    return jax.lax.dynamic_slice_in_dim(x, r * size, size, axis=dim)


def _gather_along_dim(x, dim, axis):
    return jax.lax.all_gather(_vary(x, axis), axis, axis=dim, tiled=True)


def _reduce_scatter_along_dim(x, dim, axis):
    return jax.lax.psum_scatter(_vary(x, axis), axis, scatter_dimension=dim,
                                tiled=True)


def _mk(name, fwd_fn, bwd_fn):
    @functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
    def f(x, axis=TENSOR_AXIS):
        return fwd_fn(x, axis)

    def f_fwd(x, axis):
        return fwd_fn(x, axis), None

    def f_bwd(axis, _, g):
        return (bwd_fn(g, axis),)

    f.defvjp(f_fwd, f_bwd)
    f.__name__ = name
    f.__qualname__ = name
    return f


copy_to_tensor_model_parallel_region = _mk(
    "copy_to_tensor_model_parallel_region",
    lambda x, ax: _vary(x, ax),
    lambda g, ax: _reduce(g, ax))

reduce_from_tensor_model_parallel_region = _mk(
    "reduce_from_tensor_model_parallel_region",
    lambda x, ax: _reduce(x, ax),
    lambda g, ax: _vary(g, ax))

scatter_to_tensor_model_parallel_region = _mk(
    "scatter_to_tensor_model_parallel_region",
    lambda x, ax: _split_along_dim(_vary(x, ax), -1, ax),
    lambda g, ax: _gather_along_dim(g, -1, ax))

gather_from_tensor_model_parallel_region = _mk(
    "gather_from_tensor_model_parallel_region",
    lambda x, ax: _gather_along_dim(x, -1, ax),
    lambda g, ax: _split_along_dim(_vary(g, ax), -1, ax))

scatter_to_sequence_parallel_region = _mk(
    "scatter_to_sequence_parallel_region",
    lambda x, ax: _split_along_dim(_vary(x, ax), 0, ax),
    lambda g, ax: _gather_along_dim(g, 0, ax))

gather_from_sequence_parallel_region = _mk(
    "gather_from_sequence_parallel_region",
    lambda x, ax: _gather_along_dim(x, 0, ax),
    lambda g, ax: _reduce_scatter_along_dim(g, 0, ax))

reduce_scatter_to_sequence_parallel_region = _mk(
    "reduce_scatter_to_sequence_parallel_region",
    lambda x, ax: _reduce_scatter_along_dim(x, 0, ax),
    lambda g, ax: _gather_along_dim(g, 0, ax))
