"""Tensor-parallel layers — TPU rebuild of
``apex/transformer/tensor_parallel/layers.py``.

Megatron TP semantics: ``ColumnParallelLinear`` shards the output dim (weight
shard ``(out/t, in)``), ``RowParallelLinear`` shards the input dim
(``(out, in/t)``), ``VocabParallelEmbedding`` shards the vocab rows; the
fwd/bwd-paired collectives come from ``mappings``.

Two execution modes per layer:

* ``axis_name=None`` — serial reference (full weights), used for parity
  tests and as the GSPMD form: jit it with the shards given by
  ``partition_spec()`` and the compiler inserts the same collectives this
  file writes explicitly (that is the idiomatic TPU path).
* ``axis_name="model"`` — explicit collectives, for ``shard_map`` training
  loops; the params passed in are the local shards.

apex's ``linear_with_grad_accumulation_and_async_allreduce`` overlaps the
input-grad all-reduce with the weight-grad GEMM via CUDA streams; under XLA
the latency-hiding scheduler performs that overlap on the compiled graph, so
the function here is the plain mapping composition
(``gradient_accumulation_fusion``'s fp32 main-grad accumulation is likewise
an XLA fusion).  ``sequence_parallel_enabled`` swaps the TP-edge collectives
for the gather/reduce-scatter pair along the sequence (first) dim.

Compiled evidence (not just assertion):
``tests/test_on_chip.py::TestScheduledCollectiveEvidence`` AOT-compiles this
block's grad for a real v5e:2x2 topology and pins, on the scheduled TPU
module, that (a) the psums lower to ICI ring all-reduces, (b) XLA's
combiner merges the per-weight gradient psums into ONE bucketed all-reduce
(the flattened-bucket allreduce apex DDP hand-rolls), and (c) the schedule
interleaves async data movement with compute fusions.  (TPU HLO keeps
all-reduce synchronous as an instruction — the ICI pipelining lives inside
the ring emitter — so start/done-style overlap shows up in the emitter
strategy and the async copy/slice pairs, not as split collective ops.)
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer.parallel_state import TENSOR_AXIS
from apex_tpu.transformer.tensor_parallel import mappings as M
from apex_tpu.transformer.tensor_parallel.utils import divide, VocabUtility

_f32 = jnp.float32


def _normal_init(std=0.02):
    def init(key, shape, dtype=_f32):
        return std * jax.random.normal(key, shape, dtype)
    return init


def linear_with_grad_accumulation_and_async_allreduce(
        x, weight, bias=None, gradient_accumulation_fusion: bool = False,
        async_grad_allreduce: bool = True,
        sequence_parallel_enabled: bool = False,
        axis_name: Optional[str] = TENSOR_AXIS,
        seq_dim: int = 0, overlap_chunks: int = 0,
        weight_scale=None):
    """Column-parallel matmul with the apex collective pairing.

    ``async_grad_allreduce`` is parity-only: the input-grad allreduce /
    wgrad-GEMM overlap it requests is the XLA latency-hiding scheduler's
    job here.  ``gradient_accumulation_fusion`` (apex: wgrad GEMM
    accumulating directly into an fp32 ``weight.main_grad``,
    ``fused_weight_gradient_mlp_cuda``) decomposes functionally: JAX
    cotangents must match the weight dtype, so the fp32 accumulation
    lives one level up — microbatch loops accumulate with
    ``apex_tpu.parallel.DistributedDataParallel.accumulate(...,
    main_grad_dtype=jnp.float32)`` and the optimizer applies them via its
    fp32 master path (``master_weights=True``).  Same arithmetic as the
    reference: per-microbatch bf16 wgrads summed in fp32.

    ``overlap_chunks > 0`` (requires ``sequence_parallel_enabled``) takes
    the explicit latency-hiding path instead: the sequence all-gather and
    the GEMM fuse into a ``ppermute`` ring
    (:func:`mappings.column_parallel_linear_overlap`) whose custom VJP
    rings the backward reduce-scatter and accumulates the weight grad
    chunkwise during the regather — the scheduled form of the overlap the
    apex signature promises.  Each ring step's GEMM is further split into
    ``overlap_chunks`` sub-GEMMs along ``seq_dim``.
    """
    del gradient_accumulation_fusion, async_grad_allreduce
    if axis_name is not None:
        if sequence_parallel_enabled and overlap_chunks > 0:
            y = M.column_parallel_linear_overlap(
                x, weight, axis_name, seq_dim, overlap_chunks)
            if bias is not None:
                y = y + bias.astype(y.dtype)
            return y
        if sequence_parallel_enabled:
            x = M.gather_from_sequence_parallel_region(x, axis_name,
                                                       seq_dim)
        else:
            x = M.copy_to_tensor_model_parallel_region(x, axis_name)
    if weight_scale is not None:
        # int8 decode weights (GPTConfig.weight_quant="int8"): the
        # fused dequant-GEMM replaces the activation-dtype matmul;
        # per-rank weight shards carry per-shard scales, so no
        # collective changes
        from apex_tpu.ops.quant_gemm import quant_gemm
        y = quant_gemm(x, weight, weight_scale).astype(x.dtype)
        if bias is not None:
            y = y + bias.astype(y.dtype)
        return y
    # compute at the ACTIVATION dtype (Megatron bf16 training keeps fp32
    # params as masters; the GEMM runs half).  Without the cast a bf16
    # activation silently promotes the whole GEMM to f32 — wrong dtype
    # contract AND off the MXU's bf16 rate.  The astype's transpose casts
    # the weight cotangent back to the param dtype automatically.
    y = x @ weight.astype(x.dtype).T
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y


class ColumnParallelLinear:
    """Y = XAᵀ with A sharded over rows (output features).

    Parity: ``ColumnParallelLinear(input_size, output_size, bias,
    gather_output, init_method, skip_bias_add, no_async_tensor_model_parallel_allreduce,
    sequence_parallel_enabled, gradient_accumulation_fusion)``.
    """

    def __init__(self, input_size, output_size, bias=True,
                 gather_output=True, init_method: Callable = None,
                 stride=1, keep_master_weight_for_test=False,
                 skip_bias_add=False,
                 no_async_tensor_model_parallel_allreduce=False,
                 sequence_parallel_enabled=False,
                 gradient_accumulation_fusion=False,
                 world_size: Optional[int] = None,
                 axis_name: Optional[str] = TENSOR_AXIS,
                 seq_dim: int = 0, overlap_chunks: int = 0,
                 param_dtype=_f32):
        if gather_output and sequence_parallel_enabled:
            raise RuntimeError(
                "`gather_output` and `sequence_parallel_enabled` cannot "
                "both be True")  # apex parity
        if overlap_chunks > 0 and not sequence_parallel_enabled:
            raise RuntimeError(
                "`overlap_chunks` rings the sequence-parallel "
                "gather→GEMM pair; it requires "
                "`sequence_parallel_enabled=True`")
        self.input_size = int(input_size)
        self.output_size = int(output_size)
        self.use_bias = bool(bias)
        self.gather_output = bool(gather_output)
        self.skip_bias_add = bool(skip_bias_add)
        self.sequence_parallel_enabled = bool(sequence_parallel_enabled)
        self.seq_dim = int(seq_dim)
        self.overlap_chunks = int(overlap_chunks)
        self.axis_name = axis_name
        self.world_size = int(world_size) if world_size else 1
        self.output_size_per_partition = divide(self.output_size,
                                                self.world_size)
        self.init_method = init_method or _normal_init()
        self.param_dtype = param_dtype

    def init_params(self, key, partition_rank: Optional[int] = None):
        """Full weights when ``partition_rank`` is None (serial/GSPMD form);
        a single local shard otherwise."""
        out = (self.output_size if partition_rank is None
               else self.output_size_per_partition)
        kw, _ = jax.random.split(key)
        w = self.init_method(kw, (self.output_size, self.input_size),
                             _f32).astype(self.param_dtype)
        if partition_rank is not None:
            w = w[partition_rank * out:(partition_rank + 1) * out]
        p = {"weight": w}
        if self.use_bias:
            p["bias"] = jnp.zeros((out,), self.param_dtype)
        return p

    def partition_spec(self):
        """GSPMD shardings: weight rows over the tensor axis."""
        spec = {"weight": P(TENSOR_AXIS, None)}
        if self.use_bias:
            spec["bias"] = P(TENSOR_AXIS)
        return spec

    def __call__(self, params, x):
        bias = params.get("bias") if self.use_bias else None
        y = linear_with_grad_accumulation_and_async_allreduce(
            x, params["weight"],
            None if self.skip_bias_add else bias,
            sequence_parallel_enabled=self.sequence_parallel_enabled,
            axis_name=self.axis_name, seq_dim=self.seq_dim,
            overlap_chunks=self.overlap_chunks,
            weight_scale=params.get("weight_scale"))
        if self.gather_output and self.axis_name is not None:
            y = M.gather_from_tensor_model_parallel_region(y, self.axis_name)
        if self.skip_bias_add:
            return y, bias
        return y, None

    apply = __call__


class RowParallelLinear:
    """Y = XAᵀ with A sharded over columns (input features)."""

    def __init__(self, input_size, output_size, bias=True,
                 input_is_parallel=False, init_method: Callable = None,
                 stride=1, keep_master_weight_for_test=False,
                 skip_bias_add=False, sequence_parallel_enabled=False,
                 gradient_accumulation_fusion=False,
                 world_size: Optional[int] = None,
                 axis_name: Optional[str] = TENSOR_AXIS,
                 seq_dim: int = 0, overlap_chunks: int = 0,
                 param_dtype=_f32):
        if sequence_parallel_enabled and not input_is_parallel:
            raise RuntimeError(
                "To enable `sequence_parallel_enabled`, "
                "`input_is_parallel` must be `True`")  # apex parity
        if overlap_chunks > 0 and not sequence_parallel_enabled:
            raise RuntimeError(
                "`overlap_chunks` rings the sequence-parallel "
                "GEMM→reduce-scatter pair; it requires "
                "`sequence_parallel_enabled=True`")
        self.input_size = int(input_size)
        self.output_size = int(output_size)
        self.use_bias = bool(bias)
        self.input_is_parallel = bool(input_is_parallel)
        self.skip_bias_add = bool(skip_bias_add)
        self.sequence_parallel_enabled = bool(sequence_parallel_enabled)
        self.seq_dim = int(seq_dim)
        self.overlap_chunks = int(overlap_chunks)
        self.axis_name = axis_name
        self.world_size = int(world_size) if world_size else 1
        self.input_size_per_partition = divide(self.input_size,
                                               self.world_size)
        self.init_method = init_method or _normal_init()
        self.param_dtype = param_dtype

    def init_params(self, key, partition_rank: Optional[int] = None):
        inp = (self.input_size if partition_rank is None
               else self.input_size_per_partition)
        kw, _ = jax.random.split(key)
        w = self.init_method(kw, (self.output_size, self.input_size),
                             _f32).astype(self.param_dtype)
        if partition_rank is not None:
            w = w[:, partition_rank * inp:(partition_rank + 1) * inp]
        p = {"weight": w}
        if self.use_bias:
            # bias is NOT sharded (applied after the reduce), like apex
            p["bias"] = jnp.zeros((self.output_size,), self.param_dtype)
        return p

    def partition_spec(self):
        spec = {"weight": P(None, TENSOR_AXIS)}
        if self.use_bias:
            spec["bias"] = P()
        return spec

    def __call__(self, params, x):
        if self.axis_name is not None and not self.input_is_parallel:
            x = M.scatter_to_tensor_model_parallel_region(x, self.axis_name)
        if (self.axis_name is not None and self.sequence_parallel_enabled
                and self.overlap_chunks > 0):
            # GEMM and reduce-scatter fused into one ppermute ring (the
            # custom VJP rings the backward gather + chunked wgrad too)
            y = M.row_parallel_linear_overlap(
                x, params["weight"], self.axis_name, self.seq_dim,
                self.overlap_chunks)
            bias = self._bias(params)
            if self.skip_bias_add:
                return y, bias
            if bias is not None:
                y = y + bias.astype(y.dtype)
            return y, None
        if "weight_scale" in params:
            # int8 decode weights: the column-sharded input contracts
            # against a per-shard-quantized weight; the psum/
            # reduce-scatter below is unchanged (dequantization is
            # per-rank-local)
            from apex_tpu.ops.quant_gemm import quant_gemm
            y = quant_gemm(x, params["weight"],
                           params["weight_scale"]).astype(x.dtype)
        else:
            # activation-dtype GEMM (see
            # linear_with_grad_accumulation_and_async_allreduce)
            y = x @ params["weight"].astype(x.dtype).T
        if self.axis_name is not None:
            if self.sequence_parallel_enabled:
                y = M.reduce_scatter_to_sequence_parallel_region(
                    y, self.axis_name, self.seq_dim)
            else:
                y = M.reduce_from_tensor_model_parallel_region(
                    y, self.axis_name)
        bias = self._bias(params)
        if self.skip_bias_add:
            return y, bias
        if bias is not None:
            y = y + bias.astype(y.dtype)
        return y, None

    def _bias(self, params):
        bias = params.get("bias") if self.use_bias else None
        if (bias is not None and self.sequence_parallel_enabled
                and self.axis_name is not None):
            # the bias lands on the SEQ-SHARDED output, so its cotangent
            # per device only covers the local tokens; identity-fwd /
            # psum-bwd restores the full grad (Megatron's allreduce of
            # sequence-parallel-region bias grads)
            bias = M.copy_to_tensor_model_parallel_region(
                bias, self.axis_name)
        return bias

    apply = __call__


class VocabParallelEmbedding:
    """Embedding with the vocab dim sharded over the tensor axis."""

    def __init__(self, num_embeddings, embedding_dim,
                 init_method: Callable = None,
                 world_size: Optional[int] = None,
                 axis_name: Optional[str] = TENSOR_AXIS,
                 param_dtype=_f32):
        self.num_embeddings = int(num_embeddings)
        self.embedding_dim = int(embedding_dim)
        self.axis_name = axis_name
        self.world_size = int(world_size) if world_size else 1
        self.num_embeddings_per_partition = divide(self.num_embeddings,
                                                   self.world_size)
        self.init_method = init_method or _normal_init()
        self.param_dtype = param_dtype

    def init_params(self, key, partition_rank: Optional[int] = None):
        n = (self.num_embeddings if partition_rank is None
             else self.num_embeddings_per_partition)
        w = self.init_method(key, (self.num_embeddings, self.embedding_dim),
                             _f32).astype(self.param_dtype)
        if partition_rank is not None:
            w = w[partition_rank * n:(partition_rank + 1) * n]
        return {"weight": w}

    def partition_spec(self):
        return {"weight": P(TENSOR_AXIS, None)}

    def __call__(self, params, token_ids):
        w = params["weight"]
        scale = params.get("weight_scale")

        def deq(rows, ids):
            # per-row dequantization of the GATHERED rows — bitwise
            # identical to gathering the dequantized table (the scale
            # multiply is elementwise per vocab row), without ever
            # materializing the f32 table
            if scale is None:
                return rows
            return rows.astype(_f32) * jnp.take(scale, ids,
                                                axis=0)[..., None]

        if self.axis_name is None:
            return deq(jnp.take(w, token_ids, axis=0), token_ids)
        rank = jax.lax.axis_index(self.axis_name)
        per = self.num_embeddings_per_partition
        start = rank * per
        local = token_ids - start
        in_range = (local >= 0) & (local < per)
        local = jnp.where(in_range, local, 0)
        emb = deq(jnp.take(w, local, axis=0), local)
        emb = jnp.where(in_range[..., None], emb, 0.0)
        return M.reduce_from_tensor_model_parallel_region(emb,
                                                          self.axis_name)

    apply = __call__
