"""Activation checkpointing + model-parallel RNG — TPU rebuild of
``apex/transformer/tensor_parallel/random.py``.

Apex needs a ``CudaRNGStatesTracker`` so dropout inside recomputed
(checkpointed) regions replays identically, and forks a distinct RNG stream
per TP rank.  JAX's explicit keys make both disappear by construction:

* recompute determinism — ``jax.checkpoint`` replays the same traced
  function with the same key;
* per-rank streams — ``jax.random.fold_in(key, rank)``.

The tracker API is kept as a shim so Megatron-style code paths run.
``checkpoint`` wraps ``jax.checkpoint``; ``distribute_saved_activations``
(apex: shard saved activations 1-D across TP ranks) is unnecessary under
remat — residuals are recomputed, not stored — and is accepted+ignored.
"""

from __future__ import annotations

import contextlib
from typing import Callable

import jax

from apex_tpu.transformer.parallel_state import (
    TENSOR_AXIS, get_tensor_model_parallel_rank)

_MODEL_PARALLEL_RNG_TRACKER_NAME = "model-parallel-rng"


def model_parallel_rng_key(base_key, axis_name: str = TENSOR_AXIS):
    """Per-TP-rank key (apex ``model_parallel_cuda_manual_seed``:
    ``seed + 2718 + tp_rank``)."""
    try:
        rank = jax.lax.axis_index(axis_name)
    except NameError:
        rank = 0
    return jax.random.fold_in(base_key, 2718 + rank)


def model_parallel_cuda_manual_seed(seed: int):
    """Returns ``(data_parallel_key, model_parallel_key_fn)`` — the JAX
    translation of apex's seeding: a shared key for replicated regions and
    a per-rank folded key for TP regions."""
    base = jax.random.PRNGKey(seed)
    return base, lambda axis_name=TENSOR_AXIS: model_parallel_rng_key(
        base, axis_name)


class CudaRNGStatesTracker:
    """API shim for apex ``CudaRNGStatesTracker`` over JAX keys."""

    def __init__(self):
        self.states_ = {}

    def reset(self):
        self.states_ = {}

    def get_states(self):
        return dict(self.states_)

    def set_states(self, states):
        self.states_ = dict(states)

    def add(self, name, seed):
        if name in self.states_:
            raise Exception(f"cuda rng state {name} already exists")
        self.states_[name] = jax.random.PRNGKey(seed)

    @contextlib.contextmanager
    def fork(self, name=_MODEL_PARALLEL_RNG_TRACKER_NAME):
        """Yield the stream's key and advance it (deterministic fork)."""
        if name not in self.states_:
            raise Exception(f"cuda rng state {name} is not added")
        key, next_key = jax.random.split(self.states_[name])
        self.states_[name] = next_key
        yield key


_CUDA_RNG_STATE_TRACKER = CudaRNGStatesTracker()


def get_cuda_rng_tracker() -> CudaRNGStatesTracker:
    return _CUDA_RNG_STATE_TRACKER


def checkpoint(function: Callable, distribute_saved_activations: bool,
               *args):
    """apex ``tensor_parallel.checkpoint``: recompute ``function`` in the
    backward.  Lowers to ``jax.checkpoint`` (remat); activation sharding is
    moot under recompute."""
    del distribute_saved_activations
    return jax.checkpoint(function)(*args)
