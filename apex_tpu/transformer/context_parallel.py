"""Context parallelism: ring attention + all-to-all (Ulysses) sequence
parallelism — BEYOND-REFERENCE long-context support.

The reference has NO context parallelism (SURVEY §2.3: no ring
attention, no Ulysses anywhere in apex; its fused softmax caps at seq 4K
and fmha at 512; Megatron SP only reshards norm/dropout regions).  This
module is the documented parity-plus extension the survey calls for:
sequences sharded over a ``context`` mesh axis with attention computed
across the full global sequence, scaling sequence length with the mesh.

Two mechanisms (both differentiable end-to-end, both tested to
loss+grad parity against serial attention):

* :func:`ring_attention` — KV chunks rotate around the ICI ring via
  ``lax.ppermute`` while each device's queries stay resident; partial
  attention per chunk is merged with the streaming-softmax (running
  max / sum-exp) recombination, so memory is O(s_local * s_local) per
  step and the full (s_global x s_global) score matrix never exists.
  Causality is enforced through global positions, so chunks entirely in
  the future contribute nothing.  Autodiff through the
  ``scan``+``ppermute`` yields the backward ring automatically (the
  transpose of a rotation is the reverse rotation).

* :func:`ulysses_attention` — DeepSpeed-Ulysses resharding:
  ``all_to_all`` swaps the sequence shard for a HEAD shard, every device
  runs the Pallas flash kernel over the FULL sequence for its head
  slice, and a second ``all_to_all`` swaps back.  Cost is two
  all-to-alls; heads must divide the axis size.

Call either inside ``shard_map`` with the sequence dim sharded
contiguously over ``axis_name`` (rank r holds rows
``[r*s_local, (r+1)*s_local)``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.ops.flash_attention import flash_attention
from apex_tpu.utils.collectives import axis_size as _axis_size

__all__ = ["ring_attention", "ulysses_attention"]

_f32 = jnp.float32
_NEG = -1e30


def ring_attention(q, k, v, axis_name: str = "context", causal: bool = False,
                   softmax_scale=None, remat: bool = True):
    """Exact global attention over a ring-sharded sequence.

    Args:
      q, k, v: ``(batch, heads, s_local, head_dim)`` — this device's
        sequence shard.
      axis_name: mesh axis the sequence is sharded over.
      causal: apply the global causal mask.
      remat: recompute each ring step's chunk scores in backward instead
        of saving them (memory ∝ one chunk instead of n chunks).

    Returns ``(batch, heads, s_local, head_dim)`` — attention of local
    queries over the GLOBAL key/value sequence.
    """
    n = _axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    b, h, sl, d = q.shape
    scale = softmax_scale if softmax_scale is not None else d ** -0.5
    if n == 1:
        return flash_attention(q, k, v, causal=causal,
                               softmax_scale=softmax_scale)

    qf = q.astype(_f32)
    rows = jnp.arange(sl)

    def chunk_scores(kc, chunk_id):
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kc.astype(_f32)) * scale
        if causal:
            g_q = rank * sl + rows                       # global query rows
            g_k = chunk_id * sl + rows                   # global key cols
            valid = g_k[None, :] <= g_q[:, None]
            s = jnp.where(valid[None, None], s, _NEG)
        return s

    def combine(m, l, acc, kc, vc, chunk_id):
        s = chunk_scores(kc, chunk_id)
        m_chunk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_chunk)
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vc.astype(_f32))
        return m_new, l_new, acc_new

    if remat:
        combine = jax.checkpoint(combine)

    def step(carry, t):
        m, l, acc, kc, vc = carry
        m, l, acc = combine(m, l, acc, kc, vc, (rank - t) % n)
        # rotate KV one hop around the ring (device i -> i+1), so next
        # step this device holds chunk (rank - t - 1) mod n
        perm = [(i, (i + 1) % n) for i in range(n)]
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (m, l, acc, kc, vc), None

    from apex_tpu.utils.collectives import ensure_varying

    # initial accumulators are constants (device-invariant); the loop
    # makes them varying over the ring axis, so the carry must start
    # varying for scan's type check (JAX 0.9 vma tracking)
    m0, l0, acc0 = ensure_varying(
        (jnp.full((b, h, sl, 1), _NEG, _f32),
         jnp.zeros((b, h, sl, 1), _f32),
         jnp.zeros((b, h, sl, d), _f32)), axis_name)
    # n-1 (combine, rotate) steps, then the last combine WITHOUT the
    # rotation — collectives in a scan body are never DCE'd, so a full
    # n-step scan would pay one dead KV ppermute pair per call
    (m, l, acc, kc, vc), _ = jax.lax.scan(
        step, (m0, l0, acc0, k, v), jnp.arange(n - 1))
    m, l, acc = combine(m, l, acc, kc, vc, (rank - (n - 1)) % n)
    # fully-masked rows (none exist with causal self-attention, but keep
    # the kernel's l==0 guard semantics)
    out = acc / jnp.where(l == 0.0, 1.0, l)
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str = "context",
                      causal: bool = False, softmax_scale=None,
                      block_q: int = 128, block_k: int = 128):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses).

    Reshards ``(b, h, s/n, d)`` → ``(b, h/n, s, d)`` with one
    ``all_to_all``, runs the Pallas flash kernel over the full sequence
    locally (so the MXU-optimized kernel does all the math), and
    reshards back.  ``heads`` must be divisible by the axis size.
    """
    n = _axis_size(axis_name)
    b, h, sl, d = q.shape
    if n == 1:
        return flash_attention(q, k, v, causal=causal,
                               softmax_scale=softmax_scale,
                               block_q=block_q, block_k=block_k)
    if h % n:
        raise ValueError(
            f"heads ({h}) must be divisible by the context axis size ({n})")

    def to_seq(x):
        # (b, h, sl, d) -> (b, h/n, n*sl, d): split heads over the axis,
        # concatenate the gathered sequence chunks
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    def to_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    out = flash_attention(to_seq(q), to_seq(k), to_seq(v), causal=causal,
                          softmax_scale=softmax_scale, block_q=block_q,
                          block_k=block_k)
    return to_heads(out)
