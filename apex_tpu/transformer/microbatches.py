"""Microbatch calculators — TPU rebuild of
``apex/transformer/microbatches.py``.

Same three classes/factory as apex: a constant calculator and a
batch-size-rampup calculator, built by ``build_num_microbatches_calculator``.
"""

from __future__ import annotations

from typing import Optional


def build_num_microbatches_calculator(rank, rampup_batch_size,
                                      global_batch_size, micro_batch_size,
                                      data_parallel_size):
    if rampup_batch_size is None:
        return ConstantNumMicroBatches(global_batch_size, micro_batch_size,
                                       data_parallel_size)
    if len(rampup_batch_size) != 3:
        raise ValueError("expected the following format: --rampup-batch-size"
                         " <start batch size> <batch size increment> "
                         "<ramp-up samples>")
    start, incr, samples = (int(v) for v in rampup_batch_size)
    return RampupBatchsizeNumMicroBatches(
        start, incr, samples, global_batch_size, micro_batch_size,
        data_parallel_size)


class NumMicroBatchesCalculator:
    def __init__(self):
        self.num_micro_batches: Optional[int] = None
        self.current_global_batch_size: Optional[int] = None

    def get(self):
        return self.num_micro_batches

    def get_current_global_batch_size(self):
        return self.current_global_batch_size

    def update(self, consumed_samples, consistency_check):
        raise NotImplementedError


class ConstantNumMicroBatches(NumMicroBatchesCalculator):
    def __init__(self, global_batch_size, micro_batch_size,
                 data_parallel_size):
        super().__init__()
        micro_batch_times_dp = micro_batch_size * data_parallel_size
        if global_batch_size % micro_batch_times_dp != 0:
            raise ValueError(
                f"global batch size ({global_batch_size}) is not divisible "
                f"by micro batch size ({micro_batch_size}) times data "
                f"parallel size ({data_parallel_size})")
        self.num_micro_batches = global_batch_size // micro_batch_times_dp
        if self.num_micro_batches < 1:
            raise ValueError("number of micro-batches is less than 1")
        self.current_global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size

    def update(self, consumed_samples, consistency_check):
        pass


class RampupBatchsizeNumMicroBatches(NumMicroBatchesCalculator):
    def __init__(self, start_batch_size, batch_size_increment, ramup_samples,
                 global_batch_size, micro_batch_size, data_parallel_size):
        super().__init__()
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.start_batch_size = start_batch_size
        self.batch_size_increment = batch_size_increment
        self.ramup_samples = ramup_samples
        self.global_batch_size = global_batch_size
        self.micro_batch_times_data_parallel_size = \
            micro_batch_size * data_parallel_size
        if global_batch_size % self.micro_batch_times_data_parallel_size:
            raise ValueError("global batch size not divisible by micro "
                             "batch size times data parallel size")
        if batch_size_increment <= 0:
            raise ValueError(
                f"batch size increment ({batch_size_increment}) must be "
                "positive")
        diff = global_batch_size - start_batch_size
        if diff < 0 or diff % batch_size_increment != 0:
            raise ValueError(
                f"expected global batch size interval ({diff}) to be "
                f"divisible by global batch size increment "
                f"({batch_size_increment})")
        num_increments = diff // batch_size_increment
        self.rampup_samples_per_increment = (
            self.ramup_samples / num_increments if num_increments else 0)
        self.update(0, False)

    def update(self, consumed_samples, consistency_check):
        if consumed_samples > self.ramup_samples or \
                self.rampup_samples_per_increment == 0:
            self.current_global_batch_size = self.global_batch_size
        else:
            steps = int(consumed_samples //
                        self.rampup_samples_per_increment)
            self.current_global_batch_size = min(
                self.start_batch_size
                + steps * self.batch_size_increment,
                self.global_batch_size)
        if self.current_global_batch_size % \
                self.micro_batch_times_data_parallel_size != 0:
            # apex asserts here: the ramp configuration must keep every
            # intermediate batch size a multiple of micro*dp
            raise ValueError(
                f"current global batch size "
                f"({self.current_global_batch_size}) is not divisible by "
                f"micro-batch-size ({self.micro_batch_size}) times "
                f"data parallel size ({self.data_parallel_size})")
        self.num_micro_batches = (
            self.current_global_batch_size //
            self.micro_batch_times_data_parallel_size)
