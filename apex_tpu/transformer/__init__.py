"""apex.transformer equivalent: Megatron-style model parallelism on a TPU
mesh (reference: ``apex/transformer/__init__.py``)."""

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer import tensor_parallel
from apex_tpu.transformer import pipeline_parallel
from apex_tpu.transformer import context_parallel
from apex_tpu.transformer import expert_parallel
from apex_tpu.transformer.microbatches import (
    build_num_microbatches_calculator,
    ConstantNumMicroBatches,
    RampupBatchsizeNumMicroBatches,
)
from apex_tpu.transformer.enums import (AttnMaskType, AttnType, LayerType,
                                        ModelType)

__all__ = [
    "parallel_state",
    "tensor_parallel",
    "pipeline_parallel",
    "context_parallel",
    "expert_parallel",
    "build_num_microbatches_calculator",
    "ConstantNumMicroBatches",
    "RampupBatchsizeNumMicroBatches",
    "AttnMaskType",
    "AttnType",
    "LayerType",
    "ModelType",
]
