"""Scan-compiled pipeline-parallel engine on ppermute rings.

This is the heart of the rebuilt ``pipeline_parallel`` subsystem: one
``lax.scan`` whose body is a *uniform* SPMD tick — every pipeline device
runs the same program every tick, executing (at most) one forward job and
one backward job.  That uniform tick is exactly the 1F1B steady state; the
warmup and cooldown phases fall out as ticks whose forward or backward job
is masked invalid.  Interleaved virtual stages are the same scan with each
device owning ``n_virtual`` model chunks and the ring wrap carrying a
microbatch from chunk ``c`` on the last device to chunk ``c+1`` on the
first.

Why hand-rolled backward instead of ``jax.grad`` over the scan: on the jax
0.4.x era this package supports, differentiating collectives inside
``shard_map`` hits the psum-transpose bug (cotangents multiplied by axis
size) and replicated-operand grads come back as per-device partials.  The
engine therefore never differentiates through a collective: activations hop
forward and cotangents hop backward via ``ppermute`` as *plain data*, and
each backward job recomputes its stage forward under a local ``jax.vjp``
(activation recompute; only the stage-boundary inputs are saved, in an
O(n_virtual · n_stages) ring buffer).  All cross-device reductions of the
results are forward-mode ``psum`` of one-nonzero-plus-zeros, which is
bitwise-exact.

Schedule arithmetic (S = pipe axis size, v = virtual chunks per device,
L = v·S logical stages, M microbatches, logical stage ℓ = c·S + s):

* forward job of device ``s`` at tick ``t``:  ``z = t − s``; valid iff
  ``0 ≤ z < M·v``; decode ``q = z // (vS)``, ``c = (z % (vS)) // S``,
  ``i = z % S``; the job runs microbatch ``m = q·S + i`` through chunk
  ``c``.
* backward job at tick ``t``:  ``z = t + s + 2 − (v+1)·S``; same decode
  except the chunk runs in reverse: ``c = v − 1 − (z % (vS)) // S``.
* total ticks ``T = M·v + (v+1)·S − 2`` (for v=1: ``M + 2S − 2``).

Both rings advance one hop per tick, so a message sent at tick ``t``
arrives exactly when the receiving job needs it at ``t+1``; the wrap hop
(device S−1 → 0 forward, 0 → S−1 backward) carries the virtual-chunk
advance.  The backward job for microbatch ``m`` at logical stage ℓ runs
``Δ = 2S(v−c) − 2s − 2`` ticks after its forward job, bounded by 2L−2, so a
ring buffer of ``B = 2L−1`` saved stage inputs suffices (Δ = 0 on the last
logical stage: the buffer is written before it is read within the tick).

Grounding: 1F1B/interleaved schedules follow Megatron/apex
(``forward_backward_pipelining_{without,with}_interleaving``); the
single-executable collective-permute formulation follows the GSPMD
(arxiv 2105.04663) and MPMD-pipeline (arxiv 2412.14374) shifted-buffer
pattern.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import PIPELINE_AXIS
from apex_tpu.transformer.pipeline_parallel import p2p_communication as p2p
from apex_tpu.utils.collectives import axis_size as _axis_size

__all__ = [
    "JobInfo", "pipeline_schedule_step", "pipeline_forward",
    "pipeline_value_and_grad", "schedule_ticks", "bubble_fraction",
]


class JobInfo(NamedTuple):
    """Identity of the job a stage function is running (traced scalars).

    ``stage`` is the *logical* stage index ``chunk·S + device`` in
    ``[0, n_virtual·S)`` — what a layer-offset or dropout-seed computation
    wants.  ``microbatch`` indexes the leading axis of the engine's
    ``x0``/``targets``.
    """
    microbatch: Any
    stage: Any
    chunk: Any


def schedule_ticks(n_microbatches: int, n_stages: int,
                   n_virtual: int = 1) -> int:
    """Scan length of the schedule: ``M·v + (v+1)·S − 2`` uniform ticks."""
    return n_microbatches * n_virtual + (n_virtual + 1) * n_stages - 2


def bubble_fraction(n_microbatches: int, n_stages: int,
                    n_virtual: int = 1) -> float:
    """Idle fraction of the schedule in tick units: each device has
    ``M·v`` forward and ``M·v`` backward job slots over ``T`` ticks of two
    slots each, so the bubble is ``1 − M·v/T``.  Interleaving shrinks the
    fill/drain ramps from ``2S`` to ``S·(1+1/v)`` stage-times."""
    t = schedule_ticks(n_microbatches, n_stages, n_virtual)
    return 1.0 - (n_microbatches * n_virtual) / t


def _tmap(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


def _dyn_index(tree, i):
    return _tmap(
        lambda a: jax.lax.dynamic_index_in_dim(a, i, 0, keepdims=False),
        tree)


def _take_chunk(tree, c, n_virtual):
    if n_virtual == 1:
        return tree
    return _dyn_index(tree, c)


def _static_axis_size(axis_name):
    n = _axis_size(axis_name)
    try:
        return int(n)
    except (TypeError, jax.errors.TracerIntegerConversionError) as e:
        raise ValueError(
            f"pipeline axis {axis_name!r} size is not statically known "
            "inside this trace; the scan-based schedule needs a concrete "
            "mesh axis (run under shard_map over the pipe axis)") from e


def _microbatch_count(x0):
    leaves = jax.tree_util.tree_leaves(x0)
    if not leaves:
        raise ValueError("x0 has no array leaves")
    return int(leaves[0].shape[0])


def pipeline_schedule_step(stage_fn: Callable, last_fn: Callable,
                           stage_params, last_params, x0, targets, *,
                           axis_name: str = PIPELINE_AXIS,
                           n_virtual: int = 1):
    """Run one full pipeline training step (loss + grads) as one scan.

    Args:
      stage_fn: ``stage_fn(chunk_params, x, info: JobInfo) -> y`` — applies
        one model chunk.  ``x``/``y`` must share pytree structure, shapes
        and dtypes (the ring carries them); a ``(hidden, aux)`` tuple works
        (MoE aux-loss cotangents ride the backward ring like any leaf).
      last_fn: ``last_fn(last_params, y, target, info) -> scalar`` —
        per-microbatch loss from the final chunk's output (e.g. final LN +
        LM head + CE).  Called every tick on every device for SPMD
        uniformity; only the last logical stage's value is kept.
      stage_params: this device's chunk parameters.  With ``n_virtual > 1``
        every leaf carries a leading ``(n_virtual, ...)`` axis (chunk ``c``
        on device ``s`` is logical stage ``c·S + s``).
      last_params: parameters of ``last_fn`` (replicated over the pipe
        axis; their gradient is psum-reduced).
      x0: first-stage inputs, leaves ``(M, ...)`` — one slice per
        microbatch.  Replicated over the pipe axis.
      targets: per-microbatch targets, leaves ``(M, ...)``.

    Returns:
      ``(loss, stage_grads, last_grads, dx0)`` where ``loss`` is the mean
      per-microbatch loss (replicated), ``stage_grads`` matches
      ``stage_params`` (device-local), ``last_grads`` matches
      ``last_params`` (replicated), and ``dx0`` is the cotangent of ``x0``
      (replicated) for chaining into an embedding pullback.

    The accumulation order (ascending microbatch, loss cotangent seeded at
    ``1/M``) is identical at every ``(S, v)`` including S=1, so schedules
    match each other — and the no-pipelining reference — bitwise in f32.
    """
    S = _static_axis_size(axis_name)
    v = int(n_virtual)
    if v < 1:
        raise ValueError(f"n_virtual must be >= 1, got {n_virtual}")
    M = _microbatch_count(x0)
    if v > 1 and M % S != 0:
        raise ValueError(
            f"interleaved schedule needs n_microbatches % n_stages == 0, "
            f"got M={M}, S={S}")
    L = v * S
    B = 2 * L - 1
    T = schedule_ticks(M, S, v)
    s = jax.lax.axis_index(axis_name)
    inv_m = jnp.float32(1.0 / M)

    x_tmpl = _tmap(lambda a: jnp.zeros(a.shape[1:], a.dtype), x0)
    carry0 = (
        x_tmpl,                                             # fwd ring msg
        x_tmpl,                                             # bwd ring msg
        _tmap(lambda a: jnp.zeros((B,) + a.shape[1:], a.dtype), x0),
        _tmap(jnp.zeros_like, stage_params),                # stage grads
        _tmap(jnp.zeros_like, last_params),                 # last-fn grads
        _tmap(jnp.zeros_like, x0),                          # dx0 scatter
        jnp.float32(0.0),                                   # loss sum
    )

    def tick(carry, t):
        fwd_msg, bwd_msg, xsave, sgrad, lgrad, dx0_acc, loss_acc = carry

        # ---- forward job indices -------------------------------------
        zf = t - s
        fwd_valid = (zf >= 0) & (zf < M * v)
        zfc = jnp.clip(zf, 0, M * v - 1)
        cf = (zfc % (v * S)) // S
        mf = (zfc // (v * S)) * S + zfc % S
        stage_f = cf * S + s

        # ---- forward job ---------------------------------------------
        inject = (s == 0) & (cf == 0)
        x_f = _tmap(lambda xi, msg: jnp.where(inject, xi, msg),
                    _dyn_index(x0, mf), fwd_msg)
        y_f = stage_fn(_take_chunk(stage_params, cf, v), x_f,
                       JobInfo(mf, stage_f, cf))
        slot_w = jnp.mod(t, B)
        xsave = _tmap(
            lambda buf, xx: jax.lax.dynamic_update_index_in_dim(
                buf, xx, slot_w, 0),
            xsave, x_f)

        # ---- backward job indices ------------------------------------
        zb = t + s + 2 - (v + 1) * S
        bwd_valid = (zb >= 0) & (zb < M * v)
        zbc = jnp.clip(zb, 0, M * v - 1)
        cb = (v - 1) - (zbc % (v * S)) // S
        mb = (zbc // (v * S)) * S + zbc % S
        stage_b = cb * S + s
        is_last = stage_b == (L - 1)

        # ---- backward job: recompute forward under a local vjp -------
        delta = 2 * S * (v - cb) - 2 * s - 2
        x_b = _dyn_index(xsave, jnp.mod(t - delta, B))
        tgt_b = _dyn_index(targets, mb)
        info_b = JobInfo(mb, stage_b, cb)

        def job(cp, lp, xx):
            y = stage_fn(cp, xx, info_b)
            return y, last_fn(lp, y, tgt_b, info_b)

        (y_b, lm), pull = jax.vjp(
            job, _take_chunk(stage_params, cb, v), last_params, x_b)
        # Joint cotangent: interior stages pull the ring message through
        # the chunk (the loss path gets a structural-zero seed); the last
        # logical stage seeds the loss at 1/M and zeros the ring message.
        dy = _tmap(lambda m, yy: jnp.where(is_last, jnp.zeros_like(yy), m),
                   bwd_msg, y_b)
        dlm = jnp.where(is_last, inv_m, jnp.float32(0.0))
        dcp, dlp, dx = pull((dy, dlm))

        # ---- masked accumulation -------------------------------------
        def acc_chunk(a, g):
            g = jnp.where(bwd_valid, g, jnp.zeros_like(g))
            return a + g if v == 1 else a.at[cb].add(g)
        sgrad = _tmap(acc_chunk, sgrad, dcp)
        lvalid = bwd_valid & is_last
        lgrad = _tmap(lambda a, g: a + jnp.where(lvalid, g,
                                                 jnp.zeros_like(g)),
                      lgrad, dlp)
        loss_acc = loss_acc + jnp.where(lvalid, lm, jnp.float32(0.0))
        first_b = bwd_valid & (s == 0) & (cb == 0)
        dx0_acc = _tmap(
            lambda a, g: a.at[mb].add(jnp.where(first_b, g,
                                                jnp.zeros_like(g))),
            dx0_acc, dx)

        # ---- ring hops (wrap carries the virtual-chunk advance) ------
        fwd_msg = p2p.send_forward_recv_forward(
            y_f, axis_name=axis_name, wrap=True)
        bwd_msg = p2p.send_backward_recv_backward(
            dx, axis_name=axis_name, wrap=True)
        return (fwd_msg, bwd_msg, xsave, sgrad, lgrad, dx0_acc,
                loss_acc), None

    carry, _ = jax.lax.scan(tick, carry0, jnp.arange(T))
    _, _, _, sgrad, lgrad, dx0_acc, loss_acc = carry

    # Forward-mode reductions of one-nonzero-plus-zeros: bitwise-exact and
    # never differentiated through.
    loss = jax.lax.psum(loss_acc, axis_name) * inv_m
    last_grads = jax.lax.psum(lgrad, axis_name)
    dx0 = jax.lax.psum(dx0_acc, axis_name)
    return loss, sgrad, last_grads, dx0


def pipeline_forward(stage_fn: Callable, stage_params, x0, *,
                     axis_name: str = PIPELINE_AXIS, n_virtual: int = 1):
    """Forward-only pipeline: run every microbatch through all logical
    stages and return the last stage's outputs stacked ``(M, ...)``,
    replicated over the pipe axis.  Same job arithmetic as
    :func:`pipeline_schedule_step` with the backward half dropped
    (``T = M·v + S − 1`` ticks)."""
    S = _static_axis_size(axis_name)
    v = int(n_virtual)
    M = _microbatch_count(x0)
    if v > 1 and M % S != 0:
        raise ValueError(
            f"interleaved schedule needs n_microbatches % n_stages == 0, "
            f"got M={M}, S={S}")
    T = M * v + S - 1
    s = jax.lax.axis_index(axis_name)

    x_tmpl = _tmap(lambda a: jnp.zeros(a.shape[1:], a.dtype), x0)
    outs0 = _tmap(jnp.zeros_like, x0)

    def tick(carry, t):
        fwd_msg, outs = carry
        zf = t - s
        fwd_valid = (zf >= 0) & (zf < M * v)
        zfc = jnp.clip(zf, 0, M * v - 1)
        cf = (zfc % (v * S)) // S
        mf = (zfc // (v * S)) * S + zfc % S
        inject = (s == 0) & (cf == 0)
        x_f = _tmap(lambda xi, msg: jnp.where(inject, xi, msg),
                    _dyn_index(x0, mf), fwd_msg)
        y_f = stage_fn(_take_chunk(stage_params, cf, v), x_f,
                       JobInfo(mf, cf * S + s, cf))
        done = fwd_valid & (s == S - 1) & (cf == v - 1)
        outs = _tmap(
            lambda a, yy: a.at[mf].add(jnp.where(done, yy,
                                                 jnp.zeros_like(yy))),
            outs, y_f)
        fwd_msg = p2p.send_forward_recv_forward(
            y_f, axis_name=axis_name, wrap=True)
        return (fwd_msg, outs), None

    (_, outs), _ = jax.lax.scan(tick, (x_tmpl, outs0), jnp.arange(T))
    return jax.lax.psum(outs, axis_name)


def pipeline_value_and_grad(stage_fn: Callable, loss_fn: Callable, params,
                            microbatches, targets, *,
                            axis_name: str = PIPELINE_AXIS,
                            n_virtual: int = 1):
    """Convenience wrapper for parameter-free losses: adapts plain
    ``stage_fn(params, x)`` / ``loss_fn(y, target)`` callables onto
    :func:`pipeline_schedule_step` and returns ``(loss, stage_grads)``."""
    loss, sgrad, _, _ = pipeline_schedule_step(
        lambda p, x, info: stage_fn(p, x),
        lambda lp, y, tgt, info: loss_fn(y, tgt),
        params, (), microbatches, targets,
        axis_name=axis_name, n_virtual=n_virtual)
    return loss, sgrad
