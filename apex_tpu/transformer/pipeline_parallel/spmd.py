"""SPMD pipeline engine — the TPU-native core under the apex schedule API
(reference: ``apex/transformer/pipeline_parallel/schedules/fwd_bwd_schedules``).

Apex drives MPMD pipelining imperatively: each rank loops over microbatches
doing NCCL P2P ``recv_forward → forward → send_forward`` with a 1F1B
steady state.  The TPU-native equivalent is a *single SPMD program*: every
pipeline stage runs the same ``lax.scan`` over ticks, activations rotate one
hop per tick via ``lax.ppermute`` over the ``pipe`` mesh axis, and autodiff
of the scan yields the backward pipeline (the transpose of ``ppermute`` is
the reverse rotation, so backward activations flow stage S-1 → 0 exactly
like apex's ``send_backward``).  The warmup/cooldown bubbles appear as
ticks where early/late stages compute on garbage that is masked out —
the same bubble fraction (S-1)/(M+S-1) as 1F1B.  Scheduling
(compute/communication overlap) is XLA's latency-hiding scheduler's job;
memory is bounded by applying ``jax.checkpoint`` to the stage function
(pass ``remat=True``) instead of 1F1B's early-backward trick.

Interleaved (virtual) pipelining stacks ``v`` model chunks per stage
(leading axis of the params pytree); an activation traverses logical stage
``c*S + s`` = chunk ``c`` on device ``s``, hopping device ring each tick and
advancing chunk on the wrap, reproducing apex's
``virtual_pipeline_model_parallel_size`` placement.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import PIPELINE_AXIS


def _ring_perm(n):
    return [(i, (i + 1) % n) for i in range(n)]


def spmd_pipeline(stage_fn: Callable, params, microbatches, *,
                  axis_name: str = PIPELINE_AXIS, n_virtual: int = 1,
                  remat: bool = False):
    """Run ``M`` microbatches through an ``S``(×``v``)-stage pipeline.

    Must be called inside ``shard_map`` with ``axis_name`` in scope.

    Args:
      stage_fn: ``(params_chunk, x) -> y`` — this device's stage (or one
        chunk of it); activation shapes must be uniform across stages.
      params: stage-local params; with ``n_virtual > 1`` every leaf has a
        leading ``(n_virtual, ...)`` chunk axis.
      microbatches: ``(M, ...)`` microbatched activations; only stage 0's
        value is read (other stages may pass the same array — it arrives
        replicated from the data loader anyway).
      remat: rematerialize the stage in backward (activation
        checkpointing; replaces apex's 1F1B memory policy).

    Returns:
      ``(M, ...)`` outputs of the final logical stage (meaningful on the
      last device; other devices hold garbage the caller masks — apex
      likewise only has losses on the last rank).
    """
    S = jax.lax.axis_size(axis_name)
    s = jax.lax.axis_index(axis_name)
    M = microbatches.shape[0]
    v = int(n_virtual)
    L = S * v
    T = M + L - 1

    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    def run_chunks(params, x):
        # x: (v, mb...) — chunk c's incoming activation
        if v == 1:
            return stage_fn(
                jax.tree_util.tree_map(lambda p: p[0], params),
                x[0])[None]
        return jax.vmap(stage_fn)(params, x)

    stacked_params = params
    if v == 1:
        stacked_params = jax.tree_util.tree_map(lambda p: p[None],
                                                params)

    # Make every param leaf varying over the activation axes (e.g. the
    # data axis in a dp x pp mesh): the backward scan's param-cotangent
    # carries are varying over those axes, and JAX 0.9 requires carry vma
    # to match.  pcast's transpose is a psum over the added axes, which is
    # exactly the cross-device grad accumulation those params need.
    act_vma = set(jax.typeof(microbatches).vma) | {axis_name}

    def _vary(p):
        missing = tuple(act_vma - set(jax.typeof(p).vma))
        return jax.lax.pcast(p, missing, to="varying") if missing else p

    stacked_params = jax.tree_util.tree_map(_vary, stacked_params)

    def tick(buf, t):
        # inject microbatch t at stage 0 chunk 0 (clamped gather is masked
        # out naturally: those outputs never reach a collected slot)
        inj = microbatches[jnp.minimum(t, M - 1)]
        x0 = jnp.where(s == 0, inj, buf[0])
        x = jnp.concatenate([x0[None], buf[1:]], axis=0) if v > 1 \
            else x0[None]
        y = run_chunks(stacked_params, x)
        # rotate each chunk's output one device forward
        sent = jax.lax.ppermute(y, axis_name, _ring_perm(S))
        if v > 1:
            # on the wrap (stage S-1 → 0) the activation advances a chunk
            shifted = jnp.concatenate([sent[-1:], sent[:-1]], axis=0)
            nxt = jnp.where(s == 0, shifted, sent)
        else:
            nxt = sent
        return nxt, y[v - 1]

    buf0 = jnp.zeros((v,) + microbatches.shape[1:], microbatches.dtype)
    # the scan carry must be varying over the pipe axis AND every axis the
    # microbatches vary over (e.g. the data axis in a dp x pp mesh), or the
    # carry types won't match the tick output under JAX 0.9 vma tracking
    vma = set(jax.typeof(microbatches).vma) | {axis_name}
    buf0 = jax.lax.pcast(buf0, tuple(vma), to="varying")
    _, outs = jax.lax.scan(tick, buf0, jnp.arange(T))
    # microbatch m leaves the last logical stage at tick m + L - 1
    return outs[L - 1:]


def last_stage_mean_loss(loss_fn, outs, targets, axis_name):
    """Mean microbatch loss, masked so only the final pipeline stage
    contributes, psum-replicated across stages (apex: loss lives on the
    last rank only)."""
    S = jax.lax.axis_size(axis_name)
    s = jax.lax.axis_index(axis_name)
    per = jax.vmap(loss_fn)(outs, targets)
    local = jnp.mean(per)
    return jax.lax.psum(jnp.where(s == S - 1, local, 0.0), axis_name)


def pipeline_value_and_grad(stage_fn, loss_fn, params, microbatches,
                            targets, *, axis_name: str = PIPELINE_AXIS,
                            n_virtual: int = 1, remat: bool = False):
    """Forward+backward through the pipeline; the workhorse under the apex
    ``forward_backward_pipelining_*`` schedule functions.

    ``loss_fn(y, target) -> scalar`` runs on the last stage's outputs; the
    mean over microbatches is psum-masked so only the last stage
    contributes (apex: loss exists only on the last rank).  Returns
    ``(mean_loss, grads)`` with grads local to each stage's params.
    """
    def total_loss(params):
        outs = spmd_pipeline(stage_fn, params, microbatches,
                             axis_name=axis_name, n_virtual=n_virtual,
                             remat=remat)
        return last_stage_mean_loss(loss_fn, outs, targets, axis_name)

    return jax.value_and_grad(total_loss)(params)
