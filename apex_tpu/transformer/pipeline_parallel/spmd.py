"""SPMD pipeline engine — the TPU-native core under the apex schedule API
(reference: ``apex/transformer/pipeline_parallel/schedules/fwd_bwd_schedules``).

Apex drives MPMD pipelining imperatively: each rank loops over microbatches
doing NCCL P2P ``recv_forward → forward → send_forward`` with a 1F1B
steady state.  The TPU-native equivalent is a *single SPMD program*: every
pipeline stage runs the same ``lax.scan`` over ticks, activations rotate one
hop per tick via ``lax.ppermute`` over the ``pipe`` mesh axis, and autodiff
of the scan yields the backward pipeline (the transpose of ``ppermute`` is
the reverse rotation, so backward activations flow stage S-1 → 0 exactly
like apex's ``send_backward``).  The warmup/cooldown bubbles appear as
ticks where early/late stages compute on garbage that is masked out —
the same bubble fraction (S-1)/(M+S-1) as 1F1B.  Scheduling
(compute/communication overlap) is XLA's latency-hiding scheduler's job;
memory is bounded by applying ``jax.checkpoint`` to the stage function
(pass ``remat=True``) instead of 1F1B's early-backward trick.

Interleaved (virtual) pipelining stacks ``v`` model chunks per stage
(leading axis of the params pytree); an activation traverses logical stage
``c*S + s`` = chunk ``c`` on device ``s``, hopping device ring each tick and
advancing chunk on the wrap, reproducing apex's
``virtual_pipeline_model_parallel_size`` placement.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import PIPELINE_AXIS
from apex_tpu.utils.collectives import axis_size as _axis_size


def _ring_perm(n):
    return [(i, (i + 1) % n) for i in range(n)]


def spmd_pipeline(stage_fn: Callable, params, microbatches, *,
                  axis_name: str = PIPELINE_AXIS, n_virtual: int = 1,
                  remat: bool = False, remat_policy=None):
    """Run ``M`` microbatches through an ``S``(×``v``)-stage pipeline.

    Must be called inside ``shard_map`` with ``axis_name`` in scope.

    Args:
      stage_fn: ``(params_chunk, x) -> y`` — this device's stage (or one
        chunk of it); activation shapes must be uniform across stages.
        ``x``/``y`` may be arbitrary (matching) pytrees — e.g. an
        ``(activation, aux_scalar)`` pair for MoE models whose aux loss
        rides the pipeline with the activation.
      params: stage-local params; with ``n_virtual > 1`` every leaf has a
        leading ``(n_virtual, ...)`` chunk axis.
      microbatches: pytree of ``(M, ...)`` microbatched activations; only
        stage 0's value is read (other stages may pass the same arrays —
        they arrive replicated from the data loader anyway).
      remat: rematerialize the stage in backward (activation
        checkpointing; replaces apex's 1F1B memory policy).

    Returns:
      pytree of ``(M, ...)`` outputs of the final logical stage
      (meaningful on the last device; other devices hold garbage the
      caller masks — apex likewise only has losses on the last rank).
    """
    tmap = jax.tree_util.tree_map
    S = _axis_size(axis_name)
    s = jax.lax.axis_index(axis_name)
    mb_leaves = jax.tree_util.tree_leaves(microbatches)
    M = mb_leaves[0].shape[0]
    v = int(n_virtual)
    L = S * v
    T = M + L - 1

    if remat:
        # remat_policy: jax.checkpoint policy (e.g. dots saveable for
        # Megatron-style SELECTIVE activation recompute); None = full
        stage_fn = jax.checkpoint(stage_fn, policy=remat_policy)

    def run_chunks(params, x):
        # x leaves: (v, mb...) — chunk c's incoming activation
        if v == 1:
            y = stage_fn(tmap(lambda p: p[0], params),
                         tmap(lambda a: a[0], x))
            return tmap(lambda a: a[None], y)
        return jax.vmap(stage_fn)(params, x)

    stacked_params = params
    if v == 1:
        stacked_params = tmap(lambda p: p[None], params)

    # Every activation leaf and the scan carry must be varying over the
    # pipe axis AND every axis any microbatch leaf varies over (e.g. the
    # data axis in a dp x pp mesh): JAX 0.9 requires carry vma to match
    # tick output vma.  Same for param leaves — the backward scan's
    # param-cotangent carries vary over those axes, and pcast's transpose
    # is a psum over the added axes, which is exactly the cross-device
    # grad accumulation those params need.
    act_vma = None
    if hasattr(jax, "typeof"):  # pre-vma JAX: everything implicitly varying
        act_vma = set().union(*(jax.typeof(l).vma for l in mb_leaves)) \
            | {axis_name}

    def _vary(p):
        if act_vma is None:
            return p
        missing = tuple(act_vma - set(jax.typeof(p).vma))
        return jax.lax.pcast(p, missing, to="varying") if missing else p

    stacked_params = tmap(_vary, stacked_params)
    microbatches = tmap(_vary, microbatches)

    def tick(buf, t):
        # inject microbatch t at stage 0 chunk 0 (clamped gather is masked
        # out naturally: those outputs never reach a collected slot)
        ti = jnp.minimum(t, M - 1)

        def inject(m, b):
            x0 = jnp.where(s == 0, m[ti], b[0])
            return jnp.concatenate([x0[None], b[1:]], axis=0) if v > 1 \
                else x0[None]

        x = tmap(inject, microbatches, buf)
        y = run_chunks(stacked_params, x)
        # rotate each chunk's output one device forward
        sent = tmap(lambda a: jax.lax.ppermute(a, axis_name,
                                               _ring_perm(S)), y)
        if v > 1:
            # on the wrap (stage S-1 → 0) the activation advances a chunk
            def wrap(a):
                shifted = jnp.concatenate([a[-1:], a[:-1]], axis=0)
                return jnp.where(s == 0, shifted, a)
            nxt = tmap(wrap, sent)
        else:
            nxt = sent
        return nxt, tmap(lambda a: a[v - 1], y)

    buf0 = tmap(lambda m: _vary(jnp.zeros((v,) + m.shape[1:], m.dtype)),
                microbatches)
    _, outs = jax.lax.scan(tick, buf0, jnp.arange(T))
    # microbatch m leaves the last logical stage at tick m + L - 1
    return tmap(lambda o: o[L - 1:], outs)


def last_stage_mean_loss(loss_fn, outs, targets, axis_name):
    """Mean microbatch loss, masked so only the final pipeline stage
    contributes, psum-replicated across stages (apex: loss lives on the
    last rank only)."""
    S = _axis_size(axis_name)
    s = jax.lax.axis_index(axis_name)
    per = jax.vmap(loss_fn)(outs, targets)
    local = jnp.mean(per)
    return jax.lax.psum(jnp.where(s == S - 1, local, 0.0), axis_name)


def pipeline_value_and_grad(stage_fn, loss_fn, params, microbatches,
                            targets, *, axis_name: str = PIPELINE_AXIS,
                            n_virtual: int = 1, remat: bool = False,
                            remat_policy=None):
    """Forward+backward through the pipeline; the workhorse under the apex
    ``forward_backward_pipelining_*`` schedule functions.

    ``loss_fn(y, target) -> scalar`` runs on the last stage's outputs; the
    mean over microbatches is psum-masked so only the last stage
    contributes (apex: loss exists only on the last rank).  Returns
    ``(mean_loss, grads)`` with grads local to each stage's params.
    """
    def total_loss(params):
        outs = spmd_pipeline(stage_fn, params, microbatches,
                             axis_name=axis_name, n_virtual=n_virtual,
                             remat=remat, remat_policy=remat_policy)
        return last_stage_mean_loss(loss_fn, outs, targets, axis_name)

    return jax.value_and_grad(total_loss)(params)
