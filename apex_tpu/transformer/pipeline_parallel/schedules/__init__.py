"""Pipeline schedules — TPU rebuild of
``apex/transformer/pipeline_parallel/schedules/``.

``get_forward_backward_func`` dispatches exactly like apex: no pipelining
when the pipe axis is 1, interleaved when a virtual size is set, 1F1B
otherwise.  All schedule functions share the functional signature::

    fwd_bwd_func(stage_fn, loss_fn, params, microbatches, targets,
                 forward_only=False, **kw) -> (mean_loss, grads | None)

with ``stage_fn(params, x) -> y`` and ``loss_fn(y, target) -> scalar``.
The pipelined schedules run inside ``shard_map`` over the ``pipe`` axis on
the scan+ppermute engine (``ring.py``); ``forward_backward_no_pipelining``
runs anywhere and uses the *same* accumulation order (ascending microbatch,
loss cotangent seeded at 1/M) so it is the bitwise f32 reference for both
pipelined schedules.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import (
    PIPELINE_AXIS,
    get_pipeline_model_parallel_world_size,
    get_virtual_pipeline_model_parallel_world_size,
)
from apex_tpu.transformer.pipeline_parallel.ring import (
    pipeline_forward,
    pipeline_schedule_step,
    pipeline_value_and_grad,
)

__all__ = [
    "get_forward_backward_func",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_without_interleaving",
    "forward_backward_pipelining_with_interleaving",
    "pipeline_forward",
    "pipeline_value_and_grad",
]


def _n_microbatches(microbatches):
    return jax.tree_util.tree_leaves(microbatches)[0].shape[0]


def forward_backward_no_pipelining(stage_fn: Callable, loss_fn: Callable,
                                   params, microbatches, targets,
                                   forward_only: bool = False, **kw):
    """Sequential microbatches, grads accumulated; grad sync naturally
    happens once at the end (apex: no_sync() except last microbatch).

    Accumulation mirrors the ring engine exactly — per-microbatch ``vjp``
    seeded at 1/M, summed ascending — so pipelined runs of the same model
    match this reference bitwise in f32."""
    del kw
    m = _n_microbatches(microbatches)
    inv_m = jnp.float32(1.0 / m)

    if forward_only:
        def fbody(acc, mb):
            x, t = mb
            return acc + loss_fn(stage_fn(params, x), t), None
        total, _ = jax.lax.scan(fbody, jnp.float32(0.0),
                                (microbatches, targets))
        return total * inv_m, None

    def body(carry, mb):
        x, t = mb
        acc, gacc = carry
        lm, pull = jax.vjp(lambda p: loss_fn(stage_fn(p, x), t), params)
        (g,) = pull(inv_m)
        gacc = jax.tree_util.tree_map(jnp.add, gacc, g)
        return (acc + lm, gacc), None

    g0 = jax.tree_util.tree_map(jnp.zeros_like, params)
    (total, grads), _ = jax.lax.scan(body, (jnp.float32(0.0), g0),
                                     (microbatches, targets))
    return total * inv_m, grads


def _adapt(stage_fn: Callable, remat: bool):
    """Lift a plain ``stage_fn(params, x)`` to the engine's
    ``(params, x, info)`` signature, optionally under activation remat."""
    inner = jax.checkpoint(stage_fn) if remat else stage_fn
    return lambda p, x, info: inner(p, x)


def _forward_only_loss(stage_fn, loss_fn, params, microbatches, targets,
                       axis_name, n_virtual, remat):
    outs = pipeline_forward(_adapt(stage_fn, remat), params, microbatches,
                            axis_name=axis_name, n_virtual=n_virtual)
    m = _n_microbatches(microbatches)

    def body(acc, mb):
        y, t = mb
        return acc + loss_fn(y, t), None

    total, _ = jax.lax.scan(body, jnp.float32(0.0), (outs, targets))
    return total * jnp.float32(1.0 / m)


def forward_backward_pipelining_without_interleaving(
        stage_fn: Callable, loss_fn: Callable, params, microbatches,
        targets, forward_only: bool = False,
        axis_name: str = PIPELINE_AXIS, remat: bool = False, **kw):
    """1F1B schedule (apex
    ``forward_backward_pipelining_without_interleaving``): one model chunk
    per pipe device, ``M + 2S − 2`` scan ticks."""
    del kw
    if forward_only:
        return _forward_only_loss(stage_fn, loss_fn, params, microbatches,
                                  targets, axis_name, 1, remat), None
    loss, grads, _, _ = pipeline_schedule_step(
        _adapt(stage_fn, remat),
        lambda lp, y, tgt, info: loss_fn(y, tgt),
        params, (), microbatches, targets,
        axis_name=axis_name, n_virtual=1)
    return loss, grads


def forward_backward_pipelining_with_interleaving(
        stage_fn: Callable, loss_fn: Callable, params, microbatches,
        targets, forward_only: bool = False,
        axis_name: str = PIPELINE_AXIS, n_virtual: int = 2,
        remat: bool = False, **kw):
    """Interleaved/virtual pipeline (apex
    ``_forward_backward_pipelining_with_interleaving``): params carry a
    leading ``(n_virtual,)`` chunk axis per leaf; chunk ``c`` on device
    ``s`` is logical stage ``c·S + s``.  Needs ``M % S == 0``."""
    del kw
    if forward_only:
        return _forward_only_loss(stage_fn, loss_fn, params, microbatches,
                                  targets, axis_name, n_virtual,
                                  remat), None
    loss, grads, _, _ = pipeline_schedule_step(
        _adapt(stage_fn, remat),
        lambda lp, y, tgt, info: loss_fn(y, tgt),
        params, (), microbatches, targets,
        axis_name=axis_name, n_virtual=n_virtual)
    return loss, grads


def get_forward_backward_func(
        virtual_pipeline_model_parallel_size: Optional[int] = None,
        pipeline_model_parallel_size: Optional[int] = None):
    """apex ``get_forward_backward_func`` dispatch."""
    if pipeline_model_parallel_size is None:
        pipeline_model_parallel_size = \
            get_pipeline_model_parallel_world_size()
    if virtual_pipeline_model_parallel_size is None:
        virtual_pipeline_model_parallel_size = \
            get_virtual_pipeline_model_parallel_world_size()
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None and \
                virtual_pipeline_model_parallel_size > 1:
            return functools.partial(
                forward_backward_pipelining_with_interleaving,
                n_virtual=virtual_pipeline_model_parallel_size)
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining
