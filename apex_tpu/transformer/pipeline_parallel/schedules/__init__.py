"""Pipeline schedules — TPU rebuild of
``apex/transformer/pipeline_parallel/schedules/``.

``get_forward_backward_func`` dispatches exactly like apex: no pipelining
when the pipe axis is 1, interleaved when a virtual size is set, 1F1B
otherwise.  All schedule functions share the functional signature::

    fwd_bwd_func(stage_fn, loss_fn, params, microbatches, targets,
                 forward_only=False, **kw) -> (mean_loss, grads | None)

run inside ``shard_map`` over the ``pipe`` (and optionally other) axes.
The scan+ppermute engine (``spmd.py``) provides the actual pipelining; the
1F1B and interleaved entry points differ in chunk placement (``n_virtual``),
matching apex's schedule split, while the fine-grained backward interleaving
apex hand-codes is delegated to XLA's scheduler.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import (
    PIPELINE_AXIS,
    get_pipeline_model_parallel_world_size,
    get_virtual_pipeline_model_parallel_world_size,
)
from apex_tpu.transformer.pipeline_parallel.spmd import (
    spmd_pipeline,
    pipeline_value_and_grad,
    last_stage_mean_loss,
)

__all__ = [
    "get_forward_backward_func",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_without_interleaving",
    "forward_backward_pipelining_with_interleaving",
    "spmd_pipeline",
    "pipeline_value_and_grad",
]


def forward_backward_no_pipelining(stage_fn: Callable, loss_fn: Callable,
                                   params, microbatches, targets,
                                   forward_only: bool = False, **kw):
    """Sequential microbatches, grads accumulated; grad sync naturally
    happens once at the end (apex: no_sync() except last microbatch)."""
    del kw

    def loss_of(params):
        def body(acc, mb):
            x, t = mb
            l = loss_fn(stage_fn(params, x), t)
            return acc + l, l
        total, per = jax.lax.scan(body, jnp.zeros(()),
                                  (microbatches, targets))
        return total / microbatches.shape[0]

    if forward_only:
        return loss_of(params), None
    return jax.value_and_grad(loss_of)(params)


def forward_backward_pipelining_without_interleaving(
        stage_fn: Callable, loss_fn: Callable, params, microbatches,
        targets, forward_only: bool = False,
        axis_name: str = PIPELINE_AXIS, remat: bool = False, **kw):
    """1F1B-equivalent SPMD pipeline (apex
    ``forward_backward_pipelining_without_interleaving``)."""
    del kw
    if forward_only:
        outs = spmd_pipeline(stage_fn, params, microbatches,
                             axis_name=axis_name, remat=remat)
        return last_stage_mean_loss(loss_fn, outs, targets, axis_name), None
    return pipeline_value_and_grad(stage_fn, loss_fn, params, microbatches,
                                   targets, axis_name=axis_name,
                                   n_virtual=1, remat=remat)


def forward_backward_pipelining_with_interleaving(
        stage_fn: Callable, loss_fn: Callable, params, microbatches,
        targets, forward_only: bool = False,
        axis_name: str = PIPELINE_AXIS, n_virtual: int = 2,
        remat: bool = False, **kw):
    """Interleaved/virtual pipeline (apex
    ``_forward_backward_pipelining_with_interleaving``): params carry a
    leading ``(n_virtual,)`` chunk axis per leaf."""
    del kw
    if forward_only:
        outs = spmd_pipeline(stage_fn, params, microbatches,
                             axis_name=axis_name, n_virtual=n_virtual,
                             remat=remat)
        return last_stage_mean_loss(loss_fn, outs, targets, axis_name), None
    return pipeline_value_and_grad(stage_fn, loss_fn, params, microbatches,
                                   targets, axis_name=axis_name,
                                   n_virtual=n_virtual, remat=remat)


def get_forward_backward_func(
        virtual_pipeline_model_parallel_size: Optional[int] = None,
        pipeline_model_parallel_size: Optional[int] = None):
    """apex ``get_forward_backward_func`` dispatch."""
    if pipeline_model_parallel_size is None:
        pipeline_model_parallel_size = \
            get_pipeline_model_parallel_world_size()
    if virtual_pipeline_model_parallel_size is None:
        virtual_pipeline_model_parallel_size = \
            get_virtual_pipeline_model_parallel_world_size()
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None and \
                virtual_pipeline_model_parallel_size > 1:
            import functools
            return functools.partial(
                forward_backward_pipelining_with_interleaving,
                n_virtual=virtual_pipeline_model_parallel_size)
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining
