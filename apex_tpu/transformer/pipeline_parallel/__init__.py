from apex_tpu.transformer.pipeline_parallel.ring import (
    JobInfo,
    bubble_fraction,
    pipeline_forward,
    pipeline_schedule_step,
    pipeline_value_and_grad,
    schedule_ticks,
)
from apex_tpu.transformer.pipeline_parallel.schedules import (
    get_forward_backward_func,
    forward_backward_no_pipelining,
    forward_backward_pipelining_without_interleaving,
    forward_backward_pipelining_with_interleaving,
)
from apex_tpu.transformer.pipeline_parallel import p2p_communication

__all__ = [
    "JobInfo",
    "bubble_fraction",
    "get_forward_backward_func",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_without_interleaving",
    "forward_backward_pipelining_with_interleaving",
    "pipeline_forward",
    "pipeline_schedule_step",
    "pipeline_value_and_grad",
    "schedule_ticks",
    "p2p_communication",
]
