from apex_tpu.transformer.pipeline_parallel.schedules import (
    get_forward_backward_func,
    forward_backward_no_pipelining,
    forward_backward_pipelining_without_interleaving,
    forward_backward_pipelining_with_interleaving,
)
from apex_tpu.transformer.pipeline_parallel.spmd import (
    spmd_pipeline,
    pipeline_value_and_grad,
)
from apex_tpu.transformer.pipeline_parallel import p2p_communication

__all__ = [
    "get_forward_backward_func",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_without_interleaving",
    "forward_backward_pipelining_with_interleaving",
    "spmd_pipeline",
    "pipeline_value_and_grad",
    "p2p_communication",
]
