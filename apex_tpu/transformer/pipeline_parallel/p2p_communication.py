"""Stage-to-stage transfers — TPU rebuild of
``apex/transformer/pipeline_parallel/p2p_communication.py``.

Apex moves activations between pipeline ranks with NCCL
``batch_isend_irecv`` (plus a shape handshake for variable shapes).  On TPU
a stage hop is ``lax.ppermute`` over the ``pipe`` mesh axis — compiled to a
collective-permute riding ICI neighbors — and shapes are static under jit so
there is no handshake.  These helpers are the explicit building blocks; the
scan-based engine in ``spmd.py`` is what the schedules actually use.

All functions must run inside ``shard_map`` with the pipe axis in scope.
The boundary stages receive zeros (a ring permute wraps; the extra wrap
value is masked here to match apex's "first stage receives nothing").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import PIPELINE_AXIS
from apex_tpu.transformer.pipeline_parallel.spmd import _ring_perm
from apex_tpu.utils.collectives import ensure_varying
from apex_tpu.utils.collectives import axis_size as _axis_size


def _shift(x, axis_name, forward: bool, wrap: bool):
    n = _axis_size(axis_name)
    perm = _ring_perm(n) if forward else [(d, s) for s, d in _ring_perm(n)]
    x = ensure_varying(x, axis_name)
    out = jax.lax.ppermute(x, axis_name, perm)
    if not wrap:
        s = jax.lax.axis_index(axis_name)
        edge = (s == 0) if forward else (s == n - 1)
        out = jnp.where(edge, jnp.zeros_like(out), out)
    return out


def send_forward_recv_forward(output_tensor, *,
                              axis_name: str = PIPELINE_AXIS,
                              wrap: bool = False):
    """Send to the next stage, receive from the previous (one hop).  In an
    SPMD program send and recv are the same permute; this single primitive
    backs apex's ``send_forward``/``recv_forward`` pair."""
    return _shift(output_tensor, axis_name, forward=True, wrap=wrap)


def send_backward_recv_backward(input_tensor_grad, *,
                                axis_name: str = PIPELINE_AXIS,
                                wrap: bool = False):
    """Gradient hop toward earlier stages (apex ``send_backward`` /
    ``recv_backward``)."""
    return _shift(input_tensor_grad, axis_name, forward=False, wrap=wrap)


# apex's four half-ops map onto the two fused permutes above; aliases keep
# recipe code readable.
def send_forward(output_tensor, **kw):
    return send_forward_recv_forward(output_tensor, **kw)


def recv_forward(tensor_like, **kw):
    return send_forward_recv_forward(tensor_like, **kw)


def send_backward(input_tensor_grad, **kw):
    return send_backward_recv_backward(input_tensor_grad, **kw)


def recv_backward(tensor_like, **kw):
    return send_backward_recv_backward(tensor_like, **kw)


def send_forward_recv_backward(output_tensor, grad_like, *,
                               axis_name: str = PIPELINE_AXIS):
    """1F1B steady-state fused exchange: activations go forward while
    gradients come backward (two counter-rotating permutes XLA can
    overlap)."""
    return (send_forward_recv_forward(output_tensor, axis_name=axis_name),
            send_backward_recv_backward(grad_like, axis_name=axis_name))


def send_backward_recv_forward(input_tensor_grad, act_like, *,
                               axis_name: str = PIPELINE_AXIS):
    return (send_backward_recv_backward(input_tensor_grad,
                                        axis_name=axis_name),
            send_forward_recv_forward(act_like, axis_name=axis_name))
