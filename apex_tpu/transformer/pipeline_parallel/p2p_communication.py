"""Stage-to-stage transfers — TPU rebuild of
``apex/transformer/pipeline_parallel/p2p_communication.py``.

Apex moves activations between pipeline ranks with NCCL
``batch_isend_irecv`` (plus a shape handshake for variable shapes).  On TPU
a stage hop is ``lax.ppermute`` over the ``pipe`` mesh axis — compiled to a
collective-permute riding ICI neighbors — and shapes are static under jit so
there is no handshake.  These helpers are the explicit building blocks; the
scan-based engine in ``ring.py`` is what the schedules actually use.

Every hop is a ``custom_vjp`` primitive: the transpose of a forward
activation hop is the same masked permute run in the opposite direction, so
cotangents ride a counter-rotating ring instead of whatever jax's ppermute
transpose rule produces (which is version-dependent and, on the jax
0.4.x-era psum-transpose path, wrong inside ``shard_map``).  The engine
never differentiates *through* these hops — it moves cotangents as plain
data — but user code composing the half-ops under ``jax.grad`` gets correct
rings for free.

All functions must run inside ``shard_map`` with the pipe axis in scope.
With ``wrap=False`` (default) the boundary stages receive zeros (a ring
permute wraps; the extra wrap value is masked to match apex's "first stage
receives nothing"); ``wrap=True`` keeps the wrap value, which the
interleaved schedule uses to hand a microbatch to the next virtual chunk.
All helpers are pytree-aware.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import PIPELINE_AXIS
from apex_tpu.utils.collectives import ensure_varying
from apex_tpu.utils.collectives import axis_size as _axis_size


def _ring_perm(n):
    """Forward ring: stage ``i`` sends to ``i + 1`` (mod n)."""
    return [(i, (i + 1) % n) for i in range(n)]


def _shift_impl(x, axis_name, forward: bool, wrap: bool):
    n = _axis_size(axis_name)
    perm = _ring_perm(n) if forward else [(d, s) for s, d in _ring_perm(n)]
    x = ensure_varying(x, axis_name)
    out = jax.tree_util.tree_map(
        lambda v: jax.lax.ppermute(v, axis_name, perm), x)
    if not wrap:
        s = jax.lax.axis_index(axis_name)
        edge = (s == 0) if forward else (s == n - 1)
        out = jax.tree_util.tree_map(
            lambda v: jnp.where(edge, jnp.zeros_like(v), v), out)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _shift(x, axis_name, forward: bool, wrap: bool):
    return _shift_impl(x, axis_name, forward, wrap)


def _shift_fwd(x, axis_name, forward, wrap):
    return _shift_impl(x, axis_name, forward, wrap), None


def _shift_bwd(axis_name, forward, wrap, _res, ct):
    # Transpose of (edge-mask ∘ permute) is (permute⁻¹ ∘ edge-mask), which
    # equals the opposite-direction masked shift: the value masked at the
    # receive edge going one way is the value masked at the send edge coming
    # back.  With wrap=True the permute is a bijection and the transpose is
    # exactly the inverse permute.
    return (_shift_impl(ct, axis_name, not forward, wrap),)


_shift.defvjp(_shift_fwd, _shift_bwd)


def send_forward_recv_forward(output_tensor, *,
                              axis_name: str = PIPELINE_AXIS,
                              wrap: bool = False):
    """Send to the next stage, receive from the previous (one hop).  In an
    SPMD program send and recv are the same permute; this single primitive
    backs apex's ``send_forward``/``recv_forward`` pair."""
    return _shift(output_tensor, axis_name, True, wrap)


def send_backward_recv_backward(input_tensor_grad, *,
                                axis_name: str = PIPELINE_AXIS,
                                wrap: bool = False):
    """Gradient hop toward earlier stages (apex ``send_backward`` /
    ``recv_backward``)."""
    return _shift(input_tensor_grad, axis_name, False, wrap)


# apex's four half-ops map onto the two fused permutes above; aliases keep
# recipe code readable.
def send_forward(output_tensor, **kw):
    return send_forward_recv_forward(output_tensor, **kw)


def recv_forward(tensor_like, **kw):
    return send_forward_recv_forward(tensor_like, **kw)


def send_backward(input_tensor_grad, **kw):
    return send_backward_recv_backward(input_tensor_grad, **kw)


def recv_backward(tensor_like, **kw):
    return send_backward_recv_backward(tensor_like, **kw)


def send_forward_recv_backward(output_tensor, grad_like, *,
                               axis_name: str = PIPELINE_AXIS):
    """1F1B steady-state fused exchange: activations go forward while
    gradients come backward (two counter-rotating permutes XLA can
    overlap)."""
    return (send_forward_recv_forward(output_tensor, axis_name=axis_name),
            send_backward_recv_backward(grad_like, axis_name=axis_name))


def send_backward_recv_forward(input_tensor_grad, act_like, *,
                               axis_name: str = PIPELINE_AXIS):
    return (send_backward_recv_backward(input_tensor_grad,
                                        axis_name=axis_name),
            send_forward_recv_forward(act_like, axis_name=axis_name))
