"""apex.RNN equivalent — DEPRECATED tier kept for surface parity
(reference: ``apex/RNN/{models.py,RNNBackend.py,cells.py}``, fused
pointwise RNN/LSTM/GRU cells; upstream marks the whole package
deprecated and unmaintained).

Functional TPU form: each factory returns a model object with
``init_params(key)`` and ``apply(params, x, h0=None)`` where ``x`` is
``(seq, batch, input)`` (the reference's default time-major layout).
The recurrence is a ``lax.scan`` — the pointwise cell math fuses into
one kernel per step under XLA, which is exactly what the reference's
fused cells hand-wrote.
"""

from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp

__all__ = ["LSTM", "GRU", "RNNTanh", "RNNReLU"]


def _deprecated():
    # called from the public factory functions: _deprecated(1) ->
    # factory(2) -> USER(3); constructors don't warn, so the level is
    # the same for every entry point
    warnings.warn(
        "apex_tpu.RNN is deprecated surface parity with apex.RNN; use "
        "flax/optax recurrent layers for new code", DeprecationWarning,
        stacklevel=3)


def _linear_init(key, n_in, n_out):
    k1, k2 = jax.random.split(key)
    bound = n_in ** -0.5
    return {"weight": jax.random.uniform(k1, (n_in, n_out), jnp.float32,
                                         -bound, bound),
            "bias": jax.random.uniform(k2, (n_out,), jnp.float32,
                                       -bound, bound)}


class _Recurrent:
    """Shared scan driver over a per-step cell."""

    n_gates = 1
    n_state = 1          # 1: h only; 2: (h, c)

    def __init__(self, input_size, hidden_size, num_layers=1, bias=True,
                 dropout=0.0):
        if dropout:
            warnings.warn("dropout ignored (parity-only kwarg)")
        self.input_size = int(input_size)
        self.hidden_size = int(hidden_size)
        self.num_layers = int(num_layers)
        self.bias = bool(bias)

    def init_params(self, key):
        out = []
        for layer in range(self.num_layers):
            k_i, k_h, key = jax.random.split(key, 3)
            n_in = self.input_size if layer == 0 else self.hidden_size
            lp = {
                "i2h": _linear_init(k_i, n_in,
                                    self.n_gates * self.hidden_size),
                "h2h": _linear_init(k_h, self.hidden_size,
                                    self.n_gates * self.hidden_size),
            }
            if not self.bias:
                for lin in lp.values():
                    del lin["bias"]
            out.append(lp)
        return out

    @staticmethod
    def _affine(lin, x):
        y = x @ lin["weight"]
        return y + lin["bias"] if "bias" in lin else y

    def _cell(self, p, x_t, state):
        raise NotImplementedError

    def _zero_state(self, batch):
        z = jnp.zeros((batch, self.hidden_size), jnp.float32)
        return (z,) * self.n_state

    def apply(self, params, x, h0=None):
        """Returns ``(outputs (seq, batch, hidden), final_states)``.

        ``h0``: optional initial states — a list with one state tuple per
        layer, exactly the ``final_states`` a previous ``apply`` returned
        (so resuming is ``m.apply(p, x2, h0=states)``).
        """
        batch = x.shape[1]
        if h0 is not None and len(h0) != self.num_layers:
            raise ValueError(
                f"h0 must be a list of {self.num_layers} per-layer state "
                "tuples (as returned in final_states)")
        states = []
        for layer, p in enumerate(params):
            init = (self._zero_state(batch) if h0 is None
                    else tuple(h0[layer]))

            def step(state, x_t, p=p):
                new = self._cell(p, x_t, state)
                return new, new[0]

            final, x = jax.lax.scan(step, init, x)
            states.append(final)
        return x, states

    __call__ = apply


class _LSTM(_Recurrent):
    n_gates, n_state = 4, 2

    def _cell(self, p, x_t, state):
        h, c = state
        g = self._affine(p["i2h"], x_t) + self._affine(p["h2h"], h)
        i, f, gc, o = jnp.split(g, 4, axis=-1)
        c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(gc)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return h, c


class _GRU(_Recurrent):
    n_gates = 3

    def _cell(self, p, x_t, state):
        (h,) = state
        gi = self._affine(p["i2h"], x_t)
        gh = self._affine(p["h2h"], h)
        i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
        h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(i_r + h_r)
        z = jax.nn.sigmoid(i_z + h_z)
        n = jnp.tanh(i_n + r * h_n)
        return ((1 - z) * n + z * h,)


class _RNN(_Recurrent):
    def __init__(self, *args, nonlinearity=jnp.tanh, **kw):
        super().__init__(*args, **kw)
        self.nonlinearity = nonlinearity

    def _cell(self, p, x_t, state):
        (h,) = state
        return (self.nonlinearity(
            self._affine(p["i2h"], x_t) + self._affine(p["h2h"], h)),)


def LSTM(input_size, hidden_size, num_layers=1, **kw):
    """Reference ``apex.RNN.models.LSTM`` factory."""
    _deprecated()
    return _LSTM(input_size, hidden_size, num_layers, **kw)


def GRU(input_size, hidden_size, num_layers=1, **kw):
    _deprecated()
    return _GRU(input_size, hidden_size, num_layers, **kw)


def RNNTanh(input_size, hidden_size, num_layers=1, **kw):
    _deprecated()
    return _RNN(input_size, hidden_size, num_layers, nonlinearity=jnp.tanh,
                **kw)


def RNNReLU(input_size, hidden_size, num_layers=1, **kw):
    _deprecated()
    return _RNN(input_size, hidden_size, num_layers,
                nonlinearity=jax.nn.relu, **kw)
