"""Token-budget tick scheduler: chunked prefill mixed into decode.

The classic continuous-batching engine prefills a whole prompt at
admission, so one 4k-token prompt stalls every decoding request for a
full prefill — head-of-line blocking in the worst place, the TTFT/TPOT
tail.  The fix (Sarathi/vLLM-style chunked prefill) is to give every
engine tick a *token budget* and fill it with a mix: each decoding slot
costs its decode tokens (1, or ``1 + spec_tokens`` under speculative
decoding), and whatever budget remains is granted to pending prefills as
prompt *chunks* processed against the paged cache.  Decode latency is
then bounded per tick regardless of prompt length, and prefills make
steady progress instead of monopolizing the device.

This module is pure policy — host-side arithmetic with no device or
engine state — so it is unit-testable in isolation and swappable.  The
engine asks :meth:`TickScheduler.plan` once per tick and executes the
answer.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence, Tuple


@dataclasses.dataclass
class TickPlan:
    """What one engine tick should run: ``chunks`` maps a prefilling
    slot to the number of prompt tokens to process this tick;
    ``decode`` says whether the batched decode step runs at all."""
    chunks: Dict[int, int]
    decode: bool


class TickScheduler:
    """Budgeted prefill/decode mixing policy.

    ``token_budget``: target tokens processed per tick across decode and
    prefill chunks.  ``min_chunk``: the progress guarantee — when
    prefills are pending, at least this many prefill tokens are granted
    per tick even if decode alone exceeds the budget (without it a full
    decode batch starves admission forever, the inverse head-of-line
    problem).  ``max_chunk`` caps any single grant so one prompt cannot
    soak the whole budget every tick when several are prefilling.
    """

    def __init__(self, token_budget: int = 64, min_chunk: int = 8,
                 max_chunk: int = 64):
        if token_budget < 1 or min_chunk < 1 or max_chunk < min_chunk:
            raise ValueError(
                "need token_budget >= 1 and max_chunk >= min_chunk >= 1")
        self.token_budget = token_budget
        self.min_chunk = min_chunk
        self.max_chunk = max_chunk

    def plan(self, decoding_slots: int,
             prefilling: Sequence[Tuple[int, int]],
             spec_tokens: int = 0) -> TickPlan:
        """``decoding_slots``: live decode rows this tick;
        ``prefilling``: ``(slot, remaining_prompt_tokens)`` in admission
        order (FCFS — earlier admissions finish their prefill first);
        ``spec_tokens``: extra per-slot tokens a speculative round
        verifies.  Returns the tick's :class:`TickPlan`."""
        decode_cost = decoding_slots * (1 + spec_tokens)
        left = self.token_budget - decode_cost
        chunks: Dict[int, int] = {}
        for i, (slot, remaining) in enumerate(prefilling):
            if remaining <= 0:
                continue
            grant = min(remaining, self.max_chunk, max(left, 0))
            if grant < min(remaining, self.min_chunk) and i == 0:
                # progress guarantee: the head prefill always advances
                grant = min(remaining, self.min_chunk)
            if grant <= 0:
                break
            chunks[slot] = grant
            left -= grant
        return TickPlan(chunks=chunks, decode=decoding_slots > 0)
