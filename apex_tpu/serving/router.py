"""SLO-aware multi-replica router: admission, placement, shedding.

One :class:`Router` fronts N independent engine replicas (contiguous or
paged — anything with the :class:`~apex_tpu.inference.InferenceEngine`
surface).  Placement and admission read two signals per replica:

* **queue pressure** — ``queue_depth + active_requests``, the classic
  least-loaded signal; a replica at ``max_queue_depth`` queued requests
  is ineligible outright (its own bounded queue would reject anyway —
  the router just refuses earlier and cheaper).
* **SLO burn rate** — ``max`` over the replica's
  :class:`~apex_tpu.observability.slo.SLOTarget`\\ s of the short-window
  error-budget burn (:meth:`SLOMonitor.burn_rate`).  A replica burning
  ≥ ``burn_threshold`` with ANY backlog is ineligible: it is already
  missing its latency objectives, so adding load converts one slow
  replica into globally blown SLOs.  (Burn with an EMPTY queue does not
  shed — an idle replica's stale burn history should not refuse the
  request that would be served instantly.)

When every replica is ineligible the request is SHED —
:class:`RequestShed` raised to the caller, who got an answer in
microseconds instead of a timeout in seconds.  Shedding is the SLO
mechanism, not a failure: dropping the marginal request is what keeps
the admitted ones inside their objectives (the loadgen's ``--overload``
runs demonstrate exactly this trade).

Scheduling stays host-side and cooperative: :meth:`step` advances every
replica one engine tick (round-robin), :meth:`run` drives to drain.
"""

from __future__ import annotations

import enum
from typing import List, Optional, Sequence

from apex_tpu.inference.engine import QueueFull, Request, Response
from apex_tpu.observability.fleetobs import TraceContext, emit_flow
from apex_tpu.observability.spans import Tracer


class ShedReason(enum.Enum):
    """Machine-readable reason a request was refused — the enum a
    client maps to its own backoff/retry policy (and the loadgen's
    per-reason outcome report keys off)."""
    OVERLOAD = "overload"                    # every replica over limits
    NO_HEALTHY_REPLICA = "no_healthy_replica"  # fleet: none HEALTHY
    CONTEXT_CAP = "context_cap"              # degradation L2 prompt cap
    DEGRADED = "degraded"                    # degradation L3: shed all
    RETRY_BUDGET_EXHAUSTED = "retry_budget_exhausted"
    DRAINING = "draining"                    # fleet: capacity shift drain


class RequestShed(RuntimeError):
    """The request was refused at the door; the caller got an answer in
    microseconds instead of a timeout in seconds.

    Carries a machine-readable :class:`ShedReason` and a
    ``retry_after_s`` hint (the serving analogue of HTTP 429's
    ``Retry-After``) so clients back off *by policy* instead of
    guessing; ``tools/loadgen.py --client-retries`` honors it with
    jittered backoff."""

    def __init__(self, msg: str = "request shed", *,
                 reason: ShedReason = ShedReason.OVERLOAD,
                 retry_after_s: float = 0.05):
        super().__init__(msg)
        self.reason = reason
        self.retry_after_s = float(retry_after_s)


class Router:
    """SLO-aware admission over a set of engine replicas."""

    def __init__(self, replicas: Sequence, *,
                 max_queue_depth: int = 8,
                 burn_threshold: float = 14.4,
                 burn_window_s: float = 60.0,
                 registry=None,
                 tracer: Optional[Tracer] = None):
        if not replicas:
            raise ValueError("need at least one replica")
        if max_queue_depth < 1:
            raise ValueError("max_queue_depth must be >= 1")
        self.replicas = list(replicas)
        self.max_queue_depth = max_queue_depth
        self.burn_threshold = burn_threshold
        self.burn_window_s = burn_window_s
        self.shed_requests = 0
        # the router's own trace lane (dispatch/shed flow events); a
        # TraceContext is minted whenever ANY tracer exists in the
        # deployment, so engine-side flows link up even without a
        # router tracer
        self.tracer = tracer
        self._tracing = tracer is not None or any(
            getattr(getattr(e, "trace", None), "tracer", None) is not None
            for e in self.replicas)
        r = registry if registry is not None \
            else self.replicas[0].metrics.registry
        self._c_submitted = r.counter(
            "router_submitted_total", "requests placed, by replica",
            labelnames=("replica",))
        self._c_shed = r.counter(
            "router_shed_total",
            "requests refused with every replica overloaded")
        self._g_depth = r.gauge(
            "router_queue_depth", "replica queue depth at placement",
            labelnames=("replica",))
        self._g_burn = r.gauge(
            "router_burn_rate",
            "replica max short-window SLO burn at placement",
            labelnames=("replica",))

    # -- signals -------------------------------------------------------------

    def _live(self):
        """(index, engine) pairs skipping tombstones — a replica
        removed by the fleet's capacity lifecycle leaves ``None`` in
        its slot so every other index-keyed structure stays valid."""
        return [(i, e) for i, e in enumerate(self.replicas)
                if e is not None]

    def _burn(self, engine) -> float:
        """Max short-window burn across the replica's SLO targets (0.0
        when the replica has no SLO monitor attached)."""
        slo = getattr(engine.metrics, "slo", None)
        if slo is None or not slo.targets:
            return 0.0
        return max(slo.burn_rate(t, self.burn_window_s)
                   for t in slo.targets)

    def _overloaded(self, engine, burn: float) -> bool:
        if engine.queue_depth >= self.max_queue_depth:
            return True
        return burn >= self.burn_threshold and engine.queue_depth >= 1

    def _eligible(self, i: int, engine, burn: float) -> bool:
        """Placement eligibility hook — subclasses narrow it (the fleet
        router additionally requires the replica to be HEALTHY)."""
        return not self._overloaded(engine, burn)

    def _retry_after_hint(self) -> float:
        """Heuristic Retry-After: half a queue-drain's worth per queued
        request on the least-loaded replica — deeper backlog, longer
        hint, so backed-off clients return staggered, not in a thundering
        herd (the loadgen additionally jitters it)."""
        live = self._live()
        if not live:
            return 0.05 * 2.0
        depth = min(e.queue_depth for _, e in live)
        return 0.05 * (1.0 + depth / max(self.max_queue_depth, 1))

    # -- admission -----------------------------------------------------------

    def _dispatch_ctx(self, request: Request) -> Optional[TraceContext]:
        """Mint the request's :class:`TraceContext` (once — retries
        reuse it) and stamp the router's dispatch flow event."""
        if not self._tracing:
            return None
        if request.trace is None:
            request.trace = TraceContext.mint(request.request_id)
        emit_flow(self.tracer, request.trace, "dispatch",
                  request_id=request.request_id)
        return request.trace

    def _router_tracer(self) -> Optional[Tracer]:
        """The tracer router-level flow events land on: the router's
        own, else any replica's (a started chain must still close when
        only the engines are traced)."""
        if self.tracer is not None:
            return self.tracer
        for _, e in self._live():
            t = getattr(getattr(e, "trace", None), "tracer", None)
            if t is not None:
                return t
        return None

    def _flow_shed(self, request: Request, reason: "ShedReason") -> None:
        """Terminate a shed request's flow at the router (it never
        reaches an engine, so nothing else will)."""
        if request.trace is not None and request.trace.started:
            emit_flow(self._router_tracer(), request.trace, "finish",
                      final=True, request_id=request.request_id,
                      reason="shed", shed_reason=reason.value)

    def _try_place(self, request: Request) -> Optional[int]:
        """Place on the best eligible replica; replica index, or None
        with nowhere to go (the :class:`QueueFull` race — an eligible
        replica's own bounded queue filling concurrently — just moves
        on to the next candidate)."""
        scored = []
        for i, eng in self._live():
            burn = self._burn(eng)
            self._g_depth.set(eng.queue_depth, replica=str(i))
            self._g_burn.set(burn, replica=str(i))
            if not self._eligible(i, eng, burn):
                continue
            scored.append((eng.queue_depth + eng.active_requests, burn, i))
        for _, _, i in sorted(scored):
            try:
                self.replicas[i].submit(request)
            except QueueFull:
                continue
            self._c_submitted.inc(replica=str(i))
            return i
        return None

    def submit(self, request: Request) -> int:
        """Place ``request`` on the best eligible replica; returns the
        replica index.  Raises :class:`RequestShed` when no replica is
        eligible."""
        self._dispatch_ctx(request)
        i = self._try_place(request)
        if i is not None:
            return i
        self.shed_requests += 1
        self._c_shed.inc()
        self._flow_shed(request, ShedReason.OVERLOAD)
        raise RequestShed(
            f"all {len(self.replicas)} replicas overloaded "
            f"(max_queue_depth={self.max_queue_depth}, "
            f"burn_threshold={self.burn_threshold})",
            reason=ShedReason.OVERLOAD,
            retry_after_s=self._retry_after_hint())

    # -- scheduling ----------------------------------------------------------

    def step(self) -> bool:
        """Advance every replica one engine tick; True while any has
        (or may have) work."""
        busy = False
        for _, eng in self._live():
            busy = eng.step() or busy
        return busy

    def run(self, max_steps: Optional[int] = None) -> List[Response]:
        """Drive :meth:`step` to drain (or ``max_steps``); returns all
        completed responses across replicas."""
        steps = 0
        while any(e._queue or e._active for _, e in self._live()):
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return self.completed

    @property
    def queue_depth(self) -> int:
        return sum(e.queue_depth for _, e in self._live())

    @property
    def active_requests(self) -> int:
        return sum(e.active_requests for _, e in self._live())

    @property
    def completed(self) -> List[Response]:
        out: List[Response] = []
        for _, eng in self._live():
            out.extend(eng.completed)
        return out
