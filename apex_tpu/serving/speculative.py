"""Speculative decoding: a small draft GPT proposes, the target verifies.

Per round the draft model autoregressively proposes ``num_tokens``
tokens (one cheap decode step each), then the target model scores the
current token plus every proposal in ONE chunked forward
(:meth:`~apex_tpu.models.gpt.GPTModel.decode_chunk`) — γ+1 target
logits for the latency of a single wide step.

Acceptance rule (exact-match verification): the token at stream index
``i`` is ALWAYS ``sample(target_logits_i, fold_in(seed, i))`` — the
identical function of the identical logits and key the non-speculative
engine uses.  A proposal is "accepted" simply when it equals that
canonical token, letting the round keep consuming the already-computed
target logits for later positions; on the first mismatch the canonical
token replaces it and the round ends.  Speculation therefore changes
only HOW MANY target positions get evaluated per device round — never
what the stream emits — so greedy and seeded outputs are token-identical
to the non-speculative engine by construction (the property
``_dryrun_serving`` asserts).  This is the deterministic special case of
the Leviathan et al. rejection sampler: with the per-request
``(seed, token-index)`` stream there is exactly one canonical token per
index, and matching it is the only acceptance that preserves the
stream.  The draft samples its proposals with the same params, seed and
indices, which maximizes the match rate under stochastic sampling.

Rejected proposals leave stale KV in the pool past the accepted point;
those positions sit beyond every valid length (masked) and are
overwritten when decoding reaches them.
"""

from __future__ import annotations

import dataclasses
from typing import Any


@dataclasses.dataclass
class SpeculativeConfig:
    """Draft-model bundle for :class:`~apex_tpu.serving.PagedInferenceEngine`.

    ``model``/``params``: the draft GPT (same vocab as the target;
    typically far fewer layers/heads).  ``num_tokens``: proposals per
    round (γ) — each round costs γ draft steps + one (γ+1)-wide target
    chunk, and emits between 1 and γ+1 stream tokens.
    """
    model: Any
    params: Any
    num_tokens: int = 3

    def __post_init__(self):
        if self.num_tokens < 1:
            raise ValueError("num_tokens must be >= 1")

    def validate_against(self, target_model) -> None:
        if self.model.cfg.vocab_size != target_model.cfg.vocab_size:
            raise ValueError(
                "draft and target models must share a vocabulary "
                f"({self.model.cfg.vocab_size} != "
                f"{target_model.cfg.vocab_size})")
        self.model._check_decode_supported()
