"""Fault-tolerant serving fleet: the serving-side mirror of
:mod:`apex_tpu.resilience`.

Training became preemption-native in PRs 4/9 (guards, checkpoint
resharding, elastic re-plan); this module gives the serving tier the
same property.  A replica that crashes, hangs mid-decode, or silently
slows down must cost the fleet a bounded blip — never a lost request,
never a duplicated one, never a changed token stream.

Four pieces, each the serving analogue of a training-resilience part:

* :class:`ServingFaultInjector` — deterministic, seedable REPLICA-level
  faults (:data:`SERVING_FAULT_KINDS`), the counterpart of the training
  :class:`~apex_tpu.resilience.faults.FaultInjector` (both generate
  schedules from the shared ``seeded_schedule`` stream, both keep an
  applied-fault log as the ground truth tests assert against).  The
  admission-shaped kinds (``reject_admission``, ``kv_pool_exhaustion``)
  are injected at the engine backend hooks
  (``InferenceEngine.injected_faults``); the whole-replica kinds
  (``replica_crash``, ``stuck_decode``, ``slow_replica``) are applied by
  the fleet tick loop, which owns the replica lifecycle.
* **Health-checked routing** — :class:`FleetRouter` drives a per-replica
  state machine ``healthy → suspect → dead → recovering`` from heartbeat
  ticks (a replica heartbeats when its ``step()`` returns; a crash or a
  stuck decode is a miss) plus a relative-latency slow detector.  All
  placement decisions exclude non-healthy replicas.  Failed placements
  retry with jittered exponential backoff under a per-request retry
  budget; an optional hedged dispatch duplicates a request that has not
  produced its first token within ``hedge_after_s`` onto a second
  replica — first completion wins, the loser is cancelled, responses
  are deduplicated so completion stays exactly-once.
* **Cross-replica request migration** — when a replica is declared
  dead, :meth:`InferenceEngine.export_inflight` harvests its in-flight
  and queued requests *with their generated-so-far tokens* (exactly the
  tokens already streamed to the client, which is why a crash without
  warning still leaves them recoverable) and the fleet re-places each on
  a healthy replica via :meth:`InferenceEngine.adopt`: re-prefill
  ``prompt + generated``, resume the ``(seed, token-index)`` sampling
  stream at ``len(generated)``.  This is ``engine.preempt()``'s requeue
  machinery generalized across engines — the resumed stream is
  token-BITWISE the uninterrupted one, for greedy and seeded sampling,
  on contiguous and paged backends (asserted by ``tests/test_fleet.py``
  and ``__graft_entry__._dryrun_serving_chaos``).  A request whose
  context no longer fits the target finishes with
  ``reason="preempted"``, the same edge the single-engine requeue has.
* :class:`DegradationLadder` — graceful degradation wired to
  :class:`~apex_tpu.observability.slo.SLOMonitor` burn: level 1 drops
  speculative decoding (``spec_enabled=False`` — the acceptance rule
  makes this token-invisible), level 2 flushes the prefix trie and
  shrinks the admitted context, level 3 sheds new admissions with a
  machine-readable ``retry_after_s``.  The current level is the
  ``serving_degraded_level`` gauge; transitions land on the Perfetto
  timeline as instants.

The fleet also exposes the **capacity lifecycle** the
:class:`~apex_tpu.resilience.capacity.CapacityController` drives:
:meth:`FleetRouter.begin_drain` puts a replica in the DRAINING state
(no new placements, work migrated off via the same export/adopt
machinery, never marked dead), :meth:`FleetRouter.remove_replica`
detaches a drained replica leaving a ``None`` tombstone in its slot
(indices stay stable), and :meth:`FleetRouter.add_replica` attaches a
fresh engine, reusing tombstone slots.  :meth:`FleetRouter.cancel_drain`
is the shift-rollback path.

Fleet series: ``serving_retries_total`` / ``serving_hedges_total`` /
``serving_migrations_total`` counters, ``serving_replica_health``
(0 healthy, 1 suspect, 2 dead, 3 recovering, 4 draining, 5 removed) and
``serving_degraded_level`` gauges.  ``tools/loadgen.py --scenario``
drives the whole thing under chaos workloads (replica-kill mid-burst,
slow replica, diurnal, bursty overload) asserting SLO attainment and
exactly-once completion.
"""

from __future__ import annotations

import dataclasses
import enum
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from apex_tpu.inference.engine import QueueFull, Request, Response
from apex_tpu.observability.fleetobs import FlightRecorder, emit_flow
from apex_tpu.resilience.faults import seeded_schedule
from apex_tpu.serving.router import RequestShed, Router, ShedReason

SERVING_FAULT_KINDS = ("replica_crash", "stuck_decode", "slow_replica",
                       "kv_pool_exhaustion", "reject_admission",
                       "capacity_change")


class VirtualClock:
    """Injectable discrete-event clock: the chaos scenarios run on
    simulated seconds (``advance``) instead of wall time, so fault
    timing, backoff, hedging and SLO burn are DETERMINISTIC on any
    host — the property the chaos CI leg needs."""

    def __init__(self, start: float = 0.0):
        self.t = float(start)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)


@dataclasses.dataclass(frozen=True)
class ServingFault:
    """One scheduled replica fault, active for ``duration`` fleet ticks
    starting at ``tick``.  ``magnitude`` is the injected extra seconds
    per tick for ``slow_replica`` and the failure mode for
    ``capacity_change`` (0/1 mid-shift crash, 2 stuck drain, 3 failed
    re-shard — see ``apex_tpu.resilience.capacity.fault_mode``; that
    kind is fleet-scoped and consumed by the
    :class:`~apex_tpu.resilience.capacity.CapacityController` via
    :meth:`ServingFaultInjector.capacity_change_at`, not applied by the
    fleet tick loop)."""
    tick: int
    replica: int
    kind: str
    magnitude: float = 0.0
    duration: int = 1

    def __post_init__(self):
        if self.kind not in SERVING_FAULT_KINDS:
            raise ValueError(f"unknown serving fault kind {self.kind!r}; "
                             f"one of {SERVING_FAULT_KINDS}")
        if self.tick < 0 or self.replica < 0:
            raise ValueError("fault tick and replica must be >= 0")
        if self.duration < 1:
            raise ValueError("fault duration must be >= 1 tick")


class ServingFaultInjector:
    """Deterministic replica-fault schedule for the serving fleet.

    Mirrors the training :class:`~apex_tpu.resilience.faults.
    FaultInjector`: an explicit schedule or a seed-generated one
    (:meth:`from_seed`, same ``seeded_schedule`` stream discipline), and
    an applied-fault ``log`` of ``(tick, replica, kind)`` recorded when
    the fleet actually applies each fault — the ground truth the chaos
    tests assert against.
    """

    def __init__(self, schedule: Iterable[ServingFault] = ()):
        self.schedule: Tuple[ServingFault, ...] = tuple(schedule)
        self._by_replica: Dict[int, List[ServingFault]] = {}
        for f in self.schedule:
            self._by_replica.setdefault(f.replica, []).append(f)
        self.log: List[Tuple[int, int, str]] = []
        self._recorded: set = set()

    @classmethod
    def from_seed(cls, seed: int, n_ticks: int, n_replicas: int,
                  rates: Optional[Dict[str, float]] = None, *,
                  slow_s: float = 0.05, crash_ticks: int = 10 ** 6,
                  stuck_ticks: int = 4, slow_ticks: int = 4,
                  pressure_ticks: int = 2) -> "ServingFaultInjector":
        """Random-but-reproducible schedule over ``n_ticks`` ×
        ``n_replicas``: per (tick, replica, kind) a fault fires with
        probability ``rates[kind]`` under one seeded stream.  Crash
        defaults to effectively-permanent; pass a finite
        ``crash_ticks`` to exercise the recovering transition."""
        rates = dict(rates or {})
        bad = set(rates) - set(SERVING_FAULT_KINDS)
        if bad:
            raise ValueError(f"unknown fault kinds in rates: {sorted(bad)}")
        keys = [(rep, kind) for rep in range(n_replicas)
                for kind in SERVING_FAULT_KINDS]
        key_rates = {(rep, kind): rates.get(kind, 0.0)
                     for rep, kind in keys}
        dur = {"replica_crash": crash_ticks, "stuck_decode": stuck_ticks,
               "slow_replica": slow_ticks,
               "kv_pool_exhaustion": pressure_ticks,
               "reject_admission": pressure_ticks,
               "capacity_change": 1}
        faults = [
            ServingFault(tick, rep, kind,
                         magnitude=slow_s if kind == "slow_replica" else 0.0,
                         duration=dur[kind])
            for tick, (rep, kind) in seeded_schedule(seed, n_ticks, keys,
                                                     key_rates)]
        return cls(faults)

    def faults_at(self, tick: int, replica: int) -> Tuple[ServingFault, ...]:
        """Pure query: faults active at this (tick, replica)."""
        return tuple(f for f in self._by_replica.get(replica, ())
                     if f.tick <= tick < f.tick + f.duration)

    def activate(self, tick: int, replica: int) -> Tuple[ServingFault, ...]:
        """Active faults, recording each into the applied log the first
        tick the fleet actually applies it.  ``capacity_change`` is
        never recorded here — the fleet tick loop does not apply it;
        the capacity controller consumes it via
        :meth:`capacity_change_at`."""
        out = self.faults_at(tick, replica)
        for f in out:
            if f.kind == "capacity_change":
                continue
            if f not in self._recorded:
                self._recorded.add(f)
                self.log.append((int(tick), int(replica), f.kind))
        return out

    def capacity_change_at(self, tick: int) -> Optional[ServingFault]:
        """The first unconsumed ``capacity_change`` fault active at
        ``tick``, across ALL replicas — a capacity shift is fleet-
        scoped, so the replica field only disambiguates schedules.
        Consume-once: the fault is recorded into the applied log and
        never returned again, so one scheduled fault fails exactly one
        shift and the controller's post-rollback retry can succeed."""
        for f in self.schedule:
            if f.kind != "capacity_change" or f in self._recorded:
                continue
            if f.tick <= tick < f.tick + f.duration:
                self._recorded.add(f)
                self.log.append((int(tick), int(f.replica), f.kind))
                return f
        return None


class ReplicaHealth(enum.Enum):
    """Per-replica health states; the gauge exports the index below.

    ``DRAINING`` is the capacity-shift state: the replica still serves
    (and heartbeats) while its work migrates off, takes no new
    placements, and is NEVER marked dead — a drain is an orderly exit,
    not a failure, and declaring it dead would double-migrate the work
    the drain already moved.  ``REMOVED`` is terminal: the slot holds a
    ``None`` tombstone so every index-keyed structure stays valid."""
    HEALTHY = "healthy"
    SUSPECT = "suspect"
    DEAD = "dead"
    RECOVERING = "recovering"
    DRAINING = "draining"
    REMOVED = "removed"


HEALTH_INDEX = {ReplicaHealth.HEALTHY: 0, ReplicaHealth.SUSPECT: 1,
                ReplicaHealth.DEAD: 2, ReplicaHealth.RECOVERING: 3,
                ReplicaHealth.DRAINING: 4, ReplicaHealth.REMOVED: 5}


class DegradationLadder:
    """Burn-driven graceful degradation policy (pure, injectable).

    ``thresholds`` are the burn multiples that ENTER levels 1..3:
    level 1 drops speculative decoding, level 2 flushes the prefix trie
    and caps admitted context at ``ctx_cap_frac`` of ``max_seq``,
    level 3 sheds new admissions with ``retry_after_s``.  Escalation is
    immediate; de-escalation steps down ONE level after ``step_down_s``
    of burn below the current level's entry threshold (hysteresis — a
    ladder that flaps is worse than one that is a little sticky).

    ``burn_source`` overrides WHICH burn drives the ladder: by default
    the owning router feeds it the max burn across its own replicas,
    which is right for a homogeneous fleet but wrong for a
    disaggregated one — level 2's actions (prefix flush + context cap)
    relieve *decode* KV pressure, so a prefill-pool TTFT burn must not
    trigger them.  :class:`~apex_tpu.serving.disagg.DisaggregatedFleet`
    threads the decode pool's burn through here so every router sharing
    the ladder degrades on the signal the actions actually act on.
    """

    LEVELS = ("normal", "no_spec", "shrink_context", "shed")

    def __init__(self, thresholds: Sequence[float] = (2.0, 6.0, 14.4), *,
                 step_down_s: float = 1.0, ctx_cap_frac: float = 0.5,
                 burn_source=None):
        if len(thresholds) != 3 or list(thresholds) != sorted(thresholds):
            raise ValueError("need 3 ascending burn thresholds")
        if not 0.0 < ctx_cap_frac <= 1.0:
            raise ValueError("ctx_cap_frac must be in (0, 1]")
        self.thresholds = tuple(float(t) for t in thresholds)
        self.step_down_s = float(step_down_s)
        self.ctx_cap_frac = float(ctx_cap_frac)
        self.burn_source = burn_source
        self.level = 0
        self._calm_since: Optional[float] = None

    def target_level(self, burn: float) -> int:
        lvl = 0
        for i, t in enumerate(self.thresholds):
            if burn >= t:
                lvl = i + 1
        return lvl

    def update(self, burn: float, now: float) -> int:
        tgt = self.target_level(burn)
        if tgt > self.level:
            self.level = tgt
            self._calm_since = None
        elif tgt < self.level:
            if self._calm_since is None:
                self._calm_since = now
            elif now - self._calm_since >= self.step_down_s:
                self.level -= 1
                self._calm_since = now      # re-arm for the next step
        else:
            self._calm_since = None
        return self.level


@dataclasses.dataclass
class _ReplicaState:
    health: ReplicaHealth = ReplicaHealth.HEALTHY
    misses: int = 0                 # consecutive heartbeat misses
    ok_streak: int = 0              # consecutive beats while recovering
    slow_streak: int = 0            # consecutive slow ticks
    slow: bool = False              # SUSPECT because of latency, not misses


@dataclasses.dataclass
class _InFlight:
    request: Request
    replica: int
    submitted_t: float
    hedge_replica: Optional[int] = None


@dataclasses.dataclass
class _PendingRetry:
    request: Request
    progress: List[int]
    attempts: int
    next_t: float


class FleetRouter(Router):
    """Health-checked, self-healing multi-replica router.

    Extends :class:`Router`'s least-loaded + SLO-burn placement with the
    fleet lifecycle: every :meth:`step` is a heartbeat round (faults
    applied, replicas ticked, health transitions taken), followed by
    response collection (deduplicated — exactly-once even under
    hedging), dead-replica migration, the hedge pass, the retry pass and
    the degradation ladder.  ``health_log`` records every transition as
    ``(tick, replica, old, new)``.

    Placement eligibility = base eligibility AND ``health is HEALTHY``.
    Migrated requests bypass the overload gate (work already admitted
    once is completed, not re-litigated) but still honor engine
    backpressure.  ``submit`` returns the replica index, or ``-1`` when
    the request was parked for internal retry (it will complete — or
    terminally shed with ``finish_reason="shed"`` — via :meth:`step`).
    """

    def __init__(self, replicas: Sequence, *,
                 injector: Optional[ServingFaultInjector] = None,
                 clock=time.monotonic,
                 suspect_after: int = 2, dead_after: int = 4,
                 recover_after: int = 3,
                 slow_factor: float = 4.0, slow_after: int = 3,
                 slow_floor_s: float = 1e-3,
                 retry_budget: int = 3, retry_base_s: float = 0.02,
                 retry_jitter: float = 0.5,
                 hedge_after_s: Optional[float] = None,
                 ladder: Optional[DegradationLadder] = None,
                 recorder: Optional[FlightRecorder] = None,
                 seed: int = 0, registry=None, **kw):
        super().__init__(replicas, registry=registry, **kw)
        if suspect_after < 1 or dead_after <= suspect_after:
            raise ValueError("need dead_after > suspect_after >= 1")
        if recover_after < 1 or retry_budget < 0:
            raise ValueError("recover_after >= 1 and retry_budget >= 0")
        self.injector = injector
        self.clock = clock
        self.suspect_after = suspect_after
        self.dead_after = dead_after
        self.recover_after = recover_after
        self.slow_factor = slow_factor
        self.slow_after = slow_after
        self.slow_floor_s = slow_floor_s
        self.retry_budget = retry_budget
        self.retry_base_s = retry_base_s
        self.retry_jitter = retry_jitter
        self.hedge_after_s = hedge_after_s
        self.ladder = ladder
        self.recorder = recorder
        self._rng = np.random.RandomState(seed)
        self._tick = 0
        self._state = [_ReplicaState() for _ in self.replicas]
        self.health_log: List[Tuple[int, int, str, str]] = []
        self._inflight: Dict[object, _InFlight] = {}
        self._retry: List[_PendingRetry] = []
        self._responses: Dict[object, Response] = {}
        self._consumed = [0] * len(self.replicas)
        self.retries = 0
        self.hedges = 0
        self.migrations = 0
        self.duplicate_responses = 0
        # recovery bookkeeping for the chaos bench: first DEAD
        # declaration, first migration, first post-migration token
        self.first_dead: Optional[Tuple[int, float]] = None
        self.first_migration: Optional[Tuple[int, float]] = None
        self.first_resume: Optional[Tuple[int, float]] = None
        self._resume_watch: Dict[object, Tuple[int, int]] = {}
        r = registry if registry is not None \
            else self.replicas[0].metrics.registry
        self._c_retries = r.counter(
            "serving_retries_total",
            "placement retries after a failed or shed attempt")
        self._c_hedges = r.counter(
            "serving_hedges_total", "hedged duplicate dispatches")
        self._c_migrations = r.counter(
            "serving_migrations_total",
            "in-flight requests migrated off a dead replica")
        self._g_health = r.gauge(
            "serving_replica_health",
            "replica health (0 healthy, 1 suspect, 2 dead, 3 recovering, "
            "4 draining, 5 removed)",
            labelnames=("replica",))
        self._g_degraded = r.gauge(
            "serving_degraded_level",
            "graceful-degradation ladder level (0 normal .. 3 shed)")
        self._c_trans = r.counter(
            "serving_replica_transitions_total",
            "health state-machine transitions by edge — flapping "
            "(healthy<->suspect cycles) vs clean deaths",
            labelnames=("from", "to"))
        self._g_degraded.set(0)
        self._set_health_gauges()

    # -- health state machine ------------------------------------------------

    def health(self, i: int) -> ReplicaHealth:
        return self._state[i].health

    def _transition(self, i: int, new: ReplicaHealth) -> None:
        st = self._state[i]
        if st.health is new:
            return
        old = st.health.value
        self.health_log.append((self._tick, i, old, new.value))
        # "from" is a Python keyword — label kwargs go through a splat
        self._c_trans.inc(**{"from": old, "to": new.value})
        reg = getattr(self.replicas[i].metrics, "registry", None)
        if reg is not None:
            # into the REPLICA's stream, so a merged fleet report can
            # attribute health history per replica
            reg.event("replica_health", replica=i, state=new.value,
                      previous=old)
        if self.recorder is not None:
            self.recorder.record(f"replica{i}", "health_transition",
                                 tick=self._tick, old=old,
                                 new=new.value)
        st.health = new
        if new is ReplicaHealth.DEAD:
            if self.first_dead is None:
                self.first_dead = (self._tick, self.clock())
            self._on_dead(i)
            if self.recorder is not None:
                # cut the black box AFTER migration so the snapshot
                # carries the re-placement decisions too
                self.recorder.trigger("replica_dead", replica=i,
                                      tick=self._tick)

    def _miss(self, i: int) -> None:
        st = self._state[i]
        if st.health is ReplicaHealth.DRAINING:
            # never dead while draining: the drain already migrated the
            # work off; a death verdict would migrate it a second time
            st.misses += 1
            return
        st.ok_streak = 0
        st.misses += 1
        if st.health is ReplicaHealth.RECOVERING:
            self._transition(i, ReplicaHealth.DEAD)     # relapse
        elif st.health is ReplicaHealth.HEALTHY \
                and st.misses >= self.suspect_after:
            self._transition(i, ReplicaHealth.SUSPECT)
        elif st.health is ReplicaHealth.SUSPECT \
                and st.misses >= self.dead_after:
            self._transition(i, ReplicaHealth.DEAD)

    def _beat(self, i: int) -> None:
        st = self._state[i]
        if st.health is ReplicaHealth.DRAINING:
            st.misses = 0        # sticky: only the lifecycle exits it
            return
        st.misses = 0
        if st.health is ReplicaHealth.SUSPECT and not st.slow:
            self._transition(i, ReplicaHealth.HEALTHY)
        elif st.health is ReplicaHealth.DEAD:
            st.ok_streak = 0
            self._transition(i, ReplicaHealth.RECOVERING)
        elif st.health is ReplicaHealth.RECOVERING:
            st.ok_streak += 1
            if st.ok_streak >= self.recover_after:
                self._transition(i, ReplicaHealth.HEALTHY)

    def _update_slow(self, durations: Dict[int, float]) -> None:
        """Relative straggler detection: a replica whose tick ran
        ``slow_factor``× the peer median for ``slow_after`` consecutive
        ticks goes SUSPECT (excluded from new placements, still served
        and hedged around) and returns to HEALTHY when it normalizes.
        Slowness never escalates to DEAD — a slow replica heartbeats."""
        if len(durations) < 2:
            return
        med = float(np.median(list(durations.values())))
        floor = max(med, self.slow_floor_s)
        for i, dt in durations.items():
            st = self._state[i]
            if dt > self.slow_factor * floor:
                st.slow_streak += 1
                if st.slow_streak >= self.slow_after \
                        and st.health is ReplicaHealth.HEALTHY:
                    st.slow = True
                    self._transition(i, ReplicaHealth.SUSPECT)
            else:
                st.slow_streak = 0
                if st.slow and st.health is ReplicaHealth.SUSPECT:
                    st.slow = False
                    self._transition(i, ReplicaHealth.HEALTHY)
                st.slow = False

    def _set_health_gauges(self) -> None:
        for i, st in enumerate(self._state):
            self._g_health.set(HEALTH_INDEX[st.health], replica=str(i))

    # -- placement -----------------------------------------------------------

    def _eligible(self, i, eng, burn) -> bool:
        if self._state[i].health is not ReplicaHealth.HEALTHY:
            return False
        return super()._eligible(i, eng, burn)

    def _ctx_cap(self) -> int:
        max_seq = min((getattr(e, "max_seq", 1 << 30)
                       for _, e in self._live()), default=1 << 30)
        return int(max_seq * self.ladder.ctx_cap_frac)

    def _fleet_trace(self):
        """Any live replica's trace lane for router-level marks
        (retry/degrade) — replica 0 may be a tombstone after a
        capacity removal."""
        for _, e in self._live():
            return e.trace
        return None

    def _drain_retry_hint(self) -> float:
        """Depth-scaled Retry-After for DRAINING sheds: proportional
        to the remaining work on the least-loaded draining replica, so
        the client returns roughly when the drain completes and fresh
        placements (or re-admission after rollback) are possible."""
        loads = [e.queue_depth + e.active_requests
                 for i, e in self._live()
                 if self._state[i].health is ReplicaHealth.DRAINING]
        if not loads:
            return self._retry_after_hint()
        return 0.05 * (1.0 + min(loads) / max(self.max_queue_depth, 1))

    def submit(self, request: Request) -> int:
        now = self.clock()
        if self.ladder is not None:
            if self.ladder.level >= 3:
                self.shed_requests += 1
                self._c_shed.inc()
                self._flow_shed(request, ShedReason.DEGRADED)
                raise RequestShed(
                    "degraded to shed level; retry after backoff",
                    reason=ShedReason.DEGRADED,
                    retry_after_s=self._retry_after_hint())
            if self.ladder.level >= 2 \
                    and len(request.prompt) > self._ctx_cap():
                self.shed_requests += 1
                self._c_shed.inc()
                self._flow_shed(request, ShedReason.CONTEXT_CAP)
                raise RequestShed(
                    f"degraded context cap {self._ctx_cap()} tokens",
                    reason=ShedReason.CONTEXT_CAP,
                    retry_after_s=self._retry_after_hint())
        self._dispatch_ctx(request)
        i = self._try_place(request)
        if i is None:
            if self.retry_budget > 0:
                self._queue_retry(request, [], attempts=1, now=now)
                return -1
            self.shed_requests += 1
            self._c_shed.inc()
            healthy = any(s.health is ReplicaHealth.HEALTHY
                          for s in self._state)
            draining = any(s.health is ReplicaHealth.DRAINING
                           for s in self._state)
            if healthy:
                reason, hint = ShedReason.OVERLOAD, \
                    self._retry_after_hint()
            elif draining:
                # capacity shift in progress: tell the client WHEN the
                # drain should be over, not just that it was refused
                reason, hint = ShedReason.DRAINING, \
                    self._drain_retry_hint()
            else:
                reason, hint = ShedReason.NO_HEALTHY_REPLICA, \
                    self._retry_after_hint()
            self._flow_shed(request, reason)
            raise RequestShed("no eligible replica", reason=reason,
                              retry_after_s=hint)
        self._inflight[request.request_id] = _InFlight(request, i, now)
        if self.recorder is not None:
            self.recorder.record("router", "place",
                                 request_id=request.request_id,
                                 replica=i, tick=self._tick)
        return i

    def _queue_retry(self, request: Request, progress: List[int],
                     attempts: int, now: float) -> None:
        backoff = self.retry_base_s * (2.0 ** max(attempts - 1, 0))
        backoff *= 1.0 + self.retry_jitter * float(self._rng.uniform())
        self._retry.append(_PendingRetry(request, list(progress),
                                         attempts, now + backoff))

    def _alive(self, i: int) -> bool:
        return self._state[i].health is not ReplicaHealth.DEAD

    def _pick_target(self, exclude: int = -1) -> Optional[int]:
        """Least-loaded HEALTHY replica for migrated/hedged work —
        health-gated only; the overload gate does not apply to work the
        fleet already accepted."""
        best, best_load = None, None
        for i, eng in self._live():
            if i == exclude \
                    or self._state[i].health is not ReplicaHealth.HEALTHY:
                continue
            load = eng.queue_depth + eng.active_requests
            if best is None or load < best_load:
                best, best_load = i, load
        return best

    # -- capacity lifecycle --------------------------------------------------

    def begin_drain(self, i: int) -> None:
        """Start an orderly drain of replica ``i`` for a capacity
        shift: it stops taking placements (DRAINING is never eligible),
        its queued + in-flight work migrates to healthy peers NOW via
        the same export/adopt machinery a death uses (token-bitwise
        resume), and the heartbeat machine will never mark it dead —
        see :class:`ReplicaHealth`.  Idempotent while draining."""
        if self.replicas[i] is None:
            raise ValueError(f"replica {i} was removed")
        st = self._state[i]
        if st.health is ReplicaHealth.DRAINING:
            return
        if st.health is ReplicaHealth.DEAD:
            raise ValueError(
                f"replica {i} is dead; drain is for live exits")
        st.slow = False
        st.misses = 0
        st.slow_streak = 0
        self._transition(i, ReplicaHealth.DRAINING)
        self._drain_from(i)
        self._set_health_gauges()

    def cancel_drain(self, i: int) -> None:
        """Shift-rollback path: a draining replica returns to HEALTHY.
        Work already migrated off stays where it landed — migration is
        exactly-once, and pulling it back would risk duplication."""
        st = self._state[i]
        if self.replicas[i] is not None \
                and st.health is ReplicaHealth.DRAINING:
            st.misses = 0
            st.ok_streak = 0
            self._transition(i, ReplicaHealth.HEALTHY)
            self._set_health_gauges()

    def drained(self, i: int) -> bool:
        """True when nothing is left on replica ``i``: empty engine
        queue + active set, and no in-flight entry (primary or hedge)
        still pointing at it."""
        eng = self.replicas[i]
        if eng is None:
            return True
        if eng._queue or eng._active:
            return False
        return not any(fl.replica == i or fl.hedge_replica == i
                       for fl in self._inflight.values())

    def remove_replica(self, i: int):
        """Detach replica ``i`` and return its engine (the capacity
        controller keeps it for rollback re-add).  The slot becomes a
        ``None`` tombstone so indices in ``_state`` / ``_consumed`` /
        in-flight records stay valid; finished responses are harvested
        first and any straggler work is exported to peers."""
        eng = self.replicas[i]
        if eng is None:
            raise ValueError(f"replica {i} already removed")
        self._collect()
        self._drain_from(i)
        self._transition(i, ReplicaHealth.REMOVED)
        self.replicas[i] = None
        self._set_health_gauges()
        if self.recorder is not None:
            self.recorder.record("router", "remove_replica", replica=i,
                                 tick=self._tick)
        return eng

    def add_replica(self, engine) -> int:
        """Attach ``engine`` as a serving replica, reusing the first
        tombstone slot (else appending); returns its index.  Responses
        already inside the engine's done list count as consumed — an
        engine re-added on rollback must not re-deliver them
        (exactly-once)."""
        slot = next((j for j, e in enumerate(self.replicas)
                     if e is None), None)
        if slot is None:
            slot = len(self.replicas)
            self.replicas.append(engine)
            self._state.append(_ReplicaState())
            self._consumed.append(len(engine._done))
            self.health_log.append((self._tick, slot, "absent",
                                    "healthy"))
            self._c_trans.inc(**{"from": "absent", "to": "healthy"})
        else:
            self.replicas[slot] = engine
            self._state[slot] = _ReplicaState()
            self._consumed[slot] = len(engine._done)
            self.health_log.append((self._tick, slot, "removed",
                                    "healthy"))
            self._c_trans.inc(**{"from": "removed", "to": "healthy"})
        self._tracing = self._tracing or (
            getattr(getattr(engine, "trace", None), "tracer", None)
            is not None)
        self._set_health_gauges()
        if self.recorder is not None:
            self.recorder.record("router", "add_replica", replica=slot,
                                 tick=self._tick)
        return slot

    # -- migration -----------------------------------------------------------

    def _on_dead(self, i: int) -> None:
        self._drain_from(i)

    def _drain_from(self, i: int) -> None:
        """Move replica ``i``'s queued + in-flight work to peers:
        export with generated-so-far tokens, adopt elsewhere — the
        resumed streams are token-bitwise the uninterrupted ones."""
        eng = self.replicas[i]
        now = self.clock()
        for req, progress in eng.export_inflight():
            rid = req.request_id
            if rid in self._responses:
                continue                     # already answered elsewhere
            fl = self._inflight.get(rid)
            if fl is not None and fl.hedge_replica is not None:
                other = fl.hedge_replica if fl.replica == i else fl.replica
                if other != i and self._alive(other):
                    # the surviving copy is promoted; nothing to migrate
                    fl.replica = other
                    fl.hedge_replica = None
                    continue
            self._migrate(req, progress, src=i, now=now)

    def _migrate(self, req: Request, progress: List[int], src: int,
                 now: float) -> None:
        rid = req.request_id
        target = self._pick_target(exclude=src)
        if target is None:
            # nowhere to go right now: park it; a recovery or drain
            # will place it, so the request is delayed, never lost
            self._inflight.pop(rid, None)
            self._queue_retry(req, progress, attempts=0, now=now)
            return
        eng = self.replicas[target]
        if len(req.prompt) + len(progress) >= eng.max_seq:
            # the single-engine preemption edge, fleet-wide: context no
            # longer fits a fresh admission anywhere useful
            self._router_finish(req, progress, "preempted")
            return
        if req.trace is not None:
            # next causal hop: the adopting replica's enqueue/resume
            # flow events carry the bumped counter
            req.trace.next_hop()
        try:
            eng.adopt(req, list(progress))
        except (QueueFull, ValueError):
            self._inflight.pop(rid, None)
            self._queue_retry(req, progress, attempts=0, now=now)
            return
        self.migrations += 1
        self._c_migrations.inc()
        eng.trace.migrate(rid, src, target)
        if self.recorder is not None:
            self.recorder.record("router", "migrate", request_id=rid,
                                 src=src, dst=target, tick=self._tick,
                                 progress=len(progress))
        if self.first_migration is None:
            self.first_migration = (self._tick, now)
        self._resume_watch[rid] = (target, len(progress))
        fl = self._inflight.get(rid)
        if fl is None:
            self._inflight[rid] = _InFlight(req, target, now)
        else:
            fl.replica = target
            fl.hedge_replica = None

    def _router_finish(self, req: Request, tokens: List[int],
                       reason: str) -> None:
        self._inflight.pop(req.request_id, None)
        self._responses[req.request_id] = Response(
            req.request_id, list(req.prompt), list(tokens), reason)
        if req.trace is not None and req.trace.started:
            # terminal at the ROUTER (shed/preempted) — no engine will
            # close this flow
            emit_flow(self._router_tracer(), req.trace, "finish",
                      final=True, request_id=req.request_id,
                      reason=reason)
        if self.recorder is not None:
            self.recorder.record("router", "router_finish",
                                 request_id=req.request_id,
                                 reason=reason, tick=self._tick)

    # -- response collection -------------------------------------------------

    def _collect(self) -> None:
        for i, eng in self._live():
            done = eng._done
            while self._consumed[i] < len(done):
                resp = done[self._consumed[i]]
                self._consumed[i] += 1
                rid = resp.request_id
                if rid in self._responses:
                    self.duplicate_responses += 1
                    continue
                self._responses[rid] = resp
                if self.recorder is not None:
                    self.recorder.record(f"replica{i}", "response",
                                         request_id=rid,
                                         reason=resp.finish_reason,
                                         tick=self._tick)
                self._resume_watch.pop(rid, None)
                fl = self._inflight.pop(rid, None)
                if fl is not None and fl.hedge_replica is not None:
                    loser = (fl.hedge_replica if i == fl.replica
                             else fl.replica)
                    if loser != i:
                        self.replicas[loser].cancel(rid)

    def _check_resumed(self) -> None:
        if self.first_resume is not None or not self._resume_watch:
            return
        for rid, (rep, baseline) in list(self._resume_watch.items()):
            eng = self.replicas[rep]
            if eng is None:
                self._resume_watch.pop(rid, None)
                continue
            for st in eng._active.values():
                if st.request.request_id == rid \
                        and len(st.generated) > baseline:
                    self.first_resume = (self._tick, self.clock())
                    return
            if rid in self._responses:
                self._resume_watch.pop(rid, None)

    # -- hedging + retries ---------------------------------------------------

    def _hedge_pass(self) -> None:
        if self.hedge_after_s is None:
            return
        now = self.clock()
        for rid, fl in list(self._inflight.items()):
            if fl.hedge_replica is not None \
                    or now - fl.submitted_t < self.hedge_after_s:
                continue
            src_eng = self.replicas[fl.replica]
            if src_eng is None \
                    or rid in src_eng.metrics.ttft:
                continue                     # already past the TTFT tail
            target = self._pick_target(exclude=fl.replica)
            if target is None:
                continue
            try:
                self.replicas[target].submit(
                    dataclasses.replace(fl.request))
            except (QueueFull, ValueError):
                continue
            fl.hedge_replica = target
            self.hedges += 1
            self._c_hedges.inc()
            self.replicas[target].trace.hedge(rid, target)
            if self.recorder is not None:
                self.recorder.record("router", "hedge", request_id=rid,
                                     replica=target, tick=self._tick)

    def _retry_pass(self) -> None:
        now = self.clock()
        # swap first: _queue_retry calls made during this pass append to
        # the fresh list and survive into the next tick
        pending, self._retry = self._retry, []
        for pr in pending:
            rid = pr.request.request_id
            if rid in self._responses:
                continue                     # e.g. finished as preempted
            if pr.next_t > now:
                self._retry.append(pr)
                continue
            self.retries += 1
            self._c_retries.inc()
            tr = self._fleet_trace()
            if tr is not None:
                tr.retry(rid, pr.attempts)
            if self.recorder is not None:
                self.recorder.record("router", "retry", request_id=rid,
                                     attempt=pr.attempts,
                                     tick=self._tick)
            if pr.progress:
                # in-flight work is never shed by the budget: _migrate
                # places it, finishes it ("preempted"), or re-queues it
                # with fresh backoff — delayed, never lost
                self._migrate(pr.request, pr.progress, src=-1, now=now)
                continue
            i = self._try_place(pr.request)
            if i is not None:
                self._inflight[rid] = _InFlight(pr.request, i, now)
                continue
            pr.attempts += 1
            if pr.attempts > self.retry_budget:
                self.shed_requests += 1
                self._c_shed.inc()
                self._router_finish(pr.request, pr.progress, "shed")
                continue
            self._queue_retry(pr.request, pr.progress, pr.attempts, now)

    # -- degradation ---------------------------------------------------------

    def _degrade_pass(self) -> None:
        if self.ladder is None:
            return
        live = self._live()
        if not live:
            return
        if self.ladder.burn_source is not None:
            # per-pool signal (disaggregation): degrade on the pool
            # whose pressure the ladder's actions actually relieve
            burn = float(self.ladder.burn_source())
        else:
            burn = max(self._burn(e) for _, e in live)
        old = self.ladder.level
        lvl = self.ladder.update(burn, self.clock())
        if lvl == old:
            return
        self._g_degraded.set(lvl)
        tr = self._fleet_trace()
        if tr is not None:
            tr.degrade(lvl)
        if self.recorder is not None:
            self.recorder.record("router", "degrade", old=old, new=lvl,
                                 burn=burn, tick=self._tick)
            if lvl > old:
                self.recorder.trigger("ladder_escalation", level=lvl,
                                      burn=burn, tick=self._tick)
        for _, eng in live:
            if getattr(eng, "spec", None) is not None:
                eng.spec_enabled = lvl < 1
        if lvl >= 2 and old < 2:
            for _, eng in live:
                pool = getattr(eng, "pool", None)
                if pool is not None:
                    pool.flush_prefixes()

    # -- the fleet tick ------------------------------------------------------

    def step(self) -> bool:
        """One fleet round: faults → heartbeats/health → collect →
        resumed-token watch → hedges → retries → degradation.  True
        while any replica, retry or in-flight request has work."""
        self._tick += 1
        t = self._tick
        busy = False
        durations: Dict[int, float] = {}
        for i, eng in self._live():
            kinds: Dict[str, ServingFault] = {}
            if self.injector is not None:
                kinds = {f.kind: f for f in self.injector.activate(t, i)}
            if self.recorder is not None:
                for k in kinds:
                    self.recorder.record(f"replica{i}", "fault",
                                         fault=k, tick=t)
            eng.injected_faults = frozenset(
                k for k in kinds
                if k in ("reject_admission", "kv_pool_exhaustion"))
            if "replica_crash" in kinds or "stuck_decode" in kinds:
                # no heartbeat: a crash answers nothing; a stuck decode
                # would hang the health probe just the same
                busy = busy or bool(eng._active or eng._queue)
                self._miss(i)
                continue
            t0 = self.clock()
            try:
                busy = eng.step() or busy
            except Exception:
                self._miss(i)
                continue
            slow = kinds.get("slow_replica")
            if slow is not None:
                self._advance_clock(float(slow.magnitude) or 0.05)
            durations[i] = self.clock() - t0
            self._beat(i)
            if self.recorder is not None:
                # per-tick load deltas per replica — the "metric
                # deltas" lane of the black box
                self.recorder.record(f"replica{i}", "tick",
                                     tick=t, queue=eng.queue_depth,
                                     active=eng.active_requests,
                                     dur_s=durations[i])
        self._update_slow(durations)
        self._collect()
        self._check_resumed()
        self._hedge_pass()
        self._retry_pass()
        self._degrade_pass()
        self._set_health_gauges()
        return busy or bool(self._retry) or bool(self._inflight)

    def _advance_clock(self, dt: float) -> None:
        if hasattr(self.clock, "advance"):
            self.clock.advance(dt)
        else:                                # pragma: no cover - realtime
            time.sleep(dt)

    @property
    def pending(self) -> int:
        """Accepted requests not yet terminal (exactly-once sentinel:
        0 on a drained fleet)."""
        return len(self._inflight) + len(self._retry)

    def run(self, max_steps: Optional[int] = None) -> List[Response]:
        """Drive :meth:`step` to drain.  With permanent whole-fleet
        faults injected, pass ``max_steps`` — a fleet with zero
        heartbeating replicas can never finish parked retries."""
        steps = 0
        while True:
            busy = self.step()
            steps += 1
            if not busy and not any(e._queue or e._active
                                    for _, e in self._live()):
                break
            if max_steps is not None and steps >= max_steps:
                break
        return self.completed

    @property
    def completed(self) -> List[Response]:
        """Deduplicated responses across the fleet (engine-produced plus
        router-terminal ``shed``/``preempted``), completion order."""
        self._collect()
        return list(self._responses.values())

    def recovery_report(self) -> dict:
        """Detection → migration → first-resumed-token timeline of the
        first replica death (ticks and clock seconds; None entries mean
        the event never happened)."""
        def row(v):
            return None if v is None else {"tick": v[0], "t": v[1]}
        return {"first_dead": row(self.first_dead),
                "first_migration": row(self.first_migration),
                "first_resumed_token": row(self.first_resume)}
