"""Disaggregated prefill/decode serving: two pools, one fleet.

A monolithic replica interleaves two workloads with opposite resource
shapes: prefill is compute-bound and bursty (TTFT is its SLO), decode
is memory-bandwidth-bound and steady (TPOT).  Co-locating them means a
long prompt's chunks steal decode ticks and a deep decode batch delays
first tokens — each pool's tail latency is set by the OTHER pool's
load.  :class:`DisaggregatedFleet` splits them: a **prefill pool** of
``prefill_only=True`` :class:`~apex_tpu.serving.PagedInferenceEngine`
replicas that run chunked prefill and then *park* (never decode), and
a **decode pool** of ordinary replicas that never see a raw prompt —
each request's KV state moves between them exactly once.

The handoff is the block-shipping generalization of the fleet's
migration machinery.  ``export_kv()`` strips a parked request off its
prefill replica WITH the raw storage of every block backing its
``kv_len`` positions; :class:`KvChannel` moves those bytes over an
explicit priced link (per-byte alpha/beta from the same
:class:`~apex_tpu.observability.costmodel.CostModel` fit the MPMD
engine prices cross-pod hops with, consume-once ``dcn_fault`` retry);
``adopt_kv()`` installs them on a decode replica and resumes the
``(seed, token-index)`` sampling stream — no re-prefill, token-BITWISE
the single-pool stream because paged attention only ever gathers the
block storage the payload is a literal copy of.  Every failure mode
degrades to an existing, proven path:

* channel retries exhausted (handoff lost) → **re-prefill fallback**:
  the decode pool adopts ``prompt + generated`` through the ordinary
  :meth:`~apex_tpu.serving.fleet.FleetRouter._migrate` machinery —
  slower, still bitwise;
* decode pool full (``QueueFull``) → the handoff is buffered and
  re-attempted next tick, then falls back the same way (delayed,
  never lost);
* prefill replica killed with parked work → the fleet's death
  migration re-prefills it on a prefill peer, and the handoff happens
  from there (exactly-once: ``export_kv`` is terminal-no-Response on
  the source, deduplicated collection on both routers).

Quantized decode KV: build the decode pool over
:class:`~apex_tpu.serving.QuantizedPagedKVCache` (``kv_quant="int8"``
on BOTH pools — the handoff tags ``kind``/``block_size`` and refuses a
bitwise install across cache kinds) and per-user KV bytes drop ~4× vs
f32 (~2× vs bf16) while the handoff payload shrinks the same ratio —
``serving_kv_handoff_bytes`` is the series the CI leg gates at
< 0.3× the f32 bytes.

Degradation is per-pool: the shared
:class:`~apex_tpu.serving.DegradationLadder` is threaded a
``burn_source`` reading the DECODE pool's SLO burn, because level 2's
actions (prefix-trie flush + context cap) relieve decode KV pressure —
a prefill-pool TTFT burn must not flush the decode cache.  Sizing is
per-pool too: :class:`~apex_tpu.resilience.capacity.
PoolCapacityController` moves replicas between pools on TTFT-burn vs
TPOT-burn with the two-phase reserve→drain→commit protocol.

Fleet series: ``serving_disagg_handoffs_total`` /
``serving_disagg_fallbacks_total`` counters,
``serving_kv_handoff_bytes`` (labelled by cache kind),
``serving_disagg_pending_handoffs`` gauge.  Each handoff stamps a
``kv_handoff`` flow step on the request's trace context between the
prefill hop and the decode hop, so the Perfetto arrow chain reads
prefill-replica → channel → decode-replica end to end
(``FleetCollector.continuity()`` asserts the chains stay unbroken
across the pool boundary).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

from apex_tpu.inference.engine import QueueFull, Request, Response
from apex_tpu.mpmd.channel import DcnTimeout, Edge, LocalDcnChannel
from apex_tpu.observability.fleetobs import FlightRecorder, emit_flow
from apex_tpu.serving.engine import KvHandoff
from apex_tpu.serving.fleet import (DegradationLadder, FleetRouter,
                                    ReplicaHealth, ServingFaultInjector,
                                    _InFlight)

__all__ = ["DisaggregatedFleet", "KvChannel"]


class KvChannel(LocalDcnChannel):
    """The prefill→decode KV link: a :class:`LocalDcnChannel` (byte-
    exact host round-trip, priced alpha + beta·bytes, consume-once
    ``dcn_fault`` + bounded retry) that additionally keeps the handoff
    ledger the bench legs read (``handoffs`` / ``handoff_bytes`` /
    ``lost_handoffs``).  Build via :meth:`from_cost_model` to price the
    link off the same fitted ``dcn`` curve the MPMD engine uses."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.handoffs = 0
        self.handoff_bytes = 0
        self.lost_handoffs = 0

    def send_handoff(self, handoff: KvHandoff, *, step: int = 0,
                     edge: Optional[Edge] = None) -> KvHandoff:
        """Move ``handoff``'s block payload across the link (bytes
        preserved exactly; latency accounted into
        ``simulated_seconds``).  Raises :class:`DcnTimeout` once the
        retry budget is exhausted — the caller's re-prefill fallback
        owns the request from there."""
        try:
            handoff.payload = self.send_with_retry(
                handoff.payload, step=step, edge=edge)
        except DcnTimeout:
            self.lost_handoffs += 1
            raise
        self.handoffs += 1
        self.handoff_bytes += handoff.nbytes()
        return handoff


class _BufferedHandoff:
    """A handoff waiting for decode capacity (bounded retries, then
    the re-prefill fallback)."""

    def __init__(self, handoff: KvHandoff):
        self.handoff = handoff
        self.ticks = 0


class DisaggregatedFleet:
    """Two :class:`~apex_tpu.serving.FleetRouter` pools — prefill and
    decode — behind one placement facade, with the KV handoff between
    them (see the module docstring for the architecture).

    ``submit`` places on the prefill pool (degradation gates included:
    both routers share ``ladder``, whose ``burn_source`` is wired to
    the decode pool's burn unless the caller set one).  ``step`` runs
    one fleet round: decode pool first (so a ladder level change acts
    on decode replicas the tick it trips), then prefill, then the
    handoff pass.  ``completed`` merges both pools' deduplicated
    responses.
    """

    def __init__(self, prefill_replicas: Sequence,
                 decode_replicas: Sequence, *,
                 channel: Optional[KvChannel] = None,
                 clock=time.monotonic,
                 prefill_injector: Optional[ServingFaultInjector] = None,
                 decode_injector: Optional[ServingFaultInjector] = None,
                 ladder: Optional[DegradationLadder] = None,
                 handoff_retry_ticks: int = 8,
                 registry=None, recorder: Optional[FlightRecorder] = None,
                 tracer=None, seed: int = 0,
                 prefill_kw: Optional[dict] = None,
                 decode_kw: Optional[dict] = None):
        for e in prefill_replicas:
            if not getattr(e, "prefill_only", False):
                raise ValueError(
                    "every prefill-pool replica needs prefill_only=True "
                    "— a replica that decodes locally never parks a "
                    "handoff")
        for e in decode_replicas:
            if getattr(e, "prefill_only", False):
                raise ValueError(
                    "decode-pool replicas must not be prefill_only — "
                    "the pool exists to run the decode (and re-prefill "
                    "fallback) work")
        if handoff_retry_ticks < 1:
            raise ValueError("handoff_retry_ticks must be >= 1")
        if ladder is not None and ladder.burn_source is None:
            ladder.burn_source = self._decode_burn
        self.ladder = ladder
        self.clock = clock
        self.channel = channel if channel is not None else KvChannel()
        self.handoff_retry_ticks = int(handoff_retry_ticks)
        reg = registry if registry is not None \
            else prefill_replicas[0].metrics.registry
        self.decode = FleetRouter(
            decode_replicas, clock=clock, injector=decode_injector,
            ladder=ladder, recorder=recorder, registry=reg,
            tracer=tracer, seed=seed + 1, **(decode_kw or {}))
        self.prefill = FleetRouter(
            prefill_replicas, clock=clock, injector=prefill_injector,
            ladder=ladder, recorder=recorder, registry=reg,
            tracer=tracer, seed=seed, **(prefill_kw or {}))
        self.recorder = recorder
        self._tick = 0
        self._buffered: List[_BufferedHandoff] = []
        self.handoffs = 0
        self.fallbacks = 0
        self.duplicate_responses = 0
        self._c_handoffs = reg.counter(
            "serving_disagg_handoffs_total",
            "KV handoffs installed on the decode pool")
        self._c_fallbacks = reg.counter(
            "serving_disagg_fallbacks_total",
            "handoffs that fell back to re-prefill on the decode pool")
        self._c_handoff_bytes = reg.counter(
            "serving_kv_handoff_bytes",
            "KV block bytes shipped prefill->decode, by cache kind",
            labelnames=("kind",))
        self._g_pending = reg.gauge(
            "serving_disagg_pending_handoffs",
            "handoffs buffered awaiting decode capacity")
        self._g_pending.set(0)

    # -- signals ---------------------------------------------------------

    def _decode_burn(self) -> float:
        """The decode pool's max SLO burn — the ladder's pressure
        signal in a disaggregated fleet (see the satellite fix note on
        :class:`~apex_tpu.serving.DegradationLadder`)."""
        return max((self.decode._burn(e)
                    for _, e in self.decode._live()), default=0.0)

    def _prefill_burn(self) -> float:
        return max((self.prefill._burn(e)
                    for _, e in self.prefill._live()), default=0.0)

    # -- admission ---------------------------------------------------------

    def submit(self, request: Request) -> int:
        """Place on the prefill pool (shared-ladder degradation gates
        apply — on the DECODE pool's burn).  Returns the prefill
        replica index, or -1 when parked for internal retry."""
        return self.prefill.submit(request)

    # -- the fleet tick ------------------------------------------------------

    def step(self) -> bool:
        """One disaggregated round: decode pool ticks first (a ladder
        escalation acts on decode replicas immediately), then the
        prefill pool (producing parked prefills), then the handoff
        pass ships every ready KV payload across the channel."""
        self._tick += 1
        busy_d = self.decode.step()
        busy_p = self.prefill.step()
        self._handoff_pass()
        self._g_pending.set(len(self._buffered))
        return busy_d or busy_p or bool(self._buffered)

    def run(self, max_steps: Optional[int] = None) -> List[Response]:
        """Drive :meth:`step` to drain (or ``max_steps``)."""
        steps = 0
        while True:
            busy = self.step()
            steps += 1
            if not busy and not any(
                    e._queue or e._active
                    for r in (self.prefill, self.decode)
                    for _, e in r._live()):
                break
            if max_steps is not None and steps >= max_steps:
                break
        return self.completed

    @property
    def pending(self) -> int:
        """Accepted-but-not-terminal count across both pools plus the
        handoff buffer (exactly-once sentinel: 0 on a drained fleet)."""
        return (self.prefill.pending + self.decode.pending
                + len(self._buffered))

    @property
    def completed(self) -> List[Response]:
        """Deduplicated responses across both pools."""
        out: Dict[object, Response] = {}
        for resp in self.prefill.completed + self.decode.completed:
            if resp.request_id in out:
                self.duplicate_responses += 1
                continue
            out[resp.request_id] = resp
        return list(out.values())

    # -- the handoff ---------------------------------------------------------

    def _handoff_pass(self) -> None:
        """Ship every parked prefill to the decode pool: harvest
        ``handoffs_ready()`` from HEALTHY prefill replicas (a crashed
        or suspect replica is unreachable — its parked work rides the
        fleet's death migration instead), move the blocks through the
        channel, install with ``adopt_kv``.  Buffered handoffs (decode
        pool momentarily full) retry for ``handoff_retry_ticks`` ticks
        before falling back to re-prefill."""
        now = self.clock()
        for i, eng in self.prefill._live():
            if self.prefill._state[i].health is not ReplicaHealth.HEALTHY:
                continue
            for _slot, rid in eng.handoffs_ready():
                handoff = eng.export_kv(rid)
                handoff.src_replica = i
                try:
                    handoff = self.channel.send_handoff(
                        handoff, step=self._tick,
                        edge=Edge(src=i, dst=-1))
                except DcnTimeout:
                    # handoff lost: the blocks never arrived, but the
                    # request + generated tokens are host state — the
                    # decode pool re-prefills them (token-bitwise, the
                    # fleet's standard migration)
                    self._fallback(handoff, now)
                    continue
                self._buffered.append(_BufferedHandoff(handoff))
        still: List[_BufferedHandoff] = []
        for buf in self._buffered:
            if self._install(buf.handoff, now):
                continue
            buf.ticks += 1
            if buf.ticks >= self.handoff_retry_ticks:
                self._fallback(buf.handoff, now)
            else:
                still.append(buf)
        self._buffered = still

    def _install(self, handoff: KvHandoff, now: float) -> bool:
        """One install attempt on the least-loaded healthy decode
        replica.  True when the handoff reached a terminal state
        (installed, preempted, or handed to the fallback); False to
        keep it buffered."""
        req = handoff.request
        rid = req.request_id
        target = self.decode._pick_target()
        if target is None:
            return False                 # no healthy decode replica yet
        eng = self.decode.replicas[target]
        if len(req.prompt) + len(handoff.generated) >= eng.max_seq:
            self.decode._router_finish(req, handoff.generated,
                                       "preempted")
            self.prefill._inflight.pop(rid, None)
            return True
        if req.trace is not None:
            # the next causal hop + the arrow-chain step that stitches
            # prefill-hop -> handoff -> decode-hop in one Perfetto chain
            req.trace.next_hop(replica=str(target))
            emit_flow(self.prefill._router_tracer(), req.trace,
                      "kv_handoff", request_id=rid,
                      src=handoff.src_replica, dst=target,
                      nbytes=handoff.nbytes(), kind=handoff.kind)
        try:
            eng.adopt_kv(handoff)
        except QueueFull:
            return False                 # retry next tick
        except ValueError:
            # storage-tag or context misfit: a bitwise install is
            # impossible, a re-prefill is not
            self._fallback(handoff, now)
            return True
        self.handoffs += 1
        self._c_handoffs.inc()
        self._c_handoff_bytes.inc(handoff.nbytes(), kind=handoff.kind)
        eng.trace.migrate(rid, handoff.src_replica, target)
        fl = self.prefill._inflight.pop(rid, None)
        self.decode._inflight[rid] = _InFlight(
            req, target, fl.submitted_t if fl is not None else now)
        self.decode._resume_watch[rid] = (target, len(handoff.generated))
        if self.recorder is not None:
            self.recorder.record("disagg", "kv_handoff", request_id=rid,
                                 src=handoff.src_replica, dst=target,
                                 nbytes=handoff.nbytes(),
                                 tick=self._tick)
        return True

    def _fallback(self, handoff: KvHandoff, now: float) -> None:
        """Re-prefill fallback: the decode pool adopts
        ``prompt + generated`` through the fleet's standard migration —
        no KV bytes needed, token-bitwise, merely slower."""
        rid = handoff.request.request_id
        self.fallbacks += 1
        self._c_fallbacks.inc()
        self.prefill._inflight.pop(rid, None)
        if self.recorder is not None:
            self.recorder.record("disagg", "handoff_fallback",
                                 request_id=rid,
                                 src=handoff.src_replica,
                                 tick=self._tick)
        self.decode._migrate(handoff.request, list(handoff.generated),
                             src=-1, now=now)
