"""Paged continuous-batching engine: the serving tier of apex_tpu.

:class:`PagedInferenceEngine` subclasses the contiguous
:class:`~apex_tpu.inference.InferenceEngine` and swaps ONLY the memory
backend and the per-tick device plan; the whole request lifecycle —
validation, bounded-queue backpressure, eviction/timeout, quarantine,
preemption-requeue, metrics/trace — is inherited, and so is the
sampling stream (``_sample`` keyed by ``(seed, token-index)``).  That
shared lifecycle plus the gather-identical paged attention path is why
the engine's outputs are token-BITWISE-identical to the contiguous
engine for greedy and seeded sampling (asserted by
``__graft_entry__._dryrun_serving`` and ``tests/test_serving.py``),
while memory goes from ``slots * max_seq`` rows to demand-allocated
blocks with prefix sharing.

Three independently-switchable serving features:

* **Paged KV + prefix sharing** (always on): admission acquires blocks
  from :class:`~apex_tpu.serving.PagedKVCache`; a prompt sharing a
  cached full-block prefix skips both the KV writes AND (under chunked
  prefill) the forward compute for the shared tokens.  When the pool
  runs dry mid-decode the engine preempts the most recently admitted
  request (release blocks → requeue-with-progress → recompute later),
  the vLLM recovery policy, reusing the resilience machinery of
  ``preempt()``.
* **Chunked prefill** (``chunked_prefill=True``): prompts are processed
  in scheduler-budgeted chunks mixed into decode ticks instead of one
  monolithic prefill at admission — no head-of-line blocking of decode
  behind a long prompt.  Chunked token parity vs the contiguous path is
  deterministic and asserted at token level (the chunk forward is a
  different — gather-based — compute schedule from the bucketed
  prefill, so per-logit bitwiseness is not guaranteed by construction
  the way pure paged decode is).
* **Speculative decoding** (``speculative=SpeculativeConfig(...)``):
  see :mod:`apex_tpu.serving.speculative` — the draft proposes γ
  tokens, one (γ+1)-wide target chunk verifies, exact-match acceptance
  preserves the sampling stream exactly.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.inference.engine import (InferenceEngine, QueueFull, Request,
                                       _Active)
from apex_tpu.inference.kv_cache import KVCache
from apex_tpu.serving.paged_kv import PagedKVCache, QuantizedPagedKVCache
from apex_tpu.serving.scheduler import TickScheduler
from apex_tpu.serving.speculative import SpeculativeConfig


@dataclasses.dataclass
class KvHandoff:
    """A request's KV state in flight between engines — what
    :meth:`PagedInferenceEngine.export_kv` produces and
    :meth:`PagedInferenceEngine.adopt_kv` installs (the disaggregated
    prefill→decode handoff; see :mod:`apex_tpu.serving.disagg`).

    ``payload`` is the exporting pool's raw block storage
    (:meth:`PagedKVCache.export_blocks` — ``data``, plus ``scales`` for
    the int8 pool), ``kv_tokens`` the ``kv_len`` tokens it backs
    (``prompt + generated[:-1]`` — ``generated[-1]`` is the next token
    to FEED, whose KV the first decode step writes), and ``kind`` /
    ``block_size`` the storage-compatibility tags the adopting pool
    must match for a bitwise install."""
    request: Request
    generated: List[int]
    kv_len: int
    kv_tokens: List[int]
    payload: dict
    block_size: int
    kind: str
    src_replica: int = -1

    def nbytes(self) -> int:
        """Bytes the handoff moves over the wire (block storage only;
        the request metadata is negligible and identical across cache
        kinds)."""
        return int(sum(np.asarray(v).nbytes
                       for v in self.payload.values()))


@dataclasses.dataclass
class _ChunkPrefill:
    """Progress of one chunked prefill: ``ctx`` is the full context
    (prompt + requeued progress), ``done`` how many positions already
    hold KV (starts at the trie-shared prefix — shared tokens are never
    re-forwarded, the compute half of the prefix-cache win)."""
    ctx: List[int]
    done: int
    prev_len: int       # generated-so-far count (resume stream index)


class PagedInferenceEngine(InferenceEngine):
    """Continuous batching over a paged block pool."""

    def __init__(self, model, params, *, block_size: int = 8,
                 num_blocks: Optional[int] = None,
                 share_prefixes: bool = True,
                 chunked_prefill: bool = False,
                 scheduler: Optional[TickScheduler] = None,
                 speculative: Optional[SpeculativeConfig] = None,
                 kv_quant: Optional[str] = None,
                 prefill_only: bool = False,
                 **kw):
        if kv_quant not in (None, "int8"):
            raise ValueError(f"kv_quant must be None or 'int8', "
                             f"got {kv_quant!r}")
        if kv_quant is not None and speculative is not None:
            raise ValueError(
                "kv_quant is incompatible with speculative decoding: "
                "the verify chunk consumes INTERMEDIATE chunk logits, "
                "which later proposals' block requantization perturbs — "
                "only the final row of a quantized chunk is "
                "schedule-invariant")
        if kv_quant is not None and not chunked_prefill:
            raise ValueError(
                "kv_quant requires chunked_prefill=True: the chunked "
                "path appends+requantizes per token, which is what "
                "makes re-prefill (migration/preemption resume) bitwise "
                "on a quantized cache; monolithic prefill quantizes "
                "each block one-shot and cannot replay decode's "
                "per-token history")
        if prefill_only and not chunked_prefill:
            raise ValueError("prefill_only requires chunked_prefill=True "
                             "(prefill replicas run chunked prefill only)")
        self._block_size = block_size
        self._num_blocks = num_blocks
        self._share_prefixes = share_prefixes
        self.chunked_prefill = chunked_prefill
        self.kv_quant = kv_quant
        self.prefill_only = prefill_only
        self.scheduler = scheduler or TickScheduler()
        self.spec = speculative
        # runtime switch over the configured spec path: the fleet's
        # degradation ladder (level 1) turns speculation off under SLO
        # burn and back on when burn clears.  Exact-match acceptance
        # makes the toggle token-invisible; only throughput changes.
        self.spec_enabled = True
        self.spec_proposed = 0
        self.spec_accepted = 0
        super().__init__(model, params, **kw)

    @property
    def _spec_active(self) -> bool:
        return self.spec is not None and self.spec_enabled

    # -- backend -------------------------------------------------------------

    def _init_backend(self, max_slots: int, max_seq: int,
                      cache_dtype) -> None:
        cfg = self.model.cfg
        bs = self._block_size
        if max_seq % bs:
            raise ValueError(
                f"max_seq ({max_seq}) must be a multiple of block_size "
                f"({bs}) — equal logical depth is what keeps paged "
                "attention bitwise-identical to the contiguous cache")
        self.max_seq = max_seq
        self.max_slots = max_slots
        self.max_blocks = max_seq // bs
        if self._num_blocks is None:
            # as roomy as the contiguous ring it replaces (+ garbage
            # block); real deployments size this to HBM, not to slots
            self._num_blocks = 1 + max_slots * self.max_blocks
        pool_cls = (QuantizedPagedKVCache if self.kv_quant == "int8"
                    else PagedKVCache)
        self.pool = pool_cls(
            self._num_blocks, bs, cfg.num_layers, cfg.local_heads,
            cfg.head_dim, cache_dtype, share_prefixes=self._share_prefixes,
            registry=self.metrics.registry)
        self._free_slots = list(range(max_slots - 1, -1, -1))
        self._seqs: dict = {}            # slot -> PagedSequence
        self._tables = np.zeros((max_slots, self.max_blocks), np.int32)
        self._prefilling: dict = {}      # slot -> _ChunkPrefill
        self._prefill_order: List[int] = []
        self._handoff_ready: List[int] = []   # parked prefill_only slots
        self._admit_stamp: dict = {}     # slot -> admission counter
        self._admitted = 0
        if self.kv_quant == "int8":
            self._decode_paged_q = jax.jit(
                self.model.decode_step_paged_quant, donate_argnums=(2, 3))
            self._chunk_q = jax.jit(self.model.decode_chunk_quant,
                                    donate_argnums=(2, 3))
        else:
            self._decode_paged = jax.jit(self.model.decode_step_paged,
                                         donate_argnums=(2,))
            self._chunk = jax.jit(self.model.decode_chunk,
                                  donate_argnums=(2,))
        self._prefill = jax.jit(self.model.prefill)
        if self.spec is not None:
            self.spec.validate_against(self.model)
            dcfg = self.spec.model.cfg
            # the draft keeps a plain contiguous ring aligned on the
            # same slot ids (it is small — paging it buys nothing)
            self._draft_cache = KVCache(max_slots, dcfg.num_layers,
                                        max_seq, dcfg.local_heads,
                                        dcfg.head_dim, cache_dtype)
            self._draft_decode = jax.jit(self.spec.model.decode_step,
                                         donate_argnums=(2,))
            self._draft_prefill = jax.jit(self.spec.model.prefill)
            r = self.metrics.registry
            self._c_spec_prop = r.counter(
                "serving_spec_proposed_total", "draft tokens proposed")
            self._c_spec_acc = r.counter(
                "serving_spec_accepted_total",
                "draft tokens matching the canonical stream")

    def _export_cache_gauges(self) -> None:
        self._g_kv_free.set(self.pool.free_bytes())
        self._g_kv_occ.set(self.pool.occupancy())

    def _release(self, slot: int, st) -> None:
        seq = self._seqs.pop(slot, None)
        if seq is not None:
            self.pool.release(seq)
        self._tables[slot] = 0
        self._prefilling.pop(slot, None)
        if slot in self._prefill_order:
            self._prefill_order.remove(slot)
        if slot in self._handoff_ready:
            self._handoff_ready.remove(slot)
        self._admit_stamp.pop(slot, None)
        self._free_slots.append(slot)

    def _cache_advance(self, slot: int, st: _Active) -> None:
        # st.position was already advanced past the cached token by the
        # shared tail? No: _advance_slots calls this BEFORE appending,
        # exactly like the contiguous engine — the token fed this step
        # sits at st.position, so the valid length becomes position + 1.
        self._seqs[slot].num_tokens = st.position + 1

    # -- admission -----------------------------------------------------------

    def _admit(self) -> None:
        if "kv_pool_exhaustion" in self.injected_faults:
            return                      # injected: no blocks to admit with
        while self._queue and self._free_slots:
            req = self._queue[0]
            prev = self._progress.get(req.request_id)
            ctx = list(req.prompt) + (prev or [])
            seq = self.pool.acquire(ctx)
            if seq is None:
                # pool exhausted even after trie eviction: requests wait
                # queued until decode completions free blocks
                break
            self._queue.popleft()
            self._progress.pop(req.request_id, None)
            slot = self._free_slots.pop()
            self._admitted += 1
            self._admit_stamp[slot] = self._admitted
            if prev is None:
                self.trace.admit(req.request_id)
            clen = len(ctx)
            self._seqs[slot] = seq
            self._tables[slot] = self.pool.table_row(seq, self.max_blocks)
            if self.chunked_prefill:
                # defer ALL device work to budgeted chunks; the slot is
                # active (evictable, preemptable) but not yet decoding
                st = _Active(req, len(req.prompt), next_token=-1,
                             position=clen, generated=list(prev or []))
                self._active[slot] = st
                self._prefilling[slot] = _ChunkPrefill(
                    ctx, seq.shared_tokens, len(prev or []))
                self._prefill_order.append(slot)
                continue
            try:
                # monolithic prefill — same bucketing, same program, same
                # logits as the contiguous engine (the bitwise mode)
                toks = np.zeros((1, self._bucket(clen)), np.int32)
                toks[0, :clen] = ctx
                logits, kv = self._prefill(self.params, jnp.asarray(toks))
                self.pool.write_context_kv(seq, kv[:, :, 0], clen)
                self.pool.register_prefix(seq, ctx)
                self._draft_admit(slot, ctx)
                nxt = self._sample(req, np.asarray(logits[0, clen - 1]),
                                   len(prev or []))
            except Exception as e:          # quarantine, as in the base
                self._release(slot, None)
                self._finish_response(req, list(prev or []), "error",
                                      error=f"{type(e).__name__}: {e}")
                continue
            if prev is None:
                self.metrics.first_token(req.request_id)
                self.trace.first_token(req.request_id)
            else:
                self.metrics.token(req.request_id)
                self.trace.decode_tick(req.request_id)
                self.trace.resumed(req.request_id)
            st = _Active(req, len(req.prompt), next_token=nxt,
                         position=clen, generated=(prev or []) + [nxt])
            self._active[slot] = st
            self._maybe_finish(slot, st)

    def _draft_admit(self, slot: int, ctx: List[int]) -> None:
        if self.spec is None:
            return
        toks = np.zeros((1, self._bucket(len(ctx))), np.int32)
        toks[0, :len(ctx)] = ctx
        _, kv = self._draft_prefill(self.spec.params, jnp.asarray(toks))
        self._draft_cache.write_prompt(slot, kv[:, :, 0], len(ctx))

    # -- pool pressure -------------------------------------------------------

    def _grow(self, slot: int, n_tokens: int) -> bool:
        """Extend ``slot``'s block table to ``n_tokens`` positions,
        preempting the most recently admitted OTHER request when the
        pool (and the prefix trie's evictable tail) cannot supply
        blocks — recompute-on-readmission, the vLLM policy, riding the
        engine's existing requeue machinery."""
        seq = self._seqs[slot]
        while not self.pool.ensure_capacity(seq, n_tokens):
            victims = [s for s in self._admit_stamp if s != slot
                       and s in self._active]
            if not victims:
                return False
            self._preempt_slot(max(victims, key=self._admit_stamp.get))
        self._tables[slot] = self.pool.table_row(seq, self.max_blocks)
        return True

    # -- the tick loop -------------------------------------------------------

    def step(self) -> bool:
        self._evict_expired()
        self._admit()
        self._export_cache_gauges()
        if not self._active:
            return bool(self._queue)
        decoding = [s for s in self._active
                    if s not in self._prefilling
                    and s not in self._handoff_ready]
        if self._prefilling:
            plan = self.scheduler.plan(
                len(decoding),
                [(s, len(self._prefilling[s].ctx) - self._prefilling[s].done)
                 for s in self._prefill_order],
                self.spec.num_tokens if (self._spec_active and decoding)
                else 0)
            for slot, n in plan.chunks.items():
                if slot in self._prefilling:     # may have been evicted
                    self._run_prefill_chunk(slot, n)
        decoding = sorted(s for s in self._active
                          if s not in self._prefilling
                          and s not in self._handoff_ready)
        if decoding:
            if self._spec_active:
                self._spec_round(decoding)
            else:
                self._decode_round(decoding)
        return bool(self._active or self._queue)

    def _decode_round(self, decoding: List[int]) -> None:
        for slot in list(decoding):
            if slot in self._active and not self._grow(
                    slot, self._active[slot].position + 1):
                self._preempt_slot(slot)     # cannot even hold one more
        decoding = [s for s in decoding if s in self._active]
        if not decoding:
            return
        n = self.max_slots
        tokens = np.zeros((n,), np.int32)
        positions = np.zeros((n,), np.int32)
        for slot in decoding:
            st = self._active[slot]
            tokens[slot] = st.next_token
            positions[slot] = st.position
        if self.kv_quant == "int8":
            logits, self.pool.data, self.pool.scales = \
                self._decode_paged_q(
                    self.params, jnp.asarray(tokens), self.pool.data,
                    self.pool.scales, jnp.asarray(self._tables),
                    jnp.asarray(positions))
        else:
            logits, self.pool.data = self._decode_paged(
                self.params, jnp.asarray(tokens), self.pool.data,
                jnp.asarray(self._tables), jnp.asarray(positions))
        self.metrics.step(len(decoding), n)
        self._advance_slots(decoding, np.asarray(logits))

    # -- chunked prefill -----------------------------------------------------

    def _run_prefill_chunk(self, slot: int, n: int) -> None:
        cs = self._prefilling[slot]
        st = self._active[slot]
        seq = self._seqs[slot]
        bs = self.pool.block_size
        start = cs.done
        end = min(start + n, len(cs.ctx))
        c = end - start
        pad = self._bucket(c)
        toks = np.zeros((1, pad), np.int32)
        pos = np.zeros((1, pad), np.int32)
        wb = np.zeros((1, pad), np.int32)    # pad rows -> garbage block 0
        wo = np.zeros((1, pad), np.int32)
        toks[0, :c] = cs.ctx[start:end]
        for j in range(c):
            p = start + j
            pos[0, j] = p
            wb[0, j] = seq.block_ids[p // bs]
            wo[0, j] = p % bs
        try:
            if self.kv_quant == "int8":
                logits, self.pool.data, self.pool.scales = self._chunk_q(
                    self.params, jnp.asarray(toks), self.pool.data,
                    self.pool.scales,
                    jnp.asarray(self._tables[slot:slot + 1]),
                    jnp.asarray(pos), jnp.asarray(wb), jnp.asarray(wo))
            else:
                logits, self.pool.data = self._chunk(
                    self.params, jnp.asarray(toks), self.pool.data,
                    jnp.asarray(self._tables[slot:slot + 1]),
                    jnp.asarray(pos), jnp.asarray(wb), jnp.asarray(wo))
            cs.done = end
            if end < len(cs.ctx):
                return
            # prefill complete: publish, admit the draft, first token
            self.pool.register_prefix(seq, cs.ctx)
            self._draft_admit(slot, cs.ctx)
            nxt = self._sample(st.request, np.asarray(logits)[0, c - 1],
                               cs.prev_len)
        except Exception as e:              # quarantine
            self._finish(slot, st, "error",
                         error=f"{type(e).__name__}: {e}")
            return
        if cs.prev_len == 0:
            self.metrics.first_token(st.request.request_id)
            self.trace.first_token(st.request.request_id)
        else:
            self.metrics.token(st.request.request_id)
            self.trace.decode_tick(st.request.request_id)
            self.trace.resumed(st.request.request_id)
        st.next_token = nxt
        st.generated.append(nxt)
        del self._prefilling[slot]
        self._prefill_order.remove(slot)
        if not self._maybe_finish(slot, st) and self.prefill_only:
            # disaggregated prefill replica: the request is done with
            # its prefill phase — park it (no decode steps here) until
            # the fleet ships its KV to a decode replica via export_kv
            self._handoff_ready.append(slot)

    # -- disaggregated KV handoff ----------------------------------------------

    def handoffs_ready(self) -> List[tuple]:
        """``(slot, request_id)`` pairs parked after a completed prefill
        on a ``prefill_only`` engine, ascending slot — the export queue
        the disaggregated fleet drains each tick."""
        return [(s, self._active[s].request.request_id)
                for s in sorted(self._handoff_ready)]

    def export_kv(self, request_id) -> KvHandoff:
        """Strip ``request_id`` off this engine WITH its KV blocks — the
        block-shipping generalization of :meth:`export_inflight`.  The
        returned :class:`KvHandoff` carries the raw storage of every
        block backing ``kv_len = position`` valid positions (i.e. the KV
        of ``prompt + generated[:-1]``; ``generated[-1]`` is the next
        token to feed, whose KV the adopting engine's first decode step
        writes), so :meth:`adopt_kv` resumes WITHOUT re-running prefill
        — bitwise, because paged attention only ever gathers the block
        storage this payload is a literal copy of.  Terminal on this
        engine like a migration: reason ``"migrated"``, no Response.
        Raises KeyError when the id is not active here and ValueError
        while its prefill is still chunking (no complete KV to ship —
        let it finish or fall back to :meth:`export_inflight`)."""
        slot = next((s for s, st in self._active.items()
                     if st.request.request_id == request_id), None)
        if slot is None:
            raise KeyError(f"request {request_id!r} is not active on "
                           "this engine")
        if slot in self._prefilling:
            raise ValueError(
                f"request {request_id!r} is mid-prefill; its KV is "
                "incomplete — export_inflight() re-prefills instead")
        st = self._active[slot]
        seq = self._seqs[slot]
        kv_len = st.position
        ids = seq.block_ids[:self.pool.blocks_for(kv_len)]
        handoff = KvHandoff(
            request=st.request,
            generated=list(st.generated),
            kv_len=kv_len,
            kv_tokens=list(st.request.prompt) + list(st.generated[:-1]),
            payload=self.pool.export_blocks(ids),
            block_size=self.pool.block_size,
            kind=self.pool.kind)
        st = self._active.pop(slot)
        self._release(slot, st)
        rid = st.request.request_id
        self._submit_time.pop(rid, None)
        self._progress.pop(rid, None)
        self.metrics.request_migrated(rid)
        self.trace.finish(rid, "migrated")
        return handoff

    def adopt_kv(self, handoff: KvHandoff) -> int:
        """Install a shipped-KV request: acquire blocks for its
        ``kv_tokens``, copy the payload into them, and resume decode at
        ``position = kv_len`` feeding ``generated[-1]`` — no re-prefill.
        Storage tags must match (``kind``, ``block_size``): a bitwise
        install is a literal block copy, so bf16→int8 (or mismatched
        block geometry) must go through the re-prefill fallback
        (:meth:`~apex_tpu.inference.InferenceEngine.adopt`) instead.
        Admission is immediate (no queue pass): raises
        :class:`QueueFull` when no slot or no blocks are available so
        the fleet can retry or fall back, ValueError on tag/context
        misfit.  Returns the slot."""
        req = handoff.request
        if handoff.kind != self.pool.kind:
            raise ValueError(
                f"handoff cache kind {handoff.kind!r} does not match "
                f"this pool ({self.pool.kind!r}); re-prefill via adopt()")
        if handoff.block_size != self.pool.block_size:
            raise ValueError(
                f"handoff block_size {handoff.block_size} does not "
                f"match this pool ({self.pool.block_size})")
        if len(req.prompt) + len(handoff.generated) >= self.max_seq:
            raise ValueError(
                f"context {len(req.prompt)} + {len(handoff.generated)} "
                f"does not fit max_seq={self.max_seq}; finish with "
                "reason='preempted' instead of adopting")
        self._validate(req)
        if "reject_admission" in self.injected_faults:
            raise QueueFull("injected fault: admission rejected at this "
                            "replica")
        if not self._free_slots:
            raise QueueFull("no free decode slot for the KV handoff; "
                            "retry after step() completes one")
        seq = self.pool.acquire(handoff.kv_tokens)
        if seq is None:
            raise QueueFull("no free blocks for the KV handoff; retry "
                            "after decode completions release some")
        # trie-shared prefix blocks already hold bitwise-identical KV
        # (published by an earlier adopt of the same prefix), so the
        # payload rows are only copied for the fresh tail
        start = seq.shared_tokens // self.pool.block_size
        self.pool.import_blocks(
            seq.block_ids[start:],
            {k: v[start:] for k, v in handoff.payload.items()})
        self.pool.register_prefix(seq, handoff.kv_tokens)
        slot = self._free_slots.pop()
        self._admitted += 1
        self._admit_stamp[slot] = self._admitted
        self._seqs[slot] = seq
        self._tables[slot] = self.pool.table_row(seq, self.max_blocks)
        rid = req.request_id
        self._submit_time[rid] = self.clock()
        self.metrics.request_submitted(rid)
        self.trace.enqueue(rid, ctx=req.trace)
        self.trace.admit(rid)
        self.trace.resumed(rid)
        self._draft_admit(slot, handoff.kv_tokens)
        st = _Active(req, len(req.prompt),
                     next_token=handoff.generated[-1],
                     position=handoff.kv_len,
                     generated=list(handoff.generated))
        self._active[slot] = st
        self._maybe_finish(slot, st)
        return slot

    # -- speculative decoding ------------------------------------------------

    def _spec_round(self, decoding: List[int]) -> None:
        k = self.spec.num_tokens
        for slot in list(decoding):
            if slot in self._active and not self._grow(
                    slot,
                    min(self._active[slot].position + k + 1, self.max_seq)):
                self._preempt_slot(slot)
        decoding = [s for s in decoding if s in self._active]
        if not decoding:
            return
        n = self.max_slots
        # 1) draft proposes k tokens (k cheap batched steps), sampling
        #    with the SAME (seed, index) stream the target will replay
        dtok = np.zeros((n,), np.int32)
        dpos = np.zeros((n,), np.int32)
        for s in decoding:
            st = self._active[s]
            dtok[s] = st.next_token
            dpos[s] = st.position
        proposals = np.zeros((n, k), np.int32)
        data = self._draft_cache.data
        cur = dtok
        for j in range(k):
            dlogits, data = self._draft_decode(
                self.spec.params, jnp.asarray(cur), data,
                jnp.asarray(dpos + j))
            dl = np.asarray(dlogits)
            for s in decoding:
                st = self._active[s]
                try:
                    proposals[s, j] = self._sample(
                        st.request, dl[s], len(st.generated) + j)
                except Exception:
                    # a poison sampling config detonates identically in
                    # the verify loop, where quarantine handles it
                    proposals[s, j] = 0
            cur = proposals[:, j]
        # one write-only step: on a full accept (all k proposals + the
        # bonus token) the next round starts at p+k+1, so the draft
        # needs d_k's KV at p+k — without this its later attention reads
        # a stale row there (correctness is unaffected either way; the
        # target verifies everything, this only protects accept rate)
        _, data = self._draft_decode(
            self.spec.params, jnp.asarray(cur), data,
            jnp.asarray(dpos + k))
        self._draft_cache.data = data
        # 2) one (k+1)-wide target chunk verifies [t, d1..dk]
        c = k + 1
        toks = np.zeros((n, c), np.int32)
        pos = np.zeros((n, c), np.int32)
        wb = np.zeros((n, c), np.int32)
        wo = np.zeros((n, c), np.int32)
        bs = self.pool.block_size
        lim = {}
        for s in decoding:
            st = self._active[s]
            seq = self._seqs[s]
            toks[s] = [st.next_token] + list(proposals[s])
            lim[s] = min(c, self.max_seq - st.position)
            for j in range(lim[s]):
                p = st.position + j
                pos[s, j] = p
                wb[s, j] = seq.block_ids[p // bs]
                wo[s, j] = p % bs
        vlogits, self.pool.data = self._chunk(
            self.params, jnp.asarray(toks), self.pool.data,
            jnp.asarray(self._tables), jnp.asarray(pos),
            jnp.asarray(wb), jnp.asarray(wo))
        self.metrics.step(len(decoding), n)
        vl = np.asarray(vlogits)
        # 3) exact-match acceptance: consume canonical tokens while the
        #    draft predicted them; first mismatch (or the bonus final
        #    sample) ends the round
        for s in decoding:
            st = self._active[s]
            seq = self._seqs[s]
            for j in range(lim[s]):
                try:
                    tok = self._sample(st.request, vl[s, j],
                                       len(st.generated))
                except Exception as e:
                    self._finish(s, st, "error",
                                 error=f"{type(e).__name__}: {e}")
                    break
                self.metrics.token(st.request.request_id)
                self.trace.decode_tick(st.request.request_id)
                st.generated.append(tok)
                st.next_token = tok
                st.position += 1
                seq.num_tokens = st.position
                if self._maybe_finish(s, st):
                    break
                if j == lim[s] - 1:
                    break
                self.spec_proposed += 1
                self._c_spec_prop.inc()
                if tok != proposals[s, j]:
                    break               # rejected KV stays masked garbage
                self.spec_accepted += 1
                self._c_spec_acc.inc()

    @property
    def spec_accept_rate(self) -> float:
        return (self.spec_accepted / self.spec_proposed
                if self.spec_proposed else 0.0)
