"""apex_tpu.serving — the production-serving tier on top of inference.

What :mod:`apex_tpu.inference` leaves on the table, this package takes:

* :class:`PagedKVCache` — vLLM-style block-pool KV storage with
  ref-counted, radix-trie-keyed prefix sharing (a fleet's shared system
  prompt is cached ONCE) and copy-on-write forking.
* :class:`PagedInferenceEngine` — the continuous-batching engine over
  the block pool, token-bitwise-identical to the contiguous engine,
  with chunked prefill (:class:`TickScheduler` budgets) and
  exact-match speculative decoding (:class:`SpeculativeConfig`).
* :class:`QuantizedPagedKVCache` — the int8 scale-per-block variant:
  ~4× the concurrent users per byte of KV at a pinned numeric
  tolerance (greedy streams agree on the CI configs), prefix sharing
  and copy-on-write preserved.
* :class:`Router` — SLO-burn-aware multi-replica admission with
  explicit shedding (:class:`RequestShed` + :class:`ShedReason` +
  ``retry_after_s``).
* :mod:`apex_tpu.serving.disagg` — disaggregated prefill/decode
  serving: :class:`DisaggregatedFleet` fronts a prefill pool and a
  decode pool, shipping each request's KV blocks across an explicit
  priced :class:`KvChannel` (``export_kv``/``adopt_kv``,
  token-bitwise, re-prefill fallback on a lost handoff).
* :mod:`apex_tpu.serving.fleet` — fault tolerance: deterministic
  replica fault injection (:class:`ServingFaultInjector`), the
  health-checked :class:`FleetRouter` (retry/backoff, hedging,
  cross-replica migration with token-bitwise resume, and the
  drain/add/remove replica lifecycle the capacity controller in
  :mod:`apex_tpu.resilience.capacity` drives), and the burn-driven
  :class:`DegradationLadder`.

``tools/loadgen.py`` drives the stack under heavy-tail open-loop
traffic (and, with ``--scenario``, under chaos workloads) and reports
TTFT/TPOT/e2e percentiles with per-outcome counts.
"""

from apex_tpu.serving.disagg import DisaggregatedFleet, KvChannel
from apex_tpu.serving.engine import KvHandoff, PagedInferenceEngine
from apex_tpu.serving.fleet import (SERVING_FAULT_KINDS, DegradationLadder,
                                    FleetRouter, ReplicaHealth, ServingFault,
                                    ServingFaultInjector, VirtualClock)
from apex_tpu.serving.paged_kv import (PagedKVCache, PagedSequence,
                                       QuantizedPagedKVCache)
from apex_tpu.serving.router import RequestShed, Router, ShedReason
from apex_tpu.serving.scheduler import TickPlan, TickScheduler
from apex_tpu.serving.speculative import SpeculativeConfig

__all__ = [
    "DisaggregatedFleet",
    "KvChannel",
    "KvHandoff",
    "PagedInferenceEngine",
    "PagedKVCache",
    "PagedSequence",
    "QuantizedPagedKVCache",
    "RequestShed",
    "Router",
    "ShedReason",
    "TickPlan",
    "TickScheduler",
    "SpeculativeConfig",
    "SERVING_FAULT_KINDS",
    "DegradationLadder",
    "FleetRouter",
    "ReplicaHealth",
    "ServingFault",
    "ServingFaultInjector",
    "VirtualClock",
]
