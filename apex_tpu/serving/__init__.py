"""apex_tpu.serving — the production-serving tier on top of inference.

What :mod:`apex_tpu.inference` leaves on the table, this package takes:

* :class:`PagedKVCache` — vLLM-style block-pool KV storage with
  ref-counted, radix-trie-keyed prefix sharing (a fleet's shared system
  prompt is cached ONCE) and copy-on-write forking.
* :class:`PagedInferenceEngine` — the continuous-batching engine over
  the block pool, token-bitwise-identical to the contiguous engine,
  with chunked prefill (:class:`TickScheduler` budgets) and
  exact-match speculative decoding (:class:`SpeculativeConfig`).
* :class:`Router` — SLO-burn-aware multi-replica admission with
  explicit shedding (:class:`RequestShed` + :class:`ShedReason` +
  ``retry_after_s``).
* :mod:`apex_tpu.serving.fleet` — fault tolerance: deterministic
  replica fault injection (:class:`ServingFaultInjector`), the
  health-checked :class:`FleetRouter` (retry/backoff, hedging,
  cross-replica migration with token-bitwise resume, and the
  drain/add/remove replica lifecycle the capacity controller in
  :mod:`apex_tpu.resilience.capacity` drives), and the burn-driven
  :class:`DegradationLadder`.

``tools/loadgen.py`` drives the stack under heavy-tail open-loop
traffic (and, with ``--scenario``, under chaos workloads) and reports
TTFT/TPOT/e2e percentiles with per-outcome counts.
"""

from apex_tpu.serving.engine import PagedInferenceEngine
from apex_tpu.serving.fleet import (SERVING_FAULT_KINDS, DegradationLadder,
                                    FleetRouter, ReplicaHealth, ServingFault,
                                    ServingFaultInjector, VirtualClock)
from apex_tpu.serving.paged_kv import PagedKVCache, PagedSequence
from apex_tpu.serving.router import RequestShed, Router, ShedReason
from apex_tpu.serving.scheduler import TickPlan, TickScheduler
from apex_tpu.serving.speculative import SpeculativeConfig

__all__ = [
    "PagedInferenceEngine",
    "PagedKVCache",
    "PagedSequence",
    "RequestShed",
    "Router",
    "ShedReason",
    "TickPlan",
    "TickScheduler",
    "SpeculativeConfig",
    "SERVING_FAULT_KINDS",
    "DegradationLadder",
    "FleetRouter",
    "ReplicaHealth",
    "ServingFault",
    "ServingFaultInjector",
    "VirtualClock",
]
