"""Paged (block) KV cache with ref-counted copy-on-write prefix sharing.

vLLM-style memory management for the serving engine: instead of one
contiguous ``max_seq`` row per request (``inference.KVCache``), KV lives
in a pool of fixed-size blocks

    ``(num_blocks, layers, 2, block_size, kv_heads, head_dim)``

and each request owns an ordered *block table* mapping logical position
``p`` to ``(table[p // block_size], p % block_size)``.  Admission
allocates ``ceil(len / block_size)`` blocks instead of a whole row, so
memory fragments by at most one block per request and short requests no
longer pin ``max_seq`` worth of HBM.

Prefix sharing: full blocks of prompt tokens are keyed in a radix trie
(node key = the block's token tuple).  A new request whose prompt starts
with an already-cached block chain *shares* those blocks (refcount + 1)
instead of recomputing and rewriting them — a fleet of requests carrying
the same system prompt stores its KV exactly once.  Sharing is safe
bitwise because post-RoPE K/V for a token depends only on the token ids
at and before it (verified by the engine parity tests across prompt
buckets).  The trie itself holds one reference per cached block, so
blocks outlive the request that produced them and are reclaimed lazily:
when the free list runs dry, least-recently-matched leaves are evicted.

Copy-on-write: writes must only ever target blocks with refcount 1.  The
serve loop guarantees this structurally (shared blocks are always *full*
prefix blocks; appends go to the exclusive tail), and :meth:`fork` +
:meth:`ensure_writable` expose the general mechanism for parallel
sampling — a forked sequence shares everything until its first divergent
write, which copies just the written block.

Block 0 is reserved as the *garbage block*: inactive decode-batch rows
point their entire table at it, so their (mathematically garbage) writes
can never corrupt a live block.

Bookkeeping is host-side (python ints and lists, like ``KVCache``); the
pool array is functional and reassigned on every device write.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np


class _TrieNode:
    """One cached full block: ``key`` is the block's token tuple, keyed
    under the parent (so the path from the root spells the prefix)."""

    __slots__ = ("key", "block", "parent", "children", "stamp")

    def __init__(self, key: Tuple[int, ...], block: int,
                 parent: "_TrieNode"):
        self.key = key
        self.block = block
        self.parent = parent
        self.children: Dict[Tuple[int, ...], _TrieNode] = {}
        self.stamp = 0


class PagedSequence:
    """A request's view of the pool: its block table and valid length.

    ``block_ids[i]`` backs logical positions ``[i*bs, (i+1)*bs)``;
    ``shared_tokens`` is the prefix length served from the trie at
    acquire time (those blocks arrived with KV already written).
    """

    __slots__ = ("block_ids", "num_tokens", "shared_tokens")

    def __init__(self, block_ids: List[int], num_tokens: int,
                 shared_tokens: int):
        self.block_ids = block_ids
        self.num_tokens = num_tokens
        self.shared_tokens = shared_tokens


class PagedKVCache:
    """Block pool + block tables + prefix trie for paged decode."""

    def __init__(self, num_blocks: int, block_size: int, layers: int,
                 kv_heads: int, head_dim: int, dtype=jnp.bfloat16,
                 share_prefixes: bool = True, registry=None,
                 name: str = "pool0"):
        if num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is the "
                             "reserved garbage block)")
        if block_size < 1:
            raise ValueError("block_size must be positive")
        self.data = jnp.zeros(
            (num_blocks, layers, 2, block_size, kv_heads, head_dim), dtype)
        self.block_size = block_size
        self.share_prefixes = share_prefixes
        self.name = name
        # block 0 is reserved: never allocated, never freed
        self._ref = np.zeros((num_blocks,), np.int32)
        self._ref[0] = 1
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._root = _TrieNode((), 0, None)  # sentinel; holds no block
        self._clock = 0
        self.prefix_hit_tokens = 0
        self.prefix_lookup_tokens = 0
        self.evicted_blocks = 0
        self.cow_copies = 0
        self._g_free = self._g_used = self._g_shared = None
        self._c_hits = self._c_evict = self._c_cow = None
        if registry is not None:
            self._g_free = registry.gauge(
                "serving_paged_blocks_free", "free pool blocks", ["cache"])
            self._g_used = registry.gauge(
                "serving_paged_blocks_used", "allocated pool blocks",
                ["cache"])
            self._g_shared = registry.gauge(
                "serving_paged_blocks_shared",
                "blocks referenced more than once (prefix sharing / COW)",
                ["cache"])
            self._c_hits = registry.counter(
                "serving_paged_prefix_hit_tokens_total",
                "prompt tokens served from the prefix trie", ["cache"])
            self._c_evict = registry.counter(
                "serving_paged_evicted_blocks_total",
                "cached prefix blocks reclaimed under memory pressure",
                ["cache"])
            self._c_cow = registry.counter(
                "serving_paged_cow_total", "copy-on-write block copies",
                ["cache"])
        self._update_gauges()

    # -- accounting ----------------------------------------------------------

    @property
    def num_blocks(self) -> int:
        return self.data.shape[0]

    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1          # minus the garbage block

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.usable_blocks - len(self._free)

    @property
    def shared_blocks(self) -> int:
        return int(np.sum(self._ref[1:] > 1))

    @property
    def block_bytes(self) -> int:
        return int(np.prod(self.data.shape[1:])) * self.data.dtype.itemsize

    def free_bytes(self) -> int:
        return self.free_blocks * self.block_bytes

    def used_bytes(self) -> int:
        return self.used_blocks * self.block_bytes

    def occupancy(self) -> float:
        return self.used_blocks / self.usable_blocks

    def stats(self) -> Dict[str, Any]:
        return {"free_blocks": self.free_blocks,
                "used_blocks": self.used_blocks,
                "shared_blocks": self.shared_blocks,
                "prefix_hit_tokens": self.prefix_hit_tokens,
                "prefix_lookup_tokens": self.prefix_lookup_tokens,
                "evicted_blocks": self.evicted_blocks,
                "cow_copies": self.cow_copies}

    def _update_gauges(self) -> None:
        if self._g_free is not None:
            self._g_free.set(self.free_blocks, cache=self.name)
            self._g_used.set(self.used_blocks, cache=self.name)
            self._g_shared.set(self.shared_blocks, cache=self.name)

    # -- block-level plumbing ------------------------------------------------

    def blocks_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.block_size)

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _alloc_block(self) -> int:
        bid = self._free.pop()
        self._ref[bid] = 1
        return bid

    def _deref(self, bid: int) -> None:
        self._ref[bid] -= 1
        if self._ref[bid] == 0:
            self._free.append(bid)

    def _reserve(self, n: int) -> bool:
        """Make ``n`` blocks available, evicting cached prefixes LRU-first
        if the free list is short.  False when even a fully-drained trie
        cannot supply them."""
        while len(self._free) < n:
            if not self._evict_one():
                return False
        return True

    def _evict_one(self) -> bool:
        """Drop the least-recently-matched trie *leaf* whose block is held
        only by the trie.  Leaf-first ordering means a parent is never
        reclaimed under a live child (a sequence using the child also
        refs the parent, so the parent is never trie-only first)."""
        victim = None
        stack = [self._root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if (node is not self._root and not node.children
                    and self._ref[node.block] == 1):
                if victim is None or node.stamp < victim.stamp:
                    victim = node
        if victim is None:
            return False
        del victim.parent.children[victim.key]
        self._deref(victim.block)
        self.evicted_blocks += 1
        if self._c_evict is not None:
            self._c_evict.inc(cache=self.name)
        return True

    def flush_prefixes(self) -> int:
        """Drop the ENTIRE prefix trie at once (degradation-ladder
        level 2: shed cached state before shedding requests).  Every
        trie node's reference is released — blocks still held by live
        sequences survive until those release; trie-only blocks return
        to the free list immediately.  Returns the number of trie nodes
        dropped."""
        dropped = 0
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            self._deref(node.block)
            dropped += 1
        self._root.children = {}
        self.evicted_blocks += dropped
        if self._c_evict is not None and dropped:
            self._c_evict.inc(dropped, cache=self.name)
        self._update_gauges()
        return dropped

    # -- sequence lifecycle --------------------------------------------------

    def acquire(self, tokens: Sequence[int]) -> Optional[PagedSequence]:
        """Claim blocks for a context of ``tokens``.

        Matches the longest full-block prefix in the trie (capped so at
        least one context token is left for the caller to actually run —
        a fully-cached context would yield no logits to sample from),
        then allocates fresh exclusive blocks for the rest.  Returns
        None when the pool cannot supply them even after eviction; the
        caller is expected to requeue and retry.  Shared blocks already
        hold their KV — :meth:`write_context_kv` skips them.
        """
        n = len(tokens)
        if n < 1:
            raise ValueError("cannot acquire an empty context")
        bs = self.block_size
        shared: List[int] = []
        if self.share_prefixes:
            node = self._root
            for i in range((n - 1) // bs):
                child = node.children.get(tuple(tokens[i * bs:(i + 1) * bs]))
                if child is None:
                    break
                child.stamp = self._tick()
                shared.append(child.block)
                node = child
        shared_tokens = len(shared) * bs
        fresh_needed = self.blocks_for(n - shared_tokens)
        if not self._reserve(fresh_needed):
            return None
        blocks = shared + [self._alloc_block() for _ in range(fresh_needed)]
        for bid in shared:
            self._ref[bid] += 1
        self.prefix_hit_tokens += shared_tokens
        self.prefix_lookup_tokens += n
        if self._c_hits is not None and shared_tokens:
            self._c_hits.inc(shared_tokens, cache=self.name)
        self._update_gauges()
        return PagedSequence(blocks, n, shared_tokens)

    def register_prefix(self, seq: PagedSequence,
                        tokens: Sequence[int]) -> None:
        """Publish ``seq``'s full context blocks into the trie so later
        requests with the same prompt prefix share them.  Call after the
        blocks' KV is written.  Each newly-published node takes one trie
        reference, which is what keeps the KV alive after ``seq``
        finishes."""
        if not self.share_prefixes:
            return
        bs = self.block_size
        node = self._root
        for i in range(len(tokens) // bs):
            key = tuple(tokens[i * bs:(i + 1) * bs])
            child = node.children.get(key)
            if child is None:
                child = _TrieNode(key, seq.block_ids[i], node)
                node.children[key] = child
                self._ref[seq.block_ids[i]] += 1
            child.stamp = self._tick()
            node = child
        self._update_gauges()

    def release(self, seq: PagedSequence) -> None:
        """Drop ``seq``'s references.  Trie-published blocks stay cached
        (the trie holds its own reference); exclusive blocks return to
        the free list."""
        for bid in seq.block_ids:
            self._deref(bid)
        seq.block_ids = []
        seq.num_tokens = 0
        self._update_gauges()

    def ensure_capacity(self, seq: PagedSequence, n_tokens: int) -> bool:
        """Grow ``seq``'s table to cover ``n_tokens`` logical positions
        (fresh exclusive blocks).  False when the pool is exhausted."""
        need = self.blocks_for(n_tokens) - len(seq.block_ids)
        if need <= 0:
            return True
        if not self._reserve(need):
            return False
        seq.block_ids.extend(self._alloc_block() for _ in range(need))
        self._update_gauges()
        return True

    def ensure_writable(self, seq: PagedSequence, block_index: int) -> int:
        """Copy-on-write: make ``seq.block_ids[block_index]`` exclusively
        owned before a write.  No-op (refcount already 1) on the normal
        serve path; a forked sequence pays one block copy here.  Returns
        the (possibly new) block id; raises MemoryError when the pool
        cannot supply the copy."""
        bid = seq.block_ids[block_index]
        if self._ref[bid] == 1:
            return bid
        if not self._reserve(1):
            raise MemoryError("pool exhausted during copy-on-write")
        new = self._alloc_block()
        self.data = self.data.at[new].set(self.data[bid])
        self._ref[bid] -= 1
        seq.block_ids[block_index] = new
        self.cow_copies += 1
        if self._c_cow is not None:
            self._c_cow.inc(cache=self.name)
        self._update_gauges()
        return new

    def fork(self, seq: PagedSequence) -> Optional[PagedSequence]:
        """Clone ``seq`` sharing every block (parallel sampling: n
        continuations of one prompt).  Writers must call
        :meth:`ensure_writable` on the tail block before appending —
        that is where the copy-on-write actually triggers."""
        for bid in seq.block_ids:
            self._ref[bid] += 1
        self._update_gauges()
        return PagedSequence(list(seq.block_ids), seq.num_tokens,
                             seq.shared_tokens)

    # -- KV movement ---------------------------------------------------------

    def write_context_kv(self, seq: PagedSequence, kv,
                         context_len: int) -> None:
        """Install prefilled KV into ``seq``'s *exclusive* blocks.

        ``kv``: ``(layers, 2, s, kv_heads, head_dim)`` for one sequence
        (``s`` may be bucket-padded beyond ``context_len``).  The shared
        prefix ``[0, seq.shared_tokens)`` is skipped — those blocks
        already hold bitwise-identical KV from the prefill that
        published them, which is precisely the dedup win.
        """
        bs = self.block_size
        start = seq.shared_tokens        # block-aligned by construction
        if context_len <= start:
            return
        full_end = (context_len // bs) * bs
        if full_end > start:
            ids = np.asarray(seq.block_ids[start // bs:full_end // bs])
            sl = kv[:, :, start:full_end].astype(self.data.dtype)
            lyr, two = sl.shape[0], sl.shape[1]
            sl = sl.reshape(lyr, two, len(ids), bs, *sl.shape[3:])
            self.data = self.data.at[ids].set(sl.transpose(2, 0, 1, 3, 4, 5))
        rem = context_len - full_end
        if rem > 0:
            bid = seq.block_ids[full_end // bs]
            self.data = self.data.at[bid, :, :, :rem].set(
                kv[:, :, full_end:context_len].astype(self.data.dtype))

    def table_row(self, seq: Optional[PagedSequence],
                  max_blocks: int) -> np.ndarray:
        """``seq``'s block table padded with the garbage block (0) —
        also the whole row for an inactive slot, so stray decode writes
        land in garbage instead of a live block."""
        row = np.zeros((max_blocks,), np.int32)
        if seq is not None:
            row[:len(seq.block_ids)] = seq.block_ids
        return row

    # -- cross-pool handoff (serving.disagg) ----------------------------------

    kind = "paged"                  # handoff compatibility tag

    def export_blocks(self, block_ids: Sequence[int]) -> Dict[str, Any]:
        """Snapshot ``block_ids``'s raw storage as host arrays — the
        payload a prefill→decode KV handoff ships.  Keys are
        storage-kind-specific; :meth:`import_blocks` on a pool of the
        same :attr:`kind` installs them bitwise."""
        ids = np.asarray(block_ids, np.int32)
        return {"data": np.asarray(self.data[ids])}

    def import_blocks(self, block_ids: Sequence[int],
                      payload: Dict[str, Any]) -> None:
        """Install a :meth:`export_blocks` payload into ``block_ids``
        (exclusively owned blocks of THIS pool)."""
        ids = np.asarray(block_ids, np.int32)
        self.data = self.data.at[ids].set(
            jnp.asarray(payload["data"], self.data.dtype))


class QuantizedPagedKVCache(PagedKVCache):
    """Int8 scale-per-block paged KV cache (EQuARX idiom applied to
    storage): the pool array holds int8 with one f32 scale per
    ``(block, layer, k/v, head)``, cutting KV bytes ~4x vs f32 (~2x vs
    bf16) — roughly double the concurrent users per chip, and the same
    factor off every cross-pool handoff.

    All bookkeeping (refcounts, trie, COW, eviction) is inherited
    unchanged; only storage semantics differ:

    * ``dtype`` becomes the COMPUTE dtype (what dequantization yields
      into the attention gather path); the pool itself is always int8.
    * **Zero-on-alloc invariant**: a block is zeroed (scale reset to
      1.0) when allocated, so positions beyond a sequence's valid
      length are exact zeros.  Whole-block requantization on append is
      then deterministic — a reused block's stale data can never leak
      into a fresh sequence's scale — which is what keeps the quantized
      stream reproducible across replicas with different allocation
      histories (the disaggregated handoff's bitwise guarantee).
    * Copy-on-write copies the scales alongside the block.
    * Shared (refcount > 1) blocks are never requantized — writers only
      ever touch exclusive blocks (the same structural guarantee COW
      relies on), so a published prefix block's quantization is frozen
      and prefix sharing stays bitwise.
    """

    kind = "paged_int8"

    def __init__(self, num_blocks: int, block_size: int, layers: int,
                 kv_heads: int, head_dim: int, dtype=jnp.bfloat16,
                 share_prefixes: bool = True, registry=None,
                 name: str = "pool0"):
        super().__init__(num_blocks, block_size, layers, kv_heads,
                         head_dim, dtype=jnp.int8,
                         share_prefixes=share_prefixes,
                         registry=registry, name=name)
        self.compute_dtype = jnp.dtype(dtype)
        self.scales = jnp.ones((num_blocks, layers, 2, kv_heads),
                               jnp.float32)
        if registry is not None:
            ref_bytes = (int(np.prod(self.data.shape[1:]))
                         * self.compute_dtype.itemsize)
            registry.gauge(
                "serving_kv_quant_compression_ratio",
                "quantized block bytes (incl. scales) over the compute-"
                "dtype block bytes", ["cache"]).set(
                    self.block_bytes / ref_bytes, cache=self.name)

    @property
    def block_bytes(self) -> int:
        scale_bytes = int(np.prod(self.scales.shape[1:])) * 4
        return (int(np.prod(self.data.shape[1:]))
                * self.data.dtype.itemsize + scale_bytes)

    def _alloc_block(self) -> int:
        bid = super()._alloc_block()
        # zero-on-alloc: see the class docstring
        self.data = self.data.at[bid].set(0)
        self.scales = self.scales.at[bid].set(1.0)
        return bid

    def ensure_writable(self, seq: PagedSequence, block_index: int) -> int:
        old = seq.block_ids[block_index]
        new = super().ensure_writable(seq, block_index)
        if new != old:
            self.scales = self.scales.at[new].set(self.scales[old])
        return new

    def write_context_kv(self, seq: PagedSequence, kv,
                         context_len: int) -> None:
        """One-shot per-block quantization of a monolithic prefill's
        KV.  NOTE: this quantizes each block over its final contents in
        one pass, whereas chunked prefill / decode requantize per
        appended token — the two paths are each deterministic but not
        bitwise-equal to each other, so engines that need bitwise
        migration on a quantized cache run chunked prefill everywhere
        (enforced by ``PagedInferenceEngine``)."""
        from apex_tpu.ops.flash_attention import quantize_kv_blocks

        bs = self.block_size
        start = seq.shared_tokens        # block-aligned by construction
        if context_len <= start:
            return
        ids = np.asarray(
            seq.block_ids[start // bs:self.blocks_for(context_len)],
            np.int32)
        sl = np.zeros((kv.shape[0], kv.shape[1], len(ids) * bs,
                       *kv.shape[3:]), np.float32)
        sl[:, :, :context_len - start] = np.asarray(
            kv[:, :, start:context_len], np.float32)
        lyr, two = sl.shape[0], sl.shape[1]
        blocks = jnp.asarray(
            sl.reshape(lyr, two, len(ids), bs, *sl.shape[3:])
        ).transpose(2, 0, 1, 3, 4, 5)   # (n, layers, 2, bs, h, d)
        q8, sc = quantize_kv_blocks(blocks)
        self.data = self.data.at[ids].set(q8)
        self.scales = self.scales.at[ids].set(sc)

    def export_blocks(self, block_ids: Sequence[int]) -> Dict[str, Any]:
        ids = np.asarray(block_ids, np.int32)
        return {"data": np.asarray(self.data[ids]),
                "scales": np.asarray(self.scales[ids])}

    def import_blocks(self, block_ids: Sequence[int],
                      payload: Dict[str, Any]) -> None:
        ids = np.asarray(block_ids, np.int32)
        self.data = self.data.at[ids].set(
            jnp.asarray(payload["data"], jnp.int8))
        self.scales = self.scales.at[ids].set(
            jnp.asarray(payload["scales"], jnp.float32))
