"""SyncBatchNorm — TPU rebuild of ``apex/parallel/optimized_sync_batchnorm.py``
(+ ``csrc/syncbn.cpp``/``csrc/welford.cu`` and the pure-python variant).

Apex computes per-GPU Welford stats with a CUDA kernel, all-gathers
(mean, var, count) across the process group, combines, then normalizes.
The TPU translation: local sums in f32 + one ``psum`` of
``(sum, sum_sq, count)`` over the data-parallel mesh axis inside the jitted
step — mathematically the same chunk-parallel Welford combine, expressed as
a collective the compiler schedules.  Outside ``shard_map``/``pmap`` (plain
GSPMD jit over a batch-sharded array) the plain batch mean IS the global
mean, so the module also works with no axis at all.

``channel_last=True`` treats the trailing axis as channels (apex NHWC);
default layout is NCHW like torch.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from apex_tpu.utils.collectives import axis_size as _axis_size

_f32 = jnp.float32


class BatchNormState(NamedTuple):
    """Running stats (the mutable part of torch BN modules)."""

    running_mean: jax.Array
    running_var: jax.Array
    num_batches_tracked: jax.Array


def _axis_reduce(total, axis_name):
    if axis_name is not None:
        return jax.lax.psum(total, axis_name)
    return total


def sync_batch_norm(x, weight, bias, state: BatchNormState, *,
                    training: bool, momentum: float = 0.1, eps: float = 1e-5,
                    axis_name: Optional[str] = None,
                    channel_last: bool = False,
                    update_running_stats: bool = True):
    """Functional SyncBatchNorm.  Returns ``(y, new_state)``.

    In training mode, batch stats combine across ``axis_name`` (the
    ``process_group`` analogue); running stats update with the *unbiased*
    variance like torch/apex.  ``update_running_stats=False`` still
    normalizes with batch statistics in training mode (torch semantics for
    ``track_running_stats=False``) but leaves ``state`` untouched.
    """
    c_axis = x.ndim - 1 if channel_last else 1
    red_axes = tuple(i for i in range(x.ndim) if i != c_axis)
    shape_bc = [1] * x.ndim
    shape_bc[c_axis] = x.shape[c_axis]

    xf = x.astype(_f32)
    if training:
        count = 1.0
        for i in red_axes:
            count *= x.shape[i]
        local_sum = jnp.sum(xf, axis=red_axes)
        local_sqsum = jnp.sum(xf * xf, axis=red_axes)
        total = _axis_reduce(jnp.stack([local_sum, local_sqsum]), axis_name)
        if axis_name is not None:
            count = count * _axis_size(axis_name)
        mean = total[0] / count
        var = total[1] / count - mean * mean          # biased (normalization)
        unbiased = var * (count / max(count - 1.0, 1.0))
        if update_running_stats:
            new_state = BatchNormState(
                (1 - momentum) * state.running_mean + momentum * mean,
                (1 - momentum) * state.running_var + momentum * unbiased,
                state.num_batches_tracked + 1)
        else:
            new_state = state
    else:
        mean, var = state.running_mean, state.running_var
        new_state = state

    rstd = jax.lax.rsqrt(var + eps)
    y = (xf - mean.reshape(shape_bc)) * rstd.reshape(shape_bc)
    if weight is not None:
        y = y * weight.astype(_f32).reshape(shape_bc)
    if bias is not None:
        y = y + bias.astype(_f32).reshape(shape_bc)
    return y.astype(x.dtype), new_state


class SyncBatchNorm:
    """Module form (apex ``SyncBatchNorm(num_features, ..., process_group,
    channel_last)``).  ``process_group`` maps to a mesh axis name."""

    def __init__(self, num_features, eps=1e-5, momentum=0.1, affine=True,
                 track_running_stats=True, process_group: str | None = None,
                 channel_last: bool = False, fuse_relu: bool = False):
        self.num_features = int(num_features)
        self.eps = float(eps)
        self.momentum = float(momentum)
        self.affine = bool(affine)
        self.track_running_stats = bool(track_running_stats)
        self.axis_name = process_group
        self.channel_last = bool(channel_last)
        self.fuse_relu = bool(fuse_relu)

    def init_params(self):
        if not self.affine:
            return {}
        return {"weight": jnp.ones((self.num_features,), _f32),
                "bias": jnp.zeros((self.num_features,), _f32)}

    def init_state(self) -> BatchNormState:
        return BatchNormState(jnp.zeros((self.num_features,), _f32),
                              jnp.ones((self.num_features,), _f32),
                              jnp.zeros((), jnp.int32))

    def __call__(self, params, state, x, training: bool = True):
        # torch semantics: with track_running_stats=False there are no
        # running stats to fall back on, so batch statistics are used in
        # BOTH train and eval mode (and never written back).
        y, new_state = sync_batch_norm(
            x, params.get("weight") if self.affine else None,
            params.get("bias") if self.affine else None,
            state, training=training or not self.track_running_stats,
            momentum=self.momentum, eps=self.eps, axis_name=self.axis_name,
            channel_last=self.channel_last,
            update_running_stats=self.track_running_stats)
        if self.fuse_relu:
            y = jax.nn.relu(y)
        return y, new_state

    apply = __call__


def convert_syncbn_model(module, process_group: str | None = None,
                         channel_last: bool = False):
    """apex ``convert_syncbn_model``: rewrite BN layers to SyncBatchNorm.

    Operates on this package's module objects: any attribute or nested
    element that is a plain ``SyncBatchNorm``-shaped BN config gets its
    ``axis_name`` set.  For flax users, prefer constructing
    ``SyncBatchNorm`` directly; this helper exists for recipe parity.
    """
    if isinstance(module, SyncBatchNorm):
        module.axis_name = process_group
        module.channel_last = channel_last
        return module
    for name in dir(module):
        if name.startswith("_"):
            continue
        try:
            child = getattr(module, name)
        except AttributeError:
            continue
        if isinstance(child, SyncBatchNorm):
            child.axis_name = process_group
            child.channel_last = channel_last
    return module
