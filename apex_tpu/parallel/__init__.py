"""apex.parallel equivalent: data parallelism over the ICI mesh."""

from apex_tpu.parallel.distributed import (
    DistributedDataParallel,
    Reducer,
    allreduce_gradients,
    DEFAULT_DATA_AXIS,
)
from apex_tpu.parallel.distributed_optimizer import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
)
from apex_tpu.parallel.sync_batchnorm import (
    SyncBatchNorm,
    BatchNormState,
    sync_batch_norm,
    convert_syncbn_model,
)
from apex_tpu.parallel.LARC import LARC, larc

__all__ = [
    "DistributedDataParallel",
    "Reducer",
    "allreduce_gradients",
    "DEFAULT_DATA_AXIS",
    "DistributedFusedAdam",
    "DistributedFusedLAMB",
    "SyncBatchNorm",
    "BatchNormState",
    "sync_batch_norm",
    "convert_syncbn_model",
    "LARC",
    "larc",
]
