"""apex.parallel equivalent: data parallelism over the ICI mesh."""

from apex_tpu.parallel.distributed import (
    DistributedDataParallel,
    Reducer,
    allreduce_gradients,
    DEFAULT_DATA_AXIS,
)
from apex_tpu.parallel.distributed_optimizer import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
)
from apex_tpu.parallel.sync_batchnorm import (
    SyncBatchNorm,
    BatchNormState,
    sync_batch_norm,
    convert_syncbn_model,
)
from apex_tpu.parallel.LARC import LARC, larc
from apex_tpu.parallel.plan import PLAN_VERSION, ParallelPlan

__all__ = [
    "PLAN_VERSION",
    "ParallelPlan",
    "DistributedDataParallel",
    "Reducer",
    "allreduce_gradients",
    "DEFAULT_DATA_AXIS",
    "DistributedFusedAdam",
    "DistributedFusedLAMB",
    "SyncBatchNorm",
    "BatchNormState",
    "sync_batch_norm",
    "convert_syncbn_model",
    "LARC",
    "larc",
]
