"""``ParallelPlan`` — the one validated object every subsystem consumes.

ROADMAP item 1 / ISSUE 11: the parallelism knobs used to be scattered —
``tensor_parallel_size``/``sequence_parallel``/``overlap_chunks``/
``remat`` on :class:`~apex_tpu.models.gpt.GPTConfig` and
:class:`~apex_tpu.models.bert.BertConfig`, ``world_size``/
``allreduce_dtype`` on the distributed optimizers, ``n_virtual`` and the
microbatch count at the ``pipeline_step`` call site, and dp/tp/pp/SP/
zero on :class:`~apex_tpu.resilience.elastic.TopologySpec`.  GSPMD
(arXiv:2105.04663) makes the case for a single plan object consumed
everywhere; this module is that object.

* Every cross-knob rule lives HERE, once: SP needs tp>1,
  ``overlap_chunks`` needs SP, ``zero_shard`` ∈ {1, dp}, the interleaved
  schedule needs ``n_microbatches % pp == 0``, ``n_virtual > 1`` needs
  ``pp > 1``.
* The consumers project it: :meth:`ParallelPlan.model_kwargs` feeds the
  model configs, :meth:`ParallelPlan.optimizer_kwargs` the ZeRO
  optimizers, :meth:`ParallelPlan.topology` the elastic layer (a
  :class:`TopologySpec` is a lossless sub-projection — PR 9 checkpoint
  manifests round-trip unchanged through
  :meth:`ParallelPlan.from_topology`).
* ``tools/autotune.py`` searches the space of valid plans, prunes by
  the memory estimator, ranks by the fitted collective cost model, and
  emits the winner as versioned JSON (:meth:`to_dict` /
  :meth:`from_dict`) that :class:`~apex_tpu.resilience.elastic.
  ElasticTrainer` re-plans onto live.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

__all__ = ["PLAN_VERSION", "ParallelPlan", "apply_plan_to_config"]

# bump when the dict schema changes incompatibly; from_dict refuses
# documents stamped with a different version (missing == pre-plan
# topology dicts, accepted as the TopologySpec projection)
PLAN_VERSION = 1

_ALLREDUCE_DTYPES = (None, "f32", "bf16", "int8")
_REMAT_POLICIES = ("full", "dots")


@dataclasses.dataclass(frozen=True)
class ParallelPlan:
    """One validated description of a training parallelism layout.

    ``dp``/``tp``/``pp`` are the mesh axis sizes (``data``/``model``/
    ``pipe``); ``sequence_parallel`` and ``overlap_chunks`` configure
    the Megatron TP layers; ``n_virtual``/``n_microbatches`` the ring
    pipeline schedule (1F1B when ``n_virtual == 1``, interleaved
    otherwise); ``remat``/``remat_policy`` per-layer activation
    checkpointing; ``zero_shard`` the ZeRO optimizer-state shard factor
    over the data axis (1 = per-leaf fused optimizers, ``dp`` = the
    distributed optimizers); ``allreduce_dtype`` the ZeRO gradient
    reduce-scatter transport (None/'f32' exact, 'bf16'/'int8'
    compressed — see :mod:`apex_tpu.utils.compressed_allreduce`).

    Cross-pod (MPMD, ``apex_tpu.mpmd``): ``n_pods > 1`` splits the
    ``pp`` pipeline stages into contiguous per-pod blocks whose
    boundary edges cross the slow (DCN) tier; ``stage_plans`` — one
    intra-pod SPMD plan per pod (``pp=1``, ``n_pods=1``, same ``dp``)
    — lets pods run heterogeneous tp/SP layouts.  Plans with
    ``n_pods > 1`` are executed by the host-driven
    :class:`~apex_tpu.mpmd.MpmdPipeline`, not the single-program ring
    engine.
    """

    dp: int = 1
    tp: int = 1
    pp: int = 1
    sequence_parallel: bool = False
    overlap_chunks: int = 0
    fused_ffn: bool = False
    n_virtual: int = 1
    n_microbatches: int = 1
    remat: bool = False
    remat_policy: str = "full"
    allreduce_dtype: Optional[str] = None
    zero_shard: int = 1
    n_pods: int = 1
    stage_plans: Optional[tuple] = None

    def __post_init__(self):
        for name in ("dp", "tp", "pp", "n_virtual", "n_microbatches",
                     "zero_shard", "n_pods"):
            v = getattr(self, name)
            if not isinstance(v, (int, np.integer)) or isinstance(v, bool) \
                    or v < 1:
                raise ValueError(
                    f"{name} must be a positive int, got {v!r}")
        if not isinstance(self.overlap_chunks, (int, np.integer)) \
                or self.overlap_chunks < 0:
            raise ValueError(
                f"overlap_chunks must be an int >= 0, got "
                f"{self.overlap_chunks!r}")
        if self.zero_shard not in (1, self.dp):
            raise ValueError(
                f"zero_shard must be 1 or dp ({self.dp}), got "
                f"{self.zero_shard}: ZeRO shards the data axis")
        if self.sequence_parallel and self.tp == 1:
            raise ValueError("sequence_parallel requires tp > 1")
        if self.overlap_chunks > 0 and not self.sequence_parallel:
            raise ValueError(
                "overlap_chunks rings the sequence-parallel "
                "collective/GEMM pairs; it requires "
                "sequence_parallel=True")
        if self.n_virtual > 1 and self.pp == 1:
            raise ValueError(
                "n_virtual > 1 (interleaved schedule) requires pp > 1")
        if self.n_virtual > 1 and self.n_microbatches % self.pp:
            raise ValueError(
                f"interleaved schedule needs n_microbatches % pp == 0, "
                f"got M={self.n_microbatches} pp={self.pp}")
        if self.remat_policy not in _REMAT_POLICIES:
            raise ValueError(
                f"remat_policy must be one of {_REMAT_POLICIES}, got "
                f"{self.remat_policy!r}")
        if self.allreduce_dtype not in _ALLREDUCE_DTYPES:
            raise ValueError(
                f"allreduce_dtype must be one of {_ALLREDUCE_DTYPES}, "
                f"got {self.allreduce_dtype!r}")
        # normalize the exact-transport spelling so plan equality (and
        # the JSON round-trip) has one canonical form
        if self.allreduce_dtype == "f32":
            object.__setattr__(self, "allreduce_dtype", None)
        self._validate_cross_pod()

    def _validate_cross_pod(self):
        if self.pp % self.n_pods:
            raise ValueError(
                f"n_pods ({self.n_pods}) must divide pp ({self.pp}): "
                "cross-pod MPMD assigns each pod a contiguous block of "
                f"pp/n_pods pipeline stages — pick pp a multiple of "
                "n_pods (or drop n_pods for a single-pod ring pipeline)")
        if self.n_pods > 1 and self.n_virtual > 1:
            raise ValueError(
                f"n_virtual ({self.n_virtual}) > 1 does not compose "
                f"with n_pods ({self.n_pods}) > 1: the interleaved "
                "virtual-stage schedule belongs to the single-program "
                "ring engine, while the MPMD engine schedules whole "
                "per-pod stage programs — set n_virtual=1, or keep the "
                "pipeline inside one pod for interleaving")
        if self.stage_plans is None:
            return
        if self.n_pods <= 1:
            raise ValueError(
                f"stage_plans given but n_pods is {self.n_pods}: "
                "per-stage plans describe the intra-pod layout of an "
                "MPMD cross-pod pipeline — set n_pods > 1 (one plan "
                "per pod), or drop stage_plans to run the single-pod "
                "ring engine")
        plans = self.stage_plans
        if isinstance(plans, ParallelPlan) or not isinstance(
                plans, (tuple, list)):
            raise ValueError(
                f"stage_plans must be a sequence of ParallelPlan (one "
                f"per pod), got {type(plans).__name__}")
        plans = tuple(
            p if isinstance(p, ParallelPlan) else ParallelPlan.from_dict(p)
            for p in plans)
        if len(plans) != self.n_pods:
            raise ValueError(
                f"stage_plans has {len(plans)} entries but n_pods is "
                f"{self.n_pods}: exactly one intra-pod plan per pod — "
                "pods without an override should carry an explicit "
                "default plan, not be omitted")
        for i, sp in enumerate(plans):
            if sp.pp != 1 or sp.n_pods != 1 or sp.stage_plans is not None:
                raise ValueError(
                    f"stage_plans[{i}] must be an intra-pod SPMD plan "
                    f"with pp=1 and n_pods=1 (got pp={sp.pp}, "
                    f"n_pods={sp.n_pods}): the cross-pod schedule owns "
                    "the pipeline dimension — nested pipelines/pods are "
                    "not supported; fold extra stages into pp on the "
                    "cross-pod plan instead")
            if sp.dp != self.dp:
                raise ValueError(
                    f"stage_plans[{i}].dp ({sp.dp}) must equal the "
                    f"cross-pod plan's dp ({self.dp}): activations "
                    "cross the DCN per data shard, so every pod must "
                    "slice the batch identically — vary tp/SP per pod, "
                    "not dp")
        object.__setattr__(self, "stage_plans", plans)

    # -- projections ---------------------------------------------------------

    @property
    def n_devices(self) -> int:
        if self.stage_plans is not None:
            # heterogeneous pods: each of the pp stage programs owns
            # its pod's dp x tp worth of devices
            per_pod_stages = self.pp // self.n_pods
            return per_pod_stages * sum(sp.dp * sp.tp
                                        for sp in self.stage_plans)
        return self.dp * self.tp * self.pp

    @property
    def axis_name(self) -> Optional[str]:
        """The TP mesh axis the model layers reduce over (``None`` when
        the plan has no tensor parallelism)."""
        return "model" if self.tp > 1 else None

    def topology(self):
        """Project onto the elastic layer's :class:`~apex_tpu.
        resilience.elastic.TopologySpec` (the PR 9 checkpoint-manifest
        schema — lossless for the fields it carries)."""
        from apex_tpu.resilience.elastic import TopologySpec
        return TopologySpec(dp=self.dp, tp=self.tp, pp=self.pp,
                            sequence_parallel=self.sequence_parallel,
                            zero_shard=self.zero_shard)

    @classmethod
    def from_topology(cls, spec, **overrides) -> "ParallelPlan":
        """Lift a :class:`TopologySpec` (or its manifest dict form) into
        a full plan; ``overrides`` supply the knobs the spec does not
        carry (schedule, remat, transport)."""
        if isinstance(spec, dict):
            return cls.from_dict(spec, **overrides)
        return cls(dp=spec.dp, tp=spec.tp, pp=spec.pp,
                   sequence_parallel=spec.sequence_parallel,
                   zero_shard=spec.zero_shard, **overrides)

    def model_kwargs(self) -> dict:
        """The :class:`GPTConfig`/:class:`BertConfig` knobs this plan
        pins (pass alongside the architecture fields, or just pass
        ``plan=`` — the configs accept the plan object directly)."""
        return {"tensor_parallel_size": self.tp,
                "axis_name": self.axis_name,
                "sequence_parallel": self.sequence_parallel,
                "overlap_chunks": self.overlap_chunks,
                "fused_ffn": self.fused_ffn,
                "remat": self.remat,
                "remat_policy": self.remat_policy}

    def optimizer_kwargs(self) -> dict:
        """Ctor kwargs for the distributed (ZeRO) optimizers: the shard
        factor is ``zero_shard`` and the transport the plan's
        ``allreduce_dtype``."""
        return {"world_size": self.zero_shard,
                "axis_name": "data",
                "allreduce_dtype": self.allreduce_dtype}

    # -- serialization -------------------------------------------------------

    def to_dict(self) -> dict:
        d = {"version": PLAN_VERSION,
             "dp": int(self.dp), "tp": int(self.tp), "pp": int(self.pp),
             "sequence_parallel": bool(self.sequence_parallel),
             "overlap_chunks": int(self.overlap_chunks),
             "n_virtual": int(self.n_virtual),
             "n_microbatches": int(self.n_microbatches),
             "remat": bool(self.remat),
             "remat_policy": str(self.remat_policy),
             "allreduce_dtype": self.allreduce_dtype,
             "zero_shard": int(self.zero_shard)}
        # opt-in fields only when set, so default plan documents stay
        # byte-identical to earlier writers
        if self.fused_ffn:
            d["fused_ffn"] = True
        if self.n_pods != 1:
            d["n_pods"] = int(self.n_pods)
        if self.stage_plans is not None:
            d["stage_plans"] = [sp.to_dict() for sp in self.stage_plans]
        return d

    @classmethod
    def from_dict(cls, d: dict, **overrides) -> "ParallelPlan":
        """Rebuild from :meth:`to_dict` output OR a pre-plan topology
        dict (PR 9 manifests: dp/tp/pp/sequence_parallel/zero_shard, no
        version key) — the fields a topology dict lacks default, so old
        stamped manifests lift losslessly."""
        ver = d.get("version")
        if ver is not None and ver != PLAN_VERSION:
            raise ValueError(
                f"plan version {ver!r} != supported {PLAN_VERSION}; "
                "re-run tools/autotune.py to emit a current plan")
        kw = {"dp": int(d.get("dp", 1)), "tp": int(d.get("tp", 1)),
              "pp": int(d.get("pp", 1)),
              "sequence_parallel": bool(d.get("sequence_parallel", False)),
              "overlap_chunks": int(d.get("overlap_chunks", 0)),
              "fused_ffn": bool(d.get("fused_ffn", False)),
              "n_virtual": int(d.get("n_virtual", 1)),
              "n_microbatches": int(d.get("n_microbatches", 1)),
              "remat": bool(d.get("remat", False)),
              "remat_policy": str(d.get("remat_policy", "full")),
              "allreduce_dtype": d.get("allreduce_dtype"),
              "zero_shard": int(d.get("zero_shard", 1)),
              "n_pods": int(d.get("n_pods", 1))}
        if d.get("stage_plans") is not None:
            kw["stage_plans"] = tuple(
                cls.from_dict(sp) for sp in d["stage_plans"])
        kw.update(overrides)
        return cls(**kw)

    def describe(self) -> str:
        bits = [f"dp={self.dp}", f"tp={self.tp}", f"pp={self.pp}",
                f"sp={'on' if self.sequence_parallel else 'off'}",
                f"zero={self.zero_shard}"]
        if self.overlap_chunks:
            bits.append(f"overlap={self.overlap_chunks}")
        if self.fused_ffn:
            bits.append("ffn=fused")
        if self.pp > 1 or self.n_microbatches > 1:
            bits.append(f"mb={self.n_microbatches}")
        if self.n_virtual > 1:
            bits.append(f"v={self.n_virtual}")
        if self.remat:
            bits.append(f"remat={self.remat_policy}")
        if self.allreduce_dtype:
            bits.append(f"rs={self.allreduce_dtype}")
        if self.n_pods > 1:
            bits.append(f"pods={self.n_pods}")
            if self.stage_plans is not None:
                bits.append("stages=[" + "; ".join(
                    sp.describe() for sp in self.stage_plans) + "]")
        return " ".join(bits)


# -- config back-compat bridge ------------------------------------------------

_CONFIG_KNOBS = ("tensor_parallel_size", "sequence_parallel",
                 "overlap_chunks", "fused_ffn", "remat", "remat_policy")


def apply_plan_to_config(cfg) -> None:
    """Fold ``cfg.plan`` into a model config's per-knob parallelism
    fields (called by ``GPTConfig``/``BertConfig.__post_init__`` before
    their own validation).

    The per-knob kwargs remain the back-compat surface: passing them
    WITHOUT a plan stays silent and builds the internal plan elsewhere.
    Passing a plan AND a conflicting non-default knob is the superseded
    case — the plan wins and a :class:`DeprecationWarning` names the
    knob.  ``axis_name`` defaults from the plan (``"model"`` when
    ``tp > 1``) but an explicit value is kept, so parallel_state-style
    custom axis naming still composes.
    """
    plan = cfg.plan
    if plan is None:
        return
    import warnings
    values = {"tensor_parallel_size": plan.tp,
              "sequence_parallel": plan.sequence_parallel,
              "overlap_chunks": plan.overlap_chunks,
              "fused_ffn": plan.fused_ffn,
              "remat": plan.remat,
              "remat_policy": plan.remat_policy}
    for field in _CONFIG_KNOBS:
        default = cfg.__dataclass_fields__[field].default
        cur, want = getattr(cfg, field), values[field]
        if cur != default and cur != want:
            warnings.warn(
                f"{type(cfg).__name__}.{field}={cur!r} is superseded by "
                f"the attached ParallelPlan ({field}={want!r}); set the "
                "knob on the plan instead", DeprecationWarning,
                stacklevel=4)
        setattr(cfg, field, want)
    if cfg.axis_name is None and plan.tp > 1:
        cfg.axis_name = "model"
