"""Data parallelism — TPU rebuild of ``apex/parallel/distributed.py``.

Apex's ``DistributedDataParallel`` registers per-param backward hooks,
buckets gradients in reverse creation order (``message_size`` bytes per
bucket), flattens them (``apex_C.flatten``) and overlaps NCCL allreduce with
the remaining backward.  On TPU every one of those jobs belongs to the
compiler: gradients produced inside a jitted step with a sharded batch are
reduced by XLA-inserted collectives over ICI, and the XLA latency-hiding
scheduler overlaps them with compute.  What remains for the API is:

* expressing the data-parallel layout (mesh axis, batch sharding,
  replicated params) — :class:`DistributedDataParallel`;
* the explicit-collective path for ``shard_map`` training loops —
  :func:`allreduce_gradients` (= apex's bucketed allreduce, one ``psum``);
* the manual-trigger variant — :class:`Reducer`;
* ``delay_allreduce`` semantics → gradient-accumulation boundary control.

Knobs that only make sense for NCCL stream management
(``num_allreduce_streams``, ``allreduce_communicators``) are accepted and
ignored so apex recipes run unchanged.  ``message_size`` keeps apex's
meaning — a per-bucket BYTE cap — and is honored where buckets become
explicit collectives: the fused/distributed optimizers
(``FusedOptimizer(message_size=...)``,
:mod:`apex_tpu.parallel.distributed_optimizer`).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.utils import compressed_allreduce as _CA
from apex_tpu.utils.collectives import psum_if_varying
from apex_tpu.utils.collectives import axis_size as _axis_size

DEFAULT_DATA_AXIS = "data"


def _has_axis(axis_name) -> bool:
    # Unbound axis names have raised a different exception in nearly
    # every JAX generation: classic NameError, KeyError from the
    # axis-env lookup, ValueError ("unbound axis name"), and TypeError
    # when the frame stack is empty.  Treat them all as "no such axis".
    try:
        jax.lax.axis_index(axis_name)
        return True
    except (NameError, KeyError, ValueError, TypeError):
        return False


def allreduce_gradients(grads, axis_name: str = DEFAULT_DATA_AXIS,
                        average: bool = True, strict: bool = False):
    """Reduce a gradient pytree across the data-parallel axis.

    Inside ``shard_map``/``pmap`` this is one fused ``psum`` over the whole
    pytree (XLA concatenates it into large transfers — the moral equivalent
    of apex's flatten+bucket).  ``average=True`` mirrors apex's
    ``gradient_average`` (divide by world size).

    Leaves that are already device-invariant over a ``shard_map`` axis are
    treated as already-summed gradients (JAX auto-psums grads of replicated
    params): the psum is skipped but averaging still divides by world size.
    This is a gradient-reduction helper, not a general replicated-value
    allreduce; ``strict=True`` raises on device-invariant leaves instead
    of passing them through.
    """
    # Grads computed without mark_local arrive device-INVARIANT — JAX 0.9
    # auto-psummed them during grad-of-replicated-params — and psumming
    # again would multiply by axis size.  Reduce only the varying leaves.
    reduced = psum_if_varying(grads, axis_name, strict=strict)
    if average:
        n = _axis_size(axis_name)
        reduced = jax.tree_util.tree_map(lambda g: g / n, reduced)
    return reduced


class DistributedDataParallel:
    """API-compat DP wrapper (apex ``apex.parallel.DistributedDataParallel``).

    Functional usage over a named mesh::

        mesh = jax.make_mesh((n_devices,), ("data",))
        ddp = DistributedDataParallel(apply_fn, mesh=mesh)
        params = ddp.broadcast_params(params)       # replicate (init bcast)
        batch  = ddp.scatter(batch)                 # shard along batch dim
        # inside jit: grads come out correct — GSPMD inserts the reduction

    For explicit-collective loops (``shard_map``), use
    ``ddp.reduce(grads)`` where apex called the bucketed allreduce.

    ``delay_allreduce=True`` (apex: allreduce only at the end of backward)
    maps to gradient accumulation: accumulate with ``ddp.accumulate`` and
    reduce once via ``ddp.reduce`` at the boundary.
    """

    def __init__(self, module: Optional[Callable] = None, *,
                 mesh: Optional[Mesh] = None,
                 axis_name: str = DEFAULT_DATA_AXIS,
                 message_size: int = 10_000_000,
                 delay_allreduce: bool = False,
                 shared_param: bool = None,
                 allreduce_trigger_params=None,
                 retain_allreduce_buffers: bool = False,
                 allreduce_always_fp32: bool = False,
                 num_allreduce_streams: int = 1,
                 allreduce_communicators=None,
                 gradient_average: bool = True,
                 gradient_predivide_factor: float = 1.0,
                 allreduce_dtype=None,
                 prof: bool = False):
        del (shared_param, allreduce_trigger_params,
             retain_allreduce_buffers, num_allreduce_streams,
             allreduce_communicators, prof)  # NCCL-only knobs
        # message_size is apex's per-bucket BYTE cap.  DDP's own reduce is
        # one fused psum (XLA chunks it), so the cap matters only where
        # buckets become explicit collectives: kept here so recipes can
        # forward it to the distributed optimizers, which honor it
        # (FusedOptimizer(message_size=...), dtype-aware bytes).
        self.message_size = int(message_size)
        self.module = module
        self.mesh = mesh
        self.axis_name = axis_name
        self.delay_allreduce = bool(delay_allreduce)
        self.allreduce_always_fp32 = bool(allreduce_always_fp32)
        self.gradient_average = bool(gradient_average)
        self.gradient_predivide_factor = float(gradient_predivide_factor)
        self.allreduce_dtype = _CA.check_mode(allreduce_dtype)
        if self.allreduce_dtype is not None and mesh is None:
            raise ValueError(
                "allreduce_dtype={!r} needs the compressed collectives' "
                "static world size — pass mesh= so it can be read from "
                "mesh.shape[axis_name]".format(allreduce_dtype))

    # -- GSPMD path --------------------------------------------------------

    def broadcast_params(self, params):
        """Replicate params across the mesh (apex: init-time
        ``flat_dist_call`` broadcast from rank 0)."""
        if self.mesh is None:
            return params
        repl = NamedSharding(self.mesh, P())
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, repl), params)

    def scatter(self, batch):
        """Shard a host batch along its leading dim over the data axis."""
        if self.mesh is None:
            return batch
        sh = NamedSharding(self.mesh, P(self.axis_name))
        return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), batch)

    def __call__(self, params, *args, **kwargs):
        if self.module is None:
            raise ValueError("DistributedDataParallel wrapped no module")
        return self.module(params, *args, **kwargs)

    # -- explicit-collective path (shard_map) ------------------------------

    def mark_local(self, params):
        """Mark replicated params device-varying inside ``shard_map``.

        JAX's varying-axes tracking makes ``jax.grad`` w.r.t. *replicated*
        inputs insert the cross-device ``psum`` automatically (the transpose
        of the implicit broadcast).  To reproduce apex's DDP staging — local
        gradients first, one explicit bucketed allreduce after — cast params
        to varying before ``jax.grad``, then call :meth:`reduce` yourself::

            def step(params, x, y):
                params = ddp.mark_local(params)
                grads = jax.grad(loss_fn)(params, x, y)   # local grads
                grads = ddp.reduce(grads)                 # ONE allreduce
                ...

        Skip both calls and grads come out already summed (not averaged) —
        the compiler-managed path.
        """
        if not hasattr(jax.lax, "pcast"):
            # pre-vma JAX: every shard_map value is implicitly varying —
            # grads already come out local, nothing to mark
            return params
        return jax.tree_util.tree_map(
            lambda x: jax.lax.pcast(x, self.axis_name, to="varying"), params)

    def _psum_grads(self, grads):
        # one fused psum, or the compressed all-reduce when
        # allreduce_dtype asks for bf16/int8 transport
        if self.allreduce_dtype is None:
            return psum_if_varying(grads, self.axis_name)
        world = int(self.mesh.shape[self.axis_name])
        return _CA.psum_tree_compressed(grads, self.axis_name, world,
                                        self.allreduce_dtype)

    def reduce(self, grads):
        """The bucketed allreduce, as one collective (use inside
        ``shard_map``).  Transport follows the constructor's
        ``allreduce_dtype`` (None/'f32' exact, 'bf16'/'int8' compressed —
        see :mod:`apex_tpu.utils.compressed_allreduce`)."""
        if self.allreduce_always_fp32:
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), grads)
        factor = self.gradient_predivide_factor
        if factor != 1.0:
            # apex staging: divide by `factor` before the reduce
            # unconditionally (fp16 overflow safety), then by `world/factor`
            # after only when averaging — net sum/factor otherwise.
            grads = jax.tree_util.tree_map(lambda g: g / factor, grads)
            out = self._psum_grads(grads)
            if self.gradient_average:
                n = _axis_size(self.axis_name)
                out = jax.tree_util.tree_map(lambda g: g * (factor / n), out)
            return out
        out = self._psum_grads(grads)
        if self.gradient_average:
            n = _axis_size(self.axis_name)
            out = jax.tree_util.tree_map(lambda g: g / n, out)
        return out

    @staticmethod
    def accumulate(acc, grads, main_grad_dtype=None):
        """Microbatch gradient accumulation (``delay_allreduce`` interior).

        ``main_grad_dtype=jnp.float32`` reproduces apex's
        ``gradient_accumulation_fusion`` / ``main_grad`` contract: each
        microbatch's (possibly bf16) grads are accumulated into an fp32
        buffer (reference ``fused_weight_gradient_mlp_cuda`` accumulates
        the wgrad GEMM into ``weight.main_grad`` in fp32).
        """
        def cast(g):
            return g if main_grad_dtype is None else \
                g.astype(main_grad_dtype)
        if acc is None:
            return jax.tree_util.tree_map(cast, grads)
        return jax.tree_util.tree_map(
            lambda a, g: a + cast(g), acc, grads)


class Reducer:
    """Manual-trigger allreduce helper (apex ``apex.parallel.Reducer``):
    call ``reduce`` on whatever pytree you like, when you like."""

    def __init__(self, module_or_grads_list=None,
                 axis_name: str = DEFAULT_DATA_AXIS):
        self.axis_name = axis_name

    def reduce(self, tree, average: bool = True):
        return allreduce_gradients(tree, self.axis_name, average=average)
