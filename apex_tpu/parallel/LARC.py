"""LARC — TPU rebuild of ``apex/parallel/LARC.py``.

Layer-wise Adaptive Rate Clipping/Scaling: per-tensor adaptive lr
``η·‖p‖/(‖g‖ + wd·‖p‖)``, either clipped against the base lr (``clip=True``)
or used as a pure scale.  Apex implements it as an optimizer wrapper that
rewrites each param group's gradients before the inner ``step``; the same
shape here — :class:`LARC` wraps a fused optimizer and rescales the gradient
pytree per tensor — plus an optax ``larc`` transform for native JAX loops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_f32 = jnp.float32


def _larc_scale(p, g, lr, trust_coefficient, clip, eps, weight_decay):
    pn = jnp.linalg.norm(p.astype(_f32))
    gn = jnp.linalg.norm(g.astype(_f32))
    adaptive = trust_coefficient * pn / (gn + weight_decay * pn + eps)
    # apex guards: params with zero norm or zero grad keep the base lr
    adaptive = jnp.where((pn > 0) & (gn > 0), adaptive, lr)
    if clip:
        scale = jnp.minimum(adaptive / lr, 1.0)
    else:
        scale = adaptive / lr
    return scale


class LARC:
    """Wrapper: ``LARC(FusedSGD(lr=...), trust_coefficient=0.02)``.

    ``step(grads, params, state)`` rescales each gradient tensor by the LARC
    factor then delegates to the wrapped optimizer (which applies weight
    decay itself, like apex's flow where LARC zeroes group wd and folds it
    into the gradient)."""

    def __init__(self, optimizer, trust_coefficient: float = 0.02,
                 clip: bool = True, eps: float = 1e-8):
        self.optimizer = optimizer
        self.trust_coefficient = float(trust_coefficient)
        self.clip = bool(clip)
        self.eps = float(eps)

    def init(self, params):
        return self.optimizer.init(params)

    def step(self, grads, params, state, *, lr=None, **kw):
        base_lr = lr if lr is not None else self.optimizer.defaults["lr"]
        wd = self.optimizer.defaults.get("weight_decay", 0.0)

        def rescale(p, g):
            s = _larc_scale(p, g, base_lr, self.trust_coefficient,
                            self.clip, self.eps, wd)
            # apex folds wd into the grad, then scales: g' = s*(g + wd*p)
            gf = g.astype(_f32) + wd * p.astype(_f32)
            return (s * gf).astype(g.dtype)

        if wd != 0.0:
            grads = jax.tree_util.tree_map(rescale, params, grads)
            # inner optimizer must not double-apply decay
            kw = dict(kw)
            saved_wd = self.optimizer.defaults["weight_decay"]
            self.optimizer.defaults["weight_decay"] = 0.0
            try:
                out = self.optimizer.step(grads, params, state, lr=lr, **kw)
            finally:
                self.optimizer.defaults["weight_decay"] = saved_wd
            return out
        grads = jax.tree_util.tree_map(
            lambda p, g: (_larc_scale(p, g, base_lr,
                                      self.trust_coefficient, self.clip,
                                      self.eps, 0.0)
                          * g.astype(_f32)).astype(g.dtype),
            params, grads)
        return self.optimizer.step(grads, params, state, lr=lr, **kw)


def larc(trust_coefficient: float = 0.02, clip: bool = True,
         eps: float = 1e-8, weight_decay: float = 0.0, learning_rate=1.0):
    """optax-style gradient transformation applying LARC scaling."""
    import optax

    def init_fn(params):
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("larc requires params")

        def rescale(p, g):
            s = _larc_scale(p, g, learning_rate, trust_coefficient, clip,
                            eps, weight_decay)
            return (s * g.astype(_f32)).astype(g.dtype)

        return jax.tree_util.tree_map(rescale, params, updates), state

    return optax.GradientTransformation(init_fn, update_fn)
