"""ZeRO-style distributed fused optimizers — TPU rebuild of
``apex/contrib/optimizers/distributed_fused_adam.py`` and
``distributed_fused_lamb.py`` (+ their ``multi_tensor_distopt_*`` CUDA
helpers).

The reference pipeline is: bucketed reduce-scatter of gradients during
backward, each rank runs the fused update on its shard of params +
optimizer state, then an all-gather of updated params.  The TPU-native
equivalent keeps exactly that dataflow but over the packed ``(rows, 128)``
multi-tensor buckets the fused-optimizer engine already uses:

* buckets are padded to ``block_rows * world_size`` rows so each device
  owns ``rows / world_size`` whole kernel blocks;
* grads: one reduce-scatter (tiled) per bucket over the data axis — the
  XLA collective riding ICI, optionally with compressed transport
  (``allreduce_dtype`` — see :mod:`apex_tpu.utils.compressed_allreduce`);
* the fused Pallas update runs on the local shard only (optimizer state —
  moments, master weights — exists ONLY as ``1/world_size`` shards, the
  ZeRO memory saving);
* params: one ``lax.all_gather`` (tiled) per bucket, always exact —
  quantizing the gather would write rounding error straight into the
  weights every step, so compression applies to gradients only (upstream
  ``DistributedFusedAdam`` likewise gathers params at full precision).

``init``/``step`` are written to run INSIDE ``shard_map`` over the data
axis, params replicated, grads device-varying (the per-device microbatch
gradients — no prior allreduce needed, the scatter IS the reduction).
The gathered params are replicated in value but conservatively
device-varying in JAX's vma typing, which requires running the region
with replication checking off (``check_vma=False`` / ``check_rep=False``
depending on JAX generation — ``shard_map_compat`` picks the spelling).

**Use :meth:`~_DistributedMixin.make_init` /
:meth:`~_DistributedMixin.make_step` rather than wrapping by hand**: they
own that unchecked shard_map region — validating the mesh axis, the
stacked-gradient shapes, and the param/grad tree agreement loudly at
trace time — and return jitted callables.  (Hand-wrapping remains
supported for embedding the step inside a larger shard_map region, e.g.
a full train step; ``tests/test_distributed_optimizers.py`` keeps the
manual recipe covered.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import bucketing as B
from apex_tpu.optimizers.base import _f32
from apex_tpu.optimizers.fused_adam import FusedAdam
from apex_tpu.optimizers.fused_lamb import FusedLAMB
from apex_tpu.utils import compressed_allreduce as CA
from apex_tpu.utils.collectives import shard_map_compat

__all__ = ["DistributedFusedAdam", "DistributedFusedLAMB"]


class _DistributedMixin:
    """Reduce-scatter → local fused update → all-gather over ``axis_name``."""

    # the packed (rows, 128) buckets ARE the ZeRO sharding unit, so the
    # distributed subclasses keep bucketed as their default even though
    # the single-chip base default is per-leaf
    _default_bucketed = True

    @staticmethod
    def _resolve_plan(plan, world_size, allreduce_dtype):
        """Fold a :class:`~apex_tpu.parallel.plan.ParallelPlan` into the
        ctor's per-knob args.  The per-knob kwargs stay the back-compat
        surface (silent without a plan); a non-default knob that
        CONFLICTS with the attached plan is superseded — the plan wins
        and a DeprecationWarning names it."""
        if plan is None:
            return world_size, allreduce_dtype
        import warnings
        kw = plan.optimizer_kwargs()
        if world_size != 1 and world_size != kw["world_size"]:
            warnings.warn(
                f"world_size={world_size} is superseded by the attached "
                f"ParallelPlan (zero_shard={kw['world_size']}); set "
                "zero_shard on the plan instead", DeprecationWarning,
                stacklevel=3)
        if allreduce_dtype is not None \
                and allreduce_dtype != kw["allreduce_dtype"]:
            warnings.warn(
                f"allreduce_dtype={allreduce_dtype!r} is superseded by "
                f"the attached ParallelPlan "
                f"({kw['allreduce_dtype']!r})", DeprecationWarning,
                stacklevel=3)
        return kw["world_size"], kw["allreduce_dtype"]

    def _dist_init(self, world_size, axis_name, average_grads,
                   allreduce_dtype=None, plan=None):
        world_size, allreduce_dtype = self._resolve_plan(
            plan, world_size, allreduce_dtype)
        self.plan = plan
        if world_size < 1:
            raise ValueError("world_size must be >= 1")
        self.world_size = int(world_size)
        self.axis_name = axis_name
        self.average_grads = bool(average_grads)
        self.allreduce_dtype = CA.check_mode(allreduce_dtype)
        # ZeRO sharding IS the packed layout: the reduce-scatter /
        # all-gather shard whole (rows, 128) blocks.  The per-leaf
        # layout has nothing to shard evenly — force bucketed.
        if not self.bucketed:
            raise ValueError(
                "distributed (ZeRO) optimizers require bucketed=True — "
                "the packed (rows, 128) buckets are what reduce-scatter/"
                "all-gather shard")

    def _meta_block_rows(self):
        return self.block_rows * self.world_size

    def _local_rows(self, info):
        return info.meta.nrows // self.world_size

    # -- state --------------------------------------------------------------

    def init(self, params):
        """Per-device state SHARDS (call inside ``shard_map``; out_specs
        ``state_specs()`` reassemble the global row-sharded buckets)."""
        layout = self._layout(params)
        leaves = jax.tree_util.tree_leaves(params)
        rank = jax.lax.axis_index(self.axis_name)
        buckets = {}
        for info in layout.buckets:
            rows = self._local_rows(info)
            st = {k: jnp.zeros((rows, 128), _f32)
                  for k in self._moment_keys()}
            if self.master_weights and info.meta.dtype != _f32:
                f32_meta = info.meta._replace(dtype=_f32)
                full = B.flatten_bucket([leaves[i] for i in info.indices],
                                        f32_meta)
                st["master"] = jax.lax.dynamic_slice(
                    full, (rank * rows, 0), (rows, 128))
            buckets[info.key] = st
        return {"step": jnp.zeros((), jnp.int32), "buckets": buckets}

    def _full_master_bucket(self, packed_master):
        # master buckets are ROW SHARDS here; all-gather to the full
        # rows before the base class unflattens (call master_params
        # inside shard_map, like step)
        return jax.lax.all_gather(packed_master, self.axis_name, axis=0,
                                  tiled=True)

    def state_specs(self, params):
        """PartitionSpec pytree for ``shard_map`` out/in_specs: moment and
        master buckets row-sharded over the data axis, step replicated —
        the per-device footprint IS ``1/world_size`` of the global state."""
        from jax.sharding import PartitionSpec as P
        layout = self._layout(params)
        buckets = {}
        for info in layout.buckets:
            keys = list(self._moment_keys())
            if self.master_weights and info.meta.dtype != _f32:
                keys.append("master")
            buckets[info.key] = {k: P(self.axis_name) for k in keys}
        return {"step": P(), "buckets": buckets}

    # -- step ---------------------------------------------------------------

    def step(self, grads, params, state, *, lr=None, grad_scale=1.0,
             noop_flag=None):
        ax = self.axis_name
        layout = self._layout(params)
        p_leaves, treedef = jax.tree_util.tree_flatten(params)
        g_leaves = jax.tree_util.tree_leaves(grads)
        rank = jax.lax.axis_index(ax)
        noop = (None if noop_flag is None
                else jnp.asarray(noop_flag).reshape(()))
        step_count = state["step"] + 1
        if noop is not None:
            step_count = state["step"] + (noop == 0).astype(jnp.int32)

        packed_local = {}
        for info in layout.buckets:
            gs = [g_leaves[i] for i in info.indices]
            g_meta = info.meta._replace(dtype=jnp.dtype(gs[0].dtype))
            g_full = B.flatten_bucket(gs, g_meta)
            # the reduce-scatter IS the DDP gradient reduction (ZeRO-2);
            # allreduce_dtype selects exact vs compressed transport
            g_loc = CA.reduce_scatter(g_full, ax, self.world_size,
                                      self.allreduce_dtype)
            if self.average_grads:
                g_loc = g_loc / self.world_size
            packed_local[info.key] = g_loc

        extras = self._pre_step_sharded(layout, packed_local, state, lr=lr,
                                        grad_scale=grad_scale)
        new_p_leaves = list(p_leaves)
        new_buckets = {}
        for info in layout.buckets:
            bucket_state = dict(state["buckets"][info.key])
            rows = self._local_rows(info)
            use_master = "master" in bucket_state
            if use_master:
                p_meta = info.meta._replace(dtype=_f32)
                p_loc = bucket_state["master"]
            else:
                p_meta = info.meta
                p_full = B.flatten_bucket(
                    [p_leaves[i] for i in info.indices], p_meta)
                p_loc = jax.lax.dynamic_slice(p_full, (rank * rows, 0),
                                              (rows, 128))
            hyper = self._hyper(info.group, lr)
            new_p_loc, new_bucket = self._update_bucket_sharded(
                info, packed_local[info.key], p_loc, bucket_state, hyper,
                step_count, grad_scale, noop, extras, rank)
            if use_master:
                new_bucket["master"] = new_p_loc
            new_buckets[info.key] = new_bucket
            new_p_full = jax.lax.all_gather(new_p_loc, ax, axis=0,
                                            tiled=True)
            outs = B.unflatten_bucket(new_p_full, p_meta)
            for i, t in zip(info.indices, outs):
                new_p_leaves[i] = t.astype(p_leaves[i].dtype)
        new_params = jax.tree_util.tree_unflatten(treedef, new_p_leaves)
        return new_params, {"step": step_count, "buckets": new_buckets}

    # -- owned shard_map region ---------------------------------------------

    def _check_mesh(self, mesh):
        ax = self.axis_name
        if ax not in mesh.axis_names:
            raise ValueError(
                f"optimizer reduces over axis {ax!r} but the mesh has axes "
                f"{tuple(mesh.axis_names)}; pass axis_name={ax!r} at "
                "construction or build the mesh with that axis")
        size = mesh.shape[ax]
        if size != self.world_size:
            raise ValueError(
                f"optimizer was built with world_size={self.world_size} "
                f"but mesh axis {ax!r} has size {size}; the ZeRO shards "
                "must match the mesh")

    def _check_stacked_grads(self, grads, params):
        p_tree = jax.tree_util.tree_structure(params)
        g_tree = jax.tree_util.tree_structure(grads)
        if p_tree != g_tree:
            raise ValueError(
                f"grads tree {g_tree} does not match params tree {p_tree}")

        def chk(path, g, p):
            want = (self.world_size,) + p.shape
            if g.shape != want:
                raise ValueError(
                    f"grad leaf {jax.tree_util.keystr(path)} has shape "
                    f"{g.shape}, expected {want}: make_step takes STACKED "
                    "per-device gradients (leading axis = the "
                    f"{self.axis_name!r} mesh axis, one microbatch grad "
                    "per device — the reduce-scatter inside the step IS "
                    "the DDP reduction).  For grads already reduced or "
                    "produced inside your own shard_map region, call "
                    ".step there instead.")

        jax.tree_util.tree_map_with_path(chk, grads, params)

    def make_init(self, mesh):
        """Jitted state init owning the unchecked shard_map region;
        returns per-device ZeRO state shards laid out by
        :meth:`state_specs`."""
        from jax.sharding import PartitionSpec as P
        self._check_mesh(mesh)

        def init(params):
            return shard_map_compat(
                self.init, mesh=mesh, in_specs=(P(),),
                out_specs=self.state_specs(params))(params)

        return jax.jit(init)

    def make_step(self, mesh, donate=False):
        """Jitted ZeRO step owning the unchecked shard_map region (the
        API form of the recipe this module's docstring used to hand
        users).

        The returned callable is
        ``step(grads, params, state, lr=None, grad_scale=1.0,
        noop_flag=None) -> (new_params, new_state)`` where ``grads`` are
        the STACKED per-device microbatch gradients: leading axis =
        ``world_size`` (sharded over the optimizer's mesh axis), one
        unreduced gradient per device — the step's reduce-scatter is the
        gradient reduction.  Misuse (wrong mesh axis, unstacked grads,
        mismatched trees) raises at trace time with a message naming the
        offending leaf.  ``donate=True`` donates params+state buffers.
        """
        from jax.sharding import PartitionSpec as P
        self._check_mesh(mesh)
        ax = self.axis_name

        def step(grads, params, state, lr=None, grad_scale=1.0,
                 noop_flag=None):
            self._check_stacked_grads(grads, params)
            specs = self.state_specs(params)
            g_specs = jax.tree_util.tree_map(lambda _: P(ax), grads)
            # lr=None must REACH self.step as None — a concrete default
            # would read as an explicit override in _hyper and stomp
            # per-group lr settings
            lr_args = () if lr is None else (jnp.asarray(lr, _f32),)
            gs_val = jnp.asarray(grad_scale, _f32)
            # an explicit zero noop flag is the identity: the kernels'
            # select keeps the updated values and step_count advances
            noop = (jnp.zeros((), _f32) if noop_flag is None
                    else jnp.reshape(jnp.asarray(noop_flag, _f32), ()))

            def local(g, p, s, gs_, noop_, *lr_):
                g = jax.tree_util.tree_map(lambda x: x[0], g)
                return self.step(g, p, s,
                                 lr=lr_[0] if lr_ else None,
                                 grad_scale=gs_, noop_flag=noop_)

            return shard_map_compat(
                local, mesh=mesh,
                in_specs=(g_specs, P(), specs, P(), P())
                         + (P(),) * len(lr_args),
                out_specs=(P(), specs))(
                    grads, params, state, gs_val, noop, *lr_args)

        return jax.jit(step, donate_argnums=(1, 2) if donate else ())

    # -- subclass hooks ------------------------------------------------------

    def _moment_keys(self):
        return ("m", "v")

    def _pre_step_sharded(self, layout, packed_local, state, *, lr,
                          grad_scale):
        return None

    def _update_bucket_sharded(self, info, g_loc, p_loc, bucket_state,
                               hyper, step_count, grad_scale, noop, extras,
                               rank):
        # element-wise updates (Adam) are shard-oblivious
        return self._update_bucket(info, g_loc, p_loc, bucket_state, hyper,
                                   step_count, grad_scale, noop, extras)


class DistributedFusedAdam(_DistributedMixin, FusedAdam):
    """ZeRO-sharded FusedAdam (apex ``DistributedFusedAdam``).

    ``DistributedFusedAdam(lr=..., world_size=N, axis_name="data")``;
    run ``init``/``step`` inside ``shard_map`` over the data axis.
    ``allreduce_dtype`` in ``{None/'f32', 'bf16', 'int8'}`` selects the
    gradient reduce-scatter transport (see
    :mod:`apex_tpu.utils.compressed_allreduce`).  ``plan`` (a
    :class:`~apex_tpu.parallel.plan.ParallelPlan`) supplies
    ``world_size``/``allreduce_dtype`` from its
    ``zero_shard``/transport fields instead.
    """

    def __init__(self, params=None, lr=1e-3, world_size=1,
                 axis_name="data", average_grads=True,
                 allreduce_dtype=None, plan=None, **kw):
        super().__init__(params, lr=lr, **kw)
        self._dist_init(world_size, axis_name, average_grads,
                        allreduce_dtype, plan=plan)


class DistributedFusedLAMB(_DistributedMixin, FusedLAMB):
    """ZeRO-sharded FusedLAMB (apex ``DistributedFusedLAMB``, the
    MLPerf-BERT full-pod optimizer).

    Cross-shard couplings are handled explicitly: the global grad-norm
    clip is a ``psum`` of per-shard sums; the per-tensor trust ratios need
    per-tensor ‖p‖/‖u‖ over tensors that straddle shard boundaries, so the
    per-ROW partial sums (tiny: ``rows × 1``) are all-gathered and reduced
    against the full row→tensor map, then the ratios are applied to the
    local rows only (apex: clip-after-allreduce + two-stage
    ``multi_tensor_lamb``).  ``allreduce_dtype``/``plan`` select the
    gradient reduce-scatter transport and shard factor, same as
    :class:`DistributedFusedAdam`.
    """

    def __init__(self, params=None, lr=1e-3, world_size=1,
                 axis_name="data", average_grads=True,
                 allreduce_dtype=None, plan=None, **kw):
        super().__init__(params, lr=lr, **kw)
        self._dist_init(world_size, axis_name, average_grads,
                        allreduce_dtype, plan=plan)

    def _pre_step_sharded(self, layout, packed_local, state, *, lr,
                          grad_scale):
        from apex_tpu.ops import multi_tensor as K
        total_sq = jnp.zeros((), _f32)
        for info in layout.buckets:
            rowsq, _ = K.l2norm_rowsq_packed(packed_local[info.key],
                                             block_rows=self.block_rows)
            total_sq = total_sq + jnp.sum(rowsq)
        total_sq = jax.lax.psum(total_sq, self.axis_name)
        gnorm = jnp.sqrt(total_sq) * jnp.asarray(grad_scale, _f32)
        max_norm = jnp.asarray(self.defaults["max_grad_norm"], _f32)
        clip = jnp.where(gnorm > max_norm, max_norm / gnorm, 1.0)
        return {"global_grad_clip": clip}

    def _update_bucket_sharded(self, info, g, p, st, hyper, step_count,
                               grad_scale, noop, extras, rank):
        from apex_tpu.multi_tensor_apply.functional import _row_ids_cached
        from apex_tpu.ops import multi_tensor as K
        from apex_tpu.optimizers.base import per_tensor_sums

        beta1, beta2 = hyper["betas"]
        if hyper["bias_correction"]:
            t = step_count.astype(_f32)
            bc1 = 1.0 - beta1 ** t
            bc2 = 1.0 - beta2 ** t
        else:
            bc1 = bc2 = 1.0
        u, m_new, v_new, usq, psq = K.lamb_stage1_packed(
            g, p, st["m"], st["v"], beta1=beta1, beta2=beta2,
            eps=hyper["eps"], weight_decay=hyper["weight_decay"],
            bias_correction1=bc1, bias_correction2=bc2,
            grad_scale=grad_scale,
            global_grad_clip=extras["global_grad_clip"],
            grad_averaging=hyper["grad_averaging"],
            adam_w_mode=hyper["adam_w_mode"], noop_flag=noop,
            block_rows=self.block_rows)
        # per-tensor norms across ALL shards: gather the (rows, 1) row
        # sums (negligible traffic), reduce on the full row→tensor map
        usq_full = jax.lax.all_gather(usq, self.axis_name, axis=0,
                                      tiled=True)
        psq_full = jax.lax.all_gather(psq, self.axis_name, axis=0,
                                      tiled=True)
        p_norm = jnp.sqrt(per_tensor_sums(info.meta, psq_full))
        u_norm = jnp.sqrt(per_tensor_sums(info.meta, usq_full))
        if hyper["use_nvlamb"]:
            ratio = jnp.where(u_norm > 0, p_norm / u_norm, 1.0)
        else:
            ratio = jnp.where((p_norm > 0) & (u_norm > 0),
                              p_norm / u_norm, 1.0)
        rows = self._local_rows(info)
        ids = jnp.asarray(_row_ids_cached(info.meta))
        ids_loc = jax.lax.dynamic_slice_in_dim(ids, rank * rows, rows)
        row_ratio = ratio[ids_loc][:, None]
        p_new = K.lamb_stage2_packed(u, p, row_ratio, lr=hyper["lr"],
                                     noop_flag=noop,
                                     block_rows=self.block_rows)
        return p_new, {"m": m_new, "v": v_new}
