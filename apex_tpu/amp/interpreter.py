"""O1 autocast as a jaxpr interpreter.

Apex implements opt-level O1 by monkey-patching the torch functional surface
with cast-inserting wrappers (``apex/amp/wrap.py`` + ``apex/amp/utils.py``).
JAX has no mutable op registry, so the same *semantics* — MXU-bound ops run
in low precision, precision-sensitive ops run in f32, multi-arg ops promote
to the widest dtype — are reproduced by re-interpreting the traced jaxpr and
inserting casts per primitive.  Because the interpretation happens inside
the user's trace, ``jax.grad``/``jax.jit`` compose: the backward pass
differentiates through the inserted casts exactly as torch autograd does for
apex's forward-inserted casts.

Higher-order primitives: ``pjit``/``closed_call``/``remat`` bodies are
recursed into.  Control-flow (``scan``/``while``/``cond``) is ALSO
autocast: the op is rebuilt through the public ``lax.scan`` /
``while_loop`` / ``switch`` API with the body re-interpreted under this
autocast and its outputs restored to the traced dtypes at the carry
boundary — so carry dtypes stay fixed across iterations while the dots
INSIDE the body run at compute precision (apex O1 patches the functional
surface everywhere, including inside loops).  Custom-derivative calls
(``custom_jvp_call``/``custom_vjp_call``) are OPAQUE: inputs are restored
to the traced dtypes and the call is re-bound through
``primitive.get_bind_params`` (the ``core.eval_jaxpr`` mechanism), so the
author's derivative rule survives — required for the library's own Pallas
ops, whose bodies (bare ``pallas_call``) have no autodiff rule to inline
into.  This matches apex O1 semantics: amp patches the *functional
surface*, and the interior of a ``torch.autograd.Function`` is never
patched either.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.extend import core as jex_core


def _safe_map(f, *xs):
    lists = [list(x) for x in xs]
    assert all(len(l) == len(lists[0]) for l in lists)
    return list(map(f, *lists))

from apex_tpu.amp.lists import classify

_RECURSE = {"pjit", "jit", "closed_call", "core_call", "remat", "remat2",
            "checkpoint"}
# custom-derivative calls are re-bound whole (dtypes restored at the
# boundary) so the custom rule survives for the backward pass
_CUSTOM_CALL = {"custom_jvp_call", "custom_vjp_call",
                "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr",
                "custom_jvp_generic_call", "custom_lin"}
# control flow is rebuilt via the public API with an autocast body
_CONTROL_FLOW = {"scan", "while", "cond"}


def _is_float(x) -> bool:
    return jnp.issubdtype(jnp.result_type(x), jnp.floating)


def _cast(x, dtype):
    if _is_float(x) and jnp.result_type(x) != dtype:
        return jax.lax.convert_element_type(x, dtype)
    return x


def _widest(vals):
    dts = [jnp.result_type(v) for v in vals if _is_float(v)]
    if not dts:
        return None
    return functools.reduce(jnp.promote_types, dts)


def _restore_outs(outs, jaxpr):
    """Cast interpreted outputs back to their traced dtypes — the carry /
    branch-output boundary contract that keeps control-flow dtypes
    stable while the interior runs autocast."""
    return [_cast(o, var.aval.dtype) if _is_float(o) else o
            for o, var in zip(outs, jaxpr.outvars)]


def _closed_body(closed, compute_dtype):
    """An eager function interpreting ``closed`` under autocast, outputs
    restored to traced dtypes."""
    def fn(*xs):
        outs = _eval_jaxpr(closed.jaxpr, closed.consts, list(xs),
                           compute_dtype)
        return _restore_outs(outs, closed.jaxpr)
    return fn


def _rebuild_scan(params, invals, compute_dtype):
    nc, ncar = params["num_consts"], params["num_carry"]
    consts, init, xs = invals[:nc], invals[nc:nc + ncar], invals[nc + ncar:]
    body = _closed_body(params["jaxpr"], compute_dtype)

    def f(carry, x):
        outs = body(*consts, *carry, *x)
        return tuple(outs[:ncar]), tuple(outs[ncar:])

    carry_out, ys = jax.lax.scan(f, tuple(init), tuple(xs),
                                 length=params["length"],
                                 reverse=params["reverse"],
                                 unroll=params.get("unroll", 1))
    return list(carry_out) + list(ys)


def _rebuild_while(params, invals, compute_dtype):
    cn, bn = params["cond_nconsts"], params["body_nconsts"]
    cc, bc, init = invals[:cn], invals[cn:cn + bn], invals[cn + bn:]
    cond_body = _closed_body(params["cond_jaxpr"], compute_dtype)
    body_body = _closed_body(params["body_jaxpr"], compute_dtype)
    out = jax.lax.while_loop(
        lambda carry: cond_body(*cc, *carry)[0],
        lambda carry: tuple(body_body(*bc, *carry)),
        tuple(init))
    return list(out)


def _rebuild_cond(params, invals, compute_dtype):
    idx, ops = invals[0], invals[1:]
    branches = [_closed_body(b, compute_dtype) for b in params["branches"]]
    out = jax.lax.switch(idx, [
        (lambda *xs, _f=f: tuple(_f(*xs))) for f in branches], *ops)
    return list(out)


_REBUILD = {"scan": _rebuild_scan, "while": _rebuild_while,
            "cond": _rebuild_cond}


def _eval_jaxpr(jaxpr, consts, args, compute_dtype):
    env = {}

    def read(var):
        if isinstance(var, jex_core.Literal):
            return var.val
        return env[var]

    def write(var, val):
        env[var] = val

    _safe_map(write, jaxpr.constvars, consts)
    _safe_map(write, jaxpr.invars, args)

    for eqn in jaxpr.eqns:
        invals = _safe_map(read, eqn.invars)
        name = eqn.primitive.name
        params = eqn.params
        if name in _CUSTOM_CALL:
            invals = [_cast(v, var.aval.dtype) if _is_float(v) else v
                      for v, var in zip(invals, eqn.invars)]
            subfuns, bind_params = eqn.primitive.get_bind_params(params)
            outvals = eqn.primitive.bind(*subfuns, *invals, **bind_params)
        elif name in _RECURSE and "jaxpr" in eqn.params:
            inner = eqn.params["jaxpr"]
            inner_jaxpr = inner.jaxpr if hasattr(inner, "jaxpr") else inner
            inner_consts = (inner.consts if hasattr(inner, "consts")
                            else eqn.params.get("consts", ()))
            # dtype alignment at the call boundary: sub-jaxpr invars were
            # traced at specific dtypes
            invals = [_cast(v, var.aval.dtype) if _is_float(v) else v
                      for v, var in zip(invals, inner_jaxpr.invars)]
            outvals = _eval_jaxpr(inner_jaxpr, inner_consts, invals,
                                  compute_dtype)
        elif name in _CONTROL_FLOW:
            # restore traced dtypes at the boundary, then rebuild the op
            # through the public API with an autocast-interpreted body
            # (outputs restored per iteration, so carry dtypes are stable)
            invals = [_cast(v, var.aval.dtype) if _is_float(v) else v
                      for v, var in zip(invals, eqn.invars)]
            outvals = _REBUILD[name](params, invals, compute_dtype)
        else:
            kind = classify(eqn.primitive)
            if kind == "whitelist" and all(map(_is_float, invals)):
                invals = [_cast(v, compute_dtype) for v in invals]
                # tracing with f32 inputs bakes preferred_element_type=
                # f32 into dot/conv params; O1 semantics want half out.
                # (Integer/quantized dots fall through untouched.)
                pet = params.get("preferred_element_type")
                if pet is not None and jnp.issubdtype(pet, jnp.floating):
                    params = dict(params,
                                  preferred_element_type=compute_dtype)
            elif kind == "blacklist":
                invals = [_cast(v, jnp.float32) for v in invals]
            elif kind == "promote":
                wide = _widest(invals)
                if wide is not None:
                    invals = [_cast(v, wide) for v in invals]
            outvals = eqn.primitive.bind(*invals, **params)
        if not eqn.primitive.multiple_results:
            outvals = [outvals]
        _safe_map(write, eqn.outvars, outvals)

    return _safe_map(read, jaxpr.outvars)


def autocast(fun, compute_dtype=jnp.bfloat16):
    """Wrap ``fun`` so each primitive runs at its O1-classified precision.

    The returned function has the same signature; outputs keep their traced
    output dtypes EXCEPT where the final op itself was reclassified (matmul
    outputs become ``compute_dtype``), mirroring apex O1 where patched ops
    return fp16 tensors.

    Distributed composition: apply autocast to the PER-DEVICE function
    and wrap the result in ``shard_map`` — tracing happens inside the
    region, so collectives (``psum``/``pmean``/…) pass through and grads
    compose (apex: O1 patches compose with DDP the same way, model first,
    wrapper outside).  Covered by
    ``tests/test_amp.py::TestAutocastO1::test_autocast_inside_shard_map``.
    """

    @functools.wraps(fun)
    def wrapped(*args, **kwargs):
        closed, out_shape = jax.make_jaxpr(
            functools.partial(fun, **kwargs), return_shape=True)(*args)
        flat, _ = jax.tree_util.tree_flatten(args)
        out_tree = jax.tree_util.tree_structure(out_shape)
        outs = _eval_jaxpr(closed.jaxpr, closed.consts, flat, compute_dtype)
        return jax.tree_util.tree_unflatten(out_tree, outs)

    return wrapped
