from apex_tpu.amp.frontend import (AmpState, Properties, initialize)
from apex_tpu.amp.handle import scale_loss, unscale_step
from apex_tpu.amp.interpreter import autocast
from apex_tpu.amp.scaler import LossScaler, LossScaleState
from apex_tpu.amp.lists import WHITELIST, BLACKLIST, PROMOTE
# legacy pre-initialize surface (apex amp.py/opt.py/rnn_compat.py)
from apex_tpu.amp.legacy import (init, half_function, float_function,
                                 promote_function, register_half_function,
                                 register_float_function,
                                 register_promote_function)


def master_params(optimizer, params, opt_state):
    """fp32 master copies held by a fused optimizer (apex
    ``amp.master_params(optimizer)``; the functional form needs the param
    pytree and optimizer state explicitly)."""
    return optimizer.master_params(params, opt_state)

__all__ = [
    "AmpState",
    "Properties",
    "initialize",
    "scale_loss",
    "unscale_step",
    "master_params",
    "autocast",
    "LossScaler",
    "LossScaleState",
    "WHITELIST",
    "BLACKLIST",
    "PROMOTE",
    # legacy surface
    "init",
    "half_function",
    "float_function",
    "promote_function",
    "register_half_function",
    "register_float_function",
    "register_promote_function",
]
