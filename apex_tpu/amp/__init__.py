from apex_tpu.amp.frontend import (AmpState, Properties, initialize)
from apex_tpu.amp.handle import scale_loss, unscale_step
from apex_tpu.amp.interpreter import autocast
from apex_tpu.amp.scaler import LossScaler, LossScaleState
from apex_tpu.amp.lists import WHITELIST, BLACKLIST, PROMOTE

__all__ = [
    "AmpState",
    "Properties",
    "initialize",
    "scale_loss",
    "unscale_step",
    "autocast",
    "LossScaler",
    "LossScaleState",
    "WHITELIST",
    "BLACKLIST",
    "PROMOTE",
]
