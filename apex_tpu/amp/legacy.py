"""Legacy pre-``initialize`` amp surface — TPU rebuild of
``apex/amp/amp.py`` (the ``amp.init()`` + function-registry API),
``apex/amp/opt.py`` (``OptimWrapper``) and ``apex/amp/rnn_compat.py``.

Upstream this was the ORIGINAL amp API, kept importable after
``amp.initialize`` superseded it; same deal here.  The pieces:

* :func:`init` -> :class:`AmpHandle` — activates the registries and owns
  the loss scaler.
* :func:`half_function` / :func:`float_function` / :func:`promote_function`
  — decorators casting a function's floating args to half / fp32 / the
  widest input dtype (apex wrapped torch functions; here any jax-level
  callable).
* :func:`register_half_function` (etc.) — monkeypatch ``module.name`` in
  place, restored by ``AmpHandle._deactivate()`` — the apex mechanism for
  third-party libraries, verbatim (Python module attributes patch the
  same way torch's did).
* :class:`OptimWrapper` / ``handle.wrap_optimizer`` — the functional form
  of apex's wrapped optimizer: ``step(grads, params, opt_state)`` fuses
  unscale + overflow-skip + update + dynamic-scale adjustment via
  :func:`apex_tpu.amp.handle.unscale_step`.
* :mod:`rnn_compat <apex_tpu.amp.legacy>`: apex patched torch's cuDNN RNN
  bindings so amp casts reached them; the RNN tier here is plain scan
  cells that the O1 interpreter already descends into, so
  :func:`whitelist_rnn_cells` is a validated no-op (kept for import
  parity).

Deviation (documented): ``with handle.scale_loss(loss, opt) as scaled:
scaled.backward()`` imperatively mutates grads; functionally the scaled
loss is RETURNED (use it inside your loss fn) and the unscale happens in
``OptimWrapper.step`` — the same split ``apex_tpu.amp.handle`` uses.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.amp.handle import unscale_step
from apex_tpu.amp.scaler import LossScaler

__all__ = [
    "init", "half_function", "float_function", "promote_function",
    "register_half_function", "register_float_function",
    "register_promote_function", "AmpHandle", "NoOpHandle", "OptimWrapper",
    "whitelist_rnn_cells", "has_old_rnns",
]

_HALF_DTYPE = jnp.bfloat16


def _cast_tree(args, dtype):
    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x
    return jax.tree_util.tree_map(cast, args)


def _widest(args):
    dts = [x.dtype for x in jax.tree_util.tree_leaves(args)
           if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)]
    if not dts:
        return None
    return functools.reduce(jnp.promote_types, dts)


def _casting_wrapper(fn: Callable, mode: str, half_dtype) -> Callable:
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        if mode == "half":
            args, kwargs = _cast_tree(args, half_dtype), _cast_tree(
                kwargs, half_dtype)
        elif mode == "float":
            args, kwargs = _cast_tree(args, jnp.float32), _cast_tree(
                kwargs, jnp.float32)
        else:                                        # promote
            wide = _widest((args, kwargs))
            if wide is not None:
                args, kwargs = _cast_tree(args, wide), _cast_tree(
                    kwargs, wide)
        return fn(*args, **kwargs)

    wrapped._amp_original = fn
    return wrapped


def half_function(fn: Callable) -> Callable:
    """apex ``amp.half_function``: run ``fn`` with half-cast float args."""
    return _casting_wrapper(fn, "half", _HALF_DTYPE)


def float_function(fn: Callable) -> Callable:
    """apex ``amp.float_function``: run ``fn`` with fp32-cast float args."""
    return _casting_wrapper(fn, "float", _HALF_DTYPE)


def promote_function(fn: Callable) -> Callable:
    """apex ``amp.promote_function``: promote float args to the widest."""
    return _casting_wrapper(fn, "promote", _HALF_DTYPE)


# module-level registries staged by register_* and applied by init()
# (apex semantics: registration must precede init)
_PENDING: list = []


def register_half_function(module: Any, name: str) -> None:
    _PENDING.append((module, name, "half"))


def register_float_function(module: Any, name: str) -> None:
    _PENDING.append((module, name, "float"))


def register_promote_function(module: Any, name: str) -> None:
    _PENDING.append((module, name, "promote"))


class NoOpHandle:
    """``amp.init(enabled=False)``: every hook is the identity."""

    is_active = False

    @contextlib.contextmanager
    def scale_loss(self, loss, optimizer=None):
        yield loss

    def wrap_optimizer(self, optimizer):
        return OptimWrapper(optimizer, self)

    def loss_scale(self):
        return 1.0

    def _deactivate(self):
        pass


class AmpHandle:
    """apex ``amp_state``/``AmpHandle``: owns the scaler + applied patches."""

    is_active = True

    def __init__(self, loss_scale="dynamic", half_dtype=_HALF_DTYPE,
                 verbose=False):
        del verbose
        self.half_dtype = half_dtype
        self.scaler = LossScaler(loss_scale=loss_scale)
        self.scaler_state = self.scaler.init()
        self._patched: list = []
        for module, name, mode in _PENDING:
            orig = getattr(module, name)
            setattr(module, name, _casting_wrapper(orig, mode, half_dtype))
            self._patched.append((module, name, orig))
        _PENDING.clear()

    def loss_scale(self):
        return float(self.scaler_state.loss_scale)

    @contextlib.contextmanager
    def scale_loss(self, loss, optimizer=None):
        """Yields the SCALED loss (functional deviation documented in the
        module docstring: take grads of the yielded value; unscaling
        happens in ``OptimWrapper.step``)."""
        yield loss * self.scaler_state.loss_scale.astype(
            jnp.result_type(loss))

    def wrap_optimizer(self, optimizer):
        return OptimWrapper(optimizer, self)

    def _deactivate(self):
        """Restore every monkeypatched function (apex handle teardown)."""
        for module, name, orig in self._patched:
            setattr(module, name, orig)
        self._patched.clear()


class OptimWrapper:
    """apex ``opt.py::OptimWrapper`` functionally: fused unscale +
    overflow-skip + step + dynamic scale update on the handle's scaler."""

    def __init__(self, optimizer, handle):
        self.optimizer = optimizer
        self.handle = handle

    def step(self, grads, params, opt_state, *, lr=None):
        if not self.handle.is_active:
            return self.optimizer.step(grads, params, opt_state, lr=lr)
        new_p, new_s, scaler_state, _ = unscale_step(
            self.optimizer, grads, params, opt_state, self.handle.scaler,
            self.handle.scaler_state, lr=lr)
        # the handle is host-side state (apex kept it on the python
        # object too); fine outside jit, donate-free inside
        self.handle.scaler_state = scaler_state
        return new_p, new_s


def init(enabled: bool = True, loss_scale="dynamic",
         half_dtype=_HALF_DTYPE, enable_caching: bool = True,
         verbose: bool = False, allow_banned: bool = False):
    """apex ``amp.init()`` — returns the active :class:`AmpHandle` (or the
    no-op handle when disabled).  ``enable_caching``/``allow_banned`` are
    accepted for signature parity; weight-cast caching is XLA's job here.
    """
    del enable_caching, allow_banned
    if not enabled:
        # consume staged registrations so they cannot leak into a later
        # unrelated init() (apex: disabled init deactivates everything)
        _PENDING.clear()
        return NoOpHandle()
    return AmpHandle(loss_scale=loss_scale, half_dtype=half_dtype,
                     verbose=verbose)


# -- rnn_compat -------------------------------------------------------------

has_old_rnns = False    # apex detected pre-0.4 torch RNN internals


def whitelist_rnn_cells(handle=None, verbose=False):
    """apex ``rnn_compat.whitelist_rnn_cells``: patched torch's RNN cell
    backends into the cast registry.  The TPU RNN tier
    (:mod:`apex_tpu.RNN`) is scan cells built from whitelisted
    primitives, which the O1 interpreter autocasts INSIDE the scan body
    — there is nothing to patch, so this validates and returns."""
    del handle, verbose
    import apex_tpu.RNN  # noqa: F401  (surface exists => nothing to do)
