"""amp frontend — TPU rebuild of ``apex/amp/frontend.py``.

Opt levels keep apex's meaning, translated to TPU dtypes (half = bf16 by
default; fp16 available for parity):

* **O0** — fp32 everything (debugging baseline).
* **O1** — per-op autocast: MXU ops in half, precision-sensitive ops in
  fp32 (apex: patched functional surface; here: the jaxpr autocast
  interpreter in ``apex_tpu.amp.interpreter``).
* **O2** — "almost half": model params and inputs cast to half (except
  normalization layers when ``keep_batchnorm_fp32``), fp32 master weights
  held by the optimizer, loss scaling.
* **O3** — half everything (speed baseline).

``initialize`` wires a model-apply function, a fused optimizer, and a
``LossScaler`` into an :class:`AmpState` — the functional equivalent of
apex's patched (model, optimizer) pair.
"""

from __future__ import annotations

import re
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.amp.interpreter import autocast
from apex_tpu.amp.scaler import LossScaler, LossScaleState

_BN_PATTERN = re.compile(
    r"(batch_?norm|bn|layer_?norm|ln|group_?norm|rms_?norm|norm)",
    re.IGNORECASE)


class Properties:
    """Resolved opt-level properties (apex ``frontend.py::Properties``)."""

    def __init__(self, **kw):
        self.opt_level = kw.get("opt_level")
        self.cast_model_type = kw.get("cast_model_type")
        self.patch_torch_functions = kw.get("patch_torch_functions", False)
        self.keep_batchnorm_fp32 = kw.get("keep_batchnorm_fp32")
        self.master_weights = kw.get("master_weights", False)
        self.loss_scale = kw.get("loss_scale", 1.0)

    def _asdict(self):
        return dict(opt_level=self.opt_level,
                    cast_model_type=self.cast_model_type,
                    patch_torch_functions=self.patch_torch_functions,
                    keep_batchnorm_fp32=self.keep_batchnorm_fp32,
                    master_weights=self.master_weights,
                    loss_scale=self.loss_scale)


def _opt_level_properties(opt_level: str, half_dtype) -> Properties:
    # bf16 needs no loss scaling (8-bit exponent = f32 range); fp16 does.
    dyn = "dynamic" if half_dtype == jnp.float16 else 1.0
    table = {
        "O0": Properties(opt_level="O0", cast_model_type=jnp.float32,
                         patch_torch_functions=False,
                         keep_batchnorm_fp32=None, master_weights=False,
                         loss_scale=1.0),
        "O1": Properties(opt_level="O1", cast_model_type=None,
                         patch_torch_functions=True,
                         keep_batchnorm_fp32=None, master_weights=False,
                         loss_scale=dyn),
        "O2": Properties(opt_level="O2", cast_model_type=half_dtype,
                         patch_torch_functions=False,
                         keep_batchnorm_fp32=True, master_weights=True,
                         loss_scale=dyn),
        "O3": Properties(opt_level="O3", cast_model_type=half_dtype,
                         patch_torch_functions=False,
                         keep_batchnorm_fp32=False, master_weights=False,
                         loss_scale=1.0),
    }
    if opt_level not in table:
        raise ValueError(f"Unexpected optimization level {opt_level}; "
                         "options are 'O0', 'O1', 'O2', 'O3'.")
    return table[opt_level]


def _is_norm_param(path_str: str) -> bool:
    return bool(_BN_PATTERN.search(path_str))


class AmpState(NamedTuple):
    """Everything ``amp.initialize`` wires together (functional form)."""

    apply_fn: Callable          # policy-wrapped model apply
    optimizer: Any              # the (possibly master-weight) fused optimizer
    scaler: LossScaler
    properties: Properties

    def cast_params(self, params):
        """Apply the opt level's model-weight cast (O2/O3)."""
        dtype = self.properties.cast_model_type
        if dtype is None or dtype == jnp.float32:
            return params
        keep_bn = self.properties.keep_batchnorm_fp32

        def cast(path, x):
            if not jnp.issubdtype(x.dtype, jnp.floating):
                return x
            if keep_bn and _is_norm_param(jax.tree_util.keystr(path)):
                return x.astype(jnp.float32)
            return x.astype(dtype)

        return jax.tree_util.tree_map_with_path(cast, params)

    def cast_inputs(self, *args):
        dtype = self.properties.cast_model_type
        if dtype is None or dtype == jnp.float32:
            return args
        cast = lambda x: (x.astype(dtype)
                          if hasattr(x, "dtype") and
                          jnp.issubdtype(x.dtype, jnp.floating) else x)
        return jax.tree_util.tree_map(cast, args)


def initialize(model_apply: Callable, optimizer=None, opt_level: str = "O1",
               half_dtype=jnp.bfloat16, cast_model_type=None,
               patch_torch_functions=None, keep_batchnorm_fp32=None,
               master_weights=None, loss_scale=None,
               min_loss_scale=None, max_loss_scale=2.0 ** 24,
               verbosity=1, **unused):
    """TPU translation of ``apex.amp.initialize(model, optimizer, ...)``.

    ``model_apply`` is the functional model: ``apply(params, *inputs)``.
    Returns an :class:`AmpState`; use ``state.apply_fn`` in place of the
    model, ``state.cast_params`` once on init (O2/O3), and the
    ``scale_loss``/``unscale_step`` helpers from ``apex_tpu.amp`` in the
    train loop.  Property overrides mirror apex's keyword overrides.
    """
    props = _opt_level_properties(opt_level, half_dtype)
    for name, val in dict(cast_model_type=cast_model_type,
                          patch_torch_functions=patch_torch_functions,
                          keep_batchnorm_fp32=keep_batchnorm_fp32,
                          master_weights=master_weights,
                          loss_scale=loss_scale).items():
        if val is not None:
            setattr(props, name, val)

    if props.patch_torch_functions:
        apply_fn = autocast(model_apply, compute_dtype=half_dtype)
    else:
        apply_fn = model_apply

    if optimizer is not None and props.master_weights:
        optimizer.master_weights = True

    scaler = LossScaler(loss_scale=props.loss_scale,
                        min_loss_scale=min_loss_scale,
                        max_loss_scale=max_loss_scale)
    return AmpState(apply_fn, optimizer, scaler, props)
