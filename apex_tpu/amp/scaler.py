"""Loss scaling — TPU rebuild of ``apex/amp/scaler.py::LossScaler``.

Functional: the scaler's mutable fields (current scale, unskipped-step
counter) live in an explicit state pytree so the whole train step stays
jittable.  Overflow detection fuses into the multi-tensor unscale pass
(apex: ``amp_C.multi_tensor_scale`` writing the ``overflow_buf``), and the
skip decision is carried as an on-device ``noop`` flag — no host sync.

bf16 on TPU rarely overflows, so the default scale for bf16 policies is the
static 1.0 (machinery intact for fp16-parity and for users who want it).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import multi_tensor_scale

_f32 = jnp.float32


class LossScaleState(NamedTuple):
    loss_scale: jax.Array        # f32 scalar
    unskipped: jax.Array         # int32 — clean steps since last growth
    overflows: jax.Array         # int32 — total overflow count (diagnostics)
    skipped: jax.Array           # int32 — cumulative steps whose update
    #                              was skipped (checkpointed; surfaced in
    #                              GuardedTrainStep.stats)


class LossScaler:
    """``loss_scale``: a number for static scaling or ``"dynamic"``."""

    def __init__(self, loss_scale="dynamic", init_scale=2.0 ** 16,
                 scale_factor=2.0, scale_window=2000, min_loss_scale=None,
                 max_loss_scale=2.0 ** 24):
        self.dynamic = loss_scale == "dynamic"
        self._init_scale = float(init_scale if self.dynamic else loss_scale)
        self.scale_factor = float(scale_factor)
        self.scale_window = int(scale_window)
        self.min_loss_scale = (None if min_loss_scale is None
                               else float(min_loss_scale))
        self.max_loss_scale = float(max_loss_scale)

    def init(self) -> LossScaleState:
        return LossScaleState(jnp.asarray(self._init_scale, _f32),
                              jnp.zeros((), jnp.int32),
                              jnp.zeros((), jnp.int32),
                              jnp.zeros((), jnp.int32))

    def scale(self, loss, state: LossScaleState):
        """Multiply the loss (apex: ``scale_loss`` context entry)."""
        return loss * state.loss_scale.astype(loss.dtype)

    def unscale(self, grads, state: LossScaleState):
        """Unscale gradients with fused overflow detection.

        Returns ``(unscaled_grads, found_inf)`` — the functional analogue of
        apex's unscale-with-overflow-buffer.  Prefer passing
        ``grad_scale=1/scale`` straight to a fused optimizer instead (saves
        a pass over the gradients); use :meth:`found_inf` for the check.
        """
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        outs, finf = multi_tensor_scale(leaves, 1.0 / state.loss_scale)
        return jax.tree_util.tree_unflatten(treedef, outs), finf

    @staticmethod
    def found_inf(grads) -> jax.Array:
        """f32 0/1 flag: any non-finite value in the gradient pytree."""
        leaves = jax.tree_util.tree_leaves(grads)
        bad = jnp.zeros((), jnp.bool_)
        for g in leaves:
            bad = bad | jnp.logical_not(jnp.all(jnp.isfinite(g)))
        return bad.astype(_f32)

    def update(self, state: LossScaleState, found_inf) -> LossScaleState:
        """Post-step scale adjustment (apex ``update_scale``): halve on
        overflow, double every ``scale_window`` clean steps.  The
        cumulative ``skipped`` counter advances on every overflow-skipped
        step — including under a static scaler, where the scale itself
        never moves."""
        overflow = jnp.asarray(found_inf) > 0
        skipped = state.skipped + overflow.astype(jnp.int32)
        if not self.dynamic:
            return state._replace(skipped=skipped)
        new_scale = jnp.where(overflow,
                              state.loss_scale / self.scale_factor,
                              state.loss_scale)
        if self.min_loss_scale is not None:
            new_scale = jnp.maximum(new_scale, self.min_loss_scale)
        unskipped = jnp.where(overflow, 0, state.unskipped + 1)
        grow = unskipped >= self.scale_window
        new_scale = jnp.where(
            grow, jnp.minimum(new_scale * self.scale_factor,
                              self.max_loss_scale), new_scale)
        unskipped = jnp.where(grow, 0, unskipped)
        return LossScaleState(new_scale, unskipped,
                              state.overflows + overflow.astype(jnp.int32),
                              skipped)

    def stats(self, state: LossScaleState) -> dict:
        """Observability tap: the scaler's series as host floats/ints
        (one 4-scalar readback — call at report time, not per step;
        the per-step loss-scale series rides the guard's telemetry
        vector instead).  Consumed by
        ``apex_tpu.observability.TrainingMonitor.report``."""
        return {"loss_scale": float(state.loss_scale),
                "overflows": int(state.overflows),
                "skipped_steps": int(state.skipped),
                "steps_since_backoff": int(state.unskipped),
                "dynamic": self.dynamic}

    # apex checkpoint surface (tests/L0/run_amp/test_checkpointing.py)
    def state_dict(self, state: LossScaleState) -> dict:
        return {"loss_scale": float(state.loss_scale),
                "unskipped": int(state.unskipped),
                "overflows": int(state.overflows),
                "skipped": int(state.skipped)}

    def load_state_dict(self, d: dict) -> LossScaleState:
        return LossScaleState(jnp.asarray(d["loss_scale"], _f32),
                              jnp.asarray(d["unskipped"], jnp.int32),
                              jnp.asarray(d.get("overflows", 0), jnp.int32),
                              jnp.asarray(d.get("skipped", 0), jnp.int32))
