"""Train-loop helpers — TPU translation of ``apex/amp/handle.py``.

Apex's ``with amp.scale_loss(loss, optimizer) as scaled: scaled.backward()``
doesn't map onto functional autodiff, so the same contract is split into
composable pieces that live inside the jitted train step:

* :func:`scale_loss` — multiply the loss by the current scale (inside the
  loss function, before ``jax.grad``).
* :func:`unscale_step` — the whole post-backward sequence fused: overflow
  check on the *scaled* grads, optimizer step with ``grad_scale=1/scale``
  (unscaling fused into the update kernel) skipped on-device when overflow,
  then dynamic scale adjustment.  This is apex §3.2's hot path with zero
  host syncs.
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.amp.scaler import LossScaler, LossScaleState


def scale_loss(loss, scaler_state: LossScaleState):
    """Scale the loss (use inside the loss fn, pre-``jax.grad``)."""
    return loss * scaler_state.loss_scale.astype(loss.dtype)


def unscale_step(optimizer, grads, params, opt_state,
                 scaler: LossScaler, scaler_state: LossScaleState, *,
                 lr=None):
    """Fused unscale + overflow-skip + optimizer step + scale update.

    Returns ``(new_params, new_opt_state, new_scaler_state, found_inf)``.

    With a static scaler (the bf16 default) the overflow check is skipped
    entirely — no isfinite pass, no noop select — matching apex, which only
    pays the check under dynamic scaling.
    """
    if scaler.dynamic:
        finf = LossScaler.found_inf(grads)
        noop = finf.astype(jnp.int32)
    else:
        finf = jnp.zeros((), jnp.float32)
        noop = None
    inv_scale = 1.0 / scaler_state.loss_scale
    new_params, new_opt_state = optimizer.step(
        grads, params, opt_state, lr=lr, grad_scale=inv_scale,
        noop_flag=noop)
    new_scaler_state = scaler.update(scaler_state, finf)
    return new_params, new_opt_state, new_scaler_state, finf
