"""O1 per-op cast lists — TPU rebuild of ``apex/amp/lists/*.py``.

Apex classifies the torch functional surface into FP16-whitelist (tensor-core
ops), FP32-blacklist (precision-sensitive ops), and promote (multi-arg ops
take the widest dtype).  The JAX equivalent classifies *primitives* in the
traced jaxpr — same semantics, no monkey-patching.
"""

from __future__ import annotations

import jax

# MXU-bound ops: cast inputs to the low-precision compute dtype
# (apex/amp/lists/functional_overrides.py FP16_FUNCS: conv*, linear, matmul,
# addmm, bmm, ...)
WHITELIST = {
    "dot_general",
    "conv_general_dilated",
}

# Precision-sensitive ops: force f32 inputs
# (apex FP32_FUNCS: softmax, log_softmax, exp, expm1, log, log1p, pow,
# sum/mean-style reductions, norm, cross-entropy, ...)
BLACKLIST = {
    "exp",
    "exp2",
    "expm1",
    "log",
    "log1p",
    "pow",
    "integer_pow",
    "logistic",
    "erf",
    "erfc",
    "erf_inv",
    "rsqrt",
    "reduce_sum",
    "reduce_prod",
    "cumsum",
    "cumprod",
    "cumlogsumexp",
    "reduce_precision",
    "lgamma",
    "digamma",
    "acos",
    "asin",
    "atan",
    "atan2",
    "cosh",
    "sinh",
    "asinh",
    "acosh",
    "atanh",
}

# Multi-arg elementwise ops promote to the widest floating dtype present
# (apex CASTS/promote list: add, mul, cat, where, ...)
PROMOTE = {
    "add",
    "sub",
    "mul",
    "div",
    "max",
    "min",
    "rem",
    "nextafter",
    "concatenate",
    "select_n",
    "clamp",
    # comparisons output bool but still require equal operand dtypes,
    # which autocast can desynchronize (e.g. bf16 conv out vs f32 const)
    "eq",
    "ne",
    "lt",
    "le",
    "gt",
    "ge",
}


def classify(primitive: jax.extend.core.Primitive) -> str:
    name = primitive.name
    if name in WHITELIST:
        return "whitelist"
    if name in BLACKLIST:
        return "blacklist"
    if name in PROMOTE:
        return "promote"
    return "passthrough"
