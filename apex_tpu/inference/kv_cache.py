"""Preallocated KV-cache ring with slot allocation.

One device array holds every sequence's cache:
``(slots, layers, 2, max_seq, kv_heads, head_dim)`` — axis 2 is K/V.
The slot axis doubles as the decode batch dimension, so admission is
slot allocation and nothing ever reshapes or compacts: a freed slot's
rows are simply overwritten by the next prompt.  The array itself is
functional (reassigned on every write, aliased in place by XLA under
donation on TPU); slot bookkeeping (free list, per-slot lengths) is
host-side numpy, since the engine's control loop is host-driven.

Dtype control: the cache is typically ``bfloat16`` (half the HBM of
f32 — cache size, not FLOPs, bounds batch×context on an inference
chip) while attention accumulates in f32 regardless
(:func:`apex_tpu.ops.flash_attention.flash_attention_decode`).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np


class KVCache:
    """Slot-table KV cache for continuous-batching decode."""

    def __init__(self, slots: int, layers: int, max_seq: int,
                 kv_heads: int, head_dim: int,
                 dtype=jnp.bfloat16):
        if slots <= 0 or max_seq <= 0:
            raise ValueError("slots and max_seq must be positive")
        self.data = jnp.zeros(
            (slots, layers, 2, max_seq, kv_heads, head_dim), dtype)
        self.lengths = np.zeros((slots,), np.int32)
        # LIFO free list popping the lowest slot first keeps tests and
        # traces readable; correctness doesn't depend on the order
        self._free = list(range(slots - 1, -1, -1))

    @property
    def slots(self) -> int:
        return self.data.shape[0]

    @property
    def max_seq(self) -> int:
        return self.data.shape[3]

    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def active_slots(self) -> int:
        return self.slots - len(self._free)

    @property
    def slot_bytes(self) -> int:
        """HBM footprint of one slot row."""
        return (int(np.prod(self.data.shape[1:]))
                * self.data.dtype.itemsize)

    def free_bytes(self) -> int:
        """Bytes of cache capacity no request is holding (free slots)."""
        return len(self._free) * self.slot_bytes

    def used_bytes(self) -> int:
        """Bytes actually covered by valid entries — token-granular, not
        slot-granular: a slot holding a 10-token context counts 10
        positions' worth, not ``max_seq``.  The difference between this
        and ``slots*slot_bytes - free_bytes()`` is internal
        fragmentation, which is exactly what the paged cache removes."""
        return int(self.lengths.sum()) * self.slot_bytes // self.max_seq

    def occupancy(self) -> float:
        """Fraction of total cache capacity holding valid tokens
        (token-granular; the admission/routing signal)."""
        return float(self.lengths.sum()) / (self.slots * self.max_seq)

    def allocate(self) -> Optional[int]:
        """Claim a free slot id, or None when fully occupied."""
        if not self._free:
            return None
        return self._free.pop()

    def free(self, slot: int) -> None:
        """Return ``slot`` to the pool.  Its rows are left in place —
        the next prompt overwrites them, and until then no valid length
        references them."""
        if slot in self._free:
            raise ValueError(f"slot {slot} is already free")
        self.lengths[slot] = 0
        self._free.append(slot)

    def write_prompt(self, slot: int, kv, length: int) -> None:
        """Install a prefilled prompt into ``slot``.

        ``kv``: ``(layers, 2, s, kv_heads, head_dim)`` from
        :meth:`~apex_tpu.models.gpt.GPTModel.prefill` (one sequence),
        cast here to the cache dtype.  ``s`` may exceed ``length``
        (bucket-padded prompts): the padded rows are written but masked
        by ``length`` until real decode steps overwrite them.
        """
        s = kv.shape[2]
        if s > self.max_seq:
            raise ValueError(
                f"prompt length {s} exceeds cache max_seq {self.max_seq}")
        if not 0 < length <= s:
            raise ValueError(f"length {length} not in (0, {s}]")
        self.data = self.data.at[slot, :, :, :s].set(
            kv.astype(self.data.dtype))
        self.lengths[slot] = length

    def advance(self, slot: int) -> None:
        """Record one decoded token in ``slot`` (the device-side write
        happened inside ``decode_step``)."""
        self.lengths[slot] += 1
