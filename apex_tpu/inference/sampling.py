"""Token sampling for the decode loop: greedy, temperature, top-k.

``temperature == 0`` means greedy (argmax) — the deterministic mode the
engine's batched-vs-isolated parity guarantee is stated for.  Stochastic
modes draw from an explicit PRNG key per call; the engine folds a
per-request key per step so batch composition never changes a request's
stream.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling configuration.

    ``temperature``: 0.0 → greedy; otherwise logits are divided by it.
    ``top_k``: restrict sampling to the k highest-probability tokens
    (None → full vocab).  Ignored under greedy.
    """
    temperature: float = 0.0
    top_k: Optional[int] = None

    def __post_init__(self):
        if self.temperature < 0.0:
            raise ValueError("temperature must be >= 0")
        if self.top_k is not None and self.top_k <= 0:
            raise ValueError("top_k must be positive")

    @property
    def greedy(self) -> bool:
        return self.temperature == 0.0


def sample(logits, params: SamplingParams = SamplingParams(), key=None):
    """Draw a token id from ``logits`` (``(..., vocab)``).

    Greedy needs no key; stochastic modes require one.  Returns an int
    array of shape ``logits.shape[:-1]``.
    """
    if params.greedy:
        return jnp.argmax(logits, axis=-1)
    if key is None:
        raise ValueError("stochastic sampling requires a PRNG key")
    scaled = logits.astype(jnp.float32) / params.temperature
    if params.top_k is not None and params.top_k < logits.shape[-1]:
        kth = jnp.sort(scaled, axis=-1)[..., -params.top_k][..., None]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    return jax.random.categorical(key, scaled, axis=-1)
