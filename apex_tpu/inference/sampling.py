"""Token sampling for the decode loop: greedy, temperature, top-k, top-p.

``temperature == 0`` means greedy (argmax) — the deterministic mode the
engine's batched-vs-isolated parity guarantee is stated for.  Stochastic
modes draw from an explicit PRNG key per call; the engine folds a
per-request key per step so batch composition never changes a request's
stream.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling configuration.

    ``temperature``: 0.0 → greedy; otherwise logits are divided by it.
    ``top_k``: restrict sampling to the k highest-probability tokens
    (None → full vocab).  ``top_p``: nucleus sampling — keep the
    smallest set of tokens whose cumulative probability reaches
    ``top_p`` (None or 1.0 → full vocab); composes with ``top_k``
    (k-filter first, then the nucleus over what survives, the usual
    stacking order).  Both are ignored under greedy.
    """
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None

    def __post_init__(self):
        if self.temperature < 0.0:
            raise ValueError("temperature must be >= 0")
        if self.top_k is not None and self.top_k <= 0:
            raise ValueError("top_k must be positive")
        if self.top_p is not None and not 0.0 < self.top_p <= 1.0:
            raise ValueError("top_p must be in (0, 1]")

    @property
    def greedy(self) -> bool:
        return self.temperature == 0.0


def _nucleus_filter(scaled, top_p: float):
    """Mask ``scaled`` logits outside the smallest prefix of the
    probability-sorted vocab whose cumulative mass reaches ``top_p``.

    A token is kept iff the cumulative probability *before* it (in
    descending order) is < ``top_p`` — so the token that crosses the
    threshold is included and at least one token always survives.
    Deterministic in the logits alone: ties at the cut keep every tied
    token, never a data-dependent subset.
    """
    probs = jax.nn.softmax(scaled, axis=-1)
    sorted_p = jnp.sort(probs, axis=-1)[..., ::-1]
    cum_before = jnp.cumsum(sorted_p, axis=-1) - sorted_p
    keep = cum_before < top_p
    # smallest kept probability = the nucleus threshold
    thr = jnp.min(jnp.where(keep, sorted_p, jnp.inf), axis=-1,
                  keepdims=True)
    return jnp.where(probs >= thr, scaled, -jnp.inf)


def sample(logits, params: SamplingParams = SamplingParams(), key=None):
    """Draw a token id from ``logits`` (``(..., vocab)``).

    Greedy needs no key; stochastic modes require one.  Returns an int
    array of shape ``logits.shape[:-1]``.
    """
    if params.greedy:
        return jnp.argmax(logits, axis=-1)
    if key is None:
        raise ValueError("stochastic sampling requires a PRNG key")
    scaled = logits.astype(jnp.float32) / params.temperature
    if params.top_k is not None and params.top_k < logits.shape[-1]:
        kth = jnp.sort(scaled, axis=-1)[..., -params.top_k][..., None]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    if params.top_p is not None and params.top_p < 1.0:
        scaled = _nucleus_filter(scaled, params.top_p)
    return jax.random.categorical(key, scaled, axis=-1)
