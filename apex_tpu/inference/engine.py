"""Continuous-batching serving engine for GPT decode.

Orchestration is host-side and simple by design; the device work is two
jitted programs — one prefill per prompt bucket and ONE batched
``decode_step`` whose batch dimension is the cache slot table:

* admission — while slots are free and requests are queued, each request
  gets one prefill (prompt padded to a power-of-two bucket: causal
  masking makes the pad rows inert) whose K/V lands in its slot and
  whose last-position logits yield the first token (TTFT ends here).
* decode — every step runs ALL slots through ``decode_step``; inactive
  slots compute garbage that is never read (their writes land at stale
  positions that the next prefill overwrites before any valid length
  reaches them).  New requests admit between steps as slots free — no
  batch drain, which is the point of continuous batching.
* completion — eos / ``max_new_tokens`` / cache exhaustion free the
  slot; a request past its ``deadline`` is EVICTED mid-flight with
  whatever it has generated.

Determinism: each decode row depends only on its own slot's cache and
token (attention masks by per-row length, norms/linears are per-token),
so greedy decode of a request inside any batch mix is token-identical to
running it alone — asserted by the engine tests.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.inference.kv_cache import KVCache
from apex_tpu.inference.sampling import SamplingParams, sample
from apex_tpu.utils.platform import is_tpu_backend
from apex_tpu.utils.profiling import ServingMetrics


@dataclasses.dataclass
class Request:
    """One generation request.

    ``deadline`` is an absolute value of the engine's ``clock`` (default
    ``time.monotonic``); a request still running past it is evicted.
    ``seed`` feeds the per-request sampling stream (stochastic modes
    only) — streams are keyed by (seed, token index), never by batch
    composition.
    """
    request_id: int
    prompt: Sequence[int]
    max_new_tokens: int = 16
    eos_id: Optional[int] = None
    sampling: SamplingParams = dataclasses.field(
        default_factory=SamplingParams)
    deadline: Optional[float] = None
    seed: int = 0


@dataclasses.dataclass
class Response:
    """Completed (or evicted) request: ``tokens`` holds the generated
    ids (including the eos token when one was emitted);
    ``finish_reason`` is ``"eos"``, ``"length"`` (max_new_tokens or
    cache row exhausted) or ``"evicted"`` (deadline)."""
    request_id: int
    prompt: List[int]
    tokens: List[int]
    finish_reason: str


@dataclasses.dataclass
class _Active:
    request: Request
    prompt_len: int
    next_token: int        # fed to the next decode step
    position: int          # absolute position next_token is written at
    generated: List[int] = dataclasses.field(default_factory=list)


class InferenceEngine:
    """Continuous batching over a :class:`KVCache` slot ring."""

    def __init__(self, model, params, *, max_slots: int = 8,
                 max_seq: Optional[int] = None, cache_dtype=None,
                 clock: Callable[[], float] = time.monotonic,
                 metrics: Optional[ServingMetrics] = None,
                 min_prompt_bucket: int = 8):
        model._check_decode_supported()
        cfg = model.cfg
        self.model = model
        self.params = params
        self.cache = KVCache(max_slots, cfg.num_layers,
                             max_seq or cfg.max_seq_len, cfg.local_heads,
                             cfg.head_dim, cache_dtype or cfg.dtype)
        self.clock = clock
        self.metrics = metrics or ServingMetrics(clock)
        self._min_bucket = min_prompt_bucket
        self._queue: collections.deque = collections.deque()
        self._active: dict = {}          # slot -> _Active
        self._done: List[Response] = []
        # the cache buffer threads through every step: donate it on TPU
        # so XLA updates it in place (donation on CPU only warns)
        donate = (2,) if is_tpu_backend() else ()
        self._decode = jax.jit(model.decode_step, donate_argnums=donate)
        self._prefill = jax.jit(model.prefill)

    # -- request lifecycle ---------------------------------------------------

    def submit(self, request: Request) -> None:
        if not 0 < len(request.prompt) < self.cache.max_seq:
            raise ValueError(
                f"prompt length {len(request.prompt)} must be in "
                f"(0, {self.cache.max_seq}) to leave room for decode")
        self.metrics.request_submitted(request.request_id)
        self._queue.append(request)

    def _bucket(self, n: int) -> int:
        b = self._min_bucket
        while b < n:
            b *= 2
        return min(b, self.cache.max_seq)

    def _sample(self, req: Request, logits_row, token_index: int) -> int:
        if req.sampling.greedy:
            return int(np.argmax(logits_row))
        key = jax.random.fold_in(jax.random.PRNGKey(req.seed),
                                 token_index)
        return int(sample(jnp.asarray(logits_row), req.sampling, key))

    def _finish(self, slot: int, st: _Active, reason: str) -> None:
        self.cache.free(slot)
        del self._active[slot]
        if reason == "evicted":
            self.metrics.request_evicted(st.request.request_id)
        self._done.append(Response(st.request.request_id,
                                   list(st.request.prompt),
                                   st.generated, reason))

    def _maybe_finish(self, slot: int, st: _Active) -> bool:
        req = st.request
        if req.eos_id is not None and st.generated[-1] == req.eos_id:
            self._finish(slot, st, "eos")
        elif len(st.generated) >= req.max_new_tokens:
            self._finish(slot, st, "length")
        elif st.position >= self.cache.max_seq:
            self._finish(slot, st, "length")      # cache row exhausted
        else:
            return False
        return True

    def _evict_expired(self) -> None:
        now = self.clock()

        def expired(req):
            return req.deadline is not None and now >= req.deadline

        for slot in [s for s, st in self._active.items()
                     if expired(st.request)]:
            self._finish(slot, self._active[slot], "evicted")
        keep: collections.deque = collections.deque()
        while self._queue:
            req = self._queue.popleft()
            if expired(req):
                self.metrics.request_evicted(req.request_id)
                self._done.append(Response(req.request_id,
                                           list(req.prompt), [],
                                           "evicted"))
            else:
                keep.append(req)
        self._queue = keep

    def _admit(self) -> None:
        while self._queue and self.cache.free_slots:
            req = self._queue.popleft()
            slot = self.cache.allocate()
            plen = len(req.prompt)
            toks = np.zeros((1, self._bucket(plen)), np.int32)
            toks[0, :plen] = req.prompt
            logits, kv = self._prefill(self.params, jnp.asarray(toks))
            self.cache.write_prompt(slot, kv[:, :, 0], plen)
            first = self._sample(req, np.asarray(logits[0, plen - 1]), 0)
            self.metrics.first_token(req.request_id)
            st = _Active(req, plen, next_token=first, position=plen,
                         generated=[first])
            self._active[slot] = st
            self._maybe_finish(slot, st)

    # -- the decode loop -----------------------------------------------------

    def step(self) -> bool:
        """One engine iteration: evict, admit, one batched decode step.
        Returns True while there is (or may be) work left."""
        self._evict_expired()
        self._admit()
        if not self._active:
            return bool(self._queue)
        n = self.cache.slots
        tokens = np.zeros((n,), np.int32)
        positions = np.zeros((n,), np.int32)
        for slot, st in self._active.items():
            tokens[slot] = st.next_token
            positions[slot] = st.position
        logits, self.cache.data = self._decode(
            self.params, jnp.asarray(tokens), self.cache.data,
            jnp.asarray(positions))
        self.metrics.step(len(self._active), n)
        logits_np = np.asarray(logits)
        for slot in sorted(self._active):
            st = self._active[slot]
            self.cache.advance(slot)           # the fed token is cached now
            tok = self._sample(st.request, logits_np[slot],
                               len(st.generated))
            self.metrics.token(st.request.request_id)
            st.generated.append(tok)
            st.next_token = tok
            st.position += 1
            self._maybe_finish(slot, st)
        return bool(self._active or self._queue)

    def run(self, max_steps: Optional[int] = None) -> List[Response]:
        """Drive :meth:`step` until every submitted request completes
        (or ``max_steps``); returns responses in completion order."""
        steps = 0
        while self._queue or self._active:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return list(self._done)

    @property
    def completed(self) -> List[Response]:
        return list(self._done)
