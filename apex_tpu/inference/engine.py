"""Continuous-batching serving engine for GPT decode.

Orchestration is host-side and simple by design; the device work is two
jitted programs — one prefill per prompt bucket and ONE batched
``decode_step`` whose batch dimension is the cache slot table:

* admission — while slots are free and requests are queued, each request
  gets one prefill (prompt padded to a power-of-two bucket: causal
  masking makes the pad rows inert) whose K/V lands in its slot and
  whose last-position logits yield the first token (TTFT ends here).
* decode — every step runs ALL slots through ``decode_step``; inactive
  slots compute garbage that is never read (their writes land at stale
  positions that the next prefill overwrites before any valid length
  reaches them).  New requests admit between steps as slots free — no
  batch drain, which is the point of continuous batching.
* completion — eos / ``max_new_tokens`` / cache exhaustion free the
  slot; a request past its ``deadline`` is EVICTED mid-flight with
  whatever it has generated; a request past its per-request ``timeout``
  (a budget relative to submission, distinct from the absolute
  deadline) finishes with ``reason="timeout"``.

Resilience (ISSUE 4): the engine loop must survive its inputs.
``submit`` validates every ``Request`` field it can check statically and
applies bounded-queue backpressure (:class:`QueueFull`); whatever
validation can't catch — a sampling config that only detonates at
decode time, a seed of the wrong type — is QUARANTINED: the per-request
sampling/prefill work is wrapped so a poison request finishes with
``reason="error"`` and frees its slot instead of raising out of
``step()`` and killing every other request in flight.

Determinism: each decode row depends only on its own slot's cache and
token (attention masks by per-row length, norms/linears are per-token),
so greedy decode of a request inside any batch mix is token-identical to
running it alone — asserted by the engine tests.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.inference.kv_cache import KVCache
from apex_tpu.inference.sampling import SamplingParams, sample
from apex_tpu.observability.fleetobs import TraceContext
from apex_tpu.observability.request_trace import RequestTracer
from apex_tpu.utils.profiling import ServingMetrics


class QueueFull(RuntimeError):
    """``submit`` refused a request: the bounded queue is at capacity.
    Explicit backpressure — callers shed load or retry, instead of the
    queue growing without bound until the host OOMs."""


@dataclasses.dataclass
class Request:
    """One generation request.

    ``deadline`` is an absolute value of the engine's ``clock`` (default
    ``time.monotonic``); a request still running past it is evicted.
    ``timeout`` is a RELATIVE budget in clock units from submission —
    queued or decoding, a request over budget finishes with
    ``reason="timeout"`` (deadline eviction answers "the result is no
    longer wanted"; timeout answers "this request used up its share").
    ``seed`` feeds the per-request sampling stream (stochastic modes
    only) — streams are keyed by (seed, token index), never by batch
    composition.  ``trace`` is the fleet-wide causal identity
    (:class:`~apex_tpu.observability.fleetobs.TraceContext`): the
    router mints it, the engines stamp flow events against it, and it
    rides the request through retry/hedge/migration so the merged
    timeline shows one connected flow per request.
    """
    request_id: int
    prompt: Sequence[int]
    max_new_tokens: int = 16
    eos_id: Optional[int] = None
    sampling: SamplingParams = dataclasses.field(
        default_factory=SamplingParams)
    deadline: Optional[float] = None
    timeout: Optional[float] = None
    seed: int = 0
    trace: Optional[TraceContext] = None


@dataclasses.dataclass
class Response:
    """Completed (or evicted) request: ``tokens`` holds the generated
    ids (including the eos token when one was emitted);
    ``finish_reason`` is ``"eos"``, ``"length"`` (max_new_tokens or
    cache row exhausted), ``"evicted"`` (deadline), ``"timeout"``
    (per-request budget), ``"error"`` (poison request quarantined —
    ``error`` carries the exception message) or ``"preempted"`` (the
    engine was preempted and this request could not be requeued —
    :meth:`InferenceEngine.preempt` requeues whenever resume is
    possible, so this is the exception, not the rule).  The fleet
    router (:class:`apex_tpu.serving.FleetRouter`) additionally emits
    router-level responses with ``"shed"`` (retry budget exhausted;
    ``tokens`` carries any progress already streamed) and reuses
    ``"preempted"`` for a migrated request whose context no longer
    fits the target replica."""
    request_id: int
    prompt: List[int]
    tokens: List[int]
    finish_reason: str
    error: Optional[str] = None


@dataclasses.dataclass
class _Active:
    request: Request
    prompt_len: int
    next_token: int        # fed to the next decode step
    position: int          # absolute position next_token is written at
    generated: List[int] = dataclasses.field(default_factory=list)


class InferenceEngine:
    """Continuous batching over a :class:`KVCache` slot ring."""

    def __init__(self, model, params, *, max_slots: int = 8,
                 max_seq: Optional[int] = None, cache_dtype=None,
                 clock: Callable[[], float] = time.monotonic,
                 metrics: Optional[ServingMetrics] = None,
                 registry=None, tracer=None,
                 min_prompt_bucket: int = 8,
                 max_queue: Optional[int] = None,
                 plan=None):
        model._check_decode_supported()
        cfg = model.cfg
        if plan is not None:
            # decode runs one stage deep and token-at-a-time: of the
            # plan, only the tp degree applies, and it must match the
            # model the engine was handed
            if plan.pp > 1:
                raise ValueError(
                    f"serving does not pipeline: plan.pp={plan.pp}")
            if plan.sequence_parallel:
                raise ValueError(
                    "sequence_parallel shards the seq axis the decode "
                    "path appends to; serve with sequence_parallel=False")
            if plan.tp != cfg.tensor_parallel_size:
                raise ValueError(
                    f"plan.tp={plan.tp} does not match the model's "
                    f"tensor_parallel_size={cfg.tensor_parallel_size}; "
                    "build the model from the same plan "
                    "(GPTConfig(plan=plan))")
        self.plan = plan
        self.model = model
        if getattr(cfg, "weight_quant", None) == "int8":
            # quantize ONCE at init (never per step): every jitted
            # program below closes over the int8 tree, and the layer /
            # head dispatch keys on the weight_scale leaves.  Works
            # per-TP-shard unchanged — per-output-channel scales
            # commute with the row slices and only tighten on the
            # column slices
            from apex_tpu.models.gpt import quantize_decode_params
            params = quantize_decode_params(params)
        self.params = params
        # weight HBM per replica (the bench/CI legs' bytes accounting);
        # .nbytes on a jax array is metadata — no host transfer
        self.weight_bytes = int(sum(
            getattr(l, "nbytes", 0)
            for l in jax.tree_util.tree_leaves(params)))
        self.clock = clock
        # `registry` merges this engine's serving series into a shared
        # apex_tpu.observability.MetricsRegistry (one Prometheus/JSONL
        # sink for training + serving); ignored when `metrics` is given
        self.metrics = metrics or ServingMetrics(clock, registry=registry)
        # `tracer` (an observability.Tracer) turns on per-request Chrome
        # trace emission; the lifecycle bookkeeping itself is always on
        # and feeds the queue-wait / decode-ticks serving series
        self.trace = RequestTracer(clock=clock, tracer=tracer,
                                   metrics=self.metrics)
        self._min_bucket = min_prompt_bucket
        if max_queue is not None and max_queue < 1:
            raise ValueError("max_queue must be >= 1 (or None: unbounded)")
        self.max_queue = max_queue
        self._queue: collections.deque = collections.deque()
        # backend fault hooks: the serving fleet's fault injector sets
        # this per tick ("reject_admission" fails submit with QueueFull,
        # "kv_pool_exhaustion" stalls admission); empty in normal runs
        self.injected_faults: frozenset = frozenset()
        self._active: dict = {}          # slot -> _Active
        self._submit_time: dict = {}     # request_id -> submit clock value
        self._progress: dict = {}        # request_id -> tokens generated
                                         # before a preemption requeue
        self._done: List[Response] = []
        self._init_backend(max_slots, max_seq or cfg.max_seq_len,
                           cache_dtype or cfg.dtype)
        # cache-accounting gauges (registry-deduplicated): the router
        # and admission policies read capacity in bytes, not slots
        self._g_kv_free = self.metrics.registry.gauge(
            "serving_kv_free_bytes", "free KV-cache bytes")
        self._g_kv_occ = self.metrics.registry.gauge(
            "serving_kv_occupancy",
            "fraction of KV-cache capacity in use (token-granular)")
        self._export_cache_gauges()

    def _init_backend(self, max_slots: int, max_seq: int,
                      cache_dtype) -> None:
        """Backend hook: build the KV store and the jitted device
        programs.  The base engine is the contiguous slot ring;
        :class:`apex_tpu.serving.PagedInferenceEngine` overrides this
        with the block pool."""
        cfg = self.model.cfg
        self.cache = KVCache(max_slots, cfg.num_layers, max_seq,
                             cfg.local_heads, cfg.head_dim, cache_dtype)
        self.max_seq = self.cache.max_seq
        # the cache buffer threads through every step: donate it so XLA
        # updates it in place — without donation every decode step holds
        # TWO full caches (the lint rule donation/missing).  Donation
        # works on every backend when the output aliases the input
        # shape/dtype, which the cache ring guarantees; step() rebinds
        # self.cache.data from the output, so nothing re-reads the
        # donated buffer
        self._decode = jax.jit(self.model.decode_step, donate_argnums=(2,))
        self._prefill = jax.jit(self.model.prefill)

    def _export_cache_gauges(self) -> None:
        self._g_kv_free.set(self.cache.free_bytes())
        self._g_kv_occ.set(self.cache.occupancy())

    # -- request lifecycle ---------------------------------------------------

    def _validate(self, request: Request) -> None:
        """Reject statically-checkable poison at the door (what this
        can't see — e.g. a sampling config that only fails at decode
        time — the step-loop quarantine catches)."""
        if not 0 < len(request.prompt) < self.max_seq:
            raise ValueError(
                f"prompt length {len(request.prompt)} must be in "
                f"(0, {self.max_seq}) to leave room for decode")
        vocab = self.model.cfg.vocab_size
        for t in request.prompt:
            if not isinstance(t, (int, np.integer)) or not 0 <= t < vocab:
                raise ValueError(
                    f"prompt token {t!r} is not an int in [0, {vocab})")
        if not isinstance(request.max_new_tokens, (int, np.integer)) \
                or request.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens {request.max_new_tokens!r} must be a "
                "positive int")
        if not isinstance(request.sampling, SamplingParams):
            raise ValueError(
                f"sampling must be a SamplingParams, got "
                f"{type(request.sampling).__name__}")
        if request.eos_id is not None and not isinstance(
                request.eos_id, (int, np.integer)):
            raise ValueError(f"eos_id {request.eos_id!r} must be an int")
        if request.timeout is not None and not request.timeout > 0:
            raise ValueError(
                f"timeout {request.timeout!r} must be positive")

    def submit(self, request: Request) -> None:
        """Validate and enqueue; raises :class:`QueueFull` when the
        bounded queue is at capacity (explicit backpressure — nothing is
        silently dropped)."""
        self._validate(request)
        if "reject_admission" in self.injected_faults:
            raise QueueFull("injected fault: admission rejected at this "
                            "replica")
        if self.max_queue is not None and len(self._queue) >= self.max_queue:
            raise QueueFull(
                f"submit queue is full ({len(self._queue)}/"
                f"{self.max_queue}); retry after step() drains it")
        self._submit_time[request.request_id] = self.clock()
        self.metrics.request_submitted(request.request_id)
        self.trace.enqueue(request.request_id, ctx=request.trace)
        self._queue.append(request)

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def active_requests(self) -> int:
        return len(self._active)

    def _bucket(self, n: int) -> int:
        b = self._min_bucket
        while b < n:
            b *= 2
        return min(b, self.max_seq)

    def _sample(self, req: Request, logits_row, token_index: int) -> int:
        if req.sampling.greedy:
            return int(np.argmax(logits_row))
        key = jax.random.fold_in(jax.random.PRNGKey(req.seed),
                                 token_index)
        return int(sample(jnp.asarray(logits_row), req.sampling, key))

    def _release(self, slot: int, st: _Active) -> None:
        """Backend hook: return ``slot``'s KV storage (a cache row here;
        pool blocks + the draft row in the paged engine)."""
        self.cache.free(slot)

    def _finish(self, slot: int, st: _Active, reason: str,
                error: Optional[str] = None) -> None:
        self._release(slot, st)
        del self._active[slot]
        self._finish_response(st.request, st.generated, reason, error)

    def _finish_response(self, req: Request, generated: List[int],
                         reason: str, error: Optional[str] = None) -> None:
        """Common completion tail for active AND still-queued requests:
        metrics dispatch + the Response record."""
        self._submit_time.pop(req.request_id, None)
        self._progress.pop(req.request_id, None)
        if reason == "evicted":
            self.metrics.request_evicted(req.request_id)
        elif reason == "timeout":
            self.metrics.request_timeout(req.request_id)
        elif reason == "error":
            self.metrics.request_error(req.request_id)
        else:
            # eos/length: the metrics layer drops the request's
            # transient state (TTFT bookkeeping) — every terminal path
            # must reach ServingMetrics or the engine leaks an entry
            # per request
            self.metrics.request_finished(req.request_id, reason)
        self.trace.finish(req.request_id, reason, error=error)
        self._done.append(Response(req.request_id, list(req.prompt),
                                   generated, reason, error=error))

    def _maybe_finish(self, slot: int, st: _Active) -> bool:
        req = st.request
        if req.eos_id is not None and st.generated[-1] == req.eos_id:
            self._finish(slot, st, "eos")
        elif len(st.generated) >= req.max_new_tokens:
            self._finish(slot, st, "length")
        elif st.position >= self.max_seq:
            self._finish(slot, st, "length")      # cache row exhausted
        else:
            return False
        return True

    def _evict_expired(self) -> None:
        now = self.clock()

        def expired(req):
            # deadline wins when both trip the same tick: "no longer
            # wanted" is the stronger statement than "over budget"
            if req.deadline is not None and now >= req.deadline:
                return "evicted"
            if req.timeout is not None:
                t0 = self._submit_time.get(req.request_id)
                if t0 is not None and now - t0 >= req.timeout:
                    return "timeout"
            return None

        for slot in [s for s in sorted(self._active)
                     if expired(self._active[s].request)]:
            st = self._active[slot]
            self._finish(slot, st, expired(st.request))
        keep: collections.deque = collections.deque()
        while self._queue:
            req = self._queue.popleft()
            reason = expired(req)
            if reason:
                # a requeued request keeps its partial progress in the
                # terminal Response
                done = self._progress.get(req.request_id, [])
                self._finish_response(req, list(done), reason)
            else:
                keep.append(req)
        self._queue = keep

    def preempt(self) -> int:
        """Drain on preemption: requeue every in-flight request instead
        of dropping it.  Each active request's slot is freed, its
        generated-so-far tokens are stashed, and the request goes back
        to the FRONT of the queue (lowest slot first — nearest to done,
        first re-admitted); the next :meth:`_admit` re-prefills prompt +
        generated and resumes the per-request sampling stream at the
        token index it stopped at, so greedy (and seeded stochastic)
        outputs are unchanged by the interruption.  Timeout budgets keep
        running across the requeue (the interruption is the server's
        fault, but the deadline semantics are the client's).  Returns
        the number of requests requeued.  A request whose context no
        longer fits a cache row finishes with ``reason="preempted"``
        instead.
        """
        requeued = 0
        for slot in sorted(self._active, reverse=True):
            requeued += self._preempt_slot(slot)
        return requeued

    def _preempt_slot(self, slot: int) -> int:
        """Requeue one in-flight request (the per-slot body of
        :meth:`preempt`; the paged engine also invokes it to reclaim
        blocks under pool pressure).  Returns 1 when requeued, 0 when
        the request had to finish instead."""
        st = self._active[slot]
        req = st.request
        if len(req.prompt) + len(st.generated) >= self.max_seq:
            self._finish(slot, st, "preempted")
            return 0
        self._release(slot, st)
        del self._active[slot]
        self._progress[req.request_id] = list(st.generated)
        self.metrics.request_requeued(req.request_id)
        self.trace.requeue(req.request_id)
        self._queue.appendleft(req)
        return 1

    def adopt(self, request: Request, progress: Sequence[int] = ()) -> None:
        """Admit a request migrated from another replica: ``progress``
        is the tokens it already streamed there.  Validation and
        backpressure are :meth:`submit`'s; the progress stash makes the
        next :meth:`_admit` re-prefill ``prompt + progress`` and resume
        the ``(seed, token-index)`` sampling stream at
        ``len(progress)`` — the cross-replica form of the preemption
        requeue, token-bitwise for the same reason."""
        if len(request.prompt) + len(progress) >= self.max_seq:
            raise ValueError(
                f"context {len(request.prompt)} + {len(progress)} does "
                f"not fit max_seq={self.max_seq}; finish with "
                "reason='preempted' instead of adopting")
        self.submit(request)
        if progress:
            self._progress[request.request_id] = list(progress)

    def export_inflight(self) -> List:
        """Strip every in-flight and queued request off this engine for
        cross-replica migration; returns ``[(request, generated)]`` in
        the preemption-requeue order (ascending slot — nearest to done
        first — then the waiting queue).  ``generated`` is exactly what
        was already streamed to the client, which is why a replica that
        dies without warning still leaves its requests recoverable: a
        healthy replica :meth:`adopt`\\ s each one and the resumed
        stream is token-bitwise the uninterrupted one.  On THIS engine
        each request terminates with reason ``"migrated"`` (metrics +
        trace, no Response — the adopting replica owns the eventual
        Response)."""
        out = []
        for slot in sorted(self._active):
            st = self._active[slot]
            out.append((st.request, list(st.generated)))
        for slot in sorted(self._active, reverse=True):
            st = self._active.pop(slot)
            self._release(slot, st)
        while self._queue:
            req = self._queue.popleft()
            out.append((req, list(self._progress.get(req.request_id, []))))
        for req, _ in out:
            rid = req.request_id
            self._submit_time.pop(rid, None)
            self._progress.pop(rid, None)
            self.metrics.request_migrated(rid)
            self.trace.finish(rid, "migrated")
        return out

    def cancel(self, request_id) -> bool:
        """Withdraw one request with NO Response (the fleet uses this
        for the losing copy of a hedged dispatch): frees its slot or
        queue entry, terminal metrics reason ``"cancelled"``.  Returns
        False when the id is not on this engine."""
        for slot, st in list(self._active.items()):
            if st.request.request_id == request_id:
                self._release(slot, st)
                del self._active[slot]
                break
        else:
            hit = None
            for req in self._queue:
                if req.request_id == request_id:
                    hit = req
                    break
            if hit is None:
                return False
            self._queue.remove(hit)
        self._submit_time.pop(request_id, None)
        self._progress.pop(request_id, None)
        self.metrics.request_cancelled(request_id)
        self.trace.finish(request_id, "cancelled")
        return True

    def _admit(self) -> None:
        if "kv_pool_exhaustion" in self.injected_faults:
            return                      # injected: no capacity to admit
        while self._queue and self.cache.free_slots:
            req = self._queue.popleft()
            slot = self.cache.allocate()
            prev = self._progress.pop(req.request_id, None)
            if prev is None:
                self.trace.admit(req.request_id)
            try:
                plen = len(req.prompt)
                ctx = list(req.prompt) + (prev or [])
                clen = len(ctx)
                toks = np.zeros((1, self._bucket(clen)), np.int32)
                toks[0, :clen] = ctx
                logits, kv = self._prefill(self.params, jnp.asarray(toks))
                self.cache.write_prompt(slot, kv[:, :, 0], clen)
                nxt = self._sample(req, np.asarray(logits[0, clen - 1]),
                                   len(prev or []))
            except Exception as e:          # quarantine: free the slot,
                self.cache.free(slot)       # fail ONE request, keep going
                self._finish_response(req, list(prev or []), "error",
                                      error=f"{type(e).__name__}: {e}")
                continue
            if prev is None:
                self.metrics.first_token(req.request_id)
                self.trace.first_token(req.request_id)
            else:
                # a resumed request's TTFT already happened; the token
                # re-enters the throughput series only
                self.metrics.token(req.request_id)
                self.trace.decode_tick(req.request_id)
                self.trace.resumed(req.request_id)
            st = _Active(req, plen, next_token=nxt, position=clen,
                         generated=(prev or []) + [nxt])
            self._active[slot] = st
            self._maybe_finish(slot, st)

    # -- the decode loop -----------------------------------------------------

    def step(self) -> bool:
        """One engine iteration: evict, admit, one batched decode step.
        Returns True while there is (or may be) work left."""
        self._evict_expired()
        self._admit()
        self._export_cache_gauges()
        if not self._active:
            return bool(self._queue)
        n = self.cache.slots
        tokens = np.zeros((n,), np.int32)
        positions = np.zeros((n,), np.int32)
        for slot, st in self._active.items():
            tokens[slot] = st.next_token
            positions[slot] = st.position
        logits, self.cache.data = self._decode(
            self.params, jnp.asarray(tokens), self.cache.data,
            jnp.asarray(positions))
        self.metrics.step(len(self._active), n)
        self._advance_slots(sorted(self._active), np.asarray(logits))
        return bool(self._active or self._queue)

    def _cache_advance(self, slot: int, st: _Active) -> None:
        """Backend hook: record that the fed token's K/V is cached."""
        self.cache.advance(slot)

    def _advance_slots(self, slots: Sequence[int], logits_np) -> None:
        """Post-decode tail shared by every backend: sample each row at
        its stream index, append, and run the completion checks.  This
        being single-sourced is what keeps the paged engine's sampling
        stream bitwise-identical to the contiguous one."""
        for slot in slots:
            st = self._active[slot]
            self._cache_advance(slot, st)      # the fed token is cached now
            try:
                tok = self._sample(st.request, logits_np[slot],
                                   len(st.generated))
            except Exception as e:      # poison sampling config detonated
                self._finish(slot, st, "error",
                             error=f"{type(e).__name__}: {e}")
                continue
            self.metrics.token(st.request.request_id)
            self.trace.decode_tick(st.request.request_id)
            st.generated.append(tok)
            st.next_token = tok
            st.position += 1
            self._maybe_finish(slot, st)

    def run(self, max_steps: Optional[int] = None) -> List[Response]:
        """Drive :meth:`step` until every submitted request completes
        (or ``max_steps``); returns responses in completion order."""
        steps = 0
        while self._queue or self._active:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return list(self._done)

    @property
    def completed(self) -> List[Response]:
        return list(self._done)
