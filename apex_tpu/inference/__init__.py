"""apex_tpu.inference — KV-cache decode + continuous-batching serving.

The reference covers training only; this subsystem is the
beyond-reference serving leg (ROADMAP "inference story").  Three layers:

* :class:`KVCache` — a preallocated per-slot cache ring
  ``(slots, layers, 2, max_seq, kv_heads, head_dim)`` with host-side
  slot allocation and dtype control (bf16 cache, f32 attention
  accumulation).
* sampling — :class:`SamplingParams` / :func:`sample`: greedy,
  temperature, top-k.
* :class:`InferenceEngine` — continuous batching over the slot ring:
  requests admit as slots free (one prefill each), then ride a single
  batched ``decode_step`` whose batch dimension IS the slot table.
  Per-row math is independent, so batched greedy decode is
  token-identical to decoding each request alone.

Model side: :meth:`apex_tpu.models.gpt.GPTModel.prefill` /
``decode_step`` reuse the TP layers unchanged (serial and shard_map);
the decode attention kernel is
:func:`apex_tpu.ops.flash_attention.flash_attention_decode`.
"""

from apex_tpu.inference.engine import (InferenceEngine, QueueFull, Request,
                                       Response)
from apex_tpu.inference.kv_cache import KVCache
from apex_tpu.inference.sampling import SamplingParams, sample

__all__ = [
    "InferenceEngine",
    "KVCache",
    "QueueFull",
    "Request",
    "Response",
    "SamplingParams",
    "sample",
]
