"""apex_tpu — a TPU-native rebuild of NVIDIA Apex (reference: alpha0422/apex).

Apex is a collection of CUDA-fused training extensions layered on PyTorch
(reference layout: ``apex/__init__.py``).  apex_tpu provides the same
capability surface — a mixed-precision engine (``apex_tpu.amp``), fused
multi-tensor optimizers (``apex_tpu.optimizers``), fused norm/attention/loss
ops (``apex_tpu.normalization``, ``apex_tpu.contrib``), a data-parallel layer
(``apex_tpu.parallel``), and a Megatron-style tensor/pipeline/sequence
parallel stack (``apex_tpu.transformer``) — designed TPU-first:

* device code is JAX/XLA with Pallas (Mosaic) kernels where fusion matters,
  instead of CUDA;
* collectives are GSPMD shardings / ``shard_map`` collectives compiled over
  ICI/DCN, instead of NCCL;
* mixed precision lowers to bf16 dtype policies with (optional) dynamic loss
  scaling, instead of monkey-patched fp16 casts.

The package is functional: optimizers and amp states are explicit pytrees
(JAX-style), but constructor signatures and module names mirror apex so a
user of the reference can find every component under the same name.
"""

from apex_tpu._version import __version__

# Subpackages are imported lazily to keep `import apex_tpu` cheap and to let
# optional pieces degrade independently (mirrors apex/__init__.py's guarded
# optional imports of amp/fp16_utils/optimizers/normalization/...).
import importlib as _importlib

_SUBMODULES = (
    "RNN",
    "amp",
    "contrib",
    "fp16_utils",
    "fused_dense",
    "inference",
    "mlp",
    "models",
    "multi_tensor_apply",
    "normalization",
    "observability",
    "ops",
    "optimizers",
    "parallel",
    "resilience",
    "transformer",
    "utils",
)


def __getattr__(name):
    if name in _SUBMODULES:
        try:
            return _importlib.import_module(f"apex_tpu.{name}")
        except ModuleNotFoundError as e:
            if e.name == f"apex_tpu.{name}":
                # Keep hasattr()/getattr(default) feature-probing working —
                # the apex pattern for optional components.
                raise AttributeError(
                    f"apex_tpu submodule {name!r} is not available") from None
            raise
    raise AttributeError(f"module 'apex_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_SUBMODULES))
