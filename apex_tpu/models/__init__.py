"""Flagship model families built from apex_tpu components (reference:
``apex/transformer/testing/standalone_gpt.py`` / ``standalone_bert.py`` —
test-only toys upstream, production models here)."""

from apex_tpu.models import gpt  # noqa: F401
