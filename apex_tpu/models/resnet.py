"""ResNet model family for the imagenet example + SyncBN/bottleneck tests
(reference: apex's flagship CNN workload — ``examples/imagenet/main_amp.py``
trains torchvision ResNet-50 under amp; apex itself supplies the fused
pieces: SyncBatchNorm, groupbn NHWC, contrib.bottleneck).

TPU-first layout: **NHWC** everywhere (the MXU-friendly conv layout; the
reference's NHWC path is its fast case too), batch norm via the framework's
functional :func:`apex_tpu.parallel.sync_batchnorm.sync_batch_norm` so a
single ``axis_name`` switch turns every BN into cross-device SyncBN for the
Mask-R-CNN-style recipes (BASELINE workload 4).

Functional state: ``params`` (trainable) and ``state`` (BN running stats)
are separate pytrees; ``apply`` returns ``(logits, new_state)``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from apex_tpu.parallel.sync_batchnorm import BatchNormState, sync_batch_norm

_f32 = jnp.float32
_DN = ("NHWC", "HWIO", "NHWC")


@dataclasses.dataclass
class ResNetConfig:
    depths: Sequence[int] = (3, 4, 6, 3)       # ResNet-50
    width: int = 64
    num_classes: int = 1000
    axis_name: Optional[str] = None            # SyncBN over this mesh axis
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    dtype: jnp.dtype = jnp.float32             # activation/compute dtype
    param_dtype: jnp.dtype = jnp.float32

    @property
    def stage_channels(self):
        return [self.width * (2 ** i) for i in range(len(self.depths))]


def resnet50(**kw) -> "ResNet":
    return ResNet(ResNetConfig(depths=(3, 4, 6, 3), **kw))


def resnet26(**kw) -> "ResNet":
    """Bottleneck (2, 2, 2, 2) network — the thin end of this family.

    Every block here is a bottleneck with 4x expansion, so this is
    torchvision's *resnet26*-shaped network, NOT basic-block ResNet-18
    (different depth and ~2x the parameters).  Basic blocks are out of
    scope for this family; recipes expecting torchvision ``resnet18``
    weights/params must not assume parity with this constructor.
    """
    return ResNet(ResNetConfig(depths=(2, 2, 2, 2), **kw))


def resnet18(**kw) -> "ResNet":
    """Deprecated alias for :func:`resnet26` — kept for recipe-name
    parity only; see that docstring for why the shapes differ from
    torchvision's basic-block ResNet-18."""
    return resnet26(**kw)


def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    return jax.random.normal(key, (kh, kw, cin, cout), dtype) * fan_in ** -0.5


def _conv(x, w, stride=1, padding="SAME"):
    return jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), window_strides=(stride, stride),
        padding=padding, dimension_numbers=_DN)


class _ConvBN:
    """conv → BN(→ReLU) unit; BN is SyncBN when cfg.axis_name is set."""

    def __init__(self, cfg, kh, kw, cin, cout, stride=1):
        self.cfg, self.kh, self.kw = cfg, kh, kw
        self.cin, self.cout, self.stride = cin, cout, stride

    def init_params(self, key):
        return {"weight": _conv_init(key, self.kh, self.kw, self.cin,
                                     self.cout, self.cfg.param_dtype),
                "bn_weight": jnp.ones((self.cout,), _f32),
                "bn_bias": jnp.zeros((self.cout,), _f32)}

    def init_state(self):
        return BatchNormState(jnp.zeros((self.cout,), _f32),
                              jnp.ones((self.cout,), _f32),
                              jnp.zeros((), jnp.int32))

    def __call__(self, params, state, x, *, training, relu=True):
        h = _conv(x, params["weight"], self.stride)
        h, new_state = sync_batch_norm(
            h, params["bn_weight"], params["bn_bias"], state,
            training=training, momentum=self.cfg.bn_momentum,
            eps=self.cfg.bn_eps, axis_name=self.cfg.axis_name,
            channel_last=True)
        if relu:
            h = jax.nn.relu(h)
        return h, new_state


class _BottleneckBlock:
    """1x1 → 3x3(stride) → 1x1(×4) + residual, trainable BN (torchvision
    Bottleneck; the frozen-BN fused variant is
    ``apex_tpu.contrib.bottleneck.Bottleneck``)."""

    def __init__(self, cfg, cin, cmid, stride):
        cout = 4 * cmid
        self.units = {
            "conv1": _ConvBN(cfg, 1, 1, cin, cmid),
            "conv2": _ConvBN(cfg, 3, 3, cmid, cmid, stride),
            "conv3": _ConvBN(cfg, 1, 1, cmid, cout),
        }
        self.downsample = None
        if stride != 1 or cin != cout:
            self.downsample = _ConvBN(cfg, 1, 1, cin, cout, stride)
        self.cout = cout

    def init_params(self, key):
        names = list(self.units) + (["downsample"] if self.downsample
                                    else [])
        keys = jax.random.split(key, len(names))
        out = {n: self.units[n].init_params(k)
               for n, k in zip(names, keys) if n in self.units}
        if self.downsample:
            out["downsample"] = self.downsample.init_params(keys[-1])
        return out

    def init_state(self):
        out = {n: u.init_state() for n, u in self.units.items()}
        if self.downsample:
            out["downsample"] = self.downsample.init_state()
        return out

    def __call__(self, params, state, x, *, training):
        ns = {}
        h, ns["conv1"] = self.units["conv1"](params["conv1"],
                                             state["conv1"], x,
                                             training=training)
        h, ns["conv2"] = self.units["conv2"](params["conv2"],
                                             state["conv2"], h,
                                             training=training)
        h, ns["conv3"] = self.units["conv3"](params["conv3"],
                                             state["conv3"], h,
                                             training=training, relu=False)
        if self.downsample:
            r, ns["downsample"] = self.downsample(
                params["downsample"], state["downsample"], x,
                training=training, relu=False)
        else:
            r = x
        return jax.nn.relu(h + r), ns


class ResNet:
    """apply: ``(params, state, images_nhwc, training) -> (logits,
    new_state)``; ``loss`` adds softmax cross entropy over classes."""

    def __init__(self, cfg: ResNetConfig):
        self.cfg = cfg
        self.stem = _ConvBN(cfg, 7, 7, 3, cfg.width, stride=2)
        self.blocks = []
        cin = cfg.width
        for stage, (depth, cmid) in enumerate(
                zip(cfg.depths, cfg.stage_channels)):
            for i in range(depth):
                stride = 2 if (i == 0 and stage > 0) else 1
                blk = _BottleneckBlock(cfg, cin, cmid, stride)
                self.blocks.append(blk)
                cin = blk.cout
        self.feat_dim = cin

    def init_params(self, key):
        keys = jax.random.split(key, len(self.blocks) + 2)
        head_w = jax.random.normal(
            keys[-1], (self.feat_dim, self.cfg.num_classes),
            self.cfg.param_dtype) * self.feat_dim ** -0.5
        return {
            "stem": self.stem.init_params(keys[0]),
            "blocks": [b.init_params(k)
                       for b, k in zip(self.blocks, keys[1:-1])],
            "head": {"weight": head_w,
                     "bias": jnp.zeros((self.cfg.num_classes,),
                                       self.cfg.param_dtype)},
        }

    def init_state(self):
        return {"stem": self.stem.init_state(),
                "blocks": [b.init_state() for b in self.blocks]}

    def apply(self, params, state, x, training: bool = True):
        x = x.astype(self.cfg.dtype)
        h, stem_state = self.stem(params["stem"], state["stem"], x,
                                  training=training)
        h = jax.lax.reduce_window(
            h, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
            "SAME")
        block_states = []
        for blk, p, s in zip(self.blocks, params["blocks"],
                             state["blocks"]):
            h, ns = blk(p, s, h, training=training)
            block_states.append(ns)
        h = jnp.mean(h, axis=(1, 2))                       # global avg pool
        logits = (h.astype(_f32) @ params["head"]["weight"].astype(_f32)
                  + params["head"]["bias"].astype(_f32))
        return logits, {"stem": stem_state, "blocks": block_states}

    __call__ = apply

    def loss(self, params, state, x, labels, training: bool = True):
        logits, new_state = self.apply(params, state, x, training=training)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
        return jnp.mean(nll), new_state
