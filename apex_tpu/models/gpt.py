"""GPT model built from apex_tpu components — the flagship model family
(reference: ``apex/transformer/testing/standalone_gpt.py``, which wires
apex's TP layers/fused ops into a Megatron-style GPT for the L0 tests; the
same wiring here is the production model).

Every compute block is a framework component: VocabParallelEmbedding,
ColumnParallelLinear/RowParallelLinear (TP + sequence parallel),
MixedFusedLayerNorm (Pallas), fused RoPE, FusedScaleMaskSoftmax (causal),
vocab-parallel cross entropy.  One config serves three execution modes:

* serial  — ``tensor_parallel_size=1, axis_name=None`` (tests, single chip)
* GSPMD   — jit the serial form with ``partition_specs()``
* shard_map — ``axis_name="model"`` with sharded params; combine with the
  pipeline engine by stacking layer params per stage.

Activations are ``(batch, seq, hidden)``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.normalization import MixedFusedLayerNorm
from apex_tpu.ops.rope import fused_apply_rotary_pos_emb_cached, rope_freqs
from apex_tpu.ops.softmax import scaled_upper_triang_masked_softmax
from apex_tpu.transformer import tensor_parallel as tp

_f32 = jnp.float32


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_attention_heads: int = 12
    max_seq_len: int = 1024
    ffn_hidden_size: Optional[int] = None      # default 4*hidden
    tensor_parallel_size: int = 1
    axis_name: Optional[str] = None            # "model" inside shard_map
    sequence_parallel: bool = False
    rotary: bool = True
    dtype: jnp.dtype = jnp.float32             # activation/compute dtype
    param_dtype: jnp.dtype = jnp.float32

    def __post_init__(self):
        if self.ffn_hidden_size is None:
            self.ffn_hidden_size = 4 * self.hidden_size
        if self.hidden_size % self.num_attention_heads:
            raise ValueError(
                "hidden_size must be divisible by num_attention_heads")
        if self.num_attention_heads % self.tensor_parallel_size:
            raise ValueError(
                "num_attention_heads must be divisible by "
                "tensor_parallel_size")

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @property
    def local_heads(self):
        return self.num_attention_heads // self.tensor_parallel_size


class ParallelAttention:
    """Causal self-attention: TP-sharded QKV/proj, fused RoPE + softmax
    (apex ``transformer`` attention with FusedScaleMaskSoftmax.causal)."""

    def __init__(self, cfg: GPTConfig):
        self.cfg = cfg
        self.qkv = tp.ColumnParallelLinear(
            cfg.hidden_size, 3 * cfg.hidden_size, gather_output=False,
            world_size=cfg.tensor_parallel_size, axis_name=cfg.axis_name,
            sequence_parallel_enabled=cfg.sequence_parallel,
            param_dtype=cfg.param_dtype)
        self.proj = tp.RowParallelLinear(
            cfg.hidden_size, cfg.hidden_size, input_is_parallel=True,
            world_size=cfg.tensor_parallel_size, axis_name=cfg.axis_name,
            sequence_parallel_enabled=cfg.sequence_parallel,
            param_dtype=cfg.param_dtype)

    def init_params(self, key):
        k1, k2 = jax.random.split(key)
        return {"qkv": self.qkv.init_params(k1),
                "proj": self.proj.init_params(k2)}

    def __call__(self, params, x, rope_cos=None, rope_sin=None):
        cfg = self.cfg
        b = x.shape[0]
        qkv, _ = self.qkv(params["qkv"], x)      # (b, s, 3h/t)
        s = qkv.shape[1]
        nh = qkv.shape[-1] // (3 * cfg.head_dim)
        qkv = qkv.reshape(b, s, nh, 3 * cfg.head_dim)
        q, k, v = jnp.split(qkv, 3, axis=-1)     # (b, s, nh, hd)
        if rope_cos is not None:
            # fused rope expects (seq, batch, heads, dim)
            q = fused_apply_rotary_pos_emb_cached(
                q.transpose(1, 0, 2, 3), rope_cos, rope_sin
            ).transpose(1, 0, 2, 3)
            k = fused_apply_rotary_pos_emb_cached(
                k.transpose(1, 0, 2, 3), rope_cos, rope_sin
            ).transpose(1, 0, 2, 3)
        # (b, nh, s, hd)
        q = q.transpose(0, 2, 1, 3)
        k = k.transpose(0, 2, 1, 3)
        v = v.transpose(0, 2, 1, 3)
        scale = 1.0 / jnp.sqrt(cfg.head_dim).astype(_f32)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                            preferred_element_type=_f32)
        probs = scaled_upper_triang_masked_softmax(
            scores.reshape(b * nh, s, s), float(scale))
        probs = probs.reshape(b, nh, s, s).astype(v.dtype)
        ctx = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, nh * cfg.head_dim)
        out, _ = self.proj(params["proj"], ctx)
        return out


class ParallelMLP:
    """Column→GELU→Row block (apex ParallelMLP)."""

    def __init__(self, cfg: GPTConfig):
        self.cfg = cfg
        self.fc1 = tp.ColumnParallelLinear(
            cfg.hidden_size, cfg.ffn_hidden_size, gather_output=False,
            world_size=cfg.tensor_parallel_size, axis_name=cfg.axis_name,
            sequence_parallel_enabled=cfg.sequence_parallel,
            param_dtype=cfg.param_dtype)
        self.fc2 = tp.RowParallelLinear(
            cfg.ffn_hidden_size, cfg.hidden_size, input_is_parallel=True,
            world_size=cfg.tensor_parallel_size, axis_name=cfg.axis_name,
            sequence_parallel_enabled=cfg.sequence_parallel,
            param_dtype=cfg.param_dtype)

    def init_params(self, key):
        k1, k2 = jax.random.split(key)
        return {"fc1": self.fc1.init_params(k1),
                "fc2": self.fc2.init_params(k2)}

    def __call__(self, params, x):
        h, _ = self.fc1(params["fc1"], x)
        h = jax.nn.gelu(h, approximate=True)
        y, _ = self.fc2(params["fc2"], h)
        return y


class ParallelTransformerLayer:
    """Pre-LN transformer block (apex ParallelTransformerLayer)."""

    def __init__(self, cfg: GPTConfig):
        self.cfg = cfg
        self.input_layernorm = MixedFusedLayerNorm(cfg.hidden_size)
        self.post_attention_layernorm = MixedFusedLayerNorm(cfg.hidden_size)
        self.attention = ParallelAttention(cfg)
        self.mlp = ParallelMLP(cfg)

    def init_params(self, key):
        k1, k2 = jax.random.split(key)
        return {"input_layernorm": self.input_layernorm.init_params(),
                "attention": self.attention.init_params(k1),
                "post_attention_layernorm":
                    self.post_attention_layernorm.init_params(),
                "mlp": self.mlp.init_params(k2)}

    def __call__(self, params, x, rope_cos=None, rope_sin=None):
        h = self.input_layernorm(params["input_layernorm"], x)
        x = x + self.attention(params["attention"], h, rope_cos, rope_sin)
        h = self.post_attention_layernorm(params["post_attention_layernorm"],
                                          x)
        return x + self.mlp(params["mlp"], h)


class GPTModel:
    """Full decoder LM: vocab-parallel embedding → N layers → final LN →
    tied vocab-parallel head → (optional) vocab-parallel xent loss."""

    def __init__(self, cfg: GPTConfig):
        self.cfg = cfg
        self.embedding = tp.VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size,
            world_size=cfg.tensor_parallel_size, axis_name=cfg.axis_name,
            param_dtype=cfg.param_dtype)
        self.layers = [ParallelTransformerLayer(cfg)
                       for _ in range(cfg.num_layers)]
        self.final_layernorm = MixedFusedLayerNorm(cfg.hidden_size)

    def init_params(self, key):
        keys = jax.random.split(key, self.cfg.num_layers + 2)
        params = {
            "embedding": self.embedding.init_params(keys[0]),
            "layers": [l.init_params(k)
                       for l, k in zip(self.layers, keys[1:-1])],
            "final_layernorm": self.final_layernorm.init_params(),
        }
        if not self.cfg.rotary:
            params["position_embedding"] = 0.02 * jax.random.normal(
                keys[-1], (self.cfg.max_seq_len, self.cfg.hidden_size),
                self.cfg.param_dtype)
        return params

    def rope_tables(self, seq_len):
        if not self.cfg.rotary:
            return None, None
        f = rope_freqs(seq_len, self.cfg.head_dim)
        return jnp.cos(f), jnp.sin(f)

    def embed(self, params, tokens):
        x = self.embedding(params["embedding"], tokens)
        if not self.cfg.rotary:
            x = x + params["position_embedding"][:tokens.shape[1]]
        return x.astype(self.cfg.dtype)

    def backbone(self, params, x, seq_len=None):
        cos, sin = self.rope_tables(seq_len or x.shape[1])
        for layer, lp in zip(self.layers, params["layers"]):
            x = layer(lp, x, cos, sin)
        return x

    def logits(self, params, x):
        """Tied LM head: vocab-parallel logits ``(b, s, vocab/t)``."""
        x = self.final_layernorm(params["final_layernorm"], x)
        w = params["embedding"]["weight"]
        return jnp.einsum("bsh,vh->bsv", x.astype(_f32),
                          w.astype(_f32))

    def __call__(self, params, tokens):
        x = self.embed(params, tokens)
        x = self.backbone(params, x)
        return self.logits(params, x)

    apply = __call__

    def loss(self, params, tokens, targets):
        """Mean next-token loss via vocab-parallel cross entropy."""
        logits = self(params, tokens)
        b, s, vl = logits.shape
        per = tp.vocab_parallel_cross_entropy(
            logits.reshape(b * s, vl), targets.reshape(b * s),
            axis_name=self.cfg.axis_name)
        return jnp.mean(per)
