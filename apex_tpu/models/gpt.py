"""GPT model built from apex_tpu components — the flagship model family
(reference: ``apex/transformer/testing/standalone_gpt.py``, which wires
apex's TP layers/fused ops into a Megatron-style GPT for the L0 tests; the
same wiring here is the production model).

Every compute block is a framework component: VocabParallelEmbedding,
ColumnParallelLinear/RowParallelLinear (TP + sequence parallel),
MixedFusedLayerNorm (Pallas), fused RoPE, causal flash attention (Pallas),
vocab-parallel cross entropy.  One config serves three execution modes:

* serial  — ``tensor_parallel_size=1, axis_name=None`` (tests, single chip)
* GSPMD   — jit the serial form with ``partition_specs()``
* shard_map — ``axis_name="model"`` with sharded params; combine with the
  pipeline engine by stacking layer params per stage.

Activations are ``(batch, seq, hidden)``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.normalization import MixedFusedLayerNorm
from apex_tpu.ops.flash_attention import (flash_attention,
                                          flash_attention_chunk_paged,
                                          flash_attention_decode,
                                          flash_attention_decode_paged,
                                          flash_attention_decode_paged_quant,
                                          quantize_kv_blocks)
from apex_tpu.ops.fused_ffn import fused_ffn_tp
from apex_tpu.ops.rope import (fused_apply_rotary_pos_emb_at_positions,
                               fused_apply_rotary_pos_emb_cached, rope_freqs)
from apex_tpu.transformer import tensor_parallel as tp
from apex_tpu.utils.collectives import axis_size as _axis_size

_f32 = jnp.float32

# Dropout-stream strides: layer i / microbatch m walk the seed space at
# large odd strides (bijective mod 2^32, int32 wraparound is fine) so a
# caller advancing the base seed by +1 per training step can never land
# on a neighboring layer's or microbatch's stream from another step —
# with stride 1 ("seed + i"), step t+1 layer i would replay step t
# layer i+1's mask exactly.
_SEED_LAYER_STRIDE = 0x3C6EF35F
_SEED_MB_STRIDE = 0x5BD1E995
_SEED_TP_RANK_STRIDE = 0x7F4A7C15  # per-TP-rank dropout stream offset


def _remat_policy(name: str):
    """jax.checkpoint policy for a GPTConfig.remat_policy name."""
    if name == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    return None                                  # "full": save nothing


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_attention_heads: int = 12
    max_seq_len: int = 1024
    ffn_hidden_size: Optional[int] = None      # default 4*hidden
    tensor_parallel_size: int = 1
    axis_name: Optional[str] = None            # "model" inside shard_map
    sequence_parallel: bool = False
    overlap_chunks: int = 0                    # >0: ppermute-ring TP GEMMs
    rotary: bool = True
    context_axis: Optional[str] = None         # CP: sequence sharded here
    context_mechanism: str = "ring"            # "ring" | "ulysses"
    n_experts: int = 0                         # >0: Switch/GShard MoE FFN
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 1e-2
    expert_axis: Optional[str] = None          # EP: experts sharded here
    expert_parallel_size: int = 1
    attention_dropout: float = 0.0             # fused flash-kernel dropout
    fused_lm_head: bool = True                 # logit-free blockwise CE
    fused_ffn: bool = False                    # Pallas fused bias-GELU FFN
    weight_quant: Optional[str] = None         # "int8": decode-path weights
    remat: bool = False                        # jax.checkpoint each layer
    remat_policy: str = "full"                 # "full" | "dots" (selective)
    dtype: jnp.dtype = jnp.float32             # activation/compute dtype
    param_dtype: jnp.dtype = jnp.float32
    # one validated ParallelPlan instead of the per-knob kwargs above:
    # tp/SP/overlap/remat knobs are filled from it (plan wins on
    # conflict, with a DeprecationWarning); dp/pp/schedule fields are
    # consumed by the optimizer/pipeline layers, not the config
    plan: Optional[object] = None

    def __post_init__(self):
        if self.plan is not None:
            from apex_tpu.parallel.plan import apply_plan_to_config
            apply_plan_to_config(self)
        if self.ffn_hidden_size is None:
            self.ffn_hidden_size = 4 * self.hidden_size
        if self.hidden_size % self.num_attention_heads:
            raise ValueError(
                "hidden_size must be divisible by num_attention_heads")
        if self.num_attention_heads % self.tensor_parallel_size:
            raise ValueError(
                "num_attention_heads must be divisible by "
                "tensor_parallel_size")
        if self.context_mechanism not in ("ring", "ulysses"):
            raise ValueError(
                f"context_mechanism must be 'ring' or 'ulysses', got "
                f"{self.context_mechanism!r}")
        if self.n_experts > 0 and (
                self.ffn_hidden_size % self.tensor_parallel_size):
            raise ValueError(
                "MoE ffn_hidden_size must be divisible by "
                "tensor_parallel_size (each expert's FFN dim is "
                "Column/Row-sharded over the tensor axis)")
        if self.expert_axis is not None and self.n_experts <= 0:
            raise ValueError(
                "expert_axis requires n_experts > 0 (the axis shards "
                "the MoE expert stacks)")
        if not 0.0 <= self.attention_dropout < 1.0:
            raise ValueError(
                f"attention_dropout must be in [0, 1), got "
                f"{self.attention_dropout}")
        if self.attention_dropout > 0.0 and self.context_axis is not None:
            raise ValueError(
                "attention_dropout is not supported with context "
                "parallelism (the ring/ulysses kernels take no dropout)")
        if self.remat_policy not in ("full", "dots"):
            raise ValueError(
                f"remat_policy must be 'full' or 'dots', got "
                f"{self.remat_policy!r}")
        if self.overlap_chunks < 0:
            raise ValueError(
                f"overlap_chunks must be >= 0, got {self.overlap_chunks}")
        if self.overlap_chunks > 0 and not self.sequence_parallel:
            raise ValueError(
                "overlap_chunks rings the sequence-parallel collective/GEMM "
                "pairs; it requires sequence_parallel=True")
        if self.sequence_parallel and self.context_axis is not None:
            raise ValueError(
                "sequence_parallel and context parallelism both shard the "
                "sequence dimension; enable one or the other")
        if self.sequence_parallel and self.n_experts > 0:
            raise ValueError(
                "sequence_parallel does not compose with MoE FFNs: the "
                "router's TP-internal psum assumes every tensor rank sees "
                "the same (replicated) tokens, but SP shards them")
        if self.fused_ffn and self.n_experts > 0:
            raise ValueError(
                "fused_ffn fuses the dense ParallelMLP pair; with "
                "n_experts > 0 every FFN slot is a MoEFFN and the knob "
                "would be silently dead — enable one or the other")
        if self.weight_quant not in (None, "int8"):
            raise ValueError(
                f"weight_quant must be None or 'int8', got "
                f"{self.weight_quant!r}")
        if self.weight_quant is not None and self.n_experts > 0:
            raise ValueError(
                "weight_quant covers the dense qkv/proj/fc1/fc2/lm-head "
                "GEMMs; MoE expert stacks (n_experts > 0) keep their own "
                "3D weight layout that quantize_decode_params does not "
                "produce — disable one or the other")
        if self.weight_quant is not None and self.fused_ffn:
            raise ValueError(
                "weight_quant routes the FFN through the int8 "
                "dequant-GEMMs, which fused_ffn would bypass (the fused "
                "kernel consumes raw f32/bf16 fc1/fc2 leaves) — enable "
                "one or the other")

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @property
    def local_heads(self):
        return self.num_attention_heads // self.tensor_parallel_size


class ParallelAttention:
    """Causal self-attention: TP-sharded QKV/proj, fused RoPE + softmax
    (apex ``transformer`` attention with FusedScaleMaskSoftmax.causal)."""

    def __init__(self, cfg: GPTConfig):
        self.cfg = cfg
        self.qkv = tp.ColumnParallelLinear(
            cfg.hidden_size, 3 * cfg.hidden_size, gather_output=False,
            world_size=cfg.tensor_parallel_size, axis_name=cfg.axis_name,
            sequence_parallel_enabled=cfg.sequence_parallel,
            seq_dim=1, overlap_chunks=cfg.overlap_chunks,
            param_dtype=cfg.param_dtype)
        self.proj = tp.RowParallelLinear(
            cfg.hidden_size, cfg.hidden_size, input_is_parallel=True,
            world_size=cfg.tensor_parallel_size, axis_name=cfg.axis_name,
            sequence_parallel_enabled=cfg.sequence_parallel,
            seq_dim=1, overlap_chunks=cfg.overlap_chunks,
            param_dtype=cfg.param_dtype)

    def init_params(self, key):
        k1, k2 = jax.random.split(key)
        return {"qkv": self.qkv.init_params(k1),
                "proj": self.proj.init_params(k2)}

    def _qkv(self, params, x):
        """Project ``x`` and split into ``(q, k, v)``, each
        ``(b, s, local_heads, head_dim)``."""
        b = x.shape[0]
        qkv, _ = self.qkv(params["qkv"], x)      # (b, s, 3h/t)
        s = qkv.shape[1]
        nh = qkv.shape[-1] // (3 * self.cfg.head_dim)
        qkv = qkv.reshape(b, s, nh, 3 * self.cfg.head_dim)
        return jnp.split(qkv, 3, axis=-1)

    def __call__(self, params, x, rope_cos=None, rope_sin=None,
                 dropout_seed=None):
        cfg = self.cfg
        b = x.shape[0]
        q, k, v = self._qkv(params, x)           # (b, s, nh, hd)
        s = q.shape[1]
        nh = q.shape[2]
        if rope_cos is not None:
            # fused rope expects (seq, batch, heads, dim)
            q = fused_apply_rotary_pos_emb_cached(
                q.transpose(1, 0, 2, 3), rope_cos, rope_sin
            ).transpose(1, 0, 2, 3)
            k = fused_apply_rotary_pos_emb_cached(
                k.transpose(1, 0, 2, 3), rope_cos, rope_sin
            ).transpose(1, 0, 2, 3)
        # (b, nh, s, hd) — blockwise flash attention: O(s) memory, no
        # materialized (b*h, s, s) scores (the round-2 HBM ceiling)
        q = q.transpose(0, 2, 1, 3)
        k = k.transpose(0, 2, 1, 3)
        v = v.transpose(0, 2, 1, 3)
        if cfg.context_axis is not None:
            # context parallelism: s here is the LOCAL shard; attention
            # runs over the global sequence (beyond-reference long-context)
            from apex_tpu.transformer.context_parallel import (
                ring_attention, ulysses_attention)
            attn = (ring_attention if cfg.context_mechanism == "ring"
                    else ulysses_attention)
            ctx = attn(q, k, v, cfg.context_axis, causal=True)
        else:
            # train-time probability dropout stays on the fused O(s)
            # path (counter-hash mask, ops/flash_attention.py); no seed
            # (eval) means no dropout
            rate = cfg.attention_dropout if dropout_seed is not None \
                else 0.0
            seed = dropout_seed
            if seed is not None and cfg.axis_name is not None:
                # the counter hash keys on the LOCAL (batch, head) index,
                # so without an offset head j on every TP rank (different
                # global heads) would draw bit-identical masks; stride the
                # seed by rank like Megatron's per-TP-rank dropout RNG
                seed = seed + (jax.lax.axis_index(cfg.axis_name)
                               * _SEED_TP_RANK_STRIDE)
            ctx = flash_attention(q, k, v, causal=True, dropout=rate,
                                  dropout_seed=seed)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, nh * cfg.head_dim)
        out, _ = self.proj(params["proj"], ctx)
        return out

    def prefill(self, params, x, rope_cos=None, rope_sin=None):
        """Full-sequence causal attention that also returns the post-RoPE
        K/V in cache layout ``(b, s, local_heads, head_dim)`` — exactly
        what the decode path reads back, so prefill+decode reproduces the
        full forward token-for-token."""
        cfg = self.cfg
        b = x.shape[0]
        q, k, v = self._qkv(params, x)           # (b, s, nh, hd)
        s = q.shape[1]
        nh = q.shape[2]
        if rope_cos is not None:
            q = fused_apply_rotary_pos_emb_cached(
                q.transpose(1, 0, 2, 3), rope_cos, rope_sin
            ).transpose(1, 0, 2, 3)
            k = fused_apply_rotary_pos_emb_cached(
                k.transpose(1, 0, 2, 3), rope_cos, rope_sin
            ).transpose(1, 0, 2, 3)
        ctx = flash_attention(q.transpose(0, 2, 1, 3),
                              k.transpose(0, 2, 1, 3),
                              v.transpose(0, 2, 1, 3), causal=True)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, nh * cfg.head_dim)
        out, _ = self.proj(params["proj"], ctx)
        return out, (k, v)

    def decode(self, params, x, cache, layer_index, positions):
        """One-token decode step against the KV cache.

        ``x``: ``(b, 1, hidden)`` — the incoming token's hidden state per
        cache slot; ``cache``: the full ring
        ``(slots, layers, 2, max_seq, local_heads, head_dim)``;
        ``positions``: ``(b,)`` absolute position of the incoming token
        (== valid cache entries before this step).  Writes the new K/V at
        ``positions`` (cast to the cache dtype), then attends over
        ``positions + 1`` entries.  Returns ``(out (b, 1, hidden), cache)``.
        """
        cfg = self.cfg
        b = x.shape[0]
        q, k, v = self._qkv(params, x)           # (b, 1, nh, hd)
        q, k, v = q[:, 0], k[:, 0], v[:, 0]      # (b, nh, hd)
        if cfg.rotary:
            # full-cache-depth tables; constant-folded under jit
            f = rope_freqs(cache.shape[3], cfg.head_dim)
            q = fused_apply_rotary_pos_emb_at_positions(
                q, jnp.cos(f), jnp.sin(f), positions)
            k = fused_apply_rotary_pos_emb_at_positions(
                k, jnp.cos(f), jnp.sin(f), positions)
        rows = jnp.arange(b)
        cache = cache.at[rows, layer_index, 0, positions].set(
            k.astype(cache.dtype))
        cache = cache.at[rows, layer_index, 1, positions].set(
            v.astype(cache.dtype))
        ctx = flash_attention_decode(q, cache[:, layer_index, 0],
                                     cache[:, layer_index, 1],
                                     positions + 1)
        out, _ = self.proj(params["proj"],
                           ctx.reshape(b, 1, q.shape[1] * cfg.head_dim))
        return out, cache

    def decode_paged(self, params, x, pool, layer_index, block_tables,
                     positions):
        """One-token decode against a paged block pool — op-for-op the
        contiguous :meth:`decode` with the cache read/write indirected
        through ``block_tables`` (``(b, max_blocks)``; ``pool``:
        ``(num_blocks, layers, 2, block_size, kv_heads, head_dim)``).
        RoPE tables are built at the pool's logical depth
        ``max_blocks * block_size``, whose rows are bitwise independent
        of the total length — paged and contiguous rows match exactly.
        """
        cfg = self.cfg
        b = x.shape[0]
        bs = pool.shape[3]
        q, k, v = self._qkv(params, x)           # (b, 1, nh, hd)
        q, k, v = q[:, 0], k[:, 0], v[:, 0]      # (b, nh, hd)
        if cfg.rotary:
            f = rope_freqs(block_tables.shape[1] * bs, cfg.head_dim)
            q = fused_apply_rotary_pos_emb_at_positions(
                q, jnp.cos(f), jnp.sin(f), positions)
            k = fused_apply_rotary_pos_emb_at_positions(
                k, jnp.cos(f), jnp.sin(f), positions)
        rows = jnp.arange(b)
        bids = block_tables[rows, positions // bs]
        offs = positions % bs
        pool = pool.at[bids, layer_index, 0, offs].set(
            k.astype(pool.dtype))
        pool = pool.at[bids, layer_index, 1, offs].set(
            v.astype(pool.dtype))
        ctx = flash_attention_decode_paged(
            q, pool[:, layer_index, 0], pool[:, layer_index, 1],
            block_tables, positions + 1)
        out, _ = self.proj(params["proj"],
                           ctx.reshape(b, 1, q.shape[1] * cfg.head_dim))
        return out, pool

    def decode_chunk(self, params, x, pool, layer_index, block_tables,
                     positions, write_blocks, write_offsets):
        """Multi-token decode against the pool (chunked prefill /
        speculative verify): ``x`` is ``(b, chunk, hidden)``,
        ``positions`` ``(b, chunk)`` absolute, and
        ``write_blocks``/``write_offsets`` ``(b, chunk)`` are the
        host-precomputed pool coordinates for each token's K/V (pad rows
        point at garbage block 0).  Attends causally over the whole
        cached context up to each query's position."""
        cfg = self.cfg
        b, c = x.shape[:2]
        q, k, v = self._qkv(params, x)           # (b, c, nh, hd)
        nh = q.shape[2]
        if cfg.rotary:
            f = rope_freqs(block_tables.shape[1] * pool.shape[3],
                           cfg.head_dim)
            cos, sin = jnp.cos(f), jnp.sin(f)
            flat = positions.reshape(-1)
            q = fused_apply_rotary_pos_emb_at_positions(
                q.reshape(b * c, nh, cfg.head_dim), cos, sin, flat
            ).reshape(b, c, nh, cfg.head_dim)
            k = fused_apply_rotary_pos_emb_at_positions(
                k.reshape(b * c, nh, cfg.head_dim), cos, sin, flat
            ).reshape(b, c, nh, cfg.head_dim)
        pool = pool.at[write_blocks, layer_index, 0, write_offsets].set(
            k.astype(pool.dtype))
        pool = pool.at[write_blocks, layer_index, 1, write_offsets].set(
            v.astype(pool.dtype))
        ctx = flash_attention_chunk_paged(
            q.transpose(0, 2, 1, 3), pool[:, layer_index, 0],
            pool[:, layer_index, 1], block_tables, positions)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, c, nh * cfg.head_dim)
        out, _ = self.proj(params["proj"], ctx)
        return out, pool

    def _quant_insert(self, pool, scales, layer_index, bids, offs, k, v):
        """Write one token's K/V into an int8 pool: gather each row's
        target block, dequantize it, insert, and requantize the WHOLE
        block (safe and deterministic because quantized blocks are
        zeroed on allocation and shared blocks are never write targets
        — COW and the trie guarantee refcount 1 here).  Returns the
        updated ``(pool, scales)``."""
        rows = jnp.arange(bids.shape[0])
        blk = pool[bids, layer_index]            # (b, 2, bs, nh, hd) i8
        sc = scales[bids, layer_index]           # (b, 2, nh) f32
        deq = blk.astype(jnp.float32) * sc[..., None, :, None]
        deq = deq.at[rows, 0, offs].set(k.astype(jnp.float32))
        deq = deq.at[rows, 1, offs].set(v.astype(jnp.float32))
        q8, new_sc = quantize_kv_blocks(deq)
        pool = pool.at[bids, layer_index].set(q8)
        scales = scales.at[bids, layer_index].set(new_sc)
        return pool, scales

    def decode_paged_quant(self, params, x, pool, scales, layer_index,
                           block_tables, positions):
        """:meth:`decode_paged` against an int8 scale-per-block pool
        (``pool`` int8, ``scales`` ``(num_blocks, layers, 2, kv_heads)``
        f32).  The written block is dequantized, updated, and
        requantized; attention dequantizes per gathered block into the
        f32 score path.  Returns ``(out, pool, scales)``."""
        cfg = self.cfg
        b = x.shape[0]
        bs = pool.shape[3]
        q, k, v = self._qkv(params, x)           # (b, 1, nh, hd)
        q, k, v = q[:, 0], k[:, 0], v[:, 0]      # (b, nh, hd)
        if cfg.rotary:
            f = rope_freqs(block_tables.shape[1] * bs, cfg.head_dim)
            q = fused_apply_rotary_pos_emb_at_positions(
                q, jnp.cos(f), jnp.sin(f), positions)
            k = fused_apply_rotary_pos_emb_at_positions(
                k, jnp.cos(f), jnp.sin(f), positions)
        bids = block_tables[jnp.arange(b), positions // bs]
        pool, scales = self._quant_insert(pool, scales, layer_index,
                                          bids, positions % bs, k, v)
        ctx = flash_attention_decode_paged_quant(
            q, pool[:, layer_index, 0], pool[:, layer_index, 1],
            scales[:, layer_index, 0], scales[:, layer_index, 1],
            block_tables, positions + 1)
        out, _ = self.proj(params["proj"],
                           ctx.reshape(b, 1, q.shape[1] * cfg.head_dim))
        return out, pool, scales

    def decode_chunk_quant(self, params, x, pool, scales, layer_index,
                           block_tables, positions, write_blocks,
                           write_offsets):
        """:meth:`decode_chunk` against an int8 pool.

        Tokens are inserted (and their block requantized) SEQUENTIALLY,
        each attending right after its own insertion — exactly the
        single-token :meth:`decode_paged_quant` block op applied
        ``chunk`` times under one shared QKV projection.  That
        serialization is what makes the quantized pool state (and every
        logits row) a fold over per-token ops, independent of how the
        scheduler sliced the prompt into chunks — the property the
        disaggregated handoff's bitwise guarantee rests on.  The cost is
        a ``fori_loop`` over the chunk instead of one wide attention;
        the quantized cache trades prefill throughput for capacity.
        """
        cfg = self.cfg
        b, c = x.shape[:2]
        q, k, v = self._qkv(params, x)           # (b, c, nh, hd)
        nh = q.shape[2]
        if cfg.rotary:
            f = rope_freqs(block_tables.shape[1] * pool.shape[3],
                           cfg.head_dim)
            cos, sin = jnp.cos(f), jnp.sin(f)
            flat = positions.reshape(-1)
            q = fused_apply_rotary_pos_emb_at_positions(
                q.reshape(b * c, nh, cfg.head_dim), cos, sin, flat
            ).reshape(b, c, nh, cfg.head_dim)
            k = fused_apply_rotary_pos_emb_at_positions(
                k.reshape(b * c, nh, cfg.head_dim), cos, sin, flat
            ).reshape(b, c, nh, cfg.head_dim)

        def body(j, carry):
            pool, scales, ctx = carry
            bids = write_blocks[:, j]
            pool, scales = self._quant_insert(
                pool, scales, layer_index, bids, write_offsets[:, j],
                k[:, j], v[:, j])
            o = flash_attention_decode_paged_quant(
                q[:, j], pool[:, layer_index, 0],
                pool[:, layer_index, 1], scales[:, layer_index, 0],
                scales[:, layer_index, 1], block_tables,
                positions[:, j] + 1)
            return pool, scales, ctx.at[:, j].set(o)

        ctx0 = jnp.zeros((b, c, nh, cfg.head_dim), q.dtype)
        pool, scales, ctx = jax.lax.fori_loop(0, c, body,
                                              (pool, scales, ctx0))
        out, _ = self.proj(params["proj"],
                           ctx.reshape(b, c, nh * cfg.head_dim))
        return out, pool, scales


class ParallelMLP:
    """Column→GELU→Row block (apex ParallelMLP)."""

    def __init__(self, cfg: GPTConfig):
        self.cfg = cfg
        self.fc1 = tp.ColumnParallelLinear(
            cfg.hidden_size, cfg.ffn_hidden_size, gather_output=False,
            world_size=cfg.tensor_parallel_size, axis_name=cfg.axis_name,
            sequence_parallel_enabled=cfg.sequence_parallel,
            seq_dim=1, overlap_chunks=cfg.overlap_chunks,
            param_dtype=cfg.param_dtype)
        self.fc2 = tp.RowParallelLinear(
            cfg.ffn_hidden_size, cfg.hidden_size, input_is_parallel=True,
            world_size=cfg.tensor_parallel_size, axis_name=cfg.axis_name,
            sequence_parallel_enabled=cfg.sequence_parallel,
            seq_dim=1, overlap_chunks=cfg.overlap_chunks,
            param_dtype=cfg.param_dtype)

    def init_params(self, key):
        k1, k2 = jax.random.split(key)
        return {"fc1": self.fc1.init_params(k1),
                "fc2": self.fc2.init_params(k2)}

    def __call__(self, params, x):
        cfg = self.cfg
        if cfg.fused_ffn:
            # one Pallas op for GEMM+bias+GELU+GEMM, wrapped in the same
            # TP/SP edge collectives the unfused pair uses (bias2 after
            # the reduce) — bitwise vs unfused off-TPU at overlap 0
            return fused_ffn_tp(
                x, params["fc1"]["weight"], params["fc1"]["bias"],
                params["fc2"]["weight"], params["fc2"]["bias"],
                tensor_parallel_size=cfg.tensor_parallel_size,
                axis_name=cfg.axis_name,
                sequence_parallel=cfg.sequence_parallel, seq_dim=1)
        h, _ = self.fc1(params["fc1"], x)
        h = jax.nn.gelu(h, approximate=True)
        y, _ = self.fc2(params["fc2"], h)
        return y


class MoEFFN:
    """Switch/GShard FFN in the layer slot (beyond-reference; Megatron's
    MoE lives outside apex).  Flattens ``(b, s, h)`` to tokens for
    :class:`apex_tpu.transformer.expert_parallel.MoEMLP` and returns
    ``(y, aux_loss)``."""

    def __init__(self, cfg: GPTConfig):
        from apex_tpu.transformer.expert_parallel import MoEConfig, MoEMLP
        self.moe = MoEMLP(MoEConfig(
            hidden_size=cfg.hidden_size,
            ffn_hidden_size=cfg.ffn_hidden_size,
            n_experts=cfg.n_experts,
            top_k=cfg.moe_top_k,
            capacity_factor=cfg.moe_capacity_factor,
            expert_parallel_size=cfg.expert_parallel_size,
            axis_name=cfg.expert_axis,
            tensor_parallel_size=cfg.tensor_parallel_size,
            tensor_axis=cfg.axis_name,
            param_dtype=cfg.param_dtype,
            compute_dtype=cfg.dtype))

    def init_params(self, key):
        return self.moe.init_params(key)

    def __call__(self, params, x):
        b, s, h = x.shape
        y, aux = self.moe(params, x.reshape(b * s, h))
        return y.reshape(b, s, h), aux


class ParallelTransformerLayer:
    """Pre-LN transformer block (apex ParallelTransformerLayer); the FFN
    slot is dense (ParallelMLP) or MoE (``cfg.n_experts > 0``)."""

    def __init__(self, cfg: GPTConfig):
        self.cfg = cfg
        self.is_moe = cfg.n_experts > 0
        self.input_layernorm = MixedFusedLayerNorm(cfg.hidden_size)
        self.post_attention_layernorm = MixedFusedLayerNorm(cfg.hidden_size)
        self.attention = ParallelAttention(cfg)
        self.mlp = MoEFFN(cfg) if self.is_moe else ParallelMLP(cfg)

    def init_params(self, key):
        k1, k2 = jax.random.split(key)
        return {"input_layernorm": self.input_layernorm.init_params(),
                "attention": self.attention.init_params(k1),
                "post_attention_layernorm":
                    self.post_attention_layernorm.init_params(),
                "mlp": self.mlp.init_params(k2)}

    def _sp_ln_params(self, params, name):
        """LayerNorms run on the SEQ-SHARDED stream under SP, so their
        per-device grads only cover local tokens; identity-fwd/psum-bwd
        restores the total (Megatron's allreduce of sequence-parallel-
        region layernorm grads)."""
        p = params[name]
        if self.cfg.sequence_parallel and self.cfg.axis_name is not None:
            from apex_tpu.transformer.tensor_parallel import mappings as M
            p = M.copy_to_tensor_model_parallel_region(
                p, self.cfg.axis_name)
        return p

    def __call__(self, params, x, rope_cos=None, rope_sin=None,
                 dropout_seed=None):
        # named scopes land in HLO metadata -> visible in xprof traces
        # (the reference's nvtx range annotations, SURVEY §5)
        with jax.named_scope("attention"):
            h = self.input_layernorm(
                self._sp_ln_params(params, "input_layernorm"), x)
            x = x + self.attention(params["attention"], h, rope_cos,
                                   rope_sin, dropout_seed)
        with jax.named_scope("mlp"):
            h = self.post_attention_layernorm(
                self._sp_ln_params(params, "post_attention_layernorm"), x)
            if self.is_moe:
                y, aux = self.mlp(params["mlp"], h)
                return x + y, aux
            return x + self.mlp(params["mlp"], h)

    def prefill(self, params, x, rope_cos=None, rope_sin=None):
        """Inference forward returning ``(x_out, (k, v))`` with this
        layer's post-RoPE cache entries (MoE aux is discarded —
        load-balancing loss is a training concern)."""
        h = self.input_layernorm(params["input_layernorm"], x)
        attn, kv = self.attention.prefill(params["attention"], h,
                                          rope_cos, rope_sin)
        x = x + attn
        h = self.post_attention_layernorm(
            params["post_attention_layernorm"], x)
        y = self.mlp(params["mlp"], h)
        if self.is_moe:
            y, _ = y
        return x + y, kv

    def decode(self, params, x, cache, layer_index, positions):
        """One-token decode through this layer; see
        :meth:`ParallelAttention.decode` for the cache contract."""
        h = self.input_layernorm(params["input_layernorm"], x)
        attn, cache = self.attention.decode(params["attention"], h,
                                            cache, layer_index, positions)
        x = x + attn
        h = self.post_attention_layernorm(
            params["post_attention_layernorm"], x)
        y = self.mlp(params["mlp"], h)
        if self.is_moe:
            y, _ = y
        return x + y, cache

    def decode_paged(self, params, x, pool, layer_index, block_tables,
                     positions):
        """Paged-pool analog of :meth:`decode` (same residual/LN/MLP
        tail — only the attention cache access is indirected)."""
        h = self.input_layernorm(params["input_layernorm"], x)
        attn, pool = self.attention.decode_paged(
            params["attention"], h, pool, layer_index, block_tables,
            positions)
        x = x + attn
        h = self.post_attention_layernorm(
            params["post_attention_layernorm"], x)
        y = self.mlp(params["mlp"], h)
        if self.is_moe:
            y, _ = y
        return x + y, pool

    def decode_chunk(self, params, x, pool, layer_index, block_tables,
                     positions, write_blocks, write_offsets):
        """Chunked decode through this layer; see
        :meth:`ParallelAttention.decode_chunk`."""
        h = self.input_layernorm(params["input_layernorm"], x)
        attn, pool = self.attention.decode_chunk(
            params["attention"], h, pool, layer_index, block_tables,
            positions, write_blocks, write_offsets)
        x = x + attn
        h = self.post_attention_layernorm(
            params["post_attention_layernorm"], x)
        y = self.mlp(params["mlp"], h)
        if self.is_moe:
            y, _ = y
        return x + y, pool

    def decode_paged_quant(self, params, x, pool, scales, layer_index,
                           block_tables, positions):
        """Int8-pool analog of :meth:`decode_paged`; see
        :meth:`ParallelAttention.decode_paged_quant`."""
        h = self.input_layernorm(params["input_layernorm"], x)
        attn, pool, scales = self.attention.decode_paged_quant(
            params["attention"], h, pool, scales, layer_index,
            block_tables, positions)
        x = x + attn
        h = self.post_attention_layernorm(
            params["post_attention_layernorm"], x)
        y = self.mlp(params["mlp"], h)
        if self.is_moe:
            y, _ = y
        return x + y, pool, scales

    def decode_chunk_quant(self, params, x, pool, scales, layer_index,
                           block_tables, positions, write_blocks,
                           write_offsets):
        """Int8-pool analog of :meth:`decode_chunk`; see
        :meth:`ParallelAttention.decode_chunk_quant`."""
        h = self.input_layernorm(params["input_layernorm"], x)
        attn, pool, scales = self.attention.decode_chunk_quant(
            params["attention"], h, pool, scales, layer_index,
            block_tables, positions, write_blocks, write_offsets)
        x = x + attn
        h = self.post_attention_layernorm(
            params["post_attention_layernorm"], x)
        y = self.mlp(params["mlp"], h)
        if self.is_moe:
            y, _ = y
        return x + y, pool, scales


class GPTModel:
    """Full decoder LM: vocab-parallel embedding → N layers → final LN →
    tied vocab-parallel head → (optional) vocab-parallel xent loss."""

    def __init__(self, cfg: GPTConfig):
        self.cfg = cfg
        self.embedding = tp.VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size,
            world_size=cfg.tensor_parallel_size, axis_name=cfg.axis_name,
            param_dtype=cfg.param_dtype)
        self.layers = [ParallelTransformerLayer(cfg)
                       for _ in range(cfg.num_layers)]
        self.final_layernorm = MixedFusedLayerNorm(cfg.hidden_size)

    def init_params(self, key):
        keys = jax.random.split(key, self.cfg.num_layers + 2)
        params = {
            "embedding": self.embedding.init_params(keys[0]),
            "layers": [l.init_params(k)
                       for l, k in zip(self.layers, keys[1:-1])],
            "final_layernorm": self.final_layernorm.init_params(),
        }
        if not self.cfg.rotary:
            params["position_embedding"] = 0.02 * jax.random.normal(
                keys[-1], (self.cfg.max_seq_len, self.cfg.hidden_size),
                self.cfg.param_dtype)
        return params

    def rope_tables(self, seq_len):
        if not self.cfg.rotary:
            return None, None
        f = rope_freqs(seq_len, self.cfg.head_dim)
        return jnp.cos(f), jnp.sin(f)

    def _seq_offset(self, local_len):
        """Global position of this shard's first token (0 without CP)."""
        if self.cfg.context_axis is None:
            return 0
        return jax.lax.axis_index(self.cfg.context_axis) * local_len

    def embed(self, params, tokens):
        x = self.embedding(params["embedding"], tokens)
        if not self.cfg.rotary:
            pe = jax.lax.dynamic_slice_in_dim(
                params["position_embedding"],
                self._seq_offset(tokens.shape[1]), tokens.shape[1])
            x = x + pe
        return x.astype(self.cfg.dtype)

    def backbone(self, params, x, seq_len=None, dropout_seed=None):
        local = seq_len or x.shape[1]
        if self.cfg.context_axis is not None:
            # rope positions are GLOBAL: build full tables, take the shard
            n_ctx = _axis_size(self.cfg.context_axis)
            cos, sin = self.rope_tables(local * n_ctx)
            if cos is not None:
                off = self._seq_offset(local)
                cos = jax.lax.dynamic_slice_in_dim(cos, off, local)
                sin = jax.lax.dynamic_slice_in_dim(sin, off, local)
            return self._backbone_layers(params, x, cos, sin, dropout_seed)
        cos, sin = self.rope_tables(local)
        return self._backbone_layers(params, x, cos, sin, dropout_seed)

    def _backbone_layers(self, params, x, cos, sin, dropout_seed=None):
        """Returns ``(x, moe_aux_total)`` (aux is 0.0 for dense FFNs).

        ``dropout_seed`` (train-time attention dropout): layer ``i`` uses
        ``dropout_seed + i * _SEED_LAYER_STRIDE`` — the same per-layer
        stream walk the pipeline stage_fn reproduces by carrying a
        striding seed.  Advance the base seed by +1 per training step.
        """
        aux_total = jnp.zeros((), _f32)
        for li, (layer, lp) in enumerate(zip(self.layers,
                                             params["layers"])):
            seed = (None if dropout_seed is None
                    else dropout_seed + li * _SEED_LAYER_STRIDE)
            call = layer
            if self.cfg.remat:
                # trade recompute for activation memory (apex
                # tensor_parallel.checkpoint → jax.checkpoint).
                # remat_policy="dots" is Megatron's SELECTIVE activation
                # recompute: GEMM outputs are saved (the expensive MXU
                # work is not redone in the backward), only the cheap
                # elementwise/softmax chain recomputes
                call = jax.checkpoint(
                    lambda lp, x, c, s, sd, _l=layer: _l(lp, x, c, s, sd),
                    policy=_remat_policy(self.cfg.remat_policy))
            out = call(lp, x, cos, sin, seed)
            if layer.is_moe:
                x, aux = out
                aux_total = aux_total + aux
            else:
                x = out
        return x, aux_total

    def _final_ln_params(self, params):
        """Under SP the head's cotangents are per-vocab-shard partials, so
        the (replicated) final-LN params see partial grads; identity-fwd/
        psum-bwd restores the total (see ParallelTransformerLayer)."""
        p = params["final_layernorm"]
        if self._sp_enabled():
            p = tp.copy_to_tensor_model_parallel_region(
                p, self.cfg.axis_name)
        return p

    def _head_logits(self, params, x, eq):
        """Tied-embedding head GEMM in f32.  A quantized tree (the
        ``weight_quant="int8"`` leaves from
        :func:`quantize_decode_params`) routes through the fused
        dequant-GEMM; otherwise the original einsum runs unchanged, so
        the knob-off path stays bitwise."""
        emb = params["embedding"]
        if "weight_scale" in emb:
            from apex_tpu.ops.quant_gemm import quant_gemm
            return quant_gemm(x.astype(_f32), emb["weight"],
                              emb["weight_scale"])
        return jnp.einsum(eq, x.astype(_f32), emb["weight"].astype(_f32))

    def logits(self, params, x):
        """Tied LM head: vocab-parallel logits ``(b, s, vocab/t)``."""
        x = self.final_layernorm(self._final_ln_params(params), x)
        return self._head_logits(params, x, "bsh,vh->bsv")

    def head_loss(self, params, x, targets):
        """Per-token CE of the tied head on backbone output ``x``.

        Serial vocab (``axis_name is None``) with ``cfg.fused_lm_head``
        routes through :func:`apex_tpu.ops.lm_head.fused_linear_cross_entropy`
        — the (b·s, vocab) logits never materialize, which is the HBM
        ceiling of the training step (the serial GPT-350M config OOMs at
        batch 24 without it and runs batch 32 with it).  The
        vocab-parallel (TP) path keeps the sharded-logsumexp cross
        entropy.
        """
        b, s = targets.shape
        if self.cfg.axis_name is None and self.cfg.fused_lm_head:
            from apex_tpu.ops.lm_head import fused_linear_cross_entropy
            h = self.final_layernorm(params["final_layernorm"], x)
            # head operands at the COMPUTE dtype: the kernel dots at the
            # operand precision (f32 dots run ~1/8 the bf16 MXU rate),
            # and the head GEMMs are the largest single matmuls in the
            # step; accumulation/logsumexp stay f32 inside the kernel
            return fused_linear_cross_entropy(
                h.reshape(b * s, h.shape[-1]).astype(self.cfg.dtype),
                params["embedding"]["weight"].astype(self.cfg.dtype),
                targets.reshape(b * s)).reshape(b, s)
        logits = self.logits(params, x)
        vl = logits.shape[-1]
        return tp.vocab_parallel_cross_entropy(
            logits.reshape(b * s, vl), targets.reshape(b * s),
            axis_name=self.cfg.axis_name).reshape(b, s)

    def _sp_enabled(self):
        return (self.cfg.sequence_parallel
                and self.cfg.axis_name is not None)

    def _sp_scatter(self, x):
        """Megatron SP entry edge: shard activations along the sequence
        dim so LayerNorms, residual adds and (in the backward) their
        grads run on ``(b, s/t, h)``; each block's column gather / row
        reduce-scatter restores and reshards inside the TP regions."""
        if x.shape[1] % self.cfg.tensor_parallel_size:
            raise ValueError(
                f"sequence_parallel requires seq_len divisible by "
                f"tensor_parallel_size ({x.shape[1]} % "
                f"{self.cfg.tensor_parallel_size} != 0)")
        return tp.scatter_to_sequence_parallel_region(
            x, self.cfg.axis_name, 1)

    def _sp_gather(self, x):
        """SP exit edge before the (vocab-parallel) head; the backward is
        a reduce-scatter summing the per-rank vocab-shard contributions."""
        return tp.gather_from_sequence_parallel_region(
            x, self.cfg.axis_name, 1)

    def __call__(self, params, tokens, dropout_seed=None):
        x = self.embed(params, tokens)
        if self._sp_enabled():
            x = self._sp_scatter(x)
        x, _ = self.backbone(params, x, seq_len=tokens.shape[1],
                             dropout_seed=dropout_seed)
        if self._sp_enabled():
            x = self._sp_gather(x)
        return self.logits(params, x)

    apply = __call__

    # -- KV-cache inference --------------------------------------------------

    def _check_decode_supported(self):
        if self.cfg.context_axis is not None:
            raise ValueError(
                "KV-cache decode does not compose with context "
                "parallelism (the cache would be sequence-sharded)")
        if self.cfg.sequence_parallel:
            raise ValueError(
                "KV-cache decode requires sequence_parallel=False "
                "(decode steps are single-token)")

    def prefill(self, params, tokens):
        """Process a full prompt; returns ``(logits, kv)``.

        ``logits``: ``(b, s, vocab)`` (vocab-parallel under TP, like
        :meth:`logits`); ``kv``: ``(layers, 2, b, s, local_heads,
        head_dim)`` post-RoPE cache entries in the compute dtype — write
        them into a :class:`~apex_tpu.inference.KVCache` slot (which casts
        to the cache dtype) and continue with :meth:`decode_step`.
        Prompts padded beyond their true length are safe: causal masking
        keeps logits at positions ``< prompt_len`` unaffected, and the
        padded cache rows are masked by the per-slot length at decode.
        """
        self._check_decode_supported()
        x = self.embed(params, tokens)
        cos, sin = self.rope_tables(tokens.shape[1])
        ks, vs = [], []
        for layer, lp in zip(self.layers, params["layers"]):
            x, (k, v) = layer.prefill(lp, x, cos, sin)
            ks.append(k)
            vs.append(v)
        kv = jnp.stack([jnp.stack(ks), jnp.stack(vs)], axis=1)
        return self.logits(params, x), kv

    def decode_step(self, params, tokens, cache, positions):
        """One batched autoregressive step over the cache ring.

        ``tokens``: ``(slots,)`` int — the token to feed per cache slot;
        ``cache``: ``(slots, layers, 2, max_seq, local_heads, head_dim)``
        (any float dtype; bf16 caches accumulate attention in f32);
        ``positions``: ``(slots,)`` int — each token's absolute position,
        i.e. the number of valid cache entries before this step.

        Returns ``(logits, cache)`` with ``logits`` ``(slots, vocab)``
        (vocab-parallel under TP) and the cache advanced by one entry per
        row.  Rows are mathematically independent, so inactive slots may
        carry garbage: their writes land at their (stale) position and are
        overwritten by the next prefill before any valid length reaches
        them.
        """
        self._check_decode_supported()
        x = self.embedding(params["embedding"], tokens[:, None])
        if not self.cfg.rotary:
            x = x + params["position_embedding"][positions][:, None]
        x = x.astype(self.cfg.dtype)
        for li, (layer, lp) in enumerate(zip(self.layers,
                                             params["layers"])):
            x, cache = layer.decode(lp, x, cache, li, positions)
        x = self.final_layernorm(params["final_layernorm"], x)
        logits = self._head_logits(params, x[:, 0], "bh,vh->bv")
        return logits, cache

    def decode_step_paged(self, params, tokens, pool, block_tables,
                          positions):
        """One batched decode step against a paged block pool.

        Mirrors :meth:`decode_step` op-for-op — same embed, same RoPE
        rows, same f32 head einsum — with the cache access indirected
        through ``block_tables`` (``(slots, max_blocks)`` int32; see
        :class:`apex_tpu.serving.PagedKVCache`).  Off-TPU the attention
        gathers the table back to the contiguous layout and runs the
        identical reference, which is why the serving engine's
        paged-vs-contiguous parity is bitwise, not approximate.  Rows
        whose table is all-garbage (block 0) compute garbage that is
        never read, like inactive slots in :meth:`decode_step`.
        """
        self._check_decode_supported()
        x = self.embedding(params["embedding"], tokens[:, None])
        if not self.cfg.rotary:
            x = x + params["position_embedding"][positions][:, None]
        x = x.astype(self.cfg.dtype)
        for li, (layer, lp) in enumerate(zip(self.layers,
                                             params["layers"])):
            x, pool = layer.decode_paged(lp, x, pool, li, block_tables,
                                         positions)
        x = self.final_layernorm(params["final_layernorm"], x)
        logits = self._head_logits(params, x[:, 0], "bh,vh->bv")
        return logits, pool

    def decode_chunk(self, params, tokens, pool, block_tables, positions,
                     write_blocks, write_offsets):
        """Process ``chunk`` tokens per sequence against the paged pool
        in one forward — the workhorse of chunked prefill (a prompt slice
        at a time, mixed into decode ticks) and speculative verification
        (score γ draft tokens in one pass).

        ``tokens``/``positions``/``write_blocks``/``write_offsets``:
        ``(slots, chunk)`` — each token's id, absolute position, and
        host-precomputed pool write coordinates (pad rows target garbage
        block 0).  Returns ``(logits, pool)`` with ``logits``
        ``(slots, chunk, vocab)`` through the same tied head as
        :meth:`prefill`'s — the chunk's final row is what admission
        samples the first token from.
        """
        self._check_decode_supported()
        x = self.embedding(params["embedding"], tokens)
        if not self.cfg.rotary:
            x = x + params["position_embedding"][positions]
        x = x.astype(self.cfg.dtype)
        for li, (layer, lp) in enumerate(zip(self.layers,
                                             params["layers"])):
            x, pool = layer.decode_chunk(lp, x, pool, li, block_tables,
                                         positions, write_blocks,
                                         write_offsets)
        return self.logits(params, x), pool

    def decode_step_paged_quant(self, params, tokens, pool, scales,
                                block_tables, positions):
        """:meth:`decode_step_paged` against an int8 scale-per-block
        pool (``pool`` int8 of the same shape, ``scales``
        ``(num_blocks, layers, 2, kv_heads)`` f32; see
        :class:`apex_tpu.serving.QuantizedPagedKVCache`).  Same embed,
        RoPE rows, and f32 head einsum — the only difference is the
        per-block dequantize/requantize around the cache access.
        Returns ``(logits, pool, scales)``."""
        self._check_decode_supported()
        x = self.embedding(params["embedding"], tokens[:, None])
        if not self.cfg.rotary:
            x = x + params["position_embedding"][positions][:, None]
        x = x.astype(self.cfg.dtype)
        for li, (layer, lp) in enumerate(zip(self.layers,
                                             params["layers"])):
            x, pool, scales = layer.decode_paged_quant(
                lp, x, pool, scales, li, block_tables, positions)
        x = self.final_layernorm(params["final_layernorm"], x)
        logits = self._head_logits(params, x[:, 0], "bh,vh->bv")
        return logits, pool, scales

    def decode_chunk_quant(self, params, tokens, pool, scales,
                           block_tables, positions, write_blocks,
                           write_offsets):
        """:meth:`decode_chunk` against an int8 pool — chunked prefill
        on a quantized cache.  Inserts are serialized per token inside
        each layer (see
        :meth:`ParallelAttention.decode_chunk_quant`), which keeps the
        final pool state independent of chunk boundaries.  Returns
        ``(logits, pool, scales)``."""
        self._check_decode_supported()
        x = self.embedding(params["embedding"], tokens)
        if not self.cfg.rotary:
            x = x + params["position_embedding"][positions]
        x = x.astype(self.cfg.dtype)
        for li, (layer, lp) in enumerate(zip(self.layers,
                                             params["layers"])):
            x, pool, scales = layer.decode_chunk_quant(
                lp, x, pool, scales, li, block_tables, positions,
                write_blocks, write_offsets)
        return self.logits(params, x), pool, scales

    def loss(self, params, tokens, targets, dropout_seed=None):
        """Mean next-token loss via vocab-parallel cross entropy (+ the
        Switch aux load-balancing term when the FFNs are MoE).

        Under context parallelism the mean over local tokens is pmeaned
        across the context axis (equal shard sizes -> exact global mean).

        ``dropout_seed`` (int or traced scalar) enables the configured
        ``attention_dropout`` for this step — pass the step counter
        (advance by +1 per step; layer/microbatch streams stride the
        seed space so steps never replay each other's masks); omit it
        (None) for eval.
        """
        x = self.embed(params, tokens)
        if self._sp_enabled():
            x = self._sp_scatter(x)
        x, aux = self.backbone(params, x, seq_len=tokens.shape[1],
                               dropout_seed=dropout_seed)
        if self._sp_enabled():
            x = self._sp_gather(x)
        mean = jnp.mean(self.head_loss(params, x, targets))
        if self.cfg.n_experts > 0:
            mean = mean + self.cfg.moe_aux_weight * aux / len(self.layers)
        if self.cfg.context_axis is not None:
            mean = jax.lax.pmean(mean, self.cfg.context_axis)
        return mean

    # -- GSPMD form ---------------------------------------------------------

    def partition_specs(self):
        """PartitionSpecs for jitting the serial form under GSPMD: the
        compiler inserts the same collectives the shard_map form writes
        explicitly (the idiomatic TPU path)."""
        from jax.sharding import PartitionSpec as P
        if self.cfg.n_experts > 0:
            # MoE: each expert's FFN dim shards over the tensor axis
            # (Column/Row inside the expert); the EXPERT-dim sharding is
            # the explicit shard_map path (expert_axis)
            from apex_tpu.transformer.parallel_state import TENSOR_AXIS
            mlp_spec = {"gate": P(),
                        "w1": P(None, None, TENSOR_AXIS),
                        "w2": P(None, TENSOR_AXIS, None)}
        else:
            mlp_spec = {"fc1": self.layers[0].mlp.fc1.partition_spec(),
                        "fc2": self.layers[0].mlp.fc2.partition_spec()}
        layer_spec = {
            "input_layernorm": {"weight": P(), "bias": P()},
            "attention": {"qkv": self.layers[0].attention.qkv
                          .partition_spec(),
                          "proj": self.layers[0].attention.proj
                          .partition_spec()},
            "post_attention_layernorm": {"weight": P(), "bias": P()},
            "mlp": mlp_spec,
        }
        spec = {
            "embedding": self.embedding.partition_spec(),
            "layers": [layer_spec] * self.cfg.num_layers,
            "final_layernorm": {"weight": P(), "bias": P()},
        }
        if not self.cfg.rotary:
            spec["position_embedding"] = P()
        return spec


def shard_params_for_tp(cfg: GPTConfig, params, rank: int):
    """Slice full (serial-init) GPT params into tensor-parallel rank
    ``rank``'s local shards, matching the layer shardings
    (Column: row-block of weight/bias; Row: column-block of weight,
    replicated bias; vocab embedding: row-block).  Test/checkpoint-resharding
    utility — the shard_map form consumes these shards directly."""
    t = cfg.tensor_parallel_size

    def col(w):      # ColumnParallel weight/bias: shard dim 0
        per = w.shape[0] // t
        return w[rank * per:(rank + 1) * per]

    def row(w):      # RowParallel weight: shard dim 1
        per = w.shape[1] // t
        return w[:, rank * per:(rank + 1) * per]

    def colg(g):     # Column group: weight/bias/scale all row-sharded.
        # Per-output-channel scales ride the same dim-0 slice, which is
        # why quantize-then-shard == shard-then-quantize bitwise here
        out = {"weight": col(g["weight"])}
        if "bias" in g:
            out["bias"] = col(g["bias"])
        if "weight_scale" in g:
            out["weight_scale"] = col(g["weight_scale"])
        return out

    def rowg(g):     # Row group: weight column-sharded; bias and the
        # per-OUTPUT-row scales are replicated (the scale dim is not
        # the sharded dim)
        out = {"weight": row(g["weight"])}
        if "bias" in g:
            out["bias"] = g["bias"]
        if "weight_scale" in g:
            out["weight_scale"] = g["weight_scale"]
        return out

    out = {"embedding": colg(params["embedding"]),
           "final_layernorm": params["final_layernorm"],
           "layers": []}
    if "position_embedding" in params:
        out["position_embedding"] = params["position_embedding"]
    for lp in params["layers"]:
        if "gate" in lp["mlp"]:
            # MoE expert stacks: each expert is Column/Row-sharded on
            # its FFN dim (w1 last dim, w2 middle dim); gate replicated
            fl = cfg.ffn_hidden_size // t
            mlp = {"gate": lp["mlp"]["gate"],
                   "w1": lp["mlp"]["w1"][:, :, rank * fl:(rank + 1) * fl],
                   "w2": lp["mlp"]["w2"][:, rank * fl:(rank + 1) * fl, :]}
        else:
            mlp = {
                "fc1": colg(lp["mlp"]["fc1"]),
                "fc2": rowg(lp["mlp"]["fc2"]),
            }
        out["layers"].append({
            "input_layernorm": lp["input_layernorm"],
            "post_attention_layernorm": lp["post_attention_layernorm"],
            "attention": {
                "qkv": colg(lp["attention"]["qkv"]),
                "proj": rowg(lp["attention"]["proj"]),
            },
            "mlp": mlp,
        })
    return out


def quantize_decode_params(params):
    """Quantize a GPT param tree for the int8 decode path
    (``GPTConfig(weight_quant="int8")``) — run ONCE at inference-engine
    init, never per step.

    Every dense GEMM weight — ``embedding.weight`` (the gather *and*
    the tied lm-head), each layer's ``qkv``/``proj``/``fc1``/``fc2`` —
    becomes ``{"weight": int8, "weight_scale": f32-per-output-row}``
    via :func:`apex_tpu.ops.quant_gemm.quantize_weight`; biases,
    LayerNorms and the (tiny, gather-only) position embedding stay in
    their original dtype.  A pure function of the weight values, so
    the quantized tree is bitwise-deterministic across loads.

    TP composes per shard: the tree may already be the local shard
    from :func:`shard_params_for_tp` — per-output-channel scales make
    quantization commute bitwise with the ColumnParallel/vocab row
    slices, and RowParallel column slices only tighten the per-shard
    scale (local amax <= full amax), never loosen the error bound.
    """
    from apex_tpu.ops.quant_gemm import quantize_weight

    def q(group):
        w8, scale = quantize_weight(group["weight"])
        out = dict(group)
        out["weight"] = w8
        out["weight_scale"] = scale
        return out

    out = {"embedding": q(params["embedding"]),
           "final_layernorm": params["final_layernorm"],
           "layers": []}
    if "position_embedding" in params:
        out["position_embedding"] = params["position_embedding"]
    for lp in params["layers"]:
        if "gate" in lp["mlp"]:
            raise ValueError(
                "quantize_decode_params covers dense GPT trees; this "
                "tree has MoE expert stacks (mlp.gate) — "
                "GPTConfig(weight_quant=...) rejects n_experts > 0 for "
                "the same reason")
        out["layers"].append({
            "input_layernorm": lp["input_layernorm"],
            "post_attention_layernorm": lp["post_attention_layernorm"],
            "attention": {"qkv": q(lp["attention"]["qkv"]),
                          "proj": q(lp["attention"]["proj"])},
            "mlp": {"fc1": q(lp["mlp"]["fc1"]),
                    "fc2": q(lp["mlp"]["fc2"])},
        })
    return out


def _is_spec_leaf(x):
    from jax.sharding import PartitionSpec
    return isinstance(x, PartitionSpec)


def _is_sharded(spec) -> bool:
    return any(a is not None for a in spec)


def pack_for_shard_map(model: GPTModel, params, n_stages: Optional[int] = None,
                       tensor_axis: Optional[str] = "model",
                       pipe_axis: str = "pipe",
                       expert_axis: Optional[str] = None,
                       n_virtual: int = 1):
    """Pack serial-init GPT params for an explicit ``shard_map`` step.

    TP-sharded leaves (per :meth:`GPTModel.partition_specs`) are stacked
    along a new leading ``(tp,)`` axis to be split by the mesh; replicated
    leaves pass through whole so they stay device-INVARIANT inside
    ``shard_map`` — that is load-bearing for gradients: the cotangent of a
    replicated param is split arbitrarily across devices by the backward
    collectives, and only JAX's automatic psum-of-invariant-grads restores
    the total.  With ``n_stages`` the layer stack is additionally split
    over the pipe axis (:func:`stack_layers_for_pipeline`).  With
    ``expert_axis`` (MoE models) the expert stacks (``mlp.w1``/``w2``)
    additionally split their EXPERT dim over that axis — leading mesh
    axes are ordered ``(tp, expert, pipe)``.  ``n_virtual > 1`` keeps an
    extra per-device ``(n_virtual,)`` chunk axis on the layer leaves for
    the interleaved schedule (see :func:`stack_layers_for_pipeline`).

    Returns ``(packed, in_specs, local_fn, repack_fn)``:
    ``local_fn`` strips the unit mesh axes inside ``shard_map`` to yield
    the per-device params :class:`GPTModel`/:func:`pipeline_step` consume;
    ``repack_fn`` is its inverse for gradient pytrees (so ``out_specs`` can
    reuse ``in_specs``).
    """
    from jax.sharding import PartitionSpec as P

    cfg = model.cfg
    n_tp = cfg.tensor_parallel_size
    ep = cfg.expert_parallel_size if expert_axis is not None else 1
    if expert_axis is not None and cfg.n_experts <= 0:
        raise ValueError("expert_axis given but the model has no experts")
    shards = [shard_params_for_tp(cfg, params, r) for r in range(n_tp)]
    if n_stages is not None:
        for sh in shards:
            sh["layers"] = stack_layers_for_pipeline(sh["layers"], n_stages,
                                                     n_virtual)
    elif n_virtual != 1:
        raise ValueError("n_virtual requires n_stages")
    specs = model.partition_specs()
    if n_stages is not None:
        specs = dict(specs, layers=specs["layers"][0])

    def tmap(fn, *trees):
        return jax.tree_util.tree_map(fn, specs, *trees,
                                      is_leaf=_is_spec_leaf)

    packed = tmap(lambda s, *xs: jnp.stack(xs) if _is_sharded(s) else xs[0],
                  *shards)

    from apex_tpu.transformer.expert_parallel import is_gpt_expert_leaf

    def _is_expert(path) -> bool:
        return expert_axis is not None and is_gpt_expert_leaf(path)

    def path_aware(fn):
        # layer leaves carry the extra pipe axis when pipelined; expert
        # leaves carry the extra expert axis when expert-sharded
        def run(tree):
            out = {}
            for key, sub in tree.items():
                in_layers = (key == "layers" and n_stages is not None)
                out[key] = jax.tree_util.tree_map_with_path(
                    lambda p, s, x: fn(s, x, in_layers, _is_expert(p)),
                    specs[key], sub, is_leaf=_is_spec_leaf)
            return out
        return run

    if expert_axis is not None:
        # split the expert dim (after the tp stack [+ stage axes]) into
        # (ep, local) and move ep up to sit right after the tp stack
        def expert_split(s, x, lay, exp):
            if not exp:
                return x
            e_pos = (3 + (n_virtual > 1)) if lay else 1
            nl = x.shape[e_pos] // ep
            x = x.reshape(x.shape[:e_pos] + (ep, nl) + x.shape[e_pos + 1:])
            return jnp.moveaxis(x, e_pos, 1)
        packed = path_aware(expert_split)(packed)

    def spec_for(s, x, lay, exp):
        if exp:
            return (P(tensor_axis, expert_axis, pipe_axis) if lay
                    else P(tensor_axis, expert_axis))
        if lay:
            return P(tensor_axis, pipe_axis) if _is_sharded(s) \
                else P(pipe_axis)
        return P(tensor_axis) if _is_sharded(s) else P()

    def local_for(s, x, lay, exp):
        if exp:
            return x[0, 0, 0] if lay else x[0, 0]
        if lay:
            return x[0, 0] if _is_sharded(s) else x[0]
        return x[0] if _is_sharded(s) else x

    def repack_for(s, g, lay, exp):
        if exp:
            return g[None, None, None] if lay else g[None, None]
        if lay:
            return g[None, None] if _is_sharded(s) else g[None]
        return g[None] if _is_sharded(s) else g

    in_specs = path_aware(spec_for)(packed)
    local_fn = path_aware(local_for)
    repack_fn = path_aware(repack_for)
    return packed, in_specs, local_fn, repack_fn


def unpack_from_shard_map(model: GPTModel, packed,
                          n_stages: Optional[int] = None,
                          n_virtual: int = 1):
    """Inverse of :func:`pack_for_shard_map`: recover the serial-init
    param layout from a packed tree.

    TP-stacked leaves are concatenated back along their sharded dim
    (per :meth:`GPTModel.partition_specs` — the same specs that drove
    the packing), stage stacks are un-interleaved and flattened back to
    the per-layer list.  Pure slicing/concat, so f32 values round-trip
    bitwise — which is what makes the serial layout the canonical form
    elastic re-sharding compares topologies in (a ``dp=2 x tp=2``
    state and a ``dp=4`` state unpack to the SAME logical tensors).
    Expert-parallel packings are not invertible here (the ep split
    interleaves expert rows); unpack before applying ``expert_axis``.
    """
    cfg = model.cfg
    if cfg.n_experts > 0:
        raise ValueError(
            "unpack_from_shard_map does not support expert-parallel "
            "packings; unpack applies to dense GPT params only")
    specs = model.partition_specs()

    def shard_dim(s):
        for d, a in enumerate(s):
            if a is not None:
                return d
        return None

    def merge_plain(s, x):
        d = shard_dim(s)
        if d is None:
            return x
        return jnp.concatenate([x[r] for r in range(x.shape[0])], axis=d)

    def unstack_layers(s, x):
        d = shard_dim(s)
        parts = ([x[r] for r in range(x.shape[0])] if d is not None
                 else [x])
        flat_parts = []
        for y in parts:
            if n_virtual == 1:
                flat = y.reshape((n_stages * y.shape[1],) + y.shape[2:])
            else:
                n_logical = n_stages * n_virtual
                lpc = y.shape[2]
                z = y.reshape((n_logical, lpc) + y.shape[3:])
                perm = [c * n_stages + st for st in range(n_stages)
                        for c in range(n_virtual)]
                inv = jnp.asarray([perm.index(i)
                                   for i in range(n_logical)])
                flat = z[inv].reshape((n_logical * lpc,) + z.shape[2:])
            flat_parts.append(flat)
        # the per-layer sharded dim sits behind the layer axis now
        return (flat_parts[0] if d is None
                else jnp.concatenate(flat_parts, axis=d + 1))

    out = {}
    for key, sub in packed.items():
        if key == "layers" and n_stages is not None:
            merged = jax.tree_util.tree_map(
                unstack_layers, specs["layers"][0], sub,
                is_leaf=_is_spec_leaf)
            n_layers = jax.tree_util.tree_leaves(merged)[0].shape[0]
            out[key] = [jax.tree_util.tree_map(
                lambda leaf, i=i: leaf[i], merged)
                for i in range(n_layers)]
        else:
            out[key] = jax.tree_util.tree_map(
                merge_plain, specs[key], sub, is_leaf=_is_spec_leaf)
    return out


# -- pipeline composition ----------------------------------------------------

def stack_layers_for_pipeline(layer_params, n_stages: int,
                              n_virtual: int = 1):
    """Split per-layer params into pipeline stage stacks.

    ``layer_params`` is the ``params["layers"]`` list; returns a pytree
    whose leaves have shape ``(n_stages, layers_per_stage, ...)`` — shard
    the leading axis over the pipe mesh axis (``in_specs`` leading
    ``P("pipe", ...)``), drop the unit axis inside ``shard_map``, and each
    stage holds exactly its contiguous block of layers (apex: layer ranges
    assigned per pipeline rank).

    With ``n_virtual > 1`` (interleaved schedule) the model splits into
    ``n_stages * n_virtual`` logical stages and leaves come back as
    ``(n_stages, n_virtual, layers_per_stage, ...)`` with device ``s``
    chunk ``c`` holding logical stage ``c * n_stages + s`` (Megatron's
    interleaved chunk assignment).
    """
    n_layers = len(layer_params)
    n_logical = n_stages * n_virtual
    if n_layers % n_logical:
        raise ValueError(
            f"num_layers ({n_layers}) must be divisible by the number of "
            f"logical pipeline stages ({n_stages} x {n_virtual})")
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                     *layer_params)
    lpc = n_layers // n_logical
    if n_virtual == 1:
        return jax.tree_util.tree_map(
            lambda x: x.reshape((n_stages, lpc) + x.shape[1:]), stacked)
    perm = jnp.asarray([c * n_stages + s
                        for s in range(n_stages) for c in range(n_virtual)])
    return jax.tree_util.tree_map(
        lambda x: x.reshape((n_logical, lpc) + x.shape[1:])[perm].reshape(
            (n_stages, n_virtual, lpc) + x.shape[1:]),
        stacked)


def make_stage_fn(model: GPTModel, dropout_seed=None,
                  remat: Optional[bool] = None):
    """Build the ring-engine ``stage_fn``: scan this chunk's stacked layer
    params over the activation (``(mb, s, h) -> (mb, s, h)``), signature
    ``stage_fn(stage_params, x, info)`` (see
    :class:`~apex_tpu.transformer.pipeline_parallel.JobInfo`).

    The stage activation is ``x`` or, for MoE models, ``(x, aux)`` —
    each logical stage adds its local layers' Switch aux contributions so
    the last stage holds the per-microbatch total; the tuple rides the
    ppermute ring (and its cotangent the backward ring) like any leaf.

    ``dropout_seed`` enables attention dropout: the per-layer stream is
    derived *arithmetically* from the job identity — layer ``j`` of
    logical stage ``info.stage`` on microbatch ``info.microbatch`` draws
    ``base + m*MB_STRIDE + (stage*lpc + j)*LAYER_STRIDE`` (int32,
    wrapping) — so seeds never ride the ring and the backward recompute
    replays the exact forward masks.

    ``remat`` (default ``cfg.remat``) wraps each layer in
    ``jax.checkpoint`` with the configured policy: inside the engine's
    per-tick vjp this bounds the *within-job* residuals to layer
    boundaries (the schedule itself already recomputes the stage forward
    from the saved stage input).
    """
    layer = model.layers[0]       # all layers share the module config
    moe = model.cfg.n_experts > 0
    if remat is None:
        remat = model.cfg.remat
    call = layer
    if remat:
        call = jax.checkpoint(
            lambda lp, h, c, s, sd, _l=layer: _l(lp, h, c, s, sd),
            policy=_remat_policy(model.cfg.remat_policy))

    def stage_fn(stage_params, carry, info):
        if moe:
            x, aux = carry
        else:
            x, aux = carry, None
        # under SP the carry is sequence-scattered (mb, s/t, h) but rope
        # positions are global: tables span the FULL sequence (the
        # attention block gathers to full seq internally), mirroring
        # __call__'s ``backbone(..., seq_len=tokens.shape[1])``
        seq = x.shape[1]
        if model._sp_enabled():
            seq = seq * model.cfg.tensor_parallel_size
        cos, sin = model.rope_tables(seq)
        seed = None
        if dropout_seed is not None:
            lpc = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
            seed = (jnp.asarray(dropout_seed, jnp.int32)
                    + jnp.asarray(info.microbatch, jnp.int32)
                    * jnp.int32(_SEED_MB_STRIDE)
                    + jnp.asarray(info.stage, jnp.int32) * jnp.int32(lpc)
                    * jnp.int32(_SEED_LAYER_STRIDE))

        def body(c, lp):
            h, a, sd = c
            out = call(lp, h, cos, sin, sd)
            if moe:
                y, la = out
                a = a + la.astype(a.dtype)
            else:
                y = out
            return (y, a,
                    None if sd is None else sd + _SEED_LAYER_STRIDE), None

        (y, a, _), _ = jax.lax.scan(body, (x, aux, seed), stage_params)
        return (y, a) if moe else y

    return stage_fn


def pipeline_step(model: GPTModel, params, tokens, targets, *,
                  pipe_axis: str = "pipe", data_axis: Optional[str] = None,
                  n_virtual: int = 1, remat: Optional[bool] = None,
                  dropout_seed=None):
    """GPT training step (loss AND grads) over the ring pipeline engine —
    call inside ``shard_map``.  Returns ``(loss, grads)`` with ``grads``
    matching ``params`` leaf-for-leaf.

    ``params["layers"]`` holds this device's stacked layers (leaves
    ``(layers_per_stage, ...)``, or ``(n_virtual, layers_per_stage, ...)``
    for the interleaved schedule, from :func:`stack_layers_for_pipeline`);
    embedding/final-LN params are replicated over the pipe axis.
    ``tokens``/``targets`` are ``(M, mb, s)`` local microbatches.

    Gradients are hand-rolled around
    :func:`~apex_tpu.transformer.pipeline_parallel.pipeline_schedule_step`
    rather than taken with ``jax.grad`` over the whole step — on the jax
    0.4.x span, differentiating through ``shard_map`` collectives is
    version-blocked (psum-transpose cotangent scaling, partial grads for
    replicated leaves).  The embedding runs once outside the scan under
    its own ``jax.vjp`` (flattened over microbatches — the lookup is
    per-token, so this is bitwise-identical to per-microbatch embeds) and
    its pullback consumes the engine's psum-reduced ``dx0``; the tied
    embedding weight's gradient is the sum of that pullback and the last
    stage's head contribution.  All cross-device combining is
    forward-mode psum/pmean of one-nonzero-plus-zeros or of identical
    replicas, so pp=1 runs of this same function are the bitwise f32
    reference for any (S, n_virtual).

    Composition: TP requires ``sequence_parallel=True`` (the Megatron SP
    mappings carry custom-VJP psum rules that fully reduce
    replicated-leaf grads *inside* the local vjp; the non-SP TP path
    relies on shard_map's auto-psum, which this engine never crosses).
    ``data_axis`` pmeans loss+grads; an MoE ``expert_axis`` composes via
    the :func:`~apex_tpu.transformer.expert_parallel.reduce_moe_grads`
    recipe (dense pmean, expert leaves divided by the axis size).
    """
    from apex_tpu.transformer.pipeline_parallel.ring import (
        pipeline_schedule_step)

    cfg = model.cfg
    if cfg.weight_quant is not None:
        raise ValueError(
            f"weight_quant={cfg.weight_quant!r} is a decode/prefill-only "
            "knob: pipeline_step builds gradients, and int8 weights have "
            "none — train with weight_quant=None and let the inference "
            "engine quantize at init (quantize_decode_params)")
    if cfg.axis_name is not None and not cfg.sequence_parallel:
        raise ValueError(
            "pipeline_step under tensor parallelism requires "
            "sequence_parallel=True (non-SP TP grads need shard_map's "
            "auto-psum, which the hand-rolled pipeline backward bypasses)")
    moe = cfg.n_experts > 0
    n_mb, mb, seq = tokens.shape
    with_seed = (cfg.attention_dropout > 0.0 and dropout_seed is not None)

    # ---- embedding: one flattened-batch vjp outside the scan ----------
    embed_keys = ["embedding"] + ([] if cfg.rotary
                                  else ["position_embedding"])
    embed_params = {k: params[k] for k in embed_keys}

    def embed_fn(ep):
        x = model.embed(ep, tokens.reshape(n_mb * mb, seq))
        if model._sp_enabled():
            x = model._sp_scatter(x)
        return x.reshape((n_mb, mb) + x.shape[1:])

    x, embed_pull = jax.vjp(embed_fn, embed_params)
    x0 = (x, jnp.zeros((n_mb,), _f32)) if moe else x

    # ---- last stage: final LN + tied vocab-parallel head + CE ---------
    last_params = {"final_layernorm": params["final_layernorm"],
                   "embedding": params["embedding"]}

    def last_fn(lp, y, tgt, info):
        aux = None
        if moe:
            y, aux = y
        if model._sp_enabled():
            y = model._sp_gather(y)
        lm = jnp.mean(model.head_loss(lp, y, tgt))
        if moe:
            lm = lm + cfg.moe_aux_weight * aux / cfg.num_layers
        return lm

    loss, layer_grads, last_grads, dx0 = pipeline_schedule_step(
        make_stage_fn(model, dropout_seed if with_seed else None,
                      remat=remat),
        last_fn, params["layers"], last_params, x0, targets,
        axis_name=pipe_axis, n_virtual=n_virtual)

    # ---- embedding pullback (dx0 is psum-reduced and replicated over
    # the pipe axis, so every device computes the same grads) -----------
    dx = dx0[0] if moe else dx0      # the aux input is a constant zero
    (embed_grads,) = embed_pull(dx)
    grads = dict(embed_grads)
    grads["embedding"] = jax.tree_util.tree_map(
        jnp.add, grads["embedding"], last_grads["embedding"])
    grads["final_layernorm"] = last_grads["final_layernorm"]
    grads["layers"] = layer_grads

    if data_axis is not None:
        loss = jax.lax.pmean(loss, data_axis)
        grads = jax.lax.pmean(grads, data_axis)
    if moe and cfg.expert_axis is not None:
        # the expert axis doubles as a batch axis for the dense compute:
        # dense leaves pmean across it, expert-stack leaves are already
        # per-shard sums of the global batch (divide, don't reduce) —
        # the reduce_moe_grads recipe, applied here as forward ops
        from apex_tpu.transformer.expert_parallel import is_gpt_expert_leaf
        ep_n = _axis_size(cfg.expert_axis)

        def red(path, g):
            if is_gpt_expert_leaf(path):
                return (g / ep_n).astype(g.dtype)
            return jax.lax.pmean(g, cfg.expert_axis)

        loss = jax.lax.pmean(loss, cfg.expert_axis)
        grads = jax.tree_util.tree_map_with_path(red, grads)
    return loss, grads
