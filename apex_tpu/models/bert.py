"""BERT model family — the FusedLAMB/amp-O2 recipe workload (reference:
apex's MLPerf-BERT lineage: ``apex/contrib/fmha`` kernels are built for
BERT seq<=512, ``DistributedFusedLAMB`` exists for BERT-large pretrain,
and BASELINE workload 2 is "BERT-large pretrain, FusedLAMB +
FusedLayerNorm + amp O2").

Same component wiring as the GPT flagship — VocabParallelEmbedding,
Column/RowParallelLinear, MixedFusedLayerNorm (Pallas), flash attention
(non-causal, padding via ``kv_seqlens``), vocab-parallel cross entropy —
in the encoder arrangement: learned position + segment embeddings,
post-LN blocks, MLM head with tied decoder + NSP pooler head.

Masked-LM convention: ``mlm_labels`` holds the original token id at
masked positions and ``-1`` everywhere else (apex/Megatron's
``labels``/``loss_mask`` pair collapsed into one array).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.models.gpt import _remat_policy
from apex_tpu.normalization import MixedFusedLayerNorm
from apex_tpu.ops.flash_attention import flash_attention
from apex_tpu.ops.fused_ffn import fused_ffn_tp
from apex_tpu.transformer import tensor_parallel as tp

_f32 = jnp.float32


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30528                    # MLPerf padded vocab
    hidden_size: int = 1024                    # BERT-large
    num_layers: int = 24
    num_attention_heads: int = 16
    max_seq_len: int = 512
    type_vocab_size: int = 2
    fused_lm_head: bool = True                 # logit-free blockwise CE
    ffn_hidden_size: Optional[int] = None      # default 4*hidden
    tensor_parallel_size: int = 1
    axis_name: Optional[str] = None
    sequence_parallel: bool = False
    overlap_chunks: int = 0                    # >0: ppermute-ring TP GEMMs
    fused_ffn: bool = False                    # Pallas fused bias-GELU FFN
    remat: bool = False
    remat_policy: str = "full"                 # "full" | "dots" (selective)
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    # one validated ParallelPlan instead of the per-knob kwargs above
    # (see GPTConfig.plan — same supersede-with-warning semantics)
    plan: Optional[object] = None

    def __post_init__(self):
        if self.plan is not None:
            from apex_tpu.parallel.plan import apply_plan_to_config
            apply_plan_to_config(self)
        if self.ffn_hidden_size is None:
            self.ffn_hidden_size = 4 * self.hidden_size
        if self.remat_policy not in ("full", "dots"):
            raise ValueError(
                f"remat_policy must be 'full' or 'dots', got "
                f"{self.remat_policy!r}")
        if self.hidden_size % self.num_attention_heads:
            raise ValueError(
                "hidden_size must be divisible by num_attention_heads")
        if self.num_attention_heads % self.tensor_parallel_size:
            raise ValueError("num_attention_heads must be divisible by "
                             "tensor_parallel_size")
        if self.overlap_chunks < 0:
            raise ValueError(
                f"overlap_chunks must be >= 0, got {self.overlap_chunks}")
        if self.overlap_chunks > 0 and not self.sequence_parallel:
            raise ValueError(
                "overlap_chunks rings the sequence-parallel collective/GEMM "
                "pairs; it requires sequence_parallel=True")

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


class BertSelfAttention:
    """Bidirectional self-attention; padding handled by the flash
    kernel's ``kv_seqlens`` (the reference fmha's cu_seqlens packing)."""

    def __init__(self, cfg: BertConfig):
        self.cfg = cfg
        self.qkv = tp.ColumnParallelLinear(
            cfg.hidden_size, 3 * cfg.hidden_size, gather_output=False,
            world_size=cfg.tensor_parallel_size, axis_name=cfg.axis_name,
            sequence_parallel_enabled=cfg.sequence_parallel,
            seq_dim=1, overlap_chunks=cfg.overlap_chunks,
            param_dtype=cfg.param_dtype)
        self.proj = tp.RowParallelLinear(
            cfg.hidden_size, cfg.hidden_size, input_is_parallel=True,
            world_size=cfg.tensor_parallel_size, axis_name=cfg.axis_name,
            sequence_parallel_enabled=cfg.sequence_parallel,
            seq_dim=1, overlap_chunks=cfg.overlap_chunks,
            param_dtype=cfg.param_dtype)

    def init_params(self, key):
        k1, k2 = jax.random.split(key)
        return {"qkv": self.qkv.init_params(k1),
                "proj": self.proj.init_params(k2)}

    def __call__(self, params, x, seqlens=None):
        cfg = self.cfg
        b = x.shape[0]
        qkv, _ = self.qkv(params["qkv"], x)
        s = qkv.shape[1]
        nh = qkv.shape[-1] // (3 * cfg.head_dim)
        qkv = qkv.reshape(b, s, nh, 3 * cfg.head_dim)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.transpose(0, 2, 1, 3)
        k = k.transpose(0, 2, 1, 3)
        v = v.transpose(0, 2, 1, 3)
        ctx = flash_attention(q, k, v, causal=False, kv_seqlens=seqlens)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, nh * cfg.head_dim)
        out, _ = self.proj(params["proj"], ctx)
        return out


class BertLayer:
    """Post-LN block (original BERT arrangement: residual→LN)."""

    def __init__(self, cfg: BertConfig):
        self.cfg = cfg
        self.attention = BertSelfAttention(cfg)
        self.attention_layernorm = MixedFusedLayerNorm(cfg.hidden_size)
        self.fc1 = tp.ColumnParallelLinear(
            cfg.hidden_size, cfg.ffn_hidden_size, gather_output=False,
            world_size=cfg.tensor_parallel_size, axis_name=cfg.axis_name,
            sequence_parallel_enabled=cfg.sequence_parallel,
            seq_dim=1, overlap_chunks=cfg.overlap_chunks,
            param_dtype=cfg.param_dtype)
        self.fc2 = tp.RowParallelLinear(
            cfg.ffn_hidden_size, cfg.hidden_size, input_is_parallel=True,
            world_size=cfg.tensor_parallel_size, axis_name=cfg.axis_name,
            sequence_parallel_enabled=cfg.sequence_parallel,
            seq_dim=1, overlap_chunks=cfg.overlap_chunks,
            param_dtype=cfg.param_dtype)
        self.output_layernorm = MixedFusedLayerNorm(cfg.hidden_size)

    def init_params(self, key):
        k1, k2, k3 = jax.random.split(key, 3)
        return {"attention": self.attention.init_params(k1),
                "attention_layernorm":
                    self.attention_layernorm.init_params(),
                "fc1": self.fc1.init_params(k2),
                "fc2": self.fc2.init_params(k3),
                "output_layernorm": self.output_layernorm.init_params()}

    def _sp_ln_params(self, params, name):
        """Under SP the per-layer LNs run on the sequence shard, so their
        (replicated) params see per-shard partial grads; identity-fwd/
        psum-bwd restores the total (Megatron's SP grad allreduce)."""
        p = params[name]
        if self.cfg.sequence_parallel and self.cfg.axis_name is not None:
            p = tp.copy_to_tensor_model_parallel_region(
                p, self.cfg.axis_name)
        return p

    def __call__(self, params, x, seqlens=None):
        cfg = self.cfg
        h = self.attention(params["attention"], x, seqlens)
        x = self.attention_layernorm(
            self._sp_ln_params(params, "attention_layernorm"), x + h)
        if cfg.fused_ffn:
            # Pallas fused GEMM+bias+GELU+GEMM with the same TP/SP edge
            # collectives the unfused fc1/fc2 pair uses
            h = fused_ffn_tp(
                x, params["fc1"]["weight"], params["fc1"]["bias"],
                params["fc2"]["weight"], params["fc2"]["bias"],
                tensor_parallel_size=cfg.tensor_parallel_size,
                axis_name=cfg.axis_name,
                sequence_parallel=cfg.sequence_parallel, seq_dim=1)
        else:
            h, _ = self.fc1(params["fc1"], x)
            h = jax.nn.gelu(h, approximate=True)
            h, _ = self.fc2(params["fc2"], h)
        return self.output_layernorm(
            self._sp_ln_params(params, "output_layernorm"), x + h)


class BertModel:
    """Encoder + MLM/NSP heads.

    ``apply(params, tokens, token_type_ids=None, seqlens=None)`` returns
    the final hidden states; ``loss`` computes MLM (+ optional NSP) with
    vocab-parallel cross entropy over the tied decoder.
    """

    def __init__(self, cfg: BertConfig):
        self.cfg = cfg
        self.embedding = tp.VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size,
            world_size=cfg.tensor_parallel_size, axis_name=cfg.axis_name,
            param_dtype=cfg.param_dtype)
        self.embedding_layernorm = MixedFusedLayerNorm(cfg.hidden_size)
        self.layers = [BertLayer(cfg) for _ in range(cfg.num_layers)]
        self.mlm_layernorm = MixedFusedLayerNorm(cfg.hidden_size)

    def init_params(self, key):
        keys = jax.random.split(key, self.cfg.num_layers + 4)
        cfg = self.cfg
        init = lambda k, *s: 0.02 * jax.random.normal(k, s, cfg.param_dtype)
        return {
            "embedding": self.embedding.init_params(keys[0]),
            "position_embedding": init(keys[1], cfg.max_seq_len,
                                       cfg.hidden_size),
            "token_type_embedding": init(keys[2], cfg.type_vocab_size,
                                         cfg.hidden_size),
            "embedding_layernorm": self.embedding_layernorm.init_params(),
            "layers": [l.init_params(k)
                       for l, k in zip(self.layers, keys[3:-1])],
            "mlm_transform": {
                "weight": init(keys[-1], cfg.hidden_size, cfg.hidden_size),
                "bias": jnp.zeros((cfg.hidden_size,), cfg.param_dtype)},
            "mlm_layernorm": self.mlm_layernorm.init_params(),
            "nsp_head": {
                "weight": jnp.zeros((cfg.hidden_size, 2), cfg.param_dtype),
                "bias": jnp.zeros((2,), cfg.param_dtype)},
        }

    def apply(self, params, tokens, token_type_ids=None, seqlens=None):
        cfg = self.cfg
        x = self.embedding(params["embedding"], tokens)
        x = x + params["position_embedding"][:tokens.shape[1]]
        if token_type_ids is None:
            x = x + params["token_type_embedding"][0]
        else:
            x = x + jnp.take(params["token_type_embedding"],
                             token_type_ids, axis=0)
        x = self.embedding_layernorm(params["embedding_layernorm"], x)
        x = x.astype(cfg.dtype)
        sp = cfg.sequence_parallel and cfg.axis_name is not None
        if sp:
            # Megatron SP: the per-layer LNs and residuals run on
            # (b, s/t, h); each block's TP edges gather/reduce-scatter
            if tokens.shape[1] % cfg.tensor_parallel_size:
                raise ValueError(
                    f"sequence_parallel requires seq_len divisible by "
                    f"tensor_parallel_size ({tokens.shape[1]} % "
                    f"{cfg.tensor_parallel_size} != 0)")
            x = tp.scatter_to_sequence_parallel_region(x, cfg.axis_name, 1)
        for layer, lp in zip(self.layers, params["layers"]):
            if cfg.remat:
                x = jax.checkpoint(
                    lambda lp, x, sl, _l=layer: _l(lp, x, sl),
                    policy=_remat_policy(cfg.remat_policy))(
                        lp, x, seqlens)
            else:
                x = layer(lp, x, seqlens)
        if sp:
            x = tp.gather_from_sequence_parallel_region(x, cfg.axis_name, 1)
        return x

    __call__ = apply

    def _mlm_transform(self, params, hidden):
        """Transform + GELU + LN before the tied decoder.

        Under SP the vocab-parallel CE backward delivers per-vocab-shard
        partial cotangents here, so the replicated transform/LN params
        need an identity-fwd/psum-bwd wrap (see BertLayer._sp_ln_params).
        """
        mt, ln = params["mlm_transform"], params["mlm_layernorm"]
        if (self.cfg.sequence_parallel
                and self.cfg.axis_name is not None):
            mt = tp.copy_to_tensor_model_parallel_region(
                mt, self.cfg.axis_name)
            ln = tp.copy_to_tensor_model_parallel_region(
                ln, self.cfg.axis_name)
        h = (hidden.astype(_f32)
             @ mt["weight"].astype(_f32)
             + mt["bias"].astype(_f32))
        h = jax.nn.gelu(h, approximate=True)
        return self.mlm_layernorm(ln, h)

    def mlm_logits(self, params, hidden):
        """Tied-decoder vocab(-parallel) logits ``(b, s, vocab/t)``."""
        h = self._mlm_transform(params, hidden)
        w = params["embedding"]["weight"]
        return jnp.einsum("bsh,vh->bsv", h.astype(_f32), w.astype(_f32))

    def loss(self, params, tokens, mlm_labels, token_type_ids=None,
             seqlens=None, nsp_labels=None):
        """Mean MLM loss over masked positions (+ NSP when labels given).

        ``mlm_labels``: original ids at masked positions, -1 elsewhere.
        """
        hidden = self.apply(params, tokens, token_type_ids, seqlens)
        b, s = mlm_labels.shape
        mask = (mlm_labels >= 0)
        safe = jnp.where(mask, mlm_labels, 0)
        if self.cfg.axis_name is None and self.cfg.fused_lm_head:
            # logit-free tied decoder: the (b*s, vocab) logits never
            # materialize (see ops/lm_head.py; the masked positions'
            # losses are computed on target 0 and masked out below)
            from apex_tpu.ops.lm_head import fused_linear_cross_entropy
            h = self._mlm_transform(params, hidden)
            # compute-dtype operands: the kernel dots at operand
            # precision (see GPTModel.head_loss) — under O2 the tied
            # embedding is bf16 already and h comes out of the f32 LN
            per = fused_linear_cross_entropy(
                h.reshape(b * s, h.shape[-1]).astype(self.cfg.dtype),
                params["embedding"]["weight"].astype(self.cfg.dtype),
                safe.reshape(b * s)).reshape(b, s)
        else:
            logits = self.mlm_logits(params, hidden)
            vl = logits.shape[-1]
            per = tp.vocab_parallel_cross_entropy(
                logits.reshape(b * s, vl), safe.reshape(b * s),
                axis_name=self.cfg.axis_name).reshape(b, s)
        denom = jnp.maximum(jnp.sum(mask), 1)
        loss = jnp.sum(jnp.where(mask, per, 0.0)) / denom
        if nsp_labels is not None:
            pooled = jnp.tanh(hidden[:, 0].astype(_f32))
            nsp = (pooled @ params["nsp_head"]["weight"].astype(_f32)
                   + params["nsp_head"]["bias"].astype(_f32))
            logp = jax.nn.log_softmax(nsp)
            loss = loss - jnp.mean(
                jnp.take_along_axis(logp, nsp_labels[:, None], 1))
        return loss

    # -- GSPMD form ---------------------------------------------------------

    def partition_specs(self):
        """PartitionSpecs for jitting the serial form under GSPMD (same
        contract as :meth:`GPTModel.partition_specs`)."""
        from jax.sharding import PartitionSpec as P
        l0 = self.layers[0]
        ln = {"weight": P(), "bias": P()}
        layer_spec = {
            "attention": {"qkv": l0.attention.qkv.partition_spec(),
                          "proj": l0.attention.proj.partition_spec()},
            "attention_layernorm": ln,
            "fc1": l0.fc1.partition_spec(),
            "fc2": l0.fc2.partition_spec(),
            "output_layernorm": ln,
        }
        return {
            "embedding": self.embedding.partition_spec(),
            "position_embedding": P(),
            "token_type_embedding": P(),
            "embedding_layernorm": ln,
            "layers": [layer_spec] * self.cfg.num_layers,
            "mlm_transform": {"weight": P(), "bias": P()},
            "mlm_layernorm": ln,
            "nsp_head": {"weight": P(), "bias": P()},
        }
