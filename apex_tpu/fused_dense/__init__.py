"""FusedDense / FusedDenseGeluDense — TPU rebuild of
``apex/fused_dense/fused_dense.py`` (+ ``csrc/fused_dense_cuda.cu``).

Apex uses cuBLASLt epilogues (bias, gelu, dgelu+bgrad) to fuse the Linear(+
GELU +Linear) chain.  XLA performs the *epilogue* half of that fusion on TPU
(bias add and GELU fuse into the MXU matmul's output — pinned by
``tests/test_on_chip.py::TestXlaFusionClaim``), so by default these are
functional modules whose value is matching the apex module/`_function`
surface.  What XLA does NOT fuse is the GEMM→GEMM hop: the ``(tokens,
intermediate)`` activation still round-trips through HBM between the two
matmuls, twice per direction counting the backward.  ``fused_ffn=True``
closes that gap by routing the GELU pair onto the Pallas fused-FFN kernel
(:mod:`apex_tpu.ops.fused_ffn` — one pass, f32 accumulation, the
pre-activation as the only saved residual), the same kernel the model
configs enable via their ``fused_ffn`` knob; off-TPU it falls back to a
bitwise-identical unfused reference, so the flag is safe to leave on.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "FusedDense",
    "FusedDenseGeluDense",
    "fused_dense_function",
    "fused_dense_gelu_dense_function",
]


def fused_dense_function(x, weight, bias=None):
    """``x @ W.T + b`` (apex ``fused_dense_function``)."""
    y = x @ weight.T
    if bias is not None:
        y = y + bias
    return y


def fused_dense_gelu_dense_function(x, weight1, bias1, weight2, bias2,
                                    fused_ffn=False):
    """Linear→GELU→Linear (apex ``fused_dense_gelu_dense_function``).

    ``fused_ffn=True`` runs the pair as ONE Pallas kernel
    (:func:`apex_tpu.ops.fused_ffn.fused_ffn` — the implementation the
    model FFNs share); default keeps the XLA epilogue-fusion chain."""
    if fused_ffn:
        from apex_tpu.ops.fused_ffn import fused_ffn as _fused_ffn
        return _fused_ffn(x, weight1, bias1, weight2, bias2)
    h = jax.nn.gelu(x @ weight1.T + bias1, approximate=True)
    return h @ weight2.T + bias2


class _DenseBase:
    def _init_linear(self, key, out_f, in_f):
        bound = 1.0 / jnp.sqrt(in_f)
        k1, k2 = jax.random.split(key)
        w = jax.random.uniform(k1, (out_f, in_f), minval=-bound,
                               maxval=bound, dtype=jnp.float32)
        b = jax.random.uniform(k2, (out_f,), minval=-bound, maxval=bound,
                               dtype=jnp.float32)
        return w.astype(self.param_dtype), b.astype(self.param_dtype)


class FusedDense(_DenseBase):
    """apex ``FusedDense(in_features, out_features, bias=True)``."""

    def __init__(self, in_features, out_features, bias=True,
                 param_dtype=jnp.float32):
        self.in_features = int(in_features)
        self.out_features = int(out_features)
        self.bias = bool(bias)
        self.param_dtype = param_dtype

    def init_params(self, key):
        w, b = self._init_linear(key, self.out_features, self.in_features)
        return {"weight": w, "bias": b} if self.bias else {"weight": w}

    def __call__(self, params, x):
        return fused_dense_function(x, params["weight"],
                                    params.get("bias"))

    apply = __call__


class FusedDenseGeluDense(_DenseBase):
    """apex ``FusedDenseGeluDense(in, intermediate, out)``."""

    def __init__(self, in_features, intermediate_features, out_features,
                 bias=True, param_dtype=jnp.float32, fused_ffn=False):
        if not bias:
            raise ValueError(
                "FusedDenseGeluDense module without bias is currently not "
                "supported")  # apex parity
        self.in_features = int(in_features)
        self.intermediate_features = int(intermediate_features)
        self.out_features = int(out_features)
        self.param_dtype = param_dtype
        self.fused_ffn = bool(fused_ffn)

    def init_params(self, key):
        k1, k2 = jax.random.split(key)
        w1, b1 = self._init_linear(k1, self.intermediate_features,
                                   self.in_features)
        w2, b2 = self._init_linear(k2, self.out_features,
                                   self.intermediate_features)
        return {"weight1": w1, "bias1": b1, "weight2": w2, "bias2": b2}

    def __call__(self, params, x):
        return fused_dense_gelu_dense_function(
            x, params["weight1"], params["bias1"], params["weight2"],
            params["bias2"], fused_ffn=self.fused_ffn)

    apply = __call__
