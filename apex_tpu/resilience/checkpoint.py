"""Atomic, content-hashed, shard-aware checkpointing.

The upstream reference's checkpoint surface is the amp loss-scaler
``state_dict`` round-trip; model/optimizer persistence is user-side
``torch.save``, which at fleet scale loses work to exactly the failures
this module defends against: a preemption mid-write leaves a torn file
that a naive ``load`` deserializes into garbage (or crashes on), and a
restart can't tell the good checkpoint from the bad one.

Layout (one directory per step)::

    <dir>/step_00000012/
        state.bin        packed shard payload (native parallel write)
        MANIFEST.json    leaf/shard table + sha256 of state.bin
    <dir>/latest -> step_00000012

Commit protocol — survives a kill at ANY point:

1. everything is written into ``step_N.tmp`` (an unfinished tmp dir is
   never a restore candidate);
2. ``MANIFEST.json`` (carrying the payload's sha256) is written LAST and
   fsynced — a dir without a manifest is ignored;
3. the tmp dir is renamed to ``step_N`` (atomic on POSIX);
4. ``latest`` is repointed via a tmp symlink + ``os.replace`` (atomic).

Restore verifies the payload hash against the manifest; a mismatch
(torn or bit-flipped write that still managed to commit) discards that
candidate and falls back to the previous complete checkpoint.

Shard awareness: a leaf that is a sharded ``jax.Array`` (ZeRO optimizer
state under ``shard_map``, TP params, …) is saved as its addressable
shards — each dp/tp shard writes its own slice, no host-side gather of
the full array.  Restore reassembles the global array from the recorded
slice indices and places it with ``jax.device_put`` onto the TEMPLATE's
sharding, so a checkpoint taken on one topology restores onto another
(re-shard) or onto a single host (gather).

Async: :meth:`CheckpointManager.save_async` hands the whole save
(device→host copies included — jax arrays are immutable, so the
snapshot is free) to a background writer thread, double-buffered: up to
two saves may be in flight before the caller blocks, keeping the write
entirely off the step path.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import warnings
from typing import Any, List, Optional, Tuple

import numpy as np

from apex_tpu.utils import native

_FORMAT = 1
_PAYLOAD = "state.bin"
_MANIFEST = "MANIFEST.json"
_LATEST = "latest"


class CheckpointNotFound(FileNotFoundError):
    """No complete, hash-valid checkpoint exists in the directory."""


def _step_dirname(step: int) -> str:
    return f"step_{step:08d}"


def _parse_step(name: str) -> Optional[int]:
    if not name.startswith("step_") or name.endswith(".tmp"):
        return None
    try:
        return int(name[len("step_"):])
    except ValueError:
        return None


def _shard_entries(leaf) -> List[Tuple[tuple, np.ndarray]]:
    """``[(index, host_slice)]`` for a leaf; the index is a per-dim
    ``(start, stop)`` tuple into the global shape.  Sharded jax arrays
    contribute one entry per distinct addressable shard (replicated
    shards dedupe to one); anything else is a single whole-array entry."""
    shards = getattr(leaf, "addressable_shards", None)
    if shards is None:
        a = np.asarray(leaf)
        return [(tuple((0, d) for d in a.shape), a)]
    shape = leaf.shape
    out, seen = [], set()
    for sh in shards:
        idx = tuple(
            (0 if sl.start is None else int(sl.start),
             shape[d] if sl.stop is None else int(sl.stop))
            for d, sl in enumerate(sh.index))
        if idx in seen:
            continue
        seen.add(idx)
        out.append((idx, np.asarray(sh.data)))
    if not out:                         # 0-d / fully-addressable fallback
        a = np.asarray(leaf)
        out.append((tuple((0, d) for d in a.shape), a))
    return out


class CheckpointManager:
    """Atomic checkpoint store rooted at ``directory``.

    ``keep`` complete checkpoints are retained (older ones are deleted
    after each successful commit — the fallback chain needs at least 2).
    ``fault_injector`` threads :class:`~apex_tpu.resilience.faults.
    FaultInjector` through the IO path: a scheduled
    ``corrupt_checkpoint`` at the saved step flips payload bytes AFTER
    the commit, producing exactly the torn write the hash check must
    catch.

    ``topology`` (a :class:`~apex_tpu.resilience.elastic.TopologySpec`
    or its dict form; mutable — the elastic trainer updates it on every
    re-plan) is stamped into each manifest together with the mesh
    shape, so a restart can tell which layout a checkpoint's arrays are
    partitioned for BEFORE deserializing them into the wrong one.

    ``parallel_plan`` (a :class:`~apex_tpu.parallel.plan.ParallelPlan`
    or its dict form) is stamped under its own manifest key; the
    ``topology`` key keeps its original schema so manifests written by
    older versions of this module round-trip unchanged.
    """

    def __init__(self, directory: str, *, keep: int = 2, threads: int = 4,
                 fault_injector=None, topology=None, parallel_plan=None):
        if keep < 1:
            raise ValueError("keep must be >= 1")
        self.directory = str(directory)
        self.keep = int(keep)
        self.threads = int(threads)
        self.fault_injector = fault_injector
        self.topology = topology
        self.parallel_plan = parallel_plan
        os.makedirs(self.directory, exist_ok=True)
        self._pending: list = []          # [(step, thread, box)]
        self._lock = threading.Lock()

    def _topology_dict(self) -> Optional[dict]:
        t = self.topology
        if t is None:
            return None
        return t.to_dict() if hasattr(t, "to_dict") else dict(t)

    def _plan_dict(self) -> Optional[dict]:
        p = self.parallel_plan
        if p is None:
            return None
        return p.to_dict() if hasattr(p, "to_dict") else dict(p)

    # -- enumeration --------------------------------------------------------

    def all_steps(self) -> List[int]:
        """Committed step numbers, ascending (manifest presence only —
        hash validity is restore's concern)."""
        steps = []
        for name in os.listdir(self.directory):
            s = _parse_step(name)
            if s is not None and os.path.exists(
                    os.path.join(self.directory, name, _MANIFEST)):
                steps.append(s)
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def topology_of(self, step: int) -> Optional[dict]:
        """The topology dict stamped into ``step``'s manifest (``None``
        for checkpoints saved without one) — manifest-only, no payload
        read, so a restart can pick its restore layout cheaply."""
        mpath = os.path.join(self.directory, _step_dirname(step), _MANIFEST)
        try:
            with open(mpath) as f:
                return json.load(f).get("topology")
        except (OSError, ValueError):
            return None

    def plan_of(self, step: int) -> Optional[dict]:
        """The full parallel-plan dict stamped into ``step``'s manifest
        (``None`` for checkpoints saved before plans existed or without
        one) — manifest-only, like :meth:`topology_of`."""
        mpath = os.path.join(self.directory, _step_dirname(step), _MANIFEST)
        try:
            with open(mpath) as f:
                return json.load(f).get("parallel_plan")
        except (OSError, ValueError):
            return None

    # -- save ---------------------------------------------------------------

    def save(self, step: int, state) -> str:
        """Write and commit a checkpoint for ``step``; returns the
        committed directory path."""
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(state)
        recs, arrays = [], []
        offset = 0
        for leaf in leaves:
            dtype = str(np.asarray(leaf).dtype) if not hasattr(
                leaf, "dtype") else str(np.dtype(leaf.dtype))
            shards = []
            for idx, data in _shard_entries(leaf):
                data = np.ascontiguousarray(data)
                shards.append({"index": [list(p) for p in idx],
                               "offset": offset,
                               "nbytes": int(data.nbytes)})
                arrays.append(data)
                offset += int(data.nbytes)
            recs.append({"shape": [int(d) for d in getattr(
                leaf, "shape", np.asarray(leaf).shape)],
                "dtype": dtype, "shards": shards})

        payload = native.pack(arrays) if arrays else np.empty((0,), np.uint8)
        digest = hashlib.sha256(payload.tobytes()).hexdigest()
        manifest = {"format": _FORMAT, "step": int(step),
                    "sha256": digest, "nbytes": int(payload.nbytes),
                    "treedef": str(treedef), "leaves": recs}
        topo = self._topology_dict()
        if topo is not None:
            manifest["topology"] = topo
            manifest["mesh_shape"] = {"data": topo.get("dp", 1),
                                      "pipe": topo.get("pp", 1),
                                      "model": topo.get("tp", 1)}
        plan = self._plan_dict()
        if plan is not None:
            manifest["parallel_plan"] = plan

        final = os.path.join(self.directory, _step_dirname(step))
        tmp = final + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        native.file_write(os.path.join(tmp, _PAYLOAD), payload,
                          threads=self.threads)
        # manifest last: its presence marks the payload complete
        mpath = os.path.join(tmp, _MANIFEST)
        with open(mpath, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)
        self._point_latest(final)
        inj = self.fault_injector
        if inj is not None and inj.should_corrupt(step):
            _corrupt_payload(os.path.join(final, _PAYLOAD))
            inj.record(step, "corrupt_checkpoint")
        self._retire()
        return final

    def _point_latest(self, final: str) -> None:
        link = os.path.join(self.directory, _LATEST)
        tmp = link + ".tmp"
        if os.path.lexists(tmp):
            os.unlink(tmp)
        os.symlink(os.path.basename(final), tmp)
        os.replace(tmp, link)

    def _retire(self) -> None:
        steps = self.all_steps()
        for s in steps[:-self.keep]:
            shutil.rmtree(os.path.join(self.directory, _step_dirname(s)),
                          ignore_errors=True)

    # -- async double-buffered save -----------------------------------------

    def save_async(self, step: int, state) -> None:
        """Queue the save on a writer thread (device→host copy included;
        jax arrays are immutable so the state snapshot is free — do not
        pass buffers you are about to donate).  At most two saves run
        ahead of the caller; a third call blocks on the oldest, which is
        the explicit backpressure keeping writes off the step path."""
        with self._lock:
            while len(self._pending) >= 2:
                self._join_oldest()
            box = {}

            def work():
                try:
                    box["path"] = self.save(step, state)
                except BaseException as e:          # surfaced on wait()
                    box["error"] = e

            t = threading.Thread(target=work, daemon=True,
                                 name=f"ckpt-save-{step}")
            t.start()
            self._pending.append((step, t, box))

    def _join_oldest(self) -> None:
        step, t, box = self._pending.pop(0)
        t.join()
        if "error" in box:
            raise box["error"]

    def wait(self) -> None:
        """Block until every queued async save has committed (re-raises
        the first writer error)."""
        with self._lock:
            while self._pending:
                self._join_oldest()

    # -- restore ------------------------------------------------------------

    def restore(self, template, *, step: Optional[int] = None,
                shardings=None, topology=None) -> Tuple[Any, int]:
        """Load the newest complete, hash-valid checkpoint.

        ``template`` supplies the pytree structure and (via its leaves'
        ``.sharding``) the target placement: restoring onto a different
        mesh/topology than the save is just a different template.
        ``shardings``, when given, is a matching pytree overriding the
        per-leaf placement.  ``step`` pins a specific checkpoint instead
        of the newest.  ``topology`` declares the layout the caller is
        restoring INTO (:class:`~apex_tpu.resilience.elastic.
        TopologySpec` or dict); when it differs from the manifest's
        stamped topology a warning names BOTH specs — the state is
        still loaded (templates define placement), but the caller is on
        notice that :func:`~apex_tpu.resilience.elastic.
        reshard_optimizer_state` must run before any layout-dependent
        state (ZeRO buckets) is usable.  Returns ``(state, step)``;
        raises :class:`CheckpointNotFound` when no valid candidate
        survives the hash check.
        """
        import jax

        candidates = ([step] if step is not None
                      else sorted(self.all_steps(), reverse=True))
        for s in candidates:
            path = os.path.join(self.directory, _step_dirname(s))
            try:
                leaves, manifest = self._load_dir(path)
            except (OSError, ValueError, KeyError) as e:
                warnings.warn(
                    f"checkpoint {path} is corrupt or torn ({e}); "
                    "falling back to the previous complete checkpoint",
                    stacklevel=2)
                continue
            t_leaves, treedef = jax.tree_util.tree_flatten(template)
            if len(leaves) != len(t_leaves):
                warnings.warn(
                    f"checkpoint {path} has {len(leaves)} leaves but the "
                    f"template has {len(t_leaves)}; skipping", stacklevel=2)
                continue
            if topology is not None:
                want = (topology.to_dict() if hasattr(topology, "to_dict")
                        else dict(topology))
                saved = manifest.get("topology")
                if saved is not None and saved != want:
                    warnings.warn(
                        f"checkpoint {path} was saved under topology "
                        f"{saved} but is being restored onto {want}; "
                        "optimizer state must be re-sharded "
                        "(reshard_optimizer_state) before use",
                        stacklevel=2)
            s_leaves = (None if shardings is None
                        else jax.tree_util.tree_leaves(shardings))
            out = []
            for i, (arr, tl) in enumerate(zip(leaves, t_leaves)):
                sh = (s_leaves[i] if s_leaves is not None
                      else getattr(tl, "sharding", None))
                if sh is not None:
                    out.append(jax.device_put(arr, sh))
                elif hasattr(tl, "dtype"):
                    import jax.numpy as jnp
                    out.append(jnp.asarray(arr))
                else:
                    out.append(arr)
            return jax.tree_util.tree_unflatten(treedef, out), s
        raise CheckpointNotFound(
            f"no complete checkpoint under {self.directory!r} "
            f"(candidates tried: {candidates})")

    def _load_dir(self, path: str) -> Tuple[List[np.ndarray], dict]:
        with open(os.path.join(path, _MANIFEST)) as f:
            manifest = json.load(f)
        payload = native.file_read(os.path.join(path, _PAYLOAD),
                                   threads=self.threads)
        if payload.nbytes != manifest["nbytes"]:
            raise ValueError(
                f"payload is {payload.nbytes} bytes, manifest says "
                f"{manifest['nbytes']} (torn write)")
        digest = hashlib.sha256(payload.tobytes()).hexdigest()
        if digest != manifest["sha256"]:
            raise ValueError(
                f"payload hash {digest[:12]}… does not match manifest "
                f"{manifest['sha256'][:12]}… (corrupt write)")
        leaves = []
        for rec in manifest["leaves"]:
            dt = np.dtype(rec["dtype"])
            full = np.empty([int(d) for d in rec["shape"]], dt)
            for sh in rec["shards"]:
                sl = tuple(slice(a, b) for a, b in sh["index"])
                n = sh["nbytes"]
                part = payload[sh["offset"]:sh["offset"] + n].view(dt)
                full[sl] = part.reshape(full[sl].shape)
            leaves.append(full)
        return leaves, manifest


def _corrupt_payload(path: str, n: int = 64) -> None:
    """Flip bytes in the middle of ``path`` — the injected torn write."""
    size = os.path.getsize(path)
    if size == 0:
        return
    with open(path, "r+b") as f:
        f.seek(size // 2)
        chunk = f.read(min(n, size - size // 2))
        f.seek(size // 2)
        f.write(bytes(b ^ 0xFF for b in chunk))
        f.flush()
        os.fsync(f.fileno())
