"""Anomaly-guarded training — the loss-scaler overflow skip, generalized.

Upstream apex's dynamic loss scaling already SKIPS a step whose scaled
gradients overflow; at fleet scale the same treatment is needed for
every step-level anomaly: NaN/inf loss (bad batch, race in the input
pipeline), NaN/inf gradients (numerics), and gradient-norm spikes (the
classic loss-blowup precursor).  :class:`GuardedTrainStep` wraps a loss
function + fused optimizer into one jitted step that

* detects: non-finite loss, non-finite gradient norm, and
  ``‖g‖ > spike_factor × EMA(‖g‖)`` (EMA over clean steps only, armed
  after ``warmup_steps``);
* skips: the anomaly becomes the optimizer's on-device ``noop_flag`` —
  params, moments and the step counter are untouched, exactly the
  loss-scaler skip path, with no host sync inside the step;
* records: per-kind counters in the device-side :class:`GuardState`
  and host-side ``stats`` (which also surfaces the loss scaler's
  cumulative ``skipped_steps``);
* recovers: after ``max_consecutive`` anomalous steps in a row the
  wrapper restores the last complete checkpoint from its attached
  :class:`~apex_tpu.resilience.checkpoint.CheckpointManager` and
  returns the restored state (``rolled_back=True``) — persistent
  corruption cannot out-run the skip heuristic.

Fault injection rides the same compiled program: the injector's
per-step scalars fold in with ``jnp.where`` (data, not control flow),
so clean and faulty steps share one XLA executable.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

_f32 = jnp.float32


class GuardState(NamedTuple):
    """Device-side guard state (checkpointable pytree)."""
    ema_norm: jax.Array      # f32 — EMA of the unscaled grad norm
    clean_steps: jax.Array   # int32 — steps that fed the EMA
    consecutive: jax.Array   # int32 — current anomaly run length
    anomalies: jax.Array     # int32 — total skipped steps
    nonfinite: jax.Array     # int32 — NaN/inf loss-or-grad skips
    spikes: jax.Array        # int32 — grad-norm-spike skips


@dataclasses.dataclass
class StepResult:
    """Outcome of one guarded step.  ``next_step`` is the step index the
    train loop should run next — ``step + 1`` normally, the restored
    step after a rollback.  ``loss_value``/``loss_scale_value`` are host
    floats materialized by the SAME single readback that carries the
    anomaly flags (one 6-element transfer per step, replacing the old
    flags + grad-norm pair) — the telemetry tap the observability
    layer's ``TrainingMonitor`` reads without adding device→host
    syncs.  ``loss_scale_value`` is ``None`` when no scaler is
    attached."""
    loss: Any
    params: Any
    opt_state: Any
    guard_state: GuardState
    scaler_state: Any
    grad_norm: float
    skipped: bool
    anomaly: Optional[str]          # "nonfinite" | "spike" | None
    next_step: int
    rolled_back: bool = False
    restored_from: Optional[int] = None
    loss_value: float = float("nan")
    loss_scale_value: Optional[float] = None


_CLEAN_FLAGS = {"nan_grads": 0.0, "inf_loss": 0.0, "spike_scale": 1.0}


class GuardedTrainStep:
    """``GuardedTrainStep(loss_fn, optimizer, ...)`` then
    ``result = gstep(params, opt_state, gstate, *batch, step=i)``.

    ``loss_fn(params, *batch) -> scalar``.  For gradients that need
    their own collective context (e.g. a ``shard_map`` data-parallel
    region) pass ``grad_fn(params, *batch) -> (loss, grads)`` instead.
    ``scaler``/``scaler_state`` thread dynamic loss scaling through the
    same skip: non-finite anomalies count as scaler overflows (halving
    the scale and bumping its cumulative ``skipped`` counter) while
    spikes skip at the guard level only.  ``checkpoint`` arms rollback;
    call :meth:`save` from the train loop to keep it stocked.
    """

    def __init__(self, loss_fn: Optional[Callable] = None,
                 optimizer=None, *, grad_fn: Optional[Callable] = None,
                 scaler=None, spike_factor: float = 10.0,
                 ema_decay: float = 0.99, warmup_steps: int = 5,
                 max_consecutive: int = 3, checkpoint=None,
                 fault_injector=None, lr=None, donate: bool = False,
                 plan=None):
        if (loss_fn is None) == (grad_fn is None):
            raise ValueError("pass exactly one of loss_fn / grad_fn")
        if optimizer is None:
            raise ValueError("an optimizer is required")
        if grad_fn is not None and scaler is not None:
            raise ValueError(
                "scaler requires the loss_fn form (the guard scales the "
                "loss before autodiff); with grad_fn, scale inside it")
        # `plan` (a ParallelPlan) declares the layout this step's state
        # lives under — the elastic layer stamps it into checkpoint
        # manifests alongside the topology.  The one cross-check the
        # guard can make locally: a ZeRO optimizer's shard factor must
        # match the plan's zero_shard
        self.plan = plan
        if plan is not None:
            inner = getattr(optimizer, "inner", optimizer)
            ws = getattr(inner, "world_size", None)
            if ws is not None and ws != plan.zero_shard:
                raise ValueError(
                    f"optimizer world_size={ws} does not match "
                    f"plan.zero_shard={plan.zero_shard}; build the "
                    "optimizer from the same plan "
                    "(DistributedFusedAdam(plan=plan))")
        self.loss_fn = loss_fn
        self.grad_fn = grad_fn
        self.optimizer = optimizer
        self.scaler = scaler
        self.spike_factor = float(spike_factor)
        self.ema_decay = float(ema_decay)
        self.warmup_steps = int(warmup_steps)
        self.max_consecutive = int(max_consecutive)
        self.checkpoint = checkpoint
        self.fault_injector = fault_injector
        self.lr = lr
        self.donate = bool(donate)
        # donate the full train state (params, opt, guard, scaler): the
        # update is in-place, halving the state's HBM across the step.
        # Opt-in because the caller's input buffers die — safe with the
        # standard drive loop (it only keeps the returned state; the
        # rollback template reads shape/dtype metadata, which survives
        # donation), unsafe for callers that re-read the old state
        self._compiled = jax.jit(
            self._raw_step,
            donate_argnums=(0, 1, 2, 3) if self.donate else ())
        self._consecutive = 0
        self._last_sstate = None
        self.counters = {"steps": 0, "skipped": 0, "nonfinite": 0,
                         "spikes": 0, "rollbacks": 0}

    def init_state(self) -> GuardState:
        # one array PER field: donate=True donates this tree, and XLA
        # rejects the same buffer appearing twice in a donated argument
        return GuardState(jnp.zeros((), _f32),
                          *(jnp.zeros((), jnp.int32) for _ in range(5)))

    # -- the jitted step -----------------------------------------------------

    def _raw_step(self, params, opt_state, gstate: GuardState, sstate,
                  inj, *batch):
        scaler = self.scaler
        if self.grad_fn is not None:
            loss, grads = self.grad_fn(params, *batch)
        else:
            def lf(p):
                l = self.loss_fn(p, *batch)
                if scaler is not None:
                    l = l * sstate.loss_scale.astype(l.dtype)
                return l
            loss, grads = jax.value_and_grad(lf)(params)

        # fault injection as data: identity on clean steps
        loss = jnp.where(inj[1] > 0,
                         jnp.asarray(jnp.inf, loss.dtype), loss)
        nan = jnp.where(inj[0] > 0, jnp.asarray(jnp.nan, _f32),
                        jnp.zeros((), _f32))
        grads = jax.tree_util.tree_map(
            lambda g: g * inj[2].astype(g.dtype) + nan.astype(g.dtype),
            grads)

        inv_scale = (1.0 / sstate.loss_scale if scaler is not None
                     else jnp.ones((), _f32))
        gsq = jnp.zeros((), _f32)
        for g in jax.tree_util.tree_leaves(grads):
            gsq = gsq + jnp.sum(jnp.square(g.astype(_f32)))
        gnorm = jnp.sqrt(gsq) * inv_scale       # unscaled grad norm

        bad = (~jnp.isfinite(loss.astype(_f32))) | (~jnp.isfinite(gnorm))
        armed = gstate.clean_steps >= self.warmup_steps
        spike = armed & ~bad & (gnorm > self.spike_factor
                                * gstate.ema_norm)
        anomaly = bad | spike
        noop = anomaly.astype(jnp.int32)

        new_params, new_opt = self.optimizer.step(
            grads, params, opt_state, lr=self.lr, grad_scale=inv_scale,
            noop_flag=noop)

        first = gstate.clean_steps == 0
        ema = jnp.where(
            anomaly, gstate.ema_norm,
            jnp.where(first, gnorm,
                      self.ema_decay * gstate.ema_norm
                      + (1.0 - self.ema_decay) * gnorm))
        new_gstate = GuardState(
            ema, gstate.clean_steps + (1 - noop),
            jnp.where(anomaly, gstate.consecutive + 1, 0),
            gstate.anomalies + noop,
            gstate.nonfinite + bad.astype(jnp.int32),
            gstate.spikes + spike.astype(jnp.int32))

        if scaler is not None:
            sstate = scaler.update(sstate, bad.astype(_f32))
            loss = loss.astype(_f32) * inv_scale
        # one telemetry vector = one device->host transfer on the host
        # side: anomaly flags + grad norm + loss + (post-update) loss
        # scale all materialize together
        telemetry = jnp.stack([
            anomaly.astype(_f32), bad.astype(_f32), spike.astype(_f32),
            gnorm, loss.astype(_f32), sstate.loss_scale.astype(_f32)])
        return (loss, new_params, new_opt, new_gstate, sstate, telemetry)

    # -- host wrapper --------------------------------------------------------

    def __call__(self, params, opt_state, guard_state: GuardState, *batch,
                 scaler_state=None, step: Optional[int] = None
                 ) -> StepResult:
        if any(getattr(l, "dtype", None) == jnp.int8
               for l in jax.tree_util.tree_leaves(params)):
            raise ValueError(
                "params contain int8 leaves — a weight_quant='int8' "
                "decode tree (quantize_decode_params output). "
                "GuardedTrainStep differentiates and updates f32/bf16 "
                "master weights; quantization is inference-engine-init "
                "only.  Train on the unquantized tree and set "
                "weight_quant on the serving GPTConfig instead")
        if (self.scaler is None) != (scaler_state is None):
            raise ValueError("scaler_state must be passed iff the guard "
                             "was built with a scaler")
        if step is None:
            step = self.counters["steps"]
        inj = self.fault_injector
        flags_in = _CLEAN_FLAGS
        if inj is not None:
            inj.check_preempt(step)     # raises Preemption — no cleanup
            inj.maybe_slow_host(step)
            flags_in = inj.grad_flags(step)
        inj_arr = jnp.asarray([flags_in["nan_grads"], flags_in["inf_loss"],
                               flags_in["spike_scale"]], _f32)
        sstate = (scaler_state if scaler_state is not None
                  else _null_scaler_state())
        (loss, new_params, new_opt, new_gstate, new_sstate,
         telemetry) = self._compiled(params, opt_state, guard_state,
                                     sstate, inj_arr, *batch)
        (anomaly_f, bad_f, spike_f, gnorm_f, loss_f,
         scale_f) = (float(x) for x in np.asarray(telemetry))
        skipped = anomaly_f > 0
        kind = ("nonfinite" if bad_f > 0
                else "spike" if spike_f > 0 else None)
        self.counters["steps"] += 1
        self.counters["skipped"] += int(skipped)
        self.counters["nonfinite"] += int(bad_f > 0)
        self.counters["spikes"] += int(spike_f > 0)
        self._consecutive = self._consecutive + 1 if skipped else 0
        out_sstate = new_sstate if self.scaler is not None else None
        self._last_sstate = out_sstate
        out_scale = scale_f if self.scaler is not None else None

        if (skipped and self.checkpoint is not None
                and self._consecutive >= self.max_consecutive):
            restored, ck_step = self.checkpoint.restore(self._template(
                params, opt_state, new_gstate, out_sstate))
            self.counters["rollbacks"] += 1
            self._consecutive = 0
            return StepResult(
                loss=loss, params=restored["params"],
                opt_state=restored["opt"], guard_state=restored["guard"],
                scaler_state=restored.get("scaler"),
                grad_norm=gnorm_f, skipped=True, anomaly=kind,
                next_step=int(np.asarray(restored["step"])),
                rolled_back=True, restored_from=ck_step,
                loss_value=loss_f, loss_scale_value=out_scale)
        return StepResult(
            loss=loss, params=new_params, opt_state=new_opt,
            guard_state=new_gstate, scaler_state=out_sstate,
            grad_norm=gnorm_f, skipped=skipped, anomaly=kind,
            next_step=step + 1, loss_value=loss_f,
            loss_scale_value=out_scale)

    # -- checkpoint plumbing -------------------------------------------------

    @staticmethod
    def _template(params, opt_state, guard_state, scaler_state):
        t = {"params": params, "opt": opt_state, "guard": guard_state,
             "step": jnp.zeros((), jnp.int32)}
        if scaler_state is not None:
            t["scaler"] = scaler_state
        return t

    def save(self, next_step: int, params, opt_state,
             guard_state: GuardState, scaler_state=None, *,
             async_: bool = False) -> None:
        """Checkpoint the full guarded train state.  ``next_step`` is
        the step index training would run next (i.e. call this AFTER
        step ``next_step - 1``); rollback resumes there."""
        if self.checkpoint is None:
            raise ValueError("no CheckpointManager attached")
        state = self._template(params, opt_state, guard_state,
                               scaler_state)
        state["step"] = jnp.asarray(next_step, jnp.int32)
        if async_:
            self.checkpoint.save_async(next_step, state)
        else:
            self.checkpoint.save(next_step, state)

    @property
    def stats(self) -> dict:
        """Host-side counters; includes the scaler's cumulative
        ``skipped_steps`` when dynamic loss scaling is attached."""
        out = dict(self.counters)
        if self._last_sstate is not None:
            out["scaler_skipped_steps"] = int(self._last_sstate.skipped)
        return out


# placeholder threaded through the jitted signature when no scaler is
# attached (never read: every use is behind `scaler is not None`)
class _NullScalerState(NamedTuple):
    loss_scale: jax.Array


def _null_scaler_state() -> _NullScalerState:
    # built lazily: module import must not initialize the jax backend
    return _NullScalerState(jnp.ones((), _f32))
