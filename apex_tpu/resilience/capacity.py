"""Unified train+serve capacity shifting (ROADMAP item 4).

A :class:`CapacityController` owns one chip budget split between an
:class:`~apex_tpu.resilience.elastic.ElasticTrainer` and a
:class:`~apex_tpu.serving.fleet.FleetRouter`-fronted serving fleet, and
moves chips between them under live traffic.  Decisions are driven by
the serving side's :class:`~apex_tpu.observability.slo.SLOMonitor` burn
rate: sustained burn above ``burn_high`` shifts capacity **to serving**
(shrink training dp at a checkpoint boundary, start new replicas on the
freed chips); sustained burn below ``burn_low`` shifts it back **to
training** (drain the leased replicas via migration, grow training dp).

The robustness machinery is the point, not the policy:

* **Hysteresis + cooldown** — a shift needs ``confirm_ticks``
  consecutive ticks beyond the band edge, and no shift starts within
  ``cooldown_s`` of the previous shift OR rollback.  Burn alternating
  inside ``(burn_low, burn_high)`` can never cause plan thrash;
  :meth:`CapacityController.audit` proves it after the fact (the
  day-in-the-life gate asserts it returns ``[]``).
* **Two-phase shift protocol** — reserve → drain (a serving replica via
  the fleet's migration drain, or training via the elastic trainer's
  boundary checkpoint) → re-shard → commit.  Every phase can fail or
  time out; any failure rolls the split back to the prior one — the
  trainer re-plans back (bitwise, via the boundary checkpoint) and
  removed replicas are re-attached, so a failed shift costs latency,
  never state.
* **Fault injection** — the ``capacity_change`` fault kind in BOTH
  injectors lands here: :data:`CAPACITY_FAULT_MODES` maps the fault's
  ``magnitude`` to a mid-shift crash (partial mutation, then the
  recovery rollback), a stuck drain (the drain phase never converges;
  the ``drain_timeout_ticks`` timeout fires), or a failed re-shard
  (:class:`ReshardFailed` raised at the re-shard boundary — the same
  observable point as a real factory-build failure).
* **Flight recording** — every shift start, phase, commit and rollback
  lands in the recorder's ``capacity`` source; commits trigger a
  ``capacity_shift`` snapshot, rollbacks a ``capacity_rollback`` one.

After every commit the controller calls
:meth:`~apex_tpu.observability.slo.SLOMonitor.reset_windows` on each
live replica's monitor: burn computed over a pre-shift window describes
a fleet that no longer exists, and acting on it is the stale-burn
flapping bug the window epoch exists to prevent.

Series: ``capacity_train_chips`` / ``capacity_serve_chips`` /
``capacity_serve_replicas`` / ``capacity_burn`` gauges,
``capacity_shifts_total{direction}`` / ``capacity_rollbacks_total``
counters, ``capacity_shift_seconds`` histogram.  Proven end-to-end by
``tools/day_in_life.py`` and ``__graft_entry__._dryrun_capacity``.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Callable, List, Optional, Tuple

CAPACITY_FAULT_MODES = ("mid_shift_crash", "stuck_drain",
                        "failed_reshard")


def fault_mode(magnitude: float) -> str:
    """Map a ``capacity_change`` fault's ``magnitude`` to its failure
    mode: 0/1 mid-shift crash, 2 stuck drain, 3 failed re-shard (out of
    range clamps to mid-shift crash, the most general failure)."""
    m = int(magnitude)
    if m == 2:
        return "stuck_drain"
    if m == 3:
        return "failed_reshard"
    return "mid_shift_crash"


class ReshardFailed(RuntimeError):
    """Injected re-shard failure (``capacity_change`` magnitude 3) —
    raised at the exact point a factory build or re-shard error would
    surface, so the rollback path it exercises is the real one."""


@dataclasses.dataclass(frozen=True)
class CapacityBudget:
    """The current chip split.  ``chips_per_replica`` is the exchange
    rate between the two sides: a shift frees/consumes training dp in
    whole-replica units."""
    total_chips: int
    train_chips: int
    serve_chips: int
    chips_per_replica: int = 1

    def __post_init__(self):
        if self.chips_per_replica < 1:
            raise ValueError("chips_per_replica must be >= 1")
        if self.train_chips + self.serve_chips != self.total_chips:
            raise ValueError(
                f"split {self.train_chips}+{self.serve_chips} != "
                f"total {self.total_chips}")


@dataclasses.dataclass
class _Shift:
    """In-flight shift state (one at a time — concurrent requests
    queue, never interleave)."""
    direction: str                        # "to_serving" | "to_training"
    mode: Optional[str]                   # injected failure mode
    entry: dict                           # the shift_log row
    t0: float
    started_tick: int
    phase: str = "reserve"
    old_dp: int = 0
    new_dp: int = 0
    victims: Tuple[int, ...] = ()
    drain_started_tick: int = 0
    drain_t0: float = 0.0
    drain_s: float = 0.0
    reshard_s: float = 0.0


class CapacityController:
    """Burn-driven chip budget controller over one trainer + one fleet.

    ``replica_factory() -> engine`` builds a serving replica for chips
    freed from training (the day-in-the-life sim builds engines sharing
    the serving model).  ``tick()`` is the single entry point: call it
    once per fleet tick, after ``fleet.step()`` — it either advances an
    in-flight shift one phase or evaluates the hysteresis machine.

    Shifts **to serving** shrink the trainer to
    ``max(min_train_dp, dp // 2)`` and start one replica per
    ``chips_per_replica`` freed chips; each commit pushes a lease so
    shifts **to training** return exactly the leased capacity (drain
    those replicas, grow back to the pre-shift dp).  The trainer's
    boundary checkpoint + re-plan is bitwise-preserving, which is what
    makes rollback restore the prior split exactly.
    """

    def __init__(self, trainer, fleet, replica_factory: Callable, *,
                 min_train_dp: int = 1, chips_per_replica: int = 1,
                 burn_high: float = 6.0, burn_low: float = 1.0,
                 burn_window_s: float = 30.0, confirm_ticks: int = 3,
                 cooldown_s: float = 60.0, drain_timeout_ticks: int = 50,
                 injector=None, serving_injector=None,
                 registry=None, tracer=None, recorder=None,
                 clock: Optional[Callable[[], float]] = None):
        if burn_low >= burn_high:
            raise ValueError("need burn_low < burn_high (the hysteresis "
                             "band is what prevents thrash)")
        if confirm_ticks < 1 or drain_timeout_ticks < 1:
            raise ValueError("confirm_ticks and drain_timeout_ticks "
                             "must be >= 1")
        self.trainer = trainer
        self.fleet = fleet
        self.replica_factory = replica_factory
        self.min_train_dp = int(min_train_dp)
        self.chips_per_replica = int(chips_per_replica)
        self.burn_high = float(burn_high)
        self.burn_low = float(burn_low)
        self.burn_window_s = float(burn_window_s)
        self.confirm_ticks = int(confirm_ticks)
        self.cooldown_s = float(cooldown_s)
        self.drain_timeout_ticks = int(drain_timeout_ticks)
        self.injector = injector                  # training FaultInjector
        self.serving_injector = serving_injector
        self.tracer = tracer
        self.recorder = recorder
        self.clock = clock if clock is not None else fleet.clock
        self._tick = 0
        self._hi = self._lo = 0
        self._cooldown_until = float("-inf")
        self._shift: Optional[_Shift] = None
        self._queue: collections.deque = collections.deque()
        # (grow-back dp, shrunk dp, replica slots) per committed
        # to_serving shift — to_training pops, returning the lease
        self._leases: List[Tuple[int, int, Tuple[int, ...]]] = []
        self.shift_log: List[dict] = []
        self.stats = {"shifts": 0, "rollbacks": 0, "queued": 0,
                      "last_shift": None}
        dp = trainer.plan.spec.dp
        serve = len(fleet._live()) * self.chips_per_replica
        self.budget = CapacityBudget(dp + serve, dp, serve,
                                     self.chips_per_replica)
        self._g_train = self._g_serve = self._g_reps = None
        self._g_burn = self._c_shifts = self._c_rollbacks = None
        self._h_shift = None
        if registry is not None:
            self._g_train = registry.gauge(
                "capacity_train_chips", "chips held by training")
            self._g_serve = registry.gauge(
                "capacity_serve_chips", "chips held by serving")
            self._g_reps = registry.gauge(
                "capacity_serve_replicas", "live serving replicas")
            self._g_burn = registry.gauge(
                "capacity_burn",
                "fleet max short-window SLO burn the controller sees")
            self._c_shifts = registry.counter(
                "capacity_shifts_total", "committed capacity shifts",
                labelnames=("direction",))
            self._c_rollbacks = registry.counter(
                "capacity_rollbacks_total",
                "capacity shifts rolled back (fault, timeout, failure)")
            self._h_shift = registry.histogram(
                "capacity_shift_seconds",
                "end-to-end shift latency (drain+reshard+commit)")
        self._publish_split()

    # -- observability -------------------------------------------------------

    @property
    def shifting(self) -> bool:
        """True while a shift is in flight."""
        return self._shift is not None

    @property
    def outstanding_leases(self) -> int:
        """to_serving commits not yet returned by a to_training one."""
        return len(self._leases)

    @property
    def split(self) -> Tuple[int, int]:
        """(train_chips, serve_chips) — the quantity a rollback must
        restore bitwise alongside the trainer state."""
        return (self.budget.train_chips, self.budget.serve_chips)

    def _publish_split(self) -> None:
        dp = self.trainer.plan.spec.dp
        reps = len(self.fleet._live())
        self.budget = CapacityBudget(
            self.budget.total_chips, dp,
            self.budget.total_chips - dp, self.chips_per_replica)
        if self._g_train is not None:
            self._g_train.set(dp)
            self._g_serve.set(self.budget.serve_chips)
            self._g_reps.set(reps)

    def _record(self, what: str, **kw) -> None:
        if self.recorder is not None:
            self.recorder.record("capacity", what, tick=self._tick, **kw)
        if self.tracer is not None:
            self.tracer.instant(f"capacity/{what}", tick=self._tick, **kw)

    def audit(self) -> List[dict]:
        """Out-of-band flap check over the full shift history: every
        burn-driven shift must have started with burn OUTSIDE the
        hysteresis band and after the cooldown expired.  The
        day-in-the-life gate asserts this returns ``[]``."""
        out = []
        for e in self.shift_log:
            if not e["manual"] \
                    and self.burn_low < e["burn"] < self.burn_high:
                out.append({"tick": e["tick"], "reason":
                            "shift started with burn inside the "
                            "hysteresis band", "burn": e["burn"]})
            if not e["cooldown_ok"]:
                out.append({"tick": e["tick"], "reason":
                            "shift started before cooldown expiry"})
        return out

    # -- signals -------------------------------------------------------------

    def _serving_burn(self) -> float:
        burns = []
        for _, e in self.fleet._live():
            slo = getattr(e.metrics, "slo", None)
            if slo is None or not slo.targets:
                continue
            burns.append(max(slo.burn_rate(t, self.burn_window_s)
                             for t in slo.targets))
        return max(burns, default=0.0)

    def _reset_slo_windows(self, tag: str) -> None:
        for _, e in self.fleet._live():
            slo = getattr(e.metrics, "slo", None)
            if slo is not None:
                slo.reset_windows(epoch=tag)

    def _consume_fault(self) -> Optional[str]:
        """One injected ``capacity_change`` for THIS shift, serving
        schedule first (tick-keyed) then training (step-keyed); both
        are consume-once, so a fault fails one shift and the
        post-rollback retry can succeed."""
        if self.serving_injector is not None:
            f = self.serving_injector.capacity_change_at(self._tick)
            if f is not None:
                return fault_mode(f.magnitude)
        if self.injector is not None:
            f = self.injector.check_capacity_change(
                self.trainer.current_step)
            if f is not None:
                return fault_mode(f.magnitude)
        return None

    # -- public control ------------------------------------------------------

    def request_shift(self, direction: str) -> str:
        """Queue an operator-requested shift.  Requests made while a
        shift is in flight are QUEUED, never interleaved; they run as
        soon as the current shift finishes and the cooldown expires.
        Returns ``"queued"``."""
        if direction not in ("to_serving", "to_training"):
            raise ValueError(
                "direction must be 'to_serving' or 'to_training'")
        self._queue.append(direction)
        self.stats["queued"] += 1
        self._record("shift_queued", direction=direction)
        return "queued"

    def tick(self) -> None:
        """Advance the controller one fleet tick: progress the
        in-flight shift, or evaluate the hysteresis machine."""
        self._tick += 1
        burn = self._serving_burn()
        if self._g_burn is not None:
            self._g_burn.set(burn)
        if self._shift is not None:
            self._advance_shift()
            return
        now = self.clock()
        if self._queue:
            if now >= self._cooldown_until:
                direction = self._queue.popleft()
                if self._feasible(direction):
                    self._start_shift(direction, burn, manual=True)
                else:
                    self._record("shift_infeasible",
                                 direction=direction)
            return
        if burn >= self.burn_high:
            self._hi += 1
        else:
            self._hi = 0
        if burn <= self.burn_low:
            self._lo += 1
        else:
            self._lo = 0
        if now < self._cooldown_until:
            return
        if self._hi >= self.confirm_ticks \
                and self._feasible("to_serving"):
            self._start_shift("to_serving", burn, manual=False)
        elif self._lo >= self.confirm_ticks \
                and self._feasible("to_training"):
            self._start_shift("to_training", burn, manual=False)

    def _feasible(self, direction: str) -> bool:
        if direction == "to_serving":
            dp = self.trainer.plan.spec.dp
            new_dp = max(self.min_train_dp, dp // 2)
            return (dp - new_dp) >= self.chips_per_replica
        return bool(self._leases)

    # -- the shift state machine ---------------------------------------------

    def _dp_spec(self, new_dp: int):
        cur = self.trainer.plan.spec
        zero = new_dp if cur.zero_shard > 1 else 1
        return dataclasses.replace(cur, dp=new_dp, zero_shard=zero)

    def _start_shift(self, direction: str, burn: float,
                     manual: bool) -> None:
        now = self.clock()
        mode = self._consume_fault()
        entry = {"tick": self._tick, "t": now, "direction": direction,
                 "burn": burn, "manual": manual,
                 "cooldown_ok": now >= self._cooldown_until,
                 "fault": mode, "outcome": None, "reason": None}
        self.shift_log.append(entry)
        self._hi = self._lo = 0
        self._record("shift_start", direction=direction, burn=burn,
                     manual=manual, fault=mode)
        self._shift = _Shift(direction=direction, mode=mode,
                             entry=entry, t0=now,
                             started_tick=self._tick)
        self._advance_shift()

    def _advance_shift(self) -> None:
        sh = self._shift
        if sh.direction == "to_serving":
            self._advance_to_serving(sh)
        else:
            self._advance_to_training(sh)

    def _advance_to_serving(self, sh: _Shift) -> None:
        if sh.phase == "reserve":
            sh.old_dp = self.trainer.plan.spec.dp
            sh.new_dp = max(self.min_train_dp, sh.old_dp // 2)
            self._record("phase", phase="reserve", old_dp=sh.old_dp,
                         new_dp=sh.new_dp)
            if sh.mode == "stuck_drain":
                # the boundary-checkpoint drain never completes:
                # nothing has mutated yet, so the timeout path below
                # rolls back for free
                sh.phase = "drain_training"
                sh.drain_started_tick = self._tick
                return
            try:
                if sh.mode == "failed_reshard":
                    raise ReshardFailed(
                        "injected re-shard failure (capacity_change)")
                # drain = the boundary checkpoint inside the re-plan
                self.trainer.replan_to(self._dp_spec(sh.new_dp))
            except Exception as e:
                self._rollback(f"reshard: {e}")
                return
            sh.drain_s = self.trainer.stats["last_checkpoint_s"]
            sh.reshard_s = self.trainer.stats["last_reshard_s"]
            if sh.mode == "mid_shift_crash":
                # injected crash between the trainer shrink and the
                # replica add — the recovery re-plans back onto the
                # prior split (bitwise, via the boundary checkpoint)
                self.trainer.replan_to(self._dp_spec(sh.old_dp))
                self._rollback("mid-shift crash (injected)")
                return
            n_new = (sh.old_dp - sh.new_dp) // self.chips_per_replica
            engines = [self.replica_factory() for _ in range(n_new)]
            slots = tuple(self.fleet.add_replica(e) for e in engines)
            self._record("phase", phase="grow_fleet", slots=list(slots))
            self._leases.append((sh.old_dp, sh.new_dp, slots))
            self._commit()
        elif sh.phase == "drain_training":
            if self._tick - sh.drain_started_tick \
                    >= self.drain_timeout_ticks:
                self._rollback("stuck drain (injected): "
                               "boundary checkpoint timed out")

    def _advance_to_training(self, sh: _Shift) -> None:
        if sh.phase == "reserve":
            grow_dp, cur_dp, slots = self._leases[-1]
            sh.old_dp, sh.new_dp = cur_dp, grow_dp
            sh.victims = tuple(v for v in slots
                               if self.fleet.replicas[v] is not None)
            self._record("phase", phase="reserve",
                         victims=list(sh.victims), grow_dp=grow_dp)
            for v in sh.victims:
                try:
                    self.fleet.begin_drain(v)
                except ValueError:
                    pass          # already dead: its work migrated
            if sh.mode == "mid_shift_crash":
                # injected crash after the drain began — recovery
                # cancels it; migrated work stays where it landed
                for v in sh.victims:
                    self.fleet.cancel_drain(v)
                self._rollback("mid-shift crash (injected)")
                return
            sh.phase = "drain_serving"
            sh.drain_started_tick = self._tick
            sh.drain_t0 = self.clock()
            return
        if sh.phase != "drain_serving":
            return
        done = sh.mode != "stuck_drain" and all(
            self.fleet.drained(v) for v in sh.victims)
        if done:
            sh.drain_s = self.clock() - sh.drain_t0
            self._record("phase", phase="reshard",
                         drain_s=sh.drain_s)
            engines = [self.fleet.remove_replica(v)
                       for v in sh.victims
                       if self.fleet.replicas[v] is not None]
            try:
                if sh.mode == "failed_reshard":
                    raise ReshardFailed(
                        "injected re-shard failure (capacity_change)")
                self.trainer.replan_to(self._dp_spec(sh.new_dp))
            except Exception as e:
                for eng in engines:
                    self.fleet.add_replica(eng)
                self._rollback(f"reshard: {e}")
                return
            sh.reshard_s = self.trainer.stats["last_reshard_s"]
            self._leases.pop()
            self._commit()
        elif self._tick - sh.drain_started_tick \
                >= self.drain_timeout_ticks:
            for v in sh.victims:
                self.fleet.cancel_drain(v)
            self._rollback("drain timeout")

    # -- commit / rollback ---------------------------------------------------

    def _commit(self) -> None:
        sh = self._shift
        now = self.clock()
        total = now - sh.t0
        commit_s = max(total - sh.drain_s - sh.reshard_s, 0.0)
        sh.entry["outcome"] = "commit"
        self.stats["shifts"] += 1
        self.stats["last_shift"] = {
            "direction": sh.direction, "drain_s": sh.drain_s,
            "reshard_s": sh.reshard_s, "commit_s": commit_s,
            "total_s": total}
        if self._c_shifts is not None:
            self._c_shifts.inc(direction=sh.direction)
            self._h_shift.observe(total)
        self._publish_split()
        # pre-shift burn describes a fleet that no longer exists:
        # without this reset the stale window immediately re-triggers
        self._reset_slo_windows(f"shift-{self.stats['shifts']}")
        self._cooldown_until = now + self.cooldown_s
        self._record("shift_commit", split=list(self.split),
                     **self.stats["last_shift"])
        if self.recorder is not None:
            self.recorder.trigger("capacity_shift",
                                  direction=sh.direction,
                                  tick=self._tick,
                                  split=list(self.split))
        self._shift = None

    def _rollback(self, reason: str) -> None:
        sh = self._shift
        now = self.clock()
        sh.entry["outcome"] = "rollback"
        sh.entry["reason"] = reason
        self.stats["rollbacks"] += 1
        if self._c_rollbacks is not None:
            self._c_rollbacks.inc()
        self._publish_split()
        self._cooldown_until = now + self.cooldown_s
        self._record("shift_rollback", direction=sh.direction,
                     reason=reason, split=list(self.split))
        if self.recorder is not None:
            self.recorder.trigger("capacity_rollback",
                                  direction=sh.direction,
                                  reason=reason, tick=self._tick)
        self._shift = None


# ---------------------------------------------------------------------------
# per-pool capacity: prefill vs decode sizing for a disaggregated fleet
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _PoolShift:
    """In-flight pool-to-pool replica move (one at a time)."""
    direction: str                        # e.g. "to_decode"
    src: str
    dst: str
    mode: Optional[str]                   # injected failure mode
    entry: dict
    t0: float
    started_tick: int
    victim: int = -1
    phase: str = "reserve"
    drain_started_tick: int = 0


class PoolCapacityController:
    """:class:`CapacityController`'s hysteresis + two-phase protocol
    generalized to N serving pools — built for the disaggregated
    prefill/decode fleet, where the two pools burn DIFFERENT SLOs
    (prefill burns TTFT, decode burns TPOT) and must be sized
    independently: a prompt-heavy hour needs prefill replicas that a
    decode-heavy hour should hand back.

    ``pools`` maps pool name → :class:`~apex_tpu.serving.FleetRouter`;
    ``burn_metrics`` maps pool name → the SLO metric names whose burn
    drives THAT pool (default ``ttft``/``queue_wait`` for a pool named
    ``"prefill"``, ``token_latency`` for everything else — the TPOT
    side).  ``replica_factory(pool_name) -> engine`` builds a fresh
    replica for the receiving pool: a replica cannot simply change
    sides, because a prefill-pool engine is ``prefill_only=True`` and a
    decode-pool engine is not — the chip moves, the engine is rebuilt.

    A shift toward pool P starts when P's burn held ≥ ``burn_high``
    for ``confirm_ticks`` while the donor's burn held ≤ ``burn_low``
    for as long (a donor under its own pressure never donates), and
    never within ``cooldown_s`` of the previous shift or rollback —
    the same can-never-flap contract :meth:`audit` proves for the
    train/serve controller.  The move itself is the two-phase
    protocol over the fleet lifecycle: reserve (pick the least-loaded
    healthy donor replica) → drain (``begin_drain``; migration moves
    its work to donor peers; timeout → ``cancel_drain`` + rollback) →
    commit (``remove_replica`` from the donor, ``add_replica`` the
    rebuilt engine to the receiver, reset every SLO window).  The
    ``capacity_change`` fault kind fails a shift at the same three
    points the train/serve controller models.

    Series: ``capacity_pool_replicas{pool}`` / ``capacity_pool_burn
    {pool}`` gauges, ``capacity_pool_shifts_total{direction}`` /
    ``capacity_pool_rollbacks_total`` counters.
    """

    DEFAULT_PREFILL_METRICS = ("ttft", "queue_wait")
    DEFAULT_DECODE_METRICS = ("token_latency",)

    def __init__(self, pools: dict, replica_factory: Callable, *,
                 burn_metrics: Optional[dict] = None,
                 min_replicas: int = 1,
                 burn_high: float = 6.0, burn_low: float = 1.0,
                 burn_window_s: float = 30.0, confirm_ticks: int = 3,
                 cooldown_s: float = 60.0, drain_timeout_ticks: int = 50,
                 serving_injector=None, registry=None, tracer=None,
                 recorder=None,
                 clock: Optional[Callable[[], float]] = None):
        if len(pools) < 2:
            raise ValueError("need at least two pools to shift between")
        if burn_low >= burn_high:
            raise ValueError("need burn_low < burn_high (the hysteresis "
                             "band is what prevents thrash)")
        if confirm_ticks < 1 or drain_timeout_ticks < 1 \
                or min_replicas < 1:
            raise ValueError("confirm_ticks, drain_timeout_ticks and "
                             "min_replicas must be >= 1")
        self.pools = dict(pools)
        self.replica_factory = replica_factory
        self.burn_metrics = {
            name: tuple(burn_metrics[name]) if burn_metrics is not None
            and name in burn_metrics
            else (self.DEFAULT_PREFILL_METRICS if name == "prefill"
                  else self.DEFAULT_DECODE_METRICS)
            for name in self.pools}
        self.min_replicas = int(min_replicas)
        self.burn_high = float(burn_high)
        self.burn_low = float(burn_low)
        self.burn_window_s = float(burn_window_s)
        self.confirm_ticks = int(confirm_ticks)
        self.cooldown_s = float(cooldown_s)
        self.drain_timeout_ticks = int(drain_timeout_ticks)
        self.serving_injector = serving_injector
        self.tracer = tracer
        self.recorder = recorder
        self.clock = clock if clock is not None \
            else next(iter(self.pools.values())).clock
        self._tick = 0
        self._hi = {name: 0 for name in self.pools}
        self._lo = {name: 0 for name in self.pools}
        self._cooldown_until = float("-inf")
        self._shift: Optional[_PoolShift] = None
        self._queue: collections.deque = collections.deque()
        self.shift_log: List[dict] = []
        self.stats = {"shifts": 0, "rollbacks": 0, "queued": 0,
                      "last_shift": None}
        self._g_reps = self._g_burn = None
        self._c_shifts = self._c_rollbacks = None
        if registry is not None:
            self._g_reps = registry.gauge(
                "capacity_pool_replicas", "live replicas, by pool",
                labelnames=("pool",))
            self._g_burn = registry.gauge(
                "capacity_pool_burn",
                "per-pool max short-window SLO burn the controller sees",
                labelnames=("pool",))
            self._c_shifts = registry.counter(
                "capacity_pool_shifts_total",
                "committed pool-to-pool replica moves",
                labelnames=("direction",))
            self._c_rollbacks = registry.counter(
                "capacity_pool_rollbacks_total",
                "pool shifts rolled back (fault, timeout, failure)")
        self._publish()

    # -- observability -------------------------------------------------------

    @property
    def shifting(self) -> bool:
        return self._shift is not None

    @property
    def split(self) -> dict:
        """Live replica count per pool."""
        return {name: len(r._live()) for name, r in self.pools.items()}

    def _publish(self) -> None:
        if self._g_reps is not None:
            for name, n in self.split.items():
                self._g_reps.set(n, pool=name)

    def _record(self, what: str, **kw) -> None:
        if self.recorder is not None:
            self.recorder.record("capacity", what, tick=self._tick, **kw)
        if self.tracer is not None:
            self.tracer.instant(f"capacity/{what}", tick=self._tick, **kw)

    def audit(self) -> List[dict]:
        """Out-of-band flap check, same contract as
        :meth:`CapacityController.audit`: every burn-driven shift must
        have started with the receiving pool's burn OUTSIDE the
        hysteresis band and after the cooldown expired — the disagg
        scenarios assert this returns ``[]``."""
        out = []
        for e in self.shift_log:
            if not e["manual"] \
                    and self.burn_low < e["burn"] < self.burn_high:
                out.append({"tick": e["tick"], "reason":
                            "shift started with burn inside the "
                            "hysteresis band", "burn": e["burn"]})
            if not e["cooldown_ok"]:
                out.append({"tick": e["tick"], "reason":
                            "shift started before cooldown expiry"})
        return out

    # -- signals -------------------------------------------------------------

    def pool_burn(self, name: str) -> float:
        """Max short-window burn across pool ``name``'s replicas, over
        the pool's OWN SLO metrics only (TTFT-class for prefill,
        TPOT-class for decode) — cross-pool metrics must not trigger a
        shift toward a pool whose own objective is healthy.  Falls back
        to all targets when none match (a monitor wired with custom
        metric names still drives the controller)."""
        metrics = self.burn_metrics[name]
        burns = []
        for _, e in self.pools[name]._live():
            slo = getattr(e.metrics, "slo", None)
            if slo is None or not slo.targets:
                continue
            mine = [t for t in slo.targets if t.metric in metrics]
            burns.append(max(slo.burn_rate(t, self.burn_window_s)
                             for t in (mine or slo.targets)))
        return max(burns, default=0.0)

    def _reset_slo_windows(self, tag: str) -> None:
        for router in self.pools.values():
            for _, e in router._live():
                slo = getattr(e.metrics, "slo", None)
                if slo is not None:
                    slo.reset_windows(epoch=tag)

    def _consume_fault(self) -> Optional[str]:
        if self.serving_injector is not None:
            f = self.serving_injector.capacity_change_at(self._tick)
            if f is not None:
                return fault_mode(f.magnitude)
        return None

    # -- public control ------------------------------------------------------

    def _parse_direction(self, direction: str) -> Tuple[str, str]:
        """``"to_<pool>"`` → (donor, receiver); the donor is the OTHER
        pool (two-pool fleets), or the calmest one with spare replicas
        (N pools)."""
        if not direction.startswith("to_") \
                or direction[3:] not in self.pools:
            raise ValueError(
                f"direction must be 'to_<pool>' for one of "
                f"{sorted(self.pools)}, got {direction!r}")
        dst = direction[3:]
        donors = [n for n in self.pools if n != dst
                  and self._spare(n)]
        if not donors:
            return "", dst
        src = min(donors, key=self.pool_burn)
        return src, dst

    def request_shift(self, direction: str) -> str:
        """Queue an operator-requested move (``"to_prefill"`` /
        ``"to_decode"``); runs when the in-flight shift finishes and
        the cooldown expires.  Returns ``"queued"``."""
        self._parse_direction(direction)      # validate early
        self._queue.append(direction)
        self.stats["queued"] += 1
        self._record("shift_queued", direction=direction)
        return "queued"

    def _spare(self, name: str) -> bool:
        router = self.pools[name]
        healthy = [i for i, _ in router._live()
                   if router._state[i].health.value == "healthy"]
        return len(healthy) > self.min_replicas

    def tick(self) -> None:
        """One controller round, after the fleet's tick: advance the
        in-flight shift a phase, or evaluate the hysteresis machine."""
        self._tick += 1
        burns = {name: self.pool_burn(name) for name in self.pools}
        if self._g_burn is not None:
            for name, b in burns.items():
                self._g_burn.set(b, pool=name)
        if self._shift is not None:
            self._advance(self._shift)
            return
        now = self.clock()
        if self._queue:
            if now >= self._cooldown_until:
                direction = self._queue.popleft()
                src, dst = self._parse_direction(direction)
                if src:
                    self._start(src, dst, burns[dst], manual=True)
                else:
                    self._record("shift_infeasible", direction=direction)
            return
        for name, b in burns.items():
            self._hi[name] = self._hi[name] + 1 if b >= self.burn_high \
                else 0
            self._lo[name] = self._lo[name] + 1 if b <= self.burn_low \
                else 0
        if now < self._cooldown_until:
            return
        for dst in self.pools:
            if self._hi[dst] < self.confirm_ticks:
                continue
            donors = [n for n in self.pools if n != dst
                      and self._lo[n] >= self.confirm_ticks
                      and self._spare(n)]
            if not donors:
                continue          # every peer busy or at the floor
            src = min(donors, key=lambda n: burns[n])
            self._start(src, dst, burns[dst], manual=False)
            return

    # -- the shift state machine ---------------------------------------------

    def _start(self, src: str, dst: str, burn: float,
               manual: bool) -> None:
        now = self.clock()
        mode = self._consume_fault()
        entry = {"tick": self._tick, "t": now,
                 "direction": f"to_{dst}", "src": src, "burn": burn,
                 "manual": manual,
                 "cooldown_ok": now >= self._cooldown_until,
                 "fault": mode, "outcome": None, "reason": None}
        self.shift_log.append(entry)
        self._hi = {name: 0 for name in self.pools}
        self._lo = {name: 0 for name in self.pools}
        self._record("shift_start", direction=f"to_{dst}", src=src,
                     burn=burn, manual=manual, fault=mode)
        self._shift = _PoolShift(direction=f"to_{dst}", src=src,
                                 dst=dst, mode=mode, entry=entry,
                                 t0=now, started_tick=self._tick)
        self._advance(self._shift)

    def _advance(self, sh: _PoolShift) -> None:
        router = self.pools[sh.src]
        if sh.phase == "reserve":
            victim = None
            best = None
            for i, e in router._live():
                if router._state[i].health.value != "healthy":
                    continue
                load = e.queue_depth + e.active_requests
                if best is None or load < best:
                    victim, best = i, load
            if victim is None:
                self._rollback("no healthy donor replica")
                return
            sh.victim = victim
            self._record("phase", phase="reserve", src=sh.src,
                         victim=victim)
            router.begin_drain(victim)
            if sh.mode == "mid_shift_crash":
                router.cancel_drain(victim)
                self._rollback("mid-shift crash (injected)")
                return
            sh.phase = "drain"
            sh.drain_started_tick = self._tick
            return
        if sh.phase != "drain":
            return
        done = sh.mode != "stuck_drain" and router.drained(sh.victim)
        if done:
            self._record("phase", phase="commit", victim=sh.victim)
            removed = router.remove_replica(sh.victim)
            try:
                if sh.mode == "failed_reshard":
                    raise ReshardFailed(
                        "injected re-shard failure (capacity_change)")
                engine = self.replica_factory(sh.dst)
                slot = self.pools[sh.dst].add_replica(engine)
            except Exception as e:
                # the chip never reached the receiver: re-attach the
                # drained engine to the donor, prior split restored
                router.add_replica(removed)
                self._rollback(f"reshard: {e}")
                return
            self._commit(sh, slot)
        elif self._tick - sh.drain_started_tick \
                >= self.drain_timeout_ticks:
            router.cancel_drain(sh.victim)
            self._rollback("drain timeout")

    def _commit(self, sh: _PoolShift, slot: int) -> None:
        now = self.clock()
        sh.entry["outcome"] = "commit"
        self.stats["shifts"] += 1
        self.stats["last_shift"] = {"direction": sh.direction,
                                    "src": sh.src, "victim": sh.victim,
                                    "dst_slot": slot,
                                    "total_s": now - sh.t0}
        if self._c_shifts is not None:
            self._c_shifts.inc(direction=sh.direction)
        self._publish()
        self._reset_slo_windows(f"pool-shift-{self.stats['shifts']}")
        self._cooldown_until = now + self.cooldown_s
        self._record("shift_commit", split=self.split,
                     **self.stats["last_shift"])
        if self.recorder is not None:
            self.recorder.trigger("capacity_shift",
                                  direction=sh.direction,
                                  tick=self._tick, split=self.split)
        self._shift = None

    def _rollback(self, reason: str) -> None:
        sh = self._shift
        now = self.clock()
        sh.entry["outcome"] = "rollback"
        sh.entry["reason"] = reason
        self.stats["rollbacks"] += 1
        if self._c_rollbacks is not None:
            self._c_rollbacks.inc()
        self._publish()
        self._cooldown_until = now + self.cooldown_s
        self._record("shift_rollback", direction=sh.direction,
                     reason=reason, split=self.split)
        if self.recorder is not None:
            self.recorder.trigger("capacity_rollback",
                                  direction=sh.direction,
                                  reason=reason, tick=self._tick)
        self._shift = None
