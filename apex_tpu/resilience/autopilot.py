"""Self-driving parallelism: drift detection -> re-rank -> gated adoption.

ROADMAP item 3 closes the measure -> plan -> adopt loop that today a
human carries between the tools: ``comms_probe`` fits a
:class:`~apex_tpu.observability.costmodel.CostModel` offline,
``tools/autotune.py`` ranks plans against it, and
:meth:`~apex_tpu.resilience.elastic.ElasticTrainer.replan_to` adopts
the winner — each a manual handoff.  The
:class:`ParallelismAutopilot` runs that pipeline ONLINE, as a control
loop with the same discipline as
:class:`~apex_tpu.resilience.capacity.CapacityController`:

1. **Observe.** Production telemetry flows in continuously —
   :meth:`ParallelismAutopilot.record_step` takes measured training
   step times (what a ``TrainingMonitor`` sees), and
   :meth:`ParallelismAutopilot.observe` takes collective
   :class:`~apex_tpu.observability.costmodel.Measurement` points (what
   ``LocalDcnChannel`` transfers and per-request traces carry).
   Nothing stalls: points are buffered by ``CostModel.update``.
   :meth:`ParallelismAutopilot.observe_anatomy` is the third feed —
   measured-vs-predicted timeline diffs from
   :mod:`apex_tpu.observability.anatomy`, the STRUCTURAL drift
   channel (mis-ordered ops, unpredicted bubbles) that curve refits
   cannot see.
2. **Detect.** Each tick refits the buffer (GSPMD's premise taken to
   run-time: the machine profile is data, not configuration).  A refit
   whose curves moved past ``drift_threshold`` relative to the loaded
   profile counts toward a confirmation streak; a refit within the
   threshold RESETS it — the same hysteresis discipline as
   ``CapacityController``, so a one-window spike never moves a plan,
   and too-few fresh measurements never even refit.
3. **Re-rank.** On a confirmed streak the refreshed profile is
   adopted, and the plan space is re-ranked against it (a pluggable
   ``ranker``; the built-in one prices dp candidates by a
   telemetry-calibrated compute roofline + the alpha-beta cost of the
   gradient all-reduce — ``tools/autotune.py rank_plans`` is the
   full-space equivalent for offline shadow ranking).
4. **Adopt, gated.** A winning plan that differs from the current one
   goes through measure -> drain -> commit: re-measure ``gate_steps``
   fresh step times under the OLD plan (the pre-adoption baseline — an
   A/B where both arms see the drifted machine),
   ``trainer.replan_to(new)`` (the boundary checkpoint under the old
   plan IS the drain), then measure ``gate_steps`` under the NEW plan.
   The commit gate is ``bench_diff``'s rule: commit only when the new
   measured mean is within ``gate_tolerance`` of the baseline; on
   measured regression ROLL BACK — ``replan_to(old)`` restores the
   stamped manifest and resumes bitwise.  Commits and rollbacks both
   start a cooldown; drifts confirmed while busy or cooling down
   QUEUE, never interleave.

Chaos hooks: the ``cost_drift`` fault kind scales the (simulated)
machine's link coefficients — the injector keeps drifted telemetry
flowing so the DETECTOR must converge on it, the fault never tells the
autopilot the answer; ``plan_regression`` inflates the commit-gate
measurements so the rollback path is forced deterministically.
:meth:`ParallelismAutopilot.audit` replays the adoption log and flags
any adoption that started without a confirmed over-threshold drift or
before cooldown expiry — the flap-free gate
``tools/day_in_life.py``/CI assert ``== []``.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Callable, Deque, Dict, List, Optional

from apex_tpu.observability.costmodel import (
    CostModel, simulate_link_measurements)

ADOPTION_OUTCOMES = ("commit", "rollback", "no_change")


@dataclasses.dataclass
class _Adoption:
    """One in-flight plan adoption (at most one exists at a time)."""
    entry: dict                      # the adoption_log row, updated in place
    t0: float
    regression_scale: float = 1.0    # injected plan_regression inflation
    old_spec: object = None
    new_spec: object = None
    predicted_s: float = 0.0
    phase: str = "baseline"          # baseline -> gate
    rank_s: float = 0.0
    drain_s: float = 0.0
    reshard_s: float = 0.0
    baseline_s: float = 0.0
    baseline_times: List[float] = dataclasses.field(default_factory=list)
    gate_times: List[float] = dataclasses.field(default_factory=list)


class ParallelismAutopilot:
    """Online cost-drift detection with gated, reversible plan adoption.

    Drive it like the capacity controller: feed telemetry
    (:meth:`observe`, :meth:`record_step`) as it arrives and call
    :meth:`tick` from the control loop.  The autopilot never blocks
    the training loop — refits and ranking are cheap host-side fits,
    and the only training-visible actions are the two ``replan_to``
    calls of an adoption (drain + re-shard, exactly what a manual
    re-plan costs).

    ``ranker(cost_model)`` may be supplied to rank the full plan space
    (e.g. a closure over ``tools.autotune.rank_plans``); it must return
    ``[{"spec": TopologySpec, "predicted_s": float}, ...]`` best-first.
    The built-in fallback re-ranks the dp degrees available on the
    trainer's device pool: compute is a roofline calibrated from the
    measured baseline (``(baseline - comm(dp_cur)) * dp_cur``), comm is
    the profile's alpha-beta price of the gradient all-reduce — enough
    for a drifted interconnect to flip the winner, which is the loop
    under test.
    """

    def __init__(self, trainer, profile: CostModel, *,
                 ranker: Optional[Callable] = None,
                 drift_threshold: float = 0.3,
                 structural_threshold: Optional[float] = None,
                 confirm_windows: int = 2,
                 min_measurements: int = 8,
                 cooldown_s: float = 60.0,
                 gate_steps: int = 3,
                 gate_tolerance: float = 1.2,
                 refit_every: int = 1,
                 min_dp: int = 1,
                 link_class: str = "ici",
                 grad_bytes: Optional[int] = None,
                 max_profile_age_s: Optional[float] = None,
                 step_window: int = 8,
                 injector=None, registry=None, tracer=None,
                 recorder=None,
                 clock: Optional[Callable[[], float]] = None):
        if drift_threshold <= 0.0:
            raise ValueError("drift_threshold must be > 0")
        if confirm_windows < 1:
            raise ValueError("confirm_windows must be >= 1")
        if gate_steps < 1:
            raise ValueError("gate_steps must be >= 1")
        if gate_tolerance < 1.0:
            raise ValueError("gate_tolerance must be >= 1.0 (a gate "
                             "tighter than measured-parity would veto "
                             "every adoption on noise)")
        if refit_every < 1:
            raise ValueError("refit_every must be >= 1")
        self.trainer = trainer
        self.profile = profile
        self.ranker = ranker
        self.drift_threshold = float(drift_threshold)
        self.structural_threshold = float(
            drift_threshold if structural_threshold is None
            else structural_threshold)
        if self.structural_threshold <= 0.0:
            raise ValueError("structural_threshold must be > 0")
        self.confirm_windows = int(confirm_windows)
        self.min_measurements = int(min_measurements)
        self.cooldown_s = float(cooldown_s)
        self.gate_steps = int(gate_steps)
        self.gate_tolerance = float(gate_tolerance)
        self.refit_every = int(refit_every)
        self.min_dp = int(min_dp)
        self.link_class = str(link_class)
        self.max_profile_age_s = max_profile_age_s
        self.injector = injector
        self.registry = registry
        self.tracer = tracer
        self.recorder = recorder
        self.clock = (clock if clock is not None
                      else getattr(trainer, "clock", None)
                      or time.perf_counter)

        self._tick = 0
        self._streak = 0
        self._anat_streak = 0
        self._cooldown_until = float("-inf")
        self._queue: Deque[dict] = collections.deque()
        self._adoption: Optional[_Adoption] = None
        self._candidate: Optional[CostModel] = None
        self._grad_bytes = grad_bytes
        self._recent_dt: Deque[float] = collections.deque(
            maxlen=int(step_window))
        # injected drifted environment: (op, dtype, link_class) ->
        # [alpha, beta]; non-empty only after a cost_drift fault, and
        # from then on it keeps synthetic telemetry flowing each tick
        # (the machine STAYS drifted — the detector must converge)
        self._drift_env: Dict[tuple, List[float]] = {}
        self.adoption_log: List[dict] = []
        self.stats = {"refits": 0, "drift_confirmed": 0, "adoptions": 0,
                      "rollbacks": 0, "no_change": 0, "queued": 0,
                      "drift_faults": 0, "last_drift": None,
                      "last_refit_s": 0.0, "last_adoption": None,
                      "structural_confirmed": 0,
                      "last_structural": None}

        self._g_drift = self._c_adopt = self._h_refit = None
        if registry is not None:
            self._g_drift = registry.gauge(
                "autopilot_drift_detected",
                "1 while a confirmed cost-model drift awaits or "
                "undergoes plan adoption")
            self._c_adopt = registry.counter(
                "autopilot_adoptions_total",
                "plan adoptions by outcome (commit|rollback|no_change)",
                labelnames=("outcome",))
            self._h_refit = registry.histogram(
                "autopilot_refit_seconds",
                "wall seconds per incremental cost-model refit")

    # -- telemetry in --------------------------------------------------------

    def observe(self, measurements) -> int:
        """Feed fresh collective measurements (channel timings, traces,
        probes) into the profile's refit buffer; returns the buffered
        count.  Non-blocking — nothing is fitted until a tick's refit
        window."""
        return self.profile.update(measurements)

    def observe_anatomy(self, report) -> bool:
        """Feed one step's measured-vs-predicted timeline diff (the
        dict :func:`apex_tpu.observability.anatomy.diff_timelines`
        returns, or its bare ``drift_score``).

        This is the STRUCTURAL drift channel: the cost-model path
        sees curve drift (links got slower), this one sees the
        schedule itself diverging from the model — mis-ordered ops,
        bubbles the simulator didn't predict, one stage's ops
        suddenly off-median.  Scores at or past
        ``structural_threshold`` build their own confirmation streak
        (same ``confirm_windows`` debounce as refit drift, so one
        noisy step never moves a plan); a confirmed streak queues an
        adoption pass carrying the score and the report's worst
        offenders.  Returns True when this call confirmed."""
        if isinstance(report, dict):
            score = float(report.get("drift_score", 0.0))
            detail = {"worst_op": report.get("worst_op"),
                      "median_ratio": report.get("median_ratio"),
                      "unpredicted_bubble_fraction":
                          report.get("unpredicted_bubble_fraction"),
                      "misordered": len(report.get("misordered", []))}
        else:
            score = float(report)
            detail = {}
        self.stats["last_structural"] = score
        if score >= self.structural_threshold:
            self._anat_streak += 1
        else:
            self._anat_streak = 0
        self._record("anatomy", score=round(score, 6),
                     streak=self._anat_streak, **detail)
        if self._anat_streak < self.confirm_windows:
            return False
        self._anat_streak = 0
        self.stats["structural_confirmed"] += 1
        if self._g_drift is not None:
            self._g_drift.set(1)
        # coalesce with a pending structural request (same discipline
        # as _confirm_drift: an ongoing divergence re-confirms every
        # confirm_windows steps — refresh, don't pile up)
        for req in self._queue:
            if not req["manual"] and req.get("source") == "anatomy":
                req["drift"] = score
                req["detail"] = detail
                self._record("structural_confirmed", drift=score,
                             coalesced=True)
                return True
        self._queue.append({"model": None, "drift": score,
                            "manual": False, "source": "anatomy",
                            "detail": detail})
        self.stats["queued"] += 1
        self._record("structural_confirmed", drift=score)
        return True

    def record_step(self, dt: float) -> None:
        """Feed one measured training step duration.  Drives the rolling
        baseline the ranker calibrates against and, during an adoption,
        the K-step baseline/gate measurements (an in-flight adoption's
        samples are kept out of the rolling window until it resolves —
        they belong to exactly one arm of the A/B)."""
        ad = self._adoption
        if ad is not None:
            if ad.phase == "baseline":
                ad.baseline_times.append(float(dt))
                return
            if ad.phase == "gate":
                ad.gate_times.append(float(dt) * ad.regression_scale)
                return
        self._recent_dt.append(float(dt))

    # -- the control loop ----------------------------------------------------

    def tick(self) -> None:
        """One control-loop turn: consume due faults, refit the
        telemetry buffer, debounce drift, advance any in-flight
        adoption, and start a queued one once cooldown allows."""
        self._tick += 1
        self._poll_faults()
        if self._drift_env:
            self._synthesize_telemetry()
        if self._tick % self.refit_every == 0:
            drifted = self._refit_window()
            if drifted is not None:
                if drifted:
                    self._streak += 1
                else:
                    self._streak = 0
                if self._streak >= self.confirm_windows:
                    self._confirm_drift()
        if self._adoption is not None:
            self._advance(self._adoption)
            return
        now = self.clock()
        if (self._queue and now >= self._cooldown_until
                and self._recent_dt):
            self._start_adoption(self._queue.popleft())

    def request_adoption(self, model: Optional[CostModel] = None) -> None:
        """Operator override: queue an adoption pass (re-rank + gated
        adopt) without waiting for a drift confirmation.  Marked manual
        so :meth:`audit` does not flag it."""
        self._queue.append({"model": model, "drift": None,
                            "manual": True})
        self.stats["queued"] += 1
        self._record("adoption_queued", manual=True)

    @property
    def adopting(self) -> bool:
        return self._adoption is not None

    @property
    def queued(self) -> int:
        return len(self._queue)

    # -- fault hooks ---------------------------------------------------------

    def _poll_faults(self) -> None:
        if self.injector is None:
            return
        step = int(getattr(self.trainer, "current_step", 0))
        f = self.injector.check_cost_drift(step)
        if f is not None:
            self._apply_cost_drift(f)

    def _apply_cost_drift(self, fault) -> None:
        """An injected ``cost_drift``: the (simulated) machine's links
        change speed by ``magnitude``.  Seeds the drifted environment
        from the CURRENT profile's curves; telemetry synthesized from
        it flows every tick from here on, so detection happens the
        honest way — by refitting measurements."""
        scale = float(fault.magnitude or 0.0) or 2.0
        if not self._drift_env:
            for key, fit in self.profile.curves().items():
                self._drift_env[key] = [fit.alpha_s, fit.beta_s_per_byte]
        for ab in self._drift_env.values():
            ab[0] *= scale
            ab[1] *= scale
        self.stats["drift_faults"] += 1
        self._record("cost_drift_fault", scale=scale)

    def _synthesize_telemetry(self) -> None:
        ms = []
        for (op, dtype, lc), (a, b) in sorted(self._drift_env.items()):
            ms.extend(simulate_link_measurements(
                a, b, link_class=lc, ops=(op,), dtypes=(dtype,),
                sizes=(1 << 12, 1 << 16, 1 << 20), group_sizes=(2, 4)))
        self.observe(ms)

    # -- detect --------------------------------------------------------------

    def _refit_window(self) -> Optional[bool]:
        """One refit window; None when there was no window (too few
        fresh measurements — the buffer is kept and the confirmation
        streak is left UNTOUCHED: absence of data is not evidence of
        stability)."""
        t0 = time.perf_counter()
        res = self.profile.refit(min_measurements=self.min_measurements)
        if not res["refitted"]:
            return None
        dt = time.perf_counter() - t0
        self.stats["refits"] += 1
        self.stats["last_refit_s"] = dt
        if self._h_refit is not None:
            self._h_refit.observe(dt)
        drift = res["drift"]["max_drift"]
        self.stats["last_drift"] = drift
        self._candidate = res["model"]
        drifted = drift >= self.drift_threshold
        self._record("refit", n=res["n"], drift=round(drift, 6),
                     drifted=drifted)
        return drifted

    def _confirm_drift(self) -> None:
        self._streak = 0
        self.stats["drift_confirmed"] += 1
        if self._g_drift is not None:
            self._g_drift.set(1)
        # coalesce: while an adoption is busy or cooling down, the SAME
        # ongoing drift keeps re-confirming every confirm_windows ticks
        # — refresh the pending request to the latest refit candidate
        # instead of piling up stale duplicates (each stale entry would
        # later start its own adoption: plan churn, exactly what the
        # audit calls flapping)
        for req in self._queue:
            if not req["manual"] and req.get("source") != "anatomy":
                req["model"] = self._candidate
                req["drift"] = self.stats["last_drift"]
                self._record("drift_confirmed", drift=req["drift"],
                             coalesced=True)
                return
        self._queue.append({"model": self._candidate,
                            "drift": self.stats["last_drift"],
                            "manual": False, "source": "cost"})
        self.stats["queued"] += 1
        self._record("drift_confirmed", drift=self.stats["last_drift"])

    # -- rank ----------------------------------------------------------------

    def _rank_plans(self) -> List[dict]:
        if self.ranker is not None:
            return list(self.ranker(self.profile))
        import jax

        cur = self.trainer.plan.spec
        if self._grad_bytes is None:
            self._grad_bytes = int(sum(
                leaf.size * leaf.dtype.itemsize
                for leaf in jax.tree_util.tree_leaves(self.trainer.params)))
        base = sum(self._recent_dt) / len(self._recent_dt)

        def comm(dp):
            if dp <= 1:
                return 0.0
            return self.profile.predict("psum", self._grad_bytes, dp,
                                        link_class=self.link_class)

        # roofline calibrated from what the machine measures RIGHT NOW:
        # whatever the baseline isn't spending on the all-reduce is
        # serial compute, perfectly dp-scalable for a replicated batch
        serial_s = max(base - comm(cur.dp), 0.0) * cur.dp
        n = len(getattr(self.trainer, "_devices", ())) or cur.dp
        out = []
        for dp in range(1, n + 1):
            if n % dp or dp < self.min_dp:
                continue
            spec = dataclasses.replace(
                cur, dp=dp, zero_shard=dp if cur.zero_shard > 1 else 1)
            out.append({"spec": spec,
                        "predicted_s": serial_s / dp + comm(dp)})
        out.sort(key=lambda r: r["predicted_s"])
        return out

    # -- adopt ---------------------------------------------------------------

    def _start_adoption(self, req: dict) -> None:
        now = self.clock()
        model = req.get("model")
        if model is not None:
            # adopt the refreshed profile NOW: ranking must see it, and
            # it survives a plan rollback — the measurements don't lie,
            # only the plan bet is reversible.  Carry any telemetry
            # buffered since the refit window that produced it.
            model.update(self.profile.fresh_measurements)
            self.profile = model
        entry = {"tick": self._tick, "t": now,
                 "drift": req.get("drift"),
                 "manual": bool(req.get("manual")),
                 "source": req.get("source",
                                   "manual" if req.get("manual")
                                   else "cost"),
                 "cooldown_ok": now >= self._cooldown_until,
                 "fault": False, "old": None, "new": None,
                 "outcome": None, "reason": None}
        if req.get("detail"):
            entry["detail"] = req["detail"]
        self.adoption_log.append(entry)
        t0 = time.perf_counter()
        ranked = self._rank_plans()
        rank_s = time.perf_counter() - t0
        cur = self.trainer.plan.spec
        winner = ranked[0] if ranked else None
        entry["old"] = cur.describe()
        if winner is None or winner["spec"] == cur:
            entry["outcome"] = "no_change"
            entry["reason"] = ("ranked winner is the current plan"
                               if winner else "empty plan space")
            entry["new"] = entry["old"]
            self._resolve_counters("no_change")
            self.stats["no_change"] += 1
            self._cooldown_until = now + self.cooldown_s
            self._record("adoption_no_change", rank_s=round(rank_s, 6))
            return
        ad = _Adoption(entry=entry, t0=now, rank_s=rank_s,
                       old_spec=cur, new_spec=winner["spec"],
                       predicted_s=float(winner["predicted_s"]))
        entry["new"] = ad.new_spec.describe()
        if self.injector is not None:
            f = self.injector.check_plan_regression(
                int(getattr(self.trainer, "current_step", 0)))
            if f is not None:
                ad.regression_scale = float(f.magnitude or 0.0) or 2.0
                entry["fault"] = True
        self._adoption = ad
        self._record("adoption_start", old=entry["old"],
                     new=entry["new"], rank_s=round(rank_s, 6),
                     predicted_s=round(ad.predicted_s, 6),
                     drift=entry["drift"], manual=entry["manual"])

    def _advance(self, ad: _Adoption) -> None:
        if (ad.phase == "baseline"
                and len(ad.baseline_times) >= self.gate_steps):
            ad.baseline_s = (sum(ad.baseline_times)
                             / len(ad.baseline_times))
            ad.entry["baseline_s"] = ad.baseline_s
            self._record("phase", phase="drain",
                         baseline_s=round(ad.baseline_s, 6))
            try:
                self.trainer.replan_to(ad.new_spec)
            except Exception as e:   # manifest stamp already restored
                self._rollback(ad, f"replan failed: "
                                   f"{type(e).__name__}: {e}",
                               resharded=False)
                return
            st = getattr(self.trainer, "stats", {})
            ad.drain_s = float(st.get("last_checkpoint_s", 0.0))
            ad.reshard_s = float(st.get("last_reshard_s", 0.0))
            ad.phase = "gate"
            self._record("phase", phase="gate",
                         drain_s=round(ad.drain_s, 6),
                         reshard_s=round(ad.reshard_s, 6))
        elif (ad.phase == "gate"
                and len(ad.gate_times) >= self.gate_steps):
            gate = sum(ad.gate_times) / len(ad.gate_times)
            ad.entry["gate_s"] = gate
            if gate <= ad.baseline_s * self.gate_tolerance:
                self._commit(ad, gate)
            else:
                self._rollback(
                    ad, f"measured regression: gate mean {gate:.6f}s > "
                        f"baseline {ad.baseline_s:.6f}s x "
                        f"{self.gate_tolerance}")

    def _commit(self, ad: _Adoption, gate_s: float) -> None:
        now = self.clock()
        ad.entry["outcome"] = "commit"
        ad.entry["reason"] = (f"gate mean {gate_s:.6f}s within "
                              f"{self.gate_tolerance}x of baseline "
                              f"{ad.baseline_s:.6f}s")
        self.stats["adoptions"] += 1
        self.stats["last_adoption"] = {
            "outcome": "commit", "old": ad.entry["old"],
            "new": ad.entry["new"], "rank_s": ad.rank_s,
            "drain_s": ad.drain_s, "reshard_s": ad.reshard_s,
            "rollback_s": 0.0, "baseline_s": ad.baseline_s,
            "gate_s": gate_s, "total_s": now - ad.t0}
        self._resolve_counters("commit")
        # the new plan's gate measurements seed the rolling baseline
        self._recent_dt.clear()
        self._recent_dt.extend(ad.gate_times)
        self._cooldown_until = now + self.cooldown_s
        self._adoption = None
        self._record("adoption_commit", new=ad.entry["new"],
                     gate_s=round(gate_s, 6))
        if self.recorder is not None:
            self.recorder.trigger(
                "autopilot_adoption", old=ad.entry["old"],
                new=ad.entry["new"], gate_s=gate_s)

    def _rollback(self, ad: _Adoption, reason: str,
                  resharded: bool = True) -> None:
        t0 = time.perf_counter()
        if resharded:
            # the boundary checkpoint written under the old plan makes
            # this bitwise: replan back and resume as if never adopted
            self.trainer.replan_to(ad.old_spec)
        rollback_s = time.perf_counter() - t0
        now = self.clock()
        ad.entry["outcome"] = "rollback"
        ad.entry["reason"] = reason
        self.stats["rollbacks"] += 1
        self.stats["last_adoption"] = {
            "outcome": "rollback", "old": ad.entry["old"],
            "new": ad.entry["new"], "rank_s": ad.rank_s,
            "drain_s": ad.drain_s, "reshard_s": ad.reshard_s,
            "rollback_s": rollback_s, "baseline_s": ad.baseline_s,
            "gate_s": ad.entry.get("gate_s"), "total_s": now - ad.t0}
        self._resolve_counters("rollback")
        self._cooldown_until = now + self.cooldown_s
        self._adoption = None
        self._record("adoption_rollback", old=ad.entry["old"],
                     reason=reason)
        if self.recorder is not None:
            self.recorder.trigger(
                "autopilot_rollback", old=ad.entry["old"],
                new=ad.entry["new"], reason=reason)

    def _resolve_counters(self, outcome: str) -> None:
        if self._c_adopt is not None:
            self._c_adopt.inc(outcome=outcome)
        if self._g_drift is not None:
            self._g_drift.set(0)

    # -- audit ---------------------------------------------------------------

    def audit(self) -> List[dict]:
        """Replay the adoption log against the controller's own rules;
        a well-behaved run returns ``[]``.  Flags (a) a non-manual
        adoption that started without a confirmed over-threshold drift
        (``cost`` entries against ``drift_threshold``, ``anatomy``
        entries against ``structural_threshold``) and (b) any adoption
        that started before cooldown expiry — the plan-churn analogue
        of capacity flapping."""
        out = []
        for e in self.adoption_log:
            thr = (self.structural_threshold
                   if e.get("source") == "anatomy"
                   else self.drift_threshold)
            if not e["manual"] and (e["drift"] is None
                                    or e["drift"] < thr):
                out.append({"tick": e["tick"], "drift": e["drift"],
                            "reason": "adoption started without a "
                                      "confirmed drift past the "
                                      "threshold"})
            if not e["cooldown_ok"]:
                out.append({"tick": e["tick"],
                            "reason": "adoption started before "
                                      "cooldown expiry"})
        return out

    # -- plumbing ------------------------------------------------------------

    def _record(self, what: str, **kw) -> None:
        if self.recorder is not None:
            self.recorder.record("autopilot", what, tick=self._tick,
                                 **kw)
        if self.tracer is not None:
            self.tracer.instant(f"autopilot/{what}", tick=self._tick,
                                **kw)
