"""Elastic, preemption-native training (ISSUE 9 / ROADMAP item 4).

Preemptible TPU pods change SHAPE, not just liveness: a maintenance
event takes half the slice away, a restored reservation gives it back.
Surviving that is a layout problem, not a retrain problem — *Automatic
Cross-Replica Sharding of Weight Update* (arXiv:2004.13336) and *GSPMD*
(arXiv:2105.04663) make the point this module operationalizes: sharded
optimizer state is a pure partition of the same logical tensors, so a
``dp=8 -> dp=4`` shrink is a deterministic re-partition.

Three pieces close the loop from :class:`CheckpointManager`'s
topology-tolerant restore and :class:`FaultInjector`'s preemption model
into genuinely elastic training:

* :class:`TopologySpec` / :class:`ElasticPlan` — the (dp, tp, pp, SP,
  ZeRO-shard) descriptor plus the concrete mesh it resolves to.  The
  checkpoint manager stamps the spec into every manifest; restore
  validates it and warns (with BOTH specs) before re-sharding.
* :func:`reshard_optimizer_state` — re-partitions optimizer state
  across a topology change.  ZeRO reduce-scatter shards gather to the
  LOGICAL per-leaf tensors (``unflatten_bucket`` under the old
  ``block_rows * world_size`` padding) and re-split under the new world
  size; per-leaf fused-optimizer slots re-layout through the caller's
  param transform.  f32 moments and master weights are preserved
  bitwise — only the padding moves.
* :class:`ElasticTrainer` — the driver loop around
  :class:`~apex_tpu.resilience.guard.GuardedTrainStep`.  On a
  preemption/arrival signal (an injected ``topology_change`` fault or a
  :class:`HostSignals` delivery, the SIGTERM-with-grace-period
  analogue) it drains in-flight saves, checkpoints under the OLD
  topology, builds the new plan's components (fresh compile),
  re-shards the live state, checkpoints again under the NEW topology —
  so the guard's K-anomaly rollback can never restore an
  old-topology layout — and resumes.  A hard
  :class:`~apex_tpu.resilience.faults.Preemption` still propagates
  (no grace period); the next trainer reads the manifest's stamped
  topology, restores onto it, and re-shards to its own plan.

Which transitions are BITWISE: with the global batch replicated over
the data axis, a pmean over any power-of-two group of identical values
is exact (``n*x`` then ``/n``), so the gradient math is
topology-invariant and dp changes (including ZeRO re-shards — the
reduce-scatter sums ``ws`` identical copies, ``average_grads`` divides
them back out) resume bitwise.  With the batch SHARDED, the reduction
tree changes with dp and the run is trajectory-equivalent instead
(asserted ``allclose`` at a re-aligned step) — the documented cell in
``tools/crash_matrix.py --topology``.  See ``docs/source/resilience.md``.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import signal as _signal
import threading
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

from apex_tpu.resilience.guard import GuardedTrainStep

_DATA_AXIS = "data"
_PIPE_AXIS = "pipe"
_TENSOR_AXIS = "model"


# -- topology descriptors -----------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TopologySpec:
    """The logical parallelism layout a train state lives under.

    ``zero_shard`` is the ZeRO optimizer-state shard factor over the
    data axis — 1 (replicated optimizer state, the per-leaf fused
    optimizers) or ``dp`` (the distributed optimizers' reduce-scatter
    sharding).  Anything in between would shard rows unevenly against
    the data axis, so it is rejected.
    """
    dp: int = 1
    tp: int = 1
    pp: int = 1
    sequence_parallel: bool = False
    zero_shard: int = 1

    def __post_init__(self):
        for name in ("dp", "tp", "pp", "zero_shard"):
            v = getattr(self, name)
            if not isinstance(v, (int, np.integer)) or v < 1:
                raise ValueError(f"{name} must be a positive int, got {v!r}")
        if self.zero_shard not in (1, self.dp):
            raise ValueError(
                f"zero_shard must be 1 or dp ({self.dp}), got "
                f"{self.zero_shard}: ZeRO shards the data axis")
        if self.sequence_parallel and self.tp == 1:
            raise ValueError("sequence_parallel requires tp > 1")

    @property
    def n_devices(self) -> int:
        return self.dp * self.tp * self.pp

    def to_dict(self) -> dict:
        return {"dp": int(self.dp), "tp": int(self.tp), "pp": int(self.pp),
                "sequence_parallel": bool(self.sequence_parallel),
                "zero_shard": int(self.zero_shard)}

    @classmethod
    def from_dict(cls, d: dict) -> "TopologySpec":
        return cls(dp=int(d.get("dp", 1)), tp=int(d.get("tp", 1)),
                   pp=int(d.get("pp", 1)),
                   sequence_parallel=bool(d.get("sequence_parallel", False)),
                   zero_shard=int(d.get("zero_shard", 1)))

    def describe(self) -> str:
        return (f"dp={self.dp} tp={self.tp} pp={self.pp} "
                f"sp={'on' if self.sequence_parallel else 'off'} "
                f"zero={self.zero_shard}")

    def to_plan(self, **overrides):
        """Lift into the full :class:`~apex_tpu.parallel.plan.
        ParallelPlan` this spec is a projection of; ``overrides``
        supply the knobs the spec does not carry (schedule, remat,
        transport).  ``spec.to_plan().topology() == spec`` — the
        lossless round-trip old stamped manifests rely on."""
        from apex_tpu.parallel.plan import ParallelPlan
        return ParallelPlan.from_topology(self, **overrides)


@dataclasses.dataclass(frozen=True)
class ElasticPlan:
    """A :class:`TopologySpec` resolved onto concrete devices.

    The mesh always carries the full ``("data", "pipe", "model")`` axis
    set with sizes ``(dp, pp, tp)`` — unit axes are free, and one
    canonical axis order means every component (ZeRO reduce-scatter
    over ``"data"``, ring pipeline over ``"pipe"``, TP collectives over
    ``"model"``) addresses the same mesh regardless of which axes the
    plan actually uses.
    """
    spec: TopologySpec
    mesh: Any                      # jax.sharding.Mesh
    parallel: Any = None           # full ParallelPlan when built from one

    @classmethod
    def build(cls, spec, devices=None) -> "ElasticPlan":
        """``spec`` is a :class:`TopologySpec` or a full
        :class:`~apex_tpu.parallel.plan.ParallelPlan` — the latter is
        kept on :attr:`parallel` so factories can read the schedule/
        remat/transport knobs the topology projection drops."""
        import jax
        parallel = None
        if not isinstance(spec, TopologySpec) and hasattr(spec, "topology"):
            parallel = spec
            if getattr(parallel, "n_pods", 1) > 1:
                raise ValueError(
                    f"plan has n_pods={parallel.n_pods}: a cross-pod "
                    "MPMD plan spans multiple meshes and cannot build "
                    "one ElasticPlan — run it with "
                    "apex_tpu.mpmd.MpmdPipeline (per-stage programs), "
                    "or set n_pods=1 for a single-mesh ring pipeline")
            spec = spec.topology()
        devices = list(devices) if devices is not None else jax.devices()
        n = spec.n_devices
        if len(devices) < n:
            raise ValueError(
                f"plan {spec.describe()} needs {n} devices, have "
                f"{len(devices)}")
        mesh = jax.make_mesh((spec.dp, spec.pp, spec.tp),
                             (_DATA_AXIS, _PIPE_AXIS, _TENSOR_AXIS),
                             devices=devices[:n])
        return cls(spec=spec, mesh=mesh, parallel=parallel)

    def replicated(self):
        from jax.sharding import NamedSharding, PartitionSpec
        return NamedSharding(self.mesh, PartitionSpec())

    def sharded(self, *axes):
        from jax.sharding import NamedSharding, PartitionSpec
        return NamedSharding(self.mesh, PartitionSpec(*axes))

    def put(self, tree):
        """``device_put`` a pytree replicated onto this plan's mesh."""
        import jax
        return jax.device_put(tree, self.replicated())

    @property
    def mesh_shape(self) -> dict:
        return dict(zip(self.mesh.axis_names,
                        (int(s) for s in self.mesh.devices.shape)))


# -- optimizer state re-sharding ----------------------------------------------


def _as_f32_meta(meta):
    import jax.numpy as jnp
    return meta._replace(dtype=jnp.float32)


def _zero_reshard(state, new_plan, optimizer, params, new_optimizer,
                  new_params):
    """Gather-to-logical -> re-split for ZeRO (bucketed) state.

    Bucket padding is ``block_rows * world_size`` rows, so the packed
    layout itself depends on dp — but the pad rows are identically zero
    (zero grads keep Adam/LAMB moments at zero and the noop'd master
    rows at their initial zero), so dropping them via
    ``unflatten_bucket`` under the OLD meta and re-padding via
    ``flatten_bucket`` under the NEW meta moves only zeros.  The
    logical f32 values (moments AND master weights) transfer bitwise.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from apex_tpu.multi_tensor_apply import bucketing as B

    old_layout = optimizer._layout(params)
    new_layout = new_optimizer._layout(new_params)
    old_by_key = {info.key: info for info in old_layout.buckets}
    new_by_key = {info.key: info for info in new_layout.buckets}
    if set(old_by_key) != set(new_by_key):
        raise ValueError(
            f"bucket keys changed across the re-shard: "
            f"{sorted(old_by_key)} vs {sorted(new_by_key)} — elastic "
            "re-sharding requires a layout-stable bucketing "
            "(message_size=None, same param grouping)")
    shard = NamedSharding(new_plan.mesh, P(new_optimizer.axis_name))
    rep = NamedSharding(new_plan.mesh, P())
    buckets = {}
    for key, old_info in old_by_key.items():
        new_info = new_by_key[key]
        src = state["buckets"][key]
        dst = {}
        for slot, arr in src.items():
            full = jnp.asarray(np.asarray(arr))   # gather the global rows
            leaves = B.unflatten_bucket(full, _as_f32_meta(old_info.meta))
            repacked = B.flatten_bucket(leaves, _as_f32_meta(new_info.meta))
            dst[slot] = jax.device_put(repacked, shard)
        buckets[key] = dst
    step = jax.device_put(jnp.asarray(np.asarray(state["step"])), rep)
    return {"step": step, "buckets": buckets}


def _per_leaf_reshard(state, new_plan, optimizer, params, new_optimizer,
                      new_params, transform):
    """Re-layout per-leaf fused-optimizer slots across a param-layout
    change: each slot kind (m / v / master / ...) is lifted into a
    params-shaped tree, run through the SAME transform the params take
    (e.g. unpack-then-repack for a tp/pp change — pure slicing, so f32
    values are preserved bitwise), and redistributed into the new
    layout's buckets."""
    import jax
    import jax.numpy as jnp

    _f32 = jnp.float32
    old_layout = optimizer._layout(params)
    new_layout = new_optimizer._layout(new_params)
    old_leaves, old_treedef = jax.tree_util.tree_flatten(params)
    slot_keys = sorted({k for key in state["buckets"]
                        for k in state["buckets"][key]})
    slot_leaves: Dict[str, list] = {}
    for sk in slot_keys:
        filled: list = [None] * old_layout.n_leaves
        for info in old_layout.buckets:
            vals = state["buckets"][info.key].get(sk)
            if vals is None:
                continue
            for i, v in zip(info.indices, vals):
                filled[i] = v
        # leaves whose bucket lacks this slot (e.g. no master for f32
        # buckets) get the value a fresh init would give them; they are
        # dropped again on redistribution unless the new bucket wants
        # the slot
        filled = [
            v if v is not None else (
                old_leaves[i].astype(_f32) if sk == "master"
                else jnp.zeros(np.shape(old_leaves[i]), _f32))
            for i, v in enumerate(filled)]
        tree = jax.tree_util.tree_unflatten(old_treedef, filled)
        if transform is not None:
            tree = transform(tree)
        leaves = jax.tree_util.tree_leaves(tree)
        if len(leaves) != new_layout.n_leaves:
            raise ValueError(
                f"slot {sk!r} transformed to {len(leaves)} leaves but the "
                f"new layout has {new_layout.n_leaves}: the param "
                "transform must map old-layout trees onto the new plan's "
                "param structure")
        slot_leaves[sk] = leaves
    rep = new_plan.replicated()
    new_buckets = {}
    old_slot_sets = {key: set(state["buckets"][key]) for key
                     in state["buckets"]}
    for info in new_layout.buckets:
        wanted = old_slot_sets.get(info.key)
        if wanted is None:
            raise ValueError(
                f"bucket {info.key!r} does not exist in the old state "
                f"(old buckets: {sorted(old_slot_sets)}) — elastic "
                "re-sharding requires dtype/group-stable transforms")
        nb = {}
        for sk in wanted:
            nb[sk] = [jax.device_put(slot_leaves[sk][i], rep)
                      for i in info.indices]
        new_buckets[info.key] = nb
    step = jax.device_put(jnp.asarray(np.asarray(state["step"])), rep)
    return {"step": step, "buckets": new_buckets}


def reshard_optimizer_state(state, old_plan: ElasticPlan,
                            new_plan: ElasticPlan, *, optimizer, params,
                            new_optimizer=None, new_params=None,
                            transform: Optional[Callable] = None):
    """Re-partition optimizer ``state`` from ``old_plan`` onto
    ``new_plan``.

    ``optimizer``/``params`` are the instance and param tree the state
    was built against; ``new_optimizer``/``new_params`` the ones it
    must serve next (default: unchanged).  ``transform`` maps an
    old-layout params-shaped tree to the new layout (identity for pure
    dp changes; unpack/re-pack for tp/pp changes) and is applied to
    every per-leaf slot.

    ZeRO (distributed, bucketed) state takes the gather-to-logical ->
    re-split path — f32 moments and master weights bitwise, only the
    ``block_rows * world_size`` padding moves.  Per-leaf fused state is
    re-laid-out slot-by-slot through ``transform``.  Both paths
    ``device_put`` onto the new plan's mesh.
    """
    from apex_tpu.parallel.distributed_optimizer import _DistributedMixin

    new_optimizer = new_optimizer if new_optimizer is not None else optimizer
    new_params = new_params if new_params is not None else params
    if not (isinstance(state, dict) and "buckets" in state):
        raise ValueError(
            "expected a fused-optimizer state dict with a 'buckets' entry")
    if (optimizer.param_group_fn is not None
            or new_optimizer.param_group_fn is not None) \
            and transform is not None:
        raise ValueError(
            "param_group_fn + a layout transform cannot re-shard safely: "
            "leaf paths change across the transform, so group membership "
            "would be recomputed against different names")
    if isinstance(optimizer, _DistributedMixin):
        if not isinstance(new_optimizer, _DistributedMixin):
            raise ValueError(
                "old optimizer is ZeRO-sharded but the new one is not; "
                "build the new plan's optimizer before re-sharding")
        if transform is not None:
            raise ValueError(
                "ZeRO re-sharding supports dp/world-size changes only "
                "(the packed buckets assume an unchanged leaf set); "
                "compose tp/pp transforms at the per-leaf layer instead")
        return _zero_reshard(state, new_plan, optimizer, params,
                             new_optimizer, new_params)
    return _per_leaf_reshard(state, new_plan, optimizer, params,
                             new_optimizer, new_params, transform)


# -- ZeRO under the guard -----------------------------------------------------


class ZeROGuardAdapter:
    """Adapts a distributed (ZeRO) optimizer to
    :class:`GuardedTrainStep`'s flat ``init``/``step`` contract.

    The guard calls ``optimizer.step`` OUTSIDE any shard_map region, on
    replicated grads; the adapter opens the ZeRO region itself, feeding
    each device the SAME fully-reduced gradient.  The reduce-scatter
    inside then sums ``world_size`` identical copies and
    ``average_grads`` divides them back out — exact for power-of-two
    world sizes — so wrapping is numerically the identity while the
    state stays row-sharded (the ZeRO memory saving survives).
    """

    def __init__(self, optimizer, mesh):
        import jax.numpy as jnp
        optimizer._check_mesh(mesh)
        self.inner = optimizer
        self.mesh = mesh
        self._f32 = jnp.float32

    def init(self, params):
        from jax.sharding import PartitionSpec as P

        from apex_tpu.utils.collectives import shard_map_compat
        return shard_map_compat(
            self.inner.init, mesh=self.mesh, in_specs=(P(),),
            out_specs=self.inner.state_specs(params))(params)

    def step(self, grads, params, state, *, lr=None, grad_scale=1.0,
             noop_flag=None):
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from apex_tpu.utils.collectives import shard_map_compat

        specs = self.inner.state_specs(params)
        gs = jnp.asarray(grad_scale, self._f32)
        noop = (jnp.zeros((), self._f32) if noop_flag is None
                else jnp.reshape(jnp.asarray(noop_flag, self._f32), ()))
        lr_args = () if lr is None else (jnp.asarray(lr, self._f32),)

        def local(g, p, s, gs_, noop_, *lr_):
            return self.inner.step(g, p, s, lr=lr_[0] if lr_ else None,
                                   grad_scale=gs_, noop_flag=noop_)

        return shard_map_compat(
            local, mesh=self.mesh,
            in_specs=(P(), P(), specs, P(), P()) + (P(),) * len(lr_args),
            out_specs=(P(), specs))(grads, params, state, gs, noop,
                                    *lr_args)


# -- host signals -------------------------------------------------------------


class ElasticSignal(collections.namedtuple("ElasticSignal",
                                           ("kind", "spec"))):
    """``kind`` is ``"preempt"`` (drain + checkpoint + stop — the
    SIGTERM-with-grace analogue) or ``"replan"`` (re-shard onto
    ``spec`` and keep training — the arrival/defrag analogue)."""

    def __new__(cls, kind: str, spec=None):
        if kind not in ("preempt", "replan"):
            raise ValueError(f"unknown signal kind {kind!r}")
        if kind == "replan" and spec is None:
            raise ValueError("replan signals need a target TopologySpec "
                             "or ParallelPlan")
        return super().__new__(cls, kind, spec)


class HostSignals:
    """Thread/handler-safe mailbox for preemption & arrival signals.

    Programmatic delivery (:meth:`request_preempt` /
    :meth:`request_replan`) covers tests and schedulers with an API;
    :meth:`install` binds a POSIX signal (the real SIGTERM grace
    window) to the same mailbox.  :class:`ElasticTrainer` polls once
    per step — signals land between steps, never mid-step.
    """

    def __init__(self):
        self._pending: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._installed: dict = {}

    def request(self, sig: ElasticSignal) -> None:
        with self._lock:
            self._pending.append(sig)

    def request_preempt(self) -> None:
        self.request(ElasticSignal("preempt"))

    def request_replan(self, spec) -> None:
        """``spec`` is a :class:`TopologySpec` or a full
        :class:`~apex_tpu.parallel.plan.ParallelPlan` (e.g. the winner
        ``tools/autotune.py`` emitted)."""
        self.request(ElasticSignal("replan", spec))

    def poll(self) -> Optional[ElasticSignal]:
        with self._lock:
            return self._pending.popleft() if self._pending else None

    def install(self, signum: int = _signal.SIGTERM, *,
                kind: str = "preempt",
                spec: Optional[TopologySpec] = None) -> None:
        """Bind a POSIX signal to this mailbox (main thread only, like
        any ``signal.signal`` use); :meth:`uninstall` restores the
        previous handlers."""
        sig = ElasticSignal(kind, spec)   # validate before binding

        def handler(_signum, _frame):
            self.request(sig)

        self._installed[signum] = _signal.signal(signum, handler)

    def uninstall(self) -> None:
        while self._installed:
            signum, prev = self._installed.popitem()
            _signal.signal(signum, prev)


# -- the elastic driver loop --------------------------------------------------


@dataclasses.dataclass
class ElasticComponents:
    """What a plan factory returns: a guard wired to the trainer's
    checkpoint manager plus freshly-initialized state in THIS plan's
    layout.  ``optimizer`` is the instance
    :func:`reshard_optimizer_state` should reason about (the ZeRO inner
    optimizer when the guard holds a :class:`ZeROGuardAdapter`;
    defaults to ``guard.optimizer``).  ``transform(tree, old_plan)``
    maps a params-shaped tree from ``old_plan``'s layout into this
    plan's (``None`` = layouts agree, e.g. pure dp changes)."""
    guard: GuardedTrainStep
    params: Any
    opt_state: Any
    guard_state: Any
    scaler_state: Any = None
    optimizer: Any = None
    transform: Optional[Callable[[Any, ElasticPlan], Any]] = None

    def reshard_optimizer(self):
        return self.optimizer if self.optimizer is not None \
            else self.guard.optimizer


class ElasticTrainer:
    """Signal-driven elastic training around :class:`GuardedTrainStep`.

    ``factory(plan, checkpoint, fault_injector) -> ElasticComponents``
    builds (and implicitly compiles, on first step) everything a
    topology needs; the trainer owns the plan lifecycle::

        RUNNING --signal--> DRAIN (async saves) --> CHECKPOINT (old
        topology) --> REPLAN (factory on the new plan) --> RESHARD
        (params/optimizer/guard/scaler onto the new mesh) -->
        CHECKPOINT (new topology) --> RUNNING (recompile on first step)

    Signals come from the injector's deterministic ``topology_change``
    faults and from a :class:`HostSignals` mailbox; a hard
    :class:`~apex_tpu.resilience.faults.Preemption` propagates
    uncaught, and the NEXT trainer run auto-resumes: the manifest's
    stamped :class:`TopologySpec` picks the restore layout, the restore
    warns about the mismatch, and the state re-shards onto this
    trainer's plan before the first step.  The post-reshard checkpoint
    keeps the guard's K-anomaly rollback inside the current topology —
    a shrinking pod never resumes from (or into) a stale layout.

    Observability: ``elastic_preempt_signals`` / ``elastic_replans``
    counters, the ``elastic_reshard_seconds`` histogram and the
    ``elastic_resume_step`` gauge on ``registry``; ``elastic/replan``
    and ``elastic/restore`` spans (plus signal instants) on ``tracer``
    — a replan shows up on the same Perfetto timeline as the train
    steps around it.
    """

    def __init__(self, factory, plan: ElasticPlan, *, directory: str,
                 fault_injector=None, signals: Optional[HostSignals] = None,
                 registry=None, tracer=None, recorder=None, keep: int = 3,
                 save_every: int = 1, devices=None,
                 clock: Callable[[], float] = time.perf_counter):
        from apex_tpu.resilience.checkpoint import CheckpointManager

        self.factory = factory
        self.plan = plan
        self._base_spec = plan.spec
        self.fault_injector = fault_injector
        self.signals = signals
        self.tracer = tracer
        # optional flight recorder (fleetobs.FlightRecorder): per-step
        # entries feed its "trainer" ring; a guard rollback cuts a
        # correlated snapshot — the training-side black-box trigger
        self.recorder = recorder
        self.save_every = max(1, int(save_every))
        self.clock = clock
        self._devices = (list(devices) if devices is not None
                         else list(plan.mesh.devices.flat))
        self.checkpoint = CheckpointManager(
            directory, keep=keep, fault_injector=fault_injector,
            topology=plan.spec, parallel_plan=plan.parallel)
        self._comp: Optional[ElasticComponents] = None
        self._params = self._opt = self._gstate = self._sstate = None
        self._preempt_requested = False
        self._step = 0
        self.stats = {"replans": 0, "preempt_signals": 0,
                      "resume_step": 0, "last_checkpoint_s": 0.0,
                      "last_reshard_s": 0.0}
        self._c_signals = self._c_replans = None
        self._h_reshard = self._g_resume = None
        if registry is not None:
            self._c_signals = registry.counter(
                "elastic_preempt_signals",
                "preemption/arrival signals received")
            self._c_replans = registry.counter(
                "elastic_replans", "topology re-plans executed")
            self._h_reshard = registry.histogram(
                "elastic_reshard_seconds",
                "checkpoint+rebuild+reshard wall time per re-plan")
            self._g_resume = registry.gauge(
                "elastic_resume_step",
                "step training (re)started from after the last "
                "restore/re-plan")

    # -- small observability helpers ----------------------------------------

    def _span(self, name: str, **args):
        if self.tracer is None:
            return contextlib.nullcontext()
        return self.tracer.span(name, **args)

    def _signal_seen(self, step: int, kind: str) -> None:
        self.stats["preempt_signals"] += 1
        if self._c_signals is not None:
            self._c_signals.inc()
        if self.tracer is not None:
            self.tracer.instant("elastic/signal", step=step, kind=kind)
        if self.recorder is not None:
            self.recorder.record("trainer", "signal", step=step,
                                 kind=kind)

    def _resumed_at(self, step: int) -> None:
        self.stats["resume_step"] = int(step)
        if self._g_resume is not None:
            self._g_resume.set(int(step))

    # -- component lifecycle -------------------------------------------------

    def _build(self, plan: ElasticPlan,
               injector="inherit") -> ElasticComponents:
        inj = self.fault_injector if injector == "inherit" else injector
        comp = self.factory(plan, self.checkpoint, inj)
        if comp.guard.checkpoint is not self.checkpoint:
            raise ValueError(
                "the factory must attach the trainer's CheckpointManager "
                "to the guard (guard.checkpoint is the rollback store)")
        return comp

    def _adopt(self, comp: ElasticComponents, state: dict) -> None:
        self._comp = comp
        self._params = state["params"]
        self._opt = state["opt"]
        self._gstate = state["guard"]
        self._sstate = state.get("scaler")

    def _save(self, step: int) -> None:
        self._comp.guard.save(step, self._params, self._opt, self._gstate,
                              self._sstate)

    def _reshard_onto(self, old_plan: ElasticPlan,
                      old_comp: ElasticComponents, new_plan: ElasticPlan,
                      new_comp: ElasticComponents) -> None:
        tr = None
        if new_comp.transform is not None:
            tr = lambda t: new_comp.transform(t, old_plan)  # noqa: E731
        old_params = self._params
        new_params = tr(old_params) if tr is not None else old_params
        self._params = new_plan.put(new_params)
        self._opt = reshard_optimizer_state(
            self._opt, old_plan, new_plan,
            optimizer=old_comp.reshard_optimizer(), params=old_params,
            new_optimizer=new_comp.reshard_optimizer(),
            new_params=new_params, transform=tr)
        self._gstate = new_plan.put(self._gstate)
        if self._sstate is not None:
            self._sstate = new_plan.put(self._sstate)

    # -- restore / replan ----------------------------------------------------

    def _restore_or_init(self, resume: bool) -> int:
        if not resume or self.checkpoint.latest_step() is None:
            comp = self._build(self.plan)
            self._adopt(comp, {"params": comp.params, "opt": comp.opt_state,
                               "guard": comp.guard_state,
                               "scaler": comp.scaler_state})
            self._resumed_at(0)
            return 0
        saved = self.checkpoint.topology_of(self.checkpoint.latest_step())
        saved_spec = (TopologySpec.from_dict(saved) if saved
                      else self.plan.spec)
        with self._span("elastic/restore"):
            if saved_spec == self.plan.spec:
                comp = self._build(self.plan)
                template = GuardedTrainStep._template(
                    comp.params, comp.opt_state, comp.guard_state,
                    comp.scaler_state)
                restored, _ = self.checkpoint.restore(
                    template, topology=self.plan.spec)
                self._adopt(comp, restored)
                step = int(np.asarray(restored["step"]))
                # identity re-partition: places every leaf (params AND
                # optimizer slots) consistently on this plan's mesh —
                # per-leaf init templates carry default single-device
                # placements that the restore would otherwise keep
                self._params = self.plan.put(self._params)
                self._opt = reshard_optimizer_state(
                    self._opt, self.plan, self.plan,
                    optimizer=comp.reshard_optimizer(),
                    params=self._params)
                self._gstate = self.plan.put(self._gstate)
                if self._sstate is not None:
                    self._sstate = self.plan.put(self._sstate)
            else:
                # restore onto the SAVED topology's layout, then re-plan
                # onto ours — the restart half of a shrink/grow cycle
                old_plan = ElasticPlan.build(saved_spec,
                                             devices=self._devices)
                old_comp = self._build(old_plan, injector=None)
                template = GuardedTrainStep._template(
                    old_comp.params, old_comp.opt_state,
                    old_comp.guard_state, old_comp.scaler_state)
                restored, _ = self.checkpoint.restore(
                    template, topology=self.plan.spec)
                self._adopt(old_comp, restored)
                step = int(np.asarray(restored["step"]))
                target = (self.plan.parallel
                          if self.plan.parallel is not None
                          else self.plan.spec)
                self._replan(target, step, from_plan=old_plan,
                             checkpoint_first=False)
        self._resumed_at(step)
        return step

    def _replan(self, new_spec, step: int, *,
                from_plan: Optional[ElasticPlan] = None,
                checkpoint_first: bool = True) -> None:
        t0 = self.clock()
        old_plan = from_plan if from_plan is not None else self.plan
        old_comp = self._comp
        with self._span("elastic/replan", step=step,
                        old=old_plan.spec.describe(),
                        new=new_spec.describe()):
            if checkpoint_first:
                # drain in-flight async writes, then a boundary
                # checkpoint stamped with the OLD topology — the state a
                # hard kill mid-reshard falls back to
                self.checkpoint.wait()
                self._save(step)
            t_ck = self.clock()
            new_plan = ElasticPlan.build(new_spec, devices=self._devices)
            self.checkpoint.topology = new_plan.spec
            self.checkpoint.parallel_plan = new_plan.parallel
            try:
                new_comp = self._build(new_plan)
                self._reshard_onto(old_plan, old_comp, new_plan,
                                   new_comp)
            except Exception:
                # a failed build/re-shard must leave the manifest
                # stamped with the topology the live state still has —
                # otherwise the next save (or a crash-restart restore)
                # would claim a layout that never materialized
                self.checkpoint.topology = old_plan.spec
                self.checkpoint.parallel_plan = old_plan.parallel
                raise
            self._comp, self.plan = new_comp, new_plan
            # post-reshard checkpoint in the NEW layout: the guard's
            # K-anomaly rollback must never restore an old-topology
            # layout into the new mesh
            self._save(step)
        dt = self.clock() - t0
        self.stats["replans"] += 1
        self.stats["last_checkpoint_s"] = t_ck - t0
        self.stats["last_reshard_s"] = dt - (t_ck - t0)
        if self._c_replans is not None:
            self._c_replans.inc()
        if self._h_reshard is not None:
            self._h_reshard.observe(dt)
        if self.recorder is not None:
            self.recorder.record("trainer", "replan", step=step,
                                 old=old_plan.spec.describe(),
                                 new=new_spec.describe(),
                                 reshard_s=dt)
        self._resumed_at(step)

    # -- signal polling ------------------------------------------------------

    def _auto_spec(self, magnitude: float) -> TopologySpec:
        """Target spec for an injected ``topology_change``: magnitude >
        0 names the new dp; 0 toggles shrink-to-half / grow-to-base."""
        cur = self.plan.spec
        if magnitude > 0:
            new_dp = int(magnitude)
        else:
            new_dp = (max(1, cur.dp // 2) if cur.dp == self._base_spec.dp
                      else self._base_spec.dp)
        zero = new_dp if cur.zero_shard > 1 else 1
        return dataclasses.replace(cur, dp=new_dp, zero_shard=zero)

    def _poll_signals(self, step: int):
        target = None
        inj = self.fault_injector
        if inj is not None:
            fault = inj.check_topology_change(step)
            if fault is not None:
                self._signal_seen(step, "topology_change")
                target = self._auto_spec(fault.magnitude)
        if self.signals is not None:
            sig = self.signals.poll()
            while sig is not None:
                self._signal_seen(step, sig.kind)
                if sig.kind == "preempt":
                    self._preempt_requested = True
                else:
                    target = sig.spec
                sig = self.signals.poll()
        return target

    # -- the loop ------------------------------------------------------------

    def start(self, resume: bool = True) -> int:
        """Build — or restore, with ``resume`` and a checkpoint present
        — the live components.  Idempotent: once the trainer is live
        this is a no-op, so external drivers (the capacity controller,
        :meth:`step_once` callers) can call it freely.  Returns the
        current step."""
        if self._comp is None:
            self._step = self._restore_or_init(resume)
        return self._step

    @property
    def current_step(self) -> int:
        """The step the next :meth:`step_once` will run."""
        return self._step

    def replan_to(self, new_spec, *, checkpoint_first: bool = True) -> None:
        """Synchronous externally-driven re-plan to ``new_spec``
        (:class:`TopologySpec` or ``ParallelPlan``) at the current step
        boundary — the capacity controller's drain-training primitive.
        The boundary checkpoint inside :meth:`_replan` IS the drain;
        failures propagate so the caller can roll back (the checkpoint
        stamp is already restored by then)."""
        self.start()
        self._replan(new_spec, self._step,
                     checkpoint_first=checkpoint_first)

    def step_once(self, batch_fn) -> str:
        """Advance exactly one guarded step (after signal polling).
        Returns ``"ran"``, or ``"preempted"`` when a preempt signal
        checkpointed and stopped the trainer instead.  This is
        :meth:`train`'s loop body exposed so an external driver can
        interleave training steps with fleet ticks."""
        self.start()
        step = self._step
        target = self._poll_signals(step)
        if self._preempt_requested:
            self.checkpoint.wait()
            self._save(step)
            self._preempt_requested = False
            return "preempted"
        if target is not None:
            # a target equal to the current spec is an IN-PLACE
            # rebuild (checkpoint, recompile, identity re-partition)
            # — the device-swap case where counts survive but the
            # hardware underneath changed
            self._replan(target, step)
        comp = self._comp
        res = comp.guard(self._params, self._opt, self._gstate,
                         *batch_fn(step, self.plan),
                         scaler_state=self._sstate, step=step)
        self._params, self._opt = res.params, res.opt_state
        self._gstate, self._sstate = res.guard_state, res.scaler_state
        step = res.next_step
        if self.recorder is not None:
            self.recorder.record("trainer", "step", step=step,
                                 loss=float(res.loss_value),
                                 rolled_back=bool(res.rolled_back))
            if res.rolled_back:
                self.recorder.trigger("guard_rollback", step=step,
                                      loss=float(res.loss_value))
        if step % self.save_every == 0 or res.rolled_back:
            self._save(step)
        self._step = step
        return "ran"

    def train(self, batch_fn, n_steps: int, *, resume: bool = True) -> dict:
        """Run up to ``n_steps`` guarded steps, reacting to signals.

        ``batch_fn(step, plan) -> batch args`` supplies data laid out
        for the CURRENT plan (a constant global batch across plans is
        what makes dp transitions comparable).  Returns a summary dict;
        the live state stays readable as :attr:`params` /
        :attr:`opt_state` / :attr:`guard_state` / :attr:`scaler_state`.
        A hard :class:`Preemption` propagates to the caller — restart
        semantics are a fresh trainer with ``resume=True`` (the
        default), which restores the stamped topology and re-shards.
        """
        self.start(resume)
        status = "completed"
        while self._step < n_steps:
            if self.step_once(batch_fn) == "preempted":
                status = "preempted"
                break
        self._final_step = self._step
        return {"status": status, "step": self._step,
                "replans": self.stats["replans"],
                "preempt_signals": self.stats["preempt_signals"],
                "rollbacks": (self._comp.guard.counters["rollbacks"]
                              if self._comp else 0)}

    # -- live state ----------------------------------------------------------

    @property
    def params(self):
        return self._params

    @property
    def opt_state(self):
        return self._opt

    @property
    def guard_state(self):
        return self._gstate

    @property
    def scaler_state(self):
        return self._sstate

    @property
    def guard(self) -> Optional[GuardedTrainStep]:
        return self._comp.guard if self._comp is not None else None
