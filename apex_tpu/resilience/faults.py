"""Deterministic fault injection for the resilience test harness.

A :class:`FaultInjector` owns a schedule of :class:`Fault` events —
either written out explicitly or generated from a seed + per-kind rates
(:meth:`FaultInjector.from_seed`), so a CI sweep can replay the exact
same failure sequence on every run.  Kinds:

=================== =========================================================
``nan_grads``       every gradient leaf becomes NaN (device-side, in-jit)
``inf_loss``        the loss becomes +inf (device-side, in-jit)
``grad_spike``      gradients scaled by ``magnitude`` (default 64x)
``preempt_at_step`` :class:`Preemption` raised before the step runs — the
                    SIGTERM/maintenance-event analogue
``corrupt_checkpoint`` the checkpoint committed at that step has payload
                    bytes flipped post-commit (a torn write the manifest
                    hash must catch)
``slow_host``       the host sleeps ``magnitude`` seconds before the step
                    (straggler simulation; surfaced in step timings)
``topology_change`` the pod shrinks/grows at that step: ``magnitude`` > 0
                    names the new dp degree, 0 asks the elastic trainer to
                    toggle shrink-to-half / grow-back; consumed via
                    :meth:`FaultInjector.check_topology_change`
``capacity_change`` a train<->serve capacity shift in flight at that step
                    fails: ``magnitude`` selects the failure mode (0/1
                    mid-shift crash, 2 stuck drain, 3 failed re-shard — see
                    ``apex_tpu.resilience.capacity.fault_mode``); consumed
                    via :meth:`FaultInjector.check_capacity_change` by the
                    :class:`~apex_tpu.resilience.capacity.CapacityController`
``dcn_fault``       a cross-pod (DCN) activation/cotangent transfer at that
                    step drops/times out: the MPMD channel raises the
                    retryable :class:`apex_tpu.mpmd.DcnTimeout`; consumed
                    (recorded + removed) via :meth:`FaultInjector.check_dcn`
                    so the engine's resend succeeds
``cost_drift``      the machine's communication profile drifts at that
                    step: ``magnitude`` scales the true link alpha-beta
                    coefficients (0 = default 2x slower; < 1 = links
                    recovering); consumed via
                    :meth:`FaultInjector.check_cost_drift` by the
                    :class:`~apex_tpu.resilience.autopilot.ParallelismAutopilot`,
                    which must DETECT it from refitted telemetry — the
                    fault moves the environment, never the detector
``plan_regression`` the next adopted plan measures slower than predicted:
                    ``magnitude`` inflates the commit-gate step times
                    (0 = default 2x) so the gate must roll the adoption
                    back; consumed via
                    :meth:`FaultInjector.check_plan_regression` when an
                    adoption starts
=================== =========================================================

Every new kind is appended LAST so :meth:`FaultInjector.from_seed`
schedules for the pre-existing kinds are byte-identical to before it
existed — ``seeded_schedule`` consumes no rng state for rate-0 kinds
(asserted by ``tests/test_capacity.py`` for ``capacity_change``,
``tests/test_mpmd.py`` for ``dcn_fault``, and
``tests/test_autopilot.py`` for ``cost_drift``/``plan_regression``).

The in-jit kinds are injected as DATA, not control flow:
:meth:`grad_flags` returns three scalars the guarded train step folds in
with ``jnp.where``, so one compiled program serves both clean and
faulty steps and injection never perturbs compilation caches.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

FAULT_KINDS = ("nan_grads", "inf_loss", "grad_spike", "preempt_at_step",
               "corrupt_checkpoint", "slow_host", "topology_change",
               "capacity_change", "dcn_fault", "cost_drift",
               "plan_regression")

# the serving-side fault kinds live in apex_tpu.serving.fleet
# (SERVING_FAULT_KINDS); its ServingFaultInjector generates schedules
# from the same seeded_schedule stream below — one discipline for
# training-step faults and replica-tick faults


def seeded_schedule(seed: int, n_steps: int, keys, rates) -> list:
    """Shared deterministic event stream: for each step and key IN THE
    GIVEN ORDER, an event fires with probability ``rates[key]`` under
    one ``RandomState(seed)`` stream — same seed, same schedule, always.
    Returns ``[(step, key), ...]``.  A rate of 0.0 consumes no stream
    state, so adding a never-firing kind cannot shift the schedule of
    the others."""
    rng = np.random.RandomState(seed)
    out = []
    for step in range(n_steps):
        for key in keys:
            r = rates.get(key, 0.0)
            if r > 0.0 and rng.uniform() < r:
                out.append((step, key))
    return out


class Preemption(RuntimeError):
    """Raised by :meth:`FaultInjector.check_preempt` — the injected
    equivalent of the scheduler killing the worker.  Train loops let it
    propagate (a real preemption gives no chance to clean up); recovery
    is restart + :meth:`CheckpointManager.restore`."""

    def __init__(self, step: int):
        super().__init__(f"injected preemption at step {step}")
        self.step = step


@dataclasses.dataclass(frozen=True)
class Fault:
    """One scheduled fault.  ``magnitude`` is the spike factor for
    ``grad_spike``, the sleep seconds for ``slow_host``, the target
    dp degree for ``topology_change`` (0 = auto shrink/grow toggle),
    and the failure mode for ``capacity_change``.

    ``once=True`` makes the fault fire a single time: it is removed
    from the schedule when consumed, so steps RE-RUN after a guard
    rollback execute clean — the model of a state-dependent anomaly
    (loss blowup) that the rollback actually cures.  A step-keyed fault
    that re-fires forever would pin a K-consecutive-anomaly rollback in
    a restore/re-fire loop; ``once`` is what lets the day-in-the-life
    sim exercise a rollback that terminates."""
    step: int
    kind: str
    magnitude: float = 0.0
    once: bool = False

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; one of {FAULT_KINDS}")
        if self.step < 0:
            raise ValueError("fault step must be >= 0")


class FaultInjector:
    """Deterministic fault schedule threaded through train + IO paths."""

    def __init__(self, schedule: Iterable[Fault] = ()):
        self.schedule: Tuple[Fault, ...] = tuple(schedule)
        self._by_step: Dict[int, List[Fault]] = {}
        for f in self.schedule:
            self._by_step.setdefault(f.step, []).append(f)
        self.log: List[Tuple[int, str]] = []   # (step, kind) as applied

    @classmethod
    def from_seed(cls, seed: int, n_steps: int,
                  rates: Optional[Dict[str, float]] = None, *,
                  spike_magnitude: float = 64.0,
                  slow_host_s: float = 0.01) -> "FaultInjector":
        """Random-but-reproducible schedule: for each step and kind,
        a fault fires with probability ``rates[kind]`` under a
        ``RandomState(seed)`` stream — same seed, same schedule, always."""
        rates = dict(rates or {})
        bad = set(rates) - set(FAULT_KINDS)
        if bad:
            raise ValueError(f"unknown fault kinds in rates: {sorted(bad)}")
        faults = []
        for step, kind in seeded_schedule(seed, n_steps, FAULT_KINDS,
                                          rates):
            mag = (spike_magnitude if kind == "grad_spike"
                   else slow_host_s if kind == "slow_host" else 0.0)
            faults.append(Fault(step, kind, mag))
        return cls(faults)

    # -- queries -------------------------------------------------------------

    def faults_at(self, step: int) -> Tuple[Fault, ...]:
        return tuple(self._by_step.get(step, ()))

    def _find(self, step: int, kind: str) -> Optional[Fault]:
        for f in self._by_step.get(step, ()):
            if f.kind == kind:
                return f
        return None

    def _consume(self, step: int, kind: str) -> Optional[Fault]:
        """Find + record; ``once`` faults leave the schedule so a
        rolled-back re-run of the same step executes clean."""
        f = self._find(step, kind)
        if f is None:
            return None
        self.record(step, kind)
        if f.once:
            self._by_step[step].remove(f)
        return f

    def record(self, step: int, kind: str) -> None:
        """Append to the applied-fault log (callers record at the point
        the fault actually lands, so the log is the ground truth tests
        assert against)."""
        self.log.append((int(step), kind))

    # -- train-loop hooks ----------------------------------------------------

    def grad_flags(self, step: int) -> Dict[str, float]:
        """The in-jit injection scalars for this step:
        ``{"nan_grads": 0/1, "inf_loss": 0/1, "spike_scale": s}`` —
        identity values (0, 0, 1) on clean steps.  Folded into the
        guarded step with ``jnp.where``; see
        :class:`~apex_tpu.resilience.guard.GuardedTrainStep`."""
        out = {"nan_grads": 0.0, "inf_loss": 0.0, "spike_scale": 1.0}
        if self._consume(step, "nan_grads"):
            out["nan_grads"] = 1.0
        if self._consume(step, "inf_loss"):
            out["inf_loss"] = 1.0
        spike = self._consume(step, "grad_spike")
        if spike:
            out["spike_scale"] = float(spike.magnitude or 64.0)
        return out

    def check_preempt(self, step: int) -> None:
        if self._consume(step, "preempt_at_step"):
            raise Preemption(step)

    def check_topology_change(self, step: int) -> Optional[Fault]:
        """The scheduled ``topology_change`` at ``step``, if any —
        recorded on consumption; the elastic trainer turns it into a
        re-plan BEFORE the step runs (the step executes on the new
        topology, matching a maintenance event's grace window)."""
        f = self._find(step, "topology_change")
        if f is not None:
            self.record(step, "topology_change")
        return f

    def check_capacity_change(self, step: int) -> Optional[Fault]:
        """The scheduled ``capacity_change`` at ``step``, if any —
        consumed (recorded + removed) so one scheduled fault fails one
        shift: the capacity controller's retry after the rollback must
        be able to succeed.  ``magnitude`` selects the failure mode;
        see ``apex_tpu.resilience.capacity.fault_mode``."""
        f = self._find(step, "capacity_change")
        if f is not None:
            self.record(step, "capacity_change")
            self._by_step[step].remove(f)
        return f

    def check_dcn(self, step: int) -> Optional[Fault]:
        """The scheduled ``dcn_fault`` at ``step``, if any — consumed
        (recorded + removed) so one scheduled fault drops one cross-pod
        transfer: the MPMD channel's retry of the same send must be
        able to succeed.  ``magnitude`` is reserved for failure-mode
        selection (0 = dropped/timed-out send)."""
        f = self._find(step, "dcn_fault")
        if f is not None:
            self.record(step, "dcn_fault")
            self._by_step[step].remove(f)
        return f

    def _consume_due(self, step: int, kind: str) -> Optional[Fault]:
        """The EARLIEST scheduled ``kind`` at or before ``step``, if
        any — consumed (recorded at its scheduled step + removed).
        Window-tolerant where :meth:`_consume` is exact-step: the
        autopilot polls at controller ticks, which land between
        training steps, so a fault scheduled "at step 24" must still be
        seen when the poll happens at step 26."""
        for s in sorted(self._by_step):
            if s > step:
                break
            for f in self._by_step[s]:
                if f.kind == kind:
                    self.record(s, kind)
                    self._by_step[s].remove(f)
                    return f
        return None

    def check_cost_drift(self, step: int) -> Optional[Fault]:
        """The scheduled ``cost_drift`` due by ``step``, if any —
        consumed so one scheduled fault drifts the environment once.
        ``magnitude`` scales the drifted environment's alpha-beta
        coefficients relative to the current profile (0 = 2x)."""
        return self._consume_due(step, "cost_drift")

    def check_plan_regression(self, step: int) -> Optional[Fault]:
        """The scheduled ``plan_regression`` due by ``step``, if any —
        consumed at adoption start so one scheduled fault fails one
        commit gate: the autopilot's next adoption after the rollback
        must be able to succeed.  ``magnitude`` inflates the gate's
        measured step times (0 = 2x)."""
        return self._consume_due(step, "plan_regression")

    def maybe_slow_host(self, step: int) -> None:
        f = self._find(step, "slow_host")
        if f:
            self.record(step, "slow_host")
            time.sleep(float(f.magnitude or 0.01))

    # -- checkpoint-IO hook --------------------------------------------------

    def should_corrupt(self, step: int) -> bool:
        """True when the checkpoint committed at ``step`` must be
        corrupted post-commit (the manager calls :meth:`record`
        itself, after the bytes are actually flipped)."""
        return self._find(step, "corrupt_checkpoint") is not None
