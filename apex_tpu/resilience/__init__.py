"""Fault tolerance for production training and serving (ISSUE 4, 9).

Four layers, composable but independently usable:

* :mod:`~apex_tpu.resilience.checkpoint` — atomic, content-hashed,
  shard-aware checkpointing with a ``latest``-symlink commit protocol
  and async double-buffered writes (:class:`CheckpointManager`).
* :mod:`~apex_tpu.resilience.guard` — :class:`GuardedTrainStep`, the
  generalization of the amp loss-scaler's overflow skip: NaN/inf and
  grad-norm-spike steps are skipped on-device, K consecutive anomalies
  trigger rollback to the last complete checkpoint.
* :mod:`~apex_tpu.resilience.faults` — :class:`FaultInjector`, a
  deterministic seeded fault schedule (``nan_grads``, ``inf_loss``,
  ``grad_spike``, ``preempt_at_step``, ``corrupt_checkpoint``,
  ``slow_host``, ``topology_change``) threaded through the train loop
  and checkpoint IO so every recovery path is exercised by tests and
  ``tools/crash_matrix.py``.
* :mod:`~apex_tpu.resilience.elastic` — preemption-native elastic
  training: :class:`TopologySpec`/:class:`ElasticPlan` layout
  descriptors (stamped into checkpoint manifests),
  :func:`reshard_optimizer_state` (ZeRO gather-to-logical → re-split
  and per-leaf slot re-layout across dp/tp/pp changes, f32 bitwise),
  and :class:`ElasticTrainer`, the signal-driven drain → checkpoint →
  re-plan → re-shard → resume loop around :class:`GuardedTrainStep`.
* :mod:`~apex_tpu.resilience.capacity` — the train+serve capacity
  loop: :class:`CapacityController` shifts chips between an
  :class:`ElasticTrainer` and a serving fleet on SLO burn, with
  hysteresis + cooldown, a two-phase shift protocol with rollback, and
  ``capacity_change`` fault injection (proven by
  ``tools/day_in_life.py``).
* :mod:`~apex_tpu.resilience.autopilot` — self-driving parallelism:
  :class:`ParallelismAutopilot` refits the CostModel from production
  telemetry, debounces drift, re-ranks the plan space against the
  refreshed profile, and adopts the winner through a measured
  baseline→drain→commit gate with rollback (``cost_drift`` /
  ``plan_regression`` fault injection, flap-free audit).
"""

from apex_tpu.resilience.autopilot import (ADOPTION_OUTCOMES,
                                           ParallelismAutopilot)

from apex_tpu.resilience.capacity import (CAPACITY_FAULT_MODES,
                                          CapacityBudget,
                                          CapacityController,
                                          PoolCapacityController,
                                          ReshardFailed, fault_mode)
from apex_tpu.resilience.checkpoint import (CheckpointManager,
                                            CheckpointNotFound)
from apex_tpu.resilience.elastic import (ElasticComponents, ElasticPlan,
                                         ElasticSignal, ElasticTrainer,
                                         HostSignals, TopologySpec,
                                         ZeROGuardAdapter,
                                         reshard_optimizer_state)
from apex_tpu.resilience.faults import (FAULT_KINDS, Fault, FaultInjector,
                                        Preemption)
from apex_tpu.resilience.guard import (GuardedTrainStep, GuardState,
                                       StepResult)

__all__ = [
    "ADOPTION_OUTCOMES",
    "ParallelismAutopilot",
    "CAPACITY_FAULT_MODES",
    "CapacityBudget",
    "CapacityController",
    "PoolCapacityController",
    "ReshardFailed",
    "fault_mode",
    "CheckpointManager",
    "CheckpointNotFound",
    "ElasticComponents",
    "ElasticPlan",
    "ElasticSignal",
    "ElasticTrainer",
    "FAULT_KINDS",
    "Fault",
    "FaultInjector",
    "HostSignals",
    "Preemption",
    "GuardedTrainStep",
    "GuardState",
    "StepResult",
    "TopologySpec",
    "ZeROGuardAdapter",
    "reshard_optimizer_state",
]
