"""Fault tolerance for production training and serving (ISSUE 4).

Three layers, composable but independently usable:

* :mod:`~apex_tpu.resilience.checkpoint` — atomic, content-hashed,
  shard-aware checkpointing with a ``latest``-symlink commit protocol
  and async double-buffered writes (:class:`CheckpointManager`).
* :mod:`~apex_tpu.resilience.guard` — :class:`GuardedTrainStep`, the
  generalization of the amp loss-scaler's overflow skip: NaN/inf and
  grad-norm-spike steps are skipped on-device, K consecutive anomalies
  trigger rollback to the last complete checkpoint.
* :mod:`~apex_tpu.resilience.faults` — :class:`FaultInjector`, a
  deterministic seeded fault schedule (``nan_grads``, ``inf_loss``,
  ``grad_spike``, ``preempt_at_step``, ``corrupt_checkpoint``,
  ``slow_host``) threaded through the train loop and checkpoint IO so
  every recovery path is exercised by tests and
  ``tools/crash_matrix.py``.
"""

from apex_tpu.resilience.checkpoint import (CheckpointManager,
                                            CheckpointNotFound)
from apex_tpu.resilience.faults import (FAULT_KINDS, Fault, FaultInjector,
                                        Preemption)
from apex_tpu.resilience.guard import (GuardedTrainStep, GuardState,
                                       StepResult)

__all__ = [
    "CheckpointManager",
    "CheckpointNotFound",
    "FAULT_KINDS",
    "Fault",
    "FaultInjector",
    "Preemption",
    "GuardedTrainStep",
    "GuardState",
    "StepResult",
]
