"""fp16_utils — TPU rebuild of the legacy ``apex/fp16_utils`` package.

Pre-amp static mixed precision: manual half casts + fp32 master params +
(dynamic) loss scaling.  On TPU the half type defaults to bf16.  The modern
path is ``apex_tpu.amp``; this module keeps the legacy surface
(``network_to_half``, ``prep_param_lists``, ``master_params_to_model_params``,
``FP16_Optimizer``) for recipes written against it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import LossScaler as _AmpLossScaler
from apex_tpu.amp.frontend import _is_norm_param

__all__ = [
    "network_to_half",
    "BN_convert_float",
    "prep_param_lists",
    "master_params_to_model_params",
    "model_grads_to_master_grads",
    "FP16_Optimizer",
    "LossScaler",
    "DynamicLossScaler",
]


def network_to_half(params, half_dtype=jnp.bfloat16):
    """Cast float params to half, keeping normalization params fp32
    (reference: ``apex/fp16_utils/fp16util.py::network_to_half`` +
    ``BN_convert_float``)."""
    def cast(path, x):
        if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
            return x
        if _is_norm_param(jax.tree_util.keystr(path)):
            return jnp.asarray(x, jnp.float32)
        return jnp.asarray(x, half_dtype)
    return jax.tree_util.tree_map_with_path(cast, params)


def BN_convert_float(params):
    """Force normalization params back to fp32."""
    def cast(path, x):
        if (jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
                and _is_norm_param(jax.tree_util.keystr(path))):
            return jnp.asarray(x, jnp.float32)
        return x
    return jax.tree_util.tree_map_with_path(cast, params)


def prep_param_lists(params):
    """Return ``(model_params, master_params)`` — fp32 master copies
    (reference: ``fp16util.py::prep_param_lists``; the flat-buffer variant is
    what the packed optimizer state already does)."""
    master = jax.tree_util.tree_map(
        lambda x: (jnp.asarray(x, jnp.float32)
                   if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
                   else x), params)
    return params, master


def master_params_to_model_params(model_params, master_params):
    """Copy master values into the model-precision pytree."""
    return jax.tree_util.tree_map(
        lambda mp, m: m.astype(mp.dtype), model_params, master_params)


def model_grads_to_master_grads(model_grads):
    """Upcast model-precision grads to fp32 master grads."""
    return jax.tree_util.tree_map(
        lambda g: g.astype(jnp.float32), model_grads)


LossScaler = _AmpLossScaler


class DynamicLossScaler(_AmpLossScaler):
    """Legacy alias: always-dynamic scaler
    (reference: ``apex/fp16_utils/loss_scaler.py::DynamicLossScaler``)."""

    def __init__(self, init_scale=2.0 ** 32, scale_factor=2.0,
                 scale_window=1000):
        super().__init__("dynamic", init_scale=init_scale,
                         scale_factor=scale_factor,
                         scale_window=scale_window)


class FP16_Optimizer:
    """Legacy wrapper (reference: ``fp16_optimizer.py``): fused optimizer +
    fp32 master weights + loss scaling in one object.

    Functional usage::

        opt = FP16_Optimizer(FusedAdam(lr=1e-3), dynamic_loss_scale=True)
        state = opt.init(params)            # master copies + scaler state
        params, state = opt.step(grads, params, state)
    """

    def __init__(self, init_optimizer, static_loss_scale=1.0,
                 dynamic_loss_scale=False, dynamic_loss_args=None,
                 verbose=False):
        self.optimizer = init_optimizer
        self.optimizer.master_weights = True
        args = dynamic_loss_args or {}
        self.loss_scaler = (_AmpLossScaler("dynamic", **args)
                            if dynamic_loss_scale
                            else _AmpLossScaler(static_loss_scale))

    def init(self, params):
        return {"optimizer": self.optimizer.init(params),
                "loss_scaler": self.loss_scaler.init()}

    def scale_loss(self, loss, state):
        return self.loss_scaler.scale(loss, state["loss_scaler"])

    def step(self, grads, params, state, lr=None):
        sstate = state["loss_scaler"]
        finf = _AmpLossScaler.found_inf(grads)
        new_params, new_opt = self.optimizer.step(
            grads, params, state["optimizer"], lr=lr,
            grad_scale=1.0 / sstate.loss_scale,
            noop_flag=finf.astype(jnp.int32))
        return new_params, {
            "optimizer": new_opt,
            "loss_scaler": self.loss_scaler.update(sstate, finf)}
