"""FusedLayerNorm / FusedRMSNorm modules — TPU rebuild of
``apex/normalization/fused_layer_norm.py``.

Modules are lightweight and functional (params are explicit pytrees):
``m = FusedLayerNorm(hidden); params = m.init_params(); y = m(params, x)``.
``MixedFused*`` keeps params fp32 with fp16/bf16 IO (apex's
``MixedFusedLayerNorm``, used by ``apex/transformer/layers/layer_norm.py``).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from apex_tpu.ops.layer_norm import (
    fused_layer_norm_affine,
    fused_layer_norm,
    fused_rms_norm_affine,
    fused_rms_norm,
)

__all__ = [
    "FusedLayerNorm",
    "FusedRMSNorm",
    "MixedFusedLayerNorm",
    "MixedFusedRMSNorm",
]


def _normalize_shape(normalized_shape):
    if isinstance(normalized_shape, (int, np.integer)):
        return (int(normalized_shape),)
    return tuple(int(d) for d in normalized_shape)


class FusedLayerNorm:
    """Layer norm over the trailing ``normalized_shape`` dims.

    Parity: ``apex.normalization.FusedLayerNorm(normalized_shape, eps,
    elementwise_affine, memory_efficient)``.
    """

    rms = False

    def __init__(self, normalized_shape, eps=1e-5, elementwise_affine=True,
                 memory_efficient=False, param_dtype=jnp.float32):
        self.normalized_shape = _normalize_shape(normalized_shape)
        self.eps = float(eps)
        self.elementwise_affine = bool(elementwise_affine)
        self.memory_efficient = bool(memory_efficient)
        self.param_dtype = param_dtype

    def init_params(self):
        if not self.elementwise_affine:
            return {}
        p = {"weight": jnp.ones(self.normalized_shape, self.param_dtype)}
        if not self.rms:
            p["bias"] = jnp.zeros(self.normalized_shape, self.param_dtype)
        return p

    def __call__(self, params, x):
        if self.elementwise_affine:
            if self.rms:
                return fused_rms_norm_affine(
                    x, params["weight"], self.normalized_shape, self.eps,
                    self.memory_efficient)
            return fused_layer_norm_affine(
                x, params["weight"], params["bias"], self.normalized_shape,
                self.eps, self.memory_efficient)
        if self.rms:
            return fused_rms_norm(x, self.normalized_shape, self.eps)
        return fused_layer_norm(x, self.normalized_shape, self.eps)

    apply = __call__


class FusedRMSNorm(FusedLayerNorm):
    """RMSNorm (no mean subtraction, no bias) — apex ``FusedRMSNorm``."""

    rms = True


class MixedFusedLayerNorm(FusedLayerNorm):
    """fp32 params with low-precision IO (apex ``MixedFusedLayerNorm``)."""

    def __init__(self, normalized_shape, eps=1e-5, **kwargs):
        kwargs.pop("elementwise_affine", None)
        super().__init__(normalized_shape, eps=eps, elementwise_affine=True,
                         param_dtype=jnp.float32, **kwargs)

    def __call__(self, params, x):
        y = super().__call__(params, x)
        return y.astype(x.dtype)

    apply = __call__


class MixedFusedRMSNorm(MixedFusedLayerNorm):
    rms = True
