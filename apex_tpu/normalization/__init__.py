from apex_tpu.normalization.fused_layer_norm import (
    FusedLayerNorm,
    FusedRMSNorm,
    MixedFusedLayerNorm,
    MixedFusedRMSNorm,
)
from apex_tpu.ops.layer_norm import (
    fused_layer_norm_affine,
    fused_layer_norm,
    fused_rms_norm_affine,
    fused_rms_norm,
)

__all__ = [
    "FusedLayerNorm",
    "FusedRMSNorm",
    "MixedFusedLayerNorm",
    "MixedFusedRMSNorm",
    "fused_layer_norm_affine",
    "fused_layer_norm",
    "fused_rms_norm_affine",
    "fused_rms_norm",
]
