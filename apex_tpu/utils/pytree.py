"""Small pytree utilities shared across the package."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def tree_size(tree) -> int:
    """Total number of elements across all leaves."""
    return sum(x.size for x in jax.tree_util.tree_leaves(tree))


def tree_cast(tree, dtype):
    """Cast every inexact leaf of a pytree to ``dtype``."""
    def cast(x):
        x = jnp.asarray(x)
        if jnp.issubdtype(x.dtype, jnp.inexact):
            return x.astype(dtype)
        return x
    return jax.tree_util.tree_map(cast, tree)


def tree_zeros_like(tree, dtype=None):
    return jax.tree_util.tree_map(
        lambda x: jnp.zeros(x.shape, dtype or x.dtype), tree
    )
