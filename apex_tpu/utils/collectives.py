"""Shared collective helpers."""

from __future__ import annotations

import jax


def ensure_varying(x, axis_name):
    """Idempotently mark ``x`` device-varying over ``axis_name``.

    JAX 0.9 collectives require varying (vma-tracked) inputs inside
    ``shard_map``; ``pcast`` raises when the value is already varying, so
    this is the safe form for values of unknown provenance.  Pytree-aware.
    On JAX versions without ``pcast`` (pre-vma) every value is implicitly
    varying and the cast is a no-op.
    """
    def cast(v):
        try:
            return jax.lax.pcast(v, axis_name, to="varying")
        except ValueError:
            return v
        except AttributeError:
            return v
    return jax.tree_util.tree_map(cast, x)


def shard_map_compat(fn, *, mesh, in_specs, out_specs, check: bool = False,
                     check_vma=None, check_rep=None):
    """``shard_map`` across the supported JAX version span.

    JAX 0.6+ exposes ``jax.shard_map`` whose consistency knob is
    ``check_vma``; 0.4.x keeps it under ``jax.experimental.shard_map``
    with the older ``check_rep`` spelling.  ``check=False`` (the default
    here) is what every explicit-collective region in this package needs:
    gathered-but-replicated values fail both checkers' static inference.
    ``check_vma``/``check_rep`` are accepted as aliases of ``check`` so
    call sites written against either real API drop in unchanged.
    """
    if check_vma is not None:
        check = check_vma
    elif check_rep is not None:
        check = check_rep
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        try:
            return sm(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=check)
        except TypeError:  # jax.shard_map generations with check_rep
            return sm(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check)
    from jax.experimental.shard_map import shard_map as esm
    return esm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check)


def axis_size(axis_name):
    """``jax.lax.axis_size`` with a pre-0.6 fallback (``psum`` of the
    constant 1 is folded to the axis size without a real collective)."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)


def manual_axes() -> frozenset:
    """The current trace's ``shard_map`` manual mesh axes (empty outside
    one, or when the JAX version lacks the query)."""
    try:
        return frozenset(jax.sharding.get_abstract_mesh().manual_axes)
    except (AttributeError, TypeError):
        return frozenset()


def is_varying(x, axis_name) -> bool:
    """True if ``x`` is device-varying over ``axis_name`` (JAX 0.9 vma
    tracking).  vma only exists for ``shard_map`` *manual* mesh axes; for a
    vmap/pmap axis (or outside any trace) the notion doesn't apply, so
    report True and let callers fall through to the normal collective."""
    if axis_name not in manual_axes():
        return True
    return axis_name in jax.typeof(x).vma


def psum_if_varying(tree, axis_name, strict: bool = False):
    """``psum`` only the leaves that are actually device-varying.

    An *invariant* leaf inside ``shard_map`` holds the same value on every
    device — for gradients that means it was already cross-device reduced
    (JAX auto-psums grads of replicated inputs), and psumming it again
    would multiply by axis size.  Such leaves pass through unchanged,
    treated as ALREADY-SUMMED: callers that average afterwards still divide
    them by axis size.  Pass a value that is replicated-but-not-a-sum and
    that division is wrong — these helpers are for gradients only.

    ``strict=True`` makes that contract loud: any invariant leaf raises
    instead of silently passing through, for callers who expect every leaf
    to be a locally-computed (varying) gradient.
    """
    def one(path, v):
        if is_varying(v, axis_name):
            return jax.lax.psum(v, axis_name)
        if strict:
            raise ValueError(
                f"psum_if_varying(strict=True): leaf {jax.tree_util.keystr(path)} "
                f"is device-invariant over axis {axis_name!r}; it would be "
                "passed through as an already-summed gradient. If this leaf "
                "is not a gradient, do not route it through this helper.")
        return v
    return jax.tree_util.tree_map_with_path(one, tree)


def sds_like(shape, dtype, like):
    """ShapeDtypeStruct for a ``pallas_call`` output, vma-aware.

    Inside ``shard_map`` (manual mesh axes) JAX 0.9 requires the output's
    varying-axes set; inherit it from a representative input so kernels
    work standalone AND inside explicit-collective regions.  Under a
    ``vmap``/``scan`` trace inside the region the batched aval can lose
    its vma — fall back to "varying over every manual axis", the only
    sound upper bound there.
    """
    ma = manual_axes()
    if not ma:
        return jax.ShapeDtypeStruct(shape, dtype)
    vma = getattr(jax.typeof(like), "vma", None)
    if vma is None:
        vma = frozenset(ma)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
