"""Shared collective helpers."""

from __future__ import annotations

import jax


def ensure_varying(x, axis_name):
    """Idempotently mark ``x`` device-varying over ``axis_name``.

    JAX 0.9 collectives require varying (vma-tracked) inputs inside
    ``shard_map``; ``pcast`` raises when the value is already varying, so
    this is the safe form for values of unknown provenance.  Pytree-aware.
    """
    def cast(v):
        try:
            return jax.lax.pcast(v, axis_name, to="varying")
        except ValueError:
            return v
    return jax.tree_util.tree_map(cast, x)
