"""Block-quantized collectives — EQuARX-style compressed all-reduce.

EQuARX (arXiv:2506.17615) shows that the dominant cost of data-parallel
gradient all-reduce on TPU ICI is wire bytes, and that block-quantized
int8 transport with full-precision accumulation recovers most of it at
negligible quality loss.  This module is that design over the package's
native ``(rows, 128)`` packed-bucket layout:

* the all-reduce is decomposed into reduce-scatter + all-gather (the
  same decomposition the ZeRO optimizer uses for its sharded update);
* each hop's payload is quantized per LANE=128-element block — one int8
  value per element plus one f32 scale per block (~8.25 bits/element,
  a ~3.9x wire-byte reduction vs f32, ~1.9x vs bf16);
* dequantization and the cross-replica SUM always run in f32 ("quantized
  transport, f32 accumulation"), so error comes only from the rounding
  of each payload, never from low-precision accumulation.

The ``allreduce_dtype`` knob shared by
:class:`~apex_tpu.parallel.DistributedDataParallel` and the distributed
optimizers selects the transport:

=============  ==========================================================
``None``/f32   plain ``psum``/``psum_scatter`` — bitwise-identical to the
               uncompressed path (the safe default)
``bf16``       bf16 payload, f32 accumulation (~2x fewer wire bytes;
               error = one bf16 rounding per element per hop)
``int8``       per-block int8 + f32 scale, f32 accumulation (~3.9x fewer
               wire bytes; observed grad-bucket max relative error vs the
               block max ~0.8% per hop — see tests)
=============  ==========================================================

Implementation note: the quantized reduce-scatter is an ``all_to_all`` of
quantized shards followed by a local f32 tree-sum, i.e. ONE quantization
per producer (not one per ring hop) — on an ICI torus XLA lowers
all-to-all to the same bisection traffic a ring reduce-scatter uses, and
a single quantization is both faster and lower-error than requantizing
at every hop.  Collective inputs/outputs keep shapes static: callers pad
to ``world_size``-divisible rows (:func:`pad_rows`), zero padding rows
quantize to exact zeros, and the f32 accumulation keeps them zero.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply.bucketing import LANE

_f32 = jnp.float32

#: transports accepted by every ``allreduce_dtype`` knob
MODES = (None, "f32", "bf16", "int8")


def check_mode(mode):
    """Normalize/validate an ``allreduce_dtype`` value (None == "f32")."""
    if mode in (None, "f32", jnp.float32):
        return None
    if mode in ("bf16", jnp.bfloat16):
        return "bf16"
    if mode in ("int8", jnp.int8):
        return "int8"
    raise ValueError(
        f"allreduce_dtype={mode!r} not supported; choose one of "
        "None/'f32' (exact), 'bf16', 'int8'")


# -- per-block int8 codec ----------------------------------------------------

def quantize_int8(x):
    """Symmetric per-block int8 quantization over the last axis.

    ``x`` is any float array whose last axis is the quantization block
    (the packed buffers use LANE=128).  Returns ``(q, scale)`` with ``q``
    int8 in [-127, 127] and ``scale`` f32 shaped like ``x`` with the last
    axis reduced to 1.  All-zero blocks get scale 1 so they round-trip to
    exact zeros.
    """
    x = x.astype(_f32)
    scale = jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 127.0
    scale = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(_f32)


def dequantize_int8(q, scale):
    """f32 reconstruction of :func:`quantize_int8` output."""
    return q.astype(_f32) * scale


def pad_rows(x, multiple: int):
    """Zero-pad axis 0 of ``(rows, LANE)`` to a multiple (static shape)."""
    rows = x.shape[0]
    target = -(-rows // multiple) * multiple
    if target == rows:
        return x
    return jnp.pad(x, ((0, target - rows), (0, 0)))


# -- collectives (call inside shard_map over ``axis_name``) ------------------

def _all_to_all_rows(x, axis_name):
    return jax.lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0,
                              tiled=True)


def reduce_scatter(x, axis_name, world_size: int, mode=None):
    """Reduce-scatter a packed ``(rows, 128)`` buffer over ``axis_name``.

    ``rows`` must be divisible by ``world_size``; returns the caller's
    ``(rows / world_size, 128)`` shard of the cross-replica SUM, in
    ``x.dtype``.  ``mode=None``/``"f32"`` is ``lax.psum_scatter`` —
    bitwise-identical to the uncompressed path.  The quantized modes
    transport compressed payloads via all-to-all and accumulate the
    ``world_size`` dequantized shards in f32.
    """
    mode = check_mode(mode)
    rows = x.shape[0]
    if rows % world_size:
        raise ValueError(
            f"reduce_scatter: rows={rows} not divisible by "
            f"world_size={world_size}; pad with pad_rows() first")
    if mode is None:
        return jax.lax.psum_scatter(x, axis_name, scatter_dimension=0,
                                    tiled=True)
    local = rows // world_size
    if mode == "bf16":
        payload = _all_to_all_rows(x.astype(jnp.bfloat16), axis_name)
        parts = payload.astype(_f32)
    else:  # int8
        q, s = quantize_int8(x)
        q = _all_to_all_rows(q, axis_name)
        s = _all_to_all_rows(s, axis_name)
        parts = dequantize_int8(q, s)
    total = jnp.sum(parts.reshape(world_size, local, x.shape[1]), axis=0)
    return total.astype(x.dtype)


def all_gather_rows(x, axis_name, mode=None):
    """All-gather shards along axis 0, optionally with compressed payload.

    The inverse of :func:`reduce_scatter`'s layout: every rank contributes
    its ``(local_rows, 128)`` shard and receives the ``(world * local_rows,
    128)`` concatenation.  Quantized modes compress the outgoing shard
    once; the gathered result is dequantized to ``x.dtype``.
    """
    mode = check_mode(mode)
    if mode is None:
        return jax.lax.all_gather(x, axis_name, axis=0, tiled=True)
    if mode == "bf16":
        g = jax.lax.all_gather(x.astype(jnp.bfloat16), axis_name, axis=0,
                               tiled=True)
        return g.astype(x.dtype)
    q, s = quantize_int8(x)
    q = jax.lax.all_gather(q, axis_name, axis=0, tiled=True)
    s = jax.lax.all_gather(s, axis_name, axis=0, tiled=True)
    return dequantize_int8(q, s).astype(x.dtype)


def psum_compressed(x, axis_name, world_size: int, mode=None):
    """All-reduce (SUM) one array with compressed transport.

    Arbitrary shape/float dtype; result has ``x``'s shape and dtype.
    ``mode=None``/``"f32"`` is a plain ``lax.psum``.  Otherwise the leaf
    is flattened into LANE-blocks padded to ``world_size`` rows, reduce-
    scattered (quantized transport, f32 accumulation), and the reduced
    shard is re-quantized once for the all-gather — two quantizations
    total, matching EQuARX's per-direction cost.
    """
    mode = check_mode(mode)
    if mode is None:
        return jax.lax.psum(x, axis_name)
    flat = jnp.ravel(x).astype(_f32)
    n = flat.shape[0]
    rows = -(-n // LANE)
    flat = jnp.pad(flat, (0, rows * LANE - n)).reshape(rows, LANE)
    flat = pad_rows(flat, world_size)
    shard = reduce_scatter(flat, axis_name, world_size, mode)
    full = all_gather_rows(shard, axis_name, mode)
    out = jnp.ravel(full)[:n].reshape(x.shape)
    return out.astype(x.dtype)


def psum_tree_compressed(tree, axis_name, world_size: int, mode=None,
                         strict: bool = False):
    """Compressed :func:`~apex_tpu.utils.collectives.psum_if_varying`.

    Same gradient-only contract: device-invariant leaves (already-summed
    grads under vma tracking) pass through unchanged — ``strict=True``
    raises on them — and varying leaves take :func:`psum_compressed`.
    Non-float leaves always take the exact ``psum`` path (quantizing
    integer counters would corrupt them).
    """
    from apex_tpu.utils.collectives import is_varying

    mode = check_mode(mode)

    def one(path, v):
        if not is_varying(v, axis_name):
            if strict:
                raise ValueError(
                    "psum_tree_compressed(strict=True): leaf "
                    f"{jax.tree_util.keystr(path)} is device-invariant "
                    f"over axis {axis_name!r}")
            return v
        if mode is None or not jnp.issubdtype(v.dtype, jnp.floating):
            return jax.lax.psum(v, axis_name)
        return psum_compressed(v, axis_name, world_size, mode)

    return jax.tree_util.tree_map_with_path(one, tree)
