from apex_tpu.utils.platform import (
    is_tpu_backend,
    use_pallas,
    set_force_pallas,
    interpret_mode,
)
from apex_tpu.utils.pytree import (
    tree_size,
    tree_cast,
    tree_zeros_like,
)
from apex_tpu.utils.compressed_allreduce import (
    psum_compressed,
    psum_tree_compressed,
)

__all__ = [
    "psum_compressed",
    "psum_tree_compressed",
    "is_tpu_backend",
    "use_pallas",
    "set_force_pallas",
    "interpret_mode",
    "tree_size",
    "tree_cast",
    "tree_zeros_like",
]
