"""ctypes loader for the native host runtime (``apex_tpu/csrc``).

The reference builds its host-side machinery as C++ extensions (apex_C,
gpu_direct_storage, …) flag-gated in setup.py.  Here the library is a
plain C-ABI shared object: ``pip install`` with ``APEX_TPU_CPP_EXT=1``
builds it, and as a developer convenience this loader will also compile
it on first use with g++ into the package directory.  Every caller must
tolerate ``lib() is None`` (pure-Python fallback) — the native path is a
host-side performance feature, never a correctness requirement.
"""

from __future__ import annotations

import ctypes
import glob
import os
import subprocess
import threading

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.dirname(__file__)), "csrc",
                    "host_runtime.cpp")
_BUILT = os.path.join(os.path.dirname(_SRC), "libapex_host_runtime.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _configure(lib) -> bool:
    try:
        lib.apex_version.restype = ctypes.c_int
        if lib.apex_version() != 1:
            return False
        lib.apex_pack.restype = ctypes.c_int
        lib.apex_pack.argtypes = [ctypes.POINTER(ctypes.c_void_p),
                                  ctypes.POINTER(ctypes.c_size_t),
                                  ctypes.c_int, ctypes.c_void_p]
        lib.apex_unpack.restype = ctypes.c_int
        lib.apex_unpack.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_void_p),
                                    ctypes.POINTER(ctypes.c_size_t),
                                    ctypes.c_int]
        lib.apex_file_write.restype = ctypes.c_int
        lib.apex_file_write.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                        ctypes.c_size_t, ctypes.c_int]
        lib.apex_file_read.restype = ctypes.c_int
        lib.apex_file_read.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                       ctypes.c_size_t, ctypes.c_int]
        return True
    except AttributeError:
        return False


def _try_load(path):
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    return lib if _configure(lib) else None


def _compile() -> str | None:
    if not os.path.exists(_SRC):
        return None
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
             _SRC, "-o", _BUILT],
            check=True, capture_output=True, timeout=120)
        return _BUILT
    except (OSError, subprocess.SubprocessError):
        return None


def lib():
    """The loaded native library, or None (use the Python fallback)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        if os.environ.get("APEX_TPU_NO_NATIVE"):
            _tried = True
            return None
        # 1. already built (pip build or a previous on-demand compile)
        candidates = [_BUILT] + glob.glob(
            os.path.join(os.path.dirname(_SRC), "*.so"))
        for c in candidates:
            if os.path.exists(c):
                _lib = _try_load(c)
                if _lib is not None:
                    _tried = True
                    return _lib
        # 2. on-demand compile (developer path)
        built = _compile()
        if built:
            _lib = _try_load(built)
        _tried = True
        return _lib


def _as_1d_bytes(a: np.ndarray) -> np.ndarray:
    a = np.ascontiguousarray(a)
    return a.view(np.uint8).reshape(-1)


def pack(arrays, out: np.ndarray | None = None) -> np.ndarray:
    """Gather a list of numpy arrays into one contiguous uint8 buffer.

    Native path releases the GIL and memcpys with all host cores; the
    fallback is np.concatenate.  This is the host-side stage of bucket
    packing (device-side packing stays inside jit — see
    ``multi_tensor_apply.bucketing``).
    """
    views = [_as_1d_bytes(a) for a in arrays]
    total = int(sum(v.size for v in views))
    if out is None:
        out = np.empty((total,), np.uint8)
    else:
        assert out.dtype == np.uint8 and out.size == total
    L = lib()
    if L is None:
        off = 0
        for v in views:
            out[off:off + v.size] = v
            off += v.size
        return out
    n = len(views)
    srcs = (ctypes.c_void_p * n)(*[v.ctypes.data for v in views])
    sizes = (ctypes.c_size_t * n)(*[v.size for v in views])
    rc = L.apex_pack(srcs, sizes, n, out.ctypes.data)
    if rc != 0:
        raise OSError(-rc, f"apex_pack failed: {rc}")
    return out


def unpack(buf: np.ndarray, arrays) -> None:
    """Scatter a contiguous uint8 buffer back into the given arrays."""
    views = [_as_1d_bytes(a) for a in arrays]
    # _as_1d_bytes may copy non-contiguous inputs; require contiguous so
    # the scatter lands in the caller's memory
    for a, v in zip(arrays, views):
        if a.__array_interface__["data"][0] != \
                v.__array_interface__["data"][0]:
            raise ValueError("unpack needs contiguous destination arrays")
    buf = np.ascontiguousarray(buf).view(np.uint8).reshape(-1)
    L = lib()
    if L is None:
        off = 0
        for v in views:
            v[:] = buf[off:off + v.size]
            off += v.size
        return
    n = len(views)
    dsts = (ctypes.c_void_p * n)(*[v.ctypes.data for v in views])
    sizes = (ctypes.c_size_t * n)(*[v.size for v in views])
    rc = L.apex_unpack(buf.ctypes.data, dsts, sizes, n)
    if rc != 0:
        raise OSError(-rc, f"apex_unpack failed: {rc}")


def file_write(path: str, buf: np.ndarray, threads: int = 4) -> None:
    """Write a contiguous buffer to ``path`` (parallel pwrite natively)."""
    v = _as_1d_bytes(buf)
    L = lib()
    if L is None:
        with open(path, "wb") as f:
            f.write(v.tobytes())
        return
    rc = L.apex_file_write(path.encode(), v.ctypes.data, v.size,
                           int(threads))
    if rc != 0:
        raise OSError(-rc, f"apex_file_write({path}) failed")


def file_read(path: str, nbytes: int | None = None,
              threads: int = 4) -> np.ndarray:
    """Read ``path`` into a fresh uint8 buffer (parallel pread natively)."""
    size = os.path.getsize(path) if nbytes is None else int(nbytes)
    out = np.empty((size,), np.uint8)
    L = lib()
    if L is None:
        with open(path, "rb") as f:
            data = f.read(size)
        out[:] = np.frombuffer(data, np.uint8)
        return out
    rc = L.apex_file_read(path.encode(), out.ctypes.data, size,
                          int(threads))
    if rc != 0:
        raise OSError(-rc, f"apex_file_read({path}) failed")
    return out
