"""Profiling + observability utilities (SURVEY §5).

The reference's op-level tracing was ``apex.pyprof`` (removed upstream)
plus scattered ``torch.cuda.nvtx.range_push/pop`` annotations read by
Nsight.  The TPU equivalents wired here:

* :func:`annotate` / :func:`range_push` / :func:`range_pop` —
  ``jax.named_scope`` as the nvtx analogue; scope names survive into XLA
  HLO metadata and show up in profiler traces.
* :func:`trace` — ``jax.profiler.trace`` wrapper (TensorBoard-readable).
* :func:`memory_stats` — compiled-program memory analysis (argument /
  output / temp bytes), the measurement tool for the pipeline engine's
  activation-residency claims.
* :func:`program_hash` / :func:`assert_same_program` — the survey's
  multi-controller race-safety replacement: XLA programs are data-race
  free, so the remaining divergence risk is hosts compiling DIFFERENT
  programs; hash the optimized HLO and compare.
* :class:`ServingMetrics` — inference-serving observability (TTFT,
  per-token latency, slot occupancy, tokens/s) for
  ``apex_tpu.inference``'s continuous-batching engine.
"""

from __future__ import annotations

import contextlib
import hashlib
import threading
import time
from typing import Any, Callable

import jax

named_scope = jax.named_scope        # re-export: the nvtx range analogue

_SCOPES = threading.local()          # per-thread stack: pops must nest


def range_push(name: str) -> None:
    """``torch.cuda.nvtx.range_push`` equivalent (paired with
    :func:`range_pop`); prefer the :func:`annotate` context manager.

    The push/pop stack is per-thread and pops must nest within their
    thread — interleaving pairs across threads is undefined, as it was
    for nvtx ranges.
    """
    cm = jax.named_scope(name)
    cm.__enter__()
    if not hasattr(_SCOPES, "stack"):
        _SCOPES.stack = []
    _SCOPES.stack.append(cm)


def range_pop() -> None:
    stack = getattr(_SCOPES, "stack", None)
    if stack:
        stack.pop().__exit__(None, None, None)


@contextlib.contextmanager
def annotate(name: str):
    """Named-scope context manager: ops traced inside carry ``name`` in
    their HLO metadata (visible in xprof/TensorBoard)."""
    with jax.named_scope(name):
        yield


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_link: bool = False):
    """Capture a device trace viewable in TensorBoard (``jax.profiler``)."""
    jax.profiler.start_trace(log_dir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def memory_stats(fn: Callable, *args, static_argnums=(), **kwargs) -> dict:
    """Compile ``fn`` for the given args and return its memory analysis.

    Returns a dict with ``argument``, ``output``, ``temp``, ``alias`` and
    ``generated_code`` sizes in bytes.  ``temp`` is the interesting one
    for remat/pipeline decisions: it is XLA's peak scratch (live
    activations + workspaces) beyond inputs/outputs.
    """
    lowered = jax.jit(fn, static_argnums=static_argnums,
                      **kwargs).lower(*args)
    ma = lowered.compile().memory_analysis()
    if ma is None:                    # backend without the query
        return {}
    return {
        "argument": int(ma.argument_size_in_bytes),
        "output": int(ma.output_size_in_bytes),
        "temp": int(ma.temp_size_in_bytes),
        "alias": int(ma.alias_size_in_bytes),
        "generated_code": int(ma.generated_code_size_in_bytes),
    }


def program_hash(fn: Callable, *args, **jit_kwargs) -> str:
    """sha256 of the program ``jit(fn)`` would run for these args.

    Hashes the stable (unoptimized) StableHLO text, so the value is a
    fingerprint of the MATH the host built — identical sources on every
    controller hash identically even if backend optimization differs.
    """
    text = jax.jit(fn, **jit_kwargs).lower(*args).as_text()
    return hashlib.sha256(text.encode()).hexdigest()


def assert_same_program(fn_or_hash: Any, *args, **jit_kwargs) -> str:
    """Multi-controller divergence guard (SURVEY §5: "same program hash on
    all hosts" in place of race detection).

    Pass either a precomputed hash string or ``(fn, *args)``.  Under
    multi-controller JAX the hash is all-gathered over hosts and all
    values must agree; single-controller it's a cheap no-op pass-through.
    Returns the (verified) hash.
    """
    h = (fn_or_hash if isinstance(fn_or_hash, str)
         else program_hash(fn_or_hash, *args, **jit_kwargs))
    if jax.process_count() > 1:
        import numpy as np
        from jax.experimental import multihost_utils

        bits = np.frombuffer(bytes.fromhex(h), np.uint8)
        gathered = multihost_utils.process_allgather(bits)
        for rank, other in enumerate(gathered):
            if not np.array_equal(other, bits):
                raise AssertionError(
                    f"program hash divergence: host {jax.process_index()} "
                    f"has {h}, host {rank} differs — the controllers built "
                    "different programs")
    return h


class ServingMetrics:
    """Host-side serving observability for the continuous-batching engine.

    Tracks, per request, time-to-first-token (submit → first sampled
    token, i.e. including queueing + prefill) and per-token decode
    latencies; plus per-step slot occupancy samples for the engine as a
    whole.  ``clock`` is injectable (tests pass a fake counter) and
    defaults to ``time.monotonic``.  All aggregation is lazy —
    :meth:`summary` computes percentiles over whatever has been recorded.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self.clock = clock
        self._submitted: dict = {}       # request_id -> submit time
        self._last_token: dict = {}      # request_id -> last token time
        self.ttft: dict = {}             # request_id -> seconds
        self.token_latencies: list = []  # seconds, across all requests
        self.occupancy: list = []        # (active, total) per engine step
        self.tokens_emitted = 0
        self.evicted = 0                 # deadline evictions (active+queued)
        self.errors = 0                  # poison requests quarantined
        self.timeouts = 0                # per-request timeout expiries
        self._started: float | None = None

    def request_submitted(self, request_id) -> None:
        self._submitted[request_id] = self.clock()
        if self._started is None:
            self._started = self._submitted[request_id]

    def first_token(self, request_id) -> None:
        now = self.clock()
        self.ttft[request_id] = now - self._submitted.get(request_id, now)
        self._last_token[request_id] = now
        self.tokens_emitted += 1

    def token(self, request_id) -> None:
        now = self.clock()
        prev = self._last_token.get(request_id)
        if prev is not None:
            self.token_latencies.append(now - prev)
        self._last_token[request_id] = now
        self.tokens_emitted += 1

    def step(self, active_slots: int, total_slots: int) -> None:
        self.occupancy.append((active_slots, total_slots))

    def request_evicted(self, request_id) -> None:
        """A request hit its deadline — mid-decode or still queued.
        Without this the slot simply vanished from the stats (a request
        that never reached first_token left no trace in ``summary``)."""
        self.evicted += 1

    def request_error(self, request_id) -> None:
        """A poison request was quarantined (its sampling/decode raised);
        the engine finished it with ``reason="error"`` instead of dying."""
        self.errors += 1

    def request_timeout(self, request_id) -> None:
        """A request exceeded its per-request ``timeout`` budget
        (distinct from absolute-``deadline`` eviction)."""
        self.timeouts += 1

    @staticmethod
    def _pct(xs, q):
        if not xs:
            return 0.0
        xs = sorted(xs)
        i = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
        return xs[i]

    def summary(self) -> dict:
        elapsed = (self.clock() - self._started) if self._started else 0.0
        occ = ([a / t for a, t in self.occupancy if t]
               if self.occupancy else [])
        return {
            "requests": len(self.ttft),
            "tokens": self.tokens_emitted,
            "evicted": self.evicted,
            "errors": self.errors,
            "timeouts": self.timeouts,
            "tokens_per_s": (self.tokens_emitted / elapsed
                             if elapsed > 0 else 0.0),
            "ttft_p50_s": self._pct(list(self.ttft.values()), 0.5),
            "ttft_max_s": max(self.ttft.values()) if self.ttft else 0.0,
            "token_latency_p50_s": self._pct(self.token_latencies, 0.5),
            "token_latency_p90_s": self._pct(self.token_latencies, 0.9),
            "slot_occupancy_mean": (sum(occ) / len(occ)) if occ else 0.0,
        }
