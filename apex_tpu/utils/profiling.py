"""Profiling + observability utilities (SURVEY §5).

The reference's op-level tracing was ``apex.pyprof`` (removed upstream)
plus scattered ``torch.cuda.nvtx.range_push/pop`` annotations read by
Nsight.  The TPU equivalents wired here:

* :func:`annotate` / :func:`range_push` / :func:`range_pop` —
  ``jax.named_scope`` as the nvtx analogue; scope names survive into XLA
  HLO metadata and show up in profiler traces.
* :func:`trace` — ``jax.profiler.trace`` wrapper (TensorBoard-readable).
* :func:`memory_stats` — compiled-program memory analysis (argument /
  output / temp bytes), the measurement tool for the pipeline engine's
  activation-residency claims.
* :func:`program_hash` / :func:`assert_same_program` — the survey's
  multi-controller race-safety replacement: XLA programs are data-race
  free, so the remaining divergence risk is hosts compiling DIFFERENT
  programs; hash the optimized HLO and compare.
"""

from __future__ import annotations

import contextlib
import hashlib
import threading
from typing import Any, Callable

import jax

named_scope = jax.named_scope        # re-export: the nvtx range analogue

_SCOPES = threading.local()          # per-thread stack: pops must nest


def range_push(name: str) -> None:
    """``torch.cuda.nvtx.range_push`` equivalent (paired with
    :func:`range_pop`); prefer the :func:`annotate` context manager.

    The push/pop stack is per-thread and pops must nest within their
    thread — interleaving pairs across threads is undefined, as it was
    for nvtx ranges.
    """
    cm = jax.named_scope(name)
    cm.__enter__()
    if not hasattr(_SCOPES, "stack"):
        _SCOPES.stack = []
    _SCOPES.stack.append(cm)


def range_pop() -> None:
    stack = getattr(_SCOPES, "stack", None)
    if stack:
        stack.pop().__exit__(None, None, None)


@contextlib.contextmanager
def annotate(name: str):
    """Named-scope context manager: ops traced inside carry ``name`` in
    their HLO metadata (visible in xprof/TensorBoard)."""
    with jax.named_scope(name):
        yield


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_link: bool = False):
    """Capture a device trace viewable in TensorBoard (``jax.profiler``)."""
    jax.profiler.start_trace(log_dir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def memory_stats(fn: Callable, *args, static_argnums=(), **kwargs) -> dict:
    """Compile ``fn`` for the given args and return its memory analysis.

    Returns a dict with ``argument``, ``output``, ``temp``, ``alias`` and
    ``generated_code`` sizes in bytes.  ``temp`` is the interesting one
    for remat/pipeline decisions: it is XLA's peak scratch (live
    activations + workspaces) beyond inputs/outputs.
    """
    lowered = jax.jit(fn, static_argnums=static_argnums,
                      **kwargs).lower(*args)
    ma = lowered.compile().memory_analysis()
    if ma is None:                    # backend without the query
        return {}
    return {
        "argument": int(ma.argument_size_in_bytes),
        "output": int(ma.output_size_in_bytes),
        "temp": int(ma.temp_size_in_bytes),
        "alias": int(ma.alias_size_in_bytes),
        "generated_code": int(ma.generated_code_size_in_bytes),
    }


def program_hash(fn: Callable, *args, **jit_kwargs) -> str:
    """sha256 of the program ``jit(fn)`` would run for these args.

    Hashes the stable (unoptimized) StableHLO text, so the value is a
    fingerprint of the MATH the host built — identical sources on every
    controller hash identically even if backend optimization differs.
    """
    text = jax.jit(fn, **jit_kwargs).lower(*args).as_text()
    return hashlib.sha256(text.encode()).hexdigest()


def assert_same_program(fn_or_hash: Any, *args, **jit_kwargs) -> str:
    """Multi-controller divergence guard (SURVEY §5: "same program hash on
    all hosts" in place of race detection).

    Pass either a precomputed hash string or ``(fn, *args)``.  Under
    multi-controller JAX the hash is all-gathered over hosts and all
    values must agree; single-controller it's a cheap no-op pass-through.
    Returns the (verified) hash.
    """
    h = (fn_or_hash if isinstance(fn_or_hash, str)
         else program_hash(fn_or_hash, *args, **jit_kwargs))
    if jax.process_count() > 1:
        import numpy as np
        from jax.experimental import multihost_utils

        bits = np.frombuffer(bytes.fromhex(h), np.uint8)
        gathered = multihost_utils.process_allgather(bits)
        for rank, other in enumerate(gathered):
            if not np.array_equal(other, bits):
                raise AssertionError(
                    f"program hash divergence: host {jax.process_index()} "
                    f"has {h}, host {rank} differs — the controllers built "
                    "different programs")
    return h
