"""Profiling + observability utilities (SURVEY §5).

The reference's op-level tracing was ``apex.pyprof`` (removed upstream)
plus scattered ``torch.cuda.nvtx.range_push/pop`` annotations read by
Nsight.  The TPU equivalents wired here:

* :func:`annotate` / :func:`range_push` / :func:`range_pop` —
  ``jax.named_scope`` as the nvtx analogue; scope names survive into XLA
  HLO metadata and show up in profiler traces.
* :func:`trace` — ``jax.profiler.trace`` wrapper (TensorBoard-readable).
* :func:`memory_stats` — compiled-program memory analysis (argument /
  output / temp bytes), the measurement tool for the pipeline engine's
  activation-residency claims.
* :func:`program_hash` / :func:`assert_same_program` — the survey's
  multi-controller race-safety replacement: XLA programs are data-race
  free, so the remaining divergence risk is hosts compiling DIFFERENT
  programs; hash the optimized HLO and compare.
* :class:`ServingMetrics` — inference-serving observability (TTFT,
  per-token latency, slot occupancy, tokens/s) for
  ``apex_tpu.inference``'s continuous-batching engine; backed by the
  :mod:`apex_tpu.observability` metrics registry, so serving series
  export as Prometheus text / JSONL next to training telemetry.
"""

from __future__ import annotations

import collections
import contextlib
import hashlib
import threading
import time
import warnings
from typing import Any, Callable, Optional

import jax

named_scope = jax.named_scope        # re-export: the nvtx range analogue

_SCOPES = threading.local()          # per-thread stack: pops must nest
_POP_MISMATCH_WARNED = False         # warn-once flag for unmatched pops


def range_push(name: str) -> None:
    """``torch.cuda.nvtx.range_push`` equivalent (paired with
    :func:`range_pop`); prefer the :func:`annotate` context manager.

    The push/pop stack is per-thread and pops must nest within their
    thread — interleaving pairs across threads is undefined, as it was
    for nvtx ranges.
    """
    cm = jax.named_scope(name)
    cm.__enter__()
    if not hasattr(_SCOPES, "stack"):
        _SCOPES.stack = []
    _SCOPES.stack.append(cm)


def range_pop() -> None:
    """Pop the innermost :func:`range_push` scope on this thread.

    An unmatched pop (empty stack) is a caller bug — annotations above
    it are silently mis-nested from that point on — so it warns (once
    per process; nvtx printed an error per event, which floods) instead
    of no-opping invisibly."""
    stack = getattr(_SCOPES, "stack", None)
    if stack:
        stack.pop().__exit__(None, None, None)
        return
    global _POP_MISMATCH_WARNED
    if not _POP_MISMATCH_WARNED:
        _POP_MISMATCH_WARNED = True
        warnings.warn(
            "range_pop() with no matching range_push() on this thread — "
            "push/pop pairs are mis-nested (warning once per process)",
            RuntimeWarning, stacklevel=2)


def range_depth() -> int:
    """Current :func:`range_push` nesting depth on THIS thread (tests
    assert push/pop balance with it)."""
    return len(getattr(_SCOPES, "stack", None) or ())


@contextlib.contextmanager
def annotate(name: str):
    """Named-scope context manager: ops traced inside carry ``name`` in
    their HLO metadata (visible in xprof/TensorBoard)."""
    with jax.named_scope(name):
        yield


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_link: bool = False):
    """Capture a device trace viewable in TensorBoard (``jax.profiler``)."""
    jax.profiler.start_trace(log_dir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def memory_stats(fn: Callable, *args, static_argnums=(), **kwargs) -> dict:
    """Compile ``fn`` for the given args and return its memory analysis.

    Returns a dict with ``argument``, ``output``, ``temp``, ``alias`` and
    ``generated_code`` sizes in bytes.  ``temp`` is the interesting one
    for remat/pipeline decisions: it is XLA's peak scratch (live
    activations + workspaces) beyond inputs/outputs.
    """
    lowered = jax.jit(fn, static_argnums=static_argnums,
                      **kwargs).lower(*args)
    ma = lowered.compile().memory_analysis()
    if ma is None:                    # backend without the query
        return {}
    return {
        "argument": int(ma.argument_size_in_bytes),
        "output": int(ma.output_size_in_bytes),
        "temp": int(ma.temp_size_in_bytes),
        "alias": int(ma.alias_size_in_bytes),
        "generated_code": int(ma.generated_code_size_in_bytes),
    }


def program_hash(fn: Callable, *args, **jit_kwargs) -> str:
    """sha256 of the program ``jit(fn)`` would run for these args.

    Hashes the stable (unoptimized) StableHLO text, so the value is a
    fingerprint of the MATH the host built — identical sources on every
    controller hash identically even if backend optimization differs.
    """
    text = jax.jit(fn, **jit_kwargs).lower(*args).as_text()
    return hashlib.sha256(text.encode()).hexdigest()


def assert_same_program(fn_or_hash: Any, *args, **jit_kwargs) -> str:
    """Multi-controller divergence guard (SURVEY §5: "same program hash on
    all hosts" in place of race detection).

    Pass either a precomputed hash string or ``(fn, *args)``.  Under
    multi-controller JAX the hash is all-gathered over hosts and all
    values must agree; single-controller it's a cheap no-op pass-through.
    Returns the (verified) hash.
    """
    h = (fn_or_hash if isinstance(fn_or_hash, str)
         else program_hash(fn_or_hash, *args, **jit_kwargs))
    if jax.process_count() > 1:
        import numpy as np
        from jax.experimental import multihost_utils

        bits = np.frombuffer(bytes.fromhex(h), np.uint8)
        gathered = multihost_utils.process_allgather(bits)
        for rank, other in enumerate(gathered):
            if not np.array_equal(other, bits):
                raise AssertionError(
                    f"program hash divergence: host {jax.process_index()} "
                    f"has {h}, host {rank} differs — the controllers built "
                    "different programs")
    return h


class ServingMetrics:
    """Host-side serving observability for the continuous-batching engine.

    Tracks, per request, time-to-first-token (submit → first sampled
    token, i.e. including queueing + prefill) and per-token decode
    latencies; plus per-step slot occupancy samples for the engine as a
    whole.  ``clock`` is injectable (tests pass a fake counter) and
    defaults to ``time.monotonic``.  All aggregation is lazy —
    :meth:`summary` computes percentiles over whatever has been recorded.

    Since the observability PR this is a thin wrapper over a
    :class:`~apex_tpu.observability.MetricsRegistry` — every recording
    ALSO feeds registry counters/histograms (``serving_*`` series), so
    an engine's metrics export as Prometheus text or a JSONL stream
    alongside training telemetry.  Pass a shared ``registry`` to merge
    serving and training series into one sink; the public recording API
    and :meth:`summary` values are unchanged (summary still computes
    exact percentiles over the raw samples, not histogram buckets).

    Per-request transient state (``_submitted``/``_last_token``) is
    dropped when a request reaches ANY terminal state — finished,
    evicted, errored or timed out — so a long-running engine no longer
    leaks an entry per request that finished without tokens.

    Memory is BOUNDED: raw-sample retention (``ttft`` /
    ``token_latencies`` / ``occupancy`` / ``queue_waits`` /
    ``decode_ticks``) keeps the most recent ``max_samples`` entries —
    :meth:`summary` percentiles are exact over that window, while the
    registry histograms (fixed buckets) and counters aggregate the full
    run.  A serving process that runs for weeks holds O(max_samples)
    state, not O(requests).
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 registry: Optional[Any] = None, *,
                 slo: Optional[Any] = None,
                 max_samples: int = 4096):
        from apex_tpu.observability import MetricsRegistry

        self.clock = clock
        self.registry = registry if registry is not None \
            else MetricsRegistry(clock=clock)
        self.slo = slo                   # optional observability.SLOMonitor
        self.max_samples = max_samples
        self._submitted: dict = {}       # request_id -> submit time
        self._last_token: dict = {}      # request_id -> last token time
        self.ttft: dict = collections.OrderedDict()   # request_id -> s
        self.token_latencies: collections.deque = \
            collections.deque(maxlen=max_samples)
        self.occupancy: collections.deque = \
            collections.deque(maxlen=max_samples)  # (active, total)/step
        self.queue_waits: collections.deque = \
            collections.deque(maxlen=max_samples)  # enqueue->admit, s
        self.decode_ticks: collections.deque = \
            collections.deque(maxlen=max_samples)  # ticks per request
        self._first_tokens = 0           # requests that reached a token
        self.tokens_emitted = 0
        self.evicted = 0                 # deadline evictions (active+queued)
        self.errors = 0                  # poison requests quarantined
        self.timeouts = 0                # per-request timeout expiries
        self.requeued = 0                # preemption requeues (non-terminal)
        self.migrated = 0                # moved to another replica (fleet)
        self.cancelled = 0               # hedge losers withdrawn (fleet)
        self._started: float | None = None
        r = self.registry
        self._c_requests = r.counter("serving_requests_total",
                                     "requests submitted")
        self._c_finished = r.counter(
            "serving_finished_total", "requests reaching a terminal "
            "state, by reason", labelnames=("reason",))
        self._c_tokens = r.counter("serving_tokens_total",
                                   "tokens sampled")
        self._h_ttft = r.histogram("serving_ttft_seconds",
                                   "submit -> first token")
        self._h_latency = r.histogram("serving_token_latency_seconds",
                                      "inter-token decode latency")
        self._g_occupancy = r.gauge("serving_slot_occupancy",
                                    "active/total slots (last step)")
        self._g_queue = r.gauge("serving_active_requests",
                                "requests currently admitted")
        self._h_queue_wait = r.histogram(
            "serving_queue_wait_seconds",
            "enqueue -> admission wait (from the request trace)")
        self._h_ticks = r.histogram(
            "serving_decode_ticks",
            "decode ticks per request (from the request trace)",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))
        self._c_requeued = r.counter(
            "serving_requests_requeued_total",
            "in-flight requests requeued by an engine preemption")

    def request_submitted(self, request_id) -> None:
        self._submitted[request_id] = self.clock()
        if self._started is None:
            self._started = self._submitted[request_id]
        self._c_requests.inc()

    def first_token(self, request_id) -> None:
        now = self.clock()
        ttft = now - self._submitted.get(request_id, now)
        self.ttft[request_id] = ttft
        while len(self.ttft) > self.max_samples:
            self.ttft.popitem(last=False)
        self._last_token[request_id] = now
        self._first_tokens += 1
        self.tokens_emitted += 1
        self._h_ttft.observe(ttft)
        self._c_tokens.inc()
        if self.slo is not None:
            self.slo.observe("ttft", ttft)

    def token(self, request_id) -> None:
        now = self.clock()
        prev = self._last_token.get(request_id)
        if prev is not None:
            self.token_latencies.append(now - prev)
            self._h_latency.observe(now - prev)
            if self.slo is not None:
                self.slo.observe("token_latency", now - prev)
        self._last_token[request_id] = now
        self.tokens_emitted += 1
        self._c_tokens.inc()

    def request_admitted(self, request_id, queue_wait_s: float) -> None:
        """Admission edge, fed by the request trace: ``queue_wait_s`` is
        the measured enqueue→admit wait on the trace's clock."""
        self.queue_waits.append(queue_wait_s)
        self._h_queue_wait.observe(queue_wait_s)
        if self.slo is not None:
            self.slo.observe("queue_wait", queue_wait_s)

    def request_decode_ticks(self, request_id, ticks: int) -> None:
        """Decode ticks a completed request consumed (request trace)."""
        self.decode_ticks.append(int(ticks))
        self._h_ticks.observe(ticks)

    def step(self, active_slots: int, total_slots: int) -> None:
        self.occupancy.append((active_slots, total_slots))
        self._g_occupancy.set(active_slots / total_slots
                              if total_slots else 0.0)
        self._g_queue.set(active_slots)

    def _terminal(self, request_id, reason: str) -> None:
        # terminal-state cleanup: without these pops a request that
        # finished without tokens leaked its _submitted/_last_token
        # entries for the life of the engine
        self._submitted.pop(request_id, None)
        self._last_token.pop(request_id, None)
        self._c_finished.inc(reason=reason)

    def request_finished(self, request_id, reason: str = "done") -> None:
        """A request completed normally (eos / length).  Drops its
        transient state and counts the terminal reason."""
        self._terminal(request_id, reason)

    def request_evicted(self, request_id) -> None:
        """A request hit its deadline — mid-decode or still queued.
        Without this the slot simply vanished from the stats (a request
        that never reached first_token left no trace in ``summary``)."""
        self.evicted += 1
        self._terminal(request_id, "evicted")

    def request_error(self, request_id) -> None:
        """A poison request was quarantined (its sampling/decode raised);
        the engine finished it with ``reason="error"`` instead of dying."""
        self.errors += 1
        self._terminal(request_id, "error")

    def request_timeout(self, request_id) -> None:
        """A request exceeded its per-request ``timeout`` budget
        (distinct from absolute-``deadline`` eviction)."""
        self.timeouts += 1
        self._terminal(request_id, "timeout")

    def request_requeued(self, request_id) -> None:
        """An in-flight request was requeued by an engine preemption.
        NON-terminal: the request's transient state (TTFT bookkeeping,
        token-latency chain) survives — it will be re-admitted and its
        next token lands in the same per-request series."""
        self.requeued += 1
        self._c_requeued.inc()

    def request_migrated(self, request_id) -> None:
        """The fleet moved this request to another replica (this
        engine's replica was declared dead).  Terminal FOR THIS ENGINE —
        the adopting replica restarts the request's transient state; the
        fleet-level count lives in ``serving_migrations_total``."""
        self.migrated += 1
        self._terminal(request_id, "migrated")

    def request_cancelled(self, request_id) -> None:
        """The fleet withdrew this request without a Response (the
        losing copy of a hedged dispatch)."""
        self.cancelled += 1
        self._terminal(request_id, "cancelled")

    @property
    def pending_requests(self) -> int:
        """Requests submitted but not yet terminal (leak sentinel:
        returns to 0 on an idle engine)."""
        return len(self._submitted)

    @staticmethod
    def _pct(xs, q):
        if not xs:
            return 0.0
        xs = sorted(xs)
        i = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
        return xs[i]

    def summary(self) -> dict:
        elapsed = (self.clock() - self._started) if self._started else 0.0
        occ = ([a / t for a, t in self.occupancy if t]
               if self.occupancy else [])
        return {
            "requests": self._first_tokens,
            "tokens": self.tokens_emitted,
            "evicted": self.evicted,
            "errors": self.errors,
            "timeouts": self.timeouts,
            "requeued": self.requeued,
            "migrated": self.migrated,
            "cancelled": self.cancelled,
            "tokens_per_s": (self.tokens_emitted / elapsed
                             if elapsed > 0 else 0.0),
            "ttft_p50_s": self._pct(list(self.ttft.values()), 0.5),
            "ttft_max_s": max(self.ttft.values()) if self.ttft else 0.0,
            "token_latency_p50_s": self._pct(self.token_latencies, 0.5),
            "token_latency_p90_s": self._pct(self.token_latencies, 0.9),
            "queue_wait_p50_s": self._pct(self.queue_waits, 0.5),
            "decode_ticks_p50": self._pct(self.decode_ticks, 0.5),
            "slot_occupancy_mean": (sum(occ) / len(occ)) if occ else 0.0,
        }
