"""Backend/platform helpers.

Apex gates its native kernels at build time (setup.py feature flags) and each
Python wrapper raises ImportError when its extension is missing.  On TPU the
equivalent gate is *runtime*: Pallas kernels run on the TPU backend, and every
op carries a pure-jnp fallback with identical semantics for CPU/GPU (used by
the unit-test suite running on a fake 8-device CPU mesh).
"""

from __future__ import annotations

import functools
import os

import jax

_FORCE_PALLAS: bool | None = None


@functools.lru_cache(maxsize=None)
def is_tpu_backend() -> bool:
    """True when the default JAX backend is a TPU (incl. tunneled axon TPU)."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - backend init failure
        return False


def set_force_pallas(value: bool | None) -> None:
    """Force Pallas kernels on (interpret mode off-TPU) / off, or None=auto."""
    global _FORCE_PALLAS
    _FORCE_PALLAS = value


def use_pallas() -> bool:
    """Whether fused ops should lower to Pallas kernels.

    Auto policy: Pallas on TPU, jnp fallback elsewhere.  Override with
    :func:`set_force_pallas` or ``APEX_TPU_FORCE_PALLAS=1/0``.
    """
    if _FORCE_PALLAS is not None:
        return _FORCE_PALLAS
    env = os.environ.get("APEX_TPU_FORCE_PALLAS")
    if env is not None:
        return env not in ("0", "false", "False")
    return is_tpu_backend()


def interpret_mode() -> bool:
    """Pallas ``interpret=`` flag: interpret when not actually on TPU."""
    return not is_tpu_backend()


def tpu_compiler_params(dimension_semantics: tuple):
    """``pltpu.CompilerParams`` across JAX versions (older releases call
    the same dataclass ``TPUCompilerParams``) — single compat point for
    every Pallas op's ``compiler_params=``."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(dimension_semantics=dimension_semantics)
