"""apex_tpu.analysis — static analysis of jitted programs.

A linter over the artifacts jit already produces (closed jaxpr,
optimized scheduled HLO, the compiled object): dtype-promotion leaks,
missing buffer donation, host-sync hazards, recompile hazards, sharding
lint, collective-overlap audit, plus a liveness-based peak-memory
estimator cross-checked against ``compiled.memory_analysis()``.

Compile-only: nothing is ever executed.  ``tools/lint_graph.py`` runs
the registry over every canonical train/serve program against a
committed baseline; ``__graft_entry__`` carries the same check as a CI
leg.
"""

from apex_tpu.analysis.findings import (BASELINE_VERSION, Finding,
                                        LintReport, load_baseline,
                                        save_baseline)
from apex_tpu.analysis.hlo import (HloModule, Instruction, parse_hlo_module,
                                   scope_of, shape_bytes)
from apex_tpu.analysis.linter import ANALYZERS, LintConfig, lint, lint_fn
from apex_tpu.analysis.memory import (MemoryEstimate, estimate_from_hlo_text,
                                      estimate_peak_memory, xla_peak_bytes)
from apex_tpu.analysis.program import LintProgram

__all__ = [
    "ANALYZERS", "BASELINE_VERSION", "Finding", "HloModule", "Instruction",
    "LintConfig", "LintProgram", "LintReport", "MemoryEstimate",
    "estimate_from_hlo_text", "estimate_peak_memory", "lint", "lint_fn",
    "load_baseline", "parse_hlo_module", "save_baseline", "scope_of",
    "shape_bytes", "xla_peak_bytes",
]
