"""HLO-level analyzers: sharding lint and collective-overlap audit.

These run on the optimized, scheduled HLO text — after GSPMD has
propagated shardings and materialized the collectives — because that is
the only place the questions are answerable: the jaxpr has `psum`, the
HLO has the actual ``all-reduce`` with its replica groups, its byte
count, and its position in the schedule.

Rule ids:

* ``sharding/replicated-large`` — a tensor above the size threshold is
  fully replicated across a partitioned mesh: every device holds the
  whole thing, the per-device HBM win of sharding it is (n-1)/n.
* ``sharding/gather-roundtrip`` — a reduce-scatter (or dynamic-slice of
  a collective result) whose output is immediately all-gathered back to
  full size: the round trip means GSPMD failed to keep the value
  sharded between the two ops.
* ``sharding/large-gather`` — an all-gather materializing a full-size
  copy above the threshold; often the "replicated weight" pattern in
  disguise.
* ``overlap/serialized-collectives`` — collective B's operand chain
  reaches collective A through LIGHT_OPS only (no compute between
  them): the pair serializes on the ICI where an async pair would
  overlap.  The async-collective forms (``all-reduce-start/-done``)
  already overlap and are skipped.
"""

from __future__ import annotations

from typing import Dict, List

from apex_tpu.analysis.findings import Finding
from apex_tpu.analysis.hlo import LIGHT_OPS, HloModule

_COLLECTIVES = frozenset({
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute"})
_GATHERISH = frozenset({"all-gather"})
_SCATTERISH = frozenset({"reduce-scatter"})


def _iter_device_computations(module: HloModule):
    """Entry + every computation reachable from it (while/call bodies
    run on device too; collectives inside a pipeline `while` loop are
    the ones that matter most)."""
    seen = set()
    stack = [module.entry.name]
    while stack:
        name = stack.pop()
        if name in seen:
            continue
        seen.add(name)
        comp = module.computations.get(name)
        if comp is None:
            continue
        yield comp
        for ins in comp.instructions:
            stack.extend(ins.called)


def analyze_sharding(program, config):
    """Large replicated tensors and gather round-trips."""
    module = program.hlo_module()
    nparts = module.num_partitions
    findings = []
    if nparts <= 1:
        return findings     # single device: nothing to shard
    big = config.large_bytes

    # replicated-large: entry params / outputs carrying an explicit
    # replicated sharding while the mesh is partitioned
    seen_repl = set()
    for ins in module.entry.instructions:
        sh = ins.sharding
        if sh is None or "replicated" not in sh:
            continue
        if ins.nbytes < big:
            continue
        scope = ins.scope or ins.name
        if scope in seen_repl:
            continue
        seen_repl.add(scope)
        findings.append(Finding(
            rule="sharding/replicated-large", severity="warning",
            message=(f"{ins.opcode} `{ins.name}` ({ins.nbytes:,} B) is "
                     f"fully replicated across {nparts} partitions — "
                     "every device holds the whole tensor; sharding it "
                     f"saves {(nparts - 1)}/{nparts} of its HBM per "
                     "device"),
            scope=scope, op=ins.opcode,
            fix_hint=("give the tensor a PartitionSpec over the mesh "
                      "(or mark it with with_sharding_constraint)"),
            details={"bytes": ins.nbytes, "partitions": nparts}))

    for comp in _iter_device_computations(module):
        by_name = comp.by_name()
        for ins in comp.instructions:
            if ins.opcode not in _GATHERISH:
                continue
            # gather-roundtrip: the gather's operand chain reaches a
            # reduce-scatter through light ops — sharded then
            # immediately unsharded
            frontier = list(ins.operands)
            for _ in range(16):
                nxt = []
                hit = None
                for op in frontier:
                    src = by_name.get(op)
                    if src is None:
                        continue
                    if src.opcode in _SCATTERISH:
                        hit = src
                        break
                    if src.opcode in LIGHT_OPS:
                        nxt.extend(src.operands)
                if hit is not None or not nxt:
                    break
                frontier = nxt
            if hit is not None:
                scope = ins.scope or ins.name
                findings.append(Finding(
                    rule="sharding/gather-roundtrip", severity="warning",
                    message=(f"all-gather `{ins.name}` re-materializes "
                             f"the output of reduce-scatter "
                             f"`{hit.name}` ({ins.nbytes:,} B) — the "
                             "value went sharded->full with no compute "
                             "between, a full ICI round trip"),
                    scope=scope, op=ins.opcode,
                    fix_hint=("keep the value sharded between the two "
                              "ops (with_sharding_constraint) or fuse "
                              "into a single all-reduce"),
                    details={"bytes": ins.nbytes,
                             "scatter": hit.name}))
                continue
            if ins.nbytes >= big:
                scope = ins.scope or ins.name
                findings.append(Finding(
                    rule="sharding/large-gather", severity="info",
                    message=(f"all-gather `{ins.name}` materializes a "
                             f"full-size {ins.nbytes:,} B copy on every "
                             "device"),
                    scope=scope, op=ins.opcode,
                    fix_hint=("check whether the consumer really needs "
                              "the unsharded value, or gather just-in-"
                              "time inside the consuming loop"),
                    details={"bytes": ins.nbytes}))
    return findings


def analyze_overlap(program, config):
    """Directly chained (serialized) synchronous collectives."""
    module = program.hlo_module()
    findings = []
    for comp in _iter_device_computations(module):
        by_name = comp.by_name()
        for ins in comp.instructions:
            if ins.opcode not in _COLLECTIVES:
                continue
            # walk the operand chain through light ops; stop at the
            # first real op — if it's another sync collective, the pair
            # cannot overlap
            frontier = list(ins.operands)
            hit = None
            for _ in range(16):
                nxt = []
                for op in frontier:
                    src = by_name.get(op)
                    if src is None:
                        continue
                    if src.opcode in _COLLECTIVES:
                        hit = src
                        break
                    if src.opcode in LIGHT_OPS:
                        nxt.extend(src.operands)
                if hit is not None or not nxt:
                    break
                frontier = nxt
            if hit is None:
                continue
            scope = ins.scope or ins.name
            findings.append(Finding(
                rule="overlap/serialized-collectives",
                severity="warning",
                message=(f"{ins.opcode} `{ins.name}` directly consumes "
                         f"{hit.opcode} `{hit.name}` with no compute "
                         "between them — the two collectives serialize "
                         "on the ICI (combined "
                         f"{ins.nbytes + hit.nbytes:,} B)"),
                scope=scope, op=ins.opcode,
                fix_hint=("fuse them into one collective over the "
                          "combined axis, or interleave compute so the "
                          "scheduler can overlap (see "
                          "observability.comms overlap notes)"),
                details={"bytes": ins.nbytes, "upstream": hit.name,
                         "upstream_op": hit.opcode}))
    return findings
