"""Jaxpr-level analyzers: dtype promotion, donation, host sync,
recompilation.

These run on the CLOSED JAXPR (pre-XLA), where the op stream still
carries user-level structure: primitive names, ``named_scope``
provenance on every eqn (``eqn.source_info.name_stack``) and the
argument pytree paths.  Each analyzer is a pure function
``(LintProgram, LintConfig) -> [Finding]`` registered with the linter.

Rule ids (catalog in ``docs/source/analysis.md``):

* ``dtype/bf16-upcast-matmul`` — a matmul executing in f32 whose
  operand was upcast from bf16/f16: in an amp/bf16 path this silently
  runs the MXU at the f32 rate (~1/8th) and doubles operand traffic.
* ``dtype/f64-op`` — any f64/c128 op: unintended x64 promotion
  (catastrophic on TPU — f64 is emulated).
* ``donation/missing`` — an input leaf that is shape/dtype-aliasable
  with an output but not donated: params + opt state held twice (the
  double-HBM hazard donation exists to prevent).
* ``host-sync/callback`` — callbacks/debug prints reachable from the
  step fn: each one is a device->host round trip per step.
* ``recompile/unhashable-static`` / ``recompile/identity-static`` —
  static args that cannot hash (jit raises) or hash by object identity
  (every fresh instance silently retraces).
"""

from __future__ import annotations

import numpy as np
from jax import core as jax_core

from apex_tpu.analysis.findings import Finding

# dataflow the dtype walk may cross while tracking "the same value"
_TRANSPARENT = frozenset({
    "transpose", "reshape", "broadcast_in_dim", "squeeze", "copy",
    "slice", "rev"})
_MATMUL = frozenset({"dot_general", "conv_general_dilated"})
_SMALL_FLOATS = ("bfloat16", "float16")

_CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "infeed", "outfeed", "host_callback_call"})


def _all_jaxprs(closed_jaxpr):
    """Yield the top jaxpr and every sub-jaxpr (scan/cond/remat/pjit
    bodies), depth-first."""
    import jax
    seen = []

    def walk(jaxpr):
        seen.append(jaxpr)
        for sub in jax.core.subjaxprs(jaxpr):
            walk(sub)

    walk(closed_jaxpr.jaxpr)
    return seen


def _scope(eqn) -> str:
    try:
        return str(eqn.source_info.name_stack)
    except Exception:
        return ""


def _dtype_of(var):
    aval = getattr(var, "aval", None)
    return getattr(aval, "dtype", None)


def analyze_dtype_promotion(program, config):
    """bf16->f32 upcasts feeding f32 matmuls, and any f64 op."""
    findings = []
    f64_count = 0
    f64_first = None
    upcast_hits = []
    for jaxpr in _all_jaxprs(program.closed_jaxpr()):
        # vars produced by a small-float -> f32 convert in this jaxpr
        upcast_vars = {}
        producers = {}
        for eqn in jaxpr.eqns:
            for v in eqn.outvars:
                producers[v] = eqn
            if eqn.primitive.name == "convert_element_type":
                src = _dtype_of(eqn.invars[0])
                dst = _dtype_of(eqn.outvars[0])
                if (src is not None and dst is not None
                        and str(src) in _SMALL_FLOATS
                        and str(dst) == "float32"):
                    upcast_vars[eqn.outvars[0]] = str(src)
        for eqn in jaxpr.eqns:
            for v in eqn.outvars:
                dt = _dtype_of(v)
                if dt is not None and str(dt) in ("float64", "complex128"):
                    f64_count += 1
                    if f64_first is None:
                        f64_first = (eqn.primitive.name, _scope(eqn))
            if eqn.primitive.name not in _MATMUL:
                continue
            out_dt = _dtype_of(eqn.outvars[0])
            if out_dt is None or str(out_dt) != "float32":
                continue
            for invar in eqn.invars:
                # walk back through transparent ops to the origin
                v = invar
                for _ in range(32):
                    if isinstance(v, jax_core.Literal):
                        break          # inline constant: no producer
                    if v in upcast_vars:
                        upcast_hits.append(
                            (upcast_vars[v], eqn.primitive.name,
                             _scope(eqn)))
                        break
                    p = producers.get(v)
                    if p is None or p.primitive.name not in _TRANSPARENT:
                        break
                    v = p.invars[0]
    if upcast_hits:
        src, prim, scope = upcast_hits[0]
        findings.append(Finding(
            rule="dtype/bf16-upcast-matmul", severity="warning",
            message=(f"{len(upcast_hits)} matmul(s) execute in f32 on "
                     f"operands upcast from {src} (first: {prim} at "
                     f"{scope or '<top>'}) — the MXU runs f32 at ~1/8 "
                     "the bf16 rate and operand traffic doubles"),
            scope=scope, op=prim,
            fix_hint=("keep the matmul operands in the compute dtype and "
                      "accumulate in f32 via preferred_element_type, as "
                      "ops.lm_head does"),
            details={"count": len(upcast_hits), "source_dtype": src}))
    if f64_count:
        prim, scope = f64_first
        findings.append(Finding(
            rule="dtype/f64-op", severity="error",
            message=(f"{f64_count} op(s) compute in f64/c128 (first: "
                     f"{prim} at {scope or '<top>'}) — unintended x64 "
                     "promotion; TPUs emulate f64 at ~1/100 rate"),
            scope=scope, op=prim,
            fix_hint=("keep jax_enable_x64 off, or cast the offending "
                      "input to f32 at the boundary"),
            details={"count": f64_count}))
    return findings


def analyze_donation(program, config):
    """Input leaves aliasable with outputs but not donated."""
    import jax
    jaxpr = program.closed_jaxpr()
    leaves = program.arg_leaves()
    invars = jaxpr.jaxpr.invars
    if len(invars) != len(leaves):
        return []                      # closure-captured consts etc.
    out_avals = [getattr(v, "aval", None) for v in jaxpr.jaxpr.outvars]

    def sig(aval):
        shape = getattr(aval, "shape", None)
        dtype = getattr(aval, "dtype", None)
        return (None if shape is None else tuple(shape), str(dtype))

    out_pool = {}
    for aval in out_avals:
        s = sig(aval)
        out_pool[s] = out_pool.get(s, 0) + 1
    donated = set(program.donate_argnums)
    # donated inputs claim their matching outputs first
    for argnum, path, leaf in leaves:
        if argnum in donated:
            s = sig(getattr(leaf, "aval", None) or _np_aval(leaf))
            if out_pool.get(s, 0) > 0:
                out_pool[s] -= 1
    # remaining matches against non-donated inputs, grouped per argnum
    per_arg = {}
    for argnum, path, leaf in leaves:
        if argnum in donated:
            continue
        aval = getattr(leaf, "aval", None) or _np_aval(leaf)
        s = sig(aval)
        if s[0] is None or out_pool.get(s, 0) <= 0:
            continue
        out_pool[s] -= 1
        nbytes = int(np.prod(s[0], dtype=np.int64) *
                     np.dtype(s[1]).itemsize) if s[0] is not None else 0
        ex_bytes, ex_count, ex_path = per_arg.get(argnum, (0, 0, path))
        per_arg[argnum] = (ex_bytes + nbytes, ex_count + 1, ex_path)
    findings = []
    for argnum, (nbytes, count, path) in sorted(per_arg.items()):
        if nbytes < config.donation_min_bytes:
            continue
        findings.append(Finding(
            rule="donation/missing", severity="warning",
            message=(f"arg {argnum} has {count} leaf(s) totalling "
                     f"{nbytes:,} B whose shape/dtype matches an output "
                     f"but is not donated (first leaf {path!r}) — both "
                     "copies are live across the step (double-HBM "
                     "hazard)"),
            scope=f"arg{argnum}", op="",
            fix_hint=(f"add {argnum} to donate_argnums (and stop reading "
                      "the input buffer after the call)"),
            details={"argnum": argnum, "aliasable_bytes": nbytes,
                     "leaves": count, "example_path": path}))
    return findings


def _np_aval(leaf):
    class _A:
        def __init__(self, x):
            x = np.asarray(x)
            self.shape, self.dtype = x.shape, x.dtype
    return _A(leaf)


def analyze_host_sync(program, config):
    """Callbacks / debug prints / infeed-outfeed inside the program."""
    hits = []
    for jaxpr in _all_jaxprs(program.closed_jaxpr()):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in _CALLBACK_PRIMS or name.endswith("_callback"):
                hits.append((name, _scope(eqn)))
    findings = []
    seen = set()
    for name, scope in hits:
        key = (name, scope)
        if key in seen:
            continue
        seen.add(key)
        findings.append(Finding(
            rule="host-sync/callback", severity="warning",
            message=(f"`{name}` reachable from the step fn at "
                     f"{scope or '<top>'} — a device->host round trip "
                     "per step (the class of sync PR 5 cut 2->1 by "
                     "hand)"),
            scope=scope or name, op=name,
            fix_hint=("move the readback out of the step (batch it with "
                      "the telemetry vector) or gate it behind a debug "
                      "flag"),
            details={"primitive": name}))
    return findings


def analyze_recompile(program, config):
    """Static args that cannot hash or hash by identity."""
    findings = []
    for i in program.static_argnums:
        if i >= len(program.args):
            continue
        v = program.args[i]
        try:
            hash(v)
        except TypeError:
            findings.append(Finding(
                rule="recompile/unhashable-static", severity="error",
                message=(f"static arg {i} ({type(v).__name__}) is "
                         "unhashable — jit raises at call time"),
                scope=f"arg{i}", op=type(v).__name__,
                fix_hint=("pass it as a hashable (tuple / frozen "
                          "dataclass) or make it a traced arg"),
                details={"argnum": i, "type": type(v).__name__}))
            continue
        t = type(v)
        if (t.__hash__ is object.__hash__
                and getattr(t, "__eq__", None) is object.__eq__):
            findings.append(Finding(
                rule="recompile/identity-static", severity="warning",
                message=(f"static arg {i} ({t.__name__}) hashes by "
                         "object identity — every fresh instance "
                         "silently retraces and recompiles"),
                scope=f"arg{i}", op=t.__name__,
                fix_hint=("pass a module-level singleton, or give the "
                          "type __eq__/__hash__ over its contents"),
                details={"argnum": i, "type": t.__name__}))
    return findings
