"""The lint driver: analyzer registry, config, and the `lint()` entry.

Usage::

    from apex_tpu.analysis import lint, LintProgram

    report = lint(LintProgram("train_step", fn=step, args=(params, batch),
                              donate_argnums=(0,)))
    print(report.format_table())

or, for the common case::

    report = lint_fn(step, params, batch, name="train_step")

Analyzers are plain ``(LintProgram, LintConfig) -> [Finding]``
functions in a registry keyed by name; jaxpr-level analyzers are
skipped automatically when the program was built from a prebuilt
``Lowered``/``Compiled`` (no fn to retrace).  The memory estimator runs
unless disabled and its result rides on the report.

Linting is compile-only — the program is traced, lowered and compiled
but never executed, so donated-input programs and collective programs
lint safely on a single host.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from apex_tpu.analysis.findings import Finding, LintReport
from apex_tpu.analysis.program import LintProgram
from apex_tpu.analysis import jaxpr_rules, hlo_rules
from apex_tpu.analysis.memory import estimate_peak_memory


@dataclasses.dataclass
class LintConfig:
    """Thresholds and switches shared by all analyzers."""
    # tensors at or above this are "large" for the sharding rules
    large_bytes: int = 64 << 20
    # donation findings below this aliasable-bytes total are dropped
    # (tiny scalars/counters are not worth donating)
    donation_min_bytes: int = 1 << 10
    # analyzer names to run; None = the full registry
    analyzers: Optional[Sequence[str]] = None
    # attach the peak-memory estimate (and its XLA cross-check)
    estimate_memory: bool = True


# name -> (needs_jaxpr, analyzer fn)
ANALYZERS: Dict[str, Tuple[bool, Callable]] = {
    "dtype": (True, jaxpr_rules.analyze_dtype_promotion),
    "donation": (True, jaxpr_rules.analyze_donation),
    "host-sync": (True, jaxpr_rules.analyze_host_sync),
    "recompile": (True, jaxpr_rules.analyze_recompile),
    "sharding": (False, hlo_rules.analyze_sharding),
    "overlap": (False, hlo_rules.analyze_overlap),
}


def lint(program: LintProgram,
         config: Optional[LintConfig] = None) -> LintReport:
    """Run every applicable analyzer over one program."""
    config = config or LintConfig()
    names = list(config.analyzers) if config.analyzers is not None \
        else list(ANALYZERS)
    t0 = time.perf_counter()
    findings: List[Finding] = []
    ran: List[str] = []
    for name in names:
        if name not in ANALYZERS:
            raise KeyError(
                f"unknown analyzer {name!r}; have {sorted(ANALYZERS)}")
        needs_jaxpr, fn = ANALYZERS[name]
        if needs_jaxpr and not program.has_jaxpr:
            continue
        findings.extend(fn(program, config))
        ran.append(name)
    memory = None
    if config.estimate_memory:
        memory = estimate_peak_memory(program.get_compiled())
    return LintReport(
        program=program.name, findings=findings, memory=memory,
        analyzers=ran, elapsed_s=time.perf_counter() - t0)


def lint_fn(fn: Callable, *args, name: Optional[str] = None,
            static_argnums: Sequence[int] = (),
            donate_argnums: Sequence[int] = (),
            config: Optional[LintConfig] = None,
            **jit_kwargs) -> LintReport:
    """Convenience wrapper: lint a jittable fn on example args."""
    return lint(LintProgram(
        name=name or getattr(fn, "__name__", "program"),
        fn=fn, args=args,
        static_argnums=tuple(static_argnums),
        donate_argnums=tuple(donate_argnums),
        jit_kwargs=jit_kwargs), config)
