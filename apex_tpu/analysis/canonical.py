"""The canonical train/serve programs the lint gate covers.

Six programs spanning every execution shape the repo ships: the GPT
train step at dp=N, at tp=2 + sequence parallelism, and at pp=2 (ring
1F1B under a ``while``); the anomaly-guarded train step; and the two
serving programs (batch prefill, cache-ring decode).  Each is the SAME
idiom the ``__graft_entry__`` dryrun legs and the benchmarks use —
linting a toy stand-in would gate nothing.

Models are tiny (vocab 32, hidden 16, 2 layers): the lint rules key on
STRUCTURE (dataflow, donation, collective chains), not size, and tiny
programs keep the CI leg seconds-cheap.  Builders construct fn + args
only; compilation happens lazily inside ``lint()``.

``tools/lint_graph.py`` runs these against the committed baseline
(``tools/lint_baseline.json``); the ``_dryrun_lint`` entry leg carries
the same check on the 8-device CPU mesh.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from apex_tpu.analysis.program import LintProgram

TINY = dict(vocab_size=32, hidden_size=16, num_layers=2,
            num_attention_heads=4, max_seq_len=8)


def _tiny_batch(n_rows: int, seq: int, seed: int = 0):
    import jax.numpy as jnp
    import numpy as np
    r = np.random.RandomState(seed)
    return (jnp.asarray(r.randint(0, TINY["vocab_size"], (n_rows, seq))),
            jnp.asarray(r.randint(0, TINY["vocab_size"], (n_rows, seq))))


def make_gpt_train_dp(n_devices: int) -> LintProgram:
    """Data-parallel GPT train step: shard_map grads + pmean + FusedAdam,
    params and opt state donated."""
    import jax
    from jax.sharding import PartitionSpec as P

    from apex_tpu.models.gpt import GPTConfig, GPTModel
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.utils.collectives import shard_map_compat

    dp = max(2, n_devices)
    mesh = jax.make_mesh((dp,), ("data",), devices=jax.devices()[:dp])
    model = GPTModel(GPTConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(0))
    adam = FusedAdam(lr=1e-3)
    opt_state = adam.init(params)

    def dp_body(p, tk, tg):
        loss, g = jax.value_and_grad(model.loss)(p, tk, tg)
        return (jax.lax.pmean(loss, "data"),
                jax.tree_util.tree_map(
                    lambda a: jax.lax.pmean(a, "data"), g))

    grad = shard_map_compat(dp_body, mesh=mesh,
                            in_specs=(P(), P("data"), P("data")),
                            out_specs=(P(), P()))

    def train_step(p, opt, tk, tg):
        loss, g = grad(p, tk, tg)
        new_p, new_opt = adam.step(g, p, opt)
        return loss, new_p, new_opt

    tokens, targets = _tiny_batch(dp * 2, TINY["max_seq_len"], seed=1)
    return LintProgram("gpt_train_dp", fn=train_step,
                       args=(params, opt_state, tokens, targets),
                       donate_argnums=(0, 1))


def make_gpt_train_tp_sp(n_devices: int) -> LintProgram:
    """tp=2 + sequence-parallel GPT train step (Megatron-SP collective
    algebra: gather(tiled)/psum_scatter edges)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from apex_tpu.models.gpt import (GPTConfig, GPTModel,
                                     pack_for_shard_map)
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.utils.collectives import shard_map_compat

    tp = 2
    if n_devices < tp:
        raise ValueError(f"gpt_train_tp_sp needs >= {tp} devices")
    mesh = jax.make_mesh((tp,), ("model",), devices=jax.devices()[:tp])
    model = GPTModel(GPTConfig(tensor_parallel_size=tp, axis_name="model",
                               sequence_parallel=True, **TINY))
    init = GPTModel(GPTConfig(**TINY)).init_params(jax.random.PRNGKey(2))
    packed, in_specs, local_fn, repack_fn = pack_for_shard_map(model, init)
    adam = FusedAdam(lr=1e-3)
    opt_state = adam.init(packed)

    def body(sp, tk, tg):
        loss, g = jax.value_and_grad(model.loss)(local_fn(sp), tk, tg)
        return loss, repack_fn(g)

    grad = shard_map_compat(body, mesh=mesh,
                            in_specs=(in_specs, P(), P()),
                            out_specs=(P(), in_specs))

    def train_step(p, opt, tk, tg):
        loss, g = grad(p, tk, tg)
        new_p, new_opt = adam.step(g, p, opt)
        return loss, new_p, new_opt

    tokens, targets = _tiny_batch(2, TINY["max_seq_len"], seed=2)
    return LintProgram("gpt_train_tp_sp", fn=train_step,
                       args=(packed, opt_state, tokens, targets),
                       donate_argnums=(0, 1))


def make_gpt_train_pp(n_devices: int) -> LintProgram:
    """pp=2 GPT train step: ring 1F1B ``pipeline_step`` under shard_map
    on the (data, pipe) mesh from ``parallel_state``."""
    import jax
    from jax.sharding import PartitionSpec as P

    from apex_tpu.models.gpt import (GPTConfig, GPTModel,
                                     pack_for_shard_map, pipeline_step)
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.transformer import parallel_state
    from apex_tpu.utils.collectives import shard_map_compat

    pp = 2
    if n_devices < pp:
        raise ValueError(f"gpt_train_pp needs >= {pp} devices")
    parallel_state.destroy_model_parallel()
    mesh = parallel_state.initialize_model_parallel(
        1, pp, devices=jax.devices()[:n_devices])
    dp = parallel_state.get_data_parallel_world_size()

    kw = dict(TINY, num_layers=2 * pp)
    model = GPTModel(GPTConfig(**kw))
    params = model.init_params(jax.random.PRNGKey(3))
    M, mb, seq = 2, 2, kw["max_seq_len"]
    packed, in_specs, local_fn, repack_fn = pack_for_shard_map(
        model, params, n_stages=pp, tensor_axis=None)
    adam = FusedAdam(lr=1e-3)
    opt_state = adam.init(packed)

    def grad_step(sp, tokens, targets):
        tk = tokens.reshape(M, mb, seq)
        tg = targets.reshape(M, mb, seq)
        loss, g = pipeline_step(model, local_fn(sp), tk, tg,
                                pipe_axis="pipe", data_axis="data")
        return loss, repack_fn(g)

    def train_step(p, opt, tokens, targets):
        loss, grads = shard_map_compat(
            grad_step, mesh=mesh,
            in_specs=(in_specs, P("data"), P("data")),
            out_specs=(P(), in_specs))(p, tokens, targets)
        new_p, new_opt = adam.step(grads, p, opt)
        return loss, new_p, new_opt

    tokens, targets = _tiny_batch(dp * M * mb, seq, seed=3)
    return LintProgram("gpt_train_pp", fn=train_step,
                       args=(packed, opt_state, tokens, targets),
                       donate_argnums=(0, 1))


def make_guarded_step(n_devices: int) -> LintProgram:
    """The anomaly-guarded train step's jitted core (`_raw_step`):
    detect/skip/telemetry fused with the optimizer update, full train
    state donated (the ``donate=True`` guard configuration)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.models.gpt import GPTConfig, GPTModel
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.resilience import GuardedTrainStep
    from apex_tpu.resilience.guard import _null_scaler_state

    model = GPTModel(GPTConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(4))
    adam = FusedAdam(lr=1e-3)
    guard = GuardedTrainStep(model.loss, adam, donate=True)
    opt_state = adam.init(params)
    gstate = guard.init_state()
    sstate = _null_scaler_state()
    inj = jnp.asarray([0.0, 0.0, 1.0], jnp.float32)
    tokens, targets = _tiny_batch(2, TINY["max_seq_len"], seed=4)
    return LintProgram(
        "guarded_step", fn=guard._raw_step,
        args=(params, opt_state, gstate, sstate, inj, tokens, targets),
        donate_argnums=(0, 1, 2, 3))


def make_prefill(n_devices: int) -> LintProgram:
    """Serving prefill: full-prompt forward returning (logits, kv).
    Nothing donated — params serve every subsequent request."""
    import jax

    from apex_tpu.models.gpt import GPTConfig, GPTModel

    model = GPTModel(GPTConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(5))
    tokens, _ = _tiny_batch(1, TINY["max_seq_len"], seed=5)
    return LintProgram("prefill", fn=model.prefill, args=(params, tokens))


def make_decode(n_devices: int) -> LintProgram:
    """Serving decode: one batched step over the KV-cache slot ring,
    cache donated (the in-place update the inference engine relies on —
    without it every step holds two full caches)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.models.gpt import GPTConfig, GPTModel

    model = GPTModel(GPTConfig(**TINY))
    params = model.init_params(jax.random.PRNGKey(6))
    slots = 4
    head_dim = TINY["hidden_size"] // TINY["num_attention_heads"]
    cache = jnp.zeros((slots, TINY["num_layers"], 2, TINY["max_seq_len"],
                       TINY["num_attention_heads"], head_dim),
                      jnp.float32)
    tokens = jnp.zeros((slots,), jnp.int32)
    positions = jnp.ones((slots,), jnp.int32)
    return LintProgram("decode", fn=model.decode_step,
                       args=(params, tokens, cache, positions),
                       donate_argnums=(2,))


BUILDERS: Dict[str, Callable[[int], LintProgram]] = {
    "gpt_train_dp": make_gpt_train_dp,
    "gpt_train_tp_sp": make_gpt_train_tp_sp,
    "gpt_train_pp": make_gpt_train_pp,
    "guarded_step": make_guarded_step,
    "prefill": make_prefill,
    "decode": make_decode,
}


def canonical_programs(names: Optional[Sequence[str]] = None,
                       n_devices: Optional[int] = None
                       ) -> List[LintProgram]:
    """Build the requested canonical programs (all six by default)."""
    import jax
    if n_devices is None:
        n_devices = jax.device_count()
    names = list(names) if names else list(BUILDERS)
    out = []
    for name in names:
        if name not in BUILDERS:
            raise KeyError(
                f"unknown canonical program {name!r}; have "
                f"{sorted(BUILDERS)}")
        out.append(BUILDERS[name](n_devices))
    return out
