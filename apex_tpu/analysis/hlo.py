"""A tolerant parser for optimized HLO text.

The compiled module's ``as_text()`` is the one artifact that cannot
drift from what executes: post-GSPMD, post-fusion, and (on every
backend this repo targets) SCHEDULED — ``is_scheduled=true`` in the
module header means the instruction order inside each computation IS
the execution order, which is what makes text-level liveness analysis
(:mod:`apex_tpu.analysis.memory`) meaningful.

This is not a full HLO grammar; it extracts exactly what the analyzers
need per instruction: name, result shape(s) with byte sizes, opcode,
operand names, called computations, ``sharding``/``replica_groups``
attributes and the ``op_name`` metadata carrying ``named_scope``
provenance.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Tuple

from apex_tpu.observability.comms import _DTYPE_BYTES, _SHAPE_RE

_METADATA_OP_NAME_RE = re.compile(r'op_name="([^"]+)"')
# one attribute = one computation; branch lists are brace-wrapped.  The
# two cannot share a comma-continuation regex: `condition=%c, body=%b`
# would slurp `, body` into the condition's name.
_CALLED_SINGLE_RE = re.compile(
    r"(?:to_apply|body|condition|true_computation|false_computation"
    r"|calls)=\{?%?([\w\.\-]+)\}?")
_CALLED_MULTI_RE = re.compile(r"branch_computations=\{([^}]*)\}")
_OPERAND_RE = re.compile(r"%([\w\.\-]+)")
_SHARDING_RE = re.compile(r"sharding=\{([^}]*)\}")
_ALIAS_RE = re.compile(r"\{\s*(\d*)\s*\}\s*:\s*\(\s*(\d+)")
_NUM_PARTITIONS_RE = re.compile(r"num_partitions=(\d+)")

# ops that define no new buffer: views over their operands
VIEW_OPS = frozenset({"get-tuple-element", "tuple", "bitcast", "parameter"})
# ops whose body we recurse into for the memory estimate
CALL_OPS = frozenset({"while", "call", "conditional"})
# "light" ops a dataflow chain may cross while still counting as the
# same value (no real compute) — used by the overlap/roundtrip rules
LIGHT_OPS = frozenset({"convert", "bitcast", "copy", "reshape",
                       "transpose", "get-tuple-element", "tuple"})


def shape_bytes(type_str: str) -> int:
    """Total bytes of an HLO type string (tuple types sum elements)."""
    total = 0
    for dt, dims in _SHAPE_RE.findall(type_str):
        width = _DTYPE_BYTES.get(dt)
        if width is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * width
    return total


def scope_of(op_name: Optional[str]) -> str:
    """named_scope provenance from an ``op_name`` metadata string:
    ``jit(f)/jit(main)/attn/psum`` -> ``attn/psum`` (jit/pjit frames
    dropped, user scopes and the primitive kept)."""
    if not op_name:
        return ""
    parts = [p for p in op_name.split("/")
             if not (p.startswith("jit(") or p.startswith("pjit("))]
    return "/".join(parts)


@dataclasses.dataclass
class Instruction:
    name: str
    opcode: str
    type_str: str
    nbytes: int
    operands: List[str]
    called: List[str]
    line: str
    index: int
    is_root: bool = False
    is_param: bool = False
    param_number: Optional[int] = None

    @property
    def scope(self) -> str:
        m = _METADATA_OP_NAME_RE.search(self.line)
        return scope_of(m.group(1) if m else None)

    @property
    def sharding(self) -> Optional[str]:
        m = _SHARDING_RE.search(self.line)
        return m.group(1) if m else None

    @property
    def replica_group_size(self) -> Optional[int]:
        m = re.search(r"replica_groups=\{?\{([0-9,]+)\}", self.line)
        return len(m.group(1).split(",")) if m else None


@dataclasses.dataclass
class Computation:
    name: str
    instructions: List[Instruction]
    is_entry: bool = False

    @property
    def root(self) -> Instruction:
        for ins in self.instructions:
            if ins.is_root:
                return ins
        return self.instructions[-1]

    @property
    def params(self) -> List[Instruction]:
        return sorted((i for i in self.instructions if i.is_param),
                      key=lambda i: i.param_number or 0)

    def by_name(self) -> Dict[str, Instruction]:
        return {i.name: i for i in self.instructions}


@dataclasses.dataclass
class HloModule:
    header: str
    computations: Dict[str, Computation]
    entry: Computation

    @property
    def input_output_aliases(self) -> List[Tuple[int, int]]:
        """``(output_index, param_number)`` pairs from the module-level
        ``input_output_alias`` attribute (donated buffers).  The braces
        nest (``{ {0}: (0, {}, may-alias) }``), so scan to the balanced
        close instead of regexing for the first ``}``.  A non-tuple
        output aliases as ``{}: (...)`` — empty index means output 0."""
        start = self.header.find("input_output_alias={")
        if start < 0:
            return []
        i = start + len("input_output_alias=")
        depth = 0
        for j in range(i, len(self.header)):
            depth += (self.header[j] == "{") - (self.header[j] == "}")
            if depth == 0:
                body = self.header[i + 1:j]
                break
        else:
            return []
        return [(int(o or 0), int(p))
                for o, p in _ALIAS_RE.findall(body)]

    @property
    def num_partitions(self) -> int:
        m = _NUM_PARTITIONS_RE.search(self.header)
        return int(m.group(1)) if m else 1

    @property
    def is_scheduled(self) -> bool:
        return "is_scheduled=true" in self.header


def _parse_type_and_opcode(rhs: str) -> Tuple[str, str, str]:
    """Split the right-hand side of ``name = <type> <opcode>(...)``.
    Tuple types contain parens, so match brackets for a leading ``(``."""
    rhs = rhs.strip()
    if rhs.startswith("("):
        depth = 0
        for i, c in enumerate(rhs):
            depth += (c == "(") - (c == ")")
            if depth == 0:
                type_str = rhs[:i + 1]
                rest = rhs[i + 1:].strip()
                break
        else:                                    # unbalanced: bail
            type_str, rest = "", rhs
    else:
        type_str, _, rest = rhs.partition(" ")
    m = re.match(r"([\w\-]+)", rest)
    opcode = m.group(1) if m else ""
    tail = rest[m.end():] if m else rest
    return type_str, opcode, tail


def parse_hlo_module(text: str) -> HloModule:
    """Parse ``compiled.as_text()`` into computations + instructions."""
    lines = text.splitlines()
    header = lines[0] if lines else ""
    computations: Dict[str, Computation] = {}
    entry_name = None
    current: Optional[Computation] = None
    for line in lines[1:]:
        stripped = line.strip()
        if not stripped or stripped.startswith("//"):
            continue
        if stripped.endswith("{") and "=" not in stripped.split("(")[0]:
            # computation open: `%name (args) -> type {` or `ENTRY %name …`
            is_entry = stripped.startswith("ENTRY")
            m = re.search(r"%([\w\.\-]+)", stripped)
            if not m:
                continue
            current = Computation(m.group(1), [], is_entry=is_entry)
            computations[current.name] = current
            if is_entry:
                entry_name = current.name
            continue
        if stripped.startswith("}"):
            current = None
            continue
        if current is None or " = " not in stripped:
            continue
        lhs, _, rhs = stripped.partition(" = ")
        is_root = lhs.startswith("ROOT ")
        name = lhs.replace("ROOT ", "").strip().lstrip("%")
        type_str, opcode, tail = _parse_type_and_opcode(rhs)
        if not opcode:
            continue
        # operands: %refs in the call parens only (drop attribute refs —
        # called computations are captured separately)
        paren = tail.partition("(")[2]
        depth, end = 1, len(paren)
        for i, c in enumerate(paren):
            depth += (c == "(") - (c == ")")
            if depth == 0:
                end = i
                break
        operands = _OPERAND_RE.findall(paren[:end])
        attrs = tail[end:] if end < len(tail) else tail
        called = [m2.group(1)
                  for m2 in _CALLED_SINGLE_RE.finditer(attrs)]
        for m2 in _CALLED_MULTI_RE.finditer(attrs):
            for nm in m2.group(1).split(","):
                called.append(nm.strip().lstrip("%"))
        ins = Instruction(
            name=name, opcode=opcode, type_str=type_str,
            nbytes=shape_bytes(type_str), operands=operands,
            called=called, line=stripped,
            index=len(current.instructions), is_root=is_root,
            is_param=(opcode == "parameter"))
        if ins.is_param:
            pm = re.match(r"\s*(\d+)", tail.partition("(")[2])
            ins.param_number = int(pm.group(1)) if pm else None
        current.instructions.append(ins)
    if entry_name is None:
        # fall back: last computation
        entry_name = list(computations)[-1] if computations else ""
    entry = computations.get(entry_name) or Computation("", [])
    return HloModule(header=header, computations=computations, entry=entry)
