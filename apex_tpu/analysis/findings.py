"""Structured lint findings and baseline bookkeeping.

A :class:`Finding` is one analyzer hit: a stable rule id
(``family/name``), a severity, a human message, provenance (the
``named_scope``/arg path the op came from) and a fix hint.  Findings are
designed to be DIFFED against a committed baseline file: ``key`` is the
(rule, scope) pair only — byte counts, shapes and op counts live in
``details`` so a config tweak that changes sizes does not churn the
baseline, while a new rule firing in a new place does.

A :class:`LintReport` is one program's lint result (findings + the peak
memory estimate); :func:`load_baseline`/:func:`save_baseline` persist
the accepted-finding keys per program, and
:meth:`LintReport.new_findings` is the CI gate: anything not in the
baseline fails the run.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence

SEVERITIES = ("error", "warning", "info")
_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}


@dataclasses.dataclass
class Finding:
    """One analyzer hit.

    ``rule`` — stable ``family/name`` id (see ``docs/source/analysis.md``
    for the catalog); ``scope`` — provenance: a ``named_scope`` path for
    device ops, an ``argN(path)`` string for argument-level rules;
    ``op`` — the jaxpr primitive or HLO opcode involved; ``fix_hint`` —
    one actionable sentence; ``details`` — sizes/counts/paths (never part
    of the baseline key).
    """
    rule: str
    severity: str
    message: str
    scope: str = ""
    op: str = ""
    fix_hint: str = ""
    details: Dict = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"severity must be one of {SEVERITIES}, got "
                f"{self.severity!r}")

    @property
    def key(self) -> str:
        """Baseline identity: rule + scope (sizes/counts excluded)."""
        return f"{self.rule}|{self.scope}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Finding":
        return cls(**d)


@dataclasses.dataclass
class LintReport:
    """All findings for one linted program."""
    program: str
    findings: List[Finding] = dataclasses.field(default_factory=list)
    memory: Optional["object"] = None     # analysis.memory.MemoryEstimate
    analyzers: List[str] = dataclasses.field(default_factory=list)
    elapsed_s: float = 0.0

    def counts(self) -> Dict[str, int]:
        out = {s: 0 for s in SEVERITIES}
        for f in self.findings:
            out[f.severity] += 1
        return out

    def sorted_findings(self) -> List[Finding]:
        return sorted(self.findings,
                      key=lambda f: (_SEV_RANK[f.severity], f.rule, f.scope))

    def new_findings(self, baseline_keys: Sequence[str]) -> List[Finding]:
        """Findings not accepted by the baseline (the CI failure set)."""
        accepted = set(baseline_keys)
        return [f for f in self.sorted_findings() if f.key not in accepted]

    def to_dict(self) -> dict:
        mem = None
        if self.memory is not None:
            mem = (self.memory.to_dict()
                   if hasattr(self.memory, "to_dict") else self.memory)
        return {"program": self.program,
                "findings": [f.to_dict() for f in self.sorted_findings()],
                "counts": self.counts(),
                "memory": mem,
                "analyzers": list(self.analyzers),
                "elapsed_s": round(self.elapsed_s, 3)}

    def format_table(self) -> str:
        """Human-readable per-program table."""
        lines = [f"== {self.program} "
                 f"({len(self.findings)} finding(s), "
                 f"{self.elapsed_s:.2f}s) =="]
        if not self.findings:
            lines.append("  clean")
        for f in self.sorted_findings():
            lines.append(f"  [{f.severity:<7}] {f.rule:<30} "
                         f"{f.scope or '-'}")
            lines.append(f"            {f.message}")
            if f.fix_hint:
                lines.append(f"            fix: {f.fix_hint}")
        if self.memory is not None:
            lines.append("  " + self.memory.format_summary().replace(
                "\n", "\n  "))
        return "\n".join(lines)


# -- baseline persistence ----------------------------------------------------

BASELINE_VERSION = 1


def save_baseline(path: str, reports: Sequence[LintReport]) -> None:
    """Write the accepted-findings baseline: per program, the sorted
    finding keys (rule|scope).  Details are NOT stored — the baseline
    accepts the finding, not its current byte counts."""
    data = {"version": BASELINE_VERSION,
            "programs": {r.program: sorted({f.key for f in r.findings})
                         for r in reports}}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def load_baseline(path: str) -> Dict[str, List[str]]:
    """Load ``{program: [finding keys]}``; missing programs lint against
    an empty accepted set (every finding is new)."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline version {data.get('version')!r} != "
            f"{BASELINE_VERSION} — regenerate with --write-baseline")
    return {k: list(v) for k, v in data.get("programs", {}).items()}
