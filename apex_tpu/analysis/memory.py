"""Peak-memory / liveness estimation from scheduled HLO.

The feasibility term the auto-parallel planner needs (AMP, GSPMD — both
prune candidate plans by predicted per-device memory before measuring
anything): given a compiled program's HLO text, estimate the per-device
peak bytes and name the top live-set contributors.

Method — classic linear-scan liveness over the SCHEDULED instruction
order (``is_scheduled=true``: the text order is the execution order):

* every non-view instruction defines a buffer of its result bytes, live
  from its position to its last use (the root's buffers to the end);
* ``parameter``/``get-tuple-element``/``tuple``/``bitcast`` are views —
  no new bytes, but they keep their source buffers alive;
* entry parameters are caller-owned: live for the whole program;
* donated inputs (``input_output_alias``) zero out the aliased OUTPUT
  buffers — the update writes in place, which is exactly the
  double-HBM hazard the donation lint rule is about;
* ``while``/``call``/``conditional`` recurse: the callee's internal
  peak is added at the call site (its parameters alias the caller's
  operands, so only genuinely new bytes count).

Fusion internals are invisible (their temps are register/scratch-sized
by construction), constants count at their position.  The estimate is
validated against ``compiled.memory_analysis()`` to within 1.5x in the
test suite and the CI dryrun leg.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from apex_tpu.analysis.hlo import (CALL_OPS, Computation, HloModule,
                                   VIEW_OPS, parse_hlo_module)


@dataclasses.dataclass
class MemoryEstimate:
    """Per-device peak-memory estimate for one compiled program."""
    peak_bytes: int
    argument_bytes: int
    output_bytes: int
    aliased_bytes: int            # output bytes served by donated inputs
    temp_peak_bytes: int          # peak - (args + outputs - aliased)
    top_live: List[Tuple[int, str, str]]   # (bytes, instr, scope) at peak
    xla_peak_bytes: Optional[int] = None   # from compiled.memory_analysis()
    xla_ratio: Optional[float] = None      # estimate / xla, when available

    def to_dict(self) -> dict:
        return {
            "peak_bytes": self.peak_bytes,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "aliased_bytes": self.aliased_bytes,
            "temp_peak_bytes": self.temp_peak_bytes,
            "top_live": [{"bytes": b, "instruction": n, "scope": s}
                         for b, n, s in self.top_live],
            "xla_peak_bytes": self.xla_peak_bytes,
            "xla_ratio": (None if self.xla_ratio is None
                          else round(self.xla_ratio, 3)),
        }

    def format_summary(self) -> str:
        lines = [f"peak ~{_fmt(self.peak_bytes)} "
                 f"(args {_fmt(self.argument_bytes)}, "
                 f"outputs {_fmt(self.output_bytes)}"
                 + (f" [{_fmt(self.aliased_bytes)} donated-in-place]"
                    if self.aliased_bytes else "")
                 + f", temps {_fmt(self.temp_peak_bytes)})"]
        if self.xla_peak_bytes is not None:
            lines[0] += (f"  vs XLA {_fmt(self.xla_peak_bytes)} "
                         f"({self.xla_ratio:.2f}x)")
        for b, name, scope in self.top_live[:10]:
            lines.append(f"  live@peak {_fmt(b):>10}  {name}"
                         + (f"  [{scope}]" if scope else ""))
        return "\n".join(lines)


def _fmt(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return (f"{n:.0f}{unit}" if unit == "B"
                    else f"{n / 1.0:.1f}{unit}")
        n /= 1024
    return f"{n}B"


def _storage_map(comp: Computation) -> Dict[str, frozenset]:
    """Map each instruction name to the set of DEFINING buffer names its
    value lives in (views forward their operands' storage).

    ``while`` forwards too: XLA's in-place loop execution aliases the
    init operand, the body parameter, the body root and the while result
    into ONE allocation, so a while defines no new bytes — its carry is
    whatever buffers built the init (and a chained scan, e.g. the 1F1B
    forward stash feeding the backward loop, shares a single carry
    allocation instead of double-counting)."""
    storage: Dict[str, frozenset] = {}
    by_name = comp.by_name()
    for ins in comp.instructions:
        if (ins.opcode in VIEW_OPS and ins.opcode != "parameter") \
                or ins.opcode == "while":
            s: frozenset = frozenset()
            for op in ins.operands:
                s |= storage.get(op, frozenset())
            storage[ins.name] = s
        else:
            storage[ins.name] = frozenset({ins.name})
    return storage


def _comp_peak(module: HloModule, comp: Computation,
               memo: Dict[Tuple[str, bool], int], *, entry: bool = False,
               zero_root: bool = False,
               aliased_outputs: frozenset = frozenset()
               ) -> Tuple[int, int, List[Tuple[int, str, str]]]:
    """(peak_bytes, output_bytes, top_live_at_peak) for one computation.

    Non-entry computations exclude their parameters (they alias caller
    buffers).  ``aliased_outputs`` (entry only) holds root tuple indices
    whose buffers are donated inputs — counted as zero new bytes.
    ``zero_root`` (while bodies) zeroes ALL root buffers: the next carry
    is written in place over the current one (XLA's in-place loop
    execution — dynamic-update-slice on the carry does not allocate), so
    only genuinely transient per-iteration temps count; the carry itself
    is the caller's ``while`` result.
    """
    instrs = comp.instructions
    if not instrs:
        return 0, 0, []
    storage = _storage_map(comp)
    by_name = comp.by_name()

    # buffer sizes: defining instructions only; views/params define none
    size: Dict[str, int] = {}
    def_pos: Dict[str, int] = {}
    for ins in instrs:
        if ins.is_param:
            if entry:
                size[ins.name] = ins.nbytes
                def_pos[ins.name] = 0
            continue
        if ins.opcode in VIEW_OPS or ins.opcode == "while":
            continue
        size[ins.name] = ins.nbytes
        def_pos[ins.name] = ins.index

    # root storage: the output buffers (live to the end)
    root = comp.root
    root_bufs = set(storage.get(root.name, frozenset()))
    if zero_root:
        for b in root_bufs:
            if b in size and not by_name[b].is_param:
                size[b] = 0
    if entry and aliased_outputs:
        # donated outputs write in place: zero those element buffers
        # (tuple roots alias per element; a non-tuple root is output 0)
        if root.opcode == "tuple":
            donated_ops = [root.operands[k] for k in aliased_outputs
                           if k < len(root.operands)]
        else:
            donated_ops = [root.name] if 0 in aliased_outputs else []
        for opnd in donated_ops:
            for b in storage.get(opnd, frozenset()):
                if b in size and not by_name[b].is_param:
                    size[b] = 0

    last_ref: Dict[str, int] = {b: p for b, p in def_pos.items()}
    for ins in instrs:
        for op in ins.operands:
            for b in storage.get(op, frozenset()):
                if b in last_ref:
                    last_ref[b] = max(last_ref[b], ins.index)
    end = len(instrs) - 1
    for b in root_bufs:
        if b in last_ref:
            last_ref[b] = end
    if entry:
        for ins in instrs:
            if ins.is_param:
                last_ref[ins.name] = end       # caller-owned

    # call-site transient: callee internal peak, live only at that index
    callee_extra: Dict[int, int] = {}
    for ins in instrs:
        if ins.opcode in CALL_OPS:
            zr = ins.opcode == "while"
            extra = 0
            for cname in ins.called:
                sub = module.computations.get(cname)
                if sub is None:
                    continue
                key = (cname, zr)
                if key not in memo:
                    memo[key] = 0              # cycle guard
                    memo[key] = _comp_peak(module, sub, memo,
                                           zero_root=zr)[0]
                extra = max(extra, memo[key])
            if extra:
                callee_extra[ins.index] = extra

    # sweep: +size at def, -size after last ref
    delta = [0] * (len(instrs) + 1)
    for b, sz in size.items():
        if sz <= 0:
            continue
        delta[def_pos[b]] += sz
        delta[last_ref[b] + 1] -= sz
    live = 0
    peak = 0
    peak_pos = 0
    for i in range(len(instrs)):
        live += delta[i]
        total = live + callee_extra.get(i, 0)
        if total > peak:
            peak, peak_pos = total, i

    # top live buffers at the peak position
    top = [(sz, b, by_name[b].scope) for b, sz in size.items()
           if sz > 0 and def_pos[b] <= peak_pos <= last_ref[b]]
    if peak_pos in callee_extra:
        top.append((callee_extra[peak_pos],
                    f"<{instrs[peak_pos].opcode} body "
                    f"{instrs[peak_pos].name}>",
                    instrs[peak_pos].scope))
    top.sort(key=lambda t: -t[0])

    out_bytes = sum(size.get(b, 0) for b in root_bufs)
    return peak, out_bytes, top[:10]


def estimate_from_hlo_text(text: str) -> MemoryEstimate:
    """Estimate per-device peak bytes from optimized HLO text."""
    module = parse_hlo_module(text)
    comp = module.entry
    aliases = module.input_output_aliases
    aliased_out = frozenset(o for o, _ in aliases)
    alias_params = {p for _, p in aliases}
    arg_bytes = sum(p.nbytes for p in comp.params)
    aliased_bytes = sum(p.nbytes for p in comp.params
                        if p.param_number in alias_params)
    memo: Dict[Tuple[str, bool], int] = {}
    peak, out_bytes, top = _comp_peak(module, comp, memo, entry=True,
                                      aliased_outputs=aliased_out)
    return MemoryEstimate(
        peak_bytes=peak,
        argument_bytes=arg_bytes,
        output_bytes=out_bytes + aliased_bytes,
        aliased_bytes=aliased_bytes,
        temp_peak_bytes=max(0, peak - arg_bytes - out_bytes),
        top_live=top)


def xla_peak_bytes(compiled) -> Optional[int]:
    """Comparable peak from ``compiled.memory_analysis()``:
    args + outputs + temps - aliased (donated outputs reuse argument
    memory).  ``None`` when the backend doesn't report, or reports all
    zeros (some backends stub the call out)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    try:
        total = (ma.argument_size_in_bytes + ma.output_size_in_bytes
                 + ma.temp_size_in_bytes - ma.alias_size_in_bytes)
    except AttributeError:
        return None
    return total if total > 0 else None


def estimate_peak_memory(compiled) -> MemoryEstimate:
    """Estimate from a jax ``Compiled`` object, with the XLA
    cross-check attached when the backend reports one."""
    est = estimate_from_hlo_text(compiled.as_text())
    xla = xla_peak_bytes(compiled)
    if xla:
        est.xla_peak_bytes = xla
        est.xla_ratio = est.peak_bytes / xla
    return est
