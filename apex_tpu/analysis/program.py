"""The artifact bundle the analyzers consume.

One :class:`LintProgram` wraps a jittable fn + example args (or a
prebuilt ``Lowered``/``Compiled``) and lazily materializes the three
representations the analyzer registry works over:

* the CLOSED JAXPR (user-level op stream with ``named_scope``
  provenance on every eqn) — dtype/donation/host-sync rules;
* the OPTIMIZED, SCHEDULED HLO text — sharding/overlap rules and the
  memory estimator (post-GSPMD, post-fusion: what actually executes);
* the COMPILED object — ``memory_analysis()`` cross-checks.

Everything is compile-only: linting never executes the program, so it
is safe on programs whose donation invalidates inputs and cheap enough
for a CI gate.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple


def _argnum_paths(args: Sequence, static_argnums: Sequence[int]
                  ) -> List[Tuple[int, str, Any]]:
    """Flatten dynamic args to ``(argnum, path, leaf)`` triples, in the
    order jit traces them (static args skipped)."""
    import jax
    static = set(static_argnums)
    out = []
    for i, a in enumerate(args):
        if i in static:
            continue
        flat = jax.tree_util.tree_flatten_with_path(a)[0]
        for path, leaf in flat:
            out.append((i, jax.tree_util.keystr(path), leaf))
    return out


@dataclasses.dataclass
class LintProgram:
    """Lazily-built analyzer inputs for one program."""
    name: str
    fn: Optional[Callable] = None
    args: Sequence = ()
    static_argnums: Tuple[int, ...] = ()
    donate_argnums: Tuple[int, ...] = ()
    jit_kwargs: Dict = dataclasses.field(default_factory=dict)
    lowered: Any = None            # prebuilt jax Lowered (optional)
    compiled: Any = None           # prebuilt jax Compiled (optional)

    _jaxpr: Any = dataclasses.field(default=None, repr=False)
    _hlo_text: Optional[str] = dataclasses.field(default=None, repr=False)
    _hlo_module: Any = dataclasses.field(default=None, repr=False)

    def __post_init__(self):
        if self.fn is None and self.lowered is None and \
                self.compiled is None:
            raise ValueError("pass fn+args, lowered=, or compiled=")
        self.static_argnums = tuple(self.static_argnums)
        self.donate_argnums = tuple(self.donate_argnums)

    # -- jaxpr level ---------------------------------------------------------

    @property
    def has_jaxpr(self) -> bool:
        return self.fn is not None

    def closed_jaxpr(self):
        if self._jaxpr is None:
            if self.fn is None:
                raise ValueError(
                    f"{self.name}: no fn — jaxpr-level analyzers need "
                    "the (fn, args) form")
            import jax
            self._jaxpr = jax.make_jaxpr(
                self.fn, static_argnums=self.static_argnums)(*self.args)
        return self._jaxpr

    def arg_leaves(self) -> List[Tuple[int, str, Any]]:
        return _argnum_paths(self.args, self.static_argnums)

    # -- HLO level -----------------------------------------------------------

    def get_compiled(self):
        if self.compiled is None:
            lowered = self.lowered
            if lowered is None:
                import jax
                lowered = jax.jit(
                    self.fn, static_argnums=self.static_argnums,
                    donate_argnums=self.donate_argnums,
                    **self.jit_kwargs).lower(*self.args)
            self.compiled = lowered.compile()
        return self.compiled

    def hlo_text(self) -> str:
        if self._hlo_text is None:
            self._hlo_text = self.get_compiled().as_text()
        return self._hlo_text

    def hlo_module(self):
        if self._hlo_module is None:
            from apex_tpu.analysis.hlo import parse_hlo_module
            self._hlo_module = parse_hlo_module(self.hlo_text())
        return self._hlo_module
