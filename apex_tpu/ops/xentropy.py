"""Fused softmax + cross-entropy — TPU rebuild of
``apex/contrib/csrc/xentropy/xentropy_kernel.cu`` (+
``apex/contrib/xentropy/softmax_xentropy.py``).

The fused kernel's value is memory, not math: forward computes the loss from
one pass (max, logsumexp, label pick) without materializing softmax;
backward reconstructs ``softmax - onehot`` from the saved logsumexp.  The
custom_vjp below has the same residual footprint (logits are the function's
own input; only ``lse`` and ``max`` are extra) and XLA fuses each pass.
Label smoothing matches apex: loss = (1-s)·nll + s·mean-over-classes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_f32 = jnp.float32


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def softmax_cross_entropy_loss(logits, labels, smoothing=0.0,
                               ignore_index=-100, half_to_float=False):
    """Per-example loss ``(N,)`` for logits ``(N, C)`` and int labels
    ``(N,)``; apex ``SoftmaxCrossEntropyLoss.apply`` semantics (half grads
    OK, ``ignore_index`` rows contribute zero loss and zero grad).
    ``half_to_float`` keeps the f32-computed loss at full precision instead
    of rounding to the logits dtype (apex's fused kernel returns f32)."""
    loss, _ = _xent_fwd(logits, labels, smoothing, ignore_index,
                        half_to_float)
    return loss


def _xent_fwd(logits, labels, smoothing, ignore_index, half_to_float=False):
    x = logits.astype(_f32)
    m = jnp.max(x, axis=-1, keepdims=True)
    lse = jnp.log(jnp.sum(jnp.exp(x - m), axis=-1)) + m[..., 0]
    n = x.shape[0]
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    picked = x[jnp.arange(n), safe_labels]
    nll = lse - picked
    if smoothing > 0.0:
        smooth_loss = lse - jnp.mean(x, axis=-1)
        loss = (1.0 - smoothing) * nll + smoothing * smooth_loss
    else:
        loss = nll
    loss = jnp.where(valid, loss, 0.0)
    if not half_to_float:
        loss = loss.astype(logits.dtype)
    return loss, (logits, safe_labels, valid, lse)


def _xent_bwd(smoothing, ignore_index, half_to_float, res, dloss):
    logits, labels, valid, lse = res
    x = logits.astype(_f32)
    n, c = x.shape
    soft = jnp.exp(x - lse[:, None])
    grad = soft
    onehot = jax.nn.one_hot(labels, c, dtype=_f32)
    if smoothing > 0.0:
        grad = grad - (1.0 - smoothing) * onehot - smoothing / c
    else:
        grad = grad - onehot
    grad = grad * jnp.where(valid, dloss.astype(_f32), 0.0)[:, None]
    return grad.astype(logits.dtype), None


softmax_cross_entropy_loss.defvjp(_xent_fwd, _xent_bwd)


class SoftmaxCrossEntropyLoss:
    """Class shim matching ``apex.contrib.xentropy.SoftmaxCrossEntropyLoss``
    (static ``apply``)."""

    @staticmethod
    def apply(logits, labels, smoothing=0.0, padding_idx=-100,
              half_to_float=False):
        return softmax_cross_entropy_loss(logits, labels, float(smoothing),
                                          int(padding_idx),
                                          bool(half_to_float))
