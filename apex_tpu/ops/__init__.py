"""Low-level fused ops (Pallas kernels with jnp fallbacks)."""

from apex_tpu.ops import multi_tensor

__all__ = ["multi_tensor"]
