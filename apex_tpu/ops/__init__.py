"""Low-level fused ops (Pallas kernels with jnp fallbacks)."""

from apex_tpu.ops import (layer_norm, multi_tensor, quant_gemm, rope,
                          softmax, xentropy)

__all__ = ["layer_norm", "multi_tensor", "quant_gemm", "rope", "softmax",
           "xentropy"]
