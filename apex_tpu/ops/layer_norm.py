"""Fused LayerNorm / RMSNorm kernels — TPU rebuild of
``csrc/layer_norm_cuda.cpp`` + ``csrc/layer_norm_cuda_kernel.cu``.

Design: rows are normalized over the last (hidden) axis.  The forward Pallas
kernel computes per-row mean/rstd with the E[x²]−E[x]² form in f32 (zero
padding of the hidden axis then needs no correction) and saves ``rstd`` (and
``mean`` for LN) for the backward.  The backward kernel produces ``dx`` plus
*per-block* partial ``dgamma``/``dbeta`` sums; the wrapper reduces partials
across blocks — the same two-stage reduction the CUDA kernel does across
thread blocks.

``memory_efficient=True`` (apex flag): the forward saves the *output* ``y``
instead of the input, and the backward reconstructs the normalized value as
``(y - beta) / gamma`` (RMS: ``y / gamma``), halving residual memory.  Like
apex, this requires gamma to be nonzero everywhere.

Inputs of any shape are flattened to ``(rows, hidden)``; hidden is padded to
a lane multiple and rows to a block multiple with zeros (sliced away after).
Off-TPU the same math runs as plain jnp.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.multi_tensor_apply.bucketing import LANE, _round_up
from apex_tpu.utils.collectives import sds_like
from apex_tpu.utils.platform import interpret_mode, use_pallas

_f32 = jnp.float32
# Per-operand block budget.  The bwd kernel materializes ~10 f32
# block-sized temporaries on Mosaic's scoped-vmem stack (16 MB limit), so
# the per-operand budget must stay well under limit/10 — 4 MB blocks OOM
# the scoped stack at hidden=1024 on v5e.
_VMEM_BUDGET = 1024 * 1024  # bytes per operand block


def _pick_block_rows(hidden_p: int) -> int:
    rows = _VMEM_BUDGET // (hidden_p * 4)
    return int(max(8, min(512, _round_up(rows, 8) - 8 if rows % 8 else rows)))


# ---------------------------------------------------------------------------
# shared math (single source of truth for Pallas kernel + jnp fallback)
# ---------------------------------------------------------------------------

def _ln_fwd_math(x, w, b, eps, hidden: int, rms: bool):
    """x: (rows, hidden_p) f32 zero-padded; returns (y, mean, rstd)."""
    inv_h = 1.0 / hidden
    if rms:
        mean = jnp.zeros((x.shape[0], 1), _f32)
        ms = jnp.sum(x * x, axis=1, keepdims=True) * inv_h
        rstd = jax.lax.rsqrt(ms + eps)
        xhat = x * rstd
    else:
        mean = jnp.sum(x, axis=1, keepdims=True) * inv_h
        ms = jnp.sum(x * x, axis=1, keepdims=True) * inv_h
        var = ms - mean * mean
        rstd = jax.lax.rsqrt(var + eps)
        xhat = (x - mean) * rstd
    y = xhat * w
    if b is not None:
        y = y + b
    return y, mean, rstd


def _ln_bwd_math(dy, xhat, w, rstd, hidden: int, rms: bool):
    """Returns (dx, dw_rowsum(hidden,), db_rowsum(hidden,))."""
    inv_h = 1.0 / hidden
    wdy = dy * w
    c1 = jnp.sum(wdy * xhat, axis=1, keepdims=True) * inv_h
    if rms:
        dx = (wdy - xhat * c1) * rstd
    else:
        c2 = jnp.sum(wdy, axis=1, keepdims=True) * inv_h
        dx = (wdy - xhat * c1 - c2) * rstd
    dw = jnp.sum(dy * xhat, axis=0)
    db = jnp.sum(dy, axis=0)
    return dx, dw, db


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------

def _fwd_kernel(rms, has_bias, eps, hidden, x_ref, w_ref, b_ref,
                y_ref, mean_ref, rstd_ref):
    x = x_ref[:].astype(_f32)
    w = w_ref[:].astype(_f32)
    b = b_ref[:].astype(_f32) if has_bias else None
    y, mean, rstd = _ln_fwd_math(x, w, b, eps, hidden, rms)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mean
    rstd_ref[:] = rstd


def _bwd_kernel(rms, from_y, has_bias, hidden, dy_ref, res_ref, w_ref, b_ref,
                mean_ref, rstd_ref, dx_ref, dwp_ref, dbp_ref):
    dy = dy_ref[:].astype(_f32)
    w = w_ref[:].astype(_f32)
    rstd = rstd_ref[:]
    if from_y:
        y = res_ref[:].astype(_f32)
        if has_bias:
            y = y - b_ref[:].astype(_f32)
        # guard the hidden-axis zero padding of gamma (0/0 → NaN would
        # poison the row reductions)
        xhat = y / jnp.where(w == 0.0, 1.0, w)
    else:
        x = res_ref[:].astype(_f32)
        xhat = (x - mean_ref[:]) * rstd if not rms else x * rstd
    dx, dw, db = _ln_bwd_math(dy, xhat, w, rstd, hidden, rms)
    dx_ref[:] = dx.astype(dx_ref.dtype)
    # partials blocks are 8 sublanes tall (TPU tiling minimum); row 0 holds
    # the sums, rows 1-7 stay zero and wash out in the cross-block reduce
    dwp_ref[:] = jnp.zeros_like(dwp_ref[:])
    dbp_ref[:] = jnp.zeros_like(dbp_ref[:])
    dwp_ref[0:1, :] = dw[None, :]
    dbp_ref[0:1, :] = db[None, :]


def _pallas_fwd(x2, w, b, eps, hidden, rms):
    rows, hidden_p = x2.shape
    br = _pick_block_rows(hidden_p)
    rows_p = _round_up(rows, br)
    if rows_p != rows:
        x2 = jnp.pad(x2, ((0, rows_p - rows), (0, 0)))
    has_bias = b is not None
    args = (x2, w.reshape(1, -1)) + ((b.reshape(1, -1),) if has_bias else ())
    row_spec = pl.BlockSpec((br, hidden_p), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    wb_spec = pl.BlockSpec((1, hidden_p), lambda i: (0, 0),
                           memory_space=pltpu.VMEM)
    stat_spec = pl.BlockSpec((br, 1), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    if has_bias:
        kernel = functools.partial(_fwd_kernel, rms, True, eps, hidden)
    else:
        def kernel(x_ref, w_ref, y_ref, mean_ref, rstd_ref,
                   _rms=rms, _eps=eps, _h=hidden):
            _fwd_kernel(_rms, False, _eps, _h, x_ref, w_ref, None,
                        y_ref, mean_ref, rstd_ref)
    y, mean, rstd = pl.pallas_call(
        kernel,
        grid=(rows_p // br,),
        in_specs=[row_spec, wb_spec] + ([wb_spec] if has_bias else []),
        out_specs=[row_spec, stat_spec, stat_spec],
        out_shape=[sds_like((rows_p, hidden_p), x2.dtype, x2),
                   sds_like((rows_p, 1), _f32, x2),
                   sds_like((rows_p, 1), _f32, x2)],
        interpret=interpret_mode(),
    )(*args)
    return y[:rows], mean[:rows], rstd[:rows]


def _pallas_bwd(dy2, res2, w, b, mean, rstd, hidden, rms, from_y):
    rows, hidden_p = dy2.shape
    br = _pick_block_rows(hidden_p)
    rows_p = _round_up(rows, br)
    pad = rows_p - rows
    if pad:
        dy2 = jnp.pad(dy2, ((0, pad), (0, 0)))
        res2 = jnp.pad(res2, ((0, pad), (0, 0)))
        mean = jnp.pad(mean, ((0, pad), (0, 0)))
        rstd = jnp.pad(rstd, ((0, pad), (0, 0)), constant_values=1.0)
    has_bias = b is not None
    nblocks = rows_p // br
    row_spec = pl.BlockSpec((br, hidden_p), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    wb_spec = pl.BlockSpec((1, hidden_p), lambda i: (0, 0),
                           memory_space=pltpu.VMEM)
    stat_spec = pl.BlockSpec((br, 1), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    part_spec = pl.BlockSpec((8, hidden_p), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    b_arr = b.reshape(1, -1) if has_bias else jnp.zeros((1, hidden_p), _f32)

    def kernel(dy_ref, res_ref, w_ref, b_ref, mean_ref, rstd_ref,
               dx_ref, dwp_ref, dbp_ref,
               _rms=rms, _fy=from_y, _hb=has_bias, _h=hidden):
        _bwd_kernel(_rms, _fy, _hb, _h, dy_ref, res_ref, w_ref, b_ref,
                    mean_ref, rstd_ref, dx_ref, dwp_ref, dbp_ref)

    dx, dwp, dbp = pl.pallas_call(
        kernel,
        grid=(nblocks,),
        in_specs=[row_spec, row_spec, wb_spec, wb_spec, stat_spec,
                  stat_spec],
        out_specs=[row_spec, part_spec, part_spec],
        out_shape=[sds_like((rows_p, hidden_p), dy2.dtype, dy2),
                   sds_like((nblocks * 8, hidden_p), _f32, dy2),
                   sds_like((nblocks * 8, hidden_p), _f32, dy2)],
        interpret=interpret_mode(),
    )(dy2, res2, w.reshape(1, -1), b_arr, mean, rstd)
    return dx[:rows], jnp.sum(dwp, axis=0), jnp.sum(dbp, axis=0)


# ---------------------------------------------------------------------------
# public functional ops with custom VJP
# ---------------------------------------------------------------------------

def _prep(x, hidden):
    """Flatten to (rows, hidden) and zero-pad hidden to a lane multiple."""
    rows = x.size // hidden
    x2 = x.reshape(rows, hidden)
    hidden_p = _round_up(hidden, LANE)
    if hidden_p != hidden:
        x2 = jnp.pad(x2, ((0, 0), (0, hidden_p - hidden)))
    return x2, hidden_p


def _pad_vec(v, hidden_p, dtype=_f32):
    v = v.reshape(-1).astype(dtype)
    if v.shape[0] != hidden_p:
        v = jnp.pad(v, (0, hidden_p - v.shape[0]))
    return v


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _norm_affine(x, weight, bias, hidden, eps, rms, memory_efficient):
    (y, _, _), _ = _norm_fwd(x, weight, bias, hidden, eps, rms,
                             memory_efficient)
    return y


def _norm_fwd(x, weight, bias, hidden, eps, rms, memory_efficient):
    orig_shape = x.shape
    x2, hidden_p = _prep(x, hidden)
    wp = _pad_vec(weight, hidden_p)
    bp = _pad_vec(bias, hidden_p) if bias is not None else None
    if use_pallas() and x2.dtype != jnp.float16:
        y2, mean, rstd = _pallas_fwd(x2, wp, bp, eps, hidden, rms)
    else:
        y2, mean, rstd = _ln_fwd_math(x2.astype(_f32), wp, bp, eps, hidden,
                                      rms)
        y2 = y2.astype(x2.dtype)
    y = y2[:, :hidden].reshape(orig_shape)
    res2 = y2 if memory_efficient else x2
    # dtypes ride along as zero-size carrier arrays (residuals must be
    # arrays; dx/dw/db cotangent dtypes must match the primals)
    carriers = (jnp.zeros((0,), x.dtype), jnp.zeros((0,), weight.dtype),
                None if bias is None else jnp.zeros((0,), bias.dtype))
    return (y, mean, rstd), (res2, wp, bp, mean, rstd, carriers)


def _norm_fwd_vjp(x, weight, bias, hidden, eps, rms, memory_efficient):
    (y, _, _), residuals = _norm_fwd(x, weight, bias, hidden, eps, rms,
                                     memory_efficient)
    return y, residuals


def _norm_bwd_vjp(hidden, eps, rms, memory_efficient, residuals, dy):
    res2, wp, bp, mean, rstd, (xc, wc, bc) = residuals
    orig_shape = dy.shape
    dy2, _ = _prep(dy, hidden)
    dy2 = dy2.astype(res2.dtype)
    if use_pallas() and res2.dtype != jnp.float16:
        dx2, dw, db = _pallas_bwd(dy2, res2, wp, bp, mean, rstd, hidden,
                                  rms, memory_efficient)
    else:
        dyf = dy2.astype(_f32)
        resf = res2.astype(_f32)
        if memory_efficient:
            yf = resf - bp if bp is not None else resf
            xhat = yf / jnp.where(wp == 0.0, 1.0, wp)
        else:
            xhat = (resf - mean) * rstd if not rms else resf * rstd
        dx2, dw, db = _ln_bwd_math(dyf, xhat, wp, rstd, hidden, rms)
    dx = dx2[:, :hidden].reshape(orig_shape).astype(xc.dtype)
    dw = dw[:hidden].astype(wc.dtype)
    if bc is None:
        return dx, dw, None
    return dx, dw, db[:hidden].astype(bc.dtype)


_norm_affine.defvjp(_norm_fwd_vjp, _norm_bwd_vjp)


def _affine(x, weight, bias, eps, rms, memory_efficient):
    hidden = int(weight.size)
    return _norm_affine(x, weight.reshape(-1),
                        None if bias is None else bias.reshape(-1),
                        hidden, float(eps), rms, bool(memory_efficient))


def fused_layer_norm_affine(x, weight, bias, normalized_shape=None,
                            eps=1e-5, memory_efficient=False):
    """apex ``fused_layer_norm_affine``: LN over the trailing dims with
    learnable gamma/beta."""
    return _affine(x, weight, bias, eps, False, memory_efficient)


def fused_rms_norm_affine(x, weight, normalized_shape=None, eps=1e-5,
                          memory_efficient=False):
    """apex ``fused_rms_norm_affine``: RMSNorm with learnable gamma."""
    return _affine(x, weight, None, eps, True, memory_efficient)


def fused_layer_norm(x, normalized_shape, eps=1e-5):
    """Non-affine LN (apex ``fused_layer_norm``)."""
    hidden = 1
    for d in normalized_shape:
        hidden *= d
    w = jnp.ones((hidden,), _f32)
    b = jnp.zeros((hidden,), _f32)
    return _norm_affine(x, w, b, hidden, float(eps), False, False)


def fused_rms_norm(x, normalized_shape, eps=1e-5):
    """Non-affine RMSNorm."""
    hidden = 1
    for d in normalized_shape:
        hidden *= d
    w = jnp.ones((hidden,), _f32)
    return _norm_affine(x, w, None, hidden, float(eps), True, False)
