"""Multi-tensor fused kernels — the TPU equivalent of apex's ``amp_C``.

Reference surface (``csrc/amp_C_frontend.cpp`` + ``csrc/multi_tensor_*.cu``):
``multi_tensor_scale``, ``multi_tensor_axpby``, ``multi_tensor_l2norm``,
``multi_tensor_adam``, ``multi_tensor_sgd``, ``multi_tensor_lamb`` (two
stages), ``multi_tensor_novograd``, ``multi_tensor_adagrad`` — each updates N
tensors with one kernel launch and carries a ``noop``/overflow side channel.

TPU design: tensors are packed per dtype into ``(rows, 128)`` buffers (see
``apex_tpu.multi_tensor_apply.bucketing``); each op is ONE Pallas kernel
sweeping the buffer with a 1-D grid (block = ``block_rows`` × 128 lanes on
the VPU), with scalars (lr, betas, loss-scale, …) in SMEM so they are traced
values — changing the learning rate does not recompile.  The overflow flag is
an f32 scalar kernel output accumulated across the sequential TPU grid;
optimizer kernels take a ``noop`` scalar and pass inputs through unchanged
when it is set, so a dynamic-loss-scale skip costs no host sync (apex
achieves the same with its ``noop_gpu`` buffer).

The update math of every op lives in ONE pure f32 function (``_*_math``)
called both from inside the Pallas kernel and from the jnp fallback used
off-TPU, so the two paths cannot diverge.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.multi_tensor_apply.bucketing import LANE
from apex_tpu.utils.platform import interpret_mode, use_pallas

_f32 = jnp.float32


def _use_kernel(*arrays) -> bool:
    """Route to the Pallas kernel unless off-TPU or a dtype Mosaic lacks.

    TPU Mosaic has no f16 vector type (bf16 is the native half precision);
    fp16 buckets — kept for apex API parity — take the jnp path, which XLA
    lowers with f32 compute.
    """
    if not use_pallas():
        return False
    return all(a.dtype != jnp.float16 for a in arrays)


def _grid(nrows: int, block_rows: int):
    assert nrows % block_rows == 0, (nrows, block_rows)
    return (nrows // block_rows,)


def _block(block_rows: int):
    return pl.BlockSpec((block_rows, LANE), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)


def _smem():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _rowsum_block(block_rows: int):
    return pl.BlockSpec((block_rows, 1), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)


def _nonfinite_any(x) -> jax.Array:
    return jnp.logical_not(jnp.all(jnp.isfinite(x)))


def _as_noop(noop_flag):
    if noop_flag is None:
        return jnp.zeros((1,), jnp.int32)
    return jnp.asarray(noop_flag, jnp.int32).reshape(1)


def _finf_accumulate(finf_ref, x):
    """Init-at-first-program / max-accumulate an overflow flag in SMEM.

    Relies on the TPU grid executing sequentially (Pallas TPU semantics).
    """
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        finf_ref[0, 0] = 0.0

    finf_ref[0, 0] = jnp.maximum(finf_ref[0, 0],
                                 _nonfinite_any(x).astype(_f32))


# ---------------------------------------------------------------------------
# scale  (csrc/multi_tensor_scale_kernel.cu)
# ---------------------------------------------------------------------------

def _scale_kernel(scal_ref, x_ref, out_ref, finf_ref):
    x = x_ref[:].astype(_f32) * scal_ref[0]
    _finf_accumulate(finf_ref, x)
    out_ref[:] = x.astype(out_ref.dtype)


def scale_packed(x: jax.Array, scale, out_dtype=None, *, block_rows: int):
    """``out = x * scale`` with fused non-finite detection.

    Returns ``(out, found_inf)`` where ``found_inf`` is f32 0.0/1.0.
    """
    out_dtype = out_dtype or x.dtype
    scale = jnp.asarray(scale, _f32).reshape(1)
    if not _use_kernel(x):
        xf = x.astype(_f32) * scale[0]
        return xf.astype(out_dtype), _nonfinite_any(xf).astype(_f32)
    out, finf = pl.pallas_call(
        _scale_kernel,
        grid=_grid(x.shape[0], block_rows),
        in_specs=[_smem(), _block(block_rows)],
        out_specs=[_block(block_rows), _smem()],
        out_shape=[jax.ShapeDtypeStruct(x.shape, out_dtype),
                   jax.ShapeDtypeStruct((1, 1), _f32)],
        interpret=interpret_mode(),
    )(scale, x)
    return out, finf[0, 0]


# ---------------------------------------------------------------------------
# axpby  (csrc/multi_tensor_axpby_kernel.cu)
# ---------------------------------------------------------------------------

def _axpby_kernel(scal_ref, x_ref, y_ref, out_ref, finf_ref):
    out = scal_ref[0] * x_ref[:].astype(_f32) + scal_ref[1] * y_ref[:].astype(_f32)
    _finf_accumulate(finf_ref, out)
    out_ref[:] = out.astype(out_ref.dtype)


def axpby_packed(a, x: jax.Array, b, y: jax.Array, out_dtype=None, *,
                 block_rows: int):
    """``out = a*x + b*y`` with fused non-finite detection."""
    out_dtype = out_dtype or x.dtype
    scal = jnp.stack([jnp.asarray(a, _f32), jnp.asarray(b, _f32)])
    if not _use_kernel(x, y):
        out = scal[0] * x.astype(_f32) + scal[1] * y.astype(_f32)
        return out.astype(out_dtype), _nonfinite_any(out).astype(_f32)
    out, finf = pl.pallas_call(
        _axpby_kernel,
        grid=_grid(x.shape[0], block_rows),
        in_specs=[_smem(), _block(block_rows), _block(block_rows)],
        out_specs=[_block(block_rows), _smem()],
        out_shape=[jax.ShapeDtypeStruct(x.shape, out_dtype),
                   jax.ShapeDtypeStruct((1, 1), _f32)],
        interpret=interpret_mode(),
    )(scal, x, y)
    return out, finf[0, 0]


# ---------------------------------------------------------------------------
# l2norm  (csrc/multi_tensor_l2norm_kernel.cu)
# ---------------------------------------------------------------------------

def _l2norm_kernel(x_ref, rowsq_ref, finf_ref):
    x = x_ref[:].astype(_f32)
    _finf_accumulate(finf_ref, x)
    rowsq_ref[:] = jnp.sum(x * x, axis=1, keepdims=True)


def l2norm_rowsq_packed(x: jax.Array, *, block_rows: int):
    """Per-row sum-of-squares ``(rows, 1)`` plus non-finite flag.

    The caller reduces row sums to a global norm (``sqrt(sum)``) and/or
    per-tensor norms via a row→tensor segment-sum, giving apex's
    ``per_tensor_python`` variant (multi_tensor_l2norm_kernel.cu).
    """
    if not _use_kernel(x):
        xf = x.astype(_f32)
        return (jnp.sum(xf * xf, axis=1, keepdims=True),
                _nonfinite_any(xf).astype(_f32))
    rowsq, finf = pl.pallas_call(
        _l2norm_kernel,
        grid=_grid(x.shape[0], block_rows),
        in_specs=[_block(block_rows)],
        out_specs=[_rowsum_block(block_rows), _smem()],
        out_shape=[jax.ShapeDtypeStruct((x.shape[0], 1), _f32),
                   jax.ShapeDtypeStruct((1, 1), _f32)],
        interpret=interpret_mode(),
    )(x)
    return rowsq, finf[0, 0]


# ---------------------------------------------------------------------------
# adam  (csrc/multi_tensor_adam.cu)
# ---------------------------------------------------------------------------

def _adam_math(adam_w_mode, scal, skip, g, p, m, v):
    """Pure f32 Adam/AdamW update — single source of truth for kernel+fallback.

    scal: [lr, beta1, beta2, eps, weight_decay, bc1, bc2, grad_scale]
    """
    lr, beta1, beta2, eps, wd, bc1, bc2, gscale = (scal[k] for k in range(8))
    g = g * gscale
    if not adam_w_mode:            # classic Adam: L2 folded into the gradient
        g = g + wd * p
    m_new = beta1 * m + (1.0 - beta1) * g
    v_new = beta2 * v + (1.0 - beta2) * g * g
    update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
    if adam_w_mode:                # AdamW: decoupled weight decay
        update = update + wd * p
    p_new = p - lr * update
    return (jnp.where(skip, p, p_new),
            jnp.where(skip, m, m_new),
            jnp.where(skip, v, v_new))


def _adam_kernel(adam_w_mode, scal_ref, noop_ref, g_ref, p_ref, m_ref, v_ref,
                 p_out, m_out, v_out):
    skip = noop_ref[0] != 0
    p_new, m_new, v_new = _adam_math(
        adam_w_mode, scal_ref, skip, g_ref[:].astype(_f32),
        p_ref[:].astype(_f32), m_ref[:].astype(_f32), v_ref[:].astype(_f32))
    p_out[:] = p_new.astype(p_out.dtype)
    m_out[:] = m_new.astype(m_out.dtype)
    v_out[:] = v_new.astype(v_out.dtype)


def adam_packed(g, p, m, v, *, lr, beta1, beta2, eps, weight_decay,
                bias_correction1, bias_correction2, grad_scale=1.0,
                adam_w_mode=True, noop_flag=None, block_rows: int):
    """One fused Adam/AdamW step over a packed bucket → ``(p, m, v)``.

    ``bias_correction{1,2}`` are ``1 - beta^t`` computed by the caller
    (pass 1.0 to disable).  ``grad_scale`` multiplies gradients (use
    ``1/loss_scale`` to fuse amp unscaling into the step).  When
    ``noop_flag`` is non-zero the step is skipped on-device.
    """
    scal = jnp.stack([jnp.asarray(s, _f32) for s in
                      (lr, beta1, beta2, eps, weight_decay,
                       bias_correction1, bias_correction2, grad_scale)])
    noop = _as_noop(noop_flag)
    if not _use_kernel(g, p, m, v):
        p_new, m_new, v_new = _adam_math(
            bool(adam_w_mode), scal, noop[0] != 0, g.astype(_f32),
            p.astype(_f32), m.astype(_f32), v.astype(_f32))
        return (p_new.astype(p.dtype), m_new.astype(m.dtype),
                v_new.astype(v.dtype))
    kernel = functools.partial(_adam_kernel, bool(adam_w_mode))
    return pl.pallas_call(
        kernel,
        grid=_grid(p.shape[0], block_rows),
        in_specs=[_smem(), _smem()] + [_block(block_rows)] * 4,
        out_specs=[_block(block_rows)] * 3,
        out_shape=[jax.ShapeDtypeStruct(p.shape, p.dtype),
                   jax.ShapeDtypeStruct(m.shape, m.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        input_output_aliases={3: 0, 4: 1, 5: 2},
        interpret=interpret_mode(),
    )(scal, noop, g, p, m, v)


# ---------------------------------------------------------------------------
# sgd  (csrc/multi_tensor_sgd_kernel.cu)
# ---------------------------------------------------------------------------

def _sgd_math(nesterov, first_run, wd_after_momentum, momentum_zero,
              scal, skip, g, p, buf):
    """Pure f32 SGD update.  scal: [lr, wd, momentum, dampening, grad_scale]."""
    lr, wd, mom_c, damp, gscale = (scal[k] for k in range(5))
    g = g * gscale
    if not wd_after_momentum:
        g = g + wd * p
    if momentum_zero:
        new_buf, upd = buf, g
    else:
        new_buf = g if first_run else mom_c * buf + (1.0 - damp) * g
        upd = g + mom_c * new_buf if nesterov else new_buf
    if wd_after_momentum:
        upd = upd + wd * p
    p_new = p - lr * upd
    return jnp.where(skip, p, p_new), jnp.where(skip, buf, new_buf)


def _sgd_kernel(flags, scal_ref, noop_ref, g_ref, p_ref, mom_ref,
                p_out, mom_out):
    skip = noop_ref[0] != 0
    p_new, buf_new = _sgd_math(*flags, scal_ref, skip,
                               g_ref[:].astype(_f32), p_ref[:].astype(_f32),
                               mom_ref[:].astype(_f32))
    p_out[:] = p_new.astype(p_out.dtype)
    mom_out[:] = buf_new.astype(mom_out.dtype)


def sgd_packed(g, p, mom, *, lr, weight_decay, momentum, dampening,
               nesterov=False, first_run=False, wd_after_momentum=False,
               grad_scale=1.0, noop_flag=None, block_rows: int):
    """One fused SGD(+momentum) step over a packed bucket → ``(p, mom)``.

    ``momentum`` may be traced; the momentum==0 shortcut (apex's
    ``momentum_mode``) only engages when it is a concrete Python number.
    """
    scal = jnp.stack([jnp.asarray(s, _f32) for s in
                      (lr, weight_decay, momentum, dampening, grad_scale)])
    noop = _as_noop(noop_flag)
    momentum_zero = isinstance(momentum, (int, float)) and momentum == 0.0
    flags = (bool(nesterov), bool(first_run), bool(wd_after_momentum),
             momentum_zero)
    if not _use_kernel(g, p, mom):
        p_new, buf_new = _sgd_math(*flags, scal, noop[0] != 0,
                                   g.astype(_f32), p.astype(_f32),
                                   mom.astype(_f32))
        return p_new.astype(p.dtype), buf_new.astype(mom.dtype)
    kernel = functools.partial(_sgd_kernel, flags)
    return pl.pallas_call(
        kernel,
        grid=_grid(p.shape[0], block_rows),
        in_specs=[_smem(), _smem()] + [_block(block_rows)] * 3,
        out_specs=[_block(block_rows)] * 2,
        out_shape=[jax.ShapeDtypeStruct(p.shape, p.dtype),
                   jax.ShapeDtypeStruct(mom.shape, mom.dtype)],
        input_output_aliases={3: 0, 4: 1},
        interpret=interpret_mode(),
    )(scal, noop, g, p, mom)


# ---------------------------------------------------------------------------
# lamb stage 1/2  (csrc/multi_tensor_lamb.cu, _stage_1.cu, _stage_2.cu)
# ---------------------------------------------------------------------------

def _lamb_stage1_math(adam_w_mode, scal, skip, g, p, m, v):
    """Pure f32 LAMB stage-1: moments + raw update + row sums of u², p².

    scal: [beta1, beta2, eps, wd, bc1, bc2, grad_scale, clip, beta3]
    (beta3 = 1-beta1 with grad averaging — apex's ``grad_averaging`` — or
    1.0 without.)
    """
    beta1, beta2, eps, wd, bc1, bc2, gscale, clip, beta3 = (
        scal[k] for k in range(9))
    g = g * gscale * clip
    if not adam_w_mode:
        g = g + wd * p
    m_new = beta1 * m + beta3 * g
    v_new = beta2 * v + (1.0 - beta2) * g * g
    u = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
    if adam_w_mode:
        u = u + wd * p
    u = jnp.where(skip, 0.0, u)
    return (u,
            jnp.where(skip, m, m_new),
            jnp.where(skip, v, v_new),
            jnp.sum(u * u, axis=1, keepdims=True),
            jnp.sum(p * p, axis=1, keepdims=True))


def _lamb_stage1_kernel(adam_w_mode, scal_ref, noop_ref,
                        g_ref, p_ref, m_ref, v_ref,
                        u_out, m_out, v_out, usq_out, psq_out):
    skip = noop_ref[0] != 0
    u, m_new, v_new, usq, psq = _lamb_stage1_math(
        adam_w_mode, scal_ref, skip, g_ref[:].astype(_f32),
        p_ref[:].astype(_f32), m_ref[:].astype(_f32), v_ref[:].astype(_f32))
    u_out[:] = u
    m_out[:] = m_new.astype(m_out.dtype)
    v_out[:] = v_new.astype(v_out.dtype)
    usq_out[:] = usq
    psq_out[:] = psq


def lamb_stage1_packed(g, p, m, v, *, beta1, beta2, eps, weight_decay,
                       bias_correction1, bias_correction2, grad_scale=1.0,
                       global_grad_clip=1.0, grad_averaging=True,
                       adam_w_mode=True, noop_flag=None, block_rows: int):
    """LAMB stage 1: moments + raw update + per-row ‖u‖², ‖p‖² sums.

    Returns ``(u, m, v, u_rowsq, p_rowsq)``.  ``global_grad_clip``
    pre-multiplies gradients (apex folds global-norm clipping into the
    kernel the same way).
    """
    beta3 = 1.0 - beta1 if grad_averaging else 1.0
    scal = jnp.stack([jnp.asarray(s, _f32) for s in
                      (beta1, beta2, eps, weight_decay, bias_correction1,
                       bias_correction2, grad_scale, global_grad_clip,
                       beta3)])
    noop = _as_noop(noop_flag)
    if not _use_kernel(g, p, m, v):
        u, m_new, v_new, usq, psq = _lamb_stage1_math(
            bool(adam_w_mode), scal, noop[0] != 0, g.astype(_f32),
            p.astype(_f32), m.astype(_f32), v.astype(_f32))
        return u, m_new.astype(m.dtype), v_new.astype(v.dtype), usq, psq
    kernel = functools.partial(_lamb_stage1_kernel, bool(adam_w_mode))
    nrows = p.shape[0]
    return pl.pallas_call(
        kernel,
        grid=_grid(nrows, block_rows),
        in_specs=[_smem(), _smem()] + [_block(block_rows)] * 4,
        out_specs=[_block(block_rows)] * 3 + [_rowsum_block(block_rows)] * 2,
        out_shape=[jax.ShapeDtypeStruct(p.shape, _f32),
                   jax.ShapeDtypeStruct(m.shape, m.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype),
                   jax.ShapeDtypeStruct((nrows, 1), _f32),
                   jax.ShapeDtypeStruct((nrows, 1), _f32)],
        input_output_aliases={4: 1, 5: 2},
        interpret=interpret_mode(),
    )(scal, noop, g, p, m, v)


def _lamb_stage2_kernel(scal_ref, noop_ref, u_ref, p_ref, ratio_ref, p_out):
    skip = noop_ref[0] != 0
    p = p_ref[:].astype(_f32)
    p_new = p - scal_ref[0] * ratio_ref[:] * u_ref[:]
    p_out[:] = jnp.where(skip, p, p_new).astype(p_out.dtype)


def lamb_stage2_packed(u, p, row_ratio, *, lr, noop_flag=None,
                       block_rows: int):
    """LAMB stage 2: ``p -= lr * trust_ratio * u`` with per-row ratios."""
    scal = jnp.asarray(lr, _f32).reshape(1)
    noop = _as_noop(noop_flag)
    if not _use_kernel(u, p):
        skip = noop[0] != 0
        pf = p.astype(_f32)
        p_new = pf - scal[0] * row_ratio * u
        return jnp.where(skip, pf, p_new).astype(p.dtype)
    return pl.pallas_call(
        _lamb_stage2_kernel,
        grid=_grid(p.shape[0], block_rows),
        in_specs=[_smem(), _smem(), _block(block_rows), _block(block_rows),
                  _rowsum_block(block_rows)],
        out_specs=_block(block_rows),
        out_shape=jax.ShapeDtypeStruct(p.shape, p.dtype),
        input_output_aliases={3: 0},
        interpret=interpret_mode(),
    )(scal, noop, u, p, row_ratio)


# ---------------------------------------------------------------------------
# adagrad  (csrc/multi_tensor_adagrad.cu)
# ---------------------------------------------------------------------------

def _adagrad_math(scal, skip, g, p, h):
    """Pure f32 Adagrad update.  scal: [lr, eps, weight_decay, grad_scale]."""
    lr, eps, wd, gscale = (scal[k] for k in range(4))
    g = g * gscale + wd * p
    h_new = h + g * g
    p_new = p - lr * g / (jnp.sqrt(h_new) + eps)
    return jnp.where(skip, p, p_new), jnp.where(skip, h, h_new)


def _adagrad_kernel(scal_ref, noop_ref, g_ref, p_ref, h_ref, p_out, h_out):
    skip = noop_ref[0] != 0
    p_new, h_new = _adagrad_math(scal_ref, skip, g_ref[:].astype(_f32),
                                 p_ref[:].astype(_f32), h_ref[:].astype(_f32))
    p_out[:] = p_new.astype(p_out.dtype)
    h_out[:] = h_new.astype(h_out.dtype)


def adagrad_packed(g, p, h, *, lr, eps, weight_decay, grad_scale=1.0,
                   noop_flag=None, block_rows: int):
    """One fused Adagrad step over a packed bucket → ``(p, h)``."""
    scal = jnp.stack([jnp.asarray(s, _f32) for s in
                      (lr, eps, weight_decay, grad_scale)])
    noop = _as_noop(noop_flag)
    if not _use_kernel(g, p, h):
        p_new, h_new = _adagrad_math(scal, noop[0] != 0, g.astype(_f32),
                                     p.astype(_f32), h.astype(_f32))
        return p_new.astype(p.dtype), h_new.astype(h.dtype)
    return pl.pallas_call(
        _adagrad_kernel,
        grid=_grid(p.shape[0], block_rows),
        in_specs=[_smem(), _smem()] + [_block(block_rows)] * 3,
        out_specs=[_block(block_rows)] * 2,
        out_shape=[jax.ShapeDtypeStruct(p.shape, p.dtype),
                   jax.ShapeDtypeStruct(h.shape, h.dtype)],
        input_output_aliases={3: 0, 4: 1},
        interpret=interpret_mode(),
    )(scal, noop, g, p, h)


# ---------------------------------------------------------------------------
# novograd  (csrc/multi_tensor_novograd.cu)
# ---------------------------------------------------------------------------

def _novograd_math(reg_inside_moment, scal, skip, g, p, m, v_row):
    """Pure f32 NovoGrad elementwise stage.

    scal: [lr, beta1, weight_decay, eps, grad_scale, beta3]; ``v_row`` is
    the per-tensor second moment broadcast per row.  ``reg_inside_moment``
    (apex flag) selects whether weight decay feeds the momentum (True) or is
    applied outside it at the param update (False, apex default).
    """
    lr, beta1, wd, eps, gscale, beta3 = (scal[k] for k in range(6))
    g = g * gscale
    g = g / (jnp.sqrt(v_row) + eps)
    if reg_inside_moment:
        g = g + wd * p
    m_new = beta1 * m + beta3 * g
    update = m_new if reg_inside_moment else m_new + wd * p
    p_new = p - lr * update
    return jnp.where(skip, p, p_new), jnp.where(skip, m, m_new)


def _novograd_kernel(reg_inside_moment, scal_ref, noop_ref, g_ref, p_ref,
                     m_ref, vrow_ref, p_out, m_out):
    skip = noop_ref[0] != 0
    p_new, m_new = _novograd_math(reg_inside_moment, scal_ref, skip,
                                  g_ref[:].astype(_f32),
                                  p_ref[:].astype(_f32),
                                  m_ref[:].astype(_f32), vrow_ref[:])
    p_out[:] = p_new.astype(p_out.dtype)
    m_out[:] = m_new.astype(m_out.dtype)


def novograd_packed(g, p, m, v_row, *, lr, beta1, weight_decay, eps,
                    grad_scale=1.0, grad_averaging=False,
                    reg_inside_moment=False, noop_flag=None,
                    block_rows: int):
    """NovoGrad elementwise stage: per-tensor second moment ``v`` (already
    updated by the caller from per-tensor grad norms) is broadcast per row
    via ``v_row``; returns ``(p, m)``."""
    beta3 = 1.0 - beta1 if grad_averaging else 1.0
    scal = jnp.stack([jnp.asarray(s, _f32) for s in
                      (lr, beta1, weight_decay, eps, grad_scale, beta3)])
    noop = _as_noop(noop_flag)
    if not _use_kernel(g, p, m):
        p_new, m_new = _novograd_math(bool(reg_inside_moment), scal,
                                      noop[0] != 0, g.astype(_f32),
                                      p.astype(_f32), m.astype(_f32), v_row)
        return p_new.astype(p.dtype), m_new.astype(m.dtype)
    return pl.pallas_call(
        functools.partial(_novograd_kernel, bool(reg_inside_moment)),
        grid=_grid(p.shape[0], block_rows),
        in_specs=[_smem(), _smem()] + [_block(block_rows)] * 3
                 + [_rowsum_block(block_rows)],
        out_specs=[_block(block_rows)] * 2,
        out_shape=[jax.ShapeDtypeStruct(p.shape, p.dtype),
                   jax.ShapeDtypeStruct(m.shape, m.dtype)],
        input_output_aliases={3: 0, 4: 1},
        interpret=interpret_mode(),
    )(scal, noop, g, p, m, v_row)
